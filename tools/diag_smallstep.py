#!/usr/bin/env python
"""Small-step bench diagnosis: overhead-bound or kernel-bound?

The round-4 harvest measured cifar10 at 0.42x and bert at 0.87x their
round-3 floors on a rig whose MATMUL fingerprint probed faster than the
floors' — so raw compute drift cannot explain the deficit. Both benches
run at 1-2 ms/step, the regime where per-launch dispatch cost (which
varies per tunnel instance and was never fingerprinted before
bench.py's _probe_launch_us landed) can dominate the device kernels.

This tool settles it per workload with a batch sweep: step time at
batch B and 4B/16B. A step whose time barely moves with batch is
per-step-overhead-bound — its examples/sec floor tracks the rig's
launch cost, not the compiled kernels, and a sub-floor reading on a
slower-dispatch rig is a rig artifact. A step whose time scales with
batch is kernel-bound and a sub-floor reading is a real regression.

Usage: python tools/diag_smallstep.py [--budget=SECS]
Emits ONE JSON line; safe to run under `timeout` (partial results are
emitted by the same always-emit pattern bench.py uses).
"""

import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402  (repo-root bench.py: probes + timing helpers)
from tools.diag_common import (  # noqa: E402
    enable_compile_cache, make_emit, parse_budget, start_watchdog,
)

OUT: dict = {"diag": "smallstep"}
_emit = make_emit(OUT)


def _cifar_step_time(batch: int, steps: int = 30) -> dict:
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.data.sources import synthetic_images
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import cifar10

    cfg = cifar10.Cifar10Config(
        global_batch_size=batch,
        precision="bf16" if bench.BACKEND == "tpu" else "f32",
        log_every=10**9, checkpoint_every=0, eval_every=0,
        train_steps=10**6, watchdog_secs=0,
    )
    trainer = Trainer(cifar10.make_task(cfg), cfg, mesh=bench._chip_mesh())
    ds = synthetic_images(n=4096, shape=(32, 32, 3), num_classes=10, seed=0)
    it = train_iterator(ds, batch, seed=0)
    batches = [trainer._put_batch(next(it)) for _ in range(4)]
    dts = bench._time_steps(trainer, batches, steps, warmup=5)
    med = statistics.median(dts)
    return {
        "batch": batch,
        "ms_per_step": round(med / steps * 1e3, 4),
        "examples_per_sec": round(batch * steps / med, 1),
    }


def _bert_step_time(batch: int, steps: int = 20) -> dict:
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import bert_glue

    tpu = bench.BACKEND == "tpu"
    cfg = bert_glue.BertGlueConfig(
        global_batch_size=batch, precision="bf16" if tpu else "f32",
        dropout=0.0, log_every=10**9, checkpoint_every=0, eval_every=0,
        train_steps=10**6, watchdog_secs=0,
        **({} if tpu else dict(  # bench_bert's CPU-rehearsal shapes
            seq_len=32, vocab_size=512, num_layers=2, num_heads=2,
            d_model=32, d_ff=64,
        )),
    )
    trainer = Trainer(bert_glue.make_task(cfg), cfg, mesh=bench._chip_mesh())
    ds, _ = bert_glue.datasets(cfg)
    it = train_iterator(ds, batch, seed=0)
    batches = [trainer._put_batch(next(it)) for _ in range(2)]
    dts = bench._time_steps(trainer, batches, steps, warmup=3)
    med = statistics.median(dts)
    return {
        "batch": batch,
        "ms_per_step": round(med / steps * 1e3, 4),
        "examples_per_sec": round(batch * steps / med, 1),
    }


def main() -> int:
    budget = parse_budget(sys.argv[1:])
    deadline = time.monotonic() + budget
    watchdog = start_watchdog(budget, _emit)
    try:
        bench.BACKEND = bench._resolve_backend()
        OUT["backend"] = bench.BACKEND
        if bench.BACKEND == "tpu":
            # Retry windows re-pay trainer-step compiles otherwise.
            enable_compile_cache()
        OUT["launch_us"] = round(bench._probe_launch_us(), 2)
        OUT["probe_tflops"] = round(bench._probe_quick(), 2)
        tpu = bench.BACKEND == "tpu"
        cifar_batches = (128, 512, 2048) if tpu else (16, 64)
        bert_batches = (32, 128) if tpu else (4,)
        OUT["cifar10"] = []
        for b in cifar_batches:
            if time.monotonic() > deadline:
                OUT["truncated"] = True
                break
            OUT["cifar10"].append(_cifar_step_time(b))
        OUT["bert"] = []
        for b in bert_batches:
            if time.monotonic() > deadline:
                OUT["truncated"] = True
                break
            OUT["bert"].append(_bert_step_time(b))
        OUT["launch_us_post"] = round(bench._probe_launch_us(), 2)
    except Exception as e:  # noqa: BLE001 — partials must still emit
        OUT["error"] = f"{type(e).__name__}: {e}"
    watchdog.cancel()
    _emit()
    return 0


if __name__ == "__main__":
    sys.exit(main())
