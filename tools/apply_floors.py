#!/usr/bin/env python
"""Apply a sweep's floor stamps to bench.py in place.

Usage: python tools/apply_floors.py /path/to/sweep.json [--dry-run]

The mechanical half of the floors policy that stamp_floors.py leaves
to copy-paste: for every metric PRESENT in the sweep record, rewrite
its ``"metric": (value, fingerprint),`` line inside
``FLOORS[<backend>]`` and its ``"metric": rel_mfu,`` line inside
``REL_MFU_FLOORS[<backend>]``. Lines for metrics absent from the
record — and every comment — are left byte-identical, so a partial
harvest restamps exactly what it measured. A metric present in the
record but MISSING from the dict is appended at the end of the
backend block (first floor for a new bench).

The edit is refused (exit 1, bench.py untouched) when:
- the record's backend has no block in a dict;
- the record carries ``truncated``/errored metrics AND ``--partial``
  was not passed (a full-sweep stamp should be a full stamp);
- a replacement produces no change at all (suspicious no-op).

After applying, re-run the CPU suite's tools tests: they import
bench.py and will catch a syntax break immediately.
"""

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from stamp_floors import UNFLOORED, parse_sweep  # noqa: E402


def _block_span(src: str, dict_name: str, backend: str):
    """(start, end) character span of the ``"backend": {...}`` block
    inside ``dict_name = {...}``, exclusive of the closing brace."""
    m = re.search(rf"^{dict_name}[^=]*= \{{", src, re.M)
    if not m:
        raise SystemExit(f"apply_floors: {dict_name} not found")
    i = src.find(f'"{backend}": {{', m.end())
    if i < 0 or i > src.find("\n}", m.end()):
        raise SystemExit(
            f"apply_floors: no {backend!r} block in {dict_name}"
        )
    start = src.index("{", i) + 1
    depth = 1
    j = start
    while depth:
        c = src[j]
        if c == "#":
            # A brace inside a comment must not move the span: this
            # tool rewrites source in place, and a comment like
            # "# shape: {...}" would otherwise swallow the next block.
            j = src.index("\n", j)
            continue
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
        j += 1
    return start, j - 1


def _rewrite(src: str, dict_name: str, backend: str, entries: dict) -> str:
    start, end = _block_span(src, dict_name, backend)
    block = src[start:end]
    missing = []
    for metric, line_value in entries.items():
        pat = re.compile(
            rf'^(\s*)"{re.escape(metric)}": [^#\n]*,(\s*#[^\n]*)?$', re.M
        )
        m = pat.search(block)
        new_line = f'"{metric}": {line_value},'
        if m is None:
            if f'"{metric}"' in block:
                # The metric's key exists but the one-line regex missed
                # it (e.g. a formatter wrapped the tuple across lines).
                # Appending here would leave a duplicate dict key whose
                # later value silently wins while the stale wrapped
                # entry survives in source — refuse instead.
                raise SystemExit(
                    f"apply_floors: {metric!r} present in {dict_name}"
                    f"[{backend!r}] but not as a single "
                    '``"metric": value,`` line — fix the formatting, '
                    "then re-run"
                )
            missing.append(new_line)
            continue
        keep_comment = m.group(2) or ""
        block = (
            block[: m.start()]
            + f"{m.group(1)}{new_line}{keep_comment}"
            + block[m.end() :]
        )
    if missing:
        pad = "        "
        block = block.rstrip() + "\n" + "".join(
            f"{pad}{ln}  # first floor (appended by apply_floors)\n"
            for ln in missing
        ) + "    "
    return src[:start] + block + src[end:]


def main() -> int:
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    flags = {a for a in sys.argv[1:] if a.startswith("--")}
    unknown = flags - {"--dry-run", "--partial"}
    if unknown:
        print(f"apply_floors: unknown flags {sorted(unknown)} "
              "(known: --dry-run, --partial)")
        return 2
    if len(args) != 1:
        print(__doc__)
        return 2
    with open(args[0]) as f:
        d = json.load(f)
    backend, results, errored, sweep_fp = parse_sweep(d)
    results = [r for r in results if r["metric"] not in UNFLOORED]
    if (d.get("truncated") or errored) and "--partial" not in flags:
        print(
            f"apply_floors: record has truncated={d.get('truncated')} "
            f"errored={errored}; pass --partial to stamp only what ran"
        )
        return 1
    if errored or d.get("truncated"):
        # Same loud warning stamp_floors prints: unstamped metrics keep
        # their OLD (value, fingerprint) floors while the compiled
        # program may have changed — stale until fixed or removed.
        print(
            "apply_floors: WARNING — NOT stamped (old floors now stale): "
            f"errored={errored} truncated={d.get('truncated')}"
        )
    if not results:
        print("apply_floors: no stampable metrics in record")
        return 1

    floors = {}
    rel = {}
    bundles = {}
    for r in results:
        fp = r.get(
            "fingerprint_tflops_pre", r.get("fingerprint_tflops", sweep_fp)
        )
        floors[r["metric"]] = f"({r['value']}, {fp})"
        if "rel_mfu" in r:
            rel[r["metric"]] = f"{r['rel_mfu']}"
        # The launch protocol moves WITH the floor: stamp the record's
        # bundle (explicitly, even when 1 — an existing entry from an
        # earlier bundled stamp must be overwritten, not kept) so
        # bench.py's floor_protocol_mismatch flag compares against the
        # protocol this floor was actually measured under.
        bundles[r["metric"]] = str(int(r.get("bundle", 1) or 1))

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "bench.py",
    )
    with open(path) as f:
        src = f.read()
    out = _rewrite(src, "FLOORS", backend, floors)
    out = _rewrite(out, "REL_MFU_FLOORS", backend, rel)
    out = _rewrite(out, "FLOOR_BUNDLES", backend, bundles)
    if out == src:
        print("apply_floors: no-op (nothing changed) — refusing")
        return 1
    if "--dry-run" in flags:
        import difflib

        sys.stdout.writelines(
            difflib.unified_diff(
                src.splitlines(True), out.splitlines(True), "bench.py", "new"
            )
        )
        return 0
    with open(path, "w") as f:
        f.write(out)
    print(
        f"apply_floors: stamped {len(floors)} floors + {len(rel)} rel_mfu "
        f"+ {len(bundles)} bundle protocols for backend {backend!r}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
