#!/usr/bin/env python
"""Visualize a ShardingConfig against a model BEFORE running it.

Resolves every param of a workload's model through the sharding rules
and prints the param → PartitionSpec table with per-device byte totals
(replicated vs sharded), the placement digest, and — when the config
enables ZeRO-1 — the optimizer-state per-device bytes next to the
replicated baseline. A bad rule (a regex that matches nothing, a giant
table left replicated) is visible here, not ten minutes into a run.

    # The config a training run persisted:
    python tools/shard_viz.py --config /run/workdir/sharding.json --workload gpt2

    # An ad-hoc layout over the full GPT-2 124M table:
    python tools/shard_viz.py --mesh data=2,model=4 --workload gpt2 --zero1

    # Tiny model override (any workload-config field):
    python tools/shard_viz.py --mesh data=2,model=2 --workload gpt2 \
        --set num_layers=2 --set d_model=64 --set vocab_size=256

Runs fine on CPU: the model is never materialized (``jax.eval_shape``
templates only). The param table and digest resolve even when the
config's mesh exceeds the host's device count (a pod config on a
laptop) — only the optimizer-state per-device summary needs the real
mesh, and degrades to a note when it can't be built.

JSON output (``--json``) mirrors the table for scripting:
``{"mesh_shape", "digest", "rows": [...], "totals", "opt_state"}``.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORKLOADS = ("gpt2", "mnist", "cifar10", "imagenet", "bert_glue")


def parse_mesh(text: str) -> dict[str, int]:
    """'data=2,model=4' -> {'data': 2, 'model': 4}."""
    out: dict[str, int] = {}
    for part in filter(None, (p.strip() for p in text.split(","))):
        if "=" not in part:
            raise ValueError(f"mesh entry {part!r} is not axis=size")
        axis, size = part.split("=", 1)
        out[axis.strip()] = int(size)
    return out


def build_workload_config(name: str, overrides: list[str]):
    import importlib

    mod = importlib.import_module(
        f"tensorflow_examples_tpu.workloads.{name}"
    )
    cfg_cls = next(
        getattr(mod, a)
        for a in dir(mod)
        if a.endswith("Config") and dataclasses.is_dataclass(getattr(mod, a))
    )
    cfg = cfg_cls()
    fields = {f.name: f for f in dataclasses.fields(cfg)}
    updates = {}
    for item in overrides:
        if "=" not in item:
            raise ValueError(f"--set {item!r} is not field=value")
        key, value = item.split("=", 1)
        if key not in fields:
            raise ValueError(
                f"--set {key}: no such field on {cfg_cls.__name__}"
            )
        current = getattr(cfg, key)
        if isinstance(current, bool):
            updates[key] = value.lower() in ("1", "true", "yes")
        elif isinstance(current, int):
            updates[key] = int(value)
        elif isinstance(current, float):
            updates[key] = float(value)
        else:
            updates[key] = value
    return mod, dataclasses.replace(cfg, **updates)


class _ShapeOnlyMesh:
    """Shape stand-in for a mesh the host cannot build (a pod-sized
    config inspected on a laptop): enough surface for rule resolution
    and byte math (``mesh.shape[axis]``), but it cannot back real
    NamedShardings — the optimizer-state summary is skipped with it."""

    def __init__(self, shape: dict[str, int]):
        self.shape = dict(shape)
        self.axis_names = tuple(shape)


def resolve(config, workload: str, overrides: list[str]):
    """(ShardingConfig, workload) -> (ResolvedSharding, abstract state,
    state shardings | None). Everything abstract — no arrays
    materialize. When the config's mesh exceeds the host's devices the
    param table/digest still resolve (against a shape-only mesh);
    ``shardings`` comes back None and the opt-state summary is
    skipped."""
    import jax

    from tensorflow_examples_tpu.sharding import resolve_params
    from tensorflow_examples_tpu.sharding.resolve import state_shardings
    from tensorflow_examples_tpu.train.loop import state_factory

    mod, cfg = build_workload_config(workload, overrides)
    try:
        mesh = config.build_mesh()
    except ValueError as e:
        print(f"note: {e}; resolving against the shape only "
              "(opt-state summary skipped)", file=sys.stderr)
        mesh = None
    task = mod.make_task(cfg, mesh=mesh)
    rules = config.sharding_rules(default=task.sharding_rules)
    make_state, _ = state_factory(task, cfg)
    abstract = jax.eval_shape(make_state, jax.random.PRNGKey(0))
    if mesh is None:
        try:
            shape = config.mesh_shape_dict()
        except ValueError as e:
            # data=-1 that doesn't divide this host either: there is no
            # resolvable shape at all — clean error, not a traceback.
            raise SystemExit(f"shard_viz: {e}") from e
        return (
            resolve_params(abstract.params, _ShapeOnlyMesh(shape), rules),
            abstract,
            None,
        )
    resolved = resolve_params(abstract.params, mesh, rules)
    shardings = state_shardings(
        abstract, mesh, rules,
        zero1=config.zero1, batch_axes=config.batch_axes,
    )
    return resolved, abstract, shardings


def sharded_tree_bytes(abstract_tree, shardings_tree) -> int:
    """Per-device bytes of an abstract tree under a shardings tree —
    the shardings are attached to the template leaves and the ONE
    per-device byte implementation (telemetry/memory.tree_bytes, the
    same math TrainState.byte_breakdown pins) does the accounting."""
    import jax

    from tensorflow_examples_tpu.telemetry.memory import tree_bytes

    placed = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
        abstract_tree,
        shardings_tree,
    )
    return tree_bytes(placed, per_device=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument(
        "--config", help="ShardingConfig JSON (e.g. workdir/sharding.json)"
    )
    ap.add_argument(
        "--mesh", default="",
        help="ad-hoc mesh instead of --config: 'data=2,model=4'",
    )
    ap.add_argument(
        "--zero1", action="store_true",
        help="with --mesh: enable ZeRO-1 in the ad-hoc config",
    )
    ap.add_argument(
        "--workload", default="gpt2", choices=WORKLOADS,
        help="model template whose params the rules resolve against",
    )
    ap.add_argument(
        "--set", action="append", default=[], metavar="FIELD=VALUE",
        help="workload-config override (repeatable), e.g. num_layers=2",
    )
    ap.add_argument("--json", action="store_true", help="machine output")
    args = ap.parse_args(argv)

    if bool(args.config) == bool(args.mesh):
        ap.error("exactly one of --config / --mesh is required")

    from tensorflow_examples_tpu.sharding import ShardingConfig

    if args.config:
        config = ShardingConfig.load(args.config)
    else:
        config = ShardingConfig(
            mesh=parse_mesh(args.mesh), zero1=args.zero1
        )

    resolved, abstract, shardings = resolve(
        config, args.workload, args.set
    )
    mesh_shape = {
        a: int(resolved.mesh.shape[a]) for a in resolved.mesh.axis_names
    }
    opt_per_device = (
        sharded_tree_bytes(abstract.opt_state, shardings.opt_state)
        if shardings is not None
        else None
    )
    from tensorflow_examples_tpu.telemetry.memory import tree_bytes

    opt_global = tree_bytes(abstract.opt_state)

    if args.json:
        doc = {
            "mesh_shape": mesh_shape,
            "zero1": bool(config.zero1),
            "param_sharding_digest": resolved.digest(),
            "rows": [
                {
                    "path": r.path,
                    "spec": list(r.spec),
                    "shape": list(r.shape),
                    "replicated": r.replicated,
                    "global_bytes": r.global_bytes,
                    "per_device_bytes": r.per_device_bytes,
                }
                for r in resolved.rows
            ],
            "totals": resolved.byte_totals(),
            "opt_state": {
                "global_bytes": opt_global,
                "per_device_bytes": opt_per_device,
            },
        }
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 0

    print(f"mesh: {mesh_shape}  zero1: {config.zero1}")
    print(f"param sharding digest: {resolved.digest()}")
    print()
    print(resolved.table_str())
    print()
    if opt_per_device is None:
        print(
            f"optimizer state: {opt_global:,} B global (per-device "
            "summary needs the mesh's device count locally — force a "
            "CPU mesh, docs/sharding.md)"
        )
    else:
        print(
            f"optimizer state: {opt_global:,} B global, "
            f"{opt_per_device:,} B/device"
            + (
                f" ({opt_global / max(opt_per_device, 1):.1f}x reduction)"
                if config.zero1
                else " (replicated; --zero1 shards it over the batch axes)"
            )
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
