#!/bin/bash
# The one-command TPU campaign (VERDICT r3 item 1): run the moment a
# live tunnel is confirmed. Produces, under /tmp/tpu_campaign_<ts>/:
#   selftest.json  — tests_tpu compiled-kernel parity (incl. the decode
#                    bucket ladder), via bench.py --bench=selftest
#   sweep.json     — full protocol sweep, unbudgeted (every metric,
#                    3 windows, pre/post fingerprints, rel_mfu)
#   stamp.txt      — ready-to-paste FLOORS / REL_MFU_FLOORS /
#                    BASELINE.md table from tools/stamp_floors.py
# Then: paste the stamps into bench.py + BASELINE.md (floors policy:
# value+fingerprint+rel_mfu move together), resolve any sub-1.0
# vs_baseline against the round-2 floors by reading rel_mfu, commit.
set -u
cd "$(dirname "$0")/.."
ts=$(date -u +%Y%m%d_%H%M%S)
out="/tmp/tpu_campaign_$ts"
mkdir -p "$out"
echo "campaign -> $out"

rm -f /tmp/bench_backend_probe.json  # force a fresh probe verdict

echo "[1/3] compiled-kernel selftest (tests_tpu)"
timeout 2400 python bench.py --bench=selftest --budget=2300 \
  > "$out/selftest.json" 2> "$out/selftest.err"
python -c "
import json; d = json.load(open('$out/selftest.json'))
st = d.get('selftest', {})
print('  backend:', d.get('backend'), '| ok:', st.get('ok'), '|', st.get('summary', '')[:120])
exit(0 if d.get('backend') == 'tpu' else 3)
" || { echo 'NOT ON TPU — aborting campaign'; exit 3; }

echo "[2/3] full protocol sweep"
# Budget (not --budget=0): keeps bench.py's own watchdog armed so a
# bench wedging in native code still yields a partial record with an
# honest truncated list; the outer timeout's SIGTERM would not.
timeout 5400 python bench.py --budget=5300 --no-selftest \
  > "$out/sweep.json" 2> "$out/sweep.err"

echo "[3/3] floor stamps"
python tools/stamp_floors.py "$out/sweep.json" | tee "$out/stamp.txt" | head -40
echo "done: $out (paste stamp.txt into bench.py + BASELINE.md, then rerun 'timeout 600 python bench.py' to confirm vs_baseline ~1.0)"
