#!/usr/bin/env python
"""Turn a bench.py sweep JSON into ready-to-paste floor stamps.

Usage: python tools/stamp_floors.py /path/to/sweep.json

Prints, for the record's backend:
- the ``FLOORS[backend]`` entries as Python source — (median,
  fingerprint) pairs per metric, each stamped with its OWN record's
  pre-fingerprint when present (harvest merges) and the sweep-level
  pre-fingerprint otherwise (plain ``--bench=all`` sweeps);
- the ``REL_MFU_FLOORS[backend]`` entries;
- a BASELINE.md markdown table row per metric (median, window spread,
  rel_mfu) so the stamp and its evidence land together.

The floors POLICY (bench.py module docstring) requires floors to move
only with their fingerprints, from a measurement under the protocol,
recorded in BASELINE.md — this tool makes the mechanical part of that
a copy-paste so the first live-TPU sweep can be stamped in minutes.
"""

import json
import sys

# Diagnostics whose healthy value is a fixed point and whose failure
# direction _result()'s unit heuristic would misread are never floored
# (bench.py documents each beside FLOORS). Shared with apply_floors.py.
UNFLOORED = {"decode_grid_step_time_ratio"}


def parse_sweep(d):
    """(backend, results, errored, sweep_fp) from a sweep/merge record.
    The single parse both halves of the floors workflow (print + apply)
    share, so they can never disagree on what counts as stampable."""
    backend = d.get("backend", "?")
    fp = d.get("fingerprint_tflops_pre", d.get("fingerprint_tflops", 0.0))
    everything = [d] + d.get("extras", [])
    results = [r for r in everything if "error" not in r and "metric" in r
               and r.get("metric") != "selftest"]
    errored = [
        r.get("bench", r.get("metric"))
        for r in everything
        if "error" in r and r.get("metric") != "selftest"
    ]
    return backend, results, errored, fp


def main() -> int:
    if len(sys.argv) != 2:
        print(__doc__)
        return 2
    with open(sys.argv[1]) as f:
        d = json.load(f)
    backend, results, errored, fp = parse_sweep(d)
    fp_post = d.get("fingerprint_tflops_post")

    spread = d.get("fingerprint_spread")
    print(f"# backend={backend}  fingerprint pre={fp} post={fp_post}"
          + (f"  spread={spread}" if spread else ""))
    if d.get("truncated"):
        print(f"# TRUNCATED (not stamped): {d['truncated']}")
    if errored:
        # An unstamped metric keeps its OLD (value, fingerprint) floor
        # while the compiled program may have changed — the exact
        # violation the floors policy forbids. Make it loud.
        print(
            f"# ERRORED (NOT STAMPED — their old floors are now stale, "
            f"fix or remove them): {errored}"
        )
    # Each harvest record is self-contained and carries its OWN probe
    # fingerprint; stamping with the merged min-over-all-probes would
    # let a single wedged probe (e.g. a post-fingerprint taken mid
    # tunnel-death, observed at 78 vs the ~40-100k healthy range)
    # poison every floor's fingerprint at once.
    unfloored = UNFLOORED
    print(f'\n# --- FLOORS["{backend}"] entries ---')
    for r in results:
        if r["metric"] in unfloored:
            print(f'        # {r["metric"]}: {r["value"]} — diagnostic, '
                  f'deliberately unfloored')
            continue
        rfp = r.get("fingerprint_tflops_pre", r.get("fingerprint_tflops", fp))
        print(f'        "{r["metric"]}": ({r["value"]}, {rfp}),')
    print(f'\n# --- REL_MFU_FLOORS["{backend}"] entries ---')
    for r in results:
        if "rel_mfu" in r:
            print(f'        "{r["metric"]}": {r["rel_mfu"]},')
    print("\n# --- BASELINE.md table ---")
    print("| Metric | Median | Windows | rel_mfu | launch µs |")
    print("|---|---|---|---|---|")
    for r in results:
        win = " / ".join(str(w) for w in r.get("window_values", []))
        print(
            f"| {r['metric']} | {r['value']} {r.get('unit', '')} | {win} "
            f"| {r.get('rel_mfu', '—')} "
            f"| {r.get('probe_launch_us_at_bench', '—')} |"
        )
    st = d.get("selftest")
    if st is not None:
        print(f"\n# selftest: ok={st.get('ok')} — {st.get('summary')}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
