#!/usr/bin/env python
"""Turn a bench.py sweep JSON into ready-to-paste floor stamps.

Usage: python tools/stamp_floors.py /path/to/sweep.json

Prints, for the record's backend:
- the ``FLOORS[backend]`` entries as Python source — (median, the
  sweep's pre-fingerprint) pairs per metric;
- the ``REL_MFU_FLOORS[backend]`` entries;
- a BASELINE.md markdown table row per metric (median, window spread,
  rel_mfu) so the stamp and its evidence land together.

The floors POLICY (bench.py module docstring) requires floors to move
only with their fingerprints, from a measurement under the protocol,
recorded in BASELINE.md — this tool makes the mechanical part of that
a copy-paste so the first live-TPU sweep can be stamped in minutes.
"""

import json
import sys


def main() -> int:
    if len(sys.argv) != 2:
        print(__doc__)
        return 2
    with open(sys.argv[1]) as f:
        d = json.load(f)
    backend = d.get("backend", "?")
    fp = d.get("fingerprint_tflops_pre", d.get("fingerprint_tflops", 0.0))
    fp_post = d.get("fingerprint_tflops_post")
    everything = [d] + d.get("extras", [])
    results = [r for r in everything if "error" not in r and "metric" in r]
    errored = [
        r.get("bench", r.get("metric"))
        for r in everything
        if "error" in r and r.get("metric") != "selftest"
    ]

    print(f"# backend={backend}  fingerprint pre={fp} post={fp_post}")
    if d.get("truncated"):
        print(f"# TRUNCATED (not stamped): {d['truncated']}")
    if errored:
        # An unstamped metric keeps its OLD (value, fingerprint) floor
        # while the compiled program may have changed — the exact
        # violation the floors policy forbids. Make it loud.
        print(
            f"# ERRORED (NOT STAMPED — their old floors are now stale, "
            f"fix or remove them): {errored}"
        )
    print(f'\n# --- FLOORS["{backend}"] entries ---')
    for r in results:
        print(f'        "{r["metric"]}": ({r["value"]}, {fp}),')
    print(f'\n# --- REL_MFU_FLOORS["{backend}"] entries ---')
    for r in results:
        if "rel_mfu" in r:
            print(f'        "{r["metric"]}": {r["rel_mfu"]},')
    print("\n# --- BASELINE.md table ---")
    print("| Metric | Median | Windows | rel_mfu |")
    print("|---|---|---|---|")
    for r in results:
        win = " / ".join(str(w) for w in r.get("window_values", []))
        print(
            f"| {r['metric']} | {r['value']} {r.get('unit', '')} | {win} "
            f"| {r.get('rel_mfu', '—')} |"
        )
    st = d.get("selftest")
    if st is not None:
        print(f"\n# selftest: ok={st.get('ok')} — {st.get('summary')}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
