"""Shared plumbing for the one-shot on-chip measurement tools
(tools/diag_smallstep.py, tools/flash_tune.py).

Each tool prints its record as JSON lines with an always-emit
guarantee: a watchdog emits a truncated snapshot at budget-15s (so the
caller's run_bounded SIGKILL can never discard completed
measurements), and main emits the full record on normal exit.
Consumers (tools/diag_watch.sh via tools/last_json_line.py) take the
LAST parseable line, so a main that finishes inside the kill headroom
wins over the snapshot.
"""

import json
import sys
import threading


def parse_budget(argv, default: float = 600.0) -> float:
    for a in argv:
        if a.startswith("--budget="):
            return float(a.split("=", 1)[1])
    return default


def make_emit(out: dict):
    """Emit callable over a shared record dict, safe to call from the
    watchdog timer thread while main still assigns keys (snapshots a
    shallow copy — the C encoder raises on a dict that changes size
    mid-iteration — and never lets a racing snapshot kill the run)."""

    def _emit(truncated: bool = False) -> None:
        try:
            rec = dict(out)
            if truncated:
                rec["truncated"] = True
            sys.stdout.write(json.dumps(rec) + "\n")
            sys.stdout.flush()
        except Exception:
            pass

    return _emit


def start_watchdog(budget: float, emit) -> threading.Timer:
    """Daemon timer that emits a truncated snapshot shortly before the
    caller's outer deadline; cancel() it on the normal-exit path."""
    t = threading.Timer(max(budget - 15.0, 5.0), emit, (True,))
    t.daemon = True
    t.start()
    return t


def enable_compile_cache(path: str = "/tmp/jax_diag_cache") -> None:
    """Persistent compiled-executable cache, same rationale as
    tests_tpu/conftest.py: a tunnel wedge mid-run loses the window but
    not the compiles, so retry windows get cheaper until a full pass
    fits the budget."""
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
