#!/usr/bin/env python
"""Attribute the delta between two runs (ISSUE 4 tentpole (3)).

    python tools/run_diff.py <run_a> <run_b>
    python tools/run_diff.py <run_a> <run_b> --json diff.json
    python tools/run_diff.py <run_a> <run_b> --fail-on-regression

``run_a`` / ``run_b`` are each a run dir (anything
``tools/telemetry_report.py`` accepts: the workdir, its telemetry dir,
or a metrics.jsonl path) or a pre-extracted ``telemetry_report --json``
record file. A is the baseline, B the candidate.

The comparison covers every number the telemetry record carries a
direction for — step-time p50/p95, throughput, MFU, goodput, peak
live-memory watermark, compile/recompile counts, and per-span host time
from the Chrome trace — and prints a RANKED "what changed" summary:
regressions first, largest relative change first, improvements after,
ties broken stably. Metrics absent from either record (a v1 run has no
memory watermark) are listed as not comparable, never guessed.

``--json`` writes a machine-readable document: both records, the
ranked delta list, and the candidate's gateable figures flattened at
top level — so the output is directly consumable by
``tools/bench_gate.py --record diff.json --floors floors.json`` (the
CI smoke in tests/test_tools.py self-compares a run dir through
exactly that path).

Exit codes: 0 = compared (regressions only reported), 1 = regressions
found AND ``--fail-on-regression`` was set, 2 = a record could not be
built from either argument.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import telemetry_report  # noqa: E402

# (record key, direction, unit, scale) — direction says which way is a
# regression; scale is display-only (step times print as ms).
# The serving keys (ISSUE 8) make this the canary-compare engine for
# the router tier: a serve_bench / router per-set record diffs the
# same way a training run does, with TTFT/TPOT/prefix-hit regressions
# ranked first like everything else.
DIFF_KEYS: tuple[tuple[str, str, str, float], ...] = (
    ("step_time_p50", "lower", "ms", 1e3),
    ("step_time_p95", "lower", "ms", 1e3),
    ("examples_per_sec_mean", "higher", "/s", 1.0),
    ("examples_per_sec_last", "higher", "/s", 1.0),
    ("tokens_per_sec_last", "higher", "/s", 1.0),
    ("mfu", "higher", "", 1.0),
    ("goodput", "higher", "", 1.0),
    ("peak_live_bytes", "lower", "MiB", 1.0 / 2**20),
    ("compiles", "lower", "", 1.0),
    ("recompiles", "lower", "", 1.0),
    # ---- serving records (serve_bench / router canary sets) ----
    ("ttft_p50_ms", "lower", "ms", 1.0),
    ("ttft_p95_ms", "lower", "ms", 1.0),
    ("tpot_p50_ms", "lower", "ms", 1.0),
    ("tpot_p95_ms", "lower", "ms", 1.0),
    ("e2e_p95_ms", "lower", "ms", 1.0),
    ("queue_wait_p95_ms", "lower", "ms", 1.0),
    ("req_per_s", "higher", "/s", 1.0),
    ("tok_per_s", "higher", "/s", 1.0),
    ("prefix_hit_rate", "higher", "", 1.0),
    ("post_warmup_recompiles", "lower", "", 1.0),
    # ---- chaos/availability records (ISSUE 10) ----
    ("error_rate", "lower", "", 1.0),
    ("failover_count", "lower", "", 1.0),
    ("p95_vs_baseline", "lower", "", 1.0),
    # ---- speculative decoding records (ISSUE 11) ----
    ("tpot_speedup", "higher", "x", 1.0),
    ("draft_hit_rate", "higher", "", 1.0),
    ("accepted_per_step", "higher", "", 1.0),
    # ---- cache-aware scheduling records (ISSUE 12) ----
    ("prefix_hit_rate_affinity", "higher", "", 1.0),
    ("affinity_hit_gain", "higher", "", 1.0),
    # ---- overload/traffic records (ISSUE 13) ----
    ("ttft_p95_interactive_ms", "lower", "ms", 1.0),
    ("ttft_p95_batch_ms", "lower", "ms", 1.0),
    ("shed_rate_interactive", "lower", "", 1.0),
    ("shed_rate_batch", "lower", "", 1.0),
    ("scale_up_latency_s", "lower", "s", 1.0),
    ("p95_during_resize_ms", "lower", "ms", 1.0),
    # ---- weight quantization records (ISSUE 15) ----
    ("tpot_speedup_quant", "higher", "x", 1.0),
    ("hbm_bytes_per_replica", "lower", "MiB", 1.0 / 2**20),
    ("stream_agreement", "higher", "", 1.0),
    # ---- control-plane takeover records (ISSUE 16) ----
    ("takeover_latency_s", "lower", "s", 1.0),
    ("lost_requests", "lower", "", 1.0),
    ("resumed_streams", "higher", "", 1.0),
    ("dedup_hits", "higher", "", 1.0),
    # ---- distributed-tracing records (ISSUE 18) ----
    ("trace_coverage", "higher", "", 1.0),
    ("slow_trace_count", "lower", "", 1.0),
    # ---- SLO alerting records (ISSUE 19) ----
    ("alert_count", "lower", "", 1.0),
    ("error_budget_remaining", "higher", "", 1.0),
    ("probe_success_rate", "higher", "", 1.0),
)

# The candidate keys flattened into the --json doc for bench_gate
# --record (mirrors bench_gate.RECORD_KEYS plus the last-window rate).
GATE_KEYS = (
    "step_time_p50",
    "step_time_p95",
    "sharded_step_time",
    "peak_live_bytes",
    "mfu",
    "goodput",
    "examples_per_sec_mean",
    # serving gate keys (ISSUE 8): bench_gate.RECORD_KEYS accepts them
    # so a canary diff doc gates straight against serving floors.
    "ttft_p95_ms",
    "tpot_p95_ms",
    "req_per_s",
    "tok_per_s",
    "prefix_hit_rate",
    # chaos/availability gate keys (ISSUE 10)
    "error_rate",
    "p95_vs_baseline",
    # speculative-decoding gate keys (ISSUE 11)
    "tpot_speedup",
    "draft_hit_rate",
    # cache-aware scheduling gate keys (ISSUE 12)
    "prefix_hit_rate_affinity",
    # overload/traffic gate keys (ISSUE 13)
    "ttft_p95_interactive_ms",
    "ttft_p95_batch_ms",
    "shed_rate_interactive",
    "scale_up_latency_s",
    # weight-quantization gate keys (ISSUE 15)
    "tpot_speedup_quant",
    "hbm_bytes_per_replica",
    # control-plane takeover gate keys (ISSUE 16)
    "takeover_latency_s",
    # distributed-tracing gate keys (ISSUE 18)
    "trace_coverage",
    "slow_trace_count",
    # SLO alerting gate keys (ISSUE 19)
    "alert_count",
    "probe_success_rate",
)

# Relative change below this is "unchanged" (run-to-run wobble, not a
# finding); overridable with --threshold.
DEFAULT_THRESHOLD = 0.02

# Ranking magnitude assigned to a zero-baseline jump (JSON cannot carry
# Infinity; anything appearing from zero outranks any finite change).
_INF_MAGNITUDE = 1e9


def load_record(arg: str) -> tuple[dict | None, str]:
    """(record, error). Accepts a telemetry_report --json file, a
    serving bench record (serve_bench / router canary set — anything
    carrying a ``"bench"`` key), or anything telemetry_report resolves
    as a run dir."""
    if os.path.isfile(arg) and not arg.endswith(".jsonl"):
        try:
            with open(arg) as f:
                doc = json.load(f)
        except (json.JSONDecodeError, UnicodeDecodeError):
            doc = None
        if isinstance(doc, dict) and "windows" in doc and "counters" in doc:
            return doc, ""
        if isinstance(doc, dict) and "bench" in doc:
            return doc, ""
    record, _, err = telemetry_report.build_record(arg)
    return record, err


def _span_totals(record: dict) -> dict[str, float]:
    return {
        name: p["total_ms"]
        for name, p in (record.get("trace_phases") or {}).items()
    }


def diff_records(
    a: dict, b: dict, threshold: float = DEFAULT_THRESHOLD
) -> tuple[list[dict], list[str]]:
    """(ranked deltas, not-comparable notes). Each delta::

        {"metric", "a", "b", "unit", "scale", "rel_change",
         "direction", "verdict": "regressed"|"improved"|"unchanged",
         "severity"}

    ``rel_change`` is signed (b/a - 1), null for a zero baseline (a
    0 -> nonzero jump has no finite ratio, and ``Infinity`` is not
    legal JSON); ``severity`` is the magnitude of the change in the
    REGRESSION direction (0 for improvements / unchanged ties),
    capped finite — it is what the ranking sorts by.
    """
    rows: list[tuple[str, str, str, float, float | None, float | None]] = []
    for key, direction, unit, scale in DIFF_KEYS:
        rows.append((key, direction, unit, scale, a.get(key), b.get(key)))
    span_a, span_b = _span_totals(a), _span_totals(b)
    for name in sorted(set(span_a) | set(span_b)):
        rows.append(
            (
                f"span/{name}_total_ms",
                "lower",
                "ms",
                1.0,
                span_a.get(name),
                span_b.get(name),
            )
        )

    deltas: list[dict] = []
    skipped: list[str] = []
    for key, direction, unit, scale, va, vb in rows:
        if va is None and vb is None:
            continue  # neither run has it: not worth a line
        if va is None or vb is None:
            skipped.append(
                f"{key}: absent in {'A' if va is None else 'B'}"
            )
            continue
        va, vb = float(va), float(vb)
        if va == 0.0 and vb == 0.0:
            rel = 0.0
        elif va == 0.0:
            rel = math.inf  # 0 -> something: no finite ratio exists
        else:
            rel = vb / va - 1.0
        regression = rel > 0 if direction == "lower" else rel < 0
        # Cap the ranking magnitude finite: json has no Infinity, and
        # "appeared from zero" should outrank any finite change anyway.
        magnitude = min(abs(rel), _INF_MAGNITUDE)
        if magnitude <= threshold:
            verdict, severity = "unchanged", 0.0
        elif regression:
            verdict, severity = "regressed", magnitude
        else:
            verdict, severity = "improved", 0.0
        deltas.append(
            {
                "metric": key,
                "a": va,
                "b": vb,
                "unit": unit,
                "scale": scale,
                "rel_change": rel if math.isfinite(rel) else None,
                "direction": direction,
                "verdict": verdict,
                "severity": severity,
                "_magnitude": magnitude,
            }
        )
    order = {"regressed": 0, "improved": 1, "unchanged": 2}
    deltas.sort(
        key=lambda d: (order[d["verdict"]], -d["_magnitude"], d["metric"])
    )
    for d in deltas:
        del d["_magnitude"]
    return deltas, skipped


def _fmt_value(d: dict, which: str) -> str:
    v = d[which] * d["scale"]
    return f"{v:,.4g}{d['unit']}"


def _fmt_rel(rel: float | None) -> str:
    if rel is None:
        return "0->new"  # zero baseline: no finite ratio
    return f"{rel * 100:+.1f}%"


def render(a_arg: str, b_arg: str, a: dict, b: dict,
           deltas: list[dict], skipped: list[str]) -> str:
    out = ["== run diff (A = baseline, B = candidate) =="]
    for label, arg, rec in (("A", a_arg, a), ("B", b_arg, b)):
        out.append(
            f"{label}: {arg} (steps {rec.get('first_step')}.."
            f"{rec.get('last_step')}, {rec.get('windows')} window(s), "
            f"ended: {rec.get('exit_reason') or 'UNKNOWN'})"
        )
    # Placement provenance (schema v5, docs/sharding.md): two runs on
    # different meshes or under different rules are apples-to-oranges —
    # say so FIRST, because "regression" is usually the layout.
    mesh_a, mesh_b = a.get("mesh_shape"), b.get("mesh_shape")
    dig_a = a.get("param_sharding_digest")
    dig_b = b.get("param_sharding_digest")
    if mesh_a is not None and mesh_b is not None and mesh_a != mesh_b:
        out.append(
            f"NOTE: mesh shape changed between runs: {mesh_a} -> {mesh_b}"
        )
    if dig_a is not None and dig_b is not None and dig_a != dig_b:
        out.append(
            "NOTE: param-sharding rules changed between runs "
            f"(digest {dig_a} -> {dig_b})"
        )
    regressed = [d for d in deltas if d["verdict"] == "regressed"]
    improved = [d for d in deltas if d["verdict"] == "improved"]
    out.append(
        f"what changed ({len(regressed)} regressed, {len(improved)} "
        "improved), ranked:"
    )
    for d in deltas:
        tag = {"regressed": "REGRESSED", "improved": "improved ",
               "unchanged": "unchanged"}[d["verdict"]]
        out.append(
            f"  {tag} {d['metric']:<28} {_fmt_rel(d['rel_change']):>8}  "
            f"{_fmt_value(d, 'a')} -> {_fmt_value(d, 'b')}"
        )
    for note in skipped:
        out.append(f"  not comparable: {note}")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("run_a", help="baseline: run dir or report.json")
    ap.add_argument("run_b", help="candidate: run dir or report.json")
    ap.add_argument(
        "--json", metavar="PATH",
        help="write the machine-readable diff here ('-' = stdout); the "
        "candidate's gateable figures are flattened at top level for "
        "bench_gate --record",
    )
    ap.add_argument(
        "--threshold", type=float, default=DEFAULT_THRESHOLD,
        help="relative change below this is 'unchanged' (default 0.02)",
    )
    ap.add_argument(
        "--fail-on-regression", action="store_true",
        help="exit 1 when any metric regressed beyond the threshold",
    )
    args = ap.parse_args(argv)

    a, err_a = load_record(args.run_a)
    if a is None:
        print(f"run_a: {err_a}", file=sys.stderr)
        return 2
    b, err_b = load_record(args.run_b)
    if b is None:
        print(f"run_b: {err_b}", file=sys.stderr)
        return 2
    deltas, skipped = diff_records(a, b, args.threshold)
    print(render(args.run_a, args.run_b, a, b, deltas, skipped))
    regressions = [d for d in deltas if d["verdict"] == "regressed"]
    if args.json:
        doc = {
            "a_path": args.run_a,
            "b_path": args.run_b,
            "threshold": args.threshold,
            "ranked": deltas,
            "not_comparable": skipped,
            "regressions": len(regressions),
            "a": a,
            "b": b,
        }
        # bench_gate --record compatibility: candidate figures on top.
        doc.update({k: b.get(k) for k in GATE_KEYS})
        payload = json.dumps(doc, indent=2) + "\n"
        if args.json == "-":
            sys.stdout.write(payload)
        else:
            with open(args.json, "w") as f:
                f.write(payload)
    if regressions and args.fail_on_regression:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
