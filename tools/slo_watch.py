#!/usr/bin/env python
"""Terminal SLO watcher: poll a router's /alerts + /series (ISSUE 19).

    python tools/slo_watch.py http://127.0.0.1:9000            # loop
    python tools/slo_watch.py http://127.0.0.1:9000 --once     # one poll

Every poll renders the AlertEngine's live state — each rule's
fast/slow burn rates and budget remaining, plus every FIRING alert
with its severity and worst-offender exemplar so the responder's next
command is a copy-paste:

    FIRING ttft_interactive [page] burn 14.2x/3.1x budget 12% left
      -> python tools/trace_report.py traces.jsonl --trace-id tr-ab12..

and a compact tail of the time-series ring (``GET /series`` rollups)
for the instruments behind the burn.

Exit code is the CI/script contract: ``--once`` (and a loop ended by
``--polls N``) exits **1 while any alert is firing**, 0 when healthy,
2 when the endpoint is unreachable — a deploy pipeline can gate a
rollout step on ``slo_watch --once`` exactly like a test. A looping
watch that loses the endpoint after a healthy poll reports "endpoint
gone" and exits with the LAST poll's verdict (the run ended; its
alerts are the verdict that matters).

Works against the router frontend (fleet view: organic + canary
probes) and any replica frontend's ``/series`` (``/alerts`` is
router-side). Stdlib + repo only.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from tensorflow_examples_tpu.serving.router import _get_json  # noqa: E402

# Series rendered in the --series tail when present (the instruments
# the default SLO rules burn on), before any --series globs.
_DEFAULT_SERIES = (
    "router/e2e.p95",
    "router/requests_total",
    "probe/ttft.p95",
    "probe/failed_total",
)


def fetch(base: str, timeout: float) -> tuple[dict | None, dict | None]:
    """(alerts payload, series payload) — either may be None (a replica
    frontend serves /series but not /alerts; a gone endpoint serves
    neither)."""
    status, alerts = _get_json(base + "/alerts", timeout)
    if status != 200 or not isinstance(alerts, dict):
        alerts = None
    status, series = _get_json(base + "/series", timeout)
    if status != 200 or not isinstance(series, dict):
        series = None
    return alerts, series


def render(alerts: dict | None, series: dict | None,
           series_names: list[str]) -> tuple[str, int]:
    """(text, firing count) for one poll."""
    out = []
    firing = 0
    if alerts is not None:
        firing = int(alerts.get("alerts_firing", 0))
        out.append(
            f"slo: {firing} firing, budget remaining "
            f"{alerts.get('error_budget_remaining', 1.0):.1%}, probe "
            f"success {alerts.get('probe_success_rate', 1.0):.1%}, "
            f"{alerts.get('alert_count', 0)} fired total"
        )
        for name, rule in sorted(
            (alerts.get("rules") or {}).items()
        ):
            mark = "FIRING" if rule.get("state") == "firing" else (
                "pending" if rule.get("state") == "pending" else "ok"
            )
            out.append(
                f"  {mark:<7} {name:<24} burn "
                f"{rule.get('burn_rate_fast', 0.0):.1f}x/"
                f"{rule.get('burn_rate_slow', 0.0):.1f}x  budget "
                f"{rule.get('budget_remaining', 1.0):.1%}"
            )
        for a in alerts.get("firing") or []:
            line = (
                f"FIRING {a.get('name')} [{a.get('severity')}] "
                f"slo={a.get('slo')} burn {a.get('burn_rate', 0.0):.1f}x"
            )
            if a.get("replica"):
                line += f" replica={a['replica']}"
            out.append(line)
            if a.get("trace_id"):
                # The exemplar copy-paste (ISSUE 18 discipline).
                out.append(
                    "  -> python tools/trace_report.py <traces.jsonl> "
                    f"--trace-id {a['trace_id']}"
                )
    if series is not None:
        rollups = series.get("rollups") or {}
        names = [n for n in _DEFAULT_SERIES if n in rollups]
        names += [
            n for n in sorted(rollups)
            if any(pat in n for pat in series_names) and n not in names
        ]
        for n in names:
            r = rollups[n]
            out.append(
                f"  series {n:<28} last={r.get('last')} "
                f"p50={r.get('p50')} p95={r.get('p95')} "
                f"p99={r.get('p99')} n={r.get('count')}"
            )
    return "\n".join(out), firing


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("endpoint",
                    help="router (or replica) frontend base URL, e.g. "
                         "http://127.0.0.1:9000")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="seconds between polls (loop mode)")
    ap.add_argument("--once", action="store_true",
                    help="one poll, then exit (1 while firing)")
    ap.add_argument("--polls", type=int, default=0,
                    help=">0: stop after N polls (loop mode)")
    ap.add_argument("--timeout", type=float, default=5.0,
                    help="per-GET timeout (seconds)")
    ap.add_argument("--series", action="append", default=[],
                    metavar="SUBSTR",
                    help="also render /series rollups whose name "
                         "contains SUBSTR (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw /alerts payload per poll "
                         "instead of the rendered view")
    args = ap.parse_args(argv)
    base = args.endpoint.rstrip("/")
    if "://" not in base:
        base = "http://" + base

    last_firing = 0
    seen_healthy = False
    polls = 0
    while True:
        alerts, series = fetch(base, args.timeout)
        if alerts is None and series is None:
            if seen_healthy:
                print("endpoint gone: run ended", file=sys.stderr)
                return 1 if last_firing else 0
            print(f"unreachable: {base}", file=sys.stderr)
            return 2
        seen_healthy = True
        if args.json:
            print(json.dumps(alerts if alerts is not None else series))
            last_firing = int((alerts or {}).get("alerts_firing", 0))
        else:
            text, last_firing = render(alerts, series, args.series)
            print(f"-- {time.strftime('%H:%M:%S')} {base}")
            print(text)
        sys.stdout.flush()
        polls += 1
        if args.once or (args.polls and polls >= args.polls):
            return 1 if last_firing else 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
