#!/usr/bin/env python
"""On-chip flash-attention block-size autotune.

The Pallas flash kernel's auto block sizing targets 256x256 on the
strength of ONE end-to-end measurement (ops/attention.py:_prepare,
~1.3% over 128 on GPT-2 124M b8 s1024, round 2). This tool sweeps
block_q x block_kv over the benched shapes, forward AND
forward+backward, on the real chip — so the default can be set from a
measured table instead of a single point, and the evidence is banked
in docs/tpu_sweeps/ like every other on-chip record.

Run by tools/diag_watch.sh on a live window after the small-step diag
banks. Emits ONE JSON line (always-emit watchdog, bench.py pattern);
a truncated snapshot still carries every completed (shape, config)
cell.

Usage: python tools/flash_tune.py [--budget=SECS]
"""

import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402  (repo-root bench.py: backend resolution, probes)
from tools.diag_common import (  # noqa: E402
    enable_compile_cache, make_emit, parse_budget, start_watchdog,
)

OUT: dict = {"diag": "flash_tune", "shapes": []}
_emit = make_emit(OUT)

# (name, batch, heads, seq, head_dim, causal, timing iters/window).
# gpt2/gpt2_long mirror the bench shapes; bert's seq 128 admits only
# one block config so it is not worth sweeping.
SHAPES = [
    ("gpt2_b8_s1024", 8, 12, 1024, 64, True, 30),
    ("gpt2_long_b2_s4096", 2, 12, 4096, 64, True, 8),
]
BLOCKS = (128, 256, 512)


def _time(fn, args, iters: int, windows: int = 3) -> float:
    """Median ms per call over ``windows`` timing windows."""
    import jax

    jax.block_until_ready(fn(*args))  # compile + warm
    ts = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        ts.append((time.perf_counter() - t0) / iters)
    return statistics.median(ts) * 1e3


def _sweep_shape(name, b, h, s, d, causal, iters, deadline) -> dict:
    import jax
    import jax.numpy as jnp

    from tensorflow_examples_tpu.ops.attention import flash_attention

    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, h, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, h, s, d), jnp.bfloat16)
    rec = {"name": name, "batch": b, "heads": h, "seq": s, "head_dim": d,
           "causal": causal, "cells": []}
    for bq in BLOCKS:
        for bk in BLOCKS:
            if s % bq or s % bk:
                continue
            if time.monotonic() > deadline:
                rec["truncated"] = True
                return rec

            def fwd(q, k, v, _bq=bq, _bk=bk):
                return flash_attention(
                    q, k, v, causal=causal, block_q=_bq, block_kv=_bk
                ).mean()

            fwd_j = jax.jit(fwd)
            bwd_j = jax.jit(jax.grad(fwd, argnums=(0, 1, 2)))
            cell = {"block_q": bq, "block_kv": bk}
            cell["fwd_ms"] = round(_time(fwd_j, (q, k, v), iters), 4)
            cell["fwdbwd_ms"] = round(_time(bwd_j, (q, k, v), iters), 4)
            rec["cells"].append(cell)
    if rec["cells"]:
        rec["best_fwd"] = min(rec["cells"], key=lambda c: c["fwd_ms"])
        rec["best_fwdbwd"] = min(rec["cells"], key=lambda c: c["fwdbwd_ms"])
    return rec


def main() -> int:
    budget = parse_budget(sys.argv[1:])
    deadline = time.monotonic() + budget
    watchdog = start_watchdog(budget, _emit)
    try:
        bench.BACKEND = bench._resolve_backend()
        OUT["backend"] = bench.BACKEND
        if bench.BACKEND != "tpu":
            # Interpret-mode cells would time Python, not the chip —
            # same stance as bench.py's decode_grid microbench.
            OUT["error"] = "tpu-only microbench"
        else:
            # ~2 compiles per cell over a tunnel that charges 10-40 s
            # per compile: a cold full sweep may exceed any sane
            # budget. The persistent cache makes each retry window
            # cheaper until a complete pass fits.
            enable_compile_cache()
            OUT["probe_tflops"] = round(bench._probe_quick(), 2)
            OUT["launch_us"] = round(bench._probe_launch_us(), 2)
            for shape in SHAPES:
                if time.monotonic() > deadline:
                    OUT["truncated"] = True
                    break
                OUT["shapes"].append(_sweep_shape(*shape, deadline))
            # The banking gate keys on this: a partial table must NOT
            # freeze the tune stage (the whole point is a full table).
            OUT["complete"] = (
                "truncated" not in OUT
                and len(OUT["shapes"]) == len(SHAPES)
                and all(
                    not s.get("truncated") and s.get("cells")
                    for s in OUT["shapes"]
                )
            )
    except Exception as e:  # noqa: BLE001 — partials must still emit
        OUT["error"] = f"{type(e).__name__}: {e}"
    watchdog.cancel()
    _emit()
    return 0


if __name__ == "__main__":
    sys.exit(main())
