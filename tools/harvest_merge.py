#!/usr/bin/env python
"""Merge per-bench harvest JSONs into one sweep-shaped record.

The incremental TPU harvest (tools/tpu_harvest.sh) runs each bench as
its own ``python bench.py --bench=<name>`` subprocess so that a tunnel
wedge mid-campaign loses only the bench in flight, never the window's
completed results. Each subprocess emits a self-contained record
(its own backend probe, pre/post fingerprints, probe_tflops_at_bench,
rel_mfu). This tool folds a directory of those into ONE record shaped
like a ``--bench=all`` sweep so ``tools/stamp_floors.py`` can print the
floor stamps unchanged.

Merge semantics:
- headline = resnet50 record if present, else the first by ALL_ORDER;
- ``extras`` = every other completed record;
- every record keeps its own pre/post fingerprints (stamp_floors
  stamps per record); min/max over ALL pre/post probes — the rig
  drift across the harvest window, wedged probes included — is
  recorded as ``fingerprint_spread`` so BASELINE.md can quote it;
- records whose backend != the majority backend are dropped loudly
  (a probe that fell back to CPU mid-harvest must not stamp TPU
  floors);
- a ``harvested`` list names the per-bench files folded in.

Usage: python tools/harvest_merge.py /tmp/tpu_harvest/results > merged.json
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
try:
    # Single source of truth for the bench list — hand-duplicating it
    # here would silently miss benches added to bench.py later.
    from bench import ALL_ORDER as ORDER  # noqa: E402
except Exception:  # bench.py imports jax; fall back if that breaks
    ORDER = [
        "resnet50", "resnet50_input", "gpt2", "gpt2_long", "gpt2_long16k",
        "gpt2_decode", "gpt2_decode_long", "bert", "cifar10", "mnist",
        "collectives", "moe", "decode_grid",
    ]


def main() -> int:
    if len(sys.argv) != 2:
        print(__doc__)
        return 2
    d = sys.argv[1]
    recs = {}
    selftest = None
    for fn in sorted(os.listdir(d)):
        if not fn.endswith(".json"):
            continue
        path = os.path.join(d, fn)
        try:
            with open(path) as f:
                r = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"merge: skipping {fn}: {e}", file=sys.stderr)
            continue
        if r.get("metric") == "selftest" or "selftest" in r:
            st = r.get("selftest")
            if st is not None:
                selftest = st
            if r.get("metric") == "selftest":
                continue
        name = r.get("bench") or fn[:-5]
        if "error" in r:
            print(f"merge: {name} errored: {r['error']}", file=sys.stderr)
        recs[name] = r

    if not recs:
        print("merge: no bench records found", file=sys.stderr)
        return 1

    # Prefer tpu whenever ANY tpu record exists: a cpu-fallback majority
    # (tunnel died early) must never cause the chip-measured records to
    # be the ones dropped.
    backends = {r.get("backend", "?") for r in recs.values()}
    backend = "tpu" if "tpu" in backends else sorted(backends)[0]
    dropped = [n for n, r in recs.items() if r.get("backend", "?") != backend]
    for n in dropped:
        print(f"merge: DROPPING {n} (backend {recs[n].get('backend')!r} != "
              f"majority {backend!r})", file=sys.stderr)
        del recs[n]

    pres = [r["fingerprint_tflops_pre"] for r in recs.values()
            if isinstance(r.get("fingerprint_tflops_pre"), (int, float))]
    posts = [r["fingerprint_tflops_post"] for r in recs.values()
             if isinstance(r.get("fingerprint_tflops_post"), (int, float))]
    fps = pres + posts

    # Per-bench records may themselves carry sweep-level keys: a bench
    # subprocess's _assemble attaches any previously-banked harvest as
    # "tpu_harvest" (and lists its own skipped siblings as
    # "truncated"). Left in place these would nest the merged artifact
    # inside itself, one level per finalize cycle.
    for r in recs.values():
        for k in ("tpu_harvest", "extras", "truncated", "harvested"):
            r.pop(k, None)

    ordered = sorted(recs, key=lambda n: ORDER.index(n) if n in ORDER else 99)
    head_name = "resnet50" if "resnet50" in recs else ordered[0]
    out = dict(recs[head_name])
    out["extras"] = [recs[n] for n in ordered if n != head_name]
    out["backend"] = backend
    if fps:
        # The head record keeps ITS OWN pre/post fingerprints (it is a
        # self-contained bench record; stamp_floors stamps each metric
        # with its record's own probe). The window-wide drift — which
        # can include a wedged probe observed at ~78 vs the healthy
        # ~40-100k range — lives only in fingerprint_spread.
        out["fingerprint_spread"] = [min(fps), max(fps)]
    out["harvested"] = ordered
    missing = [n for n in ORDER if n not in recs]
    if missing:
        out["truncated"] = missing
    if selftest is not None:
        out["selftest"] = selftest
    json.dump(out, sys.stdout)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
