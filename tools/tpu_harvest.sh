#!/bin/bash
# Incremental TPU harvest — the wedge-tolerant successor to
# tpu_campaign.sh, written after the 2026-07-30 18:10 window was lost:
# the tunnel answered ONE probe, wedged during the selftest's first
# kernel compile, and the monolithic campaign got zero perf numbers out
# of a ~15-minute live window.
#
# Design:
#  - loop: probe the tunnel; while down, sleep and re-probe — this
#    script IS the watcher;
#  - on a live probe: run benches ONE PER SUBPROCESS, most-valuable
#    first, each `python bench.py --bench=<name>` bounded by
#    run_bounded (never `wait`s on an unkillably-wedged child — the
#    axon driver hang survives SIGKILL, so GNU timeout alone would
#    block forever exactly where the watcher must not). Every record is
#    self-contained (own backend probe + fingerprints + rel_mfu), lands
#    in $OUT/results/<name>.json the moment it completes, and is never
#    re-run on later passes — a wedge loses only the bench in flight;
#  - on a bench timeout: re-probe; if the tunnel is dead, back to the
#    wait loop (completed results keep accumulating across windows);
#  - after all benches: compiled-kernel selftest via pytest -v with a
#    per-test SIGALRM timeout (tests_tpu/conftest.py) so the log names
#    the test that wedges;
#  - finally: merge (tools/harvest_merge.py) + floor stamps
#    (tools/stamp_floors.py); the merged record is copied to a FIXED
#    path in docs/tpu_sweeps/ (overwritten per finalize, so partial
#    finalizes don't accumulate near-duplicates in the repo).
#
# The 1-core host is shared with the CPU test suite; any `pytest tests/`
# is SIGSTOPped for the duration of a live-window harvest and SIGCONTed
# after, so device-dispatch timing is never contended.
set -u
cd "$(dirname "$0")/.."
OUT=${1:-/tmp/tpu_harvest}
mkdir -p "$OUT/results" docs/tpu_sweeps
echo "harvest -> $OUT"

# Most-valuable-first: north star, headline LM, the three round-2
# sub-floor metrics (bert/resnet50_input/allreduce), the unfloored new
# benches, then the rest. decode_grid is the VERDICT r3 item-4
# measurement (single-token step time vs max_len).
BENCH_ORDER=${TPU_HARVEST_BENCHES:-"resnet50 gpt2 bert resnet50_input collectives gpt2_decode gpt2_decode_long moe decode_grid cifar10 mnist gpt2_long gpt2_long16k"}

# Rehearsal knobs (defaults are production): WANT_BACKEND lets the
# whole pipeline be dress-rehearsed against the CPU fallback backend;
# DEST redirects the banked-evidence copy away from the repo;
# SKIP_SELFTEST bounds a rehearsal that has no TPU to collect against.
WANT_BACKEND=${TPU_HARVEST_BACKEND:-tpu}
DEST=${TPU_HARVEST_DEST:-docs/tpu_sweeps/round5_merged.json}

# Benches whose floors need MULTI-WINDOW medians (VERDICT r4 missing
# #4: decode_grid unfloored single-window, gpt2_decode_long a 3.3x
# window band, moe restamping after the dispatch rewrite): after a
# full finalize, each is archived to results/history/ and re-measured
# on later windows until REPEAT_N separate-window records exist; the
# merged artifact always carries the latest, history carries the rest
# (tools/multiwindow_floors.py turns them into one median stamp).
REPEAT_BENCHES=${TPU_HARVEST_REPEATS:-"gpt2_decode_long moe decode_grid"}
REPEAT_N=${TPU_HARVEST_REPEAT_N:-3}

# Wedge-tolerant process discipline (run_bounded / probe / pause_suite)
# is shared with tools/diag_watch.sh:
. tools/lib_bounded.sh

budget_for() {
  case "$1" in
    moe) echo 560;;
    resnet50_input) echo 470;;
    *) echo 400;;
  esac
}

all_done() {
  for b in $BENCH_ORDER; do
    [ -s "$OUT/results/$b.json" ] || return 1
  done
  return 0
}

# Benches (and selftest nodes) are retried least-attempted-first: if
# one item reliably wedges the tunnel (e.g. a specific kernel
# compile), naive in-order retries would burn EVERY window on it and
# never reach the items behind it. Stable sort keeps the
# most-valuable-first order within an attempt count.
bump_attempts() {  # $1=counter file -> increments it
  local f="$1" n=0
  [ -f "$f" ] && n=$(cat "$f" 2>/dev/null || echo 0)
  n=$((n + 1))
  echo "$n" > "$f"
}

order_by_attempts() {  # stdin: one item per line; $1: counter dir
  local dir="$1"
  while IFS= read -r it; do
    local a=0 cf="$dir/$(echo "$it" | tr '/:[] ' '_____').attempts"
    [ -f "$cf" ] && a=$(cat "$cf" 2>/dev/null || echo 0)
    printf '%05d %s\n' "$a" "$it"
  done | sort -s -k1,1 | cut -d' ' -f2-
}

# Compiled-kernel selftest, banked PER TEST NODE like the benches: one
# bounded pytest subprocess per node id, status files accumulate across
# live windows, wedges/timeouts retry next window but assertion
# failures are kept as evidence. The persistent compile cache
# (tests_tpu/conftest.py) makes retries cheap.
#
# Each status file records the tests_tpu/+ops/ source hash the node ran
# under (line 3, tools/kernel_source_hash.py): a status from BEFORE a
# kernel edit is stale evidence and is treated as not-run (ADVICE r4 —
# bench.py's banked-reuse check guards the same hash on the consumer
# side).
node_status_file() {
  echo "$OUT/selftest_status/$(echo "$1" | tr '/:[]' '____').status"
}

refresh_kernel_hash() {
  CUR_KHASH=$(env -u PALLAS_AXON_POOL_IPS python tools/kernel_source_hash.py 2>/dev/null)
  [ -n "$CUR_KHASH" ]
}

node_status_valid() {  # $1=node — banked AND from the current sources
  local sf
  sf=$(node_status_file "$1")
  [ -s "$sf" ] && [ "$(sed -n 3p "$sf")" = "$CUR_KHASH" ]
}

collect_nodes() {
  [ -s "$OUT/selftest_nodes.txt" ] && return 0
  # Cache the node list only on a FULLY clean collection (rc=0): a
  # partial collection (rc=2, some modules errored) still prints node
  # ids, and caching those would silently truncate the suite while the
  # final record claims full coverage.
  run_bounded 300 "$OUT/selftest_collect.log" \
    python -m pytest tests_tpu/ --collect-only -q
  [ $? -eq 0 ] || { echo "  selftest: collection rc!=0, not caching"; return 1; }
  grep "::" "$OUT/selftest_collect.log" | sed 's/\r$//' > "$OUT/selftest_nodes.txt"
  [ -s "$OUT/selftest_nodes.txt" ]
}

run_selftest_nodes() {
  mkdir -p "$OUT/selftest_status"
  collect_nodes || { echo "  selftest: collection failed/empty"; return 1; }
  refresh_kernel_hash || { echo "  selftest: kernel hash failed"; return 1; }
  order_by_attempts "$OUT/attempts" < "$OUT/selftest_nodes.txt" \
    > "$OUT/selftest_nodes.run"
  while IFS= read -r node; do
    sf=$(node_status_file "$node")
    if node_status_valid "$node"; then continue; fi
    if [ -s "$sf" ]; then
      echo "$(date -u +%H:%M:%S)   selftest $node status STALE (kernel sources changed) — re-running"
      rm -f "$sf"
    fi
    defer_for_driver_bench 0
    bump_attempts "$OUT/attempts/$(echo "$node" | tr '/:[] ' '_____').attempts"
    echo "$(date -u +%H:%M:%S)   selftest $node"
    run_bounded 460 "$OUT/selftest_status/last_run.log" \
      python -m pytest "$node" -q
    rc=$?
    if [ $rc -eq 0 ]; then
      { echo "pass"; echo "$node"; echo "$CUR_KHASH"; } > "$sf"
      continue
    fi
    if [ $rc -eq 124 ]; then
      # Keep the wedge diagnostic (which compile hung) before the next
      # node's run overwrites last_run.log; retry next window.
      cp "$OUT/selftest_status/last_run.log" "$sf.wedge.log" 2>/dev/null
      echo "$(date -u +%H:%M:%S)   selftest $node WEDGED (retry next window)"
      if ! probe "$WANT_BACKEND"; then return 1; fi
      continue
    fi
    # Non-timeout nonzero rc: only pytest rc=1 with a real failure
    # summary is a GENUINE compiled-numerics failure worth banking.
    # rc=5/"no tests ran" means the conftest probe saw a dead backend
    # (fast tunnel death) and rc=2/3/4 are collection/usage/interrupt —
    # all transient harness states, NOT test evidence: re-probe and
    # retry next window.
    if [ $rc -eq 1 ] && grep -qE "^(FAILED|ERROR)|= *[0-9]+ failed" \
         "$OUT/selftest_status/last_run.log"; then
      { echo "fail rc=$rc"; echo "$node"; echo "$CUR_KHASH";
        tail -40 "$OUT/selftest_status/last_run.log"; } > "$sf"
      echo "$(date -u +%H:%M:%S)   selftest $node FAILED rc=$rc"
    else
      cp "$OUT/selftest_status/last_run.log" "$sf.transient.log" 2>/dev/null
      echo "$(date -u +%H:%M:%S)   selftest $node transient rc=$rc (retry next window)"
      if ! probe "$WANT_BACKEND"; then return 1; fi
    fi
  done < "$OUT/selftest_nodes.run"
  return 0
}

selftest_done() {
  [ -n "${TPU_HARVEST_SKIP_SELFTEST:-}" ] && return 0
  [ -s "$OUT/selftest_nodes.txt" ] || return 1
  # Always re-read: kernel sources can change between windows while
  # this watcher keeps running, and a cached hash would let stale
  # statuses satisfy the done check.
  refresh_kernel_hash || return 1
  while IFS= read -r node; do
    node_status_valid "$node" || return 1
  done < "$OUT/selftest_nodes.txt"
  return 0
}

write_selftest_record() {
  # Emitted even PARTIAL (some nodes still unattempted/wedged): the
  # banked per-node passes are on-chip evidence and must survive the
  # tunnel never reviving — `ok` stays strict (every node passed), and
  # `complete` says whether the whole suite has run. Status files are
  # the single source of truth: line 1 = pass/fail, line 2 = the node
  # id (so this reader never re-derives the shell's filename
  # sanitization).
  [ -s "$OUT/selftest_nodes.txt" ] || return 0
  env -u PALLAS_AXON_POOL_IPS python - "$OUT" "$WANT_BACKEND" <<'EOF'
import glob, json, os, sys
sys.path.insert(0, "tools")
from kernel_source_hash import kernel_source_hash

out, backend = sys.argv[1], sys.argv[2]
cur_hash = kernel_source_hash()
n_nodes = sum(1 for l in open(os.path.join(out, "selftest_nodes.txt")) if l.strip())
statuses = []
stale = 0
for path in sorted(glob.glob(os.path.join(out, "selftest_status", "*.status"))):
    with open(path) as f:
        status = f.readline().strip()
        node = f.readline().strip() or os.path.basename(path)
        ran_hash = f.readline().strip()
    # A status from before a kernel-source edit is NOT evidence about
    # the current code: count it as not-run (the harvest re-runs it).
    if ran_hash != cur_hash:
        stale += 1
        continue
    statuses.append((node, status))
fails = sorted(n for n, s in statuses if not s.startswith("pass"))
n_pass = len(statuses) - len(fails)
complete = len(statuses) == n_nodes
ok = not fails and complete
summary = (f"{n_pass}/{n_nodes} compiled-kernel tests passed on {backend} "
           f"(per-node bounded subprocesses, banked across live windows)")
if not complete:
    summary += (f"; {n_nodes - len(statuses)} not yet run on a live window "
                "(retried per window)")
if stale:
    summary += f"; {stale} stale statuses (kernel sources changed) dropped"
if fails:
    summary += "; failed: " + ", ".join(fails)
rec = {"metric": "selftest", "backend": backend,
       "selftest": {"ok": ok, "complete": complete, "passed": n_pass,
                    "total": n_nodes, "summary": summary,
                    "kernel_source_hash": cur_hash,
                    "nodes": {n: s for n, s in statuses}}}
json.dump(rec, open(os.path.join(out, "results", "selftest.json"), "w"))
EOF
}

# One-shot window measurements (the old tools/diag_watch.sh queue,
# folded in here in round 5: the two-watcher split starved the
# follow-ons whenever the harvest couldn't finish — e.g. the round-4
# lse wedge — because diag_watch waited for harvest EXIT. One process
# owning the whole window priority queue spends windows better).
# Run AFTER benches + selftest attempts in a window, least-attempted
# first so a reliably-wedging stage (lse_bisect exists to poke a known
# tunnel-wedging compile) can't starve the others. Each banks its last
# parseable JSON line to a fixed dest iff its gate holds, and is never
# re-run once banked.
ONESHOTS="moediag diag tune profile lsebisect"
oneshot_spec() {  # $1=name -> "budget|dest|gate|cmd..."
  case "$1" in
    moediag) echo "700|docs/tpu_sweeps/round5_moe_diag.json|(rec.get(\"backend\") == \"tpu\" and bool(rec.get(\"complete\")))|python tools/moe_diag.py --budget=600";;
    diag) echo "700|docs/tpu_sweeps/round5_diag.json|(rec.get(\"backend\") == \"tpu\" and \"error\" not in rec and len(rec.get(\"cifar10\") or []) >= 2 and len(rec.get(\"bert\") or []) >= 2)|python tools/diag_smallstep.py --budget=600";;
    tune) echo "700|docs/tpu_sweeps/round5_flash_tune.json|bool(rec.get(\"complete\"))|python tools/flash_tune.py --budget=600";;
    profile) echo "520|docs/tpu_sweeps/round5_profile.json|bool(rec.get(\"complete\"))|python tools/profile_trace.py --budget=420";;
    lsebisect) echo "900|docs/tpu_sweeps/round5_lse_bisect.json|bool(rec.get(\"complete\"))|python tools/lse_bisect.py --budget=780";;
  esac
}

bank_last_json() {  # $1=log $2=dest $3=gate-expr over `rec`
  env -u PALLAS_AXON_POOL_IPS python - "$1" "$2" "$3" <<'EOF'
import json, sys
sys.path.insert(0, "tools")
from last_json_line import last_json_line
rec = last_json_line(sys.argv[1])
ok = rec is not None and bool(eval(sys.argv[3], {"rec": rec, "len": len}))
if ok:
    json.dump(rec, open(sys.argv[2], "w"))
sys.exit(0 if ok else 1)
EOF
}

oneshots_done() {
  local n spec dest
  for n in $ONESHOTS; do
    spec=$(oneshot_spec "$n")
    dest=$(echo "$spec" | cut -d'|' -f2)
    [ -s "$dest" ] || return 1
  done
  return 0
}

run_oneshots() {
  mkdir -p "$OUT/oneshots"
  local n spec bud dest gate cmd
  for n in $(printf '%s\n' $ONESHOTS | order_by_attempts "$OUT/attempts"); do
    spec=$(oneshot_spec "$n")
    bud=$(echo "$spec" | cut -d'|' -f1)
    dest=$(echo "$spec" | cut -d'|' -f2)
    gate=$(echo "$spec" | cut -d'|' -f3)
    cmd=$(echo "$spec" | cut -d'|' -f4-)
    [ -s "$dest" ] && continue
    defer_for_driver_bench 0
    if ! probe "$WANT_BACKEND"; then return 1; fi
    bump_attempts "$OUT/attempts/$n.attempts"  # same name order_by_attempts reads
    echo "$(date -u +%H:%M:%S)   oneshot $n (budget ${bud}s)"
    run_bounded "$bud" "$OUT/oneshots/$n.log" $cmd
    if bank_last_json "$OUT/oneshots/$n.log" "$dest" "$gate"; then
      echo "$(date -u +%H:%M:%S)   $n banked: $dest"
    else
      echo "$(date -u +%H:%M:%S)   $n incomplete (see $OUT/oneshots/$n.log); retry next window"
    fi
  done
  return 0
}

# rotate_repeats — archive each REPEAT bench's current record into
# results/history/<bench>.w<N>.json and delete the live one so the next
# window re-measures it, until each has REPEAT_N separate-window
# records (live + history). Called ONLY from the tunnel-down branch:
# rotating inside a live window would re-measure on the same tunnel
# instance, and same-instance records can't capture the cross-window
# dispatch spread the multi-window floors exist to bound.
rotate_repeats() {
  local b n
  mkdir -p "$OUT/results/history"
  for b in $REPEAT_BENCHES; do
    [ -s "$OUT/results/$b.json" ] || continue
    n=$(ls "$OUT/results/history/$b".w*.json 2>/dev/null | wc -l)
    if [ "$((n + 1))" -lt "$REPEAT_N" ]; then
      mv "$OUT/results/$b.json" "$OUT/results/history/$b.w$((n + 1)).json"
      echo "$(date -u +%H:%M:%S) rotated $b for re-measure (window $((n + 1))/$REPEAT_N banked)"
    fi
  done
}

repeats_satisfied() {  # every repeat bench has REPEAT_N window records
  local b n
  for b in $REPEAT_BENCHES; do
    [ -s "$OUT/results/$b.json" ] || return 1
    n=$(ls "$OUT/results/history/$b".w*.json 2>/dev/null | wc -l)
    [ "$((n + 1))" -ge "$REPEAT_N" ] || return 1
  done
  return 0
}

finalize() {
  resume_suite
  if env -u PALLAS_AXON_POOL_IPS python tools/harvest_merge.py "$OUT/results" > "$OUT/merged.json" 2> "$OUT/merge.err" \
     && [ -s "$OUT/merged.json" ] \
     && env -u PALLAS_AXON_POOL_IPS python -c "import json,sys; json.load(open(sys.argv[1]))" "$OUT/merged.json" 2>/dev/null; then
    env -u PALLAS_AXON_POOL_IPS python tools/stamp_floors.py "$OUT/merged.json" > "$OUT/stamp.txt" 2>&1
    mkdir -p "$(dirname "$DEST")"
    if cp "$OUT/merged.json" "$DEST"; then
      echo "harvest finalized: $OUT/stamp.txt (banked: $DEST)"
    else
      echo "harvest finalize: COPY TO $DEST FAILED; evidence only in $OUT"
    fi
  else
    # Never clobber previously-banked evidence with a failed merge.
    echo "harvest finalize: merge failed (see $OUT/merge.err); banked artifact untouched"
  fi
}

trap 'resume_suite; rm -f /tmp/tpu_live' EXIT

while true; do
  defer_for_driver_bench
  if ! probe "$WANT_BACKEND"; then
    rm -f /tmp/tpu_live
    # The instance is gone: now (and only now) a repeat bench may be
    # rotated out for a genuinely-different-window re-measure.
    if all_done && ! repeats_satisfied; then rotate_repeats; fi
    echo "$(date -u +%H:%M:%S) tunnel down"
    sleep 90
    continue
  fi
  echo "$(date -u +%H:%M:%S) TUNNEL LIVE — harvesting"
  touch /tmp/tpu_live
  pause_suite
  window_ok=1
  mkdir -p "$OUT/attempts"
  for b in $(printf '%s\n' $BENCH_ORDER | order_by_attempts "$OUT/attempts"); do
    [ -s "$OUT/results/$b.json" ] && continue
    # A driver bench can start mid-window; never time a bench against
    # it (suite already paused by this window — don't manage it).
    defer_for_driver_bench 0
    bump_attempts "$OUT/attempts/$b.attempts"
    bud=$(budget_for "$b")
    echo "$(date -u +%H:%M:%S)   bench $b (budget ${bud}s)"
    : > "$OUT/results/$b.part"
    # In cpu rehearsal the bench child must also be pinned: its own
    # probe could see a live accelerator, tag records backend=tpu, and
    # livelock the accept check below.
    force=""
    [ "$WANT_BACKEND" = cpu ] && force=cpu
    BENCH_HARVEST_CHILD=1 BENCH_FORCE_BACKEND="$force" \
      run_bounded $((bud + 40)) "$OUT/results/$b.err2" \
      python bench.py --bench="$b" --budget="$bud" --no-selftest
    rc=$?
    # bench.py prints the ONE json line on stdout; stdout+stderr are
    # merged in the log, so extract the last line that parses. The
    # wanted backend is passed as argv so shell and Python can never
    # disagree on empty-string semantics.
    env -u PALLAS_AXON_POOL_IPS python - "$OUT/results/$b.err2" "$OUT/results/$b.part" "$WANT_BACKEND" <<'EOF'
import json, sys
sys.path.insert(0, "tools")
from last_json_line import last_json_line
rec = last_json_line(sys.argv[1])
if rec is not None:
    json.dump(rec, open(sys.argv[2], "w"))
sys.exit(0 if rec is not None
         and rec.get("backend") == sys.argv[3]
         and "error" not in rec else 1)
EOF
    ok=$?
    # Accept on a valid tpu record even if run_bounded hit its
    # deadline: the bench watchdog emits the JSON line before the
    # budget, so rc=124 with a parseable record means "completed,
    # then wedged on exit" — keep the evidence.
    if [ $ok -eq 0 ]; then
      mv "$OUT/results/$b.part" "$OUT/results/$b.json"
      echo "$(date -u +%H:%M:%S)   $b OK"
      continue
    fi
    echo "$(date -u +%H:%M:%S)   $b failed (rc=$rc parse_ok=$ok)"
    rm -f "$OUT/results/$b.part"
    if ! probe "$WANT_BACKEND"; then
      echo "$(date -u +%H:%M:%S) tunnel died mid-window; waiting"
      rm -f /tmp/tpu_live
      window_ok=0
      break
    fi
  done
  if [ $window_ok -eq 1 ] && all_done && ! selftest_done; then
    echo "$(date -u +%H:%M:%S) benches complete — compiled-kernel selftest"
    run_selftest_nodes || window_ok=0
    write_selftest_record
  fi
  # One-shots run even while the selftest is incomplete (a perpetually
  # wedging node must not starve them — the round-4 failure mode for
  # flash_tune), but only after this window already banked the benches
  # it could.
  if [ $window_ok -eq 1 ] && all_done && ! oneshots_done; then
    run_oneshots || window_ok=0
  fi
  if all_done && selftest_done && oneshots_done && repeats_satisfied; then
    finalize
    echo "$(date -u +%H:%M:%S) all benches + selftest + oneshots + repeat windows banked"
    exit 0
  fi
  if [ $window_ok -eq 1 ]; then
    # Benches done but selftest unresolved (or a bench keeps erroring):
    # partial finalize so stamps exist NOW, then keep trying.
    finalize
    sleep 120
  fi
  resume_suite
done
