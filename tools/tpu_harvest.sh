#!/bin/bash
# Incremental TPU harvest — the wedge-tolerant successor to
# tpu_campaign.sh, written after the 2026-07-30 18:10 window was lost:
# the tunnel answered ONE probe, wedged during the selftest's first
# kernel compile, and the monolithic campaign got zero perf numbers out
# of a ~15-minute live window.
#
# Design:
#  - loop: probe the tunnel; while down, sleep and re-probe — this
#    script IS the watcher;
#  - on a live probe: run benches ONE PER SUBPROCESS, most-valuable
#    first, each `python bench.py --bench=<name>` bounded by
#    run_bounded (never `wait`s on an unkillably-wedged child — the
#    axon driver hang survives SIGKILL, so GNU timeout alone would
#    block forever exactly where the watcher must not). Every record is
#    self-contained (own backend probe + fingerprints + rel_mfu), lands
#    in $OUT/results/<name>.json the moment it completes, and is never
#    re-run on later passes — a wedge loses only the bench in flight;
#  - on a bench timeout: re-probe; if the tunnel is dead, back to the
#    wait loop (completed results keep accumulating across windows);
#  - after all benches: compiled-kernel selftest via pytest -v with a
#    per-test SIGALRM timeout (tests_tpu/conftest.py) so the log names
#    the test that wedges;
#  - finally: merge (tools/harvest_merge.py) + floor stamps
#    (tools/stamp_floors.py); the merged record is copied to a FIXED
#    path in docs/tpu_sweeps/ (overwritten per finalize, so partial
#    finalizes don't accumulate near-duplicates in the repo).
#
# The 1-core host is shared with the CPU test suite; any `pytest tests/`
# is SIGSTOPped for the duration of a live-window harvest and SIGCONTed
# after, so device-dispatch timing is never contended.
set -u
cd "$(dirname "$0")/.."
OUT=${1:-/tmp/tpu_harvest}
mkdir -p "$OUT/results" docs/tpu_sweeps
echo "harvest -> $OUT"

# Most-valuable-first: north star, headline LM, the three round-2
# sub-floor metrics (bert/resnet50_input/allreduce), the unfloored new
# benches, then the rest. decode_grid is the VERDICT r3 item-4
# measurement (single-token step time vs max_len).
BENCH_ORDER=${TPU_HARVEST_BENCHES:-"resnet50 gpt2 bert resnet50_input collectives gpt2_decode gpt2_decode_long moe decode_grid cifar10 mnist gpt2_long gpt2_long16k"}

# Rehearsal knobs (defaults are production): WANT_BACKEND lets the
# whole pipeline be dress-rehearsed against the CPU fallback backend;
# DEST redirects the banked-evidence copy away from the repo;
# SKIP_SELFTEST bounds a rehearsal that has no TPU to collect against.
WANT_BACKEND=${TPU_HARVEST_BACKEND:-tpu}
DEST=${TPU_HARVEST_DEST:-docs/tpu_sweeps/round4_merged.json}

# Wedge-tolerant process discipline (run_bounded / probe / pause_suite)
# is shared with tools/diag_watch.sh:
. tools/lib_bounded.sh

budget_for() {
  case "$1" in
    moe) echo 560;;
    resnet50_input) echo 470;;
    *) echo 400;;
  esac
}

all_done() {
  for b in $BENCH_ORDER; do
    [ -s "$OUT/results/$b.json" ] || return 1
  done
  return 0
}

# Benches (and selftest nodes) are retried least-attempted-first: if
# one item reliably wedges the tunnel (e.g. a specific kernel
# compile), naive in-order retries would burn EVERY window on it and
# never reach the items behind it. Stable sort keeps the
# most-valuable-first order within an attempt count.
bump_attempts() {  # $1=counter file -> increments it
  local f="$1" n=0
  [ -f "$f" ] && n=$(cat "$f" 2>/dev/null || echo 0)
  n=$((n + 1))
  echo "$n" > "$f"
}

order_by_attempts() {  # stdin: one item per line; $1: counter dir
  local dir="$1"
  while IFS= read -r it; do
    local a=0 cf="$dir/$(echo "$it" | tr '/:[] ' '_____').attempts"
    [ -f "$cf" ] && a=$(cat "$cf" 2>/dev/null || echo 0)
    printf '%05d %s\n' "$a" "$it"
  done | sort -s -k1,1 | cut -d' ' -f2-
}

# Compiled-kernel selftest, banked PER TEST NODE like the benches: one
# bounded pytest subprocess per node id, status files accumulate across
# live windows, wedges/timeouts retry next window but assertion
# failures are kept as evidence. The persistent compile cache
# (tests_tpu/conftest.py) makes retries cheap.
node_status_file() {
  echo "$OUT/selftest_status/$(echo "$1" | tr '/:[]' '____').status"
}

collect_nodes() {
  [ -s "$OUT/selftest_nodes.txt" ] && return 0
  # Cache the node list only on a FULLY clean collection (rc=0): a
  # partial collection (rc=2, some modules errored) still prints node
  # ids, and caching those would silently truncate the suite while the
  # final record claims full coverage.
  run_bounded 300 "$OUT/selftest_collect.log" \
    python -m pytest tests_tpu/ --collect-only -q
  [ $? -eq 0 ] || { echo "  selftest: collection rc!=0, not caching"; return 1; }
  grep "::" "$OUT/selftest_collect.log" | sed 's/\r$//' > "$OUT/selftest_nodes.txt"
  [ -s "$OUT/selftest_nodes.txt" ]
}

run_selftest_nodes() {
  mkdir -p "$OUT/selftest_status"
  collect_nodes || { echo "  selftest: collection failed/empty"; return 1; }
  order_by_attempts "$OUT/attempts" < "$OUT/selftest_nodes.txt" \
    > "$OUT/selftest_nodes.run"
  while IFS= read -r node; do
    sf=$(node_status_file "$node")
    [ -s "$sf" ] && continue
    defer_for_driver_bench 0
    bump_attempts "$OUT/attempts/$(echo "$node" | tr '/:[] ' '_____').attempts"
    echo "$(date -u +%H:%M:%S)   selftest $node"
    run_bounded 460 "$OUT/selftest_status/last_run.log" \
      python -m pytest "$node" -q
    rc=$?
    if [ $rc -eq 0 ]; then
      { echo "pass"; echo "$node"; } > "$sf"
      continue
    fi
    if [ $rc -eq 124 ]; then
      # Keep the wedge diagnostic (which compile hung) before the next
      # node's run overwrites last_run.log; retry next window.
      cp "$OUT/selftest_status/last_run.log" "$sf.wedge.log" 2>/dev/null
      echo "$(date -u +%H:%M:%S)   selftest $node WEDGED (retry next window)"
      if ! probe "$WANT_BACKEND"; then return 1; fi
      continue
    fi
    # Non-timeout nonzero rc: only pytest rc=1 with a real failure
    # summary is a GENUINE compiled-numerics failure worth banking.
    # rc=5/"no tests ran" means the conftest probe saw a dead backend
    # (fast tunnel death) and rc=2/3/4 are collection/usage/interrupt —
    # all transient harness states, NOT test evidence: re-probe and
    # retry next window.
    if [ $rc -eq 1 ] && grep -qE "^(FAILED|ERROR)|= *[0-9]+ failed" \
         "$OUT/selftest_status/last_run.log"; then
      { echo "fail rc=$rc"; echo "$node";
        tail -40 "$OUT/selftest_status/last_run.log"; } > "$sf"
      echo "$(date -u +%H:%M:%S)   selftest $node FAILED rc=$rc"
    else
      cp "$OUT/selftest_status/last_run.log" "$sf.transient.log" 2>/dev/null
      echo "$(date -u +%H:%M:%S)   selftest $node transient rc=$rc (retry next window)"
      if ! probe "$WANT_BACKEND"; then return 1; fi
    fi
  done < "$OUT/selftest_nodes.run"
  return 0
}

selftest_done() {
  [ -n "${TPU_HARVEST_SKIP_SELFTEST:-}" ] && return 0
  [ -s "$OUT/selftest_nodes.txt" ] || return 1
  while IFS= read -r node; do
    [ -s "$(node_status_file "$node")" ] || return 1
  done < "$OUT/selftest_nodes.txt"
  return 0
}

write_selftest_record() {
  # Emitted even PARTIAL (some nodes still unattempted/wedged): the
  # banked per-node passes are on-chip evidence and must survive the
  # tunnel never reviving — `ok` stays strict (every node passed), and
  # `complete` says whether the whole suite has run. Status files are
  # the single source of truth: line 1 = pass/fail, line 2 = the node
  # id (so this reader never re-derives the shell's filename
  # sanitization).
  [ -s "$OUT/selftest_nodes.txt" ] || return 0
  python - "$OUT" "$WANT_BACKEND" <<'EOF'
import glob, json, os, sys
out, backend = sys.argv[1], sys.argv[2]
n_nodes = sum(1 for l in open(os.path.join(out, "selftest_nodes.txt")) if l.strip())
statuses = []
for path in sorted(glob.glob(os.path.join(out, "selftest_status", "*.status"))):
    with open(path) as f:
        status = f.readline().strip()
        node = f.readline().strip() or os.path.basename(path)
    statuses.append((node, status))
fails = sorted(n for n, s in statuses if not s.startswith("pass"))
n_pass = len(statuses) - len(fails)
complete = len(statuses) == n_nodes
ok = not fails and complete
summary = (f"{n_pass}/{n_nodes} compiled-kernel tests passed on {backend} "
           f"(per-node bounded subprocesses, banked across live windows)")
if not complete:
    summary += (f"; {n_nodes - len(statuses)} not yet run on a live window "
                "(retried per window)")
if fails:
    summary += "; failed: " + ", ".join(fails)
rec = {"metric": "selftest", "backend": backend,
       "selftest": {"ok": ok, "complete": complete, "passed": n_pass,
                    "total": n_nodes, "summary": summary,
                    "nodes": {n: s for n, s in statuses}}}
json.dump(rec, open(os.path.join(out, "results", "selftest.json"), "w"))
EOF
}

finalize() {
  resume_suite
  if python tools/harvest_merge.py "$OUT/results" > "$OUT/merged.json" 2> "$OUT/merge.err" \
     && [ -s "$OUT/merged.json" ] \
     && python -c "import json,sys; json.load(open(sys.argv[1]))" "$OUT/merged.json" 2>/dev/null; then
    python tools/stamp_floors.py "$OUT/merged.json" > "$OUT/stamp.txt" 2>&1
    mkdir -p "$(dirname "$DEST")"
    if cp "$OUT/merged.json" "$DEST"; then
      echo "harvest finalized: $OUT/stamp.txt (banked: $DEST)"
    else
      echo "harvest finalize: COPY TO $DEST FAILED; evidence only in $OUT"
    fi
  else
    # Never clobber previously-banked evidence with a failed merge.
    echo "harvest finalize: merge failed (see $OUT/merge.err); banked artifact untouched"
  fi
}

trap 'resume_suite; rm -f /tmp/tpu_live' EXIT

while true; do
  defer_for_driver_bench
  if ! probe "$WANT_BACKEND"; then
    rm -f /tmp/tpu_live
    echo "$(date -u +%H:%M:%S) tunnel down"
    sleep 90
    continue
  fi
  echo "$(date -u +%H:%M:%S) TUNNEL LIVE — harvesting"
  touch /tmp/tpu_live
  pause_suite
  window_ok=1
  mkdir -p "$OUT/attempts"
  for b in $(printf '%s\n' $BENCH_ORDER | order_by_attempts "$OUT/attempts"); do
    [ -s "$OUT/results/$b.json" ] && continue
    # A driver bench can start mid-window; never time a bench against
    # it (suite already paused by this window — don't manage it).
    defer_for_driver_bench 0
    bump_attempts "$OUT/attempts/$b.attempts"
    bud=$(budget_for "$b")
    echo "$(date -u +%H:%M:%S)   bench $b (budget ${bud}s)"
    : > "$OUT/results/$b.part"
    # In cpu rehearsal the bench child must also be pinned: its own
    # probe could see a live accelerator, tag records backend=tpu, and
    # livelock the accept check below.
    force=""
    [ "$WANT_BACKEND" = cpu ] && force=cpu
    BENCH_HARVEST_CHILD=1 BENCH_FORCE_BACKEND="$force" \
      run_bounded $((bud + 40)) "$OUT/results/$b.err2" \
      python bench.py --bench="$b" --budget="$bud" --no-selftest
    rc=$?
    # bench.py prints the ONE json line on stdout; stdout+stderr are
    # merged in the log, so extract the last line that parses. The
    # wanted backend is passed as argv so shell and Python can never
    # disagree on empty-string semantics.
    python - "$OUT/results/$b.err2" "$OUT/results/$b.part" "$WANT_BACKEND" <<'EOF'
import json, sys
sys.path.insert(0, "tools")
from last_json_line import last_json_line
rec = last_json_line(sys.argv[1])
if rec is not None:
    json.dump(rec, open(sys.argv[2], "w"))
sys.exit(0 if rec is not None
         and rec.get("backend") == sys.argv[3]
         and "error" not in rec else 1)
EOF
    ok=$?
    # Accept on a valid tpu record even if run_bounded hit its
    # deadline: the bench watchdog emits the JSON line before the
    # budget, so rc=124 with a parseable record means "completed,
    # then wedged on exit" — keep the evidence.
    if [ $ok -eq 0 ]; then
      mv "$OUT/results/$b.part" "$OUT/results/$b.json"
      echo "$(date -u +%H:%M:%S)   $b OK"
      continue
    fi
    echo "$(date -u +%H:%M:%S)   $b failed (rc=$rc parse_ok=$ok)"
    rm -f "$OUT/results/$b.part"
    if ! probe "$WANT_BACKEND"; then
      echo "$(date -u +%H:%M:%S) tunnel died mid-window; waiting"
      rm -f /tmp/tpu_live
      window_ok=0
      break
    fi
  done
  if [ $window_ok -eq 1 ] && all_done && ! selftest_done; then
    echo "$(date -u +%H:%M:%S) benches complete — compiled-kernel selftest"
    run_selftest_nodes || window_ok=0
    write_selftest_record
  fi
  if all_done && selftest_done; then
    finalize
    exit 0
  fi
  if [ $window_ok -eq 1 ]; then
    # Benches done but selftest unresolved (or a bench keeps erroring):
    # partial finalize so stamps exist NOW, then keep trying.
    finalize
    sleep 120
  fi
  resume_suite
done
