#!/usr/bin/env python
"""graftlint: the repo's own static analysis suite (ISSUE 14).

Runs the AST passes in ``tensorflow_examples_tpu/analysis/`` over the
package (or any file/dir list) and gates the findings against the
committed suppression baseline::

    python tools/graftlint.py --all tensorflow_examples_tpu/
    python tools/graftlint.py --pass locks tensorflow_examples_tpu/serving/
    python tools/graftlint.py --all --update-baseline tensorflow_examples_tpu/

Exit codes: **0** clean (no findings outside the baseline), **1**
findings, **2** bad arguments/unusable input. The tier-1 test
(``tests/test_lint.py``) runs ``--all`` over the whole package and
pins exit 0, so any new unguarded access, JAX hazard, or schema drift
is a CI failure — not a review comment.

The baseline (default ``tools/graftlint_baseline.json``) maps stable
finding keys to accepted counts; ``--update-baseline`` rewrites it
from the current findings (review the diff — the baseline growing is
a tracked metric: ``tools/bench_gate.py`` WARNs when it does).
Passes: ``locks`` (lock discipline over ``# guard:`` annotations),
``jax`` (traced branching / host syncs / use-after-donate),
``schema`` (SERVING_KEYS vs stampers vs docs, counter catalog).
See docs/static_analysis.md.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tensorflow_examples_tpu import analysis  # noqa: E402
from tensorflow_examples_tpu.analysis import common  # noqa: E402

DEFAULT_BASELINE = os.path.join(REPO, "tools", "graftlint_baseline.json")
DEFAULT_TARGET = os.path.join(REPO, "tensorflow_examples_tpu")


def run(paths, passes, *, repo_root=REPO, baseline_path=None,
        update_baseline=False, out=None) -> int:
    out = out if out is not None else sys.stdout
    for p in paths:
        if not os.path.exists(p):
            print(f"graftlint: no such path: {p}", file=sys.stderr)
            return 2
        # iter_python_files silently drops non-.py files; an
        # explicitly named one must not read as a clean exit 0.
        if os.path.isfile(p) and not p.endswith(".py"):
            print(f"graftlint: not a .py file: {p}", file=sys.stderr)
            return 2
    try:
        baseline = (
            common.Baseline.load(baseline_path)
            if baseline_path else common.Baseline()
        )
    except (ValueError, OSError) as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2
    findings = []
    for name in passes:
        findings.extend(analysis.run_pass(name, paths, repo_root))

    # Scope test for baseline keys: only keys the current invocation
    # could have produced (selected passes over the selected paths).
    # Both --update-baseline and stale-entry detection must honor it —
    # a scoped run can say nothing about the rest of the baseline.
    roots = [common.rel_path(p, repo_root) for p in paths]

    def _in_scope(key: str) -> bool:
        pass_name, _, rest = key.partition(":")
        path = rest.partition(":")[0]
        if pass_name not in passes:
            return False
        return any(
            r == "." or path == r
            or path.startswith(r.rstrip("/") + "/")
            for r in roots
        )

    if update_baseline:
        # main() rejects --no-baseline + --update-baseline before
        # calling run(), so baseline_path is always set here.
        # MERGE, don't rewrite: a targeted `--pass locks path/`
        # baseline update must not silently drop the accepted findings
        # of every other pass and file.
        kept = {
            k: v for k, v in baseline.counts.items() if not _in_scope(k)
        }
        merged = dict(kept)
        merged.update(common.Baseline.from_findings(findings).counts)
        common.Baseline(merged).save(baseline_path)
        print(
            f"graftlint: baseline rewritten with {len(findings)} "
            f"finding(s) ({len(kept)} out-of-scope entr"
            f"{'y' if len(kept) == 1 else 'ies'} preserved) "
            f"-> {baseline_path}",
            file=out,
        )
        return 0
    reported, suppressed, stale = common.apply_baseline(
        findings, baseline
    )
    # An out-of-scope entry is invisible to this run, not stale —
    # reporting it (with "remove it" advice) on a scoped run would
    # walk operators into deleting live suppressions.
    stale = [k for k in stale if _in_scope(k)]
    for f in reported:
        print(f.render(), file=out)
    for key in stale:
        print(
            f"[stale-baseline] {key}: finding occurs fewer times "
            "than the accepted count — remove the entry, or lower "
            "its count to the occurrences that remain",
            file=out,
        )
    print(
        f"graftlint: {len(reported)} finding(s), {len(suppressed)} "
        f"baselined, {len(stale)} stale baseline entr"
        f"{'y' if len(stale) == 1 else 'ies'} "
        f"(passes: {', '.join(passes)}; baseline total "
        f"{baseline.total()})",
        file=out,
    )
    return 1 if reported else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[1],
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files/directories to analyze (default: the package)",
    )
    ap.add_argument(
        "--all", action="store_true",
        help="run every pass (locks, jax, schema)",
    )
    ap.add_argument(
        "--pass", dest="passes", action="append",
        choices=list(analysis.PASSES), metavar="PASS",
        help=f"run one pass (repeatable): {', '.join(analysis.PASSES)}",
    )
    ap.add_argument(
        "--baseline", default=DEFAULT_BASELINE,
        help="suppression baseline JSON (default "
        "tools/graftlint_baseline.json)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline (report everything)",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite --baseline from the current findings and exit 0",
    )
    ap.add_argument(
        "--repo-root", default=REPO,
        help="root for relative paths in findings/contract files "
        "(default: the repo)",
    )
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0
    if args.all and args.passes:
        print(
            "graftlint: --all and --pass are mutually exclusive",
            file=sys.stderr,
        )
        return 2
    passes = list(analysis.PASSES) if args.all or not args.passes \
        else args.passes
    paths = args.paths or [DEFAULT_TARGET]
    baseline_path = None if args.no_baseline else args.baseline
    if args.no_baseline and args.update_baseline:
        print(
            "graftlint: --no-baseline and --update-baseline conflict",
            file=sys.stderr,
        )
        return 2
    return run(
        paths, passes, repo_root=args.repo_root,
        baseline_path=baseline_path,
        update_baseline=args.update_baseline,
    )


if __name__ == "__main__":
    sys.exit(main())
