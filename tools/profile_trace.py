#!/usr/bin/env python
"""Bank ONE real ``jax.profiler`` trace of the GPT-2 bench step.

VERDICT r4 weak #5: every TPU rel_mfu in the floors table is an
ANALYTIC number (XLA cost-model FLOPs / raw-matmul probe) — no
observed device-utilization measurement has ever been banked from a
live window. This tool closes that: it runs the exact gpt2 bench
configuration (batch 8, seq 1024, bf16, flash + fused CE, one-chip
mesh), traces ~10 steps with ``jax.profiler``, converts the xplane
with TensorFlow's profiler plugin (available in-image), and emits:

- ``overview``: the OverviewPage analysis fields (device duty cycle,
  MXU utilization where the backend reports it, step-time breakdown);
- ``op_profile`` / ``framework_op_stats``: JSON tool outputs, op-level
  self-times (top entries only — the full JSONs land next to the
  banked record, not inside it);
- ``step_ms_during_trace``: wall step time measured around the traced
  steps, so the trace can be cross-checked against the bench numbers.

The xplane.pb itself is copied to ``docs/tpu_sweeps/round5_trace/``
when it is small enough to commit (< 16 MB).

Emits ONE JSON line (always-emit watchdog pattern, diag_common);
``complete`` is true only when a tpu-backend trace was collected AND
converted. Run via tools/tpu_harvest.sh's one-shot queue.

Spec: SURVEY.md §5a (profiling hook) — the framework side
(``--profile``) is train/loop.py's jax.profiler integration; this is
the measurement-protocol side.
"""

import glob
import json
import os
import shutil
import sys

# Must be set before ANY google.protobuf import (TF's plugin protos are
# stale vs the image's C++ protobuf): pure-python parsing is slower but
# always compatible.
os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
# NB: PROFILE_DUTY_CYCLE stays unset here — this tool's _convert()
# already runs the (heavy) overview_page conversion on the same
# xplanes for banking; duplicating it inside the in-loop window would
# convert every trace twice.

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from tools.diag_common import (  # noqa: E402
    enable_compile_cache, make_emit, parse_budget, start_watchdog,
)

OUT: dict = {"diag": "profile_trace", "complete": False}
_emit = make_emit(OUT)

TRACE_DIR = "/tmp/tpu_profile_trace"
BANK_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs", "tpu_sweeps", "round5_trace",
)


def _trace_gpt2(steps: int = 10, warmup: int = 5) -> dict:
    """Run the gpt2 bench shape; trace ``steps`` launches.

    Capture is delegated to the trainer's in-loop profiler window
    (``profile_start_step``/``profile_num_steps``/``profile_dir``,
    telemetry/profiling.py) — the same code path ``--profile`` uses in
    production runs — so this tool keeps only the xplane-conversion and
    banking protocol. The warmup steps run before the window opens, so
    jit compilation never pollutes the trace.
    """
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.telemetry import registry as registry_mod
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import gpt2

    tpu = bench.BACKEND == "tpu"
    cfg = gpt2.Gpt2Config(
        global_batch_size=8 if tpu else 1,
        seq_len=1024 if tpu else 128,
        dropout=0.0,
        precision="bf16",
        attention="flash" if tpu else "xla",
        fused_ce=tpu,
        log_every=10**9,
        checkpoint_every=0,
        train_steps=warmup + steps,
        watchdog_secs=0,
        preempt_checkpoint=False,
        telemetry_sinks="",
        telemetry_trace=False,
        profile_start_step=warmup,
        profile_num_steps=steps,
        profile_dir=TRACE_DIR,
        **({} if tpu else dict(num_layers=2, num_heads=2, d_model=64,
                               vocab_size=512)),
    )
    shutil.rmtree(TRACE_DIR, ignore_errors=True)
    trainer = Trainer(gpt2.make_task(cfg), cfg, mesh=bench._chip_mesh())
    it = train_iterator(gpt2.datasets(cfg)[0], cfg.global_batch_size, seed=0)
    trainer.fit(it, num_steps=cfg.train_steps)
    gauges = registry_mod.default_registry().gauge_values()
    traced = int(gauges.get("profile/steps", 0) or 0)
    dt = float(gauges.get("profile/wall_secs", 0.0) or 0.0)
    tokens = cfg.global_batch_size * cfg.seq_len * traced
    out = {
        "batch": cfg.global_batch_size,
        "seq": cfg.seq_len,
        "traced_steps": traced,
        "step_ms_during_trace": (
            round(dt / traced * 1e3, 3) if traced and dt else None
        ),
        "tokens_per_sec_during_trace": round(tokens / dt, 1) if dt else None,
    }
    duty = gauges.get("profile/device_duty_cycle")
    if duty is not None:
        out["device_duty_cycle_inloop"] = round(float(duty), 4)
    return out


def _convert(xplanes: list) -> dict:
    """xplane -> tool outputs via TF's profiler plugin."""
    from tensorflow.python.profiler.internal import (
        _pywrap_profiler_plugin as pp,
    )

    out: dict = {}
    # overview_page is a serialized OverviewPage proto; its analysis
    # message carries the device utilization numbers we're after.
    try:
        from tensorboard_plugin_profile.protobuf import overview_page_pb2

        data, ok = pp.xspace_to_tools_data(list(xplanes), "overview_page", {})
        if ok:
            page = overview_page_pb2.OverviewPage()
            page.ParseFromString(data)
            out["overview"] = {
                f.name: (round(v, 4) if isinstance(v, float) else v)
                for f, v in page.analysis.ListFields()
                if isinstance(v, (int, float, str, bool))
            }
            out["input_analysis"] = {
                f.name: (round(v, 4) if isinstance(v, float) else v)
                for f, v in page.input_analysis.ListFields()
                if isinstance(v, (int, float, str, bool))
            }
    except Exception as e:  # noqa: BLE001 — partial conversion still banks
        out["overview_error"] = f"{type(e).__name__}: {e}"
    for tool, top in (("op_profile", None), ("framework_op_stats", 12)):
        try:
            data, ok = pp.xspace_to_tools_data(list(xplanes), tool, {})
            if not ok:
                out[f"{tool}_error"] = str(data)[:200]
                continue
            s = data.decode() if isinstance(data, bytes) else str(data)
            os.makedirs(BANK_DIR, exist_ok=True)
            with open(os.path.join(BANK_DIR, f"{tool}.json"), "w") as f:
                f.write(s)
            parsed = json.loads(s)
            if tool == "framework_op_stats" and isinstance(parsed, list):
                # gviz table: keep the top rows (rank, op, self-time %).
                table = parsed[0] if parsed else {}
                rows = (table.get("rows") or [])[: top or 12]
                out[tool] = [
                    [c.get("v") for c in r.get("c", [])][:6] for r in rows
                ]
            else:
                out[f"{tool}_banked"] = True
        except Exception as e:  # noqa: BLE001
            out[f"{tool}_error"] = f"{type(e).__name__}: {e}"
    return out


def main() -> int:
    budget = parse_budget(sys.argv[1:], default=420.0)
    watchdog = start_watchdog(budget, _emit)
    try:
        bench.BACKEND = bench._resolve_backend()
        OUT["backend"] = bench.BACKEND
        if bench.BACKEND == "tpu":
            enable_compile_cache()
        OUT["probe_tflops"] = round(bench._probe_quick(), 2)
        OUT["launch_us"] = round(bench._probe_launch_us(), 2)
        OUT.update(_trace_gpt2())
        xplanes = glob.glob(
            os.path.join(TRACE_DIR, "**", "*.xplane.pb"), recursive=True
        )
        OUT["xplane_files"] = [os.path.basename(p) for p in xplanes]
        if xplanes:
            OUT.update(_convert(xplanes))
            total = sum(os.path.getsize(p) for p in xplanes)
            OUT["xplane_bytes"] = total
            if total < 16 * 2**20:
                os.makedirs(BANK_DIR, exist_ok=True)
                for p in xplanes:
                    shutil.copy(p, BANK_DIR)
                OUT["trace_banked_to"] = BANK_DIR
        ok_backend = bench.BACKEND == "tpu" or os.environ.get(
            "PROFILE_ALLOW_CPU"
        )
        OUT["complete"] = bool(
            ok_backend and xplanes and "overview" in OUT
        )
    except Exception as e:  # noqa: BLE001 — partials must still emit
        OUT["error"] = f"{type(e).__name__}: {e}"
    watchdog.cancel()
    _emit()
    return 0


if __name__ == "__main__":
    sys.exit(main())
