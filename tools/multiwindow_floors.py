#!/usr/bin/env python
"""Median-of-separate-windows floor stamps for the repeat benches.

The harvest's rotate_repeats banks one record per LIVE WINDOW for each
bench in REPEAT_BENCHES (live copy in ``results/``, earlier windows in
``results/history/<bench>.w<N>.json``). A single-window floor on a rig
whose dispatch rate drifts 3x between windows is not a trustworthy
regression gate (VERDICT r4 missing #4); this tool turns the
accumulated per-window records into ONE median stamp per metric:

- value: median of the per-window record values (each itself a
  median-of-3 in-window timings);
- fingerprint: the fingerprint of the record that supplied the median
  value (floors are (value, fingerprint) pairs — the pair must come
  from the same measurement);
- rel_mfu: same record's.

Prints ready-to-paste FLOORS / REL_MFU_FLOORS lines plus the
window spread. Feed the printed JSON to apply_floors.py with
``--from-multiwindow`` semantics by writing it to a file and running
``python tools/apply_floors.py <file> --partial``.

Usage: python tools/multiwindow_floors.py /tmp/tpu_harvest/results
"""

import glob
import json
import os
import statistics
import sys


def collect(results_dir: str) -> dict:
    """bench name -> list of records (live + history), window order."""
    out = {}
    hist = os.path.join(results_dir, "history")
    for path in sorted(glob.glob(os.path.join(hist, "*.w*.json"))):
        bench = os.path.basename(path).split(".w")[0]
        with open(path) as f:
            out.setdefault(bench, []).append(json.load(f))
    for bench in list(out):
        live = os.path.join(results_dir, f"{bench}.json")
        if os.path.exists(live):
            with open(live) as f:
                out[bench].append(json.load(f))
    return out


def median_record(recs: list) -> dict:
    """The record supplying the median value (lower-median for even
    counts, so the stamp always corresponds to a real measurement)."""
    vals = sorted(r["value"] for r in recs)
    med = statistics.median_low(vals)
    return next(r for r in recs if r["value"] == med)


def main() -> int:
    if len(sys.argv) != 2:
        print(__doc__)
        return 2
    per_bench = collect(sys.argv[1])
    if not per_bench:
        print("multiwindow_floors: no history records found")
        return 1
    stamp = {"backend": None, "records": []}
    for bench, recs in sorted(per_bench.items()):
        backends = {r.get("backend") for r in recs}
        if len(backends) != 1:
            print(f"{bench}: MIXED backends {backends} — skipping")
            continue
        stamp["backend"] = backends.pop()
        rec = median_record(recs)
        vals = sorted(round(r["value"], 4) for r in recs)
        print(
            f"{bench}: {len(recs)} windows {vals} -> median record "
            f"value={rec['value']} fp={rec.get('fingerprint_tflops_pre')} "
            f"rel_mfu={rec.get('rel_mfu')}"
        )
        print(
            f'  FLOORS:         "{rec["metric"]}": '
            f"({rec['value']}, {rec.get('fingerprint_tflops_pre')}),"
        )
        if "rel_mfu" in rec:
            print(
                f'  REL_MFU_FLOORS: "{rec["metric"]}": {rec["rel_mfu"]},'
            )
        stamp["records"].append(rec)
    out_path = os.path.join(sys.argv[1], "multiwindow_stamp.json")
    if stamp["records"]:
        # apply_floors-compatible shape: head record + extras.
        head, extras = stamp["records"][0], stamp["records"][1:]
        merged = dict(head)
        merged["extras"] = extras
        with open(out_path, "w") as f:
            json.dump(merged, f)
        print(f"stamp record written: {out_path} (apply with "
              "tools/apply_floors.py <path> --partial)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
