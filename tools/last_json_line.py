#!/usr/bin/env python
"""Extract the last parseable JSON line from a log file.

Shared by the TPU watcher scripts (tools/tpu_harvest.sh,
tools/diag_watch.sh): bench/diag children print their record as one
JSON line on stdout, but the watchers capture stdout+stderr merged, so
the record must be fished out of surrounding log noise — and
always-emit children may print a truncated snapshot BEFORE the full
record, so the LAST parseable line is the authoritative one.

Usage: python tools/last_json_line.py LOG OUT [require_key=value ...]
Writes the record to OUT and exits 0 iff one was found and every
``key=value`` requirement matches (string compare); else exits 1.
"""

import json
import sys


def last_json_line(path: str):
    rec = None
    try:
        with open(path, errors="replace") as f:
            for line in f:
                line = line.strip()
                if line.startswith("{"):
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        pass
    except OSError:
        return None
    return rec


def main() -> int:
    if len(sys.argv) < 3:
        print(__doc__)
        return 2
    rec = last_json_line(sys.argv[1])
    if rec is None:
        return 1
    for req in sys.argv[3:]:
        k, _, v = req.partition("=")
        if str(rec.get(k)) != v:
            return 1
    json.dump(rec, open(sys.argv[2], "w"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
