# Shared wedge-tolerant process discipline for the TPU watcher scripts
# (tools/tpu_harvest.sh, tools/diag_watch.sh). Source, don't execute.
#
# The axon tunnel's failure mode is a wedge that survives SIGKILL (the
# child sticks in D state inside the driver), so nothing here ever
# `wait`s unconditionally on a child, and the shared 1-core host means
# any `pytest tests/` must be SIGSTOPped while device timing runs.

# run_bounded SECS LOGFILE CMD... — run CMD with stdout+stderr to
# LOGFILE, hard deadline SECS. Returns CMD's rc, or 124 on deadline.
run_bounded() {
  local secs=$1 log=$2; shift 2
  "$@" > "$log" 2>&1 &
  local pid=$! waited=0
  while kill -0 "$pid" 2>/dev/null && [ "$waited" -lt "$secs" ]; do
    sleep 5; waited=$((waited + 5))
  done
  if kill -0 "$pid" 2>/dev/null; then
    kill -9 "$pid" 2>/dev/null
    sleep 2
    if kill -0 "$pid" 2>/dev/null; then
      echo "run_bounded: pid $pid unkillable (driver wedge); abandoning" >> "$log"
    fi
    return 124
  fi
  wait "$pid" 2>/dev/null
}

# probe [want_backend] — 0 if `jax.default_backend()` answers with the
# wanted backend (default tpu) inside 90 s. want=cpu pins the platform
# in-process (a raw default_backend() would hang on a wedged axon
# plugin — same trap tests/conftest.py avoids).
probe() {
  local want=${1:-tpu} f code
  rm -f /tmp/bench_backend_probe.json
  f=$(mktemp /tmp/probe_out.XXXXXX)
  if [ "$want" = cpu ]; then
    code='import jax; jax.config.update("jax_platforms", "cpu"); print("LIVE", jax.default_backend())'
  else
    code='import jax; print("LIVE", jax.default_backend())'
  fi
  run_bounded 90 "$f" python -c "$code"
  if grep -q "LIVE $want" "$f" 2>/dev/null; then rm -f "$f"; return 0; fi
  rm -f "$f"; return 1
}

# ANCHORED pattern: an unanchored "pytest tests/" would also match the
# session driver process (its prompt text contains that substring) —
# SIGSTOPping that would freeze the whole build session.
pause_suite() { pkill -STOP -f "^[^ ]*python -m pytest tests/" 2>/dev/null && echo "  (paused CPU suite)"; true; }
resume_suite() { pkill -CONT -f "^[^ ]*python -m pytest tests/" 2>/dev/null && echo "  (resumed CPU suite)"; true; }

# driver_bench_running — 0 if the session driver's round-end
# `python bench.py` is live. The watchers defer their window work while
# it runs: two processes timing against one chip (or one host core)
# contaminate both records — and the driver's artifact is the official
# one. End-anchored so the harvest's own per-bench children
# (`python bench.py --bench=<name>`) never match: a wedged child
# abandoned in D state would otherwise trip this forever and deadlock
# the very watcher that abandoned it.
driver_bench_running() {
  pgrep -f "^[^ ]*python bench[.]py$" > /dev/null 2>&1
}

# defer_for_driver_bench [manage_suite=1] — wait while the driver's
# bench runs, so watcher work never times against it. Pauses the CPU
# suite meanwhile (the official record must not be contended on the
# 1-core host) unless manage_suite=0 — callers already inside a live
# window paused the suite themselves, and resuming it for them here
# would undo that. Capped at 900 s: the driver bounds its run with
# `timeout 600`, so a match persisting past the cap is a
# SIGKILL-surviving driver wedge (D state) that will never exit —
# waiting longer would livelock the watcher on exactly the failure
# mode this library exists to survive.
defer_for_driver_bench() {
  local manage=${1:-1} waited=0
  while driver_bench_running && [ "$waited" -lt 900 ]; do
    if [ "$waited" -eq 0 ]; then
      echo "$(date -u +%H:%M:%S) driver bench.py live; deferring (cap 900s)"
      [ "$manage" = 1 ] && pause_suite
    fi
    sleep 30; waited=$((waited + 30))
  done
  if [ "$waited" -ge 900 ]; then
    echo "$(date -u +%H:%M:%S) driver bench still matching after 900s wedged; proceeding"
  fi
  # A harvest window may have gone live DURING the wait; its own
  # pause_suite already ran at window start, and resuming here would
  # undo it mid-window (pause/resume is not refcounted).
  [ "$waited" -gt 0 ] && [ "$manage" = 1 ] && [ ! -f /tmp/tpu_live ] && resume_suite
  true
}
