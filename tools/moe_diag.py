#!/usr/bin/env python
"""MoE grouped-path on-chip component diagnosis.

The round-5 first live window measured the rewritten sort-based
grouped MoE bench at 20.7k tok/s (rel_mfu 0.00026) — 3x SLOWER than
the round-4 scatter formulation it replaced (62.6k, rel_mfu 0.00154)
and ~170x below dense GPT-2, even though at the bench shape
([16384, 768] x [8, 768, 3072], every dim %128 == 0) the megablox gmm
Pallas kernel should engage. Window values were stable (±0.3%), so the
compiled program itself is slow, not dispatch.

This tool times each component of the grouped path in isolation on the
chip so the regression can be attributed to ONE of: the gmm kernel
forward, its custom-vjp backward (tgmm), the argsort-based slotting,
the permutation gathers, or the surrounding step. For each it also
times the obvious alternative (ragged_dot, scatter impl) at the same
shape.

Usage: python tools/moe_diag.py [--budget=SECS]
Emits ONE JSON line (always, partial on budget/deadline like bench.py).
"""

import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from tools.diag_common import (  # noqa: E402
    enable_compile_cache, make_emit, parse_budget, start_watchdog,
)

OUT: dict = {"diag": "moe_components"}
_emit = make_emit(OUT)

# The TPU bench shape (bench.bench_moe): GPT-2 124M, batch 8, seq 1024,
# E=8 top-2 -> n·k = 16384 rows through d=768 / ff=3072 experts.
N_TOK, TOP_K, E, D, FF = 8192, 2, 8, 768, 3072
ROWS = N_TOK * TOP_K


def _timeit(fn, *args, iters: int = 10, warmup: int = 3) -> float:
    """Median wall ms per call of jitted fn (block_until_ready)."""
    import jax

    jfn = jax.jit(fn)
    out = jfn(*args)
    for _ in range(warmup - 1):
        out = jfn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(iters):
        t0 = time.monotonic()
        jax.block_until_ready(jfn(*args))
        ts.append((time.monotonic() - t0) * 1e3)
    return round(statistics.median(ts), 4)


def _component_benches(deadline: float) -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax, random

    from tensorflow_examples_tpu.parallel import moe

    # CPU rehearsal uses tiny shapes (the TPU ones would take minutes
    # per ragged_dot on this 1-core host); the on-chip run uses the
    # exact bench shape.
    tpu = bench.BACKEND == "tpu"
    rows, d, ff = (ROWS, D, FF) if tpu else (256, 64, 128)
    n_tok, bsz, seq = (N_TOK, 8, 1024) if tpu else (rows // TOP_K, 2, 64)

    k0 = random.PRNGKey(0)
    lhs = random.normal(k0, (rows, d), jnp.bfloat16)
    rhs_in = random.normal(k0, (E, d, ff), jnp.bfloat16)
    rhs_out = random.normal(k0, (E, ff, d), jnp.bfloat16)
    h = random.normal(k0, (rows, ff), jnp.bfloat16)
    sizes_even = jnp.full((E,), rows // E, jnp.int32)
    expert_ids = random.randint(k0, (rows,), 0, E, jnp.int32)

    def gmm_like(lo, hi):  # pin backend decision out of the way
        from jax.experimental.pallas.ops.tpu.megablox import ops as mb
        return mb.gmm(lo, hi, sizes_even, lo.dtype)

    def gmm_tiling_sweep():
        # The kernel's default (128,128,128) was never swept on v5e;
        # if gmm_fwd_in reads slow, this says whether tiling is why.
        from jax.experimental.pallas.ops.tpu.megablox import ops as mb
        res = {}
        for t in ((128, 128, 128), (256, 256, 256), (512, 256, 256),
                  (512, 512, 512), (1024, 768, 512)):
            try:
                # positional like parallel/moe.py: gmm is a custom_vjp
                # with nondiff_argnums — tiling= by keyword happens to
                # work today but is not contract across jax bumps.
                res["x".join(map(str, t))] = _timeit(
                    lambda lo, hi, _t=t: mb.gmm(
                        lo, hi, sizes_even, lo.dtype, _t),
                    lhs, rhs_in)
            except Exception as e:  # noqa: BLE001 — a tiling may be
                res["x".join(map(str, t))] = f"error: {type(e).__name__}: {e}"
        return res

    comp: dict = {}
    steps = ([
        ("gmm_fwd_in", lambda: _timeit(gmm_like, lhs, rhs_in)),
        ("gmm_fwd_out", lambda: _timeit(gmm_like, h, rhs_out)),
        ("gmm_fwdbwd_in", lambda: _timeit(
            jax.grad(lambda lo, hi: gmm_like(lo, hi).astype(
                jnp.float32).sum(), argnums=(0, 1)), lhs, rhs_in)),
        ("gmm_tiling_sweep", gmm_tiling_sweep),
    ] if tpu else []) + [
        ("ragged_fwd_in", lambda: _timeit(
            lambda lo, hi: lax.ragged_dot(lo, hi, sizes_even), lhs, rhs_in)),
        ("argsort_rows", lambda: _timeit(
            lambda ids: jnp.argsort(jnp.argsort(ids)), expert_ids)),
        ("pair_sort", lambda: _timeit(
            lambda ids: moe._pair_sort(
                [ids[:n_tok], ids[n_tok:]], E), expert_ids)),
        ("ragged_fwdbwd_in", lambda: _timeit(
            jax.grad(lambda lo, hi: lax.ragged_dot(
                lo, hi, sizes_even).astype(jnp.float32).sum(),
                argnums=(0, 1)), lhs, rhs_in)),
        ("dense_ffn_ref", lambda: _timeit(
            lambda t, a, b: (t @ a) @ b, lhs[:n_tok],
            rhs_in[0], rhs_out[0])),
    ]
    for name, run in steps:
        if time.monotonic() > deadline:
            OUT["truncated"] = True
            return
        try:
            comp[name] = run()
        except Exception as e:  # noqa: BLE001 — name the failing piece
            comp[name] = f"error: {type(e).__name__}: {e}"
        OUT["components_ms"] = comp
        _emit()

    # The full MoE block fwd and fwd+bwd, both impls, outside any
    # Trainer machinery: isolates the layer from the train step.
    k1, k2 = random.split(k0)
    gate_w = random.normal(k1, (d, E), jnp.float32)
    b_in = jnp.zeros((E, ff), jnp.bfloat16)
    b_out = jnp.zeros((E, d), jnp.bfloat16)
    x = random.normal(k2, (bsz, seq, d), jnp.bfloat16)

    for impl in ("grouped", "scatter"):
        if time.monotonic() > deadline:
            OUT["truncated"] = True
            return

        def blk(xx, gw, wi, wo):
            out, aux, _ = moe.moe_ffn(
                gw, wi, b_in, wo, b_out, xx, top_k=TOP_K, impl=impl)
            return out.astype(jnp.float32).sum() + aux

        try:
            comp[f"block_fwd_{impl}"] = _timeit(
                blk, x, gate_w, rhs_in, rhs_out)
            comp[f"block_fwdbwd_{impl}"] = _timeit(
                jax.grad(blk, argnums=(0, 1, 2, 3)),
                x, gate_w, rhs_in, rhs_out)
        except Exception as e:  # noqa: BLE001
            comp[f"block_{impl}"] = f"error: {type(e).__name__}: {e}"
        OUT["components_ms"] = comp
        _emit()


def _full_step(impl: str, steps: int = 10) -> dict:
    """The bench_moe train step with the impl pinned — the config is
    bench.moe_bench_config, NOT a copy, so the timing here explains
    the exact moe_top2_tokens_per_sec program."""
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import gpt2

    cfg = bench.moe_bench_config(moe_impl=impl)
    batch, seq = cfg.global_batch_size, cfg.seq_len
    trainer = Trainer(gpt2.make_task(cfg), cfg, mesh=bench._chip_mesh())
    ds, _ = gpt2.datasets(cfg)
    it = train_iterator(ds, batch, seed=0)
    batches = [trainer._put_batch(next(it)) for _ in range(4)]
    dts = bench._time_steps(trainer, batches, steps, warmup=3)
    med = statistics.median(dts)
    return {
        "impl": impl,
        "ms_per_step": round(med / steps * 1e3, 3),
        "tokens_per_sec": round(batch * seq * steps / med, 1),
    }


def main() -> int:
    budget = parse_budget(sys.argv[1:], default=600)
    deadline = time.monotonic() + budget - 30
    watchdog = start_watchdog(budget, _emit)
    try:
        bench.BACKEND = bench._resolve_backend()
        OUT["backend"] = bench.BACKEND
        if bench.BACKEND == "tpu":
            enable_compile_cache()
        OUT["probe_tflops"] = round(bench._probe_quick(), 2)
        OUT["launch_us"] = round(bench._probe_launch_us(), 2)
        _component_benches(deadline)
        OUT["full_step"] = []
        for impl in ("grouped", "scatter"):
            if time.monotonic() > deadline:
                OUT["truncated"] = True
                break
            OUT["full_step"].append(_full_step(impl))
            _emit()
        OUT["complete"] = not OUT.get("truncated", False)
    except Exception as e:  # noqa: BLE001 — partials must still emit
        OUT["error"] = f"{type(e).__name__}: {e}"
    watchdog.cancel()
    _emit()
    return 0


if __name__ == "__main__":
    sys.exit(main())
