#!/usr/bin/env python
"""Turn a banked flash_tune sweep into the committed block table.

Usage:
  python tools/flash_table_from_sweep.py docs/tpu_sweeps/round5_flash_tune.json

Writes docs/tpu_sweeps/flash_block_table.json:
  {"source": <sweep file>, "by_seq": {"1024": {"block_q": B, "block_kv": B},
   ...}}
using each shape's ``best_fwdbwd`` cell (training is the default
consumer; the fwd-only optimum is recorded alongside for reference).
ops/attention.py loads the table at kernel-build time. The kernel
source hash (tools/kernel_source_hash.py) covers the table file, so
swapping it automatically stales banked selftest evidence and the
harvest re-proves compiled parity on the next live window (the sweep
itself also ran every cell compiled on-chip).
"""

import json
import os
import sys


def main() -> int:
    if len(sys.argv) != 2:
        print(__doc__)
        return 2
    with open(sys.argv[1]) as f:
        sweep = json.load(f)
    if not sweep.get("complete"):
        print("flash_table_from_sweep: sweep record is not complete — "
              "refusing to freeze a partial table")
        return 1
    by_seq = {}
    for shape in sweep.get("shapes", []):
        best = shape.get("best_fwdbwd")
        if not best:
            continue
        by_seq[str(shape["seq"])] = {
            "block_q": best["block_q"],
            "block_kv": best["block_kv"],
            "fwdbwd_ms": best.get("fwdbwd_ms"),
            "fwd_best": shape.get("best_fwd"),
            "shape": {k: shape[k] for k in ("batch", "heads", "head_dim")},
        }
    if not by_seq:
        print("flash_table_from_sweep: no best cells in sweep")
        return 1
    out_path = os.path.join(
        os.path.dirname(os.path.abspath(sys.argv[1])),
        "flash_block_table.json",
    )
    with open(out_path, "w") as f:
        json.dump(
            {"source": os.path.basename(sys.argv[1]), "by_seq": by_seq},
            f, indent=1,
        )
    print(f"wrote {out_path}: {json.dumps(by_seq)[:300]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
