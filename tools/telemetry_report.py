#!/usr/bin/env python
"""Turn a run dir's telemetry into a human summary + machine JSON.

    python tools/telemetry_report.py /path/to/workdir
    python tools/telemetry_report.py /path/to/workdir --json report.json

Reads ``<workdir>/telemetry/metrics.jsonl`` (the schema-versioned JSONL
the trainer's Telemetry writes every log window — see
docs/observability.md) and, when present, ``trace.json`` (the Chrome
span timeline), and prints:

* run shape: steps covered, windows, wall span, how the run ended
  (the ``kind="final"`` line's exit_reason — "complete" vs. "preempt"
  vs. "error:...")
* throughput: examples/sec (mean of windows + last window), tokens/sec
  for token workloads
* step time: p50 / p95 (+ mean) from the step_time histogram
* MFU estimate: 6ND model FLOPs over the device peak (flagged when the
  peak was a fallback guess, e.g. CPU smoke runs)
* goodput + the resilience/IO counters behind it (bad steps, rollbacks,
  steps lost, preemptions, batch skips, IO retries)
* per-phase host time from the trace (where the loop's wall time went)
* device-side facts when the run recorded them (schema v2, ISSUE 3):
  peak live-memory watermark + the params/opt/other init breakdown,
  compile count + post-warmup recompile warnings, the in-loop profiler
  window cross-link, and the observed device duty cycle next to the
  analytic MFU. v1 runs simply omit these lines — absent fields degrade
  gracefully.
* fleet facts (schema v3, ISSUE 4): when the run dir holds per-host
  telemetry shards (``telemetry.host{k}.jsonl``), they are merged into
  a per-host table and the slowest host is flagged; the last
  ``kind="fleet"`` line's skew/straggler verdict is rendered either
  way. Single-shard dirs report exactly as before.
* SLO alert facts (schema v14, ISSUE 19): when the run dir holds an
  ``alerts.jsonl`` sink (serve_fleet ``--alerts-out``), the firing /
  resolved episode count, per-episode durations, the worst remaining
  error budget, and the exemplar trace ids (ready for ``trace_report
  --trace-id``) are summarized. Dirs without a sink omit the section.

``--json`` additionally writes one machine-readable record with the
same numbers — shaped for dropping into future BENCH_*.json entries.

Lines that fail schema validation are skipped LOUDLY (counted +
reported): a half-written crash tail must not silently skew the
aggregates. Exit code 1 if no valid telemetry is found.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tensorflow_examples_tpu.telemetry import accounting, schema  # noqa: E402


def load_lines(path: str) -> tuple[list[dict], int]:
    """(valid schema lines, invalid-line count) from a metrics JSONL."""
    valid, bad = [], 0
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                obj = json.loads(raw)
            except json.JSONDecodeError:
                bad += 1
                continue
            if schema.validate_line(obj):
                bad += 1
                continue
            valid.append(obj)
    return valid, bad


def _mean(vals: list[float]) -> float | None:
    vals = [v for v in vals if v is not None]
    return sum(vals) / len(vals) if vals else None


def _is_session_boundary(prev: dict, line: dict) -> bool:
    """Did a new fit-session start between these adjacent lines?

    Primary signal: ``session_start_unix`` changing — every line carries
    its session's id, exact even across SIGKILLs. Fallbacks for lines
    predating the field: a ``kind="final"`` line ends its session, and
    any per-key counter decrease means a fresh process restarted at 0.
    """
    a = prev.get("session_start_unix")
    b = line.get("session_start_unix")
    if a is not None and b is not None:
        return a != b
    if prev["kind"] == "final":
        return True
    return any(
        line["counters"].get(k, 0) < v for k, v in prev["counters"].items()
    )


def _split_sessions(lines: list[dict]) -> list[list[dict]]:
    """Split the JSONL into fit sessions (counters restart per session;
    a preempted-then-resumed run appends several to one file)."""
    sessions: list[list[dict]] = []
    cur: list[dict] = []
    for line in lines:
        if cur and _is_session_boundary(cur[-1], line):
            sessions.append(cur)
            cur = []
        cur.append(line)
    if cur:
        sessions.append(cur)
    return sessions


def _aggregate_counters(sessions: list[list[dict]]) -> dict[str, int]:
    """Whole-run counters: sum each session's last (= highest) values —
    the per-session counters are cumulative, so the last line carries
    the session total. Fleet lines are skipped: they ride immediately
    after every reduced window carrying HOST-LOCAL counters (their
    per-host evidence lives in the fleet object), so a torn tail ending
    on one would silently swap the fleet-reduced totals for one host's."""
    totals: dict[str, int] = {}
    for sess in sessions:
        last = next(
            (l for l in reversed(sess) if l["kind"] != "fleet"), sess[-1]
        )
        for k, v in last["counters"].items():
            totals[k] = totals.get(k, 0) + v
    return totals


def summarize(lines: list[dict], trace: dict | None) -> dict:
    """Aggregate validated lines (+ optional trace) into one record."""
    windows = [l for l in lines if l["kind"] == "window"]
    evals = [l for l in lines if l["kind"] == "eval"]
    finals = [l for l in lines if l["kind"] == "final"]
    memories = [l for l in lines if l["kind"] == "memory"]
    compile_warnings = [l for l in lines if l["kind"] == "compile_warning"]
    last = lines[-1]
    sessions = _split_sessions(lines)
    counters = _aggregate_counters(sessions)
    gauges = last["gauges"]
    # The freshest derived block that actually has throughput: final
    # lines often carry an empty partial window (derived nulls).
    derived = {}
    for l in reversed(lines):
        if l["derived"].get("examples_per_sec") is not None:
            derived = l["derived"]
            break
    else:
        derived = last["derived"]

    record = {
        "schema_version": schema.SCHEMA_VERSION,
        "windows": len(windows),
        "eval_windows": len(evals),
        "sessions": len(sessions),
        "first_step": lines[0]["step"],
        "last_step": last["step"],
        "wall_span_secs": last["time_unix"] - lines[0]["time_unix"],
        "exit_reason": finals[-1]["exit_reason"] if finals else None,
        "examples_per_sec_mean": _mean(
            [w["derived"].get("examples_per_sec") for w in windows]
        ),
        "examples_per_sec_last": derived.get("examples_per_sec"),
        "tokens_per_sec_last": derived.get("tokens_per_sec"),
        "step_time_p50": last["derived"].get("step_time_p50"),
        "step_time_p95": last["derived"].get("step_time_p95"),
        "mfu": derived.get("mfu"),
        "mfu_peak_is_estimate": bool(
            gauges.get("telemetry/peak_is_estimate", 1.0)
        ),
        # Whole-run goodput from the cross-session counter totals (a
        # single line's goodput only covers its own process session).
        "goodput": accounting.goodput(counters),
        "counters": counters,
        "flops_per_step": gauges.get("telemetry/flops_per_step"),
        "peak_flops_total": gauges.get("telemetry/peak_flops_total"),
    }
    # ----- schema-v2 device-side fields (None/absent on v1 runs) -----
    last_memory = next(
        (l["memory"] for l in reversed(lines)
         if isinstance(l.get("memory"), dict)),
        None,
    )
    record["memory"] = last_memory
    record["peak_live_bytes"] = (last_memory or {}).get("peak_live_bytes")
    record["memory_breakdown"] = (
        memories[-1]["memory"] if memories else None
    )
    record["compiles"] = counters.get("compile/count")
    record["recompiles"] = counters.get("compile/recompiles")
    record["compile_warnings"] = [
        {"step": l["step"], **l.get("compile", {})}
        for l in compile_warnings
    ]
    record["profile"] = next(
        (l["profile"] for l in reversed(finals) if "profile" in l), None
    )
    # ----- schema-v5 sharding provenance (None/absent on older runs) --
    sharding = next(
        (l["sharding"] for l in reversed(finals) if "sharding" in l), None
    )
    record["sharding"] = sharding
    record["mesh_shape"] = (sharding or {}).get("mesh_shape")
    record["param_sharding_digest"] = (sharding or {}).get(
        "param_sharding_digest"
    )
    # A model-parallel run's step time under its own gate key: the
    # bench_gate `sharded_step_time` record kind (a sharded layout's
    # step time is not comparable to the 1-device floor, so it gets its
    # own stamped bound).
    mesh_shape = record["mesh_shape"] or {}
    nontrivial = any(
        a != "data" and int(s) > 1 for a, s in mesh_shape.items()
    )
    record["sharded_step_time"] = (
        record["step_time_p50"] if nontrivial else None
    )
    # ----- schema-v3 fleet fields (None/absent on v1/v2 runs) -----
    fleet_lines = [l for l in lines if l["kind"] == "fleet"]
    record["fleet"] = fleet_lines[-1]["fleet"] if fleet_lines else None
    record["fleet_straggler_windows"] = sum(
        1 for l in fleet_lines if l["fleet"].get("straggler")
    )
    # From derived ONLY: the hub publishes it per fit, while the gauge
    # is process-global and would attribute an earlier fit's
    # measurement to this record.
    record["device_duty_cycle"] = derived.get("device_duty_cycle")
    if trace is not None:
        phases: dict[str, dict] = {}
        for ev in trace.get("traceEvents", []):
            p = phases.setdefault(ev["name"], {"count": 0, "total_ms": 0.0})
            p["count"] += 1
            p["total_ms"] += ev.get("dur", 0.0) / 1e3
        record["trace_phases"] = {
            name: {"count": p["count"], "total_ms": round(p["total_ms"], 3)}
            for name, p in sorted(
                phases.items(), key=lambda kv: -kv[1]["total_ms"]
            )
        }
        if trace.get("droppedEventCount"):
            record["trace_dropped_events"] = trace["droppedEventCount"]
    return record


def resolve_metrics_path(arg: str) -> str | None:
    """A run dir / telemetry dir / metrics.jsonl argument -> the primary
    metrics file (host 0's run record), or — when only host shards
    exist — the lowest-indexed shard."""
    cand = [
        arg,
        os.path.join(arg, "metrics.jsonl"),
        os.path.join(arg, "telemetry", "metrics.jsonl"),
    ]
    path = next((p for p in cand if os.path.isfile(p)), None)
    if path is not None:
        return path
    shards = _shard_paths(arg) or _shard_paths(os.path.join(arg, "telemetry"))
    return shards[0][1] if shards else None


def _shard_paths(d: str) -> list[tuple[int, str]]:
    """``(host, path)`` for each telemetry.host{k}.jsonl under ``d``,
    ordered by host index."""
    if not os.path.isdir(d):
        return []
    hits = []
    for name in os.listdir(d):
        m = re.fullmatch(r"telemetry\.host(\d+)\.jsonl", name)
        if m:
            hits.append((int(m.group(1)), os.path.join(d, name)))
    return sorted(hits)


def host_shard_records(telemetry_dir: str) -> list[dict]:
    """Per-host mini-records from the dir's host shards (ISSUE 4
    satellite): one summary row per ``telemetry.host{k}.jsonl``. Empty
    for single-shard (single-host) run dirs — their report is exactly
    the pre-fleet one.

    Process 0 writes no shard (metrics.jsonl IS its stream — see
    sinks.host_metrics_path), so when shards exist without a host-0
    one, the main record file is merged in as host 0."""
    shards = _shard_paths(telemetry_dir)
    main = os.path.join(telemetry_dir, "metrics.jsonl")
    if shards and not any(h == 0 for h, _ in shards) and os.path.isfile(main):
        shards.insert(0, (0, main))
    out = []
    for host, path in shards:
        lines, bad = load_lines(path)
        if not lines:
            continue
        rec = summarize(lines, None)
        out.append(
            {
                "host": host,
                "windows": rec["windows"],
                "last_step": rec["last_step"],
                "exit_reason": rec["exit_reason"],
                "step_time_p50": rec["step_time_p50"],
                "step_time_p95": rec["step_time_p95"],
                "examples_per_sec_last": rec["examples_per_sec_last"],
                "steps_lost": rec["counters"].get(
                    "resilience/steps_lost", 0
                ),
                "peak_live_bytes": rec["peak_live_bytes"],
                "invalid_lines": bad,
            }
        )
    return out


def alert_summary(run_dir: str) -> dict | None:
    """ISSUE 19 satellite: summarize the run dir's schema-v14
    ``kind="alert"`` firing/resolve JSONL (``alerts.jsonl``, the
    AlertEngine sink serve_fleet's ``--alerts-out`` lands) — how many
    alerts fired, how long each episode lasted (firing -> resolved,
    paired by alert name), how much error budget the worst rule had
    left, and the exemplar trace ids a responder would feed to
    ``trace_report --trace-id``. None when the run has no alert sink."""
    cand = [
        os.path.join(run_dir, "alerts.jsonl"),
        os.path.join(run_dir, "telemetry", "alerts.jsonl"),
    ]
    path = next((p for p in cand if os.path.isfile(p)), None)
    if path is None:
        return None
    from tensorflow_examples_tpu.telemetry import slo

    alerts = slo.read_alerts(path)
    if not alerts:
        return None
    firings = [a for a in alerts if a.get("state") == "firing"]
    open_since: dict[str, float] = {}
    episodes = []
    for a in alerts:
        name = a.get("name")
        t = a.get("_time_unix")
        if a.get("state") == "firing":
            if name not in open_since and t is not None:
                open_since[name] = t
        elif a.get("state") == "resolved" and name in open_since:
            start = open_since.pop(name)
            episodes.append(
                {
                    "name": name,
                    "slo": a.get("slo"),
                    "duration_s": (
                        round(t - start, 3) if t is not None else None
                    ),
                }
            )
    budgets = [
        a["budget_remaining"]
        for a in alerts
        if isinstance(a.get("budget_remaining"), (int, float))
        and not isinstance(a.get("budget_remaining"), bool)
    ]
    return {
        "path": path,
        "firings": len(firings),
        "resolved": sum(1 for a in alerts if a.get("state") == "resolved"),
        "still_firing": sorted(open_since),
        "episodes": episodes,
        "min_budget_remaining": min(budgets) if budgets else None,
        "exemplar_trace_ids": [
            a["trace_id"]
            for a in firings
            if isinstance(a.get("trace_id"), str)
        ][:5],
    }


def build_record(arg: str) -> tuple[dict | None, int, str]:
    """(record, skipped-line count, error) for a run-dir argument — the
    shared entry point for main() and tools/run_diff.py. ``record`` is
    None exactly when ``error`` is non-empty."""
    path = resolve_metrics_path(arg)
    if path is None:
        return None, 0, (
            f"no telemetry found under {arg!r} (looked for "
            "telemetry/metrics.jsonl and telemetry.host*.jsonl — was the "
            "run started with --workdir and the jsonl sink enabled?)"
        )
    lines, skipped = load_lines(path)
    if not lines:
        return None, skipped, (
            f"{path}: no valid schema-v{schema.SCHEMA_VERSION} lines "
            f"({skipped} invalid)"
        )
    trace_file = os.path.join(os.path.dirname(path), "trace.json")
    trace = None
    if os.path.isfile(trace_file):
        try:
            with open(trace_file) as f:
                trace = json.load(f)
        except json.JSONDecodeError:
            print(f"WARNING: unreadable trace {trace_file}", file=sys.stderr)
    record = summarize(lines, trace)
    # ISSUE 19: a run dir that landed an alert sink gets the SLO
    # section; dirs without one simply omit it.
    record["alerts"] = (
        alert_summary(arg if os.path.isdir(arg) else os.path.dirname(arg))
        or alert_summary(os.path.dirname(path))
    )
    hosts = host_shard_records(os.path.dirname(path))
    record["hosts"] = hosts or None
    p95s = [
        (h["step_time_p95"], h["host"])
        for h in hosts
        if h["step_time_p95"] is not None
    ]
    record["slowest_host"] = max(p95s)[1] if p95s else None
    return record, skipped, ""


def _fmt(v, unit="", nd=2) -> str:
    if v is None:
        return "n/a"
    if isinstance(v, float):
        return f"{v:,.{nd}f}{unit}"
    return f"{v}{unit}"


def render(record: dict, skipped: int) -> str:
    out = []
    out.append("== telemetry report ==")
    out.append(
        f"run: steps {record['first_step']}..{record['last_step']} over "
        f"{record['windows']} window(s) + {record['eval_windows']} eval "
        f"in {record['sessions']} session(s), "
        f"{_fmt(record['wall_span_secs'], 's')} wall; "
        f"ended: {record['exit_reason'] or 'UNKNOWN (no final line)'}"
    )
    out.append(
        f"throughput: {_fmt(record['examples_per_sec_mean'])} examples/sec "
        f"mean ({_fmt(record['examples_per_sec_last'])} last window)"
        + (
            f", {_fmt(record['tokens_per_sec_last'])} tokens/sec"
            if record["tokens_per_sec_last"] is not None
            else ""
        )
    )
    p50, p95 = record["step_time_p50"], record["step_time_p95"]
    out.append(
        "step time: p50 "
        + _fmt(p50 * 1e3 if p50 is not None else None, "ms")
        + " / p95 "
        + _fmt(p95 * 1e3 if p95 is not None else None, "ms")
    )
    mfu = record["mfu"]
    duty = record.get("device_duty_cycle")
    out.append(
        "mfu estimate: "
        + (_fmt(mfu * 100, "%", nd=4) if mfu is not None else "n/a")
        + " (6ND analytic"
        + (
            f"; observed device duty cycle {_fmt(duty * 100, '%', nd=1)} "
            "from the profiler window"
            if duty is not None
            else ""
        )
        + ")"
        + (
            " (peak FLOPs GUESSED — unknown device kind; set "
            "--telemetry_peak_tflops for a real estimate)"
            if record["mfu_peak_is_estimate"]
            else ""
        )
    )
    gp = record["goodput"]
    c = record["counters"]
    out.append(
        "goodput: "
        + (_fmt(gp * 100, "%", nd=2) if gp is not None else "n/a")
        + f" of {c.get('train/steps_total', 0)} stepped "
        + f"(bad={c.get('resilience/bad_steps', 0)} "
        + f"lost={c.get('resilience/steps_lost', 0)} "
        + f"rollbacks={c.get('resilience/rollbacks', 0)} "
        + f"preemptions={c.get('resilience/preemptions', 0)})"
    )
    out.append(
        f"input: {c.get('data/batches_fetched', 0)} batches fetched, "
        f"{c.get('data/batches_skipped', 0)} skipped poisoned, "
        f"{c.get('io/retries', 0)} io retries; checkpoints: "
        f"{c.get('checkpoint/saves', 0)} saved / "
        f"{c.get('checkpoint/restores', 0)} restored"
    )
    # ----- schema-v2 device-side sections (omitted for v1 runs) -----
    mem = record.get("memory")
    if mem and mem.get("peak_live_bytes") is not None:
        line = f"memory: peak live {mem['peak_live_bytes'] / 2**20:,.1f}MiB"
        bd = record.get("memory_breakdown")
        if bd:
            line += (
                f" (at init: params {bd.get('params_bytes', 0) / 2**20:,.1f}"
                f" / opt {bd.get('opt_bytes', 0) / 2**20:,.1f}"
                f" / other {bd.get('other_bytes', 0) / 2**20:,.1f} MiB)"
            )
        if mem.get("device_peak_bytes_in_use") is not None:
            line += (
                f"; device allocator peak "
                f"{mem['device_peak_bytes_in_use'] / 2**20:,.1f}MiB"
            )
        out.append(line)
    if record.get("compiles") is not None:
        warns = record.get("compile_warnings") or []
        line = (
            f"compiles: {record['compiles']} "
            f"({record.get('recompiles') or 0} post-warmup recompile(s), "
            f"{len(warns)} warning line(s))"
        )
        out.append(line)
        for w in warns[:5]:
            out.append(
                f"  RECOMPILE step {w.get('step')} {w.get('fn')}: "
                f"{w.get('delta')}"
            )
    prof = record.get("profile")
    if prof:
        out.append(
            f"profiler window: {prof.get('num_steps')} step(s) from "
            f"run-relative step {prof.get('start_step')} in "
            f"{_fmt(prof.get('wall_secs'), 's')} -> {prof.get('dir')}"
        )
    # ----- schema-v5 sharding provenance (omitted for older runs) -----
    sharding = record.get("sharding")
    if sharding:
        mesh_shape = sharding.get("mesh_shape") or {}
        shape = "x".join(
            f"{a}={s}" for a, s in mesh_shape.items() if int(s) > 1
        ) or "1 device"
        line = (
            f"sharding: mesh {shape}, digest "
            f"{sharding.get('param_sharding_digest')}"
        )
        if sharding.get("zero1"):
            line += ", ZeRO-1 optimizer sharding"
        if record.get("sharded_step_time") is not None:
            line += (
                "; sharded_step_time "
                f"{_fmt(record['sharded_step_time'] * 1e3, 'ms')}"
            )
        out.append(line)
    # ----- schema-v3 fleet sections (omitted for v1/v2 runs) -----
    hosts = record.get("hosts")
    if hosts:
        slowest = record.get("slowest_host")
        out.append(
            f"fleet: {len(hosts)} host shard(s)"
            + (f"; SLOWEST host {slowest}" if slowest is not None else "")
        )
        for h in hosts:
            p50, p95 = h["step_time_p50"], h["step_time_p95"]
            out.append(
                f"  host {h['host']}: step p50 "
                + _fmt(p50 * 1e3 if p50 is not None else None, "ms")
                + " / p95 "
                + _fmt(p95 * 1e3 if p95 is not None else None, "ms")
                + f", {_fmt(h['examples_per_sec_last'])} examples/sec, "
                + f"lost={h['steps_lost']}, "
                + f"ended: {h['exit_reason'] or 'UNKNOWN'}"
                + (" <- SLOWEST" if h["host"] == slowest else "")
            )
    fl = record.get("fleet")
    if fl:
        line = (
            f"fleet skew (last fleet line): {_fmt(fl.get('skew'), 'x')}"
        )
        if fl.get("slowest_host") is not None:
            line += f", slowest host {fl['slowest_host']}"
        if fl.get("side"):
            line += f", {fl['side']}-side"
        if record.get("fleet_straggler_windows"):
            line += (
                f"; STRAGGLER flagged in "
                f"{record['fleet_straggler_windows']} window(s)"
            )
        if fl.get("emergency"):
            line += " (emergency snapshot)"
        out.append(line)
    # ----- schema-v14 SLO alert section (omitted without a sink) -----
    al = record.get("alerts")
    if al:
        line = (
            f"slo alerts: {al['firings']} firing / {al['resolved']} "
            f"resolved event(s)"
        )
        if al.get("min_budget_remaining") is not None:
            line += (
                "; worst error budget remaining "
                + _fmt(al["min_budget_remaining"] * 100, "%", nd=1)
            )
        if al.get("still_firing"):
            line += f"; STILL FIRING: {', '.join(al['still_firing'])}"
        out.append(line)
        for ep in al.get("episodes", [])[:5]:
            out.append(
                f"  {ep['name']} ({ep.get('slo')}): fired for "
                + _fmt(ep.get("duration_s"), "s")
            )
        for tid in al.get("exemplar_trace_ids", []):
            out.append(
                f"  exemplar: trace_report --trace-id {tid}"
            )
    if "trace_phases" in record:
        out.append("host time by span (from trace.json):")
        for name, p in record["trace_phases"].items():
            out.append(
                f"  {name:<20} {p['total_ms']:>12,.1f}ms  x{p['count']}"
            )
    if skipped:
        out.append(
            f"WARNING: skipped {skipped} line(s) that failed schema "
            "validation (torn tail or version drift)"
        )
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument(
        "workdir",
        help="run dir (containing telemetry/metrics.jsonl), the telemetry "
        "dir itself, or a metrics.jsonl path",
    )
    ap.add_argument(
        "--json",
        metavar="PATH",
        help="also write the machine-readable record here ('-' = stdout)",
    )
    args = ap.parse_args(argv)

    record, skipped, err = build_record(args.workdir)
    if record is None:
        print(err, file=sys.stderr)
        return 1
    print(render(record, skipped))
    if args.json:
        payload = json.dumps(record, indent=2) + "\n"
        if args.json == "-":
            sys.stdout.write(payload)
        else:
            with open(args.json, "w") as f:
                f.write(payload)
    return 0


if __name__ == "__main__":
    sys.exit(main())
