#!/usr/bin/env python
"""CI perf gate: fail loudly when the trajectory or a run regresses.

Two gating modes, both exit 0 on pass / 1 on regression / 2 on unusable
input (an empty gate must read as an error, never as green):

**Trajectory mode** (default) — gate banked ``BENCH_*.json`` rounds
against the stamped floors in ``bench.py``::

    python tools/bench_gate.py BENCH_r0*.json
    python tools/bench_gate.py --threshold 0.1 BENCH_r0*.json

Each file contributes per-metric records: the driver wrapper's
``parsed`` record (head + extras) when present, else metric/value
fragments recovered from the ``tail`` text (the driver truncates long
JSON lines, so the regex sweep is the honest fallback — anything it
cannot recover is reported as skipped, not silently dropped). The
LATEST observation per (backend, metric) is compared against
``bench.FLOORS`` under the repo's floors policy: a verdict only counts
when the record's rig fingerprint is within 2x of the floor's
(``FLOORS POLICY``, bench.py docstring) — off-rig records are listed as
"not comparable", because calling them regressions would just punish
rig drift. ``*step_time*`` metrics gate lower-is-better; everything
else higher-is-better.

**Record mode** — gate one run's telemetry record (the
``tools/telemetry_report.py --json`` output) against a stamped floors
file::

    python tools/bench_gate.py --record report.json --floors floors.json
    python tools/bench_gate.py --stamp report.json --floors floors.json

``--stamp`` writes the floors file from a known-good record (step-time
p50/p95 and peak memory as maxima; MFU, goodput, and mean throughput as
minima). Gating tolerates ``--threshold`` (default 10%) slack around
each floor, and keys absent from the record (e.g. ``peak_live_bytes``
on a schema-v1 run) are skipped gracefully — reported, never failed.
"""

from __future__ import annotations

import argparse
import glob as glob_mod
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_THRESHOLD = 0.10

# Floors policy (bench.py docstring): a vs-floor comparison is only a
# regression verdict when the record's rig fingerprint is within this
# factor of the floor's.
FINGERPRINT_COMPARABLE_FACTOR = 2.0

# Telemetry-record gate keys: direction of the stamped bound.
# sharded_step_time (ISSUE 7): a model-parallel run's step-time p50
# under its own key — telemetry_report emits it only when the final
# line's mesh_shape has a nontrivial non-data axis, so a sharded
# layout gates against a sharded floor, never the 1-device one.
RECORD_KEYS: dict[str, str] = {
    "step_time_p50": "max",
    "step_time_p95": "max",
    "sharded_step_time": "max",
    "peak_live_bytes": "max",
    "mfu": "min",
    "goodput": "min",
    "examples_per_sec_mean": "min",
    # Serving-tier records (ISSUE 8): serve_bench --router banks a
    # ``serve_router`` record (and tools/run_diff.py flattens the same
    # keys from a canary diff doc) — latency maxima, throughput and
    # prefix-cache minima, recompiles pinned at their stamped count
    # (zero on a healthy tier).
    "ttft_p50_ms": "max",
    "ttft_p95_ms": "max",
    "tpot_p50_ms": "max",
    "tpot_p95_ms": "max",
    "e2e_p95_ms": "max",
    "req_per_s": "min",
    "tok_per_s": "min",
    "prefix_hit_rate": "min",
    "post_warmup_recompiles": "max",
    # Chaos/availability records (ISSUE 10): serve_bench --chaos banks
    # error_rate (gated at 0 for the smoke config — any failed request
    # under a single-replica kill is a regression; the threshold slack
    # multiplies a 0 bound into 0, so the gate is exact) and the
    # chaos-vs-baseline p95 ratio as a declared-multiple maximum.
    "error_rate": "max",
    "p95_vs_baseline": "max",
    # Cache-aware scheduling (ISSUE 12): serve_bench --router
    # --affinity ab banks the A/B record; the -affinity hit rate is
    # the floor that catches a scheduler regression quietly reverting
    # the fleet to cache-blind dispatch.
    "prefix_hit_rate_affinity": "min",
    # Speculative decoding (ISSUE 11): serve_bench --spec-decode banks
    # the off/on TPOT ratio — the one number the tentpole claims. A
    # stamped floor pins it so a drafter/verify regression that quietly
    # eats the speedup fails CI like any other perf loss.
    "tpot_speedup": "min",
    "draft_hit_rate": "min",
    # Overload robustness (ISSUE 13): serve_bench --traffic banks the
    # serve_traffic record — per-class latency maxima (the SLO split
    # the admission tier exists for), the interactive shed rate pinned
    # as a maximum (interactive must not absorb an overload batch
    # could have), and the autoscaler's scale-up latency (decision ->
    # green -> routed) as a maximum.
    "ttft_p95_interactive_ms": "max",
    "ttft_p95_batch_ms": "max",
    "shed_rate_interactive": "max",
    "scale_up_latency_s": "max",
    # Weight quantization (ISSUE 15): serve_bench --weight-dtype banks
    # the serve_quant A/B record — the f32/quant TPOT ratio pinned as
    # a minimum (a dequant-path regression that quietly eats the
    # memory-bound speedup fails CI) and HBM param bytes per replica
    # as a maximum (the ~4x replicas-per-host claim, measured via
    # engine.byte_breakdown).
    "tpot_speedup_quant": "min",
    "hbm_bytes_per_replica": "max",
    # Control-plane resilience (ISSUE 16): serve_bench --chaos banks a
    # second serve_takeover record — the standby's detect-to-serving
    # promotion wall pinned as a maximum (a probe-rebuild or journal-
    # replay regression that quietly slows takeover fails CI).
    # Floorless: the record's own ok already gates lost_requests at 0
    # and dedup_hits >= 1, so only the latency needs a floor file.
    "takeover_latency_s": "max",
    # Distributed tracing (ISSUE 18): serve_bench banks the recorder's
    # tail-sampling summary — coverage (kept / finished) pinned as a
    # minimum so a sampler regression that quietly stops keeping the
    # interesting traces fails CI, and the slow-trace count as a
    # maximum (a latency regression surfaces here as MORE traces
    # crossing their class threshold, before any p95 floor moves).
    "trace_coverage": "min",
    "slow_trace_count": "max",
    # SLO alerting (ISSUE 19): serve_bench --slo banks the AlertEngine
    # summary — alerts fired over the run pinned as a maximum (a
    # healthy smoke's floor file says 0: ANY firing alert fails CI) and
    # the canary probe success rate as a minimum (a replica that 200s
    # organic traffic but flunks the known-answer probe fails here
    # before users find it). Floorless until a floor file pins them.
    "alert_count": "max",
    "probe_success_rate": "min",
}


def _lower_is_better(metric: str) -> bool:
    return "step_time" in metric


# ---------------------------------------------------------- extraction


def _flatten_bench_record(rec: dict) -> list[dict]:
    """A driver head record + its extras -> flat per-metric records."""
    backend = rec.get("backend", "")
    out = []
    for r in [rec] + list(rec.get("extras") or []):
        if not isinstance(r, dict) or "metric" not in r:
            continue
        if "value" not in r or r.get("error"):
            continue
        fp = (
            r.get("fingerprint_tflops_pre")
            or r.get("fingerprint_tflops")
            or rec.get("fingerprint_tflops_pre")
            or rec.get("fingerprint_tflops")
            or rec.get("probe_tflops_at_bench")
        )
        out.append(
            {
                "metric": r["metric"],
                "value": float(r["value"]),
                "backend": r.get("backend", backend),
                "fingerprint": float(fp) if fp else None,
            }
        )
        # Host input-throughput rider (ISSUE 6): the resnet50_input
        # record carries the pipeline-only img/s (decode+augment with
        # no device in the loop) as an annotation. Promote it to a
        # first-class tracked metric so bench_gate floors it instead of
        # leaving it a buried extras field.
        pipeline_only = r.get("pipeline_only_images_per_sec")
        if pipeline_only is not None:
            out.append(
                {
                    "metric": _pipeline_only_metric(r["metric"]),
                    "value": float(pipeline_only),
                    "backend": r.get("backend", backend),
                    "fingerprint": float(fp) if fp else None,
                }
            )
    return out


def _pipeline_only_metric(parent_metric: str) -> str:
    """Derived metric name for a record's pipeline-only annotation."""
    base = parent_metric.replace("_examples_per_sec_per_chip", "")
    return f"{base}_pipeline_only_images_per_sec"


def _records_from_tail(tail: str) -> list[dict]:
    """Recover per-metric records from a truncated driver tail.

    The driver keeps only the last N chars of the bench output, so the
    one JSON line is usually torn at the front; individual
    ``{"metric": ..., "value": ...}`` fragments survive whole (dict
    insertion order pins the key order). Each fragment's fingerprint is
    the first ``fingerprint_tflops_pre`` that FOLLOWS it — per-record
    fingerprints trail their record in the serialized form.
    """
    metrics = [
        (m.start(), m.group(1), float(m.group(2)))
        for m in re.finditer(
            r'\{"metric": "([A-Za-z0-9_]+)", "value": ([-0-9.eE+]+)', tail
        )
    ]
    fps = [
        (m.start(), float(m.group(1)))
        for m in re.finditer(r'"fingerprint_tflops_pre": ([0-9.]+)', tail)
    ]
    backends = re.findall(r'"backend": "(\w+)"', tail)
    backend = backends[-1] if backends else "tpu"
    out = []
    for pos, metric, value in metrics:
        # No fingerprint following the record means ITS fingerprint was
        # lost to truncation — None (→ skipped as not comparable), never
        # a neighbor's.
        fp = next((v for p, v in fps if p > pos), None)
        out.append(
            {
                "metric": metric,
                "value": value,
                "backend": backend,
                "fingerprint": fp,
            }
        )
    # Pipeline-only riders (ISSUE 6): attach each to the metric fragment
    # it trails in the serialized form, mirroring _flatten_bench_record.
    for m in re.finditer(
        r'"pipeline_only_images_per_sec": ([-0-9.eE+]+)', tail
    ):
        pos = m.start()
        parents = [name for p, name, _ in metrics if p < pos]
        if not parents:
            continue  # the owning fragment was lost to truncation
        fp = next((v for p, v in fps if p > pos), None)
        out.append(
            {
                "metric": _pipeline_only_metric(parents[-1]),
                "value": float(m.group(1)),
                "backend": backend,
                "fingerprint": fp,
            }
        )
    return out


def extract_records(path: str) -> list[dict]:
    """Per-metric records from one trajectory file (or a bare record)."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        return []
    if isinstance(doc.get("parsed"), dict):
        return _flatten_bench_record(doc["parsed"])
    if "metric" in doc:  # a bare bench record (synthetic gate inputs)
        return _flatten_bench_record(doc)
    return _records_from_tail(doc.get("tail", "") or "")


# ---------------------------------------------------- trajectory gate


def gate_trajectory(paths: list[str], threshold: float,
                    floors_path: str | None = None) -> int:
    import bench  # floors + policy live with the bench driver

    latest: dict[tuple[str, str], tuple[str, dict]] = {}
    for path in sorted(paths):
        for rec in extract_records(path):
            latest[(rec["backend"], rec["metric"])] = (
                os.path.basename(path), rec,
            )
    if not latest:
        print(
            "bench_gate: no per-metric records recovered from "
            f"{len(paths)} file(s) — refusing to report green on an "
            "empty gate",
            file=sys.stderr,
        )
        return 2

    failures, passed, skipped = [], [], []
    for (backend, metric), (src, rec) in sorted(latest.items()):
        floor = bench.FLOORS.get(backend, {}).get(metric)
        if floor is None:
            skipped.append(f"{metric} [{backend}] ({src}): no stamped floor")
            continue
        floor_value, floor_fp = floor
        fp = rec["fingerprint"]
        if not fp and floor_fp:
            # A record whose fingerprint was lost (tail truncation)
            # cannot satisfy the comparability precondition — skipping
            # it is the floors policy, gating it would punish rig drift.
            skipped.append(
                f"{metric} [{backend}] ({src}): no rig fingerprint "
                "recovered for the record — comparability unknown "
                "(floors policy), not gated"
            )
            continue
        if fp and floor_fp:
            ratio = fp / floor_fp
            if not (
                1.0 / FINGERPRINT_COMPARABLE_FACTOR
                <= ratio
                <= FINGERPRINT_COMPARABLE_FACTOR
            ):
                skipped.append(
                    f"{metric} [{backend}] ({src}): rig fingerprint "
                    f"{fp:,.0f} vs floor's {floor_fp:,.0f} is outside the "
                    f"{FINGERPRINT_COMPARABLE_FACTOR:g}x comparability "
                    "window (floors policy) — read rel_mfu instead"
                )
                continue
        value = rec["value"]
        if _lower_is_better(metric):
            bad = value > floor_value * (1.0 + threshold)
            rel = value / floor_value if floor_value else float("inf")
        else:
            bad = value < floor_value * (1.0 - threshold)
            rel = value / floor_value if floor_value else 0.0
        line = (
            f"{metric} [{backend}] ({src}): {value:,.4f} vs floor "
            f"{floor_value:,.4f} ({rel:,.3f}x, "
            f"{'lower' if _lower_is_better(metric) else 'higher'}-is-better)"
        )
        (failures if bad else passed).append(line)

    for name, rows in (("PASS", passed), ("SKIP", skipped),
                       ("FAIL", failures)):
        for row in rows:
            print(f"[{name}] {row}")
    print(
        f"bench_gate trajectory: {len(passed)} passed, {len(skipped)} "
        f"skipped, {len(failures)} regressed (threshold "
        f"{threshold:.0%})"
    )
    report_floorless(floors_path)
    report_lint_baseline()
    return 1 if failures else 0


# ----------------------------------------------------- floorless keys


def floorless_keys(floors_path: str | None = None) -> list[str]:
    """Gate keys that exist with NO banked floor anywhere — neither a
    ``bench.FLOORS`` metric (any backend) nor an entry in an optional
    stamped record-mode floors file. These are claims the repo gates in
    tooling but has never pinned to a real-rig number (the ROADMAP
    standing note: ``sharded_step_time``, serving TTFT/TPOT/prefix-hit,
    ``serve_chaos`` p95) — the harvest list for the first session on
    real hardware."""
    import bench

    floored: set[str] = set()
    for metrics in bench.FLOORS.values():
        floored.update(metrics)
    if floors_path and os.path.isfile(floors_path):
        with open(floors_path) as f:
            doc = json.load(f)
        if isinstance(doc, dict):
            floored.update(doc)
    return [k for k in sorted(RECORD_KEYS) if k not in floored]


def _lint_baseline_total(baseline_path: str) -> int | None:
    """Accepted-finding total of a graftlint suppression baseline
    (None when absent/unreadable — never an exception: the perf gate
    must not fail on a lint artifact)."""
    if not os.path.isfile(baseline_path):
        return None
    try:
        with open(baseline_path) as f:
            doc = json.load(f)
    except (ValueError, OSError):
        return None
    findings = doc.get("findings") if isinstance(doc, dict) else None
    if not isinstance(findings, dict):
        return None
    return sum(v for v in findings.values() if isinstance(v, int))


def report_lint_baseline(
    baseline_path: str | None = None,
    count_path: str | None = None,
) -> int:
    """WARN (never fail) when the committed graftlint suppression
    baseline (ISSUE 14) has GROWN past its tracked count.

    The baseline total is a tracked metric exactly like a perf floor:
    ``tools/graftlint_baseline.count`` records the reviewed size, and
    growing the baseline without bumping the count file — i.e. hiding
    a new unguarded access or JAX hazard behind a suppression instead
    of fixing it — prints a WARN on every trajectory gate. Shrinking
    is celebrated and nudges the count file down. Exit 0 always."""
    baseline_path = baseline_path or os.path.join(
        REPO, "tools", "graftlint_baseline.json"
    )
    count_path = count_path or os.path.join(
        REPO, "tools", "graftlint_baseline.count"
    )
    total = _lint_baseline_total(baseline_path)
    if total is None:
        return 0
    tracked: int | None = None
    if os.path.isfile(count_path):
        try:
            with open(count_path) as f:
                tracked = int(f.read().strip())
        except (ValueError, OSError):
            tracked = None
    if tracked is None:
        print(
            f"bench_gate lint baseline: {total} accepted finding(s); "
            f"no tracked count — record it with "
            f"`echo {total} > {count_path}`"
        )
    elif total > tracked:
        print(
            f"[WARN] graftlint suppression baseline GREW: {total} "
            f"accepted finding(s) vs tracked {tracked} — new "
            "suppressions need review (fix the finding or bump "
            f"{count_path} deliberately in the same change)"
        )
    elif total < tracked:
        print(
            f"bench_gate lint baseline: shrank to {total} accepted "
            f"finding(s) (tracked {tracked}) — update {count_path}"
        )
    else:
        print(
            f"bench_gate lint baseline: {total} accepted finding(s) "
            "(matches the tracked count)"
        )
    return 0


def report_floorless(floors_path: str | None = None,
                     out_path: str | None = None) -> int:
    """WARN (never fail) for every floorless gate key; exit 0 always —
    this is a to-harvest list, not a regression.

    ``out_path`` (ISSUE 18 satellite) banks the list INTO a record:
    a JSON doc carrying the floorless keys and the full gate-key
    census, so the first real-rig session reads its harvest list from
    an artifact instead of scraping WARN lines out of CI logs."""
    missing = floorless_keys(floors_path)
    for key in missing:
        print(
            f"[WARN] gate key '{key}' has no banked floor — harvest a "
            "known-good record on the real rig and stamp it "
            "(bench_gate --stamp REPORT --floors FLOORS)"
        )
    print(
        f"bench_gate floorless: {len(missing)} gate key(s) await a "
        "banked floor"
    )
    if out_path:
        doc = {
            "kind": "bench_gate_floorless",
            "floorless": missing,
            "floorless_count": len(missing),
            "gate_keys": {
                k: RECORD_KEYS[k] for k in sorted(RECORD_KEYS)
            },
        }
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"banked floorless record -> {out_path}")
    return 0


# -------------------------------------------------------- record gate


def gate_record(record_path: str, floors_path: str, threshold: float) -> int:
    with open(record_path) as f:
        record = json.load(f)
    with open(floors_path) as f:
        floors = json.load(f)

    failures, passed, skipped = [], [], []
    for key, spec in sorted(floors.items()):
        if not isinstance(spec, dict) or not ({"max", "min"} & spec.keys()):
            skipped.append(f"{key}: malformed floor spec {spec!r}")
            continue
        value = record.get(key)
        if value is None:
            # Graceful v1 degrade: a record predating the field (e.g.
            # peak_live_bytes before schema v2) skips, never fails.
            skipped.append(f"{key}: absent from record")
            continue
        if "max" in spec:
            bound = float(spec["max"])
            bad = value > bound * (1.0 + threshold)
            line = f"{key}: {value:,.6g} vs max {bound:,.6g}"
        else:
            bound = float(spec["min"])
            bad = value < bound * (1.0 - threshold)
            line = f"{key}: {value:,.6g} vs min {bound:,.6g}"
        (failures if bad else passed).append(line)

    if not passed and not failures:
        print(
            "bench_gate: floors file gated nothing (every key absent or "
            "malformed) — refusing to report green",
            file=sys.stderr,
        )
        return 2
    for name, rows in (("PASS", passed), ("SKIP", skipped),
                       ("FAIL", failures)):
        for row in rows:
            print(f"[{name}] {row}")
    print(
        f"bench_gate record: {len(passed)} passed, {len(skipped)} "
        f"skipped, {len(failures)} regressed (threshold {threshold:.0%})"
    )
    return 1 if failures else 0


def stamp_floors(record_path: str, floors_path: str) -> int:
    with open(record_path) as f:
        record = json.load(f)
    floors = {}
    for key, direction in RECORD_KEYS.items():
        value = record.get(key)
        if value is not None:
            floors[key] = {direction: value}
    if not floors:
        print(
            f"bench_gate: nothing stampable in {record_path} (keys "
            f"{sorted(RECORD_KEYS)})",
            file=sys.stderr,
        )
        return 2
    with open(floors_path, "w") as f:
        json.dump(floors, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"stamped {len(floors)} floor(s) -> {floors_path}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument(
        "trajectory", nargs="*",
        help="BENCH_*.json files (or bare bench records) to gate against "
        "bench.py FLOORS; globs accepted",
    )
    ap.add_argument(
        "--threshold", type=float, default=DEFAULT_THRESHOLD,
        help="allowed relative slack around each floor (default 0.10)",
    )
    ap.add_argument(
        "--record", metavar="REPORT_JSON",
        help="gate one telemetry_report --json record instead",
    )
    ap.add_argument(
        "--floors", metavar="FLOORS_JSON",
        help="stamped floors file for --record / --stamp",
    )
    ap.add_argument(
        "--stamp", metavar="REPORT_JSON",
        help="write --floors from this known-good record, then exit",
    )
    ap.add_argument(
        "--floorless-report", action="store_true",
        help="list gate keys with no banked floor (WARN only, exit 0) "
        "— the to-harvest list for the first real-rig session; also "
        "appended to every trajectory gate",
    )
    ap.add_argument(
        "--out", metavar="OUT_JSON",
        help="with --floorless-report: also bank the floorless list "
        "(plus the full gate-key census) as a JSON record",
    )
    ap.add_argument(
        "--lint-baseline-report", action="store_true",
        help="report the graftlint suppression-baseline size vs its "
        "tracked count (WARN on growth, exit 0 always; also appended "
        "to every trajectory gate)",
    )
    args = ap.parse_args(argv)

    if args.floorless_report:
        return report_floorless(args.floors, args.out)
    if args.lint_baseline_report:
        return report_lint_baseline()
    if args.stamp:
        if not args.floors:
            ap.error("--stamp requires --floors")
        return stamp_floors(args.stamp, args.floors)
    if args.record:
        if not args.floors:
            ap.error("--record requires --floors")
        return gate_record(args.record, args.floors, args.threshold)

    paths: list[str] = []
    for pat in args.trajectory:
        hits = sorted(glob_mod.glob(pat))
        paths.extend(hits if hits else [pat])
    if not paths:
        ap.error("no trajectory files given (and no --record)")
    missing = [p for p in paths if not os.path.isfile(p)]
    if missing:
        print(f"bench_gate: missing file(s): {missing}", file=sys.stderr)
        return 2
    return gate_trajectory(paths, args.threshold, args.floors)


if __name__ == "__main__":
    sys.exit(main())
