#!/usr/bin/env python
"""Fleet router CLI: one endpoint over N serving replicas (ISSUE 8),
with replica supervision (ISSUE 10).

    # Replicas started elsewhere (examples/gpt2/serve.py, one per
    # host/chip), router in front:
    python tools/serve_fleet.py --port 9000 \
        --replica http://host-a:8000 --replica http://host-b:8000

    # SUPERVISED local replicas: serve_fleet spawns each --spawn
    # command (the {port} placeholder receives an assigned port),
    # waits for its /health to go green, and a supervisor thread then
    # watches it — a replica that dies (process exit) or wedges
    # (/health stalling past --health-stall) is quarantined in the
    # router, restarted (the fresh process re-warms its own AOT
    # ladder), and re-admitted only once /health is green again. A
    # crash-looping replica is given up on after --max-restarts and
    # left quarantined with an ERROR.
    python tools/serve_fleet.py --port 9000 \
        --spawn 'python examples/gpt2/serve.py --workdir w0 --port {port}' \
        --spawn 'python examples/gpt2/serve.py --workdir w0 --port {port}' \
        --spawn-base-port 8100

    # Telemetry-driven autoscaling (ISSUE 13): --spawn[0] is the
    # replica template; the fleet resizes between --min-replicas and
    # --max-replicas against the probe-fed signals (queue depth, KV
    # occupancy, brownout level, and per-replica /metrics TTFT p95
    # when --target-ttft-p95 is set). Scale-up green-gates the fresh
    # replica (AOT warmup finishes before it joins); scale-down is
    # always drain-first. A supervisor incident pauses all scaling
    # (the crash-loop guard).
    python tools/serve_fleet.py --port 9000 --autoscale \
        --spawn 'python examples/gpt2/serve.py --workdir w0 --port {port}' \
        --min-replicas 1 --max-replicas 4 --target-queue 4

    # Warm-standby control plane (ISSUE 16): accepted requests are
    # journaled durably; a second router on --standby-port answers
    # fenced 503s until the primary's lease heartbeat goes stale, then
    # promotes itself — rebuilding probe state from /health sweeps and
    # in-flight work from the journal (replayed token-identically by
    # seeding). A client keeps both URLs and retries the other on
    # transport failure; duplicate request_id retries dedupe.
    python tools/serve_fleet.py --port 9000 --standby \
        --standby-port 9001 --journal fleet.journal \
        --replica http://host-a:8000 --replica http://host-b:8000

    # Canary rollout: route 25% of traffic to the canary set and bank
    # a run_diff comparison of the two sets at exit (or on demand at
    # GET /canary):
    python tools/serve_fleet.py --port 9000 \
        --replica http://host-a:8000 --replica http://host-b:8000 \
        --canary http://host-c:8000 --canary-fraction 0.25 \
        --diff-out canary_diff.json

Ops verbs while running (the rollout runbook, docs/serving.md):

    curl -s :9000/replicas                      # fleet state
    curl -s -XPOST :9000/drain \
        -d '{"replica": "http://host-a:8000"}'  # stop NEW dispatch
    # ... restart host-a with the new build, then:
    curl -s -XPOST :9000/undrain \
        -d '{"replica": "http://host-a:8000"}'

The router stops dispatching to a drained (or self-draining — SIGTERM
on the replica flips its /health) replica while in-flight requests
finish on the replica itself; 503s and transport failures retry once
on another replica within a per-request budget, so a single-replica
drain under load completes with zero failed requests (test-pinned).

SIGTERM to the router itself closes the listening port and exits 0
(replicas are not touched — they drain on their own schedule). A
schema-v6 ``kind="serving"`` stats line is appended to ``--stats-out``
every ``--stats-every`` seconds.

SLO watching (ISSUE 19): the router always runs an AlertEngine
(``--slo slo.json`` loads declared objectives; the built-in defaults
are generous) doing error-budget burn-rate alerting — firing/resolve
transitions append schema-v14 ``kind="alert"`` lines to
``--alerts-out``, live state is ``GET /alerts``, ring-buffered
instrument history is ``GET /series``, and
``--synthetic-probe-every S`` runs the known-answer canary prober
through the router and each replica so a sick replica alerts ahead of
organic traffic (``tools/slo_watch.py`` is the terminal view).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--replica", action="append", default=[],
                    help="base-set replica URL (repeatable)")
    ap.add_argument("--canary", action="append", default=[],
                    help="canary-set replica URL (repeatable)")
    ap.add_argument("--port", type=int, default=9000,
                    help="router listen port (0 = auto-assign)")
    ap.add_argument("--probe-interval", type=float, default=0.5)
    ap.add_argument("--request-timeout", type=float, default=120.0)
    ap.add_argument("--retry-budget", type=float, default=10.0)
    ap.add_argument("--canary-fraction", type=float, default=0.25,
                    help="traffic share for the canary set")
    ap.add_argument("--stats-every", type=float, default=10.0,
                    help="seconds between stats lines (0 disables)")
    ap.add_argument("--stats-out", default="",
                    help="append stats lines here (default stderr)")
    ap.add_argument("--diff-out", default="",
                    help="write the base-vs-canary run_diff doc here "
                         "at exit (needs --canary)")
    ap.add_argument("--spawn", action="append", default=[],
                    help="spawn + SUPERVISE a local replica from this "
                         "command ({port} placeholder; repeatable)")
    ap.add_argument("--spawn-base-port", type=int, default=8100,
                    help="first port for --spawn replicas")
    ap.add_argument("--spawn-warm-timeout", type=float, default=600.0,
                    help="seconds to wait for a spawned replica's "
                         "/health to go green at startup")
    ap.add_argument("--health-stall", type=float, default=15.0,
                    help="supervisor: /health silent this long -> "
                         "restart the replica")
    ap.add_argument("--max-restarts", type=int, default=5,
                    help="supervisor: give up on a crash-looping "
                         "replica after this many restarts")
    ap.add_argument("--max-retries", type=int, default=2,
                    help="bounded retry: re-dispatches per request")
    ap.add_argument("--hedge-after", type=float, default=0.0,
                    help=">0: hedged dispatch for p99 — resend a "
                         "request unanswered this long (seconds)")
    ap.add_argument("--eject-after", type=int, default=3,
                    help="circuit breaker: consecutive dispatch "
                         "failures before ejecting a replica")
    ap.add_argument("--eject-cooldown", type=float, default=3.0,
                    help="circuit breaker: seconds ejected before the "
                         "half-open probe")
    ap.add_argument("--autoscale", action="store_true",
                    help="ISSUE 13: run the telemetry-driven "
                         "autoscaler — --spawn[0] is the replica "
                         "template; the fleet resizes between "
                         "--min-replicas and --max-replicas against "
                         "the target signals, scale-down drain-first")
    ap.add_argument("--min-replicas", type=int, default=1)
    ap.add_argument("--max-replicas", type=int, default=4)
    ap.add_argument("--target-queue", type=float, default=4.0,
                    help="autoscaler: mean queued requests per "
                         "eligible replica before scaling up")
    ap.add_argument("--target-kv", type=float, default=0.85,
                    help="autoscaler: mean KV occupancy before "
                         "scaling up")
    ap.add_argument("--target-ttft-p95", type=float, default=0.0,
                    help="autoscaler: worst-replica TTFT p95 seconds "
                         "before scaling up (0 disables the signal)")
    ap.add_argument("--scale-hold", type=float, default=5.0,
                    help="autoscaler: min seconds between actions")
    ap.add_argument("--scale-down-idle", type=float, default=30.0,
                    help="autoscaler: sustained-idle seconds before a "
                         "drain-first scale-down")
    ap.add_argument("--journal", default="",
                    help="ISSUE 16: durable request journal (JSONL). "
                         "Accepted requests append an intent before "
                         "dispatch; a restarted router replays the "
                         "incomplete ones (token-identical by "
                         "seeding), and duplicate request_id retries "
                         "dedupe to the original tokens")
    ap.add_argument("--standby", action="store_true",
                    help="ISSUE 16: run a warm-standby router pair "
                         "over the fleet — the standby tails the "
                         "journal, answers fenced 503s until the "
                         "primary's lease heartbeat goes stale, then "
                         "promotes itself (monotonic fencing token: "
                         "a stalled-then-revived primary refuses its "
                         "own dispatches). Needs --journal")
    ap.add_argument("--standby-port", type=int, default=0,
                    help="standby router listen port (0 = auto)")
    ap.add_argument("--lease", default="",
                    help="active-router lease file (default: "
                         "<journal>.lease)")
    ap.add_argument("--heartbeat-miss", type=float, default=2.0,
                    help="standby: promote after the primary's lease "
                         "heartbeat is stale this many seconds")
    ap.add_argument("--slo", default="",
                    help="ISSUE 19: SLO config JSON (slo.json) for the "
                         "router's AlertEngine (default: built-in "
                         "generous objectives)")
    ap.add_argument("--alerts-out", default="",
                    help="append schema-v14 kind=\"alert\" firing/"
                         "resolve lines here (JSONL, fsync per line)")
    ap.add_argument("--synthetic-probe-every", type=float, default=0.0,
                    help="ISSUE 19: >0 runs the canary prober — "
                         "deterministic known-answer requests through "
                         "the router AND each replica frontend at this "
                         "cadence (seconds), feeding the AlertEngine "
                         "ahead of organic traffic; 0 disables")
    ap.add_argument("--no-affinity", action="store_true",
                    help="disable prefix-affinity dispatch (ISSUE 12; "
                         "on by default — the router prefers the "
                         "replica already caching the prompt's prefix "
                         "chain, load-guarded)")
    ap.add_argument("--affinity-load-gap", type=float, default=2.0,
                    help="affinity only wins while the chain-holder's "
                         "load score is within this gap of the "
                         "least-loaded replica")
    args = ap.parse_args(argv)
    if not args.replica and not args.spawn:
        ap.error("at least one --replica URL or --spawn command is "
                 "required")
    if args.diff_out and not args.canary:
        ap.error("--diff-out needs a --canary set to compare against")
    if args.autoscale and not args.spawn:
        ap.error("--autoscale needs a --spawn command to use as the "
                 "replica template")
    if args.standby and not args.journal:
        ap.error("--standby needs --journal (the standby rebuilds "
                 "in-flight work from the journal at takeover)")
    if args.standby and (args.canary or args.autoscale):
        ap.error("--standby does not compose with --canary/--autoscale "
                 "yet (the pair owns router lifecycle)")
    if args.standby and (args.slo or args.alerts_out):
        ap.error("--standby does not compose with --slo/--alerts-out "
                 "yet (the pair constructs both routers itself)")

    from tensorflow_examples_tpu.serving.router import (
        Router,
        RouterConfig,
        RouterFrontend,
        _get_json,
    )
    from tensorflow_examples_tpu.serving.supervisor import (
        Autoscaler,
        AutoscalerConfig,
        ProcessReplica,
        Supervisor,
    )

    spawned = []
    try:
        for i, cmd in enumerate(args.spawn):
            rep = ProcessReplica(
                cmd, port=args.spawn_base_port + i
            ).start()
            spawned.append(rep)
        for rep in spawned:
            deadline = time.monotonic() + args.spawn_warm_timeout
            while time.monotonic() < deadline:
                status, body = _get_json(rep.url + "/health", 2.0)
                if status == 200 and body.get("ok"):
                    print(f"replica {rep.url} green", file=sys.stderr)
                    break
                if not rep.alive():
                    raise SystemExit(
                        f"spawned replica {rep.url} exited before its "
                        "/health ever went green"
                    )
                time.sleep(0.5)
            else:
                raise SystemExit(
                    f"spawned replica {rep.url} not green within "
                    f"{args.spawn_warm_timeout:.0f}s"
                )
    except BaseException:
        # A failed startup must not orphan the replicas already
        # spawned — they hold their ports (and devices) with no
        # supervisor attached.
        for rep in spawned:
            rep.close()
        raise

    replica_urls = args.replica + [rep.url for rep in spawned]
    cfg = RouterConfig(
        probe_interval_s=args.probe_interval,
        request_timeout_s=args.request_timeout,
        retry_budget_s=args.retry_budget,
        max_retries=args.max_retries,
        hedge_after_s=args.hedge_after,
        eject_after=args.eject_after,
        eject_cooldown_s=args.eject_cooldown,
        canary_fraction=args.canary_fraction,
        prefix_affinity=not args.no_affinity,
        affinity_load_gap=args.affinity_load_gap,
    )
    pair = None
    journal = None
    if args.standby:
        # ISSUE 16: warm-standby control plane. The pair owns both
        # routers, the journal and the lease; the primary serves
        # --port, the standby answers fenced 503s on --standby-port
        # until it promotes itself on missed heartbeat.
        from tensorflow_examples_tpu.serving.chaos import RouterPair

        pair = RouterPair(
            replica_urls,
            journal_path=args.journal,
            lease_path=args.lease or args.journal + ".lease",
            router_cfg=cfg,
            primary_port=args.port,
            standby_port=args.standby_port,
            miss_budget_s=args.heartbeat_miss,
        ).start()
        router = pair.primary
        if pair.replayed_at_start:
            print(
                f"journal: replayed {pair.replayed_at_start} "
                "incomplete intent(s) from a previous incarnation",
                file=sys.stderr,
            )
    else:
        if args.journal:
            from tensorflow_examples_tpu.serving.journal import (
                RequestJournal,
            )

            journal = RequestJournal(args.journal)
            journal.refresh()
        slo_cfg = None
        if args.slo:
            from tensorflow_examples_tpu.telemetry.slo import SLOConfig

            slo_cfg = SLOConfig.load(args.slo)
            print(
                f"slo: {len(slo_cfg.objectives)} objective(s) from "
                f"{args.slo}",
                file=sys.stderr,
            )
        router = Router(
            replica_urls, canary=args.canary, cfg=cfg, journal=journal,
            slo_cfg=slo_cfg, alert_path=args.alerts_out or None,
        ).start()
        if journal is not None:
            replayed = router.replay_incomplete()
            if replayed:
                print(
                    f"journal: replayed {replayed} incomplete "
                    "intent(s) from a previous incarnation",
                    file=sys.stderr,
                )
    supervisor = None
    if spawned:
        supervisor = Supervisor(
            router,
            spawned,
            poll_s=1.0,
            health_stall_s=args.health_stall,
            warm_timeout_s=args.spawn_warm_timeout,
            max_restarts=args.max_restarts,
        ).start()
        if pair is not None:
            # Takeover re-points supervision at the promoted standby.
            pair.supervisor = supervisor
    autoscaler = None
    if args.autoscale:
        # The spawn template: --spawn[0]'s command at the next free
        # port in the spawn range. ProcessReplica.start returns as
        # soon as the process exists; the autoscaler's green gate then
        # waits for the replica's own AOT warmup to finish (/health ok)
        # before it ever joins the router.
        next_port = [args.spawn_base_port + len(args.spawn)]

        def spawn_replica(idx):
            port = next_port[0]
            next_port[0] += 1
            return ProcessReplica(args.spawn[0], port=port).start()

        autoscaler = Autoscaler(
            router,
            supervisor,
            spawn_replica,
            alerts=router.alerts,  # firing SLO alerts = advisory hot
            cfg=AutoscalerConfig(
                min_replicas=args.min_replicas,
                max_replicas=args.max_replicas,
                target_queue_depth=args.target_queue,
                target_kv_occupancy=args.target_kv,
                target_ttft_p95_s=args.target_ttft_p95,
                hold_s=args.scale_hold,
                scale_down_idle_s=args.scale_down_idle,
                warm_timeout_s=args.spawn_warm_timeout,
            ),
        ).start()
        print(
            f"autoscaler on: {args.min_replicas}..{args.max_replicas} "
            f"replicas, targets queue<{args.target_queue} "
            f"kv<{args.target_kv} ttft_p95<"
            f"{args.target_ttft_p95 or 'off'}",
            file=sys.stderr,
        )
    if pair is not None:
        frontend = pair.primary_frontend  # started by pair.start()
        print(
            f"standby router on :{pair.standby_frontend.port} "
            f"(fenced; promotes after {args.heartbeat_miss:.1f}s of "
            "missed heartbeats)",
            file=sys.stderr,
        )
    else:
        frontend = RouterFrontend(router, port=args.port).start()
    # Role topology (ISSUE 12): heterogeneous prefill/decode fleets are
    # first-class — say what the probe sweep actually found, so a
    # mis-roled rollout is visible before it serves.
    roles: dict = {}
    for rep in router.replicas:
        roles[rep.role] = roles.get(rep.role, 0) + 1
    print(
        f"router on :{frontend.port} over {len(replica_urls)} base + "
        f"{len(args.canary)} canary replica(s)"
        + (f", supervising {len(spawned)}" if spawned else "")
        + f"; roles {roles}; prefix affinity "
        + ("off" if args.no_affinity else "on"),
        file=sys.stderr,
    )
    prober = None
    if args.synthetic_probe_every > 0:
        # ISSUE 19: black-box canary probes through the router (the
        # client path) and against every replica directly (a router
        # would mask a single sick replica by failing over around it).
        # Probes carry the "probe" tag, so they never enter the
        # journal dedupe window or the organic counters; failures feed
        # the router's AlertEngine on the probe cadence.
        from tensorflow_examples_tpu.serving.prober import (
            CanaryProber,
            fleet_targets,
        )

        prober = CanaryProber(
            fleet_targets(
                f"http://127.0.0.1:{frontend.port}", replica_urls
            ),
            alerts=router.alerts,
            registry=router.registry,
            interval_s=args.synthetic_probe_every,
        ).start()
        print(
            f"canary prober on: {len(prober.targets)} target(s) every "
            f"{args.synthetic_probe_every:.1f}s",
            file=sys.stderr,
        )

    stop = []
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.append(1))

    def emit_stats():
        live = pair.active_router if pair is not None else router
        line = json.dumps(live.stats_line())
        if args.stats_out:
            with open(args.stats_out, "a") as f:
                f.write(line + "\n")
        else:
            print(line, file=sys.stderr)

    last_stats = time.monotonic()
    try:
        while not stop:
            time.sleep(0.2)
            if (
                args.stats_every > 0
                and time.monotonic() - last_stats >= args.stats_every
            ):
                emit_stats()
                last_stats = time.monotonic()
    finally:
        if prober is not None:
            prober.close()
        frontend.close()
        if autoscaler is not None:
            autoscaler.close()
        if supervisor is not None:
            supervisor.close()
        if pair is not None:
            pair.close()  # both routers + journal + lease monitor
        else:
            router.close()
            if journal is not None:
                journal.close()
        for rep in spawned:
            rep.close()
        if autoscaler is not None:
            # Replicas the autoscaler spawned after startup.
            for url, handle in list(autoscaler.supervisor.handles.items()):
                handle.close()
        if args.diff_out:
            import run_diff

            base, canary = router.canary_records()
            deltas, skipped = run_diff.diff_records(base, canary)
            doc = {
                "a_path": "router:base",
                "b_path": "router:canary",
                "ranked": deltas,
                "not_comparable": skipped,
                "regressions": sum(
                    1 for d in deltas if d["verdict"] == "regressed"
                ),
                "a": base,
                "b": canary,
            }
            doc.update({k: canary.get(k) for k in run_diff.GATE_KEYS})
            with open(args.diff_out, "w") as f:
                json.dump(doc, f, indent=2)
                f.write("\n")
            print(f"canary diff -> {args.diff_out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
