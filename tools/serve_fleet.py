#!/usr/bin/env python
"""Fleet router CLI: one endpoint over N serving replicas (ISSUE 8).

    # Replicas started elsewhere (examples/gpt2/serve.py, one per
    # host/chip), router in front:
    python tools/serve_fleet.py --port 9000 \
        --replica http://host-a:8000 --replica http://host-b:8000

    # Canary rollout: route 25% of traffic to the canary set and bank
    # a run_diff comparison of the two sets at exit (or on demand at
    # GET /canary):
    python tools/serve_fleet.py --port 9000 \
        --replica http://host-a:8000 --replica http://host-b:8000 \
        --canary http://host-c:8000 --canary-fraction 0.25 \
        --diff-out canary_diff.json

Ops verbs while running (the rollout runbook, docs/serving.md):

    curl -s :9000/replicas                      # fleet state
    curl -s -XPOST :9000/drain \
        -d '{"replica": "http://host-a:8000"}'  # stop NEW dispatch
    # ... restart host-a with the new build, then:
    curl -s -XPOST :9000/undrain \
        -d '{"replica": "http://host-a:8000"}'

The router stops dispatching to a drained (or self-draining — SIGTERM
on the replica flips its /health) replica while in-flight requests
finish on the replica itself; 503s and transport failures retry once
on another replica within a per-request budget, so a single-replica
drain under load completes with zero failed requests (test-pinned).

SIGTERM to the router itself closes the listening port and exits 0
(replicas are not touched — they drain on their own schedule). A
schema-v6 ``kind="serving"`` stats line is appended to ``--stats-out``
every ``--stats-every`` seconds.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--replica", action="append", default=[],
                    help="base-set replica URL (repeatable)")
    ap.add_argument("--canary", action="append", default=[],
                    help="canary-set replica URL (repeatable)")
    ap.add_argument("--port", type=int, default=9000,
                    help="router listen port (0 = auto-assign)")
    ap.add_argument("--probe-interval", type=float, default=0.5)
    ap.add_argument("--request-timeout", type=float, default=120.0)
    ap.add_argument("--retry-budget", type=float, default=10.0)
    ap.add_argument("--canary-fraction", type=float, default=0.25,
                    help="traffic share for the canary set")
    ap.add_argument("--stats-every", type=float, default=10.0,
                    help="seconds between stats lines (0 disables)")
    ap.add_argument("--stats-out", default="",
                    help="append stats lines here (default stderr)")
    ap.add_argument("--diff-out", default="",
                    help="write the base-vs-canary run_diff doc here "
                         "at exit (needs --canary)")
    args = ap.parse_args(argv)
    if not args.replica:
        ap.error("at least one --replica URL is required")
    if args.diff_out and not args.canary:
        ap.error("--diff-out needs a --canary set to compare against")

    from tensorflow_examples_tpu.serving.router import (
        Router,
        RouterConfig,
        RouterFrontend,
    )

    router = Router(
        args.replica,
        canary=args.canary,
        cfg=RouterConfig(
            probe_interval_s=args.probe_interval,
            request_timeout_s=args.request_timeout,
            retry_budget_s=args.retry_budget,
            canary_fraction=args.canary_fraction,
        ),
    ).start()
    frontend = RouterFrontend(router, port=args.port).start()
    print(
        f"router on :{frontend.port} over {len(args.replica)} base + "
        f"{len(args.canary)} canary replica(s)",
        file=sys.stderr,
    )

    stop = []
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.append(1))

    def emit_stats():
        line = json.dumps(router.stats_line())
        if args.stats_out:
            with open(args.stats_out, "a") as f:
                f.write(line + "\n")
        else:
            print(line, file=sys.stderr)

    last_stats = time.monotonic()
    try:
        while not stop:
            time.sleep(0.2)
            if (
                args.stats_every > 0
                and time.monotonic() - last_stats >= args.stats_every
            ):
                emit_stats()
                last_stats = time.monotonic()
    finally:
        frontend.close()
        router.close()
        if args.diff_out:
            import run_diff

            base, canary = router.canary_records()
            deltas, skipped = run_diff.diff_records(base, canary)
            doc = {
                "a_path": "router:base",
                "b_path": "router:canary",
                "ranked": deltas,
                "not_comparable": skipped,
                "regressions": sum(
                    1 for d in deltas if d["verdict"] == "regressed"
                ),
                "a": base,
                "b": canary,
            }
            doc.update({k: canary.get(k) for k in run_diff.GATE_KEYS})
            with open(args.diff_out, "w") as f:
                json.dump(doc, f, indent=2)
                f.write("\n")
            print(f"canary diff -> {args.diff_out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
