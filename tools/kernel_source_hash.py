"""Content hash of the kernel sources the compiled selftest proves.

A banked ``tests_tpu/`` selftest record is evidence about the kernel
code AS IT WAS when the nodes ran on the chip. Reusing it after an
``ops/`` edit would silently satisfy the on-chip-parity requirement
with stale evidence (ADVICE r4). This module defines the one hash both
sides use: the harvest embeds it in the banked record, and bench.py's
``run_selftest(allow_banked=True)`` refuses a record whose hash does
not match the working tree.

Scope: every ``.py`` under ``tests_tpu/`` (the parity assertions),
``tensorflow_examples_tpu/ops/`` (the kernels they compile), and
``tensorflow_examples_tpu/parallel/`` (round 5: the gmm parity nodes
compile through parallel/moe.py's dispatch — a gmm-tiling edit there
must stale them, and ring/ulysses sit in the same boat for the lse
nodes). Hash is over (relative path, content) pairs in sorted order,
so renames and adds/removes change it too.

Usage: ``python tools/kernel_source_hash.py`` prints the hash.
"""

import hashlib
import os


def kernel_source_hash(repo_root: "str | None" = None) -> str:
    root = repo_root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    h = hashlib.sha256()
    # The flash block table is kernel configuration living outside the
    # package (docs/): swapping it changes every compiled flash kernel,
    # so it must stale banked selftest evidence exactly like a source
    # edit (flash_table_from_sweep.py used to delegate that to the
    # operator).
    table = os.path.join(
        root, "docs", "tpu_sweeps", "flash_block_table.json"
    )
    if os.path.exists(table):
        h.update(b"flash_block_table.json\0")
        with open(table, "rb") as f:
            h.update(f.read())
        h.update(b"\0")
    for sub in (
        "tests_tpu",
        os.path.join("tensorflow_examples_tpu", "ops"),
        os.path.join("tensorflow_examples_tpu", "parallel"),
    ):
        base = os.path.join(root, sub)
        files = []
        for dirpath, _dirnames, filenames in os.walk(base):
            files.extend(
                os.path.join(dirpath, f)
                for f in filenames
                if f.endswith(".py")
            )
        for path in sorted(files):
            h.update(os.path.relpath(path, root).encode())
            h.update(b"\0")
            with open(path, "rb") as f:
                h.update(f.read())
            h.update(b"\0")
    return h.hexdigest()


if __name__ == "__main__":
    print(kernel_source_hash())
