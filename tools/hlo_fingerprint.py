#!/usr/bin/env python
"""Structural fingerprint of a workload's compiled train step.

Prints one JSON line with XLA cost-model FLOPs/bytes/transcendentals,
the optimized-HLO instruction count, and an op histogram — all
rig-speed-independent — so two repo versions can be diffed for
compiled-program changes (`git worktree add /tmp/old <rev>`, run this
in both, diff the lines).

Used to resolve the round-4 bert 0.87x / cifar10 0.42x sub-floor TPU
readings (BASELINE.md): both steps fingerprinted identically between
the round-3 floor-stamp commit (d99bceb) and HEAD — FLOPs equal to
<0.0001%, op histograms within 0.3%, HEAD marginally leaner — proving
the deficits were rig-side (tunnel dispatch behavior), not code.

Usage: python tools/hlo_fingerprint.py {cifar10|bert|mnist}
Compiles on the CPU backend: structure, not speed, is the signal.
gpt2 is deliberately unsupported: its bench program runs the Pallas
flash kernel + fused CE, which on CPU compile as interpret-mode scan
loops structurally unrelated to the TPU custom calls — a fingerprint
of that would adjudicate the wrong program.
"""

import collections
import dataclasses
import json
import os
import re
import sys


def main() -> int:
    if len(sys.argv) != 2 or sys.argv[1] not in ("cifar10", "bert", "mnist"):
        print(__doc__)
        return 2
    which = sys.argv[1]

    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.train.loop import Trainer

    # The TPU bench shape of each workload (bench.py), so the
    # fingerprint tracks the program the floors measure.
    common = dict(log_every=10**9, checkpoint_every=0, eval_every=0,
                  train_steps=10**6, watchdog_secs=0, precision="bf16",
                  dropout=0.0)  # bench.py sets dropout=0.0 everywhere
    if which == "cifar10":
        from tensorflow_examples_tpu.data.sources import synthetic_images
        from tensorflow_examples_tpu.workloads import cifar10 as wl

        cfg_cls, batch = wl.Cifar10Config, 128
        make_ds = lambda cfg: synthetic_images(
            n=256, shape=(32, 32, 3), num_classes=10, seed=0
        )
    elif which == "mnist":
        from tensorflow_examples_tpu.data.sources import synthetic_images
        from tensorflow_examples_tpu.workloads import mnist as wl

        cfg_cls, batch = wl.MnistConfig, 256
        make_ds = lambda cfg: synthetic_images(
            n=256, shape=(28, 28, 1), num_classes=10, seed=0
        )
    else:
        from tensorflow_examples_tpu.workloads import bert_glue as wl

        cfg_cls, batch = wl.BertGlueConfig, 32
        make_ds = lambda cfg: wl.datasets(cfg)[0]

    fields = {f.name for f in dataclasses.fields(cfg_cls)}
    cfg = cfg_cls(
        global_batch_size=batch,
        **{k: v for k, v in common.items() if k in fields},
    )
    mesh = create_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    trainer = Trainer(wl.make_task(cfg), cfg, mesh=mesh)
    it = train_iterator(make_ds(cfg), cfg.global_batch_size, seed=0)
    dev_batch = trainer._put_batch(next(it))
    c = trainer._train_step.lower(trainer.state, dev_batch).compile()
    ca = c.cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca
    hlo = c.as_text()
    # Opcode after `= <type>`: the type may be a tuple — possibly
    # NESTED, e.g. a while carrying `(f32[2]{0}, (s32[], u32[]))` —
    # and opcodes may be hyphenated (`all-reduce`, `get-tuple-element`).
    # A regex `\([^)]*\)` stops at the first `)`, silently dropping
    # nested-tuple ops (exactly the control-flow ops a perf diff cares
    # about), so tuple types are skipped by balanced-paren scan.
    def _opcodes(text):
        for line in text.splitlines():
            m = re.search(r"=\s+", line)
            if not m:
                continue
            i, n = m.end(), len(line)
            if i < n and line[i] == "(":
                depth = 0
                while i < n:
                    depth += (line[i] == "(") - (line[i] == ")")
                    i += 1
                    if depth == 0:
                        break
                m2 = re.match(r"\s*([\w-]+)\(", line[i:])
            else:
                m2 = re.match(r"\S+\s+([\w-]+)\(", line[i:])
            if m2:
                yield m2.group(1)

    ops = collections.Counter(_opcodes(hlo))
    print(json.dumps({
        "workload": which,
        "batch": cfg.global_batch_size,
        "flops": ca.get("flops"),
        "bytes": ca.get("bytes accessed"),
        "transcendentals": ca.get("transcendentals"),
        "hlo_instructions": sum(ops.values()),
        "top_ops": sorted(ops.items(), key=lambda kv: -kv[1])[:18],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
