#!/usr/bin/env python
"""Convert GLUE TSV files into the tokenized ``.npz`` features for BERT.

The GLUE loader (data/sources.py:load_glue) consumes
``<task>_<split>.npz`` with ``tokens``/``attention_mask``/
``token_type_ids``/``label`` — the output of a BERT tokenizer run
offline. This tool is that run: it reads the standard GLUE TSV layout
for each task and featurizes with the in-repo WordPiece tokenizer
(data/tokenizers.py), loading a vendored ``vocab.txt`` (--vocab) or
building a vocabulary from the task's own training text (--build_vocab N,
saved to the output dir).

    python tools/prepare_glue.py --task=sst2 --input=train.tsv \
        --split=train --out_dir=/data/glue --build_vocab=8192
    python examples/bert_glue/train.py --task=sst2 --data_dir=/data/glue
"""

import csv
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np
from absl import app, flags

from tensorflow_examples_tpu.data.sources import GLUE_NUM_LABELS
from tensorflow_examples_tpu.data.tokenizers import WordPiece

flags.DEFINE_string("task", "sst2", f"one of {sorted(GLUE_NUM_LABELS)}")
flags.DEFINE_string("input", "", "input TSV file for the split")
flags.DEFINE_string("split", "train", "train | validation | test")
flags.DEFINE_string("out_dir", "", "output dir for <task>_<split>.npz")
flags.DEFINE_string("vocab", "", "path to a BERT vocab.txt")
flags.DEFINE_integer("build_vocab", 0, "build a vocab of this size instead")
flags.DEFINE_integer("seq_len", 128, "max sequence length")
FLAGS = flags.FLAGS


_ENTAIL = {"entailment": 0, "not_entailment": 1}
_MNLI = {"entailment": 0, "neutral": 1, "contradiction": 2}

# Per-task TSV schema: (text_a col, text_b col, label col, label map).
# Column names follow the official GLUE distribution headers; CoLA has no
# header (source/label/star/sentence columns).
_TASKS = {
    "cola": (3, None, 1, int),  # positional: no header row
    "sst2": ("sentence", None, "label", int),
    "mrpc": ("#1 String", "#2 String", "Quality", int),
    "stsb": ("sentence1", "sentence2", "score", float),
    "qqp": ("question1", "question2", "is_duplicate", int),
    "mnli": ("sentence1", "sentence2", "gold_label", _MNLI),
    "qnli": ("question", "sentence", "label", _ENTAIL),
    "rte": ("sentence1", "sentence2", "label", _ENTAIL),
    "wnli": ("sentence1", "sentence2", "label", int),
}


def read_tsv(path: str, task: str):
    """Yield (text_a, text_b|None, raw_label) rows for the task."""
    a_col, b_col, y_col, conv = _TASKS[task]
    with open(path, encoding="utf-8") as f:
        reader = csv.reader(f, delimiter="\t", quoting=csv.QUOTE_NONE)
        rows = list(reader)
    if isinstance(a_col, int):  # headerless (cola)
        for r in rows:
            yield r[a_col], None, conv(r[y_col])
        return
    header = rows[0]
    idx = {name: i for i, name in enumerate(header)}
    for r in rows[1:]:
        if len(r) < len(header):
            continue
        a = r[idx[a_col]]
        b = r[idx[b_col]] if b_col else None
        raw = r[idx[y_col]]
        label = conv[raw] if isinstance(conv, dict) else conv(raw)
        yield a, b, label


def main(argv):
    del argv
    task = FLAGS.task
    if task not in _TASKS:
        raise app.UsageError(f"unknown --task={task}")
    if not FLAGS.input or not FLAGS.out_dir:
        raise app.UsageError("--input and --out_dir are required")
    if bool(FLAGS.vocab) == bool(FLAGS.build_vocab):
        raise app.UsageError("exactly one of --vocab / --build_vocab")

    rows = list(read_tsv(FLAGS.input, task))
    if FLAGS.vocab:
        wp = WordPiece.from_vocab_file(FLAGS.vocab)
    else:
        corpus = [a for a, _, _ in rows] + [b for _, b, _ in rows if b]
        wp = WordPiece.build(corpus, FLAGS.build_vocab)
        os.makedirs(FLAGS.out_dir, exist_ok=True)
        wp.save(os.path.join(FLAGS.out_dir, "vocab.txt"))
        print(f"built vocab: {wp.vocab_size} tokens -> {FLAGS.out_dir}/vocab.txt")

    feats = [wp.encode(a, b, seq_len=FLAGS.seq_len) for a, b, _ in rows]
    labels = np.asarray(
        [y for _, _, y in rows],
        np.float32 if task == "stsb" else np.int32,
    )
    out = {
        "tokens": np.stack([f["tokens"] for f in feats]),
        "attention_mask": np.stack([f["attention_mask"] for f in feats]),
        "token_type_ids": np.stack([f["token_type_ids"] for f in feats]),
        "label": labels,
    }
    os.makedirs(FLAGS.out_dir, exist_ok=True)
    path = os.path.join(FLAGS.out_dir, f"{task}_{FLAGS.split}.npz")
    np.savez(path, **out)
    print(
        f"{path}: {len(labels)} examples, seq_len={FLAGS.seq_len}, "
        f"vocab={wp.vocab_size}"
    )


if __name__ == "__main__":
    app.run(main)
