#!/usr/bin/env python
"""Host input-pipeline bench: the pipeline-only img/s figure, measured.

Drives the ISSUE-6 hot path end to end on synthetic ImageNet-sized
JPEGs written as real TFRecord shards — sharded parallel readers
(data/sources.ShardedReader) → Example parse → background decode/augment
worker pool (data/workers.py, native fastjpeg or the PIL/numpy fallback)
— and compares it against the sequential single-reader, zero-worker
reference the parallel stream is contractually bit-identical to.

Emits ONE BENCH-style JSON record (``metric``/``value``/``backend``/
``fingerprint_tflops``) so ``tools/bench_gate.py`` gates it against
``bench.FLOORS["cpu"]["host_input_pipeline_images_per_sec"]`` like any
other banked metric, plus the verification verdict:

* ``identical``: the parallel stream's batches matched the sequential
  reference byte-for-byte under the fixed seed (exit 1 when they don't —
  a determinism break is a failure, not a footnote);
* ``speedup``: parallel vs sequential images/sec;
* ``decoder``: which decode stage ran (``native`` = fastjpeg C++,
  ``fallback`` = PIL/numpy mirror; force the fallback with
  ``TFE_TPU_NATIVE_DECODE=0`` — the CI smoke exercises both).

Usage::

    python tools/host_input_bench.py --smoke --json   # tiny CI smoke
    python tools/host_input_bench.py                  # full-size bench
    python tools/host_input_bench.py --curve          # legacy native-vs-
                                                      # tf thread curve

Pure host tool — no jax, no TPU.
"""

import io
import json
import os
import shutil
import statistics
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_jpegs(n: int, seed: int = 0, *, lo: int = 350, hi: int = 550) -> list:
    """ImageNet-like sources: ~350-550 px, quality 85 (smoke: smaller)."""
    from PIL import Image

    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        h = int(rng.integers(lo, hi))
        w = int(rng.integers(lo, hi))
        yy = np.linspace(0, np.pi * 4, h)[:, None]
        xx = np.linspace(0, np.pi * 5, w)[None, :]
        img = np.stack(
            [
                127
                + 80 * np.sin(yy * (1 + 0.1 * k) + i) * np.cos(xx + k)
                + 20 * rng.standard_normal((h, w))
                for k in range(3)
            ],
            axis=-1,
        ).clip(0, 255).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(img).save(buf, format="JPEG", quality=85)
        out.append(buf.getvalue())
    return out


def write_shards(jpegs: list, root: str, *, n_shards: int, seed: int = 0):
    """Spread the jpegs over ``n_shards`` standard TFRecord shards."""
    from tensorflow_examples_tpu.data import sources as sources_mod

    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(seed)
    for s in range(n_shards):
        recs = [
            sources_mod.make_example(
                {
                    "image/encoded": jpegs[i],
                    "image/class/label": int(rng.integers(1, 1001)),
                }
            )
            for i in range(s, len(jpegs), n_shards)
        ]
        sources_mod.write_tfrecord(
            os.path.join(root, f"train-{s:05d}-of-{n_shards:05d}"), recs
        )


def _take(it, n: int) -> list:
    out = [next(it) for _ in range(n)]
    close = getattr(it, "close", None)
    if close is not None:
        close()
    return out


def bench_pipeline(
    root: str,
    *,
    batch: int,
    batches: int,
    image_size: int,
    readers: int,
    workers: int,
    reps: int,
    seed: int = 0,
) -> float:
    """Median steady-state images/sec of one pipeline config.

    One long-lived iterator (the train stream is infinite): pool/reader
    spin-up and the first decode land in the warmup, then ``reps``
    windows of ``batches`` are timed back to back — the number a
    steady training loop would see. The sequential reference
    (readers=1, workers=0) pins the native stage to ONE thread: a
    single-reader path that secretly multithreads its decode would
    understate the pipeline's win on many-core hosts."""
    from tensorflow_examples_tpu.data import imagenet as imagenet_data

    it = imagenet_data.parallel_tfrecord_iter(
        root, "train", batch, train=True, image_size=image_size,
        seed=seed, num_readers=readers, num_workers=workers,
        host_index=0, host_count=1,
        decode_threads=1 if workers == 0 else None,
        shuffle_window=2 * batch,  # < the tiny bench epoch: measure the
        #   streaming regime real (epoch >> window) runs are in
    )
    try:
        for _ in range(2):  # warm: spin-up + first decode
            next(it)
        vals = []
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(batches):
                next(it)
            vals.append(batches * batch / (time.perf_counter() - t0))
    finally:
        close = getattr(it, "close", None)
        if close is not None:
            close()
    return statistics.median(vals)


def verify_identical(
    root: str, *, batch, batches, image_size, readers, workers, seed=0
) -> bool:
    """Parallel stream == sequential single-reader reference, bytewise."""
    from tensorflow_examples_tpu.data import imagenet as imagenet_data

    def take(r, w):
        return _take(
            imagenet_data.parallel_tfrecord_iter(
                root, "train", batch, train=True, image_size=image_size,
                seed=seed, num_readers=r, num_workers=w,
                host_index=0, host_count=1,
                shuffle_window=2 * batch,
            ),
            batches,
        )

    ref = take(1, 0)
    par = take(readers, workers)
    return all(
        np.array_equal(a["image"], b["image"])
        and np.array_equal(a["label"], b["label"])
        for a, b in zip(ref, par)
    )


def cpu_probe_tflops() -> float:
    """f32 GEMM probe: the record's rig fingerprint, comparable against
    the floor stamped by the same probe (floors policy). Median of
    several windows after a real warmup — a single cold window swings
    several-fold on a shared host, which would randomly break the 2x
    comparability gate."""
    n = 512
    a = np.random.default_rng(0).standard_normal((n, n), dtype=np.float32)
    for _ in range(3):
        a @ a  # warm (BLAS thread pool spin-up, cache)
    vals = []
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(4):
            a @ a
        vals.append(4 * 2 * n**3 / (time.perf_counter() - t0) / 1e12)
    return statistics.median(vals)


# ------------------------------------------------- legacy thread curve


def bench_native(jpegs, threads: int, reps: int) -> float:
    from tensorflow_examples_tpu import native
    from tensorflow_examples_tpu.data.imagenet import MEAN_RGB, STDDEV_RGB

    seeds = np.arange(len(jpegs), dtype=np.uint64)
    args = dict(
        train=True, out_size=224, seeds=seeds,
        mean=MEAN_RGB, std=STDDEV_RGB, threads=threads,
    )
    native.decode_augment_batch(jpegs, **args)  # warm
    vals = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out, ok = native.decode_augment_batch(jpegs, **args)
        vals.append(len(jpegs) / (time.perf_counter() - t0))
        assert ok.all()
    return statistics.median(vals)


def bench_tf(jpegs, reps: int) -> float:
    """The tf.image decode+crop+resize+flip path this stage replaces
    (per-image graph calls, AUTOTUNE threading left to tf)."""
    import tensorflow as tf

    tf.config.set_visible_devices([], "GPU")

    def one(b):
        shape = tf.io.extract_jpeg_shape(b)
        begin, size, _ = tf.image.sample_distorted_bounding_box(
            shape,
            bounding_boxes=tf.zeros([1, 0, 4], tf.float32),
            area_range=(0.08, 1.0),
            aspect_ratio_range=(3 / 4, 4 / 3),
            max_attempts=10,
            use_image_if_no_bounding_boxes=True,
        )
        y, x, _ = tf.unstack(begin)
        h, w, _ = tf.unstack(size)
        img = tf.image.decode_and_crop_jpeg(
            b, tf.stack([y, x, h, w]), channels=3
        )
        img = tf.image.resize(img, [224, 224])
        return tf.image.random_flip_left_right(img)

    ds = (
        tf.data.Dataset.from_tensor_slices(tf.constant(jpegs))
        .map(one, num_parallel_calls=tf.data.AUTOTUNE)
        .batch(len(jpegs))
    )
    next(iter(ds))  # warm
    vals = []
    for _ in range(reps):
        t0 = time.perf_counter()
        next(iter(ds))
        vals.append(len(jpegs) / (time.perf_counter() - t0))
    return statistics.median(vals)


def run_curve(budget: float, n: int) -> dict:
    out = {
        "diag": "host_input_bench_curve",
        "n_images": n,
        "host_cpus": os.cpu_count(),
        "complete": False,
    }
    deadline = time.monotonic() + budget
    jpegs = make_jpegs(n)
    out["avg_jpeg_kb"] = round(
        sum(len(j) for j in jpegs) / len(jpegs) / 1024, 1
    )
    curve = {}
    for t in (1, 2, 4, 8, 16):
        if time.monotonic() > deadline:
            out["truncated"] = True
            break
        if t > (os.cpu_count() or 1) * 2:
            break
        curve[str(t)] = round(bench_native(jpegs, t, reps=3), 1)
    out["native_images_per_sec_by_threads"] = curve
    if time.monotonic() < deadline:
        out["tf_data_images_per_sec"] = round(bench_tf(jpegs, 3), 1)
    out["complete"] = bool(curve)
    return out


# --------------------------------------------------------------- main


def main(argv=None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    smoke = "--smoke" in argv
    curve = "--curve" in argv
    budget = 600.0
    n = 96 if smoke else 192
    workers = 4
    readers = 2
    image_size = 128 if smoke else 224
    for a in argv:
        if a.startswith("--budget="):
            budget = float(a.split("=", 1)[1])
        if a.startswith("--n="):
            n = int(a.split("=", 1)[1])
        if a.startswith("--workers="):
            workers = int(a.split("=", 1)[1])
        if a.startswith("--readers="):
            readers = int(a.split("=", 1)[1])
        if a.startswith("--image-size="):
            image_size = int(a.split("=", 1)[1])

    rc = 0
    if curve:
        out = {}
        try:
            out = run_curve(budget, n)
        except Exception as e:  # noqa: BLE001
            out["error"] = f"{type(e).__name__}: {e}"
            rc = 1
        print(json.dumps(out), flush=True)
        return rc

    from tensorflow_examples_tpu.data.imagenet import _native_decode_enabled

    batch = 8 if smoke else 32
    out = {
        "metric": "host_input_pipeline_images_per_sec",
        "value": None,
        "unit": "images/sec",
        "backend": "cpu",
        "smoke": smoke,
        "n_images": n,
        "batch": batch,
        "image_size": image_size,
        "workers": workers,
        "readers": readers,
        "host_cpus": os.cpu_count(),
        "complete": False,
    }
    root = tempfile.mkdtemp(prefix="host_input_bench_")
    # Point the record-count cache into the bench tempdir, restoring the
    # caller's value afterwards — in-process callers (the CI smoke test)
    # must not inherit a cache path that the finally below deletes.
    prev_cache = os.environ.get("TFE_TPU_CACHE_DIR")
    if prev_cache is None:
        os.environ["TFE_TPU_CACHE_DIR"] = os.path.join(root, "cache")
    try:
        jpegs = make_jpegs(
            n, lo=280 if smoke else 350, hi=400 if smoke else 550
        )
        write_shards(jpegs, root, n_shards=max(8, readers * 2))
        batches = max(n // batch, 1)
        out["decoder"] = (
            "native" if _native_decode_enabled() else "fallback"
        )
        out["identical"] = verify_identical(
            root, batch=batch, batches=batches, image_size=image_size,
            readers=readers, workers=workers,
        )
        reps = 3
        seq = bench_pipeline(
            root, batch=batch, batches=batches, image_size=image_size,
            readers=1, workers=0, reps=reps,
        )
        par = bench_pipeline(
            root, batch=batch, batches=batches, image_size=image_size,
            readers=readers, workers=workers, reps=reps,
        )
        out["value"] = round(par, 1)
        out["sequential_images_per_sec"] = round(seq, 1)
        out["speedup"] = round(par / seq, 2) if seq else None
        cpus = os.cpu_count() or 1
        if workers > cpus:
            # The decode is compute-bound C: speedup is core-limited,
            # not worker-limited. Say so rather than letting a 2-core
            # CI box read as a pipeline defect.
            out["speedup_ceiling_cores"] = cpus
        out["fingerprint_tflops"] = round(cpu_probe_tflops(), 4)
        out["extras"] = [
            {
                "metric": "host_input_seq_images_per_sec",
                "value": round(seq, 1),
                "unit": "images/sec",
            }
        ]
        out["complete"] = True
        if not out["identical"]:
            rc = 1  # determinism break is a failure, not a footnote
    except Exception as e:  # noqa: BLE001
        out["error"] = f"{type(e).__name__}: {e}"
        rc = 1
    finally:
        if prev_cache is None:
            os.environ.pop("TFE_TPU_CACHE_DIR", None)
        shutil.rmtree(root, ignore_errors=True)
    print(json.dumps(out), flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
