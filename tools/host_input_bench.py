#!/usr/bin/env python
"""Host input-path microbench: images/s vs threads (VERDICT r4 weak #2).

The resnet50_input TPU bench is host-bound on this rig's single CPU
core, so on-rig gains can't show the decode stage's real headroom.
This tool measures the C++ stage (native/fastjpeg.cpp: DCT-scaled JPEG
decode + crop + resize + flip + normalize) on synthetic ImageNet-sized
JPEGs across thread counts, plus the tf.data decode path it replaces,
so the 1-core number extrapolates to real TPU-VM hosts (a v5e-8 host
has 112 vCPUs): images/s scales ~linearly until memory bandwidth.

Pure host tool — no jax, no TPU. Emits ONE JSON line.

Usage: python tools/host_input_bench.py [--budget=SECS] [--n=IMAGES]
"""

import io
import json
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_jpegs(n: int, seed: int = 0) -> list:
    """ImageNet-like sources: ~350-550 px, quality 85."""
    from PIL import Image

    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        h = int(rng.integers(350, 550))
        w = int(rng.integers(350, 550))
        yy = np.linspace(0, np.pi * 4, h)[:, None]
        xx = np.linspace(0, np.pi * 5, w)[None, :]
        img = np.stack(
            [
                127
                + 80 * np.sin(yy * (1 + 0.1 * k) + i) * np.cos(xx + k)
                + 20 * rng.standard_normal((h, w))
                for k in range(3)
            ],
            axis=-1,
        ).clip(0, 255).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(img).save(buf, format="JPEG", quality=85)
        out.append(buf.getvalue())
    return out


def bench_native(jpegs, threads: int, reps: int) -> float:
    from tensorflow_examples_tpu import native
    from tensorflow_examples_tpu.data.imagenet import MEAN_RGB, STDDEV_RGB

    seeds = np.arange(len(jpegs), dtype=np.uint64)
    args = dict(
        train=True, out_size=224, seeds=seeds,
        mean=MEAN_RGB, std=STDDEV_RGB, threads=threads,
    )
    native.decode_augment_batch(jpegs, **args)  # warm
    vals = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out, ok = native.decode_augment_batch(jpegs, **args)
        vals.append(len(jpegs) / (time.perf_counter() - t0))
        assert ok.all()
    return statistics.median(vals)


def bench_tf(jpegs, reps: int) -> float:
    """The tf.image decode+crop+resize+flip path this stage replaces
    (per-image graph calls, AUTOTUNE threading left to tf)."""
    import tensorflow as tf

    tf.config.set_visible_devices([], "GPU")

    def one(b):
        shape = tf.io.extract_jpeg_shape(b)
        begin, size, _ = tf.image.sample_distorted_bounding_box(
            shape,
            bounding_boxes=tf.zeros([1, 0, 4], tf.float32),
            area_range=(0.08, 1.0),
            aspect_ratio_range=(3 / 4, 4 / 3),
            max_attempts=10,
            use_image_if_no_bounding_boxes=True,
        )
        y, x, _ = tf.unstack(begin)
        h, w, _ = tf.unstack(size)
        img = tf.image.decode_and_crop_jpeg(
            b, tf.stack([y, x, h, w]), channels=3
        )
        img = tf.image.resize(img, [224, 224])
        return tf.image.random_flip_left_right(img)

    ds = (
        tf.data.Dataset.from_tensor_slices(tf.constant(jpegs))
        .map(one, num_parallel_calls=tf.data.AUTOTUNE)
        .batch(len(jpegs))
    )
    next(iter(ds))  # warm
    vals = []
    for _ in range(reps):
        t0 = time.perf_counter()
        next(iter(ds))
        vals.append(len(jpegs) / (time.perf_counter() - t0))
    return statistics.median(vals)


def main() -> int:
    budget = 600.0
    n = 64
    for a in sys.argv[1:]:
        if a.startswith("--budget="):
            budget = float(a.split("=", 1)[1])
        if a.startswith("--n="):
            n = int(a.split("=", 1)[1])
    deadline = time.monotonic() + budget
    out = {
        "diag": "host_input_bench",
        "n_images": n,
        "host_cpus": os.cpu_count(),
        "complete": False,
    }
    try:
        jpegs = make_jpegs(n)
        out["avg_jpeg_kb"] = round(
            sum(len(j) for j in jpegs) / len(jpegs) / 1024, 1
        )
        curve = {}
        for t in (1, 2, 4, 8, 16):
            if time.monotonic() > deadline:
                out["truncated"] = True
                break
            if t > (os.cpu_count() or 1) * 2:
                break
            curve[str(t)] = round(bench_native(jpegs, t, reps=3), 1)
        out["native_images_per_sec_by_threads"] = curve
        if time.monotonic() < deadline:
            out["tf_data_images_per_sec"] = round(bench_tf(jpegs, 3), 1)
        out["complete"] = bool(curve)
    except Exception as e:  # noqa: BLE001
        out["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
