#!/usr/bin/env python
"""Tokenize a text corpus into the flat-token ``.bin`` format for GPT-2.

The LM loader (data/sources.py:load_lm_tokens) consumes ``train.bin`` /
``val.bin`` uint16 token streams — the common GPT-2 prep format. This
tool produces them offline with the in-repo byte-level BPE
(data/tokenizers.py): either load a vendored ``vocab.json``/``merges.txt``
(--vocab_dir) or train a fresh vocabulary from the input corpus itself
(--train_vocab N, saved next to the output for generate.py to decode
with).

    python tools/prepare_lm.py --input=corpus.txt --out_dir=/data/lm \
        --train_vocab=8192 --val_fraction=0.01

Then: python examples/gpt2/train.py --data_dir=/data/lm --vocab_size=8192
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np
from absl import app, flags

from tensorflow_examples_tpu.data.tokenizers import ByteLevelBPE

flags.DEFINE_list("input", [], "input .txt file(s), one document per file")
flags.DEFINE_string("out_dir", "", "output directory for train.bin/val.bin")
flags.DEFINE_string("vocab_dir", "", "load vocab.json+merges.txt from here")
flags.DEFINE_integer("train_vocab", 0, "train a BPE vocab of this size instead")
flags.DEFINE_float("val_fraction", 0.01, "fraction of tokens for val.bin")
FLAGS = flags.FLAGS


def main(argv):
    del argv
    if not FLAGS.input or not FLAGS.out_dir:
        raise app.UsageError("--input and --out_dir are required")
    if bool(FLAGS.vocab_dir) == bool(FLAGS.train_vocab):
        raise app.UsageError("exactly one of --vocab_dir / --train_vocab")

    texts = []
    for path in FLAGS.input:
        with open(path, encoding="utf-8") as f:
            texts.append(f.read())

    if FLAGS.vocab_dir:
        tok = ByteLevelBPE.from_dir(FLAGS.vocab_dir)
    else:
        tok = ByteLevelBPE.train(texts, FLAGS.train_vocab)
        tok.save(FLAGS.out_dir)
        print(f"trained BPE vocab: {tok.vocab_size} tokens -> {FLAGS.out_dir}")
    if tok.vocab_size > np.iinfo(np.uint16).max + 1:
        raise ValueError(f"vocab {tok.vocab_size} exceeds uint16 .bin format")

    ids = []
    eot = tok.eot_id
    for text in texts:
        ids.extend(tok.encode(text))
        if eot is not None:
            ids.append(eot)
    flat = np.asarray(ids, np.uint16)

    os.makedirs(FLAGS.out_dir, exist_ok=True)
    n_val = int(len(flat) * FLAGS.val_fraction)
    splits = {"train": flat[: len(flat) - n_val], "val": flat[len(flat) - n_val:]}
    for split, arr in splits.items():
        out = os.path.join(FLAGS.out_dir, f"{split}.bin")
        arr.tofile(out)
        print(f"{out}: {len(arr)} tokens (vocab {tok.vocab_size})")


if __name__ == "__main__":
    app.run(main)
