#!/usr/bin/env python
"""Minimize the ``test_flash_lse_compiled_parity`` tunnel wedge.

Round-4 harvest: the lse node's first on-chip compile hung the axon
tunnel >460 s and cost the window (BASELINE.md round-4 harvest note).
VERDICT r5 asks for a root cause, not a retry: the failing test differs
from the tests that PASSED on-chip in two ways at once — it returns the
lse output AND runs at a different shape (1,8,2048,64 vs 2,12,1024,64)
— so "the lse variant is pathological" is only one of three
hypotheses. This tool separates them with one bounded subprocess per
case, safest first, the exact wedge repro LAST (wedging it ends the
window, but by then the discriminating cases are banked):

  ref_2048      the test's XLA reference einsum+logsumexp alone
  plain_2048    flash_attention (no lse output) at the lse test shape
  lse_1024      flash_attention_with_lse at the shape the fwd tests
                passed with
  lse_2048_b128 the repro with 128x128 blocks (Mosaic tiling axis)
  lse_2048      the exact repro (block 256 default)

Parent stays jax-free (it must outlive any wedge) and persists
per-case state in ``--state`` (default /tmp/lse_bisect_state.json)
across windows: ok/fail are terminal; a timeout is probed — tunnel
still alive means the case hung only itself; tunnel dead means wedge —
and a case that wedges twice is classified terminal "wedge". Emits ONE
JSON line; ``complete`` when every case is terminal. Run from
tools/tpu_harvest.sh's one-shot queue.

Child mode (``--case=NAME``) imports jax, compiles + runs the case
once, prints a JSON line with timing and parity error.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)  # child mode imports the package from source

CASES = ["ref_2048", "plain_2048", "lse_1024", "lse_2048_b128", "lse_2048"]
# hang = hung its own process twice with the tunnel still alive;
# wedge = took the tunnel down twice. Both are terminal diagnoses.
TERMINAL = {"ok", "fail", "wedge", "hang"}
CASE_BUDGET = 150.0  # compile ~20-40 s healthy; >150 s is a hang


# ------------------------------------------------------------ child side


def _run_case(name: str) -> dict:
    import jax
    import jax.numpy as jnp

    from tensorflow_examples_tpu.ops.attention import (
        attention_reference,
        flash_attention,
        flash_attention_with_lse,
    )

    if name != "ref_2048" and jax.default_backend() != "tpu":
        # The pallas cases exist to poke Mosaic's compiled path; off-TPU
        # there is nothing to diagnose (rehearsals must not burn the
        # parent's retry budget).
        return {"case": name, "skipped": "non-tpu backend"}

    def qkv(b, h, s, d, seed=3):
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        return tuple(
            jax.random.normal(k, (b, h, s, d), jnp.bfloat16) for k in ks
        )

    def ref_lse(q, k, v):
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
        ) * (q.shape[-1] ** -0.5)
        row = jax.lax.broadcasted_iota(jnp.int32, s.shape[-2:], 0)
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape[-2:], 1)
        s = jnp.where(row >= col, s, -1e30)
        return jax.nn.logsumexp(s, axis=-1)

    err = None
    t0 = time.perf_counter()
    if name == "ref_2048":
        q, k, v = qkv(1, 8, 2048, 64)
        out = jax.jit(ref_lse)(q, k, v)
        out.block_until_ready()
    elif name == "plain_2048":
        q, k, v = qkv(1, 8, 2048, 64)
        out = flash_attention(q, k, v, causal=True, interpret=False)
        out.block_until_ready()
        err = float(
            jnp.max(
                jnp.abs(
                    out.astype(jnp.float32)
                    - attention_reference(q, k, v, causal=True).astype(
                        jnp.float32
                    )
                )
            )
        )
    else:
        shapes = {"lse_1024": (2, 12, 1024, 64)}
        b, h, s, d = shapes.get(name, (1, 8, 2048, 64))
        blocks = {"lse_2048_b128": 128}
        blk = blocks.get(name)
        q, k, v = qkv(b, h, s, d)
        out, lse = flash_attention_with_lse(
            q, k, v, causal=True, interpret=False,
            block_q=blk, block_kv=blk,
        )
        lse.block_until_ready()
        err = float(jnp.max(jnp.abs(lse - ref_lse(q, k, v))))
    dt = time.perf_counter() - t0
    rec = {"case": name, "seconds": round(dt, 2)}
    if err is not None:
        rec["max_abs_err"] = round(err, 5)
        rec["parity"] = err < 2e-2
    return rec


# ----------------------------------------------------------- parent side


def _probe_tpu(timeout: float = 90.0) -> bool:
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax; print('LIVE', jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout, cwd=REPO,
        )
        return "LIVE tpu" in (p.stdout or "")
    except Exception:
        return False


def _child(case: str, timeout: float) -> "dict | None":
    """Run one case subprocess; None on timeout (possible wedge)."""
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), f"--case={case}"],
            capture_output=True, text=True, timeout=timeout, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return None
    for line in reversed((p.stdout or "").splitlines()):
        try:
            return json.loads(line)
        except Exception:
            continue
    return {"case": case, "error": (p.stderr or "no output")[-400:],
            "rc": p.returncode}


def main() -> int:
    argv = sys.argv[1:]
    for a in argv:
        if a.startswith("--case="):
            rec = _run_case(a.split("=", 1)[1])
            print(json.dumps(rec), flush=True)
            return 0

    budget = 780.0
    state_path = "/tmp/lse_bisect_state.json"
    for a in argv:
        if a.startswith("--budget="):
            budget = float(a.split("=", 1)[1])
        if a.startswith("--state="):
            state_path = a.split("=", 1)[1]
    deadline = time.monotonic() + budget

    state: dict = {}
    try:
        with open(state_path) as f:
            state = json.load(f)
    except Exception:
        pass

    out = {"diag": "lse_bisect", "cases": state, "complete": False}
    for case in CASES:
        st = state.get(case) or {}
        if st.get("status") in TERMINAL:
            continue
        if time.monotonic() + CASE_BUDGET + 60 > deadline:
            break
        rec = _child(case, CASE_BUDGET)
        if rec is None:
            alive = _probe_tpu()
            attempts = int(st.get("wedge_attempts", 0)) + 1
            if attempts >= 2:
                status = "hang" if alive else "wedge"
            else:
                status = "hung_once" if alive else "wedged_once"
            state[case] = {"status": status, "wedge_attempts": attempts,
                           "tunnel_alive_after": alive}
            if not alive:
                break  # window over either way
        elif "error" in rec:
            # Child crashed cleanly (not a hang): keep the error, retry
            # next window unless it has now failed twice.
            attempts = int(st.get("err_attempts", 0)) + 1
            state[case] = {
                "status": "fail" if attempts >= 2 else "error",
                "err_attempts": attempts, "detail": rec.get("error"),
            }
        elif "skipped" in rec:
            state[case] = {"status": "skipped", **rec}  # non-terminal
        else:
            ok = rec.get("parity", True)
            state[case] = {"status": "ok" if ok else "fail", **rec}
    out["cases"] = state
    out["complete"] = all(
        (state.get(c) or {}).get("status") in TERMINAL for c in CASES
    )
    if out["complete"]:
        wedged = [c for c in CASES if state[c]["status"] == "wedge"]
        okset = [c for c in CASES if state[c]["status"] == "ok"]
        out["conclusion"] = (
            f"wedging: {wedged or 'none'}; passing: {okset}"
        )
    try:
        with open(state_path, "w") as f:
            json.dump(state, f)
    except Exception:
        pass
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
