#!/usr/bin/env python3
"""Render tail-sampled trace trees and attribute tail latency (ISSUE 18).

The serving tier's tail sampler (``telemetry/tracing.py``) lands the
interesting traces — slow, errored, retried, failed-over, preempted,
deduped, resumed, plus a seeded slice of normal traffic — as
schema-v13 ``kind="trace"`` JSONL lines. This tool answers the two
questions an operator actually asks of them:

* ``--trace-id ID`` — ONE request's story: the span tree rendered with
  per-span wall and tags, plus its critical path (the chain of spans
  that actually bounds the request's end time — time spent anywhere
  else was hidden behind it).

* default — WHERE the tail lives: pick the traces at or above the
  ``--percentile`` e2e (within ``--slo``, default all classes), run
  each one's critical path, and aggregate SELF time per span name.
  The top row is the leg your p99 is made of — queue wait vs prefill
  vs decode vs a failover's burned dispatch — measured, not guessed.

Reads any number of trace JSONL files (multiple routers' sinks merge
by trace_id — a takeover-survived request stitches here exactly like
it does in the recorder). Tolerant of torn tails by construction
(``tracing.read_traces``). Stdlib + repo only; no device, no network.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tensorflow_examples_tpu.telemetry import tracing  # noqa: E402


# ----------------------------------------------------------- loading


def load_traces(paths: list[str]) -> dict:
    """{trace_id: merged doc} across every given sink file — the same
    merge discipline as a takeover stitch (span union by span_id, e2e
    max, non-200 status sticks)."""
    merged: dict = {}
    for path in paths:
        for tid, doc in tracing.read_traces(path).items():
            prior = merged.get(tid)
            if prior is None:
                merged[tid] = doc
                continue
            seen = {s["span_id"] for s in prior["spans"]}
            prior["spans"].extend(
                s for s in doc["spans"] if s["span_id"] not in seen
            )
            prior["spans"].sort(key=lambda s: s["start_unix"])
            prior["e2e_s"] = max(prior["e2e_s"], doc["e2e_s"])
            if doc["status"] != 200:
                prior["status"] = doc["status"]
    return merged


# ------------------------------------------------------- span algebra


def build_tree(doc: dict) -> tuple[list, dict]:
    """(roots, children-by-span_id), children start-ordered. A span
    whose parent never landed (dropped by the per-trace cap, or a leg
    the wire lost) renders as its own root rather than vanishing."""
    spans = doc.get("spans", [])
    by_id = {s["span_id"]: s for s in spans}
    children: dict = {}
    roots: list = []
    for s in spans:
        p = s.get("parent_id")
        if p and p in by_id and p != s["span_id"]:
            children.setdefault(p, []).append(s)
        else:
            roots.append(s)
    for kids in children.values():
        kids.sort(key=lambda s: s["start_unix"])
    roots.sort(key=lambda s: s["start_unix"])
    return roots, children


def critical_path(doc: dict) -> list:
    """The chain of spans bounding the request's end time: from the
    longest root, repeatedly descend into the child whose END is
    latest — everything off that chain overlapped it and could not
    have delayed the reply. Each step carries ``self_s``: the span's
    wall MINUS its on-path child's, i.e. the time this leg itself
    added (the attribution unit)."""
    roots, children = build_tree(doc)
    if not roots:
        return []
    root = max(roots, key=lambda s: float(s.get("dur_s", 0.0)))
    path = [root]
    cur = root
    while True:
        kids = children.get(cur["span_id"])
        if not kids:
            break
        cur = max(
            kids,
            key=lambda s: float(s["start_unix"]) + float(s["dur_s"]),
        )
        path.append(cur)
    out = []
    for i, s in enumerate(path):
        child_dur = (
            float(path[i + 1]["dur_s"]) if i + 1 < len(path) else 0.0
        )
        out.append({
            "name": s["name"],
            "dur_s": float(s["dur_s"]),
            "self_s": max(0.0, float(s["dur_s"]) - child_dur),
            "tags": s.get("tags", {}),
        })
    return out


def attribution(docs: list, percentile: float) -> dict:
    """Aggregate critical-path SELF time per span name over the traces
    at/above the e2e percentile. Returns the ranked rows plus the
    threshold and population, so the report says which tail it
    measured, not just what it found."""
    if not docs:
        return {"threshold_s": None, "tail": 0, "total": 0, "rows": []}
    e2es = sorted(float(d.get("e2e_s", 0.0)) for d in docs)
    idx = min(
        len(e2es) - 1,
        max(0, int(round((percentile / 100.0) * (len(e2es) - 1)))),
    )
    threshold = e2es[idx]
    tail = [d for d in docs if float(d.get("e2e_s", 0.0)) >= threshold]
    agg: dict = {}
    for doc in tail:
        for step in critical_path(doc):
            row = agg.setdefault(
                step["name"], {"name": step["name"], "self_s": 0.0,
                               "count": 0}
            )
            row["self_s"] += step["self_s"]
            row["count"] += 1
    rows = sorted(agg.values(), key=lambda r: -r["self_s"])
    return {
        "threshold_s": threshold,
        "tail": len(tail),
        "total": len(docs),
        "rows": rows,
    }


# ----------------------------------------------------------- rendering


def _fmt_tags(tags: dict) -> str:
    if not tags:
        return ""
    inner = " ".join(f"{k}={tags[k]}" for k in sorted(tags))
    return f"  [{inner}]"


def render_tree(doc: dict) -> str:
    """The span tree as indented text, one span per line:
    name, wall, start offset from the trace's first span, tags."""
    roots, children = build_tree(doc)
    t0 = min(
        (float(s["start_unix"]) for s in doc.get("spans", [])),
        default=0.0,
    )
    lines = [
        f"trace {doc['trace_id']}  slo={doc.get('slo')}  "
        f"status={doc.get('status')}  e2e={doc.get('e2e_s', 0.0):.4f}s  "
        f"keep={doc.get('keep_reason')}  "
        f"flags={','.join(doc.get('flags', [])) or '-'}"
    ]

    def walk(span, depth):
        off = float(span["start_unix"]) - t0
        lines.append(
            f"{'  ' * depth}- {span['name']}  "
            f"{float(span['dur_s']):.4f}s  (+{off:.4f}s)"
            f"{_fmt_tags(span.get('tags', {}))}"
        )
        for kid in children.get(span["span_id"], ()):
            walk(kid, depth + 1)

    for root in roots:
        walk(root, 1)
    path = critical_path(doc)
    if path:
        lines.append("critical path:")
        for step in path:
            lines.append(
                f"  {step['name']}  self={step['self_s']:.4f}s  "
                f"(span {step['dur_s']:.4f}s)"
            )
    return "\n".join(lines)


def render_attribution(report: dict, percentile: float) -> str:
    if not report["total"]:
        return "no traces loaded"
    head = (
        f"p{percentile:g} attribution: {report['tail']} tail trace(s) "
        f"of {report['total']} at e2e >= {report['threshold_s']:.4f}s"
    )
    lines = [head]
    total_self = sum(r["self_s"] for r in report["rows"]) or 1.0
    for r in report["rows"]:
        lines.append(
            f"  {r['name']:<24} self={r['self_s']:.4f}s  "
            f"({100.0 * r['self_s'] / total_self:5.1f}%)  "
            f"spans={r['count']}"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------- main


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument(
        "paths", nargs="+",
        help="trace JSONL sink file(s); multiple files merge by "
        "trace_id",
    )
    ap.add_argument(
        "--trace-id", default="",
        help="render ONE trace's span tree + critical path",
    )
    ap.add_argument(
        "--percentile", type=float, default=99.0,
        help="e2e percentile the attribution report targets "
        "(default 99)",
    )
    ap.add_argument(
        "--slo", default="",
        help="restrict the attribution to one SLO class",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit the report as JSON instead of text",
    )
    args = ap.parse_args(argv)

    traces = load_traces(args.paths)
    if args.trace_id:
        doc = traces.get(args.trace_id)
        if doc is None:
            print(
                f"trace_report: unknown trace {args.trace_id!r} "
                f"({len(traces)} trace(s) loaded)",
                file=sys.stderr,
            )
            return 2
        if args.json:
            doc = dict(doc)
            doc["critical_path"] = critical_path(doc)
            print(json.dumps(doc, sort_keys=True))
        else:
            print(render_tree(doc))
        return 0

    docs = [
        d for d in traces.values()
        if not args.slo or d.get("slo") == args.slo
    ]
    report = attribution(docs, args.percentile)
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        print(render_attribution(report, args.percentile))
    return 0


if __name__ == "__main__":
    sys.exit(main())
