#!/bin/bash
# Poll the axon TPU tunnel until it answers; exit 0 on first live probe.
# Each probe is a subprocess with a hard timeout (axon init can hang
# indefinitely — see docs/DESIGN.md rig notes). Writes /tmp/tpu_live on
# success so concurrent tooling can check cheaply.
rm -f /tmp/tpu_live
while true; do
  out=$(timeout 120 nice -n 19 python - <<'EOF' 2>&1
import jax
ds = jax.devices()
print("LIVE", ds[0].platform, len(ds))
EOF
)
  if echo "$out" | grep -q "^LIVE tpu"; then
    echo "$out" > /tmp/tpu_live
    echo "TPU TUNNEL LIVE: $out"
    exit 0
  fi
  echo "$(date -u +%H:%M:%S) probe: down"
  sleep 240
done
