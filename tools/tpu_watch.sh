#!/bin/bash
# Watch a run (or the TPU tunnel) from the outside.
#
# Three modes, picked by argument (ISSUE 4 satellite):
#
#   tpu_watch.sh --metrics HOST:PORT [--interval N]
#       Poll the live telemetry endpoints (TrainConfig.metrics_port,
#       telemetry/serve.py): each tick prints /health (watchdog phase,
#       stall age, 503 = stalled), the /window summary (step, loss,
#       step-time p50), and any /fleet straggler verdict. Waits
#       patiently while the endpoint has never answered (the run may
#       not have bound the port yet); once it HAS been up, a dead
#       endpoint means the run ended (the server closes on every exit
#       path, usually before the next poll can observe the final
#       window) — fall back to the workdir file tail when --workdir is
#       also given for the definitive verdict, else exit 0 when the
#       last health probe was healthy (normal end; the exact exit
#       reason lives in the run dir) or 2 when it was stalled (the run
#       likely died — watchdog fatal, crash).
#
#   tpu_watch.sh --workdir DIR [--interval N]
#       File-tail fallback for runs without a metrics port: print the
#       last line of DIR/telemetry/metrics.jsonl each tick, exit 0 on a
#       final line.
#
#   tpu_watch.sh
#       Legacy mode: poll the axon TPU tunnel until it answers; exit 0
#       on first live probe. Each probe is a subprocess with a hard
#       timeout (axon init can hang indefinitely — see docs/DESIGN.md
#       rig notes). Writes /tmp/tpu_live on success so concurrent
#       tooling can check cheaply.
#
# METRICS_ADDR=HOST:PORT in the environment implies --metrics.
set -u

interval=10
metrics_addr="${METRICS_ADDR:-}"
workdir=""

while [ $# -gt 0 ]; do
  case "$1" in
    --metrics) metrics_addr="$2"; shift 2 ;;
    --workdir) workdir="$2"; shift 2 ;;
    --interval) interval="$2"; shift 2 ;;
    *) echo "usage: tpu_watch.sh [--metrics HOST:PORT] [--workdir DIR] [--interval N]" >&2; exit 64 ;;
  esac
done

# One JSONL line on stdin -> a one-line human summary. Prints FINAL on
# its own line first when the run ended (the caller's exit signal).
SUMMARIZE_PY='
import json, sys
try:
    line = json.loads(sys.stdin.read())
except Exception:
    sys.exit(1)
if "kind" not in line:
    sys.exit(1)  # the 404 {"error": ...} body pre-first-window
kind = line.get("kind", "?")
if kind == "final":
    print("FINAL")
d = line.get("derived") or {}
m = line.get("metrics") or {}
parts = ["step %s" % line.get("step"), "kind=%s" % kind]
if kind == "final":
    parts.append("exit=%s" % line.get("exit_reason"))
loss = m.get("train/loss")
if loss is not None:
    parts.append("loss=%.4f" % loss)
p50 = d.get("step_time_p50")
if p50 is not None:
    parts.append("p50=%.1fms" % (p50 * 1e3))
eps = d.get("examples_per_sec")
if eps is not None:
    parts.append("%.0f ex/s" % eps)
fleet = line.get("fleet") or {}
if fleet.get("straggler"):
    parts.append("STRAGGLER host %s %.1fx %s-side" % (
        fleet.get("slowest_host"), fleet.get("skew") or 0.0,
        fleet.get("side")))
print(" ".join(parts))
'

summarize_window() {
  python -c "$SUMMARIZE_PY"
}

if [ -n "$metrics_addr" ]; then
  # ---- live-endpoint mode (metrics_port is set on the run) ----
  base="http://$metrics_addr"
  echo "watching $base (interval ${interval}s)"
  seen_up=0
  last_ok=1
  down_count=0
  while true; do
    # -s without -f: a 503 (stalled) still carries a JSON body we want.
    health=$(curl -s --max-time 5 "$base/health" 2>/dev/null)
    if [ -z "$health" ]; then
      down_count=$((down_count + 1))
      if [ "$seen_up" = 1 ] && [ "$down_count" -lt 2 ]; then
        # One empty probe can be a transient blip (busy host, curl
        # timeout) — only consecutive failures mean the port is gone.
        echo "$(date -u +%H:%M:%S) health probe failed (retrying)"
        sleep "$interval"; continue
      fi
      if [ "$seen_up" = 1 ]; then
        # The server closes on every exit path, usually milliseconds
        # after the final window — a now-dead endpoint IS the end
        # signal; don't poll a closed port forever. The final window
        # itself is almost never observable from here (emitted and the
        # port closed between two polls), so the verdict comes from
        # the file tail when we have one, else from the last health
        # probe: healthy-then-gone = normal end, stalled-then-gone =
        # the run likely died.
        echo "$(date -u +%H:%M:%S) endpoint gone: run ended"
        if [ -n "$workdir" ]; then break; fi  # file tail has the verdict
        echo "exit reason is in the run dir (tools/telemetry_report.py <rundir>)"
        if [ "$last_ok" = 1 ]; then exit 0; fi
        echo "last health probe was STALLED — the run likely died" >&2
        exit 2
      fi
      # Never came up but the run is already writing telemetry: the
      # bind likely failed (loop.py survives a taken port and trains
      # on) — the file tail is the only view we will ever get. A few
      # ticks of grace first: a resumed run has an old metrics.jsonl
      # on disk while the new process is still starting up.
      if [ -n "$workdir" ] && [ "$down_count" -ge 6 ] \
          && [ -f "$workdir/telemetry/metrics.jsonl" ]; then
        echo "$(date -u +%H:%M:%S) endpoint never came up but telemetry exists: falling back to the file tail"
        break
      fi
      echo "$(date -u +%H:%M:%S) endpoint not up yet (run not started?)"
      sleep "$interval"; continue
    fi
    seen_up=1
    down_count=0
    case "$health" in *'"ok": true'*) last_ok=1 ;; *) last_ok=0 ;; esac
    window=$(curl -s --max-time 5 "$base/window" 2>/dev/null)
    summary=$(printf '%s' "$window" | summarize_window)
    echo "$(date -u +%H:%M:%S) health: $health"
    [ -n "$summary" ] && echo "$(date -u +%H:%M:%S) window: $(printf '%s\n' "$summary" | tail -1)"
    fleet=$(curl -s --max-time 5 "$base/fleet" 2>/dev/null | summarize_window | grep -o 'STRAGGLER.*')
    [ -n "$fleet" ] && echo "$(date -u +%H:%M:%S) fleet:  $fleet"
    if printf '%s\n' "$summary" | grep -q '^FINAL$'; then
      echo "run ended"; exit 0
    fi
    sleep "$interval"
  done
fi

if [ -n "$workdir" ]; then
  # ---- file-tail fallback ----
  jsonl="$workdir/telemetry/metrics.jsonl"
  echo "tailing $jsonl (interval ${interval}s)"
  while true; do
    if [ -f "$jsonl" ]; then
      summary=$(tail -1 "$jsonl" | summarize_window)
      [ -n "$summary" ] && echo "$(date -u +%H:%M:%S) $(printf '%s\n' "$summary" | tail -1)"
      if printf '%s\n' "$summary" | grep -q '^FINAL$'; then
        echo "run ended"; exit 0
      fi
    else
      echo "$(date -u +%H:%M:%S) no telemetry yet"
    fi
    sleep "$interval"
  done
fi

# ---- legacy mode: poll the axon TPU tunnel until it answers ----
rm -f /tmp/tpu_live
while true; do
  out=$(timeout 120 nice -n 19 python - <<'EOF' 2>&1
import jax
ds = jax.devices()
print("LIVE", ds[0].platform, len(ds))
EOF
)
  if echo "$out" | grep -q "^LIVE tpu"; then
    echo "$out" > /tmp/tpu_live
    echo "TPU TUNNEL LIVE: $out"
    exit 0
  fi
  echo "$(date -u +%H:%M:%S) probe: down"
  sleep 240
done
