#!/bin/bash
# Follow-on to tools/tpu_harvest.sh: wait for the harvest loop to exit
# (it exits only after all benches + all selftest nodes are banked),
# then run the small-step diagnosis (tools/diag_smallstep.py) on the
# next live window and bank its record to docs/tpu_sweeps/. Exists so
# a live window arriving mid-session is never wasted waiting for a
# human turn: harvest → diag chains unattended.
set -u
cd "$(dirname "$0")/.."
OUT=${1:-/tmp/tpu_diag}
DEST=${2:-docs/tpu_sweeps/round4_diag.json}
mkdir -p "$OUT" "$(dirname "$DEST")"
. tools/lib_bounded.sh

echo "diag_watch: waiting for tpu_harvest to finish"
# Startup grace: a harvest launched in the same breath may not have a
# process entry yet — without this, the pgrep below sees nothing and
# diag runs CONCURRENTLY with the harvest, contending for the tunnel
# and interleaving pause/resume_suite with the harvest's.
sleep 90
# Anchored like lib_bounded.sh's pause_suite — an unanchored match
# would also hit any long-lived process whose cmdline merely MENTIONS
# the script (e.g. a session driver carrying these instructions) —
# but loose after the interpreter so `bash -x` variants still match.
while pgrep -f "^[^ ]*bash .*tools/tpu_harvest.sh" > /dev/null 2>&1; do
  sleep 60
done
echo "$(date -u +%H:%M:%S) harvest gone — watching for a live window"

trap 'resume_suite' EXIT

while true; do
  # Belt-and-braces: /tmp/tpu_live is touched by an actively-harvesting
  # window; never time the diag against a concurrent harvest even if
  # the pgrep wait was somehow skipped.
  if [ -f /tmp/tpu_live ]; then
    echo "$(date -u +%H:%M:%S) harvest window active; deferring"
    sleep 90
    continue
  fi
  if ! probe tpu; then
    echo "$(date -u +%H:%M:%S) tunnel down"
    sleep 90
    continue
  fi
  echo "$(date -u +%H:%M:%S) TUNNEL LIVE — running diag_smallstep"
  pause_suite
  run_bounded 700 "$OUT/diag.log" python tools/diag_smallstep.py --budget=600
  resume_suite
  # Bank the last parseable JSON line (always-emit children may print a
  # truncated snapshot before the full record) iff it is a TPU record
  # carrying at least the two batch points per workload the
  # overhead-vs-kernel classification needs — else retry next window.
  if python - "$OUT/diag.log" "$DEST" <<'EOF'
import json, sys
sys.path.insert(0, "tools")
from last_json_line import last_json_line
rec = last_json_line(sys.argv[1])
ok = (rec is not None and rec.get("backend") == "tpu"
      and "error" not in rec
      and len(rec.get("cifar10") or []) >= 2
      and len(rec.get("bert") or []) >= 2)
if ok:
    json.dump(rec, open(sys.argv[2], "w"))
sys.exit(0 if ok else 1)
EOF
  then
    echo "$(date -u +%H:%M:%S) diag banked: $DEST"
    exit 0
  fi
  echo "$(date -u +%H:%M:%S) diag incomplete (see $OUT/diag.log); retrying"
  sleep 90
done
