#!/bin/bash
# Follow-on to tools/tpu_harvest.sh: wait for the harvest loop to exit
# (it exits only after all benches + all selftest nodes are banked),
# then spend subsequent live windows on the queued one-shot
# measurements, each banked to docs/tpu_sweeps/ the moment it
# completes and never re-run:
#   1. tools/diag_smallstep.py — overhead-vs-kernel classification for
#      the bert/cifar10 sub-floor readings (BASELINE.md round-4);
#   2. tools/flash_tune.py — flash-attention block-size sweep so the
#      kernel default rests on a measured table, not one point.
# Exists so a live window arriving mid-session is never wasted waiting
# for a human turn: harvest → diag → tune chains unattended.
set -u
cd "$(dirname "$0")/.."
OUT=${1:-/tmp/tpu_diag}
DIAG_DEST=${2:-docs/tpu_sweeps/round4_diag.json}
TUNE_DEST=${3:-docs/tpu_sweeps/round4_flash_tune.json}
mkdir -p "$OUT" "$(dirname "$DIAG_DEST")" "$(dirname "$TUNE_DEST")"
. tools/lib_bounded.sh

echo "diag_watch: waiting for tpu_harvest to finish"
# Startup grace: a harvest launched in the same breath may not have a
# process entry yet — without this, the pgrep below sees nothing and
# the stages run CONCURRENTLY with the harvest, contending for the
# tunnel and interleaving pause/resume_suite with the harvest's.
sleep 90
# Anchored like lib_bounded.sh's pause_suite — an unanchored match
# would also hit any long-lived process whose cmdline merely MENTIONS
# the script (e.g. a session driver carrying these instructions) —
# but loose after the interpreter so `bash -x` variants still match.
while pgrep -f "^[^ ]*bash .*tools/tpu_harvest.sh" > /dev/null 2>&1; do
  sleep 60
done
echo "$(date -u +%H:%M:%S) harvest gone — watching for live windows"

trap 'resume_suite' EXIT

# bank_last_json LOG DEST GATE — fish the last parseable JSON line out
# of LOG (always-emit children may print a truncated snapshot before
# the full record) and write it to DEST iff GATE (a python expression
# over `rec`) holds. Returns 0 on bank.
bank_last_json() {
  python - "$1" "$2" "$3" <<'EOF'
import json, sys
sys.path.insert(0, "tools")
from last_json_line import last_json_line
rec = last_json_line(sys.argv[1])
ok = rec is not None and bool(eval(sys.argv[3], {"rec": rec, "len": len}))
if ok:
    json.dump(rec, open(sys.argv[2], "w"))
sys.exit(0 if ok else 1)
EOF
}

# Parenthesized: these are eval()'d as single expressions, and a bare
# newline between `and` clauses would be a SyntaxError.
DIAG_GATE='(rec.get("backend") == "tpu" and "error" not in rec
and len(rec.get("cifar10") or []) >= 2 and len(rec.get("bert") or []) >= 2)'
# flash_tune marks rec["complete"] only when every shape's full cell
# table timed inside the budget — banking anything less would freeze a
# partial table forever (the [ -s ] check never re-runs a stage).
TUNE_GATE='bool(rec.get("complete"))'

while true; do
  [ -s "$DIAG_DEST" ] && [ -s "$TUNE_DEST" ] && { echo "all banked"; exit 0; }
  # Belt-and-braces: /tmp/tpu_live is touched by an actively-harvesting
  # window; never time a stage against a concurrent harvest even if
  # the pgrep wait was somehow skipped. Checked BEFORE the driver-bench
  # defer so the defer's suite resume can't fire inside a live window.
  if [ -f /tmp/tpu_live ]; then
    echo "$(date -u +%H:%M:%S) harvest window active; deferring"
    sleep 90
    continue
  fi
  defer_for_driver_bench
  [ -f /tmp/tpu_live ] && continue
  if ! probe tpu; then
    echo "$(date -u +%H:%M:%S) tunnel down"
    sleep 90
    continue
  fi
  if [ ! -s "$DIAG_DEST" ]; then
    echo "$(date -u +%H:%M:%S) TUNNEL LIVE — diag_smallstep"
    pause_suite
    run_bounded 700 "$OUT/diag.log" python tools/diag_smallstep.py --budget=600
    resume_suite
    if bank_last_json "$OUT/diag.log" "$DIAG_DEST" "$DIAG_GATE"; then
      echo "$(date -u +%H:%M:%S) diag banked: $DIAG_DEST"
    else
      echo "$(date -u +%H:%M:%S) diag incomplete (see $OUT/diag.log); retrying"
      sleep 90
      continue
    fi
  fi
  if [ ! -s "$TUNE_DEST" ]; then
    defer_for_driver_bench
    [ -f /tmp/tpu_live ] && continue
    if ! probe tpu; then continue; fi
    echo "$(date -u +%H:%M:%S) TUNNEL LIVE — flash_tune"
    pause_suite
    run_bounded 700 "$OUT/tune.log" python tools/flash_tune.py --budget=600
    resume_suite
    if bank_last_json "$OUT/tune.log" "$TUNE_DEST" "$TUNE_GATE"; then
      echo "$(date -u +%H:%M:%S) tune banked: $TUNE_DEST"
    else
      echo "$(date -u +%H:%M:%S) tune incomplete (see $OUT/tune.log); retrying"
      sleep 90
    fi
  fi
done
