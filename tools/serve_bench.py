#!/usr/bin/env python
"""Closed-loop load generator for the serving stack; banks a BENCH record.

Stands the whole serving path up — engine (AOT warmup over the bucket
ladder), continuous batcher, HTTP frontend — then drives it closed-loop
(``--concurrency`` worker threads, each submitting its next request the
moment the previous one resolves: offered load = concurrency / mean
latency, the standard closed-loop operating point) and emits ONE
BENCH-style JSON record::

    {"bench": "serving", "backend": "cpu", "requests": 20,
     "concurrency": 8, "req_per_s": ..., "tok_per_s": ...,
     "ttft_p50_ms": ..., "ttft_p95_ms": ..., "tpot_p50_ms": ...,
     "tpot_p95_ms": ..., "e2e_p95_ms": ..., "queue_wait_p95_ms": ...,
     "expected_compiles": ..., "compiles": ...,
     "post_warmup_recompiles": 0, "shed": 0, "errors": 0,
     "verified": 3, "verify_ok": true, "ok": true}

``ok`` is the CI verdict: every request completed, the verified subset
is token-identical to the engine's unbatched reference replay, and NOT
ONE compile happened after warmup (``post_warmup_recompiles == 0`` —
the zero-recompile steady-state claim, measured, not asserted).

Modes:

* ``--smoke`` — tier-1 CI: a tiny random-param GPT-2 on whatever
  backend is present (CPU in CI), 20 mixed-length requests over HTTP,
  3 of them verified against the reference. Seconds, not minutes.
* ``--workdir DIR`` — load a real trained checkpoint (the
  ``examples/gpt2`` layout, trained at the DEFAULT model shape — the
  workdir banks no config, so a checkpoint from non-default
  ``--num_layers``/``--d_model``/... flags will fail the template
  restore; serve those via ``examples/gpt2/serve.py``, which takes the
  full flag surface) and measure serving throughput/latency at
  ``--concurrency`` on the local accelerator.
* ``--router`` (ISSUE 8) — stand up ``--replicas`` N full serving
  stacks IN THIS PROCESS (each its own engine + batcher + HTTP
  frontend on a loopback port), put ``serving/router.py`` in front,
  and drive the whole tier through the router. Replicas default to the
  paged KV pool (``--kv-block-size``, ``--kv-dtype``) and a quarter of
  the prompts share a common prefix so the prefix cache takes real
  hits; the record (``"bench": "serve_router"``) adds replica count,
  router retry counters, and ``prefix_hit_rate`` to the latency/
  throughput keys, and ``ok`` additionally requires zero post-warmup
  recompiles summed over EVERY replica. ``--smoke --router`` is the
  tier-1 fleet smoke.

* ``--chaos`` (ISSUE 10) — availability under injected faults: a
  SUPERVISED 3-replica (default) in-proc paged fleet behind the
  hardened router, a fault-free baseline phase, then a deterministic
  serve fault schedule (``--fault-spec``, ``utils/faults.py`` grammar;
  default crashes replica 1 mid-decode) under the same load, then
  wait for the supervisor to restore the fleet. Banks a
  ``serve_chaos`` record: ``error_rate`` (0 on a healthy tier —
  in-flight failover means replica death drops nothing),
  ``failover_count``, ejection/readmit/restart counters, and
  ``p95_vs_baseline`` (client-observed e2e p95 ratio vs the declared
  ``CHAOS_P95_BUDGET``). ``bench_gate`` gates ``error_rate`` at 0 and
  ``p95_vs_baseline`` as a max. ``--smoke --chaos`` is the tier-1
  chaos smoke. The run then reuses the warm fleet for the ISSUE 16
  router-kill phase: a fresh primary/standby ``RouterPair`` over the
  same replicas, ``killrouter@T`` hard-aborting the primary
  mid-stream, clients failing over on idempotency keys. Banks a
  second ``serve_takeover`` record (to ``<out>_takeover.json``):
  ``takeover_latency_s`` vs ``TAKEOVER_LATENCY_BUDGET_S``,
  ``lost_requests`` (gated at 0 — an accepted request survives router
  death via the durable journal), ``resumed_streams``, ``dedup_hits``
  (a duplicated request_id retry returns the ORIGINAL tokens), and
  zero post-warmup recompiles fleet-wide.

* ``--affinity {on,off,ab}`` (ISSUE 12, with ``--router``) — prefix-
  affinity dispatch control. ``ab`` is the A/B mode: the SAME shared-
  prefix-heavy prompt sequence through an affinity-off fleet then an
  affinity-on one, driven sequentially with manual probe sweeps so
  routing is deterministic, banking a ``serve_affinity`` record —
  ``prefix_hit_rate_affinity`` strictly above
  ``prefix_hit_rate_no_affinity`` is the acceptance inequality ``ok``
  asserts, with the shared-vs-cold TTFT split and zero post-warmup
  recompiles alongside. ``bench_gate`` pins the -affinity rate as a
  stamped minimum.

* ``--spec-decode K`` (ISSUE 11) — speculative-decoding A/B: the SAME
  prompt-like prompts (tiled motifs — the traffic speculation exists
  for) through two engines, speculation off then on at draft window K,
  banking a ``serve_spec`` record: ``tpot_speedup`` (off/on TPOT p50
  ratio — the headline the tentpole claims), ``draft_hit_rate`` and
  ``accepted_per_step`` p50 (why it moved), ``tokens_identical`` (the
  determinism contract, checked over EVERY request) and zero
  post-warmup recompiles across both engines. ``bench_gate`` gates
  ``tpot_speedup`` as a stamped minimum.

* ``--weight-dtype {int8,fp8}`` (ISSUE 15) — weight-quantization A/B:
  the SAME mixed-length prompts through an f32 engine and one whose
  weights the precision registry quantized at load time, banking a
  ``serve_quant`` record: ``hbm_bytes_per_replica`` +
  ``hbm_ratio_vs_f32`` (the ~4x HBM claim, via
  ``engine.byte_breakdown``), ``tpot_speedup_quant`` /
  ``ttft_speedup_quant``, and the bounded-divergence verdict int8 KV
  established — ``first_token_exact`` over every request plus
  ``stream_agreement`` >= ``QUANT_AGREEMENT_FLOOR``, zero post-warmup
  recompiles on both engines. ``--smoke --weight-dtype int8`` is the
  tier-1 quantization smoke; ``bench_gate`` gates
  ``tpot_speedup_quant`` (min) and ``hbm_bytes_per_replica`` (max).

* ``--traffic {ramp,flash,diurnal}`` (ISSUE 13) — the replayable
  open-loop traffic model: seeded exponential arrivals at a per-mode
  rate profile, heavy-tail prompt lengths, a seeded interactive/batch
  SLO mix, driven open-loop (arrivals never back off) against a
  brownout-enabled fleet. ``flash`` pins the flash-crowd golden (all
  shedding on batch, interactive flash TTFT p95 within
  ``FLASH_TTFT_BUDGET`` x steady, token-identical streams, ladder
  cleared); ``ramp`` pins the autoscaler golden (1 -> ``--max-
  replicas`` -> 1, drain-first, ``scale_up_latency_s`` +
  ``p95_during_resize_ms`` stamped); ``diurnal`` is the long-horizon
  shape. Banks the ``serve_traffic`` record whose per-class p95s /
  shed rates / scale-up latency ``bench_gate`` accepts. Same
  ``--traffic-seed`` = byte-identical scenario — composable with a
  ``--fault-spec``-style chaos schedule by arming the fault env
  around the run.

``--inproc`` skips the HTTP hop (batcher futures driven directly) to
separate transport cost from engine cost; ``--out`` banks the record
as a JSON file next to the BENCH_r*.json trajectory.

``--slo`` (ISSUE 19, plain + ``--router`` modes) runs the SLO
AlertEngine over the organic traffic plus a known-answer canary probe
sweep after the drive; the record banks ``alert_count`` (gated max by
``bench_gate``), ``probe_success_rate`` (gated min) and
``error_budget_remaining``, and ``ok`` additionally requires
``alert_count == 0`` — the healthy smoke's zero-alerts claim. Probe
traffic is excluded from the banked percentiles and counters (the
record / counter snapshot is taken first).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)


SMOKE_MODEL = dict(
    vocab_size=211,
    max_len=64,
    num_layers=2,
    num_heads=2,
    d_model=32,
    dropout=0.0,
    attention="xla",
)


def build_smoke_engine(serve_cfg=None, *, registry=None):
    """Tiny random-param GPT-2 + engine, shared with tests/test_serving:
    big enough to cross prefill buckets, small enough for tier-1."""
    import jax

    from tensorflow_examples_tpu.models import transformer
    from tensorflow_examples_tpu.serving.engine import (
        InferenceEngine,
        ServeConfig,
    )

    cfg = transformer.TransformerConfig(**SMOKE_MODEL)
    model = transformer.Transformer(cfg)
    import jax.numpy as jnp

    tokens = jnp.zeros((1, 8), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, tokens)["params"]
    return InferenceEngine(
        cfg,
        params,
        cfg=serve_cfg or ServeConfig(max_slots=8, prefill_bucket_floor=16,
                                     kv_bucket_floor=32),
        registry=registry,
    )


def build_checkpoint_engine(workdir: str, serve_cfg, *, registry=None):
    """Engine over the latest checkpoint in an ``examples/gpt2`` workdir
    (restores through an eval_shape template like generate.py). The
    template is the DEFAULT Gpt2Config — the workdir banks no config,
    so non-default-shape checkpoints cannot be restored here."""
    import jax
    import jax.numpy as jnp

    from tensorflow_examples_tpu.serving.engine import InferenceEngine
    from tensorflow_examples_tpu.train.checkpoint import CheckpointManager
    from tensorflow_examples_tpu.train.loop import state_factory
    from tensorflow_examples_tpu.workloads import gpt2

    cfg = gpt2.Gpt2Config(workdir=workdir)
    make_state, _ = state_factory(gpt2.make_task(cfg), cfg)
    abstract = jax.eval_shape(make_state, jax.random.PRNGKey(0))
    try:
        restored = CheckpointManager(workdir).restore_latest(abstract)
    except Exception as e:
        raise SystemExit(
            f"restore failed against the default-shape template — a "
            f"checkpoint trained with non-default model flags must be "
            f"served via examples/gpt2/serve.py instead: {e}"
        ) from None
    if restored is None:
        raise SystemExit(f"no checkpoint under {workdir}")
    params = jax.tree.map(jnp.asarray, restored[0].params)
    return InferenceEngine(
        gpt2.model_config(cfg), params, cfg=serve_cfg, registry=registry
    )


def make_patterned_prompts(n: int, *, vocab: int, max_len: int,
                           max_new: int,
                           seed: int = 0) -> list[list[int]]:
    """Prompt-LIKE prompts for the speculation A/B (ISSUE 11): each is
    a short random motif tiled to a mixed length, the repetitive shape
    of real prompt traffic (code, templates, boilerplate) that the
    self-speculative n-gram drafter exists for. Random-token prompts
    (``make_prompts``) are the adversarial case — near-zero draft hits
    — and exactly what a speculation bench must NOT quietly use."""
    import numpy as np

    rng = np.random.default_rng(seed)
    cap = max(4, max_len - max_new)
    prompts = []
    for i in range(n):
        motif = [
            int(t) for t in rng.integers(0, vocab, int(rng.integers(3, 7)))
        ]
        ln = int(rng.integers(max(4, cap // 3), cap + 1))
        prompts.append((motif * (ln // len(motif) + 1))[:ln])
    prompts[0] = prompts[0][:max(4, cap // 3)]
    prompts[-1] = (prompts[-1] * 4)[:cap]
    return prompts


def make_prompts(n: int, *, vocab: int, max_len: int, max_new: int,
                 seed: int = 0,
                 shared_prefix_every: int = 0) -> list[list[int]]:
    """Mixed-length prompts spanning the prefill buckets (that's the
    continuous-batching claim under test: different lengths coalesce).

    ``shared_prefix_every=k`` gives every k-th prompt one common
    system-prompt-style prefix (half the prompt budget) plus a random
    tail — the traffic shape the paged pool's prefix cache exists for
    (the first such prompt prefills it, later ones hit)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    cap = max(2, max_len - max_new)
    lengths = [int(rng.integers(1, cap + 1)) for _ in range(n)]
    # Force the extremes so every run exercises bucket 1 and the top.
    lengths[0], lengths[-1] = 1, cap
    prompts = [
        [int(t) for t in rng.integers(0, vocab, (ln,))] for ln in lengths
    ]
    if shared_prefix_every:
        pre_len = max(1, cap // 2)
        prefix = [int(t) for t in rng.integers(0, vocab, (pre_len,))]
        for i in range(1, n, shared_prefix_every):
            tail = 1 + int(rng.integers(0, max(1, cap - pre_len)))
            prompts[i] = prefix + [
                int(t) for t in rng.integers(0, vocab, (tail,))
            ]
    return prompts


def make_affinity_prompts(n: int, *, vocab: int, max_len: int,
                          max_new: int, block: int = 16,
                          seed: int = 0):
    """The affinity A/B's traffic shape (ISSUE 12): two distinct
    shared prefixes (block-aligned, so their chain keys are exactly
    matchable) interleaved with cold prompts —
    ``(prompts, groups)`` where groups[i] is "shared" or "cold". A
    cache-BLIND router spreads each shared group over the fleet (every
    replica pays its own cold prefill of the prefix); an affinity
    router parks each group on the replica already holding its chain,
    which is the measured hit-rate gap the record banks."""
    import numpy as np

    rng = np.random.default_rng(seed)
    cap = max(block + 2, max_len - max_new)
    pre_len = max(block, (cap * 2 // 3) // block * block)
    pre_len = min(pre_len, (cap - 2) // block * block)
    prefixes = {
        "A": [int(t) for t in rng.integers(0, vocab, pre_len)],
        "B": [int(t) for t in rng.integers(0, vocab, pre_len)],
    }
    prompts, groups = [], []
    for i in range(n):
        g = ("A", "B", "cold")[i % 3]
        if g == "cold":
            ln = int(rng.integers(2, cap + 1))
            prompts.append(
                [int(t) for t in rng.integers(0, vocab, ln)]
            )
            groups.append("cold")
        else:
            tail = 1 + int(rng.integers(0, max(1, cap - pre_len)))
            prompts.append(
                prefixes[g]
                + [int(t) for t in rng.integers(0, vocab, tail)]
            )
            groups.append("shared")
    return prompts, groups


def _post_json(url: str, body: dict, timeout: float) -> tuple[int, dict]:
    # The serving stack's one JSON-over-HTTP client: transport-level
    # failures (URLError, reset, timeout, torn JSON body) come back as
    # status 0 and count as THIS request's error instead of killing
    # the worker thread and stranding every prompt it would have
    # pulled next.
    from tensorflow_examples_tpu.serving.router import post_json

    return post_json(url, body, timeout)


def drive(frontend, prompts, *, concurrency: int, max_new: int,
          temperature: float, top_k: int, http_url: str | None,
          timeout: float, trace_recorder=None) -> dict:
    """Closed loop: workers pull the next prompt off a shared list the
    moment their current request resolves. Returns per-request replies
    (index-aligned with ``prompts``), per-request CLIENT wall times
    (``client_s`` — includes every router retry/failover, which the
    replica-measured ``total_s`` cannot see), + wall time.

    ``trace_recorder`` (ISSUE 18): a ``tracing.TraceRecorder`` makes
    the bench the CLIENT-side trace originator for replica-direct
    runs — each request ships a wire context, the reply's
    ``trace_spans`` ingest under a client root span, and the trace
    finishes with the client wall. Router runs leave this None: the
    router mints and owns the trace there."""
    from tensorflow_examples_tpu.telemetry import tracing

    replies: list[tuple[int, dict] | None] = [None] * len(prompts)
    client_s: list[float | None] = [None] * len(prompts)
    next_i = [0]
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                i = next_i[0]
                if i >= len(prompts):
                    return
                next_i[0] += 1
            body = {
                "prompt": prompts[i],
                "max_new_tokens": max_new,
                "temperature": temperature,
                "top_k": top_k,
                "seed": i,  # per-request stream: replayable
            }
            root_id = None
            ctx = None
            if trace_recorder is not None:
                ctx = trace_recorder.new_context()
                root_id = tracing.new_span_id()
                body["trace"] = {
                    "trace_id": ctx.trace_id,
                    "parent_span_id": root_id,
                    "sampled": True,
                }
            t_mono = time.monotonic()
            t_req = time.perf_counter()
            if http_url is not None:
                replies[i] = _post_json(http_url, body, timeout)
            else:
                replies[i] = frontend.handle_request(body, kind="generate")
            client_s[i] = time.perf_counter() - t_req
            if trace_recorder is not None:
                status, reply = replies[i] or (0, {})
                spans = (
                    reply.pop("trace_spans", None)
                    if isinstance(reply, dict) else None
                )
                if spans:
                    trace_recorder.ingest(
                        ctx.trace_id, spans, parent_id=root_id
                    )
                trace_recorder.add_span(
                    ctx.trace_id, tracing.close_span(
                        "request", t_mono, span_id=root_id,
                        tags={"status": int(status)},
                    )
                )
                trace_recorder.finish(
                    ctx.trace_id, slo="interactive",
                    status=int(status), e2e_s=client_s[i],
                )

    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=worker, name=f"serve-bench-{k}", daemon=True)
        for k in range(concurrency)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout * max(1, len(prompts)))
    wall = time.perf_counter() - t0
    return {"replies": replies, "client_s": client_s, "wall_s": wall}


def tally_replies(replies) -> dict:
    """Split non-200 outcomes by MEANING (ISSUE 13 satellite): a
    503 load-shed is correct overload behavior, a 4xx is the request's
    own fault, and only transport failures / unexpected statuses are
    ``errors`` — so an overload run with correct shedding doesn't read
    as a broken fleet, and a chaos record's error_rate-at-0 criterion
    stays honest about what it counts."""
    completed = shed = rejected = transport = other = 0
    for r in replies:
        if r is None:
            transport += 1  # the worker never got an answer
            continue
        status = r[0]
        if status == 200:
            completed += 1
        elif status == 0:
            transport += 1
        elif status == 503:
            shed += 1
        elif 400 <= status < 500:
            rejected += 1
        else:
            other += 1
    return {
        "completed": completed,
        "shed_total": shed,
        "rejected_total": rejected,
        "transport_errors": transport,
        "errors": transport + other,
    }


def bench_record(engine, registry, outcome, prompts, *, concurrency,
                 verified, verify_ok, backend) -> dict:
    hists = registry.histogram_summaries()

    def pct(name, q):
        h = hists.get(f"serving/{name}")
        v = h and h.get(f"p{q}")
        return round(v * 1e3, 3) if v is not None else None

    replies = outcome["replies"]
    done = [r for r in replies if r is not None and r[0] == 200]
    toks = sum(len(r[1].get("tokens", ())) for r in done)
    wall = outcome["wall_s"]
    counters = registry.counter_values()
    tally = tally_replies(replies)
    rec = {
        "bench": "serving",
        "backend": backend,
        "requests": len(prompts),
        "completed": len(done),
        "errors": tally["errors"],
        "shed_total": tally["shed_total"],
        "rejected_total": tally["rejected_total"],
        "transport_errors": tally["transport_errors"],
        "concurrency": concurrency,
        "max_slots": engine.cfg.max_slots,
        "wall_s": round(wall, 3),
        "req_per_s": round(len(done) / wall, 3) if wall else None,
        "tok_per_s": round(toks / wall, 3) if wall else None,
        "generated_tokens": toks,
        "queue_wait_p95_ms": pct("queue_wait", 95),
        "prefill_p95_ms": pct("prefill", 95),
        "ttft_p50_ms": pct("ttft", 50),
        "ttft_p95_ms": pct("ttft", 95),
        "tpot_p50_ms": pct("tpot", 50),
        "tpot_p95_ms": pct("tpot", 95),
        "e2e_p50_ms": pct("e2e", 50),
        "e2e_p95_ms": pct("e2e", 95),
        "expected_compiles": engine.expected_compiles(),
        "compiles": int(counters.get("compile/count", 0)),
        "post_warmup_recompiles": engine.post_warmup_recompiles(),
        "shed": int(counters.get("serving/shed_total", 0)),
        "verified": verified,
        "verify_ok": verify_ok,
    }
    paged = getattr(engine.pool, "paged_stats", None)
    if callable(paged):
        stats = paged()
        rec["kv_block_size"] = stats["block_size"]
        rec["kv_bits"] = stats["kv_bits"]
        rec["prefix_hits"] = stats["prefix_hits"]
        rec["prefix_misses"] = stats["prefix_misses"]
        rec["prefix_hit_rate"] = stats["prefix_hit_rate"]
    # Closed-loop benches must COMPLETE everything — a shed here is a
    # misconfigured bench, not acceptable overload behavior — but the
    # record still says which kind of non-200 happened.
    rec["ok"] = bool(
        len(done) == len(replies)
        and verify_ok
        and rec["post_warmup_recompiles"] == 0
    )
    return rec


def _pct_from_values(values, q):
    """Client-side percentile over per-reply values, in ms (router mode
    has no shared registry to read — every replica owns its own)."""
    import numpy as np

    vals = [v for v in values if isinstance(v, (int, float))]
    if not vals:
        return None
    return round(float(np.percentile(vals, q)) * 1e3, 3)


def build_replica_stacks(args, serve_kw, n: int) -> list:
    """``n`` warmed in-proc serving stacks — (engine, batcher,
    frontend, registry) each on its own loopback port. Warmups run
    concurrently: XLA compilation releases the GIL, so N replicas warm
    in roughly one replica's wall time."""
    from tensorflow_examples_tpu.serving.batcher import ContinuousBatcher
    from tensorflow_examples_tpu.serving.engine import ServeConfig
    from tensorflow_examples_tpu.serving.frontend import ServingFrontend
    from tensorflow_examples_tpu.telemetry.registry import MetricsRegistry

    replicas: list = [None] * n

    def build_one(k: int) -> None:
        reg = MetricsRegistry()
        serve_cfg = ServeConfig(**serve_kw)
        if args.workdir:
            engine = build_checkpoint_engine(
                args.workdir, serve_cfg, registry=reg
            )
        else:
            engine = build_smoke_engine(serve_cfg, registry=reg)
        # Fleet identity (ISSUE 10): serve-side fault specs
        # (kind@replica:arg, $TPU_SERVE_FAULT_INJECT) key on it.
        engine.replica_id = k
        engine.warmup()
        batcher = ContinuousBatcher(engine, registry=reg).start()
        frontend = ServingFrontend(batcher, port=0).start()
        replicas[k] = (engine, batcher, frontend, reg)

    warm_threads = [
        threading.Thread(target=build_one, args=(k,), daemon=True)
        for k in range(n)
    ]
    for t in warm_threads:
        t.start()
    for t in warm_threads:
        t.join()
    return replicas


def run_router_bench(args) -> dict:
    """Stand up --replicas in-proc serving stacks behind the router and
    drive the tier end-to-end; returns the ``serve_router`` record."""
    import jax

    from tensorflow_examples_tpu.serving.router import (
        Router,
        RouterConfig,
        RouterFrontend,
    )

    kv_block = args.kv_block_size if args.kv_block_size >= 0 else 16
    serve_kw = dict(
        max_slots=args.max_slots,
        max_delay_s=0.002,
        request_timeout_s=args.timeout,
        kv_block_size=kv_block,
        kv_dtype=args.kv_dtype,
    )
    if args.smoke:
        serve_kw.update(prefill_bucket_floor=16, kv_bucket_floor=32)

    t0 = time.perf_counter()
    replicas = build_replica_stacks(args, serve_kw, args.replicas)
    warmup_s = time.perf_counter() - t0
    print(
        f"# {args.replicas} replicas warm "
        f"({replicas[0][0].expected_compiles()} programs each, paged "
        f"block={kv_block}, kv_dtype={args.kv_dtype or 'fp'}) in "
        f"{warmup_s:.1f}s",
        file=sys.stderr,
    )

    urls = [f"http://127.0.0.1:{fe.port}" for _, _, fe, _ in replicas]
    router = Router(
        urls,
        cfg=RouterConfig(
            probe_interval_s=0.2, request_timeout_s=args.timeout,
            prefix_affinity=(args.affinity != "off"),
            # Bench runs keep every trace (ISSUE 18): coverage banks
            # at 1.0 on a healthy tier, and the kept set is the full
            # population the attribution tool reads.
            trace_sample_fraction=1.0,
        ),
        trace_path=(args.trace_out or None),
    ).start()
    rfront = RouterFrontend(router, port=0).start()

    n = args.requests or (20 if args.smoke else 64)
    verify = args.verify if args.verify >= 0 else (3 if args.smoke else 0)
    model_cfg = replicas[0][0].model_cfg
    # Every 4th prompt shares a system-prompt-style prefix: the first
    # one prefills the prefix cache, later ones hit it (the record's
    # prefix_hit_rate is the measured claim, and the tier-1 smoke
    # asserts >= 1 hit).
    prompts = make_prompts(
        n,
        vocab=model_cfg.vocab_size,
        max_len=model_cfg.max_len,
        max_new=args.max_new_tokens,
        shared_prefix_every=4,
    )
    try:
        outcome = drive(
            None, prompts,
            concurrency=args.concurrency, max_new=args.max_new_tokens,
            temperature=args.temperature, top_k=args.top_k,
            http_url=rfront.url("/generate"), timeout=args.timeout,
        )
        verify_ok = True
        for i in range(min(verify, n)):
            reply = outcome["replies"][i]
            if reply is None or reply[0] != 200:
                verify_ok = False
                continue
            ref = replicas[0][0].reference_generate(
                prompts[i], max_new=args.max_new_tokens, seed=i,
                temperature=args.temperature, top_k=args.top_k,
            )
            if reply[1]["tokens"] != ref:
                verify_ok = False
                print(
                    f"# VERIFY FAIL req {i}: served "
                    f"{reply[1]['tokens']} != reference {ref}",
                    file=sys.stderr,
                )
        # Snapshot the router counters BEFORE the --slo probe phase:
        # probes ride the router too and must not inflate the banked
        # router_dispatched (ISSUE 19 exclusion contract).
        router_counters = router.registry.counter_values()
        if args.slo:
            # The organic traffic already fed router.alerts through
            # the trace path; the prober adds the black-box
            # availability sweep (router + every replica directly).
            from tensorflow_examples_tpu.serving.prober import (
                CanaryProber,
                fleet_targets,
            )

            prober = CanaryProber(
                fleet_targets(f"http://127.0.0.1:{rfront.port}", urls),
                alerts=router.alerts,
            )
            for _ in range(3):
                prober.probe_once()
    finally:
        rfront.close()
        router.close()
        for _, batcher, frontend, _ in replicas:
            batcher.close(drain=True)
            frontend.close()

    replies = outcome["replies"]
    done = [r for r in replies if r is not None and r[0] == 200]
    toks = sum(len(r[1].get("tokens", ())) for r in done)
    wall = outcome["wall_s"]
    tally = tally_replies(replies)
    errors = tally["errors"]

    def field(name):
        return [r[1].get(name) for r in done]

    tpots = [
        (r[1]["total_s"] - r[1]["ttft_s"]) / (len(r[1]["tokens"]) - 1)
        for r in done
        if isinstance(r[1].get("ttft_s"), (int, float))
        and isinstance(r[1].get("total_s"), (int, float))
        and len(r[1].get("tokens", ())) > 1
    ]
    # --kv-block-size 0 runs DENSE replicas behind the router: the
    # prefix-cache fields degrade to zero instead of crashing the
    # record assembly after a full benchmark run.
    hits = sum(
        getattr(e.pool, "prefix_hits", 0) for e, _, _, _ in replicas
    )
    misses = sum(
        getattr(e.pool, "prefix_misses", 0) for e, _, _, _ in replicas
    )
    recompiles = sum(
        e.post_warmup_recompiles() for e, _, _, _ in replicas
    )
    rec = {
        "bench": "serve_router",
        "backend": jax.default_backend(),
        "replicas": args.replicas,
        "requests": len(prompts),
        "completed": len(done),
        "errors": errors,
        "shed_total": tally["shed_total"],
        "rejected_total": tally["rejected_total"],
        "transport_errors": tally["transport_errors"],
        "concurrency": args.concurrency,
        "max_slots": args.max_slots,
        "wall_s": round(wall, 3),
        "req_per_s": round(len(done) / wall, 3) if wall else None,
        "tok_per_s": round(toks / wall, 3) if wall else None,
        "generated_tokens": toks,
        "queue_wait_p95_ms": _pct_from_values(field("queue_wait_s"), 95),
        "ttft_p50_ms": _pct_from_values(field("ttft_s"), 50),
        "ttft_p95_ms": _pct_from_values(field("ttft_s"), 95),
        "tpot_p50_ms": _pct_from_values(tpots, 50),
        "tpot_p95_ms": _pct_from_values(tpots, 95),
        "e2e_p50_ms": _pct_from_values(field("total_s"), 50),
        "e2e_p95_ms": _pct_from_values(field("total_s"), 95),
        "expected_compiles": sum(
            e.expected_compiles() for e, _, _, _ in replicas
        ),
        "compiles": sum(
            int(reg.counter_values().get("compile/count", 0))
            for _, _, _, reg in replicas
        ),
        "post_warmup_recompiles": recompiles,
        "shed": sum(
            int(reg.counter_values().get("serving/shed_total", 0))
            for _, _, _, reg in replicas
        ),
        "kv_block_size": kv_block,
        "kv_bits": replicas[0][0].pool.kv_bits,
        "prefix_hits": hits,
        "prefix_misses": misses,
        "prefix_hit_rate": hits / (hits + misses) if hits + misses else 0.0,
        "router_dispatched": int(
            router_counters.get("router/dispatched_total", 0)
        ),
        "router_retries": int(
            router_counters.get("router/retries_total", 0)
        ),
        "router_no_replica": int(
            router_counters.get("router/no_replica_total", 0)
        ),
        "verified": min(verify, n),
        "verify_ok": verify_ok,
        "warmup_s": round(warmup_s, 3),
        "affinity": args.affinity != "off",
        "transport": "router-http",
    }
    # The router owns the traces in this mode; its recorder's summary
    # is the record's tracing claim (ISSUE 18). stats() only reads
    # registry counters, so the closed router is safe to ask.
    rec.update(router.recorder.stats())
    rec["ok"] = bool(
        len(done) == len(replies) and verify_ok and recompiles == 0
    )
    if args.slo:
        # Healthy fleet smoke banks alert_count=0 and
        # probe_success_rate=1.0 (the ISSUE 19 acceptance golden).
        rec.update(router.alerts.stats())
        rec["ok"] = bool(rec["ok"] and rec["alert_count"] == 0)
    return rec


def run_affinity_bench(args) -> dict:
    """``--router --affinity ab`` (ISSUE 12): the SAME shared-prefix-
    heavy prompt sequence through one 2-replica fleet twice — prefix
    affinity OFF, then ON, with every replica's prefix cache reset
    between phases so both start cold — banking one ``serve_affinity``
    record. Requests run sequentially with a manual probe sweep before
    each dispatch, so routing (and therefore the hit counts) is
    deterministic: the record's claim is measured, not sampled.

    The claims it carries: ``prefix_hit_rate_affinity`` strictly above
    ``prefix_hit_rate_no_affinity`` on shared-prefix traffic (the
    acceptance headline — bench_gate pins the -affinity rate as a
    minimum), TTFT p50/p95 split shared-vs-cold for the on phase, every
    verified stream token-identical to the unbatched reference, and
    zero post-warmup recompiles across both phases."""
    import jax

    from tensorflow_examples_tpu.serving.router import (
        Router,
        RouterConfig,
    )

    kv_block = args.kv_block_size if args.kv_block_size >= 0 else 16
    serve_kw = dict(
        max_slots=args.max_slots,
        max_delay_s=0.002,
        request_timeout_s=args.timeout,
        kv_block_size=kv_block,
        kv_dtype=args.kv_dtype,
    )
    if args.smoke:
        # A single coarse bucket per program family: the A/B measures
        # ROUTING (hit counts, shared-vs-cold TTFT), which is bucket-
        # granularity-agnostic — a 3-program ladder keeps the tier-1
        # smoke's two warmups cheap.
        serve_kw.update(prefill_bucket_floor=64, kv_bucket_floor=64)

    n = args.requests or (12 if args.smoke else 48)
    verify = args.verify if args.verify >= 0 else (3 if args.smoke else 0)
    n_rep = args.replicas  # main() already defaulted it (2 for --router)

    t0 = time.perf_counter()
    replicas = build_replica_stacks(args, serve_kw, n_rep)
    warmup_s = time.perf_counter() - t0
    urls = [f"http://127.0.0.1:{fe.port}" for _, _, fe, _ in replicas]

    def phase(affinity: bool, prompts):
        # Fresh caches per phase (the A/B must compare cold-start to
        # cold-start) without paying a second fleet warmup: reset every
        # pool — prefix cache, hit counters, and all slots — while the
        # batchers idle between the sequential requests.
        for engine, _, _, _ in replicas:
            engine.pool.reset()
        # No probe thread: one manual sweep before every dispatch keeps
        # the router's load/digest view exact, so the phase's routing
        # is a pure function of the prompt sequence.
        router = Router(
            urls,
            cfg=RouterConfig(
                probe_interval_s=3600.0,
                request_timeout_s=args.timeout,
                prefix_affinity=affinity,
            ),
        )
        replies = []
        t0 = time.perf_counter()
        try:
            for i, prompt in enumerate(prompts):
                router.probe_once()
                replies.append(router.handle(
                    {
                        "prompt": prompt,
                        "max_new_tokens": args.max_new_tokens,
                        "temperature": args.temperature,
                        "top_k": args.top_k,
                        "seed": i,
                    },
                    kind="generate",
                ))
            wall = time.perf_counter() - t0
            verify_ok = True
            for i in range(min(verify, len(prompts))):
                status, reply = replies[i]
                ref = replicas[0][0].reference_generate(
                    prompts[i], max_new=args.max_new_tokens, seed=i,
                    temperature=args.temperature, top_k=args.top_k,
                )
                if status != 200 or reply.get("tokens") != ref:
                    verify_ok = False
                    print(
                        f"# VERIFY FAIL affinity req {i}: "
                        f"{reply.get('tokens')} != reference {ref}",
                        file=sys.stderr,
                    )
            hits = sum(
                getattr(e.pool, "prefix_hits", 0)
                for e, _, _, _ in replicas
            )
            misses = sum(
                getattr(e.pool, "prefix_misses", 0)
                for e, _, _, _ in replicas
            )
            recompiles = sum(
                e.post_warmup_recompiles() for e, _, _, _ in replicas
            )
            affinity_hits = int(
                router.registry.counter_values().get(
                    "router/affinity_hits_total", 0
                )
            )
        finally:
            router.close()
        return {
            "replies": replies,
            "wall_s": wall,
            "hits": hits,
            "misses": misses,
            "recompiles": recompiles,
            "affinity_hits": affinity_hits,
            "verify_ok": verify_ok,
        }

    if args.workdir:
        from tensorflow_examples_tpu.workloads import gpt2

        model_cfg = gpt2.model_config(gpt2.Gpt2Config())
    else:
        from tensorflow_examples_tpu.models import transformer

        model_cfg = transformer.TransformerConfig(**SMOKE_MODEL)
    prompts, groups = make_affinity_prompts(
        n, vocab=model_cfg.vocab_size, max_len=model_cfg.max_len,
        max_new=args.max_new_tokens, block=kv_block,
    )
    try:
        off = phase(False, prompts)
        on = phase(True, prompts)
    finally:
        for _, batcher, frontend, _ in replicas:
            batcher.close(drain=True)
            frontend.close()

    def rate(p):
        looked = p["hits"] + p["misses"]
        return p["hits"] / looked if looked else 0.0

    def group_ttfts(p, want):
        return [
            reply.get("ttft_s")
            for (status, reply), g in zip(p["replies"], groups)
            if status == 200 and g == want
        ]

    errors = sum(
        1 for p in (off, on) for status, _ in p["replies"]
        if status != 200
    )
    on_rate, off_rate = rate(on), rate(off)
    recompiles = off["recompiles"] + on["recompiles"]
    rec = {
        "bench": "serve_affinity",
        "backend": jax.default_backend(),
        "replicas": n_rep,
        "requests": 2 * n,
        "requests_per_phase": n,
        "shared_requests_per_phase": groups.count("shared"),
        "errors": errors,
        "wall_s": round(off["wall_s"] + on["wall_s"], 3),
        "warmup_s": round(warmup_s, 3),
        "kv_block_size": kv_block,
        "prefix_hit_rate_affinity": round(on_rate, 4),
        "prefix_hit_rate_no_affinity": round(off_rate, 4),
        "affinity_hit_gain": round(on_rate - off_rate, 4),
        "prefix_hits_on": on["hits"],
        "prefix_hits_off": off["hits"],
        "affinity_dispatches": on["affinity_hits"],
        "ttft_shared_p50_ms": _pct_from_values(
            group_ttfts(on, "shared"), 50
        ),
        "ttft_shared_p95_ms": _pct_from_values(
            group_ttfts(on, "shared"), 95
        ),
        "ttft_cold_p50_ms": _pct_from_values(
            group_ttfts(on, "cold"), 50
        ),
        "ttft_cold_p95_ms": _pct_from_values(
            group_ttfts(on, "cold"), 95
        ),
        "post_warmup_recompiles": recompiles,
        "verified": min(verify, n),
        "verify_ok": bool(off["verify_ok"] and on["verify_ok"]),
        "transport": "router-http",
    }
    rec["ok"] = bool(
        errors == 0
        and rec["verify_ok"]
        and recompiles == 0
        and on_rate > off_rate
    )
    return rec


# Declared p95 budget for the chaos record (ISSUE 10): the chaos
# phase's client-observed e2e p95 must stay within this multiple of the
# fault-free baseline phase's. Generous on purpose — a failover adds
# one full re-prefill + backoff to the victims, and the 2-vCPU CI rig
# is load-noisy; the claim is "bounded", not "free".
CHAOS_P95_BUDGET = 25.0

# ISSUE 16: detect-to-serving promotion wall the serve_takeover record
# gates on (the time from the standby noticing the stale lease to its
# first post-promotion dispatch being possible — probe rebuild plus
# journal replay; the heartbeat miss budget itself is configured, not
# measured).
TAKEOVER_LATENCY_BUDGET_S = 10.0


def _client_p95_ms(outcome) -> float | None:
    vals = [
        s for s, r in zip(outcome["client_s"], outcome["replies"])
        if s is not None and r is not None and r[0] == 200
    ]
    return _pct_from_values(vals, 95)


def _drive_takeover(endpoints, prompts, *, concurrency, max_new,
                    temperature, top_k, timeout) -> dict:
    """Closed loop with CLIENT-SIDE failover (ISSUE 16): every request
    carries an idempotency key, and a worker that sees a transport
    reset or a fenced/retryable 503 simply retries against the other
    router endpoint until its deadline — the protocol a real client of
    a primary/standby pair speaks. Because retries reuse the
    request_id, a request the dying primary already completed comes
    back as a journal dedupe hit, and one it only accepted comes back
    from the standby's replay; the caller can't tell, which is the
    point."""
    replies: list[tuple[int, dict] | None] = [None] * len(prompts)
    client_s: list[float | None] = [None] * len(prompts)
    retries = [0]
    next_i = [0]
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                i = next_i[0]
                if i >= len(prompts):
                    return
                next_i[0] += 1
            body = {
                "prompt": prompts[i],
                "max_new_tokens": max_new,
                "temperature": temperature,
                "top_k": top_k,
                "seed": i,  # per-request stream: replayable
                "request_id": f"tko-{i}",
            }
            t_req = time.perf_counter()
            deadline = t_req + timeout
            last = None
            while True:
                for url in endpoints:
                    last = _post_json(url, body, timeout)
                    if last[0] == 200:
                        break
                    with lock:
                        retries[0] += 1
                if last[0] == 200 or time.perf_counter() > deadline:
                    break
                time.sleep(0.05)
            replies[i] = last
            client_s[i] = time.perf_counter() - t_req

    t0 = time.perf_counter()
    threads = [
        threading.Thread(
            target=worker, name=f"serve-bench-{k}", daemon=True
        )
        for k in range(concurrency)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout * max(1, len(prompts)))
    wall = time.perf_counter() - t0
    return {
        "replies": replies, "client_s": client_s, "wall_s": wall,
        "client_retries": retries[0],
    }


def _takeover_phase(args, fleet, mk) -> dict:
    """The --chaos router-kill phase (ISSUE 16): a fresh RouterPair
    over the already-warm fleet, ``killrouter@T`` armed mid-stream,
    clients failing over between the two endpoints. Banks the
    ``serve_takeover`` record: takeover_latency_s, ZERO lost accepted
    requests, resumed_streams, dedup_hits — each measured, not
    asserted by construction."""
    import shutil
    import tempfile

    from tensorflow_examples_tpu.serving.chaos import RouterPair
    from tensorflow_examples_tpu.utils import faults as faults_mod

    n = args.requests or (12 if args.smoke else 48)
    kill_at = max(2, n // 3)
    miss_budget_s = 1.0
    tmp = tempfile.mkdtemp(prefix="serve_takeover_")
    pair = RouterPair(
        fleet.urls,
        journal_path=os.path.join(tmp, "journal.jsonl"),
        lease_path=os.path.join(tmp, "lease.json"),
        router_cfg=fleet.router_cfg,
        standby_interval_s=0.1,
        miss_budget_s=miss_budget_s,
    ).start()
    prompts = make_prompts(n, seed=303, **mk)
    faults_mod.serve_clear()
    fault_engine = faults_mod.serve_install(f"killrouter@{kill_at}")
    print(
        f"# takeover phase: killrouter@{kill_at} over {n} requests, "
        f"heartbeat miss budget {miss_budget_s:.1f}s",
        file=sys.stderr,
    )
    try:
        out = _drive_takeover(
            pair.endpoints(), prompts,
            concurrency=args.concurrency,
            max_new=args.max_new_tokens,
            temperature=args.temperature, top_k=args.top_k,
            timeout=args.timeout,
        )
        # The standby starts serving the moment it grabs the lease,
        # BEFORE journal replay finishes — clients can drain while
        # promote() is still running, so wait for the completion event
        # instead of sampling it.
        promoted = pair.monitor.promoted.wait(
            timeout=TAKEOVER_LATENCY_BUDGET_S
        )
        # Every stream — died-in-flight, journal-replayed, deduped —
        # must be token-identical to the unbatched reference.
        verify_ok = True
        ref_engine = fleet.replicas[0].engine
        verify = min(len(prompts), max(
            args.verify if args.verify >= 0 else 3, 3
        ))
        for i in range(verify):
            reply = out["replies"][i]
            if reply is None or reply[0] != 200:
                verify_ok = False
                continue
            ref = ref_engine.reference_generate(
                prompts[i], max_new=args.max_new_tokens, seed=i,
                temperature=args.temperature, top_k=args.top_k,
            )
            if reply[1]["tokens"] != ref:
                verify_ok = False
                print(
                    f"# VERIFY FAIL takeover req {i}: served "
                    f"{reply[1]['tokens']} != reference {ref}",
                    file=sys.stderr,
                )
        # Idempotency: a duplicated request_id retry must return the
        # ORIGINAL tokens as a dedupe hit, not burn a generation.
        active = pair.endpoints()[1] if promoted else pair.endpoints()[0]
        first_ok = next(
            (i for i, r in enumerate(out["replies"])
             if r is not None and r[0] == 200), None
        )
        dedup_ok = False
        resume_ok = False
        if first_ok is not None:
            orig = out["replies"][first_ok][1]["tokens"]
            status, dup = _post_json(active, {
                "prompt": prompts[first_ok],
                "max_new_tokens": args.max_new_tokens,
                "temperature": args.temperature, "top_k": args.top_k,
                "seed": first_ok, "request_id": f"tko-{first_ok}",
            }, args.timeout)
            dedup_ok = (
                status == 200 and dup.get("dedup") is True
                and dup.get("tokens") == orig
            )
            # Client resume: reconnect at a committed offset, get the
            # remainder of the SAME stream.
            cut = max(1, len(orig) // 2)
            status, res = _post_json(active, {
                "prompt": prompts[first_ok],
                "max_new_tokens": args.max_new_tokens,
                "temperature": args.temperature, "top_k": args.top_k,
                "seed": first_ok, "request_id": f"tko-{first_ok}",
                "resume_from": cut,
            }, args.timeout)
            resume_ok = (
                status == 200 and res.get("tokens") == orig[cut:]
            )
        tally = tally_replies(out["replies"])
        counters = pair.registry.counter_values()
        recompiles = sum(
            rep.engine.post_warmup_recompiles()
            for rep in fleet.replicas if rep.engine is not None
        )
        lost = n - tally["completed"]
        latency = pair.monitor.takeover_latency_s
        rec = {
            "bench": "serve_takeover",
            "replicas": len(fleet.replicas),
            "fault_spec": f"killrouter@{kill_at}",
            "faults_fired": len(fault_engine.fired),
            "requests": n,
            "completed": tally["completed"],
            "lost_requests": lost,
            "client_retries": out["client_retries"],
            "concurrency": args.concurrency,
            "promoted": promoted,
            "heartbeat_miss_budget_s": miss_budget_s,
            "takeover_latency_s": (
                round(latency, 4) if latency is not None else None
            ),
            "takeover_budget_s": TAKEOVER_LATENCY_BUDGET_S,
            "replayed_intents": pair.monitor.replayed,
            "journal_appends": int(
                counters.get("router/journal_appends_total", 0)
            ),
            "resumed_streams": int(
                counters.get("router/resumed_streams_total", 0)
            ),
            "dedup_hits": int(
                counters.get("router/dedup_hits_total", 0)
            ),
            "fenced_dispatches": int(
                counters.get("router/fenced_dispatch_total", 0)
            ),
            "post_warmup_recompiles": recompiles,
            "verified": verify,
            "verify_ok": verify_ok,
            "dedup_ok": dedup_ok,
            "resume_ok": resume_ok,
            "transport": "router-http",
        }
        rec["ok"] = bool(
            tally["completed"] == n
            and lost == 0
            and promoted
            and fault_engine.fired
            and verify_ok
            and dedup_ok
            and resume_ok
            and rec["dedup_hits"] >= 1
            and recompiles == 0
            and latency is not None
            and latency <= TAKEOVER_LATENCY_BUDGET_S
        )
        return rec
    finally:
        faults_mod.serve_clear()
        pair.close()
        shutil.rmtree(tmp, ignore_errors=True)


def run_chaos_bench(args) -> dict:
    """ISSUE 10: availability under injected faults. Stands up a
    3-replica (default) in-proc paged fleet WITH supervision
    (serving/chaos.ChaosFleet), measures a fault-free baseline phase,
    arms a deterministic serve fault schedule (default: crash replica 1
    mid-decode), drives a chaos phase through the hardened router, then
    waits for the supervisor to restore the fleet. The record is the
    availability claim CI gates: ``error_rate`` (must be 0 — in-flight
    failover means a replica death drops nothing), ``failover_count``,
    ejection/restart counters, and ``p95_vs_baseline`` (client-observed
    e2e p95 ratio, bounded by the declared budget)."""
    import jax

    from tensorflow_examples_tpu.serving.chaos import ChaosFleet
    from tensorflow_examples_tpu.serving.engine import ServeConfig
    from tensorflow_examples_tpu.serving.router import (
        RouterConfig,
        RouterFrontend,
    )
    from tensorflow_examples_tpu.telemetry.registry import MetricsRegistry
    from tensorflow_examples_tpu.utils import faults as faults_mod

    kv_block = args.kv_block_size if args.kv_block_size >= 0 else 16
    serve_kw = dict(
        max_slots=args.max_slots,
        max_delay_s=0.002,
        request_timeout_s=args.timeout,
        kv_block_size=kv_block,
        kv_dtype=args.kv_dtype,
    )
    if args.smoke:
        serve_kw.update(prefill_bucket_floor=16, kv_bucket_floor=32)

    def factory():
        reg = MetricsRegistry()
        serve_cfg = ServeConfig(**serve_kw)
        if args.workdir:
            return build_checkpoint_engine(
                args.workdir, serve_cfg, registry=reg
            )
        return build_smoke_engine(serve_cfg, registry=reg)

    n_replicas = args.replicas if args.replicas > 0 else 3
    spec = args.fault_spec or f"crash@{min(1, n_replicas - 1)}:4"
    fleet = ChaosFleet(
        [factory] * n_replicas,
        router_cfg=RouterConfig(
            probe_interval_s=0.1,
            request_timeout_s=args.timeout,
            retry_budget_s=min(30.0, args.timeout),
            max_retries=4,
            eject_after=2,
            eject_cooldown_s=1.0,
        ),
    )
    t0 = time.perf_counter()
    fleet.start()
    warmup_s = time.perf_counter() - t0
    print(
        f"# chaos fleet: {n_replicas} supervised paged replicas warm "
        f"in {warmup_s:.1f}s; schedule: {spec}",
        file=sys.stderr,
    )
    rfront = RouterFrontend(fleet.router, port=0).start()

    n = args.requests or (12 if args.smoke else 48)
    verify = args.verify if args.verify >= 0 else (3 if args.smoke else 0)
    model_cfg = fleet.replicas[0].engine.model_cfg
    mk = dict(
        vocab=model_cfg.vocab_size, max_len=model_cfg.max_len,
        max_new=args.max_new_tokens, shared_prefix_every=4,
    )
    drive_kw = dict(
        concurrency=args.concurrency, max_new=args.max_new_tokens,
        temperature=args.temperature, top_k=args.top_k,
        http_url=rfront.url("/generate"), timeout=args.timeout,
    )
    base_prompts = make_prompts(n, seed=101, **mk)
    chaos_prompts = make_prompts(n, seed=202, **mk)
    fault_engine = None
    try:
        base_out = drive(None, base_prompts, **drive_kw)
        fault_engine = faults_mod.serve_install(spec)
        chaos_out = drive(None, chaos_prompts, **drive_kw)
        restored = fleet.await_fleet_green(
            n_replicas, timeout_s=args.timeout * 3
        )
        # Verify chaos-phase replies (the failed-over ones included)
        # token-for-token against the unbatched reference on a
        # SURVIVOR engine — failover replay must be invisible.
        verify_ok = True
        ref_engine = fleet.replicas[0].engine
        for i in range(min(verify, n)):
            reply = chaos_out["replies"][i]
            if reply is None or reply[0] != 200:
                verify_ok = False
                continue
            ref = ref_engine.reference_generate(
                chaos_prompts[i],
                max_new=args.max_new_tokens, seed=i,
                temperature=args.temperature, top_k=args.top_k,
            )
            if reply[1]["tokens"] != ref:
                verify_ok = False
                print(
                    f"# VERIFY FAIL chaos req {i}: served "
                    f"{reply[1]['tokens']} != reference {ref}",
                    file=sys.stderr,
                )
        # ISSUE 16: the router-kill phase rides the same warm fleet —
        # a fresh primary/standby RouterPair, killrouter mid-stream,
        # clients failing over on their idempotency keys.
        takeover = _takeover_phase(args, fleet, mk)
    finally:
        faults_mod.serve_clear()
        rfront.close()
        supervisor = fleet.supervisor
        router = fleet.router
        fleet.close()

    base_tally = tally_replies(base_out["replies"])
    chaos_tally = tally_replies(chaos_out["replies"])
    base_done = base_tally["completed"]
    chaos_done = chaos_tally["completed"]
    # ISSUE 13 satellite: error_rate counts transport failures and
    # unexpected statuses ONLY — a load-shed 503 is stamped separately
    # (shed_total), so the error_rate-at-0 gate criterion says "no
    # request was LOST", not "the fleet never shed".
    base_errors = base_tally["errors"]
    chaos_errors = chaos_tally["errors"]
    shed_total = base_tally["shed_total"] + chaos_tally["shed_total"]
    rejected_total = (
        base_tally["rejected_total"] + chaos_tally["rejected_total"]
    )
    base_p95 = _client_p95_ms(base_out)
    chaos_p95 = _client_p95_ms(chaos_out)
    p95_ratio = (
        round(chaos_p95 / base_p95, 3)
        if base_p95 and chaos_p95 else None
    )
    counters = router.registry.counter_values()
    restarts = sum(supervisor.restarts.values())
    survivor_recompiles = sum(
        rep.engine.post_warmup_recompiles()
        for rep in fleet.replicas if rep.engine is not None
    )
    errors = base_errors + chaos_errors
    fired = list(fault_engine.fired) if fault_engine is not None else []
    rec = {
        "bench": "serve_chaos",
        "backend": jax.default_backend(),
        "replicas": n_replicas,
        "fault_spec": spec,
        "faults_fired": len(fired),
        "requests": 2 * n,
        "completed": base_done + chaos_done,
        "errors": errors,
        "error_rate": round(errors / (2 * n), 4),
        "shed_total": shed_total,
        "rejected_total": rejected_total,
        "transport_errors": (
            base_tally["transport_errors"]
            + chaos_tally["transport_errors"]
        ),
        "concurrency": args.concurrency,
        "baseline_e2e_p95_ms": base_p95,
        "chaos_e2e_p95_ms": chaos_p95,
        "p95_vs_baseline": p95_ratio,
        "p95_budget": CHAOS_P95_BUDGET,
        "failover_count": int(
            counters.get("router/failovers_total", 0)
        ),
        "router_retries": int(counters.get("router/retries_total", 0)),
        "router_ejections": int(
            counters.get("router/ejections_total", 0)
        ),
        "router_readmits": int(
            counters.get("router/readmits_total", 0)
        ),
        "router_restarts": restarts,
        "fleet_restored": bool(restored),
        "post_warmup_recompiles": survivor_recompiles,
        "verified": min(verify, n),
        "verify_ok": verify_ok,
        "warmup_s": round(warmup_s, 3),
        "kv_block_size": kv_block,
        "transport": "router-http",
    }
    # ok still requires every request SERVED (shed included in the
    # completeness check — this closed-loop tier must not shed), but
    # error_rate itself stays an honest lost-request rate.
    rec["ok"] = bool(
        base_done + chaos_done == 2 * n
        and verify_ok
        and restored
        and fired
        and survivor_recompiles == 0
        and (p95_ratio is None or p95_ratio <= CHAOS_P95_BUDGET)
    )
    rec["takeover"] = takeover
    return rec


def run_spec_bench(args) -> dict:
    """--spec-decode K (ISSUE 11): drive the SAME prompt-like prompts
    through two freshly built engines — speculation off, then
    speculation on at draft window K — and bank one ``serve_spec``
    record. The claims it carries, all measured: ``tpot_speedup``
    (off-phase TPOT p50 / on-phase TPOT p50 — the headline),
    ``draft_hit_rate`` and ``accepted_per_step`` p50 (why the headline
    moved), ``tokens_identical`` (every on-phase stream token-for-token
    equal to its off-phase twin — speculation is a latency
    optimization, never a numerics change), and zero post-warmup
    recompiles across BOTH engines (the verify_k rungs are part of the
    warmed ladder, counted in expected_compiles)."""
    import jax

    from tensorflow_examples_tpu.serving.batcher import ContinuousBatcher
    from tensorflow_examples_tpu.serving.engine import ServeConfig
    from tensorflow_examples_tpu.serving.frontend import ServingFrontend
    from tensorflow_examples_tpu.telemetry.registry import MetricsRegistry

    serve_kw = dict(
        max_slots=args.max_slots,
        max_delay_s=0.002,
        request_timeout_s=args.timeout,
        kv_block_size=max(args.kv_block_size, 0),
        kv_dtype=args.kv_dtype,
    )
    if args.smoke:
        serve_kw.update(prefill_bucket_floor=16, kv_bucket_floor=32)

    def build(spec_k: int):
        reg = MetricsRegistry()
        cfg = ServeConfig(spec_decode_k=spec_k, **serve_kw)
        if args.workdir:
            eng = build_checkpoint_engine(args.workdir, cfg, registry=reg)
        else:
            eng = build_smoke_engine(cfg, registry=reg)
        eng.warmup()
        return eng, reg

    def phase(eng, reg, prompts):
        batcher = ContinuousBatcher(eng, registry=reg).start()
        frontend = ServingFrontend(batcher, port=0)  # in-proc transport
        try:
            outcome = drive(
                frontend, prompts,
                concurrency=args.concurrency,
                max_new=args.max_new_tokens,
                temperature=args.temperature, top_k=args.top_k,
                http_url=None, timeout=args.timeout,
            )
        finally:
            batcher.close(drain=True)
            frontend.close()
        return outcome

    n = args.requests or (12 if args.smoke else 48)
    # Both engines (and their full AOT warmups) are built BEFORE the
    # clock starts: wall_s measures request driving only, comparable
    # with every other serve_bench record's.
    off_eng, off_reg = build(0)
    on_eng, on_reg = build(args.spec_decode)
    model_cfg = off_eng.model_cfg
    prompts = make_patterned_prompts(
        n, vocab=model_cfg.vocab_size, max_len=model_cfg.max_len,
        max_new=args.max_new_tokens,
    )
    t0 = time.perf_counter()
    off_out = phase(off_eng, off_reg, prompts)
    on_out = phase(on_eng, on_reg, prompts)
    wall = time.perf_counter() - t0

    def done(outcome):
        return [
            r for r in outcome["replies"] if r is not None and r[0] == 200
        ]

    errors = 2 * n - len(done(off_out)) - len(done(on_out))
    identical = len(done(off_out)) == n and len(done(on_out)) == n and all(
        a[1].get("tokens") == b[1].get("tokens")
        for a, b in zip(off_out["replies"], on_out["replies"])
    )

    def tpot_ms(reg, q):
        h = reg.histogram_summaries().get("serving/tpot")
        v = h and h.get(f"p{q}")
        return round(v * 1e3, 4) if v is not None else None

    def toks_per_s(outcome):
        toks = sum(len(r[1].get("tokens", ())) for r in done(outcome))
        return round(toks / outcome["wall_s"], 3) if outcome["wall_s"] \
            else None

    on_counters = on_reg.counter_values()
    req_steps = on_counters.get("serving/spec_request_steps", 0)
    drafted = on_counters.get("serving/spec_drafted_total", 0)
    accepted = on_counters.get("serving/spec_accepted_total", 0)
    acc_hist = on_reg.histogram_summaries().get(
        "serving/accepted_per_step"
    )
    off_tpot, on_tpot = tpot_ms(off_reg, 50), tpot_ms(on_reg, 50)
    recompiles = (
        off_eng.post_warmup_recompiles() + on_eng.post_warmup_recompiles()
    )
    rec = {
        "bench": "serve_spec",
        "backend": jax.default_backend(),
        "requests": n,
        "spec_k": args.spec_decode,
        "draft": "ngram",
        "max_new_tokens": args.max_new_tokens,
        "concurrency": args.concurrency,
        "temperature": args.temperature,
        "errors": errors,
        "wall_s": round(wall, 3),
        "tpot_off_p50_ms": off_tpot,
        "tpot_on_p50_ms": on_tpot,
        "tpot_speedup": (
            round(off_tpot / on_tpot, 3)
            if off_tpot and on_tpot else None
        ),
        "tok_per_s_off": toks_per_s(off_out),
        "tok_per_s_on": toks_per_s(on_out),
        "draft_hit_rate": (
            round(accepted / drafted, 4) if drafted else 0.0
        ),
        "accepted_per_step": (
            round((req_steps + accepted) / req_steps, 4)
            if req_steps else 0.0
        ),
        "accepted_per_step_p50": (
            acc_hist and acc_hist.get("p50")
        ),
        "tokens_identical": identical,
        "expected_compiles": on_eng.expected_compiles(),
        "post_warmup_recompiles": recompiles,
        "kv_block_size": serve_kw["kv_block_size"],
        "verified": n,
        "verify_ok": identical,
        "transport": "inproc",
    }
    rec["ok"] = bool(errors == 0 and identical and recompiles == 0)
    return rec


# Divergence floor for the serve_quant verdict: mean fraction of
# stream positions agreeing with the f32 twin — the same gate shape
# the int8 KV golden uses (first token exact, bounded divergence).
QUANT_AGREEMENT_FLOOR = 0.75


def run_quant_bench(args) -> dict:
    """--weight-dtype D (ISSUE 15): drive the SAME mixed-length
    prompts through two freshly built engines — weights served as
    loaded (f32), then weight-quantized to D via the precision
    registry — and bank one ``serve_quant`` record. The claims it
    carries, all measured: ``hbm_bytes_per_replica`` (quantized param
    bytes from ``engine.byte_breakdown``) with ``hbm_ratio_vs_f32``
    (the ~4x HBM-per-replica claim, the fleet-economics headline),
    ``tpot_speedup_quant`` / ``ttft_speedup_quant`` (f32 p50 / quant
    p50 — decode is memory-bound, so 1-byte weights buy TPOT on HBM
    rigs; ~1.0 where weights fit in cache), and the divergence verdict
    int8 KV established: ``first_token_exact`` over EVERY request plus
    ``stream_agreement`` >= QUANT_AGREEMENT_FLOOR, with zero
    post-warmup recompiles on both engines (the quantized tree warms
    the same AOT ladder)."""
    import jax

    from tensorflow_examples_tpu.serving.batcher import ContinuousBatcher
    from tensorflow_examples_tpu.serving.engine import ServeConfig
    from tensorflow_examples_tpu.serving.frontend import ServingFrontend
    from tensorflow_examples_tpu.telemetry.registry import MetricsRegistry

    serve_kw = dict(
        max_slots=args.max_slots,
        max_delay_s=0.002,
        request_timeout_s=args.timeout,
        kv_block_size=max(args.kv_block_size, 0),
        kv_dtype=args.kv_dtype,
    )
    if args.smoke:
        serve_kw.update(prefill_bucket_floor=16, kv_bucket_floor=32)

    def build(weight_dtype: str):
        reg = MetricsRegistry()
        cfg = ServeConfig(weight_dtype=weight_dtype, **serve_kw)
        if args.workdir:
            eng = build_checkpoint_engine(args.workdir, cfg, registry=reg)
        else:
            eng = build_smoke_engine(cfg, registry=reg)
        eng.warmup()
        return eng, reg

    def phase(eng, reg, prompts):
        batcher = ContinuousBatcher(eng, registry=reg).start()
        frontend = ServingFrontend(batcher, port=0)  # in-proc transport
        try:
            outcome = drive(
                frontend, prompts,
                concurrency=args.concurrency,
                max_new=args.max_new_tokens,
                temperature=args.temperature, top_k=args.top_k,
                http_url=None, timeout=args.timeout,
            )
        finally:
            batcher.close(drain=True)
            frontend.close()
        return outcome

    n = args.requests or (12 if args.smoke else 48)
    # Both engines (and their AOT warmups) are built before the clock
    # starts: wall_s measures request driving only.
    f32_eng, f32_reg = build("")
    q_eng, q_reg = build(args.weight_dtype)
    model_cfg = f32_eng.model_cfg
    prompts = make_prompts(
        n, vocab=model_cfg.vocab_size, max_len=model_cfg.max_len,
        max_new=args.max_new_tokens,
    )
    t0 = time.perf_counter()
    f32_out = phase(f32_eng, f32_reg, prompts)
    q_out = phase(q_eng, q_reg, prompts)
    wall = time.perf_counter() - t0

    def done(outcome):
        return [
            r for r in outcome["replies"] if r is not None and r[0] == 200
        ]

    errors = 2 * n - len(done(f32_out)) - len(done(q_out))
    # first_token_exact is a NUMERICS verdict over the pairs that both
    # completed — a transport error/timeout is already counted in
    # ``errors`` (which fails ``ok`` on its own) and must not
    # masquerade as quantization divergence.
    first_exact = True
    agreements = []
    for a, b in zip(f32_out["replies"], q_out["replies"]):
        if a is None or b is None or a[0] != 200 or b[0] != 200:
            continue
        ta, tb = a[1].get("tokens") or [], b[1].get("tokens") or []
        if not ta or not tb or ta[0] != tb[0]:
            first_exact = False
        width = max(len(ta), len(tb))
        if width:
            agreements.append(
                sum(x == y for x, y in zip(ta, tb)) / width
            )
    agreement = (
        round(sum(agreements) / len(agreements), 4)
        if agreements else 0.0
    )

    def p50_ms(reg, hist):
        h = reg.histogram_summaries().get(f"serving/{hist}")
        v = h and h.get("p50")
        return round(v * 1e3, 4) if v is not None else None

    def speedup(f32_v, q_v):
        return round(f32_v / q_v, 3) if f32_v and q_v else None

    def toks_per_s(outcome):
        toks = sum(len(r[1].get("tokens", ())) for r in done(outcome))
        return round(toks / outcome["wall_s"], 3) if outcome["wall_s"] \
            else None

    bb_q = q_eng.byte_breakdown()
    bb_f = f32_eng.byte_breakdown()
    tpot_f, tpot_q = p50_ms(f32_reg, "tpot"), p50_ms(q_reg, "tpot")
    ttft_f, ttft_q = p50_ms(f32_reg, "ttft"), p50_ms(q_reg, "ttft")
    recompiles = (
        f32_eng.post_warmup_recompiles() + q_eng.post_warmup_recompiles()
    )
    rec = {
        "bench": "serve_quant",
        "backend": jax.default_backend(),
        "requests": n,
        "weight_dtype": args.weight_dtype,
        "weight_bits": bb_q["weight_bits"],
        "max_new_tokens": args.max_new_tokens,
        "concurrency": args.concurrency,
        "temperature": args.temperature,
        "errors": errors,
        "wall_s": round(wall, 3),
        "tpot_f32_p50_ms": tpot_f,
        "tpot_quant_p50_ms": tpot_q,
        "tpot_speedup_quant": speedup(tpot_f, tpot_q),
        "ttft_f32_p50_ms": ttft_f,
        "ttft_quant_p50_ms": ttft_q,
        "ttft_speedup_quant": speedup(ttft_f, ttft_q),
        "tok_per_s_f32": toks_per_s(f32_out),
        "tok_per_s_quant": toks_per_s(q_out),
        "hbm_bytes_per_replica": bb_q["params_bytes"],
        "hbm_bytes_per_replica_f32": bb_f["params_bytes"],
        "hbm_ratio_vs_f32": (
            round(bb_q["params_bytes"] / bb_f["params_bytes"], 4)
            if bb_f["params_bytes"] else None
        ),
        "first_token_exact": first_exact,
        "stream_agreement": agreement,
        "expected_compiles": q_eng.expected_compiles(),
        "post_warmup_recompiles": recompiles,
        "kv_block_size": serve_kw["kv_block_size"],
        "kv_dtype": args.kv_dtype,
        "verified": n,
        "verify_ok": bool(
            first_exact and agreement >= QUANT_AGREEMENT_FLOOR
        ),
        "transport": "inproc",
    }
    rec["ok"] = bool(
        errors == 0
        and rec["verify_ok"]
        and recompiles == 0
    )
    return rec


# ---------------------------------------------------------------------------
# Replayable traffic model (ISSUE 13 tentpole (4)): "millions of
# users" as a seeded, deterministic scenario.

# Flash-crowd acceptance budget: interactive TTFT p95 during the flash
# window must stay within this multiple of the steady-state window's.
# The golden's 2x — the whole point of SLO classes + brownout is that
# a 3x arrival spike lands on batch, not on interactive latency.
FLASH_TTFT_BUDGET = 2.0


def traffic_rate_multiplier(mode: str, frac: float,
                            flash_factor: float) -> float:
    """Arrival-rate multiplier at request-index fraction ``frac`` of
    the run — index-based, so the shape is exact for any n and fully
    deterministic."""
    if mode == "flash":
        # Steady -> 3x flash crowd -> steady.
        return flash_factor if 0.35 <= frac < 0.70 else 1.0
    if mode == "ramp":
        # Quiet start -> sustained peak (the scale-up forcing
        # function) -> cool-down (lets the autoscaler drain back).
        if frac < 0.10:
            return 0.3
        if frac < 0.70:
            return 1.0
        return 0.2
    if mode == "diurnal":
        # Two "days" of sinusoidal load.
        import math

        return 0.25 + 0.75 * (
            0.5 - 0.5 * math.cos(2 * math.pi * 2 * frac)
        )
    raise ValueError(f"unknown traffic mode {mode!r}")


def traffic_phase(mode: str, frac: float) -> str:
    if mode == "flash":
        if frac < 0.35:
            return "steady"
        return "flash" if frac < 0.70 else "recover"
    if mode == "ramp":
        if frac < 0.10:
            return "low"
        return "peak" if frac < 0.70 else "cool"
    return "diurnal"


def make_traffic_schedule(mode: str, n: int, *, rate: float,
                          vocab: int, max_len: int, max_new: int,
                          batch_fraction: float = 0.3,
                          flash_factor: float = 3.0,
                          seed: int = 0) -> list[dict]:
    """A seeded OPEN-LOOP arrival schedule: n requests with exponential
    inter-arrival times at the mode's rate profile, heavy-tail
    (lognormal) prompt lengths, and a seeded interactive/batch class
    mix. Same seed -> byte-identical schedule, so every scenario —
    including a flash crowd composed with a chaos fault spec — replays
    exactly."""
    import numpy as np

    rng = np.random.default_rng(seed)
    cap = max(4, max_len - max_new)
    median = max(3, cap // 6)
    schedule = []
    t = 0.0
    for i in range(n):
        frac = i / max(n - 1, 1)
        r = rate * traffic_rate_multiplier(mode, frac, flash_factor)
        t += float(rng.exponential(1.0 / r))
        ln = int(np.clip(
            rng.lognormal(mean=np.log(median), sigma=0.9), 1, cap
        ))
        schedule.append({
            "t": t,
            "prompt": [int(x) for x in rng.integers(0, vocab, (ln,))],
            "slo": (
                "batch" if rng.random() < batch_fraction
                else "interactive"
            ),
            "seed": i,
            "max_new": max_new,
            "phase": traffic_phase(mode, frac),
        })
    return schedule


def drive_open_loop(frontend, schedule, *, http_url: str | None,
                    timeout: float, temperature: float = 0.0,
                    top_k: int = 0, workers: int | None = None) -> dict:
    """OPEN-loop driver: requests fire at their scheduled arrival time
    whether or not earlier ones resolved — the load does not politely
    back off when the fleet slows down, which is exactly what a flash
    crowd doesn't do. ``workers`` defaults to one per request (true
    open loop); an explicit cap can serialize arrivals once every
    worker is tied up in a slow request, so late fires (> 50 ms behind
    schedule) are counted in the outcome's ``late_fires`` rather than
    silently skewing the phase-labeled percentiles. Returns
    index-aligned replies, client wall times, and each request's fire
    time (wall clock, for the resize-window percentile)."""
    import concurrent.futures as cf

    n = len(schedule)
    if workers is None:
        workers = min(n, 1024)
    replies: list = [None] * n
    client_s: list = [None] * n
    fired_unix: list = [None] * n
    late = [0]
    late_lock = threading.Lock()

    def fire(i: int, ev: dict) -> None:
        if (time.perf_counter() - t0) - ev["t"] > 0.05:
            with late_lock:
                late[0] += 1
        body = {
            "prompt": ev["prompt"],
            "max_new_tokens": ev["max_new"],
            "temperature": temperature,
            "top_k": top_k,
            "seed": ev["seed"],
            "slo": ev["slo"],
        }
        fired_unix[i] = time.time()
        t_req = time.perf_counter()
        if http_url is not None:
            replies[i] = _post_json(http_url, body, timeout)
        else:
            replies[i] = frontend.handle_request(body, kind="generate")
        client_s[i] = time.perf_counter() - t_req

    t0 = time.perf_counter()
    with cf.ThreadPoolExecutor(max_workers=workers) as pool:
        for i, ev in enumerate(schedule):
            delay = ev["t"] - (time.perf_counter() - t0)
            if delay > 0:
                time.sleep(delay)
            pool.submit(fire, i, ev)
    if late[0]:
        print(
            f"# open-loop driver: {late[0]}/{n} requests fired "
            ">50ms behind schedule (worker saturation)",
            file=sys.stderr,
        )
    return {
        "replies": replies,
        "client_s": client_s,
        "fired_unix": fired_unix,
        "late_fires": late[0],
        "wall_s": time.perf_counter() - t0,
    }


def _stream_matches(reply: dict, ref: list) -> bool:
    """Token-identity under brownout: a level-2-capped stream is a
    PREFIX of the reference; anything else must match exactly."""
    toks = reply.get("tokens") or []
    if reply.get("truncated") == "brownout":
        return bool(toks) and toks == ref[: len(toks)]
    return toks == ref


def _class_values(outcome, schedule, field: str, *, slo: str,
                  phases=None) -> list:
    return [
        r[1].get(field)
        for r, ev in zip(outcome["replies"], schedule)
        if r is not None and r[0] == 200 and ev["slo"] == slo
        and (phases is None or ev["phase"] in phases)
    ]


def run_traffic_bench(args) -> dict:
    """``--traffic {ramp,flash,diurnal}`` (ISSUE 13): the replayable
    million-user traffic model, driven open-loop against a
    brownout-enabled fleet, banking one ``serve_traffic`` record.

    * ``flash`` — a fixed fleet (default 2 replicas) under a seeded
      3x flash crowd. The record's headline claims: all shedding lands
      on the batch class (``shed_interactive == 0``), interactive TTFT
      p95 during the flash stays within ``FLASH_TTFT_BUDGET`` x the
      steady window's, every delivered stream token-identical (prefix
      under a brownout cap) to ``reference_generate``, zero post-warmup
      recompiles fleet-wide, and the brownout ladder fully cleared by
      the end of the run.
    * ``ramp`` — a 1-replica fleet + the telemetry-driven autoscaler
      (supervisor.Autoscaler over in-proc replicas). The record stamps
      ``scale_up_latency_s`` (decision -> green -> routed),
      ``p95_during_resize_ms``, peak replica count, and drain-first
      scale-down back to min with zero lost requests.
    * ``diurnal`` — two sinusoidal load "days" over the fixed fleet;
      the long-horizon stability shape the chaos tier can compose
      with.
    """
    import jax

    from tensorflow_examples_tpu.serving.router import (
        Router,
        RouterConfig,
        RouterFrontend,
    )

    mode = args.traffic
    kv_block = args.kv_block_size if args.kv_block_size >= 0 else 16
    serve_kw = dict(
        max_slots=args.max_slots,
        max_delay_s=0.002,
        request_timeout_s=args.timeout,
        kv_block_size=kv_block,
        kv_dtype=args.kv_dtype,
        # The whole point of the traffic tier: overload is a
        # first-class input. Ladder thresholds scale with the slot
        # count; the hold is short so a CI-scale run can walk the
        # ladder up AND back down.
        brownout=True,
        brownout_queue_hi=max(4, 2 * args.max_slots),
        brownout_hold_s=0.25,
        brownout_max_new_tokens=max(2, args.max_new_tokens // 2),
    )
    if args.smoke:
        serve_kw.update(prefill_bucket_floor=16, kv_bucket_floor=32)

    if mode == "ramp":
        # The ramp's peak must actually OUTRUN one replica or there is
        # nothing to autoscale: the smoke default arrives well above a
        # single smoke engine's throughput, so the queue builds, the
        # ladder engages, and the scale-up golden has a forcing
        # function (flash/diurnal run at a fixed-fleet rate instead).
        n = args.requests or (240 if args.smoke else 400)
        rate = args.rate or (300.0 if args.smoke else 50.0)
    else:
        n = args.requests or (60 if args.smoke else 400)
        rate = args.rate or (25.0 if args.smoke else 50.0)
    verify = args.verify if args.verify >= 0 else (3 if args.smoke else 0)

    t0 = time.perf_counter()
    autoscaler = supervisor = None
    spawned: list = []
    # Telemetry of replicas the autoscaler scaled DOWN mid-run: stop()
    # tears the engine/batcher away, so their recompiles, brownout
    # events, and counters are snapshotted here first — otherwise a
    # drained replica's numbers silently vanish from the record (and
    # "zero post-warmup recompiles fleet-wide" could pass falsely).
    harvest: dict = {"recompiles": 0, "events": [], "counters": {}}
    if mode == "ramp":
        from tensorflow_examples_tpu.serving.chaos import InProcReplica
        from tensorflow_examples_tpu.serving.engine import ServeConfig
        from tensorflow_examples_tpu.serving.supervisor import (
            Autoscaler,
            AutoscalerConfig,
            Supervisor,
        )
        from tensorflow_examples_tpu.telemetry.registry import (
            MetricsRegistry,
        )

        def build_engine():
            reg = MetricsRegistry()
            cfg = ServeConfig(**serve_kw)
            if args.workdir:
                return build_checkpoint_engine(
                    args.workdir, cfg, registry=reg
                )
            return build_smoke_engine(cfg, registry=reg)

        class _HarvestingReplica(InProcReplica):
            def stop(self):
                eng, batcher = self.engine, self.batcher
                if eng is not None:
                    harvest["recompiles"] += \
                        eng.post_warmup_recompiles()
                    for k, v in eng.registry.counter_values().items():
                        harvest["counters"][k] = \
                            harvest["counters"].get(k, 0) + v
                if batcher is not None:
                    harvest["events"].extend(batcher._overload.events)
                super().stop()

        first = _HarvestingReplica(build_engine, replica_id=0).start()
        spawned.append(first)
        router = Router(
            [first.url],
            cfg=RouterConfig(
                probe_interval_s=0.1, request_timeout_s=args.timeout,
            ),
        ).start()
        supervisor = Supervisor(
            router, [first], poll_s=0.25, health_stall_s=15.0,
        ).start()

        def spawn(idx):
            rep = _HarvestingReplica(
                build_engine, replica_id=idx
            ).start()
            spawned.append(rep)
            return rep

        autoscaler = Autoscaler(
            router,
            supervisor,
            spawn,
            cfg=AutoscalerConfig(
                min_replicas=1,
                max_replicas=args.max_replicas,
                target_queue_depth=args.target_queue,
                hold_s=0.4,
                scale_down_idle_s=1.0,
                drain_timeout_s=args.timeout,
                warm_timeout_s=300.0,
                evaluate_every_s=0.15,
            ),
        ).start()
        engines = lambda: [  # noqa: E731 - tiny accessor
            rep.engine for rep in spawned if rep.engine is not None
        ]
        regs = lambda: [  # noqa: E731
            rep.engine.registry for rep in spawned
            if rep.engine is not None
        ]
        batchers = lambda: [  # noqa: E731
            rep.batcher for rep in spawned if rep.batcher is not None
        ]
        n_initial = 1
    else:
        replicas = build_replica_stacks(args, serve_kw, args.replicas)
        router = Router(
            [f"http://127.0.0.1:{fe.port}" for _, _, fe, _ in replicas],
            cfg=RouterConfig(
                probe_interval_s=0.1, request_timeout_s=args.timeout,
            ),
        ).start()
        engines = lambda: [e for e, _, _, _ in replicas]  # noqa: E731
        regs = lambda: [r for _, _, _, r in replicas]  # noqa: E731
        batchers = lambda: [b for _, b, _, _ in replicas]  # noqa: E731
        n_initial = args.replicas
    rfront = RouterFrontend(router, port=0).start()
    warmup_s = time.perf_counter() - t0
    model_cfg = engines()[0].model_cfg
    schedule = make_traffic_schedule(
        mode, n, rate=rate, vocab=model_cfg.vocab_size,
        max_len=model_cfg.max_len, max_new=args.max_new_tokens,
        batch_fraction=args.batch_fraction,
        flash_factor=args.flash_factor, seed=args.traffic_seed,
    )
    print(
        f"# traffic={mode} n={n} rate={rate}/s "
        f"batch_fraction={args.batch_fraction} over "
        f"{n_initial} replica(s), warm in {warmup_s:.1f}s",
        file=sys.stderr,
    )

    # Sample the fleet size during the drive (ramp's replicas_peak).
    peak = [len(router.replicas)]
    sampling = threading.Event()

    def sampler():
        while not sampling.is_set():
            peak[0] = max(peak[0], len(router.replicas))
            time.sleep(0.05)

    sampler_thread = threading.Thread(target=sampler, daemon=True)
    sampler_thread.start()

    try:
        outcome = drive_open_loop(
            None, schedule, http_url=rfront.url("/generate"),
            timeout=args.timeout, temperature=args.temperature,
            top_k=args.top_k,
        )
        # Let the ladder walk back down (and, in ramp mode, the
        # autoscaler drain back to min) before the verdict: "engages
        # AND fully clears within the run" is the acceptance claim.
        settle_deadline = time.monotonic() + (
            30.0 if args.smoke else 120.0
        )
        while time.monotonic() < settle_deadline:
            levels = [b.brownout_level for b in batchers()]
            scaled_in = (
                autoscaler is None
                or (len(router.replicas) <= 1
                    and not autoscaler.acting())
            )
            if all(lv == 0 for lv in levels) and scaled_in:
                break
            time.sleep(0.2)
        # Verify the first --verify completed interactive streams
        # against the unbatched reference (prefix-identical under a
        # brownout cap).
        verify_ok = True
        checked = 0
        ref_engine = engines()[0]
        for i, ev in enumerate(schedule):
            if checked >= verify:
                break
            reply = outcome["replies"][i]
            if reply is None or reply[0] != 200:
                continue
            checked += 1
            ref = ref_engine.reference_generate(
                ev["prompt"], max_new=ev["max_new"], seed=ev["seed"],
                temperature=args.temperature, top_k=args.top_k,
            )
            if not _stream_matches(reply[1], ref):
                verify_ok = False
                print(
                    f"# VERIFY FAIL traffic req {i}: "
                    f"{reply[1].get('tokens')} !~ reference {ref}",
                    file=sys.stderr,
                )
        brownout_events = list(harvest["events"])
        for b in batchers():
            brownout_events.extend(b._overload.events)
        # A scaled-down replica's frozen level is moot (it was drained
        # and removed); "cleared" is about the LIVE fleet.
        brownout_levels = [b.brownout_level for b in batchers()]
        recompiles = harvest["recompiles"] + sum(
            e.post_warmup_recompiles() for e in engines()
        )
        counter_sum: dict = dict(harvest["counters"])
        for reg in regs():
            for k, v in reg.counter_values().items():
                counter_sum[k] = counter_sum.get(k, 0) + v
    finally:
        sampling.set()
        sampler_thread.join(timeout=2)
        rfront.close()
        if autoscaler is not None:
            autoscaler.close()
        if supervisor is not None:
            supervisor.close()
        router.close()
        if mode == "ramp":
            for rep in spawned:
                rep.close()
        else:
            for _, batcher, fe, _ in replicas:
                batcher.close(drain=True)
                fe.close()

    tally = tally_replies(outcome["replies"])
    by_class = {
        slo: [
            r for r, ev in zip(outcome["replies"], schedule)
            if ev["slo"] == slo and r is not None
        ]
        for slo in ("interactive", "batch")
    }
    shed_by_class = {
        slo: sum(1 for r in rs if r[0] == 503)
        for slo, rs in by_class.items()
    }
    n_by_class = {
        slo: sum(1 for ev in schedule if ev["slo"] == slo)
        for slo in ("interactive", "batch")
    }
    steady_p95 = _pct_from_values(
        _class_values(outcome, schedule, "ttft_s",
                      slo="interactive", phases=("steady",)), 95,
    )
    flash_p95 = _pct_from_values(
        _class_values(outcome, schedule, "ttft_s",
                      slo="interactive", phases=("flash",)), 95,
    )
    # Resize-window latency (ramp): TTFT p95 of requests fired while a
    # scale action was in flight (scale-up: decision -> green; plus a
    # 2s tail after any event while dispatch redistributes).
    resize_windows = []
    if autoscaler is not None:
        up_times = [
            t for t, verb, _ in autoscaler.events if verb == "scale_up"
        ]
        for t, lat in zip(up_times, autoscaler.scale_up_latencies):
            resize_windows.append((t - lat, t + 2.0))
        for t, verb, _ in autoscaler.events:
            if verb == "scale_down":
                resize_windows.append((t, t + 2.0))
    resize_ttfts = [
        r[1].get("ttft_s")
        for r, fu in zip(outcome["replies"], outcome["fired_unix"])
        if r is not None and r[0] == 200 and fu is not None
        and any(a <= fu <= b for a, b in resize_windows)
    ]
    scale_up_lat = (
        max(autoscaler.scale_up_latencies)
        if autoscaler is not None and autoscaler.scale_up_latencies
        else None
    )
    brownout_max_level = max(
        (to for _, _, to, _ in brownout_events), default=0
    )
    rec = {
        "bench": "serve_traffic",
        "traffic": mode,
        "backend": jax.default_backend(),
        "seed": args.traffic_seed,
        "replicas": n_initial,
        "replicas_peak": peak[0],
        "replicas_final": len(router.replicas),
        "requests": n,
        "completed": tally["completed"],
        "errors": tally["errors"],
        "shed_total": tally["shed_total"],
        "rejected_total": tally["rejected_total"],
        "transport_errors": tally["transport_errors"],
        "shed_interactive": shed_by_class["interactive"],
        "shed_batch": shed_by_class["batch"],
        "shed_rate_interactive": round(
            shed_by_class["interactive"]
            / max(n_by_class["interactive"], 1), 4
        ),
        "shed_rate_batch": round(
            shed_by_class["batch"] / max(n_by_class["batch"], 1), 4
        ),
        "preempted_batch": int(
            counter_sum.get("serving/preempted_total", 0)
        ),
        "rate_req_per_s": rate,
        "flash_factor": args.flash_factor,
        "batch_fraction": args.batch_fraction,
        "wall_s": round(outcome["wall_s"], 3),
        "late_fires": outcome["late_fires"],
        "warmup_s": round(warmup_s, 3),
        "ttft_p50_interactive_ms": _pct_from_values(
            _class_values(outcome, schedule, "ttft_s",
                          slo="interactive"), 50),
        "ttft_p95_interactive_ms": _pct_from_values(
            _class_values(outcome, schedule, "ttft_s",
                          slo="interactive"), 95),
        "ttft_p95_batch_ms": _pct_from_values(
            _class_values(outcome, schedule, "ttft_s", slo="batch"),
            95),
        "e2e_p95_interactive_ms": _pct_from_values(
            _class_values(outcome, schedule, "total_s",
                          slo="interactive"), 95),
        "e2e_p95_batch_ms": _pct_from_values(
            _class_values(outcome, schedule, "total_s", slo="batch"),
            95),
        "steady_ttft_p95_interactive_ms": steady_p95,
        "flash_ttft_p95_interactive_ms": flash_p95,
        "flash_vs_steady_ttft": (
            round(flash_p95 / steady_p95, 3)
            if steady_p95 and flash_p95 else None
        ),
        "flash_ttft_budget": FLASH_TTFT_BUDGET,
        "brownout_max_level": brownout_max_level,
        "brownout_transitions": len(brownout_events),
        "brownout_engaged": bool(brownout_events),
        "brownout_cleared": bool(
            all(lv == 0 for lv in brownout_levels)
        ),
        "scale_ups": (
            int(len(autoscaler.scale_up_latencies))
            if autoscaler is not None else 0
        ),
        "scale_downs": (
            int(sum(1 for _, verb, _ in autoscaler.events
                    if verb == "scale_down"))
            if autoscaler is not None else 0
        ),
        "scale_up_latency_s": (
            round(scale_up_lat, 3) if scale_up_lat else None
        ),
        "p95_during_resize_ms": _pct_from_values(resize_ttfts, 95),
        "post_warmup_recompiles": recompiles,
        "verified": checked,
        "verify_ok": verify_ok,
        "kv_block_size": kv_block,
        "transport": "router-http",
    }
    if mode == "flash":
        rec["ok"] = bool(
            rec["errors"] == 0
            and rec["shed_interactive"] == 0
            and verify_ok
            and recompiles == 0
            and rec["brownout_cleared"]
            and (
                rec["flash_vs_steady_ttft"] is None
                or rec["flash_vs_steady_ttft"] <= FLASH_TTFT_BUDGET
            )
        )
    elif mode == "ramp":
        rec["ok"] = bool(
            rec["errors"] == 0
            and verify_ok
            and recompiles == 0
            and rec["scale_ups"] >= 1
            and rec["replicas_peak"] >= min(args.max_replicas, 2)
            and rec["replicas_final"] <= 1
            and rec["scale_up_latency_s"] is not None
            and rec["brownout_engaged"]
            and rec["brownout_cleared"]
        )
    else:
        rec["ok"] = bool(
            rec["errors"] == 0
            and verify_ok
            and recompiles == 0
            and rec["brownout_cleared"]
        )
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny model, 20 requests, verify 3 (tier-1 CI)")
    ap.add_argument("--workdir", default="",
                    help="serve the latest checkpoint in this run dir")
    ap.add_argument("--router", action="store_true",
                    help="drive --replicas in-proc serving stacks "
                         "through serving/router.py (ISSUE 8)")
    ap.add_argument("--chaos", action="store_true",
                    help="ISSUE 10: supervised in-proc fleet + injected "
                         "fault schedule; banks the serve_chaos "
                         "availability record (error_rate, failovers, "
                         "p95-vs-baseline)")
    ap.add_argument("--spec-decode", type=int, default=0, metavar="K",
                    help="ISSUE 11: A/B the same prompt-like prompts "
                         "with speculation off vs on (K drafts per "
                         "step); banks the serve_spec record "
                         "(tpot_speedup, draft_hit_rate, "
                         "accepted_per_step, tokens_identical)")
    ap.add_argument("--affinity", choices=("on", "off", "ab"),
                    default="on",
                    help="ISSUE 12 (--router): prefix-affinity dispatch"
                         " on/off, or 'ab' — drive the same shared-"
                         "prefix traffic through an affinity-off fleet "
                         "then an affinity-on one and bank the "
                         "serve_affinity A/B record "
                         "(prefix_hit_rate_affinity vs "
                         "prefix_hit_rate_no_affinity, shared-vs-cold "
                         "TTFT)")
    ap.add_argument("--traffic", choices=("ramp", "flash", "diurnal"),
                    default="",
                    help="ISSUE 13: replayable open-loop traffic model "
                         "against a brownout-enabled fleet. 'flash' = "
                         "3x flash crowd over a fixed fleet (per-class "
                         "shed/latency claims); 'ramp' = the "
                         "autoscaler golden (1->max->1, drain-first); "
                         "'diurnal' = two sinusoidal load days. Banks "
                         "the serve_traffic record")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="traffic: steady-state arrival rate req/s "
                         "(default 25 smoke / 50)")
    ap.add_argument("--batch-fraction", type=float, default=0.3,
                    help="traffic: fraction of arrivals in the batch "
                         "SLO class")
    ap.add_argument("--flash-factor", type=float, default=3.0,
                    help="traffic flash: arrival-rate multiple during "
                         "the flash window")
    ap.add_argument("--traffic-seed", type=int, default=0,
                    help="traffic: schedule seed (same seed = "
                         "byte-identical scenario)")
    ap.add_argument("--max-replicas", type=int, default=3,
                    help="traffic ramp: autoscaler ceiling")
    ap.add_argument("--target-queue", type=float, default=3.0,
                    help="traffic ramp: autoscaler queue-depth target "
                         "per replica")
    ap.add_argument("--fault-spec", default="",
                    help="serve fault schedule for --chaos "
                         "(utils/faults.py grammar, e.g. 'crash@1:4,"
                         "badhealth@0:3'); default: crash replica 1 "
                         "mid-decode")
    ap.add_argument("--replicas", type=int, default=0,
                    help="replica count (default: 2 for --router, "
                         "3 for --chaos)")
    ap.add_argument("--kv-block-size", type=int, default=-1,
                    help="paged KV block size; -1 = dense pool "
                         "(--router defaults to 16)")
    ap.add_argument("--kv-dtype", default="",
                    help="'' (cache dtype), 'int8', or 'fp8' (paged "
                         "only; fp8 needs backend float8 support)")
    ap.add_argument("--weight-dtype", default="",
                    choices=("", "int8", "fp8"),
                    help="ISSUE 15: A/B the same prompts through an "
                         "f32 engine and a weight-quantized one; "
                         "banks the serve_quant record "
                         "(tpot_speedup_quant, hbm_bytes_per_replica, "
                         "first_token_exact + stream_agreement)")
    ap.add_argument("--requests", type=int, default=0,
                    help="request count (default: 20 smoke / 64 otherwise)")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--max-new-tokens", type=int, default=8)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--max-slots", type=int, default=8)
    ap.add_argument("--verify", type=int, default=-1,
                    help="replay N requests unbatched and compare "
                         "token-for-token (-1: 3 in smoke, 0 otherwise)")
    ap.add_argument("--inproc", action="store_true",
                    help="skip the HTTP hop (engine+batcher cost only)")
    ap.add_argument("--timeout", type=float, default=120.0,
                    help="per-request client timeout (seconds)")
    ap.add_argument("--out", default="", help="bank the record here")
    ap.add_argument("--trace-out", default="",
                    help="ISSUE 18: land the run's kept traces as "
                         "schema-v13 kind=\"trace\" JSONL here "
                         "(plain + --router modes); the record banks "
                         "trace_coverage / slow_trace_count either way")
    ap.add_argument("--slo", action="store_true",
                    help="ISSUE 19: run the SLO AlertEngine + canary "
                         "prober over the run (plain + --router "
                         "modes); the record banks alert_count / "
                         "probe_success_rate / error_budget_remaining "
                         "and ok additionally requires alert_count==0")
    args = ap.parse_args(argv)
    if not args.smoke and not args.workdir:
        ap.error("pick a target: --smoke or --workdir DIR")
    if args.affinity == "ab" and not args.router:
        ap.error("--affinity ab is a --router A/B mode")
    if args.slo and args.inproc:
        ap.error("--slo needs the HTTP frontend for black-box probes "
                 "(drop --inproc)")
    if args.slo and (args.chaos or args.traffic or args.weight_dtype
                     or args.spec_decode > 0 or args.affinity == "ab"):
        ap.error("--slo composes with the plain and --router modes "
                 "only")
    modes = [name for name, on in (
        ("--weight-dtype", bool(args.weight_dtype)),
        ("--spec-decode", args.spec_decode > 0),
        ("--traffic", bool(args.traffic)),
        ("--chaos", args.chaos),
        ("--router", args.router),
    ) if on]
    if len(modes) > 1:
        # Each mode banks its own record; silently running only one
        # would label the output as measuring something it didn't.
        ap.error(f"pick ONE bench mode: {' + '.join(modes)} don't "
                 "compose")
    if args.replicas <= 0:
        args.replicas = 3 if args.chaos else 2

    if args.traffic:
        rec = run_traffic_bench(args)
        print(json.dumps(rec))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(rec, f, indent=1)
                f.write("\n")
        return 0 if rec["ok"] else 1

    if args.router and args.affinity == "ab":
        rec = run_affinity_bench(args)
        print(json.dumps(rec))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(rec, f, indent=1)
                f.write("\n")
        return 0 if rec["ok"] else 1

    if args.weight_dtype:
        rec = run_quant_bench(args)
        print(json.dumps(rec))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(rec, f, indent=1)
                f.write("\n")
        return 0 if rec["ok"] else 1

    if args.spec_decode > 0:
        rec = run_spec_bench(args)
        print(json.dumps(rec))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(rec, f, indent=1)
                f.write("\n")
        return 0 if rec["ok"] else 1

    if args.chaos:
        rec = run_chaos_bench(args)
        takeover = rec.pop("takeover", None)
        print(json.dumps(rec))
        if takeover is not None:
            print(json.dumps(takeover))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(rec, f, indent=1)
                f.write("\n")
            if takeover is not None:
                root, ext = os.path.splitext(args.out)
                tko_out = f"{root}_takeover{ext or '.json'}"
                with open(tko_out, "w") as f:
                    json.dump(takeover, f, indent=1)
                    f.write("\n")
        return 0 if (
            rec["ok"] and (takeover is None or takeover["ok"])
        ) else 1

    if args.router:
        rec = run_router_bench(args)
        print(json.dumps(rec))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(rec, f, indent=1)
                f.write("\n")
        return 0 if rec["ok"] else 1

    import jax

    from tensorflow_examples_tpu.serving.batcher import ContinuousBatcher
    from tensorflow_examples_tpu.serving.engine import ServeConfig
    from tensorflow_examples_tpu.serving.frontend import ServingFrontend
    from tensorflow_examples_tpu.telemetry.registry import MetricsRegistry

    registry = MetricsRegistry()  # private: the record owns its counters
    serve_cfg = ServeConfig(
        max_slots=args.max_slots,
        max_delay_s=0.002,
        request_timeout_s=args.timeout,
        kv_block_size=max(args.kv_block_size, 0),
        kv_dtype=args.kv_dtype,
        **(dict(prefill_bucket_floor=16, kv_bucket_floor=32)
           if args.smoke else {}),
    )
    if args.workdir:
        engine = build_checkpoint_engine(
            args.workdir, serve_cfg, registry=registry
        )
    else:
        engine = build_smoke_engine(serve_cfg, registry=registry)

    n = args.requests or (20 if args.smoke else 64)
    verify = args.verify if args.verify >= 0 else (3 if args.smoke else 0)
    prompts = make_prompts(
        n,
        vocab=engine.model_cfg.vocab_size,
        max_len=engine.model_cfg.max_len,
        max_new=args.max_new_tokens,
    )

    t0 = time.perf_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0
    print(
        f"# warm: {engine.expected_compiles()} programs in {warmup_s:.1f}s "
        f"(prefill ladder {engine.prefill_ladder}, "
        f"kv ladder {engine.kv_ladder})",
        file=sys.stderr,
    )

    batcher = ContinuousBatcher(engine, registry=registry).start()
    frontend = ServingFrontend(batcher, port=0)
    http_url = None
    if not args.inproc:
        frontend.start()
        http_url = frontend.url("/generate")
    # Client-originated tracing (ISSUE 18): a closed-loop bench keeps
    # EVERY trace (sample_fraction=1.0 — it is measuring, not
    # serving production traffic), so trace_coverage banks at 1.0 on
    # a healthy run and the slow-trace count is exhaustive.
    from tensorflow_examples_tpu.telemetry import tracing

    recorder = tracing.TraceRecorder(
        registry=registry, path=args.trace_out or None,
        sample_fraction=1.0,
    )
    try:
        outcome = drive(
            frontend, prompts,
            concurrency=args.concurrency, max_new=args.max_new_tokens,
            temperature=args.temperature, top_k=args.top_k,
            http_url=http_url, timeout=args.timeout,
            trace_recorder=recorder,
        )
        verify_ok = True
        for i in range(min(verify, n)):
            reply = outcome["replies"][i]
            if reply is None or reply[0] != 200:
                verify_ok = False
                continue
            ref = engine.reference_generate(
                prompts[i], max_new=args.max_new_tokens, seed=i,
                temperature=args.temperature, top_k=args.top_k,
            )
            if reply[1]["tokens"] != ref:
                verify_ok = False
                print(
                    f"# VERIFY FAIL req {i}: served {reply[1]['tokens']} "
                    f"!= reference {ref}",
                    file=sys.stderr,
                )
        # The record is assembled BEFORE the --slo probe phase: probe
        # traffic must never pollute the banked percentiles (ISSUE 19).
        rec = bench_record(
            engine, registry, outcome, prompts,
            concurrency=args.concurrency, verified=min(verify, n),
            verify_ok=verify_ok, backend=jax.default_backend(),
        )
        if args.slo:
            from tensorflow_examples_tpu.serving.prober import (
                CanaryProber,
            )
            from tensorflow_examples_tpu.telemetry.slo import AlertEngine

            # The SLO stack owns its own registry so probe/ and
            # alert/ instruments never mix into the bench record's.
            alerts = AlertEngine(registry=MetricsRegistry())
            for r in outcome["replies"]:  # organic feed first
                body = r[1] if r is not None and r[0] == 200 else {}
                alerts.observe(
                    "interactive",
                    ttft_s=body.get("ttft_s"),
                    e2e_s=body.get("total_s"),
                    error=(r is None or r[0] >= 500),
                )
            prober = CanaryProber(
                {"replica": frontend.url("")},
                alerts=alerts, registry=alerts.registry,
            )
            for _ in range(3):
                prober.probe_once()
            rec.update(alerts.stats())
            # Probes ride the warmed buckets: a probe-induced
            # recompile fails the record, same as an organic one.
            rec["post_warmup_recompiles"] = engine.post_warmup_recompiles()
            rec["ok"] = bool(
                rec["ok"]
                and rec["post_warmup_recompiles"] == 0
                and rec["alert_count"] == 0
            )
    finally:
        batcher.close(drain=True)
        frontend.close()
        recorder.close()

    rec["warmup_s"] = round(warmup_s, 3)
    rec["transport"] = "inproc" if args.inproc else "http"
    rec.update(recorder.stats())  # trace_coverage / slow_trace_count
    print(json.dumps(rec))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
