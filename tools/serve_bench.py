#!/usr/bin/env python
"""Closed-loop load generator for the serving stack; banks a BENCH record.

Stands the whole serving path up — engine (AOT warmup over the bucket
ladder), continuous batcher, HTTP frontend — then drives it closed-loop
(``--concurrency`` worker threads, each submitting its next request the
moment the previous one resolves: offered load = concurrency / mean
latency, the standard closed-loop operating point) and emits ONE
BENCH-style JSON record::

    {"bench": "serving", "backend": "cpu", "requests": 20,
     "concurrency": 8, "req_per_s": ..., "tok_per_s": ...,
     "ttft_p50_ms": ..., "ttft_p95_ms": ..., "tpot_p50_ms": ...,
     "tpot_p95_ms": ..., "e2e_p95_ms": ..., "queue_wait_p95_ms": ...,
     "expected_compiles": ..., "compiles": ...,
     "post_warmup_recompiles": 0, "shed": 0, "errors": 0,
     "verified": 3, "verify_ok": true, "ok": true}

``ok`` is the CI verdict: every request completed, the verified subset
is token-identical to the engine's unbatched reference replay, and NOT
ONE compile happened after warmup (``post_warmup_recompiles == 0`` —
the zero-recompile steady-state claim, measured, not asserted).

Modes:

* ``--smoke`` — tier-1 CI: a tiny random-param GPT-2 on whatever
  backend is present (CPU in CI), 20 mixed-length requests over HTTP,
  3 of them verified against the reference. Seconds, not minutes.
* ``--workdir DIR`` — load a real trained checkpoint (the
  ``examples/gpt2`` layout, trained at the DEFAULT model shape — the
  workdir banks no config, so a checkpoint from non-default
  ``--num_layers``/``--d_model``/... flags will fail the template
  restore; serve those via ``examples/gpt2/serve.py``, which takes the
  full flag surface) and measure serving throughput/latency at
  ``--concurrency`` on the local accelerator.

``--inproc`` skips the HTTP hop (batcher futures driven directly) to
separate transport cost from engine cost; ``--out`` banks the record
as a JSON file next to the BENCH_r*.json trajectory.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)


SMOKE_MODEL = dict(
    vocab_size=211,
    max_len=64,
    num_layers=2,
    num_heads=2,
    d_model=32,
    dropout=0.0,
    attention="xla",
)


def build_smoke_engine(serve_cfg=None, *, registry=None):
    """Tiny random-param GPT-2 + engine, shared with tests/test_serving:
    big enough to cross prefill buckets, small enough for tier-1."""
    import jax

    from tensorflow_examples_tpu.models import transformer
    from tensorflow_examples_tpu.serving.engine import (
        InferenceEngine,
        ServeConfig,
    )

    cfg = transformer.TransformerConfig(**SMOKE_MODEL)
    model = transformer.Transformer(cfg)
    import jax.numpy as jnp

    tokens = jnp.zeros((1, 8), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, tokens)["params"]
    return InferenceEngine(
        cfg,
        params,
        cfg=serve_cfg or ServeConfig(max_slots=8, prefill_bucket_floor=16,
                                     kv_bucket_floor=32),
        registry=registry,
    )


def build_checkpoint_engine(workdir: str, serve_cfg, *, registry=None):
    """Engine over the latest checkpoint in an ``examples/gpt2`` workdir
    (restores through an eval_shape template like generate.py). The
    template is the DEFAULT Gpt2Config — the workdir banks no config,
    so non-default-shape checkpoints cannot be restored here."""
    import jax
    import jax.numpy as jnp

    from tensorflow_examples_tpu.serving.engine import InferenceEngine
    from tensorflow_examples_tpu.train.checkpoint import CheckpointManager
    from tensorflow_examples_tpu.train.loop import state_factory
    from tensorflow_examples_tpu.workloads import gpt2

    cfg = gpt2.Gpt2Config(workdir=workdir)
    make_state, _ = state_factory(gpt2.make_task(cfg), cfg)
    abstract = jax.eval_shape(make_state, jax.random.PRNGKey(0))
    try:
        restored = CheckpointManager(workdir).restore_latest(abstract)
    except Exception as e:
        raise SystemExit(
            f"restore failed against the default-shape template — a "
            f"checkpoint trained with non-default model flags must be "
            f"served via examples/gpt2/serve.py instead: {e}"
        ) from None
    if restored is None:
        raise SystemExit(f"no checkpoint under {workdir}")
    params = jax.tree.map(jnp.asarray, restored[0].params)
    return InferenceEngine(
        gpt2.model_config(cfg), params, cfg=serve_cfg, registry=registry
    )


def make_prompts(n: int, *, vocab: int, max_len: int, max_new: int,
                 seed: int = 0) -> list[list[int]]:
    """Mixed-length prompts spanning the prefill buckets (that's the
    continuous-batching claim under test: different lengths coalesce)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    cap = max(2, max_len - max_new)
    lengths = [int(rng.integers(1, cap + 1)) for _ in range(n)]
    # Force the extremes so every run exercises bucket 1 and the top.
    lengths[0], lengths[-1] = 1, cap
    return [
        [int(t) for t in rng.integers(0, vocab, (ln,))] for ln in lengths
    ]


def _post_json(url: str, body: dict, timeout: float) -> tuple[int, dict]:
    data = json.dumps(body).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"}
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")
    except (OSError, ValueError) as e:
        # Transport-level failure (URLError, reset, timeout, torn JSON
        # body): count it as THIS request's error instead of letting it
        # kill the worker thread and strand every prompt it would have
        # pulled next.
        return 0, {"error": f"{type(e).__name__}: {e}"}


def drive(frontend, prompts, *, concurrency: int, max_new: int,
          temperature: float, top_k: int, http_url: str | None,
          timeout: float) -> dict:
    """Closed loop: workers pull the next prompt off a shared list the
    moment their current request resolves. Returns per-request replies
    (index-aligned with ``prompts``) + wall time."""
    replies: list[tuple[int, dict] | None] = [None] * len(prompts)
    next_i = [0]
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                i = next_i[0]
                if i >= len(prompts):
                    return
                next_i[0] += 1
            body = {
                "prompt": prompts[i],
                "max_new_tokens": max_new,
                "temperature": temperature,
                "top_k": top_k,
                "seed": i,  # per-request stream: replayable
            }
            if http_url is not None:
                replies[i] = _post_json(http_url, body, timeout)
            else:
                replies[i] = frontend.handle_request(body, kind="generate")

    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=worker, name=f"serve-bench-{k}", daemon=True)
        for k in range(concurrency)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout * max(1, len(prompts)))
    wall = time.perf_counter() - t0
    return {"replies": replies, "wall_s": wall}


def bench_record(engine, registry, outcome, prompts, *, concurrency,
                 verified, verify_ok, backend) -> dict:
    hists = registry.histogram_summaries()

    def pct(name, q):
        h = hists.get(f"serving/{name}")
        v = h and h.get(f"p{q}")
        return round(v * 1e3, 3) if v is not None else None

    replies = outcome["replies"]
    done = [r for r in replies if r is not None and r[0] == 200]
    toks = sum(len(r[1].get("tokens", ())) for r in done)
    wall = outcome["wall_s"]
    counters = registry.counter_values()
    errors = len(replies) - len(done)
    rec = {
        "bench": "serving",
        "backend": backend,
        "requests": len(prompts),
        "completed": len(done),
        "errors": errors,
        "concurrency": concurrency,
        "max_slots": engine.cfg.max_slots,
        "wall_s": round(wall, 3),
        "req_per_s": round(len(done) / wall, 3) if wall else None,
        "tok_per_s": round(toks / wall, 3) if wall else None,
        "generated_tokens": toks,
        "queue_wait_p95_ms": pct("queue_wait", 95),
        "prefill_p95_ms": pct("prefill", 95),
        "ttft_p50_ms": pct("ttft", 50),
        "ttft_p95_ms": pct("ttft", 95),
        "tpot_p50_ms": pct("tpot", 50),
        "tpot_p95_ms": pct("tpot", 95),
        "e2e_p50_ms": pct("e2e", 50),
        "e2e_p95_ms": pct("e2e", 95),
        "expected_compiles": engine.expected_compiles(),
        "compiles": int(counters.get("compile/count", 0)),
        "post_warmup_recompiles": engine.post_warmup_recompiles(),
        "shed": int(counters.get("serving/shed_total", 0)),
        "verified": verified,
        "verify_ok": verify_ok,
    }
    rec["ok"] = bool(
        errors == 0
        and verify_ok
        and rec["post_warmup_recompiles"] == 0
    )
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny model, 20 requests, verify 3 (tier-1 CI)")
    ap.add_argument("--workdir", default="",
                    help="serve the latest checkpoint in this run dir")
    ap.add_argument("--requests", type=int, default=0,
                    help="request count (default: 20 smoke / 64 otherwise)")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--max-new-tokens", type=int, default=8)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--max-slots", type=int, default=8)
    ap.add_argument("--verify", type=int, default=-1,
                    help="replay N requests unbatched and compare "
                         "token-for-token (-1: 3 in smoke, 0 otherwise)")
    ap.add_argument("--inproc", action="store_true",
                    help="skip the HTTP hop (engine+batcher cost only)")
    ap.add_argument("--timeout", type=float, default=120.0,
                    help="per-request client timeout (seconds)")
    ap.add_argument("--out", default="", help="bank the record here")
    args = ap.parse_args(argv)
    if not args.smoke and not args.workdir:
        ap.error("pick a target: --smoke or --workdir DIR")

    import jax

    from tensorflow_examples_tpu.serving.batcher import ContinuousBatcher
    from tensorflow_examples_tpu.serving.engine import ServeConfig
    from tensorflow_examples_tpu.serving.frontend import ServingFrontend
    from tensorflow_examples_tpu.telemetry.registry import MetricsRegistry

    registry = MetricsRegistry()  # private: the record owns its counters
    serve_cfg = ServeConfig(
        max_slots=args.max_slots,
        max_delay_s=0.002,
        request_timeout_s=args.timeout,
        **(dict(prefill_bucket_floor=16, kv_bucket_floor=32)
           if args.smoke else {}),
    )
    if args.workdir:
        engine = build_checkpoint_engine(
            args.workdir, serve_cfg, registry=registry
        )
    else:
        engine = build_smoke_engine(serve_cfg, registry=registry)

    n = args.requests or (20 if args.smoke else 64)
    verify = args.verify if args.verify >= 0 else (3 if args.smoke else 0)
    prompts = make_prompts(
        n,
        vocab=engine.model_cfg.vocab_size,
        max_len=engine.model_cfg.max_len,
        max_new=args.max_new_tokens,
    )

    t0 = time.perf_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0
    print(
        f"# warm: {engine.expected_compiles()} programs in {warmup_s:.1f}s "
        f"(prefill ladder {engine.prefill_ladder}, "
        f"kv ladder {engine.kv_ladder})",
        file=sys.stderr,
    )

    batcher = ContinuousBatcher(engine, registry=registry).start()
    frontend = ServingFrontend(batcher, port=0)
    http_url = None
    if not args.inproc:
        frontend.start()
        http_url = frontend.url("/generate")
    try:
        outcome = drive(
            frontend, prompts,
            concurrency=args.concurrency, max_new=args.max_new_tokens,
            temperature=args.temperature, top_k=args.top_k,
            http_url=http_url, timeout=args.timeout,
        )
        verify_ok = True
        for i in range(min(verify, n)):
            reply = outcome["replies"][i]
            if reply is None or reply[0] != 200:
                verify_ok = False
                continue
            ref = engine.reference_generate(
                prompts[i], max_new=args.max_new_tokens, seed=i,
                temperature=args.temperature, top_k=args.top_k,
            )
            if reply[1]["tokens"] != ref:
                verify_ok = False
                print(
                    f"# VERIFY FAIL req {i}: served {reply[1]['tokens']} "
                    f"!= reference {ref}",
                    file=sys.stderr,
                )
    finally:
        batcher.close(drain=True)
        frontend.close()

    rec = bench_record(
        engine, registry, outcome, prompts,
        concurrency=args.concurrency, verified=min(verify, n),
        verify_ok=verify_ok, backend=jax.default_backend(),
    )
    rec["warmup_s"] = round(warmup_s, 3)
    rec["transport"] = "inproc" if args.inproc else "http"
    print(json.dumps(rec))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
