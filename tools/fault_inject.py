#!/usr/bin/env python
"""Standalone chaos runner: arm a fault plan and exec any workload CLI.

Manual chaos testing against the real example entrypoints (ISSUE 1):

    python tools/fault_inject.py --spec 'sigterm@10' -- \
        python examples/mnist/train.py --device=cpu --train_steps=20 \
        --workdir=/tmp/chaos

    python tools/fault_inject.py --spec 'slow@5:60' -- \
        python examples/gpt2/train.py --device=cpu --watchdog_secs=10 \
        --watchdog_fatal_secs=30

The spec is exported as $TPU_FAULT_INJECT; the trainer's instrumentation
points (tensorflow_examples_tpu/utils/faults.py) pick it up lazily, so
this works for ANY command that runs the shared training loop — no
wrapper imports in the child. Exit code is the child's, with an
interpretation printed for the ones the resilience layer defines:

    0   clean exit — including a preemption that checkpointed and left
    87  watchdog fail-fast (HUNG_EXIT_CODE): a step or input fetch
        stalled past --watchdog_fatal_secs

Fault kinds (comma-separated kind@arg tokens):
    sigterm@N     SIGTERM right before train step N
    nan@N[:M]     NaN-poison the batch floats for steps N..N+M-1
    slow@N[:S]    sleep S (default 5) seconds fetching host batch N
    ioerr@K       first K file reads raise OSError (retry/backoff path)
    badbatch@N    corrupt host batch N (poisoned-batch skip path)

**Serving faults** (ISSUE 10): ``--serve`` switches the spec grammar to
the serve-side plan (exported as $TPU_SERVE_FAULT_INJECT; picked up by
the serving engine's decode hook and the frontend — any command that
runs the serving stack, e.g. ``tools/serve_bench.py --router`` or
``examples/gpt2/serve.py``). Tokens are ``kind@replica:arg``, keyed on
each replica's own decode-step/request/probe counters:

    python tools/fault_inject.py --serve --spec 'crash@1:4' -- \
        python tools/serve_bench.py --smoke --router --replicas 3

    crash@R:N       kill replica R's transport before its Nth decode
                    step (in-proc fleets; needs the chaos harness's
                    registered kill — serving/chaos.py)
    slowrep@R:S     every decode step on replica R sleeps S seconds
    transport@R:K   drop replica R's first K requests with no response
                    bytes (clients see a reset -> router failover)
    kvexhaust@R:N   force BlockExhausted on replica R's Nth decode step
    badhealth@R:K   replica R's first K /health replies are non-JSON
                    garbage (the probe must mark it unhealthy)
    killrouter@T    ISSUE 16, no replica index: hard-abort the ACTIVE
                    router's frontend after its Tth accepted GENERATE
                    dispatch — classify/score traffic never advances T
                    (clients see resets; the warm standby promotes and
                    replays the journal's incomplete intents)
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tensorflow_examples_tpu.utils.diagnostics import HUNG_EXIT_CODE  # noqa: E402
from tensorflow_examples_tpu.utils.faults import (  # noqa: E402
    ENV_VAR,
    SERVE_ENV_VAR,
    parse_serve_spec,
    parse_spec,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument(
        "--spec",
        required=True,
        help="fault plan, e.g. 'sigterm@10,ioerr@2' (see module docstring)",
    )
    parser.add_argument(
        "--serve",
        action="store_true",
        help="treat --spec as a SERVING fault plan (kind@replica:arg "
        "grammar, exported as $TPU_SERVE_FAULT_INJECT)",
    )
    parser.add_argument(
        "command",
        nargs=argparse.REMAINDER,
        help="workload CLI to run (prefix with -- )",
    )
    args = parser.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given; usage: fault_inject.py --spec ... -- <cmd>")

    # Validate before spawning anything.
    if args.serve:
        plan = parse_serve_spec(args.spec)
    else:
        plan = parse_spec(args.spec)
    env = dict(os.environ)
    env[SERVE_ENV_VAR if args.serve else ENV_VAR] = args.spec
    print(f"[fault_inject] armed {plan} for: {' '.join(command)}", flush=True)
    proc = subprocess.run(command, env=env)
    rc = proc.returncode

    if rc == 0:
        print("[fault_inject] child exited cleanly (0)")
    elif rc == HUNG_EXIT_CODE:
        print(
            f"[fault_inject] child exited {rc} = watchdog fail-fast "
            "(hung step/input past watchdog_fatal_secs)"
        )
    elif rc < 0:
        print(
            f"[fault_inject] child killed by signal "
            f"{signal.Signals(-rc).name}"
        )
    else:
        print(f"[fault_inject] child exited {rc}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
