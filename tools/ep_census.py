#!/usr/bin/env python
"""Itemized collective census for the MoE EP train step (VERDICT r3
item 3: the 105 residual all-reduces in the dp2×model4 compiled step
must be *attributed*, not just counted).

Compiles the same MoE GPT-2 train step as bench.py's census probe on an
8-virtual-device dp2×model4 CPU mesh, then walks the optimized HLO and
classifies every collective instruction by

- **kind** (all-reduce / all-gather / all-to-all / reduce-scatter /
  collective-permute),
- **mesh axis**, decoded from ``replica_groups`` (on a dp2×model4 mesh
  with row-major device order, groups of 4 consecutive ids = ``model``,
  groups of stride-4 pairs = ``data``, the full set = both),
- **origin bucket**, from the ``op_name`` metadata XLA carries through
  from jaxpr equation names (router/aux math, expert dispatch,
  backward (transpose), optimizer update, train metrics, other).

Prints a human table plus one ``EP_CENSUS <json>`` line for tooling.
Run: ``python tools/ep_census.py`` (self-pins CPU + 8 devices).
"""

import collections
import json
import re
import sys


def _ids_to_axis(ids: list, n_devices: int, model: int) -> str:
    if not ids or all(len(g) <= 1 for g in ids):
        return "none"
    sizes = {len(g) for g in ids}
    if sizes == {n_devices}:
        return "data+model"
    first = sorted(ids[0])
    if len(first) == model and first == list(
        range(first[0], first[0] + model)
    ):
        return "model"
    return "data"


def classify_axis(line: str, n_devices: int, model: int) -> str:
    """Decode the mesh axis from an HLO replica_groups attribute.

    Handles both the literal ``{{0,1},{2,3}}`` form and the iota form
    ``[G,S]<=[dims]T(perm)`` (materialized with numpy: iota over
    prod(dims), reshape, transpose, flatten, regroup into G rows)."""
    g = re.search(r"replica_groups=(\{\{[^}]*\}(?:,\{[^}]*\})*\})", line)
    if g:
        ids = [
            [int(x) for x in grp.split(",") if x.strip() != ""]
            for grp in re.findall(r"\{([\d,]*)\}", g.group(1))
        ]
        return _ids_to_axis(ids, n_devices, model)
    g = re.search(
        r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?",
        line,
    )
    if g:
        import numpy as np

        ng, gs = int(g.group(1)), int(g.group(2))
        dims = [int(x) for x in g.group(3).split(",")]
        arr = np.arange(int(np.prod(dims))).reshape(dims)
        if g.group(4):
            arr = arr.transpose([int(x) for x in g.group(4).split(",")])
        ids = arr.reshape(ng, gs).tolist()
        return _ids_to_axis(ids, n_devices, model)
    return "?"


_BUCKET_RULES = (
    # (bucket, regex over op_name) — first match wins; ordered so the
    # backward pass is recognized before forward-ish keywords inside it.
    ("optimizer", re.compile(r"adamw?|lamb|lars|sgd|opt_update|scale_by")),
    ("metrics", re.compile(r"metrics|grad_norm|global_norm|loss_mean")),
    ("backward", re.compile(r"transpose\(|/vjp|backward|grad")),
    ("router/aux", re.compile(r"moe.*(route|gate|aux|pmean|softmax)|aux_loss")),
    ("ep_dispatch", re.compile(r"all_to_all|moe|expert")),
)


def classify_bucket(op_name: str) -> str:
    low = op_name.lower()
    for bucket, rx in _BUCKET_RULES:
        if rx.search(low):
            return bucket
    return "other"


def census(hlo: str, n_devices: int, model: int):
    rows = []
    # Definition sites only (the %name = shape opcode(...) form) — a
    # plain substring count also hits operand REFERENCES like
    # %all-reduce.12 and overcounts ~2-3x (the round-2/3 census did
    # exactly that; BASELINE.md round-4 note). Shape is non-greedy so
    # tuple-shaped collectives (lax.all_to_all lowers to one) match,
    # and the async -start halves count once (-done is skipped).
    for m in re.finditer(
        r"^\s*(?:ROOT\s+)?%?[\w.\-]+ = (.+?) (all-reduce|all-gather|"
        r"all-to-all|reduce-scatter|collective-permute)(?:-start)?\(",
        hlo,
        re.M,
    ):
        line_end = hlo.find("\n", m.start())
        line = hlo[m.start(): line_end if line_end != -1 else None]
        shape, kind = m.group(1), m.group(2)
        axis = classify_axis(line, n_devices, model)
        op = re.search(r'op_name="([^"]*)"', line)
        op_name = op.group(1) if op else ""
        rows.append(
            {
                "kind": kind,
                "axis": axis,
                "bucket": classify_bucket(op_name),
                "shape": shape,
                "op_name": op_name[-160:],
            }
        )
    return rows


def main() -> int:
    import os

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")

    from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import gpt2

    cfg = gpt2.Gpt2Config(
        vocab_size=512, seq_len=128, num_layers=2, num_heads=4, d_model=64,
        dropout=0.0, moe_experts=8, moe_top_k=2, moe_every=1,
        global_batch_size=8, precision="f32", log_every=10**9,
        checkpoint_every=0, watchdog_secs=0,
    )
    mesh = create_mesh(MeshConfig(data=2, model=4))
    trainer = Trainer(gpt2.make_task(cfg, mesh), cfg, mesh=mesh)
    ds, _ = gpt2.datasets(cfg)
    batch = trainer._put_batch(next(train_iterator(ds, 8, seed=0)))
    hlo = trainer._train_step.lower(trainer.state, batch).compile().as_text()

    rows = census(hlo, n_devices=8, model=4)
    by_kind = collections.Counter(r["kind"] for r in rows)
    table = collections.Counter(
        (r["kind"], r["axis"], r["bucket"]) for r in rows
    )
    print(f"{'kind':<20} {'axis':<12} {'bucket':<12} count")
    for (kind, axis, bucket), cnt in sorted(table.items()):
        print(f"{kind:<20} {axis:<12} {bucket:<12} {cnt}")
    print()
    samples = {}
    for r in rows:
        samples.setdefault((r["kind"], r["axis"], r["bucket"]), []).append(
            (r["shape"], r["op_name"])
        )
    for key, items in sorted(samples.items()):
        print(f"--- {key} ({len(items)})")
        for shape, op in items[:3]:
            print(f"    {shape}  {op}")
    out = {
        "totals": dict(by_kind),
        "table": [
            {"kind": k, "axis": a, "bucket": b, "count": c}
            for (k, a, b), c in sorted(table.items())
        ],
    }
    print("EP_CENSUS " + json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
