#!/usr/bin/env python
"""Framework benchmark — prints exactly ONE JSON line for the driver.

North-star metric (BASELINE.json:metric): **ResNet-50 ImageNet
examples/sec/chip**, measured two ways so input-pipeline cost is visible
separately (SURVEY.md §3(4), §7 hard-part (a)):

- ``resnet50``        — synthetic batches already resident on device
                        (pure compute ceiling).
- ``resnet50_input``  — fed by the real host pipeline: tf.data TFRecord
                        shards → JPEG decode → augment → threaded C++
                        normalize → async device prefetch.

Secondary benches: GPT-2 124M tokens/sec (``gpt2``, ``gpt2_long``,
``gpt2_long16k``, ``gpt2_decode``), BERT, CIFAR-10, MNIST step-time,
ICI/mesh collective bandwidth (``collectives``), MoE (``moe``).
``--bench=all`` (the default) runs the suite and emits the north-star
as the headline with the rest under ``"extras"``.

Measurement protocol (VERDICT r2 item 1 — the perf record must be
readable by a skeptic on a tunnel whose raw speed drifts 13x between
runs):

- every bench times **3 windows** and reports the **median**; the
  per-window values are emitted (``window_values``) so noise is visible
  in the record, not asserted away;
- the raw-matmul rig probe runs **before and after** the sweep
  (``fingerprint_tflops_pre/post``, each a median of 5 windows) AND
  once, quickly, immediately before each bench
  (``probe_tflops_at_bench``);
- every compute bench emits ``model_tflops_per_sec`` — analytic
  FLOPs/step from XLA's cost model on the exact compiled executable
  (hand-counted for the decode bench: XLA's count includes a lax.scan
  body once, not × trip count), divided by the median step time — and
  ``rel_mfu`` =
  model_tflops / probe_tflops_at_bench. **rel_mfu is the cross-round
  comparable number**: rig drift multiplies numerator and denominator
  alike and cancels.

FLOORS POLICY (VERDICT r2): a floor is a (value, rig-fingerprint) PAIR
measured by this protocol. ``vs_baseline`` compares the current median
against the floor value; it is only a regression verdict when the
current fingerprint is within ~2x of the floor's — otherwise read
``rel_mfu`` against REL_MFU_FLOORS (drift-cancelled). A floor may only
be moved together with its fingerprint, by a measurement under this
protocol, recorded in BASELINE.md with the date. The reference itself
published no numbers (BASELINE.json:published == {}).

Driver robustness (VERDICT r1): this rig's TPU plugin can HANG during
backend init — not just raise — so the ambient backend is probed in a
subprocess with a hard timeout; on failure the bench falls back to an
in-process CPU pin and tags the output ``"backend": "cpu"``. Any
failure still prints one parseable JSON line and exits 0.

Budget (VERDICT r3 item 2 — BENCH_r03.json was rc=124/parsed:null
because a driver-side ``timeout`` killed the sweep): the whole run now
operates under a wall-clock budget (``--budget=S`` /
``$BENCH_BUDGET_S``, default 540 s so an outer ``timeout 600`` can
never win). Benches that don't fit the remaining budget are skipped and
listed under ``"truncated"``; a watchdog thread is the backstop — if
the main thread is wedged inside a compile when the budget expires, the
watchdog emits everything completed so far as the one JSON line and
exits 0. Subprocess helpers (backend probe, MoE census, TPU selftest)
are capped by the remaining budget, the probe verdict is cached in
/tmp for 300 s so a process tree pays the dead-tunnel hang at most
once, and a persistent XLA compilation cache (/tmp/jax_bench_cache)
makes warm re-runs cheap.
"""

import json
import os
import statistics
import subprocess
import sys
import threading
import time

# Regression floors: (value, rig_fingerprint_tflops) pairs per
# (backend, metric) — see FLOORS POLICY in the module docstring. Both
# backends are stamped from round-4 protocol sweeps (dates in the
# per-backend comments); the rig's probe drifts across sessions — and
# mid-harvest — which is exactly why rel_mfu and the per-record
# fingerprints exist.
FLOORS = {
    "tpu": {
        # 2026-07-31 round-4 incremental harvest, the first full
        # protocol sweep on a live chip (median-of-3 windows, per-bench
        # pre-probes; BASELINE.md "Round-4 TPU harvest" table has the
        # (value, fingerprint, rel_mfu, window-spread) evidence). Each
        # floor carries ITS OWN record's pre-fingerprint — the rig
        # drifted [78, 99912] probe-TFLOP/s across the window, the low
        # end being a probe taken mid tunnel-wedge. bert/cifar10 moved
        # DOWN vs their round-3 single-window stamps on a rig whose
        # matmul probe ran faster; dispatch-rate differences are the
        # suspect (those stamps predate the launch-µs fingerprint), see
        # BASELINE.md for the diag.
        "resnet50_examples_per_sec_per_chip": (185187.0807, 65958.3),
        "resnet50_input_examples_per_sec_per_chip": (124.0052, 53598.89),  # 1-CPU host!
        # ISSUE 6: the r02 pipeline-only figure (host decode+augment,
        # no device in the loop) promoted from a buried extras
        # annotation to a tracked, floored metric. Fingerprint is the
        # r02 record's own (a mid-wedge probe — the floors policy
        # carries each floor with its record's evidence).
        "resnet50_input_pipeline_only_images_per_sec": (474.6, 2279.33),
        "gpt2_124m_tokens_per_sec": (3592223.8352, 59962.35),
        "gpt2_long4k_tokens_per_sec": (4231329.5553, 47927.17),
        "gpt2_long16k_tokens_per_sec": (9130385.6576, 70377.3),
        "gpt2_decode_tokens_per_sec": (3094517.5665, 62363.12),
        "gpt2_decode_long_tokens_per_sec": (1510532.0, 51264.06),
        # bert/cifar10/mnist: restamped 2026-08-01 from the round-5
        # harvest's first live window under the K=8 bundled protocol
        # (FLOOR_BUNDLES carries the 8; a future unbundled record flags
        # floor_protocol_mismatch). The window's dispatch was fast
        # (launch ~15-19 µs vs the ~ms-scale round-4 instances), so
        # these floors encode bundling AND a healthy tunnel — rel_mfu
        # and the per-record fingerprints are the cross-instance
        # comparables, per the floors policy.
        "bert_base_examples_per_sec_per_chip": (174256.466, 69610.49),
        "cifar10_resnet20_examples_per_sec_per_chip": (1602954.8218, 54962.94),
        "mnist_mlp_step_time": (0.0104, 55840.55),  # ms/step
        "allreduce_busbw": (3401.0685, 86610.5),  # GB/s, n=1 loopback
        "moe_top2_tokens_per_sec": (62555.0, 45538.05),
        # decode_grid_step_time_ratio is deliberately NOT floored: it is
        # a diagnostic whose healthy value is ~1.0 (O(context)
        # sequencing) and whose failure direction is UP toward ~8
        # (O(max_len)); a floor at the measured 0.78 would make a
        # healthy 1.0 read as a regression through the lower-is-better
        # branch. The measurement lives in BASELINE.md.
    },
    "cpu": {
        # 2026-07-30 round-4 protocol sweep (median-of-3 windows, probe
        # pre 0.10 / post 0.09 TFLOP/s, uncontended single-core host;
        # BASELINE.md "Round-4 CPU sweep"). Restamped after the round-4
        # code changes (gather-free CE, decode bucket ladder, EP token
        # split) changed the compiled programs AND XLA's analytic FLOPs
        # for some steps — see BASELINE.md. NB this host's CPU
        # throughput swings ±2x with ambient load — read rel_mfu first.
        # resnet50/resnet50_input restamped at the batch-4 CPU shape
        # (headline must fit the 540 s dead-tunnel budget).
        "resnet50_examples_per_sec_per_chip": (0.436, 0.09),
        "resnet50_input_examples_per_sec_per_chip": (0.472, 0.10),
        # ISSUE 6: stamped 2026-08-04 from tools/host_input_bench.py
        # --smoke on this 2-vCPU rig (parallel pipeline, 4 workers /
        # 2 readers, native decode, record-shuffle window on;
        # sequential reference ~610-700). LOWEST of three back-to-back
        # healthy records (runs here spread ~715-915 with ambient
        # load; the tool's own median-of-5 GEMM probe is the
        # fingerprint — NOT bench.py's probe — and a loaded run's
        # probe collapses with it, so the 2x comparability window
        # already skips the worst noise).
        "host_input_pipeline_images_per_sec": (715.9, 0.0881),
        "gpt2_124m_tokens_per_sec": (37.3, 0.10),
        "gpt2_long4k_tokens_per_sec": (19.6, 0.10),
        "gpt2_long16k_tokens_per_sec": (23.6, 0.10),
        "gpt2_decode_tokens_per_sec": (3200.8, 0.10),
        "gpt2_decode_long_tokens_per_sec": (1965.0, 0.10),
        "bert_base_examples_per_sec_per_chip": (1607.1, 0.10),
        "cifar10_resnet20_examples_per_sec_per_chip": (92.1, 0.10),
        "mnist_mlp_step_time": (3.86, 0.10),  # ms/step
        "allreduce_busbw": (0.88, 0.10),  # GB/s, 8 virtual devices
        "moe_top2_tokens_per_sec": (8606.3, 0.10),
    },
}

# Launch protocol each floor was stamped under: steps_per_launch of the
# record that produced the FLOORS value (metrics absent here were
# stamped unbundled, bundle=1). _result flags "floor_protocol_mismatch"
# whenever a record's bundle differs from its floor's — vs_baseline
# across that boundary mixes launch amortization with per-step change.
# Restamps must move these entries together with FLOORS (stamped
# mechanically by tools/apply_floors.py from each record's "bundle"
# key; the round-4 pre-registered bert/cifar10/mnist K=8 protocol
# landed with the 2026-08-01 round-5 restamp below).
FLOOR_BUNDLES: dict[str, dict[str, int]] = {
    "tpu": {
        "resnet50_examples_per_sec_per_chip": 1,
        "resnet50_input_examples_per_sec_per_chip": 1,
        "gpt2_124m_tokens_per_sec": 1,
        "gpt2_long4k_tokens_per_sec": 1,
        "gpt2_long16k_tokens_per_sec": 1,
        "gpt2_decode_tokens_per_sec": 1,
        "bert_base_examples_per_sec_per_chip": 8,
        "cifar10_resnet20_examples_per_sec_per_chip": 8,
        "mnist_mlp_step_time": 8,
        "allreduce_busbw": 1,
    },
    "cpu": {},
}

# Drift-cancelled floors: rel_mfu = model_tflops/probe_tflops measured
# under the 3-window protocol. Stamped per-metric by
# tools/apply_floors.py from each metric's most recent harvest record
# (mixed rounds by design — the floors policy moves each floor WITH
# its own evidence; provenance per metric in BASELINE.md). CPU side
# from the 2026-07-30 round-4 sweep. Same policy as FLOORS.
REL_MFU_FLOORS: dict[str, dict[str, float]] = {
    "tpu": {
        "resnet50_examples_per_sec_per_chip": 0.07961,
        "resnet50_input_examples_per_sec_per_chip": 6e-05,
        "gpt2_124m_tokens_per_sec": 0.06236,
        "gpt2_long4k_tokens_per_sec": 0.0515,
        "gpt2_long16k_tokens_per_sec": 0.10832,
        "gpt2_decode_tokens_per_sec": 0.01937,
        "gpt2_decode_long_tokens_per_sec": 0.13992,
        # bert/cifar10/mnist rel_mfu floors were DROPPED with the K=8
        # restamp (2026-08-01): their round-4 stamps were per-step
        # values, and a bundled record's rel_mfu (chip no longer idle
        # between launches) would read ~10x over them — a silent
        # protocol conflation, not an efficiency gain. They return when
        # the queued re-measure banks bundled records WITH rel_mfu
        # (the compiled-bundled/k FLOPs fallback) and apply_floors
        # restamps all three consistently.
        "moe_top2_tokens_per_sec": 0.00154,
    },
    "cpu": {
        # Round-4 sweep (2026-07-30). gpt2 dropped 0.729 → 0.306 NOT
        # from a slowdown (raw tokens/s moved 40.9 → 37.3, within this
        # host's ambient swing) but because the gather-free CE changed
        # the step's XLA cost-analysis FLOPs — the rel_mfu NUMERATOR.
        # Full restamp rationale in BASELINE.md round-4 table.
        "resnet50_examples_per_sec_per_chip": 0.102,
        "resnet50_input_examples_per_sec_per_chip": 0.127,
        "gpt2_124m_tokens_per_sec": 0.306,
        "gpt2_long4k_tokens_per_sec": 0.232,
        "gpt2_long16k_tokens_per_sec": 0.604,
        "gpt2_decode_tokens_per_sec": 0.019,
        "gpt2_decode_long_tokens_per_sec": 0.028,
        "bert_base_examples_per_sec_per_chip": 0.078,
        "cifar10_resnet20_examples_per_sec_per_chip": 0.224,
        "mnist_mlp_step_time": 0.324,
        "moe_top2_tokens_per_sec": 0.299,
    },
}

BACKEND = "cpu"  # resolved in main()
WINDOWS = 3  # timing windows per bench; median reported

# ------------------------------------------------------- budget machinery
#
# One deadline for the whole process (None = unbounded). Everything that
# can block — benches, subprocess helpers, the backend probe — consults
# _remaining(); the watchdog thread is the last line of defense for
# hangs inside native code where Python-level checks never run.

_DEADLINE: "float | None" = None
_RESULTS: list = []  # completed per-bench dicts, in completion order
_META: dict = {}  # backend / fingerprints / selftest, merged at emit
# Full sweep plan (set in main for --bench=all BEFORE anything can
# block, so even a watchdog firing during backend resolution emits an
# honest truncated list). _assemble derives "truncated" as
# planned − completed.
_SWEEP_PLANNED: list = []
_IN_FLIGHT: "str | None" = None
_EMIT_LOCK = threading.Lock()
_EMITTED = False


def _remaining() -> float:
    return float("inf") if _DEADLINE is None else _DEADLINE - time.monotonic()


def _assemble() -> dict:
    """Fold completed benches into the single driver JSON object:
    headline = highest-priority error-free bench per ALL_ORDER (benches
    EXECUTE cheapest-first to maximize coverage under the budget, but
    the record is always presented headline-first), everything else
    under "extras", budget victims under "truncated"."""
    rank = {n: i for i, n in enumerate(ALL_ORDER)}
    results = sorted(_RESULTS, key=lambda r: rank.get(r.get("bench"), 99))
    head = next((r for r in results if "error" not in r), None)
    if head is None and results:
        # Everything errored: surface the first real error (with its
        # bench identity) at top level rather than a generic message.
        head = results[0]
    out = dict(head) if head is not None else {"error": "no bench completed"}
    extras = [r for r in results if r is not head]
    if extras:
        out["extras"] = extras
    done = {r.get("bench") for r in results}
    trunc = []
    if _IN_FLIGHT is not None and _IN_FLIGHT not in done:
        trunc.append(_IN_FLIGHT)
    # Every planned-but-not-completed bench — skipped by the budget
    # check, in flight at watchdog fire, or never reached — is
    # truncated; absence would read as "not part of the sweep".
    for name in _SWEEP_PLANNED:
        if name not in done and name not in trunc:
            trunc.append(name)
    if trunc:
        out["truncated"] = trunc
    out.update(_META)
    # If an incremental harvest (tools/tpu_harvest.sh) has banked an
    # on-chip record, carry it inside the driver artifact:
    # BENCH_r03.json was lost to a dead tunnel and round 3 ended with
    # ZERO TPU numbers on file — the official artifact must never again
    # depend on the tunnel being alive at the one moment the driver
    # runs. Attached unconditionally (a live-TPU driver run may itself
    # be budget-truncated; the banked record is the fuller evidence).
    if os.environ.get("BENCH_HARVEST_CHILD"):
        return out  # harvest subprocess: never embed the banked record
    try:
        with open(_banked_harvest_path()) as f:
            harvested = json.load(f)
        if harvested.get("backend") == "tpu":
            out["tpu_harvest"] = harvested
    except Exception:
        pass
    return out


def _kernel_source_hash() -> str:
    """tools/kernel_source_hash.py without touching sys.path (repeated
    inserts would let tools/ modules shadow same-named imports)."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "tools",
        "kernel_source_hash.py",
    )
    spec = importlib.util.spec_from_file_location("_ksh", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.kernel_source_hash()


def _banked_harvest_path() -> str:
    """Where tools/tpu_harvest.sh banks the merged on-chip record.
    ``BENCH_BANKED_HARVEST`` overrides (tests; future-round renames).
    Prefers the current round's artifact, falling back to the previous
    round's so a round with no live window still attaches the freshest
    banked on-chip evidence."""
    env = os.environ.get("BENCH_BANKED_HARVEST")
    if env:
        return env
    d = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "docs", "tpu_sweeps"
    )
    r5 = os.path.join(d, "round5_merged.json")
    return r5 if os.path.exists(r5) else os.path.join(
        d, "round4_merged.json"
    )


def _emit(out: "dict | None" = None) -> None:
    """Print the ONE JSON line, exactly once per process. Never raises:
    a failure here would break the always-one-parseable-line contract
    for both the main thread and the watchdog."""
    global _EMITTED
    with _EMIT_LOCK:
        if _EMITTED:
            return
        try:
            line = json.dumps(out if out is not None else _assemble())
        except Exception as e:  # non-serializable value in a bench dict
            line = json.dumps({"error": f"emit failed: {type(e).__name__}: {e}"})
        try:
            print(line)
            sys.stdout.flush()
        except Exception:
            pass  # stdout gone (driver killed the pipe); nothing to do
        _EMITTED = True


def _watchdog_fire() -> None:
    _META.setdefault("budget_expired", True)
    _emit()
    os._exit(0)  # main thread may be wedged in native code; don't wait


_PROBE_CACHE = "/tmp/bench_backend_probe.json"
_PROBE_CACHE_TTL = 300.0  # tunnel state changes on minutes timescales


def _probe_backend(timeout_s: float = 90.0):
    """Probe the ambient jax backend in a subprocess (it can hang).

    The verdict is cached in /tmp with a short TTL so a process tree
    (driver retries, selftest, my own repeated runs) pays the
    dead-tunnel hang at most once per 5 minutes."""
    try:
        with open(_PROBE_CACHE) as f:
            c = json.load(f)
        if time.time() - c["time"] < _PROBE_CACHE_TTL:
            return c["platform"], c["n"], c["err"]
    except Exception:
        pass
    full_timeout = timeout_s
    timeout_s = max(10.0, min(timeout_s, _remaining() - 30.0))
    # ANY budget-derived reduction disqualifies a negative verdict from
    # being cached: a live-but-slow tunnel must not be miscalled dead
    # for the next TTL window (see below).
    clamped = timeout_s < full_timeout
    code = (
        "import jax, sys\n"
        "d = jax.devices()\n"
        "sys.stdout.write('PROBE %s %d\\n' % (d[0].platform, len(d)))\n"
    )
    plat, n, err = None, 0, "probe failed"
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        for line in r.stdout.splitlines():
            if line.startswith("PROBE "):
                _, p, num = line.split()
                plat, n, err = p, int(num), None
                break
        else:
            err = (r.stderr or r.stdout).strip()[-400:] or "probe failed"
    except subprocess.TimeoutExpired:
        err = f"backend init hung >{timeout_s:.0f}s"
    if plat is None and clamped:
        # Negative verdict under a budget-clamped timeout: a live-but-
        # slow tunnel could be miscalled dead. Don't poison the cache.
        return plat, n, err
    try:
        with open(_PROBE_CACHE, "w") as f:
            json.dump(
                {"platform": plat, "n": n, "err": err, "time": time.time()}, f
            )
    except Exception:
        pass
    return plat, n, err


def _resolve_backend() -> str:
    """Pick a live backend; pin CPU in-process if the default is dead.

    The env-var route (JAX_PLATFORMS=cpu) does NOT work on this rig —
    sitecustomize pre-imports jax — so the fallback is the in-process
    config pin, same as tests/conftest.py.
    """
    forced = os.environ.get("BENCH_FORCE_BACKEND")
    plat, _n, err = (forced, None, None) if forced else _probe_backend()
    if plat is None or plat == "cpu":
        # 8 virtual devices so the collectives bench exercises a real
        # mesh; workload benches pin a 1-device mesh (per-chip metrics).
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            )
        import jax

        jax.config.update("jax_platforms", "cpu")
        if err:
            print(
                f"bench: default backend unusable ({err}); CPU fallback",
                file=sys.stderr,
            )
        _enable_compile_cache()
        return "cpu"
    _enable_compile_cache()
    return "tpu"  # axon / tpu / anything accelerator-shaped


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache: compile time is the dominant
    wall-clock cost of a sweep on this 1-core host (and the first TPU
    compile is 20-40 s/program), and it counts against the budget even
    though it never enters a timing window. Warm re-runs skip it."""
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", "/tmp/jax_bench_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # cache is an optimization, never a failure
        print(f"bench: compile cache unavailable ({e})", file=sys.stderr)


# ------------------------------------------------------------- rig probe


_PROBE_STATE: dict = {}


def _probe_window(iters: int) -> float:
    """One raw big-matmul timing window → TFLOP/s. The jitted matmul and
    its inputs are built once per process (a fresh lambda per window
    would miss the jit cache and recompile every probe)."""
    import jax
    import jax.numpy as jnp

    if BACKEND not in _PROBE_STATE:
        n = 8192 if BACKEND == "tpu" else 1024
        dtype = jnp.bfloat16 if BACKEND == "tpu" else jnp.float32
        k = jax.random.PRNGKey(0)
        a = jax.random.normal(k, (n, n), dtype)
        b = jax.random.normal(k, (n, n), dtype)
        f = jax.jit(lambda a, b: a @ b)
        f(a, b).block_until_ready()  # compile once
        _PROBE_STATE[BACKEND] = (f, a, b, n)
    f, a, b, n = _PROBE_STATE[BACKEND]
    f(a, b).block_until_ready()  # warm window
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(a, b)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    return 2 * n**3 * iters / dt / 1e12


def fingerprint_tflops(windows: int = 5) -> float:
    """Rig behavior stamp: median of ``windows`` probe windows."""
    iters = 10 if BACKEND == "tpu" else 3
    return statistics.median(_probe_window(iters) for _ in range(windows))


def _probe_quick() -> float:
    """Cheap single-window probe run immediately before each bench."""
    return _probe_window(5 if BACKEND == "tpu" else 2)


def _probe_launch_us(n: int = 200, windows: int = 3) -> float:
    """Dispatch-chain fingerprint: wall µs per chained jitted no-op step.

    The matmul probe saturates on device FLOPs and cannot see per-launch
    host/tunnel dispatch cost — but the small-step benches (cifar10,
    mnist, resnet50_input, decode) run exactly in the regime where that
    cost dominates, and it varies between tunnel instances in a way the
    TFLOP/s fingerprint never records (the round-4 harvest measured
    cifar10 at 0.42x a floor whose rig probed SLOWER on matmuls).
    Chained x = f(x) launches replicate _time_steps' async-dispatch
    pattern: one block at the end, so the figure is launch pipeline
    throughput, not round-trip latency."""
    import jax
    import jax.numpy as jnp

    key = ("launch", BACKEND)
    if key not in _PROBE_STATE:
        f = jax.jit(lambda x: x + 1.0, donate_argnums=0)
        x0 = f(jnp.zeros((8, 128), jnp.float32))
        x0.block_until_ready()  # compile once
        _PROBE_STATE[key] = (f, x0)
    f, x = _PROBE_STATE[key]
    best = None
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(n):
            x = f(x)
        x.block_until_ready()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    _PROBE_STATE[key] = (f, x)
    return best / n * 1e6


# -------------------------------------------------------------- plumbing


def _result(
    metric: str,
    values: "float | list[float]",
    unit: str,
    *,
    model_tflops_per_sec: "float | None" = None,
    **extra,
) -> dict:
    """Assemble one bench record. ``values``: per-window measurements
    (a scalar is accepted for benches without windows); the median is
    the headline value and the sorted window list is emitted so
    run-to-run spread is part of the record."""
    if isinstance(values, (int, float)):
        values = [float(values)]
    value = statistics.median(values)
    floor, floor_fp = FLOORS.get(BACKEND, {}).get(metric, (0.0, 0.0))
    if "step_time" in metric or "ms" in unit:
        vs = floor / value if floor else 1.0  # lower is better
    else:
        vs = value / floor if floor else 1.0
    out = {
        "metric": metric,
        "value": round(value, 4),
        "unit": unit,
        "vs_baseline": round(vs, 4),
        # Compare with probe_tflops_at_bench before reading vs_baseline
        # as a regression/improvement (FLOORS POLICY, module docstring).
        "floor_fingerprint_tflops": floor_fp,
        "window_values": [round(v, 4) for v in sorted(values)],
        **extra,
    }
    # A floor is only comparable to a record measured under the same
    # launch protocol: flag when the record's steps_per_launch differs
    # from the bundle the floor was stamped at, so a vs_baseline that
    # conflates launch amortization with per-step perf is visibly
    # transitional rather than silently green.
    rec_bundle = int(extra.get("bundle", 1) or 1)
    floor_bundle = FLOOR_BUNDLES.get(BACKEND, {}).get(metric, 1)
    if floor and rec_bundle != floor_bundle:
        out["floor_protocol_mismatch"] = (
            f"record bundle={rec_bundle}, floor stamped at "
            f"bundle={floor_bundle}"
        )
    if model_tflops_per_sec is not None:
        out["model_tflops_per_sec"] = round(model_tflops_per_sec, 3)
        # Which analysis produced the FLOPs numerator (ADVICE r4):
        # "compiled" = XLA cost model on the compiled executable,
        # "lowered" = pre-optimization lowering (verified equal on this
        # rig but not guaranteed on other versions/backends),
        # "hand-counted" = analytic formula in the bench itself.
        out["flops_analysis"] = _step_flops.last_mode or "hand-counted"
        _step_flops.last_mode = None
    return out


def _chip_mesh():
    """1-device mesh: workload benches measure per-chip throughput."""
    import jax

    from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh

    return create_mesh(MeshConfig(data=1), devices=jax.devices()[:1])


def _step_flops(
    trainer, batch, *, bundle: int = 1
) -> "float | None":
    """Analytic FLOPs/step from XLA's cost model on the train step.

    ``bundle == 1`` (unbundled benches): analyse the exact compiled
    executable — AOT lower+compile populates the jit cache (verified on
    this rig), so the bench pays the one compile it would pay anyway.
    Call BEFORE the first execution — the step donates its state
    buffers.

    ``bundle`` > 1 (bundled benches, which execute a DIFFERENT scanned
    program; ``batch`` is the [k, ...] stack): first try the
    single-step LOWERING only — no backend compile, so the
    never-executed single-step program costs no wedge-prone tunnel
    compile time. The axon plugin's pre-compile cost model returns
    None though (the round-5 first window banked bert/cifar10/mnist
    with no rel_mfu because of it), so when the lowering gives
    nothing, analyse the compiled BUNDLED program itself — the same
    executable the bench warms up anyway — and report flops / k.
    The record's "flops_analysis" key says which path produced the
    number (ADVICE r4)."""
    import jax

    _step_flops.last_mode = None

    def _flops_of(analysable) -> "float | None":
        ca = analysable.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        if ca is None:  # lowering-only analysis unsupported (axon)
            return None
        f = float(ca.get("flops", 0.0))
        # Only a usable value earns provenance (a zero-FLOPs result
        # returns None and must not label a later bench).
        return f if f > 0 else None

    try:
        one = jax.tree.map(lambda x: x[0], batch) if bundle > 1 else batch
        use_compiled = bundle == 1
        lowered = trainer._train_step.lower(trainer.state, one)
        f = _flops_of(lowered.compile() if use_compiled else lowered)
        if f is not None:
            _step_flops.last_mode = "compiled" if use_compiled else "lowered"
            return f
        if bundle > 1:
            bundled = trainer._build_bundled_step(bundle)
            f = _flops_of(bundled.lower(trainer.state, batch).compile())
            if f is not None:
                _step_flops.last_mode = "compiled-bundled/k"
                return f / bundle
        return None
    except Exception as e:  # cost model availability varies by backend
        print(f"bench: cost_analysis unavailable ({e})", file=sys.stderr)
        return None


# Read-once provenance for the most recent _step_flops call; _result
# consumes it into the record's "flops_analysis" key.
_step_flops.last_mode = None


def _time_steps(
    trainer, batches, steps, warmup, windows: int = WINDOWS, bundle: int = 1
):
    """Time jitted train steps over pre-placed device batches.

    Returns per-window wall times (seconds for ``steps`` steps each).
    State threads through all windows (the step donates its input).

    ``bundle`` > 1: ``batches`` are [k, batch, ...] stacks (from
    ``_bundle_prep``) and each launch is the steps_per_launch scanned
    step — ``steps`` still counts TRAIN steps, so windows time
    ``steps / bundle`` launches and throughput math is unchanged."""
    import jax

    step_fn = (
        trainer._train_step if bundle == 1 else trainer._build_bundled_step(bundle)
    )
    assert steps % bundle == 0, (steps, bundle)
    state = trainer.state
    for i in range(max(1, warmup // bundle)):
        state, m = step_fn(state, batches[i % len(batches)])
    jax.block_until_ready(m["loss"])
    dts = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for i in range(steps // bundle):
            state, m = step_fn(state, batches[i % len(batches)])
        jax.block_until_ready(m["loss"])
        dts.append(time.perf_counter() - t0)
    return dts


def _bundle_prep(trainer, it, n: int, bundle: int):
    """Pre-place ``n`` [bundle, batch, ...] stacks for bundled timing."""
    from tensorflow_examples_tpu.core.sharding import bundle_sharding
    from tensorflow_examples_tpu.data.prefetch import bundle_batches, put_batch

    sh = bundle_sharding(trainer.mesh)
    bb = bundle_batches(it, bundle)
    return [put_batch(next(bb), sh) for _ in range(n)]


def _throughput(dts, per_step_units, steps):
    """Per-window throughput values from per-window wall times."""
    return [steps * per_step_units / dt for dt in dts]


def _model_tflops(flops, steps, dt_window):
    """Analytic model TFLOP/s: per-step FLOPs × steps over one window's
    wall time (None when the cost model gave nothing)."""
    return flops * steps / dt_window / 1e12 if flops else None


# ------------------------------------------------------------- resnet-50


def _resnet50_trainer(batch: int):
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import imagenet

    cfg = imagenet.ImagenetConfig(
        global_batch_size=batch,
        precision="bf16",
        log_every=10**9,
        checkpoint_every=0,
        eval_every=0,
        train_steps=10**6,
        watchdog_secs=0,
    )
    return Trainer(imagenet.make_task(cfg), cfg, mesh=_chip_mesh()), cfg


def bench_resnet50() -> dict:
    """North-star: examples/sec/chip, synthetic data resident on device.

    CPU fallback shape (batch 4, 2-step windows, round 4): sized so the
    headline FITS the 540 s budget on a dead tunnel (~60 s/run warm) —
    at batch 8 × 3 steps the run alone was ~170 s and the headline kept
    getting truncated. Floor restamped with the shape (BASELINE.md)."""
    from tensorflow_examples_tpu.data import imagenet as imagenet_data

    batch = 256 if BACKEND == "tpu" else 4
    steps = 20 if BACKEND == "tpu" else 2
    warmup = 5 if BACKEND == "tpu" else 1
    trainer, cfg = _resnet50_trainer(batch)
    it = imagenet_data.synthetic_train_iter(
        batch, image_size=cfg.image_size, num_classes=cfg.num_classes, seed=0
    )
    batches = [trainer._put_batch(next(it)) for _ in range(2)]
    flops = _step_flops(trainer, batches[0])
    dts = _time_steps(trainer, batches, steps, warmup)
    dt_med = statistics.median(dts)
    return _result(
        "resnet50_examples_per_sec_per_chip",
        _throughput(dts, batch, steps),
        "examples/sec/chip",
        batch=batch,
        model_tflops_per_sec=_model_tflops(flops, steps, dt_med),
    )


def _write_bench_tfrecords(root: str, *, shards=4, per_shard=128, size=256):
    """Synthetic JPEG ImageNet-schema TFRecord shards for the input bench."""
    import numpy as np

    done = os.path.join(root, ".complete")
    if os.path.exists(done):
        return
    os.makedirs(root, exist_ok=True)
    import tensorflow as tf

    tf.config.set_visible_devices([], "GPU")
    rng = np.random.default_rng(0)
    for s in range(shards):
        path = os.path.join(root, f"train-{s:05d}-of-{shards:05d}")
        with tf.io.TFRecordWriter(path) as w:
            for _ in range(per_shard):
                img = rng.integers(0, 256, (size, size, 3), np.uint8)
                enc = tf.io.encode_jpeg(img).numpy()
                ex = tf.train.Example(
                    features=tf.train.Features(
                        feature={
                            "image/encoded": tf.train.Feature(
                                bytes_list=tf.train.BytesList(value=[enc])
                            ),
                            "image/class/label": tf.train.Feature(
                                int64_list=tf.train.Int64List(
                                    value=[int(rng.integers(1, 1001))]
                                )
                            ),
                        }
                    )
                ).SerializeToString()
                w.write(ex)
    with open(done, "w") as f:
        f.write("ok")


def bench_resnet50_input() -> dict:
    """North-star, host-pipeline-fed: TFRecord → decode → augment →
    C++ normalize → async device prefetch → train step."""
    import jax

    from tensorflow_examples_tpu.data import imagenet as imagenet_data
    from tensorflow_examples_tpu.data.prefetch import device_prefetch

    batch = 256 if BACKEND == "tpu" else 4
    steps = 10 if BACKEND == "tpu" else 2
    warmup = 3 if BACKEND == "tpu" else 1
    root = "/tmp/bench_imagenet_tfrecords"
    _write_bench_tfrecords(root)

    # Host-pipeline-only throughput (no device): isolates input cost.
    # ISSUE 6: measured through the sharded-parallel reader + worker-
    # pool pipeline (data/workers.py) — the production hot path — with
    # the worker count sized to the host.
    input_workers = max(2, min(8, os.cpu_count() or 1))
    input_readers = 2
    host_it = imagenet_data.parallel_tfrecord_iter(
        root, "train", batch, train=True,
        num_readers=input_readers, num_workers=input_workers,
    )
    next(host_it)  # warm the pool + native decode
    pipe_vals = []
    pipe_batches = 4 if BACKEND == "tpu" else 2
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        for _ in range(pipe_batches):
            next(host_it)
        pipe_vals.append(pipe_batches * batch / (time.perf_counter() - t0))
    host_it.close()  # drain worker/reader threads before the train feed

    trainer, cfg = _resnet50_trainer(batch)
    it = device_prefetch(
        imagenet_data.parallel_tfrecord_iter(
            root, "train", batch, train=True,
            num_readers=input_readers, num_workers=input_workers,
        ),
        trainer._batch_sharding,
    )
    flops = _step_flops(trainer, next(it))
    state = trainer.state
    for _ in range(warmup):
        state, m = trainer._train_step(state, next(it))
    jax.block_until_ready(m["loss"])
    dts = []
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = trainer._train_step(state, next(it))
        jax.block_until_ready(m["loss"])
        dts.append(time.perf_counter() - t0)
    dt_med = statistics.median(dts)
    return _result(
        "resnet50_input_examples_per_sec_per_chip",
        _throughput(dts, batch, steps),
        "examples/sec/chip",
        batch=batch,
        pipeline_only_images_per_sec=round(statistics.median(pipe_vals), 1),
        pipeline_only_windows=[round(v, 1) for v in sorted(pipe_vals)],
        input_workers=input_workers,
        input_readers=input_readers,
        model_tflops_per_sec=_model_tflops(flops, steps, dt_med),
    )


# ----------------------------------------------------------------- gpt-2


def bench_gpt2(
    steps=None,
    warmup=None,
    *,
    batch=None,
    seq=None,
    metric="gpt2_124m_tokens_per_sec",
    remat=False,
) -> dict:
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import gpt2

    tpu = BACKEND == "tpu"
    steps = steps if steps is not None else (30 if tpu else 3)
    warmup = warmup if warmup is not None else (5 if tpu else 1)
    batch = batch if batch is not None else (8 if tpu else 1)
    seq = seq if seq is not None else (1024 if tpu else 256)

    cfg = gpt2.Gpt2Config(
        global_batch_size=batch,
        seq_len=seq,
        dropout=0.0,
        precision="bf16",
        attention="flash" if tpu else "xla",
        fused_ce=tpu,
        remat=remat,
        log_every=10**9,
        checkpoint_every=0,
        train_steps=10**6,  # schedule horizon only
        watchdog_secs=0,
    )
    trainer = Trainer(gpt2.make_task(cfg), cfg, mesh=_chip_mesh())
    ds, _ = gpt2.datasets(cfg)
    it = train_iterator(ds, cfg.global_batch_size, seed=0)
    batches = [trainer._put_batch(next(it)) for _ in range(4)]
    flops = _step_flops(trainer, batches[0])
    dts = _time_steps(trainer, batches, steps, warmup)
    dt_med = statistics.median(dts)
    return _result(
        metric,
        _throughput(dts, batch * seq, steps),
        "tokens/sec/chip",
        batch=batch,
        seq=seq,
        model_tflops_per_sec=_model_tflops(flops, steps, dt_med),
    )


def bench_gpt2_long() -> dict:
    """Long-context variant: rematerialized blocks + blockwise attention."""
    tpu = BACKEND == "tpu"
    return bench_gpt2(
        steps=10 if tpu else 2,
        warmup=3 if tpu else 1,
        batch=2 if tpu else 1,
        seq=4096 if tpu else 512,
        metric="gpt2_long4k_tokens_per_sec",
        remat=True,
    )


def bench_gpt2_long16k() -> dict:
    """16k-token single-chip training step (VERDICT r1 item 6): possible
    because the flash kernel streams KV blocks through VMEM (grid over
    KV) instead of holding the whole sequence resident, and remat bounds
    activation memory. CPU fallback uses 1k (interpret-mode kernels)."""
    tpu = BACKEND == "tpu"
    return bench_gpt2(
        steps=4 if tpu else 2,
        warmup=2 if tpu else 1,
        batch=1,
        seq=16384 if tpu else 1024,
        metric="gpt2_long16k_tokens_per_sec",
        remat=True,
    )


def bench_gpt2_decode(
    *,
    prompt_len=None,
    dec=None,
    batch=None,
    seq_len=None,
    metric="gpt2_decode_tokens_per_sec",
) -> dict:
    """KV-cache sampling throughput (the reference's eval.py sampling
    path): prefill ``prompt_len``-token prompts, decode ``dec`` tokens
    per sequence through the static-shape cache, one jitted program.
    Attention runs the flash-decode kernel (ops/decode.py): O(context)
    cache reads per step, not O(max_len)."""
    import jax
    import jax.numpy as jnp

    from tensorflow_examples_tpu.models import transformer
    from tensorflow_examples_tpu.workloads import gpt2

    tpu = BACKEND == "tpu"
    batch = batch if batch is not None else (8 if tpu else 1)
    dec = dec if dec is not None else (128 if tpu else 16)
    prompt_len = prompt_len if prompt_len is not None else (128 if tpu else 16)
    cfg = (
        gpt2.Gpt2Config(
            dropout=0.0, **({"seq_len": seq_len} if seq_len else {})
        )
        if tpu
        else gpt2.Gpt2Config(
            vocab_size=256, seq_len=seq_len or 64, num_layers=2, num_heads=2,
            d_model=64, dropout=0.0,
        )
    )
    model = transformer.Transformer(gpt2.model_config(cfg))
    prompt = jnp.ones((batch, prompt_len), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, prompt)["params"]
    if tpu:
        params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)

    gen = jax.jit(
        lambda p, pr, rng: transformer.generate(
            model, p, pr, num_tokens=dec, rng=rng, temperature=1.0, top_k=40
        )
    )
    rng = jax.random.PRNGKey(1)
    # Analytic fwd FLOPs, hand-counted: XLA cost_analysis counts the
    # decode lax.scan body ONCE (not × trip count), so it can't be used
    # here. Matmuls: 2·(12·L·d²) per token + LM head 2·d·V per scored
    # position; attention: 4·d·n per layer per token attending n keys.
    L, d, V = cfg.num_layers, cfg.d_model, cfg.vocab_size
    t_p = prompt.shape[1]
    mat = 24 * L * d * d
    prefill = t_p * (mat + 2 * d * V) + 4 * d * L * t_p * (t_p + 1) // 2
    decode = dec * (mat + 2 * d * V) + 4 * d * L * (
        dec * t_p + dec * (dec - 1) // 2
    )
    flops = float(batch * (prefill + decode))
    gen(params, prompt, rng).block_until_ready()
    iters = 5 if tpu else 2
    dts = []
    for w in range(WINDOWS):
        t0 = time.perf_counter()
        for i in range(iters):
            out = gen(params, prompt, jax.random.PRNGKey(w * iters + i))
        out.block_until_ready()
        dts.append(time.perf_counter() - t0)
    vals = [iters * batch * dec / dt for dt in dts]
    dt_med = statistics.median(dts)
    return _result(
        metric,
        vals,
        "tokens/sec/chip",
        batch=batch,
        prefill_len=prompt_len,
        decode_len=dec,
        model_tflops_per_sec=_model_tflops(flops, iters, dt_med),
    )


def bench_gpt2_decode_long() -> dict:
    """Long-prefill sampling (VERDICT r2 item 4's 'impossible-today'
    shape): prefill 4096 tokens, decode 256, through a 4352-slot cache.
    The naive decode path would read the full static cache every step;
    the flash-decode kernel's scalar-prefetch clamp bounds each step's
    reads to the populated prefix."""
    tpu = BACKEND == "tpu"
    return bench_gpt2_decode(
        prompt_len=4096 if tpu else 48,
        dec=256 if tpu else 8,
        batch=4 if tpu else 1,
        seq_len=4352 if tpu else 64,
        metric="gpt2_decode_long_tokens_per_sec",
    )


def bench_bert() -> dict:
    """BERT-base GLUE fine-tune throughput (examples/sec/chip, seq 128)."""
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import bert_glue

    tpu = BACKEND == "tpu"
    cfg = bert_glue.BertGlueConfig(
        global_batch_size=32 if tpu else 4,
        precision="bf16" if tpu else "f32",
        dropout=0.0,
        log_every=10**9,
        checkpoint_every=0,
        eval_every=0,
        train_steps=10**6,
        watchdog_secs=0,
        **({} if tpu else dict(
            seq_len=32, vocab_size=512, num_layers=2, num_heads=2,
            d_model=32, d_ff=64,
        )),
    )
    # steps_per_launch bundling on TPU: the 1.2-1.7 ms/step regime is
    # per-launch dispatch-bound on this rig (BASELINE.md round-4
    # forensics), so the bench measures the framework's bundled loop —
    # the configuration a user would run this workload with. FLOPs come
    # from the single-step program (the scanned body is the same step).
    steps, warmup, bundle = (24, 8, 8) if tpu else (3, 1, 1)
    trainer = Trainer(bert_glue.make_task(cfg), cfg, mesh=_chip_mesh())
    ds, _ = bert_glue.datasets(cfg)
    it = train_iterator(ds, cfg.global_batch_size, seed=0)
    if bundle > 1:
        batches = _bundle_prep(trainer, it, 2, bundle)
        flops = _step_flops(trainer, batches[0], bundle=bundle)
    else:
        batches = [trainer._put_batch(next(it)) for _ in range(2)]
        flops = _step_flops(trainer, batches[0])
    dts = _time_steps(trainer, batches, steps, warmup, bundle=bundle)
    dt_med = statistics.median(dts)
    return _result(
        "bert_base_examples_per_sec_per_chip",
        _throughput(dts, cfg.global_batch_size, steps),
        "examples/sec/chip",
        batch=cfg.global_batch_size,
        seq=cfg.seq_len,
        bundle=bundle,
        model_tflops_per_sec=_model_tflops(flops, steps, dt_med),
    )


def bench_cifar10() -> dict:
    """CIFAR-10 ResNet-20 training throughput (single-device workload)."""
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.data.sources import synthetic_images
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import cifar10

    tpu = BACKEND == "tpu"
    cfg = cifar10.Cifar10Config(
        global_batch_size=128 if tpu else 16,
        precision="bf16" if tpu else "f32",
        log_every=10**9,
        checkpoint_every=0,
        eval_every=0,
        train_steps=10**6,
        watchdog_secs=0,
    )
    # Bundled on TPU: ~1.2 ms/step is dispatch-bound (rel_mfu 0.00044
    # in the round-4 record — the chip idles between launches); see
    # bench_bert for the rationale.
    steps, warmup, bundle = (32, 8, 8) if tpu else (3, 1, 1)
    trainer = Trainer(cifar10.make_task(cfg), cfg, mesh=_chip_mesh())
    ds = synthetic_images(n=2048, shape=(32, 32, 3), num_classes=10, seed=0)
    it = train_iterator(ds, cfg.global_batch_size, seed=0)
    if bundle > 1:
        batches = _bundle_prep(trainer, it, 2, bundle)
        flops = _step_flops(trainer, batches[0], bundle=bundle)
    else:
        batches = [trainer._put_batch(next(it)) for _ in range(4)]
        flops = _step_flops(trainer, batches[0])
    dts = _time_steps(trainer, batches, steps, warmup, bundle=bundle)
    dt_med = statistics.median(dts)
    return _result(
        "cifar10_resnet20_examples_per_sec_per_chip",
        _throughput(dts, cfg.global_batch_size, steps),
        "examples/sec/chip",
        batch=cfg.global_batch_size,
        bundle=bundle,
        model_tflops_per_sec=_model_tflops(flops, steps, dt_med),
    )


# ----------------------------------------------------------------- mnist


def bench_mnist() -> dict:
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.data.sources import synthetic_images
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import mnist

    # Bundled on TPU: at ~0.11 ms/step the launch IS the step cost;
    # ms/step under bundling is launch_time / k (see bench_bert).
    tpu = BACKEND == "tpu"
    steps, warmup, bundle = (200, 24, 8) if tpu else (50, 5, 1)
    cfg = mnist.MnistConfig(
        global_batch_size=256,
        precision="bf16",
        dropout=0.0,
        log_every=10**9,
        checkpoint_every=0,
        watchdog_secs=0,
    )
    ds = synthetic_images(n=4096, shape=(28, 28, 1), num_classes=10, seed=0)
    trainer = Trainer(mnist.make_task(cfg), cfg, mesh=_chip_mesh())
    it = train_iterator(ds, cfg.global_batch_size, seed=0)
    if bundle > 1:
        batches = _bundle_prep(trainer, it, 4, bundle)
        flops = _step_flops(trainer, batches[0], bundle=bundle)
    else:
        batches = [trainer._put_batch(next(it)) for _ in range(8)]
        flops = _step_flops(trainer, batches[0])
    dts = _time_steps(trainer, batches, steps, warmup, bundle=bundle)
    dt_med = statistics.median(dts)
    return _result(
        "mnist_mlp_step_time",
        [dt / steps * 1e3 for dt in dts],
        "ms/step",
        bundle=bundle,
        model_tflops_per_sec=_model_tflops(flops, steps, dt_med),
    )


# ----------------------------------------------------------- collectives


def bench_collectives() -> dict:
    """All-reduce / all-gather bus bandwidth over the device mesh
    (SURVEY.md §5h: replaces the reference stack's NCCL perf tests)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    devices = jax.devices()
    n = len(devices)
    mesh = Mesh(np.asarray(devices), ("x",))
    elems = (16 * 2**20) if BACKEND == "tpu" else (2 * 2**20)  # per device
    x = jnp.ones((n * elems,), jnp.float32)
    x = jax.device_put(x, NamedSharding(mesh, P("x")))

    @jax.jit
    def do_psum(x):
        return shard_map(
            lambda v: jax.lax.psum(v, "x"),
            mesh=mesh,
            in_specs=P("x"),
            out_specs=P("x"),
        )(x)

    @jax.jit
    def do_gather(x):
        # Gather then re-slice to the local shard: keeps out_specs P("x")
        # (replication inference fails on degenerate 1-device meshes).
        return shard_map(
            lambda v: jax.lax.all_gather(v, "x", tiled=True)[: v.shape[0]],
            mesh=mesh,
            in_specs=P("x"),
            out_specs=P("x"),
        )(x)

    def timed_windows(f, iters=10):
        f(x).block_until_ready()
        dts = []
        for _ in range(WINDOWS):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = f(x)
            out.block_until_ready()
            dts.append((time.perf_counter() - t0) / iters)
        return dts

    bytes_per_dev = elems * 4
    # Ring-algorithm bus bandwidth (the NCCL convention): payload scaled
    # by 2(n-1)/n for all-reduce, (n-1)/n for all-gather.
    scale_ar = 2 * (n - 1) / n if n > 1 else 1.0
    scale_ag = (n - 1) / n if n > 1 else 1.0
    ar_vals = [
        bytes_per_dev * scale_ar / t / 1e9 for t in timed_windows(do_psum)
    ]
    ag_vals = [
        bytes_per_dev * scale_ag / t / 1e9 for t in timed_windows(do_gather)
    ]
    return _result(
        "allreduce_busbw",
        ar_vals,
        "GB/s",
        n_devices=n,
        allgather_busbw_gbps=round(statistics.median(ag_vals), 2),
        allgather_windows=[round(v, 2) for v in sorted(ag_vals)],
        payload_mb_per_device=bytes_per_dev / 2**20,
    )


# ------------------------------------------------------------------- moe

_MOE_MESH_PROBE = r"""
import os
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
)
import jax
jax.config.update("jax_platforms", "cpu")
import collections, json, re
from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh
from tensorflow_examples_tpu.data.memory import train_iterator
from tensorflow_examples_tpu.train.loop import Trainer
from tensorflow_examples_tpu.workloads import gpt2
cfg = gpt2.Gpt2Config(
    vocab_size=512, seq_len=128, num_layers=2, num_heads=4, d_model=64,
    dropout=0.0, moe_experts=8, moe_top_k=2, moe_every=1,
    global_batch_size=8, precision="f32", log_every=10**9,
    checkpoint_every=0, watchdog_secs=0,
)
mesh = create_mesh(MeshConfig(data=2, model=4))
trainer = Trainer(gpt2.make_task(cfg, mesh), cfg, mesh=mesh)
ds, _ = gpt2.datasets(cfg)
batch = trainer._put_batch(next(train_iterator(ds, 8, seed=0)))
hlo = trainer._train_step.lower(trainer.state, batch).compile().as_text()
# Definition sites only: a plain substring count also matches operand
# REFERENCES (%all-reduce.12 as an argument) and overcounted ~2-3x in
# rounds 2-3 (BASELINE.md round-4 correction). Non-greedy shape so
# tuple-shaped collectives (lax.all_to_all lowers to one) match.
ops = collections.Counter(
    m.group(1)
    for m in re.finditer(
        r"^\s*(?:ROOT\s+)?%?[\w.\-]+ = (?:.+?) (all-to-all|all-reduce|"
        r"all-gather|reduce-scatter|collective-permute)(?:-start)?\(",
        hlo, re.M,
    )
)
print("MOE_COLLECTIVES " + json.dumps(dict(ops)))
"""


def _moe_mesh_collectives(timeout_s: float = 600.0) -> dict:
    """Compile the MoE train step on an 8-device dp×model CPU mesh in a
    subprocess and count the collectives XLA inserted for expert
    dispatch (VERDICT r2 item 8: EP's comm pattern must be measured,
    not assumed). Subprocess because the mesh needs its own CPU-pinned
    8-device runtime. Capped by the remaining wall budget — the census
    is a code property, not a perf number, so losing it to the budget
    costs nothing the test suite doesn't already cover."""
    timeout_s = min(timeout_s, _remaining() - 45.0)
    if timeout_s < 30.0:
        return {"skipped": "insufficient budget for mesh census"}
    try:
        r = subprocess.run(
            [sys.executable, "-c", _MOE_MESH_PROBE],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        for line in r.stdout.splitlines():
            if line.startswith("MOE_COLLECTIVES "):
                return json.loads(line.split(" ", 1)[1])
        return {"error": (r.stderr or r.stdout).strip()[-300:]}
    except subprocess.TimeoutExpired:
        return {"error": f"mesh probe timed out >{timeout_s:.0f}s"}


def moe_bench_config(moe_impl: str = ""):
    """The ONE moe-bench model/workload config, shared with
    tools/moe_diag.py so the diagnosis always times the exact program
    the ``moe_top2_tokens_per_sec`` record measures (a drifted copy
    would attribute the wrong workload)."""
    from tensorflow_examples_tpu.workloads import gpt2

    tpu = BACKEND == "tpu"
    batch = 8 if tpu else 1
    seq = 1024 if tpu else 128
    return gpt2.Gpt2Config(
        global_batch_size=batch,
        seq_len=seq,
        dropout=0.0,
        precision="bf16",
        attention="flash" if tpu else "xla",
        fused_ce=tpu,
        moe_experts=8,
        moe_top_k=2,
        moe_every=2,
        moe_impl=moe_impl,
        log_every=10**9,
        checkpoint_every=0,
        train_steps=10**6,
        watchdog_secs=0,
        **({} if tpu else dict(
            vocab_size=512, num_layers=2, num_heads=4, d_model=64
        )),
    )


def bench_moe() -> dict:
    """MoE GPT-2 training throughput (E=8, top-2, every 2nd block) on
    the chip, with the 8-device-mesh dispatch-collective census
    attached."""
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import gpt2

    tpu = BACKEND == "tpu"
    cfg = moe_bench_config()
    batch, seq = cfg.global_batch_size, cfg.seq_len
    steps, warmup = (20, 5) if tpu else (3, 1)
    trainer = Trainer(gpt2.make_task(cfg), cfg, mesh=_chip_mesh())
    ds, _ = gpt2.datasets(cfg)
    it = train_iterator(ds, cfg.global_batch_size, seed=0)
    batches = [trainer._put_batch(next(it)) for _ in range(4)]
    flops = _step_flops(trainer, batches[0])
    dts = _time_steps(trainer, batches, steps, warmup)
    dt_med = statistics.median(dts)
    return _result(
        "moe_top2_tokens_per_sec",
        _throughput(dts, batch * seq, steps),
        "tokens/sec/chip",
        batch=batch,
        seq=seq,
        experts=cfg.moe_experts,
        top_k=cfg.moe_top_k,
        mesh_dispatch_collectives=_moe_mesh_collectives(),
        model_tflops_per_sec=_model_tflops(flops, steps, dt_med),
    )


# ----------------------------------------------------------- decode grid


def bench_decode_grid() -> dict:
    """Single-token flash-decode step time vs cache max_len at a fixed
    short context (VERDICT r3 item 4): with the power-of-two KV-grid
    bucket ladder (ops/decode.py) the step must be ~flat in max_len —
    the headline value is t(32k)/t(4k), ~1.0 when sequencing is
    O(context) and ~8 if it were O(max_len). TPU-only: interpret mode
    would time the Python grid loop, not the chip."""
    import jax
    import jax.numpy as jnp

    from tensorflow_examples_tpu.ops.decode import flash_decode_attention

    if BACKEND != "tpu":
        raise RuntimeError(
            "tpu-only microbench (interpret mode times Python, not the chip)"
        )
    b, h, d, ctx = 8, 12, 64, 256
    iters = 50
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, 1, d), jnp.bfloat16)
    f = jax.jit(flash_decode_attention)
    per_len = {}
    for max_len in (4096, 16384, 32768):
        k = jax.random.normal(
            jax.random.PRNGKey(1), (b, h, max_len, d), jnp.bfloat16
        )
        v = jax.random.normal(
            jax.random.PRNGKey(2), (b, h, max_len, d), jnp.bfloat16
        )
        ln = jnp.asarray(ctx)
        f(q, k, v, ln).block_until_ready()
        ts = []
        for _ in range(WINDOWS):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = f(q, k, v, ln)
            out.block_until_ready()
            ts.append((time.perf_counter() - t0) / iters * 1e6)
        per_len[max_len] = statistics.median(ts)
    ratios = [per_len[32768] / per_len[4096]]
    return _result(
        "decode_grid_step_time_ratio",
        ratios,
        "x (32k cache / 4k cache, ctx 256)",
        context_len=ctx,
        us_per_step={str(k_): round(v_, 1) for k_, v_ in per_len.items()},
    )


# -------------------------------------------------------------- selftest


def run_selftest(timeout_s: float = 900.0, *, allow_banked: bool = False) -> dict:
    """Compiled-kernel parity on the live chip: run tests_tpu/ in a
    subprocess (hard timeout — the plugin can hang) and summarize.
    VERDICT r2 item 6: parity must be asserted on the real chip, not
    only in interpret mode on CPU. Capped by the remaining wall budget
    (it runs after the sweep, so truncation loses the selftest, never
    the perf record).

    ``allow_banked``: reuse a COMPLETE banked per-node selftest from
    the incremental harvest (backend-guarded: the bank must itself be
    a tpu record, not a cpu rehearsal). Only the post-sweep AUTO
    selftest passes this — a monolithic ``pytest tests_tpu/`` there is
    the exact pattern that wedged the round-3 window mid-compile, and
    re-proving what per-node bounded subprocesses already proved on
    silicon spends wedge-risk for nothing. An EXPLICIT ``--selftest``
    request always runs fresh (the banked evidence is only as new as
    the harvest's status files; clear those when kernel code changes)."""
    if allow_banked:
        try:
            with open(_banked_harvest_path()) as f:
                rec = json.load(f)
            banked = rec.get("selftest") or {}
            # The banked evidence must be about THESE kernel sources:
            # records carry the tests_tpu/+ops/ content hash from the
            # moment the nodes ran (tools/kernel_source_hash.py); after
            # an ops/ edit the hash diverges and the bank is stale
            # (ADVICE r4). Legacy records without the key never match.
            if (
                rec.get("backend") == "tpu"
                and banked.get("complete")
                and banked.get("ok")
                and banked.get("kernel_source_hash")
                == _kernel_source_hash()
            ):
                return {
                    "ok": True,
                    "summary": "banked harvest selftest reused: "
                    + banked.get("summary", "")[:220],
                }
        except Exception:
            pass
    timeout_s = min(timeout_s, _remaining() - 30.0)
    if timeout_s < 45.0:
        return {"ok": False, "summary": "skipped: insufficient budget"}
    t0 = time.perf_counter()
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        r = subprocess.run(
            [sys.executable, "-m", "pytest", "tests_tpu/", "-q", "-x"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=here,
        )
        lines = [l for l in r.stdout.strip().splitlines() if l.strip()]
        # Collection/usage failures report on stderr with empty stdout.
        if not lines:
            lines = [l for l in r.stderr.strip().splitlines() if l.strip()]
        tail = lines[-1] if lines else ""
        if r.returncode == 5:
            # "No tests collected": tests_tpu/conftest.py's backend
            # probe found no live TPU and ignored the modules — surface
            # its reason (printed on stderr) rather than pytest's tail.
            reason = next(
                (l for l in r.stderr.splitlines() if "tests_tpu:" in l),
                tail,
            )
            return {
                "ok": False,
                # Head-truncate: the verdict prefix must survive even
                # when the probe detail is long.
                "summary": ("no live TPU for compiled-kernel selftest — "
                            + reason)[:300],
                "seconds": round(time.perf_counter() - t0, 1),
            }
        return {
            "ok": r.returncode == 0,
            "summary": tail[-200:],
            "seconds": round(time.perf_counter() - t0, 1),
        }
    except subprocess.TimeoutExpired:
        return {"ok": False, "summary": f"selftest timed out >{timeout_s:.0f}s"}
    except Exception as e:
        return {"ok": False, "summary": f"{type(e).__name__}: {e}"}


# ------------------------------------------------------------------ main

BENCHES = {
    "resnet50": bench_resnet50,
    "resnet50_input": bench_resnet50_input,
    "gpt2": bench_gpt2,
    "gpt2_long": bench_gpt2_long,
    "gpt2_long16k": bench_gpt2_long16k,
    "gpt2_decode": bench_gpt2_decode,
    "gpt2_decode_long": bench_gpt2_decode_long,
    "bert": bench_bert,
    "cifar10": bench_cifar10,
    "mnist": bench_mnist,
    "collectives": bench_collectives,
    "moe": bench_moe,
    "decode_grid": bench_decode_grid,
}

# Headline-first order for --bench=all.
ALL_ORDER = [
    "resnet50",
    "resnet50_input",
    "gpt2",
    "gpt2_long",
    "gpt2_long16k",
    "gpt2_decode",
    "gpt2_decode_long",
    "bert",
    "cifar10",
    "mnist",
    "collectives",
    "moe",
    "decode_grid",
]


# Conservative per-bench wall estimates (compile + windows, COLD compile
# cache) used only to ORDER execution cheapest-first (the skip decision
# is a fixed remaining-time threshold in run_all); a completed bench
# records its true cost in "bench_seconds".
_EST_SECONDS = {
    "cpu": {
        "resnet50": 80, "resnet50_input": 150, "gpt2": 75, "gpt2_long": 90,
        "gpt2_long16k": 120, "gpt2_decode": 60, "gpt2_decode_long": 60,
        "bert": 50, "cifar10": 70, "mnist": 45, "collectives": 60,
        "moe": 180, "decode_grid": 1,
    },
    "tpu": {
        "resnet50": 90, "resnet50_input": 150, "gpt2": 75, "gpt2_long": 75,
        "gpt2_long16k": 90, "gpt2_decode": 75, "gpt2_decode_long": 75,
        "bert": 60, "cifar10": 60, "mnist": 60, "collectives": 45,
        "moe": 180, "decode_grid": 90,
    },
}


def run_bench(name: str) -> dict:
    """Probe the rig immediately before the bench, run it, attach the
    drift-cancelled rel_mfu (see module docstring)."""
    global _IN_FLIGHT
    _IN_FLIGHT = name
    t0 = time.perf_counter()
    try:
        probe = _probe_quick()
        r = BENCHES[name]()
    except Exception as e:  # one bench failing must not kill output
        return {"metric": name, "bench": name, "error": f"{type(e).__name__}: {e}"}
    r["bench"] = name
    r["probe_tflops_at_bench"] = round(probe, 2)
    r["bench_seconds"] = round(time.perf_counter() - t0, 1)
    try:
        r["probe_launch_us_at_bench"] = round(_probe_launch_us(), 2)
    except Exception:  # a dying backend mid-probe must not lose the bench
        pass
    mt = r.get("model_tflops_per_sec")
    if mt:
        r["rel_mfu"] = round(mt / probe, 5)
        mfu_floor = REL_MFU_FLOORS.get(BACKEND, {}).get(r["metric"])
        if mfu_floor:
            r["rel_mfu_vs_floor"] = round(r["rel_mfu"] / mfu_floor, 4)
    return r


def run_all() -> None:
    """Run the sweep cheapest-first (estimated cold-compile cost), so
    the budget buys the maximum number of completed benches; _assemble
    re-sorts the record headline-first. A bench is attempted whenever
    >60 s remain — over-running is safe (the watchdog emits everything
    completed so far) and execution is cost-ascending, so attempting
    strictly dominates skipping. Appends to module result state so the
    watchdog can emit a partial record at any instant."""
    global _IN_FLIGHT
    est = _EST_SECONDS.get(BACKEND, {})
    for name in sorted(ALL_ORDER, key=lambda n: est.get(n, 60)):
        if _remaining() < 60:
            # Recorded as truncated by _assemble's planned-minus-done
            # sweep accounting; just log the decision here.
            print(
                f"bench: skipping {name} ({_remaining():.0f}s left)",
                file=sys.stderr,
            )
            continue
        _RESULTS.append(run_bench(name))
        # Cleared only after the result is recorded: a watchdog firing
        # mid-bench must see it as in-flight OR completed, never neither.
        _IN_FLIGHT = None


def main() -> int:
    global BACKEND, _DEADLINE, _IN_FLIGHT
    which = "all"
    selftest = None  # None = auto (on for TPU full sweeps)

    def _parse_budget(s: str, fallback: float = 540.0) -> float:
        try:
            return float(s)
        except ValueError:
            print(f"bench: bad budget {s!r}; using {fallback}", file=sys.stderr)
            return fallback

    budget = _parse_budget(os.environ.get("BENCH_BUDGET_S", "540"))
    for a in sys.argv[1:]:
        if a.startswith("--bench="):
            which = a.split("=", 1)[1]
        elif a == "--selftest":
            selftest = True
        elif a == "--no-selftest":
            selftest = False
        elif a.startswith("--budget="):
            budget = _parse_budget(a.split("=", 1)[1], budget)
    known = set(BENCHES) | {"all", "selftest"}
    if which not in known:
        _emit({"error": f"unknown --bench={which}", "known": sorted(known)})
        return 0
    if which == "all":
        # Before ANYTHING that can block (backend probe, fingerprint):
        # a watchdog firing pre-sweep must still list the whole plan.
        _SWEEP_PLANNED.extend(ALL_ORDER)
    watchdog = None
    if budget > 0:
        _DEADLINE = time.monotonic() + budget
        _META["budget_s"] = budget
        # Backstop fires shortly before the budget so the emit beats an
        # outer `timeout <budget+60>`; daemon thread survives a main
        # thread wedged inside a native compile.
        watchdog = threading.Timer(max(budget - 15.0, 5.0), _watchdog_fire)
        watchdog.daemon = True
        watchdog.start()
    try:
        BACKEND = _resolve_backend()
        _META["backend"] = BACKEND
        if which == "selftest":
            _emit(
                {
                    "metric": "selftest",
                    "selftest": run_selftest(),  # explicit: always fresh
                    "backend": BACKEND,
                }
            )
            return 0
        fp_pre = round(fingerprint_tflops(), 2)
        # Back-compat scalar stamp: the pre-sweep median.
        _META["fingerprint_tflops_pre"] = _META["fingerprint_tflops"] = fp_pre
        try:
            _META["fingerprint_launch_us_pre"] = round(_probe_launch_us(), 2)
        except Exception:  # transient probe death must not abort the sweep
            pass
        if which == "all":
            run_all()
        else:
            _RESULTS.append(run_bench(which))
            _IN_FLIGHT = None
        _META["fingerprint_tflops_post"] = round(fingerprint_tflops(), 2)
        try:
            _META["fingerprint_launch_us_post"] = round(_probe_launch_us(), 2)
        except Exception:  # the selftest below must still get its budget
            pass
        # Selftest runs AFTER the sweep: on a live TPU with a cold cache
        # the budget should be spent on perf evidence first, and the
        # selftest cap consumes whatever is left.
        if selftest or (selftest is None and which == "all" and BACKEND == "tpu"):
            # Auto post-sweep selftest may reuse complete banked evidence.
            _META["selftest"] = run_selftest(allow_banked=selftest is None)
    except Exception as e:
        # Keyed so it can never clobber a completed headline's "metric"
        # (out.update(_META) in _assemble); _assemble already supplies
        # {"error": "no bench completed"} when nothing finished.
        _META["sweep_error"] = f"{type(e).__name__}: {e}"
    finally:
        if watchdog is not None:
            watchdog.cancel()
        _emit()
    return 0


if __name__ == "__main__":
    sys.exit(main())
