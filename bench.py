#!/usr/bin/env python
"""Framework benchmark — prints ONE JSON line for the driver.

Metric (BASELINE.md): MNIST MLP step-time on one TPU chip. The reference
published no numbers (BASELINE.json:published == {}), so vs_baseline is
measured against the first bring-up value recorded in BASELINE.md (the
regression floor): vs_baseline = floor_ms / measured_ms, >1.0 == faster
than the floor.
"""

import json
import sys
import time

# First-measured regression floors (BASELINE.md "Measured baselines" table).
FLOORS_MS = {
    "mnist_mlp_step_time": 0.0702,
}


def bench_mnist_step(steps: int = 200, warmup: int = 20) -> dict:
    import jax

    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.data.sources import synthetic_images
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import mnist

    cfg = mnist.MnistConfig(
        global_batch_size=256, precision="bf16", dropout=0.0, log_every=10**9
    )
    ds = synthetic_images(n=4096, shape=(28, 28, 1), num_classes=10, seed=0)
    trainer = Trainer(mnist.make_task(cfg), cfg)
    it = train_iterator(ds, cfg.global_batch_size, seed=0)

    batches = [trainer._put_batch(next(it)) for _ in range(8)]
    state = trainer.state
    for i in range(warmup):
        state, m = trainer._train_step(state, batches[i % len(batches)])
    jax.block_until_ready(m["loss"])

    t0 = time.perf_counter()
    for i in range(steps):
        state, m = trainer._train_step(state, batches[i % len(batches)])
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0

    step_ms = dt / steps * 1e3
    return {
        "metric": "mnist_mlp_step_time",
        "value": round(step_ms, 4),
        "unit": "ms/step",
        "vs_baseline": round(FLOORS_MS["mnist_mlp_step_time"] / step_ms, 4),
    }


def main():
    result = bench_mnist_step()
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
