#!/usr/bin/env python
"""Framework benchmark — prints ONE JSON line for the driver.

Headline metric: GPT-2 124M training throughput (tokens/sec/chip) on one
TPU chip — bf16 compute, Pallas flash attention, fused Pallas
cross-entropy, whole step in one jitted XLA program. The reference
published no numbers (BASELINE.json:published == {}), so vs_baseline is
measured against the first bring-up value recorded in BASELINE.md (the
regression floor): vs_baseline = measured / floor, >1.0 == faster.

Secondary benches (run with --bench=mnist): MNIST MLP step-time.
"""

import json
import sys
import time

# First-measured regression floors (BASELINE.md "Measured baselines" table).
FLOORS = {
    "gpt2_124m_tokens_per_sec": 3224304.0,  # first bring-up, 2026-07-29
    # 0.0 = no floor measured yet on this rig; vs_baseline reports 1.0
    # until a first TPU run's value is recorded here (TPU tunnel was down
    # at authoring time).
    "gpt2_long4k_tokens_per_sec": 0.0,
    "mnist_mlp_step_time_ms": 0.0702,
}

BATCH = 8
SEQ = 1024


def bench_gpt2(
    steps: int = 30,
    warmup: int = 5,
    *,
    batch: int = BATCH,
    seq: int = SEQ,
    metric: str = "gpt2_124m_tokens_per_sec",
    remat: bool = False,
) -> dict:
    import jax

    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import gpt2

    cfg = gpt2.Gpt2Config(
        global_batch_size=batch,
        seq_len=seq,
        dropout=0.0,
        precision="bf16",
        attention="flash",
        fused_ce=True,
        remat=remat,
        log_every=10**9,
        checkpoint_every=0,
        train_steps=10**6,  # schedule horizon only
        watchdog_secs=0,
    )
    trainer = Trainer(gpt2.make_task(cfg), cfg)
    ds, _ = gpt2.datasets(cfg)
    it = train_iterator(ds, cfg.global_batch_size, seed=0)
    batches = [trainer._put_batch(next(it)) for _ in range(4)]

    state = trainer.state
    for i in range(warmup):
        state, m = trainer._train_step(state, batches[i % len(batches)])
    jax.block_until_ready(state.params)

    t0 = time.perf_counter()
    for i in range(steps):
        state, m = trainer._train_step(state, batches[i % len(batches)])
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0

    tok_per_sec = steps * batch * seq / dt
    floor = FLOORS.get(metric, 0.0)
    return {
        "metric": metric,
        "value": round(tok_per_sec, 1),
        "unit": "tokens/sec/chip",
        # No recorded floor -> 1.0 by definition (first measurement IS
        # the floor; see FLOORS comment).
        "vs_baseline": round(tok_per_sec / floor, 4) if floor else 1.0,
    }


def bench_mnist(steps: int = 200, warmup: int = 20) -> dict:
    import jax

    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.data.sources import synthetic_images
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import mnist

    cfg = mnist.MnistConfig(
        global_batch_size=256, precision="bf16", dropout=0.0, log_every=10**9
    )
    ds = synthetic_images(n=4096, shape=(28, 28, 1), num_classes=10, seed=0)
    trainer = Trainer(mnist.make_task(cfg), cfg)
    it = train_iterator(ds, cfg.global_batch_size, seed=0)

    batches = [trainer._put_batch(next(it)) for _ in range(8)]
    state = trainer.state
    for i in range(warmup):
        state, m = trainer._train_step(state, batches[i % len(batches)])
    jax.block_until_ready(m["loss"])

    t0 = time.perf_counter()
    for i in range(steps):
        state, m = trainer._train_step(state, batches[i % len(batches)])
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0

    step_ms = dt / steps * 1e3
    return {
        "metric": "mnist_mlp_step_time",
        "value": round(step_ms, 4),
        "unit": "ms/step",
        "vs_baseline": round(FLOORS["mnist_mlp_step_time_ms"] / step_ms, 4),
    }


BENCHES = {
    "gpt2": lambda: bench_gpt2(),
    # Long-context: 4k tokens, rematerialized blocks, flash attention —
    # the memory/FLOPs trade the blockwise kernel exists for.
    "gpt2_long": lambda: bench_gpt2(
        steps=10, warmup=3, batch=2, seq=4096,
        metric="gpt2_long4k_tokens_per_sec", remat=True,
    ),
    "mnist": lambda: bench_mnist(),
}


def main():
    which = "gpt2"
    for a in sys.argv[1:]:
        if a.startswith("--bench="):
            which = a.split("=", 1)[1]
    if which not in BENCHES:
        raise SystemExit(f"unknown --bench={which}; one of {sorted(BENCHES)}")
    print(json.dumps(BENCHES[which]()))


if __name__ == "__main__":
    sys.exit(main())
