#!/usr/bin/env python
"""Framework benchmark — prints exactly ONE JSON line for the driver.

North-star metric (BASELINE.json:metric): **ResNet-50 ImageNet
examples/sec/chip**, measured two ways so input-pipeline cost is visible
separately (SURVEY.md §3(4), §7 hard-part (a)):

- ``resnet50``        — synthetic batches already resident on device
                        (pure compute ceiling).
- ``resnet50_input``  — fed by the real host pipeline: tf.data TFRecord
                        shards → JPEG decode → augment → threaded C++
                        normalize → async device prefetch.

Secondary benches: GPT-2 124M tokens/sec (``gpt2``, ``gpt2_long``),
MNIST step-time (``mnist``), ICI/mesh collective bandwidth
(``collectives``). ``--bench=all`` (the default) runs the suite and
emits the north-star as the headline with the rest under ``"extras"``.

Driver robustness (VERDICT.md round 1): this rig's TPU plugin can HANG
during backend init — not just raise — so the ambient backend is probed
in a subprocess with a hard timeout; on failure the bench falls back to
an in-process CPU pin and tags the output ``"backend": "cpu"``. Any
failure still prints one parseable JSON line and exits 0.

The reference published no numbers (BASELINE.json:published == {}), so
``vs_baseline`` compares against the first value measured on each
backend (the regression floor, recorded in FLOORS/BASELINE.md). Each
floor carries the rig fingerprint (raw bf16 matmul TFLOP/s) measured
alongside it, and the current fingerprint is emitted with every result,
so cross-round comparability is machine-checkable (BASELINE.md:25: the
tunnel has reported impossible absolute numbers before).
"""

import json
import os
import subprocess
import sys
import time

# Regression floors: first (value, rig_fingerprint_tflops) measured per
# (backend, metric). The fingerprint is the raw-matmul probe AT THE TIME
# that floor was taken — this tunnel's behavior drifts 31k–61k TFLOP/s
# between runs, so vs_baseline is only interpretable next to the
# fingerprint pair, which every result emits (floor's and current).
# r1's gpt2=3224304 tok/s and mnist=0.0702 ms were taken at the 61k
# fingerprint and are kept as history in BASELINE.md, not floors.
FLOORS = {
    "tpu": {
        # 2026-07-29 round-2 measurements.
        "resnet50_examples_per_sec_per_chip": (62392.0, 31055.0),
        "resnet50_input_examples_per_sec_per_chip": (88.2, 31055.0),  # 1-CPU host!
        "gpt2_124m_tokens_per_sec": (2931492.0, 31055.0),
        "gpt2_long4k_tokens_per_sec": (2861037.0, 31055.0),
        "gpt2_long16k_tokens_per_sec": (4157890.0, 31055.0),
        "gpt2_decode_tokens_per_sec": (1808924.0, 44536.0),
        "bert_base_examples_per_sec_per_chip": (22286.0, 42508.0),
        "cifar10_resnet20_examples_per_sec_per_chip": (242176.0, 46991.0),
        "mnist_mlp_step_time": (0.18, 31055.0),  # ms/step
        "allreduce_busbw": (3396.0, 31055.0),  # GB/s, n=1 loopback
    },
    "cpu": {
        # 2026-07-29 round 2 first CPU-fallback measurements (this host).
        "resnet50_examples_per_sec_per_chip": (0.62, 0.08),
        "resnet50_input_examples_per_sec_per_chip": (0.63, 0.08),
        "gpt2_124m_tokens_per_sec": (48.4, 0.08),
        "mnist_mlp_step_time": (2.39, 0.08),  # ms/step
    },
}

BACKEND = "cpu"  # resolved in main()


def _probe_backend(timeout_s: float = 120.0):
    """Probe the ambient jax backend in a subprocess (it can hang)."""
    code = (
        "import jax, sys\n"
        "d = jax.devices()\n"
        "sys.stdout.write('PROBE %s %d\\n' % (d[0].platform, len(d)))\n"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return None, 0, f"backend init hung >{timeout_s:.0f}s"
    for line in r.stdout.splitlines():
        if line.startswith("PROBE "):
            _, plat, n = line.split()
            return plat, int(n), None
    return None, 0, (r.stderr or r.stdout).strip()[-400:] or "probe failed"


def _resolve_backend() -> str:
    """Pick a live backend; pin CPU in-process if the default is dead.

    The env-var route (JAX_PLATFORMS=cpu) does NOT work on this rig —
    sitecustomize pre-imports jax — so the fallback is the in-process
    config pin, same as tests/conftest.py.
    """
    plat, _n, err = _probe_backend()
    if plat is None or plat == "cpu":
        # 8 virtual devices so the collectives bench exercises a real
        # mesh; workload benches pin a 1-device mesh (per-chip metrics).
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            )
        import jax

        jax.config.update("jax_platforms", "cpu")
        if err:
            print(
                f"bench: default backend unusable ({err}); CPU fallback",
                file=sys.stderr,
            )
        return "cpu"
    return "tpu"  # axon / tpu / anything accelerator-shaped


def fingerprint_tflops() -> float:
    """Raw big-matmul probe: the rig behavior stamp for FLOORS entries."""
    import jax
    import jax.numpy as jnp

    n = 8192 if BACKEND == "tpu" else 1024
    dtype = jnp.bfloat16 if BACKEND == "tpu" else jnp.float32
    k = jax.random.PRNGKey(0)
    a = jax.random.normal(k, (n, n), dtype)
    b = jax.random.normal(k, (n, n), dtype)
    f = jax.jit(lambda a, b: a @ b)
    f(a, b).block_until_ready()
    iters = 10 if BACKEND == "tpu" else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(a, b)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    return 2 * n**3 * iters / dt / 1e12


def _result(metric: str, value: float, unit: str, **extra) -> dict:
    floor, floor_fp = FLOORS.get(BACKEND, {}).get(metric, (0.0, 0.0))
    if "step_time" in metric or "ms" in unit:
        vs = floor / value if floor else 1.0  # lower is better
    else:
        vs = value / floor if floor else 1.0
    return {
        "metric": metric,
        "value": round(value, 4),
        "unit": unit,
        "vs_baseline": round(vs, 4),
        # The fingerprint this metric's floor was measured at — compare
        # with the top-level current fingerprint before reading
        # vs_baseline as a real regression/improvement.
        "floor_fingerprint_tflops": floor_fp,
        **extra,
    }


def _chip_mesh():
    """1-device mesh: workload benches measure per-chip throughput."""
    import jax

    from tensorflow_examples_tpu.core.mesh import MeshConfig, create_mesh

    return create_mesh(MeshConfig(data=1), devices=jax.devices()[:1])


def _time_steps(trainer, batches, steps, warmup):
    """Time jitted train steps over pre-placed device batches."""
    import jax

    state = trainer.state
    for i in range(warmup):
        state, m = trainer._train_step(state, batches[i % len(batches)])
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for i in range(steps):
        state, m = trainer._train_step(state, batches[i % len(batches)])
    jax.block_until_ready(m["loss"])
    return time.perf_counter() - t0


# ------------------------------------------------------------- resnet-50


def _resnet50_trainer(batch: int):
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import imagenet

    cfg = imagenet.ImagenetConfig(
        global_batch_size=batch,
        precision="bf16",
        log_every=10**9,
        checkpoint_every=0,
        eval_every=0,
        train_steps=10**6,
        watchdog_secs=0,
    )
    return Trainer(imagenet.make_task(cfg), cfg, mesh=_chip_mesh()), cfg


def bench_resnet50() -> dict:
    """North-star: examples/sec/chip, synthetic data resident on device."""
    from tensorflow_examples_tpu.data import imagenet as imagenet_data

    batch = 256 if BACKEND == "tpu" else 8
    steps = 20 if BACKEND == "tpu" else 3
    warmup = 5 if BACKEND == "tpu" else 1
    trainer, cfg = _resnet50_trainer(batch)
    it = imagenet_data.synthetic_train_iter(
        batch, image_size=cfg.image_size, num_classes=cfg.num_classes, seed=0
    )
    batches = [trainer._put_batch(next(it)) for _ in range(2)]
    dt = _time_steps(trainer, batches, steps, warmup)
    return _result(
        "resnet50_examples_per_sec_per_chip",
        steps * batch / dt,
        "examples/sec/chip",
        batch=batch,
    )


def _write_bench_tfrecords(root: str, *, shards=4, per_shard=128, size=256):
    """Synthetic JPEG ImageNet-schema TFRecord shards for the input bench."""
    import numpy as np

    done = os.path.join(root, ".complete")
    if os.path.exists(done):
        return
    os.makedirs(root, exist_ok=True)
    import tensorflow as tf

    tf.config.set_visible_devices([], "GPU")
    rng = np.random.default_rng(0)
    for s in range(shards):
        path = os.path.join(root, f"train-{s:05d}-of-{shards:05d}")
        with tf.io.TFRecordWriter(path) as w:
            for _ in range(per_shard):
                img = rng.integers(0, 256, (size, size, 3), np.uint8)
                enc = tf.io.encode_jpeg(img).numpy()
                ex = tf.train.Example(
                    features=tf.train.Features(
                        feature={
                            "image/encoded": tf.train.Feature(
                                bytes_list=tf.train.BytesList(value=[enc])
                            ),
                            "image/class/label": tf.train.Feature(
                                int64_list=tf.train.Int64List(
                                    value=[int(rng.integers(1, 1001))]
                                )
                            ),
                        }
                    )
                ).SerializeToString()
                w.write(ex)
    with open(done, "w") as f:
        f.write("ok")


def bench_resnet50_input() -> dict:
    """North-star, host-pipeline-fed: TFRecord → decode → augment →
    C++ normalize → async device prefetch → train step."""
    from tensorflow_examples_tpu.data import imagenet as imagenet_data
    from tensorflow_examples_tpu.data.prefetch import device_prefetch

    batch = 256 if BACKEND == "tpu" else 8
    steps = 20 if BACKEND == "tpu" else 3
    warmup = 5 if BACKEND == "tpu" else 1
    root = "/tmp/bench_imagenet_tfrecords"
    _write_bench_tfrecords(root)

    # Host-pipeline-only throughput (no device): isolates input cost.
    host_it = imagenet_data.tfrecord_iter(root, "train", batch, train=True)
    next(host_it)  # warm tf.data
    t0 = time.perf_counter()
    pipe_batches = 8 if BACKEND == "tpu" else 4
    for _ in range(pipe_batches):
        next(host_it)
    pipeline_eps = pipe_batches * batch / (time.perf_counter() - t0)

    trainer, cfg = _resnet50_trainer(batch)
    it = device_prefetch(
        imagenet_data.tfrecord_iter(root, "train", batch, train=True),
        trainer._batch_sharding,
    )
    import jax

    state = trainer.state
    for _ in range(warmup):
        state, m = trainer._train_step(state, next(it))
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = trainer._train_step(state, next(it))
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0
    return _result(
        "resnet50_input_examples_per_sec_per_chip",
        steps * batch / dt,
        "examples/sec/chip",
        batch=batch,
        pipeline_only_images_per_sec=round(pipeline_eps, 1),
    )


# ----------------------------------------------------------------- gpt-2


def bench_gpt2(
    steps=None,
    warmup=None,
    *,
    batch=None,
    seq=None,
    metric="gpt2_124m_tokens_per_sec",
    remat=False,
) -> dict:
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import gpt2

    tpu = BACKEND == "tpu"
    steps = steps if steps is not None else (30 if tpu else 3)
    warmup = warmup if warmup is not None else (5 if tpu else 1)
    batch = batch if batch is not None else (8 if tpu else 1)
    seq = seq if seq is not None else (1024 if tpu else 256)

    cfg = gpt2.Gpt2Config(
        global_batch_size=batch,
        seq_len=seq,
        dropout=0.0,
        precision="bf16",
        attention="flash" if tpu else "xla",
        fused_ce=tpu,
        remat=remat,
        log_every=10**9,
        checkpoint_every=0,
        train_steps=10**6,  # schedule horizon only
        watchdog_secs=0,
    )
    trainer = Trainer(gpt2.make_task(cfg), cfg, mesh=_chip_mesh())
    ds, _ = gpt2.datasets(cfg)
    it = train_iterator(ds, cfg.global_batch_size, seed=0)
    batches = [trainer._put_batch(next(it)) for _ in range(4)]
    dt = _time_steps(trainer, batches, steps, warmup)
    return _result(
        metric, steps * batch * seq / dt, "tokens/sec/chip", batch=batch, seq=seq
    )


def bench_gpt2_long() -> dict:
    """Long-context variant: rematerialized blocks + blockwise attention."""
    tpu = BACKEND == "tpu"
    return bench_gpt2(
        steps=10 if tpu else 2,
        warmup=3 if tpu else 1,
        batch=2 if tpu else 1,
        seq=4096 if tpu else 512,
        metric="gpt2_long4k_tokens_per_sec",
        remat=True,
    )


def bench_gpt2_long16k() -> dict:
    """16k-token single-chip training step (VERDICT r1 item 6): possible
    because the flash kernel streams KV blocks through VMEM (grid over
    KV) instead of holding the whole sequence resident, and remat bounds
    activation memory. CPU fallback uses 1k (interpret-mode kernels)."""
    tpu = BACKEND == "tpu"
    return bench_gpt2(
        steps=4 if tpu else 2,
        warmup=2 if tpu else 1,
        batch=1,
        seq=16384 if tpu else 1024,
        metric="gpt2_long16k_tokens_per_sec",
        remat=True,
    )


def bench_gpt2_decode() -> dict:
    """KV-cache sampling throughput (the reference's eval.py sampling
    path): prefill 128-token prompts, decode 128 tokens per sequence
    through the static-shape cache, one jitted program."""
    import jax
    import jax.numpy as jnp

    from tensorflow_examples_tpu.models import transformer
    from tensorflow_examples_tpu.workloads import gpt2

    tpu = BACKEND == "tpu"
    batch = 8 if tpu else 1
    dec = 128 if tpu else 16
    cfg = (
        gpt2.Gpt2Config(dropout=0.0, attention="xla")
        if tpu
        else gpt2.Gpt2Config(
            vocab_size=256, seq_len=64, num_layers=2, num_heads=2,
            d_model=64, dropout=0.0, attention="xla",
        )
    )
    model = transformer.Transformer(gpt2.model_config(cfg))
    prompt = jnp.ones((batch, 128 if tpu else 16), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, prompt)["params"]
    if tpu:
        params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)

    gen = jax.jit(
        lambda p, pr, rng: transformer.generate(
            model, p, pr, num_tokens=dec, rng=rng, temperature=1.0, top_k=40
        )
    )
    rng = jax.random.PRNGKey(1)
    gen(params, prompt, rng).block_until_ready()
    iters = 5 if tpu else 2
    t0 = time.perf_counter()
    for i in range(iters):
        out = gen(params, prompt, jax.random.PRNGKey(i))
    out.block_until_ready()
    dt = time.perf_counter() - t0
    return _result(
        "gpt2_decode_tokens_per_sec",
        iters * batch * dec / dt,
        "tokens/sec/chip",
        batch=batch,
        decode_len=dec,
    )


def bench_bert() -> dict:
    """BERT-base GLUE fine-tune throughput (examples/sec/chip, seq 128)."""
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import bert_glue

    tpu = BACKEND == "tpu"
    cfg = bert_glue.BertGlueConfig(
        global_batch_size=32 if tpu else 4,
        precision="bf16" if tpu else "f32",
        dropout=0.0,
        log_every=10**9,
        checkpoint_every=0,
        eval_every=0,
        train_steps=10**6,
        watchdog_secs=0,
        **({} if tpu else dict(
            seq_len=32, vocab_size=512, num_layers=2, num_heads=2,
            d_model=32, d_ff=64,
        )),
    )
    steps, warmup = (20, 5) if tpu else (3, 1)
    trainer = Trainer(bert_glue.make_task(cfg), cfg, mesh=_chip_mesh())
    ds, _ = bert_glue.datasets(cfg)
    it = train_iterator(ds, cfg.global_batch_size, seed=0)
    batches = [trainer._put_batch(next(it)) for _ in range(2)]
    dt = _time_steps(trainer, batches, steps, warmup)
    return _result(
        "bert_base_examples_per_sec_per_chip",
        steps * cfg.global_batch_size / dt,
        "examples/sec/chip",
        batch=cfg.global_batch_size,
        seq=cfg.seq_len,
    )


def bench_cifar10() -> dict:
    """CIFAR-10 ResNet-20 training throughput (single-device workload)."""
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.data.sources import synthetic_images
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import cifar10

    tpu = BACKEND == "tpu"
    cfg = cifar10.Cifar10Config(
        global_batch_size=128 if tpu else 16,
        precision="bf16" if tpu else "f32",
        log_every=10**9,
        checkpoint_every=0,
        eval_every=0,
        train_steps=10**6,
        watchdog_secs=0,
    )
    steps, warmup = (30, 5) if tpu else (3, 1)
    trainer = Trainer(cifar10.make_task(cfg), cfg, mesh=_chip_mesh())
    ds = synthetic_images(n=2048, shape=(32, 32, 3), num_classes=10, seed=0)
    it = train_iterator(ds, cfg.global_batch_size, seed=0)
    batches = [trainer._put_batch(next(it)) for _ in range(4)]
    dt = _time_steps(trainer, batches, steps, warmup)
    return _result(
        "cifar10_resnet20_examples_per_sec_per_chip",
        steps * cfg.global_batch_size / dt,
        "examples/sec/chip",
        batch=cfg.global_batch_size,
    )


# ----------------------------------------------------------------- mnist


def bench_mnist() -> dict:
    from tensorflow_examples_tpu.data.memory import train_iterator
    from tensorflow_examples_tpu.data.sources import synthetic_images
    from tensorflow_examples_tpu.train.loop import Trainer
    from tensorflow_examples_tpu.workloads import mnist

    steps, warmup = (200, 20) if BACKEND == "tpu" else (50, 5)
    cfg = mnist.MnistConfig(
        global_batch_size=256,
        precision="bf16",
        dropout=0.0,
        log_every=10**9,
        checkpoint_every=0,
        watchdog_secs=0,
    )
    ds = synthetic_images(n=4096, shape=(28, 28, 1), num_classes=10, seed=0)
    trainer = Trainer(mnist.make_task(cfg), cfg, mesh=_chip_mesh())
    it = train_iterator(ds, cfg.global_batch_size, seed=0)
    batches = [trainer._put_batch(next(it)) for _ in range(8)]
    dt = _time_steps(trainer, batches, steps, warmup)
    return _result("mnist_mlp_step_time", dt / steps * 1e3, "ms/step")


# ----------------------------------------------------------- collectives


def bench_collectives() -> dict:
    """All-reduce / all-gather bus bandwidth over the device mesh
    (SURVEY.md §5h: replaces the reference stack's NCCL perf tests)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    devices = jax.devices()
    n = len(devices)
    mesh = Mesh(np.asarray(devices), ("x",))
    elems = (16 * 2**20) if BACKEND == "tpu" else (2 * 2**20)  # per device
    x = jnp.ones((n * elems,), jnp.float32)
    x = jax.device_put(x, NamedSharding(mesh, P("x")))

    @jax.jit
    def do_psum(x):
        return shard_map(
            lambda v: jax.lax.psum(v, "x"),
            mesh=mesh,
            in_specs=P("x"),
            out_specs=P("x"),
        )(x)

    @jax.jit
    def do_gather(x):
        # Gather then re-slice to the local shard: keeps out_specs P("x")
        # (replication inference fails on degenerate 1-device meshes).
        return shard_map(
            lambda v: jax.lax.all_gather(v, "x", tiled=True)[: v.shape[0]],
            mesh=mesh,
            in_specs=P("x"),
            out_specs=P("x"),
        )(x)

    def timed(f, iters=10):
        f(x).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(iters):
            out = f(x)
        out.block_until_ready()
        return (time.perf_counter() - t0) / iters

    bytes_per_dev = elems * 4
    # Ring-algorithm bus bandwidth (the NCCL convention): payload scaled
    # by 2(n-1)/n for all-reduce, (n-1)/n for all-gather.
    t_ar = timed(do_psum)
    t_ag = timed(do_gather)
    scale_ar = 2 * (n - 1) / n if n > 1 else 1.0
    scale_ag = (n - 1) / n if n > 1 else 1.0
    ar_gbps = bytes_per_dev * scale_ar / t_ar / 1e9
    ag_gbps = bytes_per_dev * scale_ag / t_ag / 1e9
    return _result(
        "allreduce_busbw",
        ar_gbps,
        "GB/s",
        n_devices=n,
        allgather_busbw_gbps=round(ag_gbps, 2),
        payload_mb_per_device=bytes_per_dev / 2**20,
    )


# ------------------------------------------------------------------ main

BENCHES = {
    "resnet50": bench_resnet50,
    "resnet50_input": bench_resnet50_input,
    "gpt2": bench_gpt2,
    "gpt2_long": bench_gpt2_long,
    "gpt2_long16k": bench_gpt2_long16k,
    "gpt2_decode": bench_gpt2_decode,
    "bert": bench_bert,
    "cifar10": bench_cifar10,
    "mnist": bench_mnist,
    "collectives": bench_collectives,
}

# Headline-first order for --bench=all.
ALL_ORDER = [
    "resnet50",
    "resnet50_input",
    "gpt2",
    "gpt2_long",
    "gpt2_long16k",
    "gpt2_decode",
    "bert",
    "cifar10",
    "mnist",
    "collectives",
]


def run_all() -> dict:
    results = []
    for name in ALL_ORDER:
        try:
            results.append(BENCHES[name]())
        except Exception as e:  # one bench failing must not kill output
            results.append({"metric": name, "error": f"{type(e).__name__}: {e}"})
    head = next((r for r in results if "error" not in r), None)
    if head is None:
        return {"error": "all benches failed", "extras": results}
    return {**head, "extras": [r for r in results if r is not head]}


def main() -> int:
    global BACKEND
    which = "all"
    for a in sys.argv[1:]:
        if a.startswith("--bench="):
            which = a.split("=", 1)[1]
    if which != "all" and which not in BENCHES:
        print(
            json.dumps(
                {"error": f"unknown --bench={which}", "known": sorted(BENCHES)}
            )
        )
        return 0
    try:
        BACKEND = _resolve_backend()
        fp = round(fingerprint_tflops(), 2)
        out = run_all() if which == "all" else BENCHES[which]()
        out["backend"] = BACKEND
        out["fingerprint_tflops"] = fp
    except Exception as e:
        out = {
            "error": f"{type(e).__name__}: {e}",
            "backend": BACKEND,
            "metric": which,
        }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
