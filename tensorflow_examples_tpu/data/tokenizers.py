"""Tokenizers: GPT-2 byte-level BPE and BERT WordPiece, pure Python.

The reference's text workloads leaned on external tokenizer assets:
"BPE tokenizer use" for GPT-2 and "tokenizer/feature conversion" for
BERT-GLUE (SURVEY.md §2a rows 4–5). This hermetic image has zero egress,
so both tokenizers here are fully offline:

- they load the standard on-disk formats (``vocab.json`` + ``merges.txt``
  for byte-level BPE; one-token-per-line ``vocab.txt`` for WordPiece),
  byte-compatible with the published GPT-2/BERT assets when vendored; and
- each ships an in-repo trainer/builder so a working vocabulary can be
  produced from any local corpus (``tools/prepare_lm.py`` /
  ``tools/prepare_glue.py`` drive these).

Encoding is host-side preprocessing (it feeds the ``.bin``/``.npz``
formats in data/sources.py); nothing here touches jax.
"""

from __future__ import annotations

import collections
import json
import os
import unicodedata

# GPT-2's pre-tokenizer: contractions, letter runs, number runs, other
# symbols, and whitespace (trailing-space lookahead keeps " word" units).
# The canonical pattern needs `regex` for \p{L}/\p{N}; without it, fall
# back to stdlib `re` with [^\W\d_]/\d classes — equivalent for all
# text whose "letters" re considers word characters (everything
# common; exotic scripts may split differently, changing BPE merges,
# and non-decimal numerics like '²' or 'Ⅻ' — \w but not \d — land in
# the letter class where canonical \p{N} calls them numbers).
try:
    import regex

    _GPT2_SPLIT = regex.compile(
        r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"
    )
except ImportError:  # pragma: no cover - regex is a declared dependency
    import re

    # NB: the symbol class must include "_" explicitly — "_" is \w (so
    # [^\s\w] excludes it) but not a letter under [^\W\d_]; without it
    # findall() would silently drop underscores and break losslessness.
    _GPT2_SPLIT = re.compile(
        r"'s|'t|'re|'ve|'m|'ll|'d| ?[^\W\d_]+| ?\d+| ?(?:[^\s\w]|_)+|\s+(?!\S)|\s+",
        re.UNICODE,
    )

END_OF_TEXT = "<|endoftext|>"


def bytes_to_unicode() -> dict[int, str]:
    """Reversible map from the 256 byte values to printable unicode chars.

    Byte-level BPE needs every byte representable as a distinct visible
    character in vocab/merges files; bytes that are already printable map
    to themselves, the rest are offset into the U+0100 range.
    """
    printable = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    chars = printable[:]
    n = 0
    for b in range(256):
        if b not in printable:
            printable.append(b)
            chars.append(256 + n)
            n += 1
    return dict(zip(printable, map(chr, chars)))


_BYTE_ENCODER = bytes_to_unicode()
_BYTE_DECODER = {c: b for b, c in _BYTE_ENCODER.items()}


def _word_to_symbols(word_bytes: bytes) -> tuple[str, ...]:
    return tuple(_BYTE_ENCODER[b] for b in word_bytes)


def _get_pairs(symbols: tuple[str, ...]) -> set[tuple[str, str]]:
    return set(zip(symbols, symbols[1:]))


class ByteLevelBPE:
    """GPT-2-style byte-level BPE: encode/decode any text, losslessly.

    ``encoder`` maps merged byte-symbol strings → ids; ``merges`` is the
    ordered merge list (rank = priority). The special ``<|endoftext|>``
    token, when present in the vocab, is never produced by encode() on
    plain text and is emitted explicitly as a document separator.
    """

    def __init__(self, encoder: dict[str, int], merges: list[tuple[str, str]]):
        self.encoder = dict(encoder)
        self.decoder = {v: k for k, v in self.encoder.items()}
        self.ranks = {pair: i for i, pair in enumerate(merges)}
        self.merges = list(merges)
        self._cache: dict[str, list[str]] = {}

    # ------------------------------------------------------------ files

    @classmethod
    def from_files(cls, vocab_json: str, merges_txt: str) -> "ByteLevelBPE":
        with open(vocab_json, encoding="utf-8") as f:
            encoder = json.load(f)
        merges = []
        with open(merges_txt, encoding="utf-8") as f:
            for line in f:
                line = line.rstrip("\n")
                if not line or line.startswith("#version"):
                    continue
                a, b = line.split(" ")
                merges.append((a, b))
        return cls(encoder, merges)

    @classmethod
    def from_dir(cls, vocab_dir: str) -> "ByteLevelBPE":
        return cls.from_files(
            os.path.join(vocab_dir, "vocab.json"),
            os.path.join(vocab_dir, "merges.txt"),
        )

    def save(self, vocab_dir: str) -> None:
        os.makedirs(vocab_dir, exist_ok=True)
        with open(os.path.join(vocab_dir, "vocab.json"), "w", encoding="utf-8") as f:
            json.dump(self.encoder, f, ensure_ascii=False)
        with open(os.path.join(vocab_dir, "merges.txt"), "w", encoding="utf-8") as f:
            f.write("#version: 0.2\n")
            for a, b in self.merges:
                f.write(f"{a} {b}\n")

    # ---------------------------------------------------------- encode

    @property
    def vocab_size(self) -> int:
        return len(self.encoder)

    @property
    def eot_id(self) -> int | None:
        return self.encoder.get(END_OF_TEXT)

    def _bpe(self, piece: str) -> list[str]:
        if piece in self._cache:
            return self._cache[piece]
        symbols = _word_to_symbols(piece.encode("utf-8"))
        while len(symbols) > 1:
            pairs = _get_pairs(symbols)
            best = min(pairs, key=lambda p: self.ranks.get(p, float("inf")))
            if best not in self.ranks:
                break
            a, b = best
            out, i = [], 0
            while i < len(symbols):
                if i < len(symbols) - 1 and symbols[i] == a and symbols[i + 1] == b:
                    out.append(a + b)
                    i += 2
                else:
                    out.append(symbols[i])
                    i += 1
            symbols = tuple(out)
        result = list(symbols)
        if len(self._cache) < 65536:
            self._cache[piece] = result
        return result

    def encode(self, text: str) -> list[int]:
        ids = []
        for piece in _GPT2_SPLIT.findall(text):
            for sym in self._bpe(piece):
                ids.append(self.encoder[sym])
        return ids

    def decode(self, ids) -> str:
        data = bytearray()
        for i in ids:
            sym = self.decoder.get(int(i))
            if sym is None or sym == END_OF_TEXT:
                continue
            data.extend(_BYTE_DECODER[c] for c in sym)
        return data.decode("utf-8", errors="replace")

    # ----------------------------------------------------------- train

    @classmethod
    def train(
        cls, texts, vocab_size: int, *, special_tokens=(END_OF_TEXT,)
    ) -> "ByteLevelBPE":
        """Byte-level BPE training: start from the 256 byte symbols and
        repeatedly merge the most frequent adjacent pair across the
        pre-tokenized corpus until ``vocab_size`` (minus specials)."""
        word_freq: collections.Counter = collections.Counter()
        for text in texts:
            for piece in _GPT2_SPLIT.findall(text):
                word_freq[piece] += 1
        words = {
            w: _word_to_symbols(w.encode("utf-8")) for w in word_freq
        }

        base = [_BYTE_ENCODER[b] for b in range(256)]
        merges: list[tuple[str, str]] = []
        n_target = vocab_size - len(base) - len(special_tokens)
        for _ in range(max(0, n_target)):
            pair_freq: collections.Counter = collections.Counter()
            for w, symbols in words.items():
                f = word_freq[w]
                for pair in zip(symbols, symbols[1:]):
                    pair_freq[pair] += f
            if not pair_freq:
                break
            (a, b), freq = pair_freq.most_common(1)[0]
            if freq < 2:
                break
            merges.append((a, b))
            merged = a + b
            new_words = {}
            for w, symbols in words.items():
                out, i = [], 0
                while i < len(symbols):
                    if (
                        i < len(symbols) - 1
                        and symbols[i] == a
                        and symbols[i + 1] == b
                    ):
                        out.append(merged)
                        i += 2
                    else:
                        out.append(symbols[i])
                        i += 1
                new_words[w] = tuple(out)
            words = new_words

        encoder = {sym: i for i, sym in enumerate(base)}
        for a, b in merges:
            encoder[a + b] = len(encoder)
        for tok in special_tokens:
            encoder[tok] = len(encoder)
        return cls(encoder, merges)


# ------------------------------------------------------------- WordPiece


BERT_SPECIALS = ("[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]")


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (
        0x4E00 <= cp <= 0x9FFF
        or 0x3400 <= cp <= 0x4DBF
        or 0xF900 <= cp <= 0xFAFF
        or 0x20000 <= cp <= 0x2FA1F
    )


def basic_tokenize(text: str, *, lowercase: bool = True) -> list[str]:
    """BERT's BasicTokenizer: clean, lowercase + strip accents, split on
    whitespace/punctuation, and isolate CJK characters."""
    out_chars = []
    for ch in text:
        cp = ord(ch)
        if cp == 0 or cp == 0xFFFD or unicodedata.category(ch).startswith("C"):
            continue
        if _is_cjk(cp):
            out_chars.append(f" {ch} ")
        elif ch.isspace():
            out_chars.append(" ")
        else:
            out_chars.append(ch)
    tokens = []
    for word in "".join(out_chars).split():
        if lowercase:
            word = word.lower()
            word = "".join(
                c
                for c in unicodedata.normalize("NFD", word)
                if unicodedata.category(c) != "Mn"
            )
        current = []
        for ch in word:
            if _is_punctuation(ch):
                if current:
                    tokens.append("".join(current))
                    current = []
                tokens.append(ch)
            else:
                current.append(ch)
        if current:
            tokens.append("".join(current))
    return tokens


class WordPiece:
    """BERT WordPiece: greedy longest-match-first with ``##`` continuations.

    Loads the standard one-token-per-line ``vocab.txt`` (line number = id,
    the published BERT format) and produces the exact feature schema the
    GLUE loader consumes (data/sources.py:load_glue): ``tokens``,
    ``attention_mask``, ``token_type_ids`` with [CLS]/[SEP]/[PAD].
    """

    def __init__(
        self,
        vocab: dict[str, int],
        *,
        lowercase: bool = True,
        max_chars_per_word: int = 100,
    ):
        self.vocab = dict(vocab)
        self.inv_vocab = {v: k for k, v in self.vocab.items()}
        self.lowercase = lowercase
        self.max_chars_per_word = max_chars_per_word
        for tok in ("[UNK]", "[CLS]", "[SEP]", "[PAD]"):
            if tok not in self.vocab:
                raise ValueError(f"WordPiece vocab missing special token {tok}")

    @classmethod
    def from_vocab_file(cls, path: str, *, lowercase: bool = True) -> "WordPiece":
        vocab = {}
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                tok = line.rstrip("\n")
                if tok:
                    vocab[tok] = i
        return cls(vocab, lowercase=lowercase)

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            for tok, _ in sorted(self.vocab.items(), key=lambda kv: kv[1]):
                f.write(tok + "\n")

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def tokenize(self, text: str) -> list[str]:
        pieces = []
        for word in basic_tokenize(text, lowercase=self.lowercase):
            if len(word) > self.max_chars_per_word:
                pieces.append("[UNK]")
                continue
            start, word_pieces, bad = 0, [], False
            while start < len(word):
                end = len(word)
                cur = None
                while start < end:
                    sub = word[start:end]
                    if start > 0:
                        sub = "##" + sub
                    if sub in self.vocab:
                        cur = sub
                        break
                    end -= 1
                if cur is None:
                    bad = True
                    break
                word_pieces.append(cur)
                start = end
            pieces.extend(["[UNK]"] if bad else word_pieces)
        return pieces

    def encode(
        self, text_a: str, text_b: str | None = None, *, seq_len: int = 128
    ) -> dict:
        """[CLS] a [SEP] (b [SEP])? → fixed-length id/mask/type arrays."""
        import numpy as np

        a = self.tokenize(text_a)
        b = self.tokenize(text_b) if text_b is not None else []
        # Truncate longest-first to fit [CLS] + a + [SEP] (+ b + [SEP]).
        budget = seq_len - 2 - (1 if b else 0)
        while len(a) + len(b) > budget:
            (a if len(a) >= len(b) else b).pop()
        toks = ["[CLS]"] + a + ["[SEP]"]
        types = [0] * len(toks)
        if b:
            toks += b + ["[SEP]"]
            types += [1] * (len(b) + 1)
        ids = [self.vocab[t] for t in toks]
        n = len(ids)
        pad = self.vocab["[PAD]"]
        return {
            "tokens": np.asarray(ids + [pad] * (seq_len - n), np.int32),
            "attention_mask": np.asarray([1] * n + [0] * (seq_len - n), np.int32),
            "token_type_ids": np.asarray(types + [0] * (seq_len - n), np.int32),
        }

    def decode(self, ids) -> str:
        words = []
        for i in ids:
            tok = self.inv_vocab.get(int(i), "[UNK]")
            if tok in BERT_SPECIALS:
                continue
            if tok.startswith("##") and words:
                words[-1] += tok[2:]
            else:
                words.append(tok)
        return " ".join(words)

    # ----------------------------------------------------------- build

    @classmethod
    def build(
        cls, texts, vocab_size: int, *, lowercase: bool = True
    ) -> "WordPiece":
        """Build a WordPiece vocab from a corpus: specials + all seen
        characters (+ ## forms), then BPE-style merges expressed as
        subword units until ``vocab_size``."""
        word_freq: collections.Counter = collections.Counter()
        for text in texts:
            for w in basic_tokenize(text, lowercase=lowercase):
                word_freq[w] += 1

        # Represent each word as char pieces: first char bare, rest ##'d.
        words = {
            w: tuple([w[0]] + ["##" + c for c in w[1:]]) for w in word_freq
        }
        vocab_set = set(BERT_SPECIALS)
        for pieces in words.values():
            vocab_set.update(pieces)

        def strip(p):  # char content of a piece
            return p[2:] if p.startswith("##") else p

        while len(vocab_set) < vocab_size:
            pair_freq: collections.Counter = collections.Counter()
            for w, pieces in words.items():
                f = word_freq[w]
                for pair in zip(pieces, pieces[1:]):
                    pair_freq[pair] += f
            if not pair_freq:
                break
            (a, b), freq = pair_freq.most_common(1)[0]
            if freq < 2:
                break
            merged = a + strip(b)
            vocab_set.add(merged)
            new_words = {}
            for w, pieces in words.items():
                out, i = [], 0
                while i < len(pieces):
                    if i < len(pieces) - 1 and pieces[i] == a and pieces[i + 1] == b:
                        out.append(merged)
                        i += 2
                    else:
                        out.append(pieces[i])
                        i += 1
                new_words[w] = tuple(out)
            words = new_words

        vocab = {}
        for tok in BERT_SPECIALS:
            vocab[tok] = len(vocab)
        for tok in sorted(vocab_set - set(BERT_SPECIALS)):
            vocab[tok] = len(vocab)
        return cls(vocab, lowercase=lowercase)
