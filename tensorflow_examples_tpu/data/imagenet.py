"""ImageNet input pipeline (SURVEY.md §3(4) — the perf-critical one).

Reference shape: ``TFRecordDataset(shards) → shuffle → map(decode_jpeg +
augment, parallel) → batch → prefetch(device)`` on host CPU threads
overlapped with the device step. Here the same stages run through
``tf.data`` **as a host-side reader only** (TF never touches the TPU;
batches cross into JAX as numpy), feeding the shared loop's async
device-prefetch queue (data/prefetch.py) which replaces
``experimental_distribute_dataset`` + device prefetch:

- standard ImageNet TFRecord schema (``image/encoded``,
  ``image/class/label``) with the classic ResNet augmentation:
  sample_distorted_bounding_box crop → resize 224 → random flip for
  train; 87.5% central crop for eval.
- per-host sharding by ``jax.process_index`` (the multi-worker
  ``dataset.shard(num_workers, index)`` equivalent, SURVEY.md §3(5)).
- without ``data_dir``: a seeded synthetic stream with label-correlated
  low-rank image structure — learnable, so integration tests assert
  actual training, with O(classes·size) memory instead of materializing
  full images.
"""

from __future__ import annotations

import os
from typing import Iterator

import numpy as np

MEAN_RGB = np.array([0.485, 0.456, 0.406], np.float32)
STDDEV_RGB = np.array([0.229, 0.224, 0.225], np.float32)


# --------------------------------------------------------------- synthetic


class SyntheticImageNet:
    """Streaming label-correlated synthetic images.

    Image for class c = outer(u_c, v_c) pattern + noise; u, v are seeded
    per class, so storage is O(classes · size), not O(n · size²)."""

    def __init__(self, *, image_size=224, num_classes=1000, seed=0):
        rng = np.random.default_rng(seed)
        self.u = rng.normal(0, 1, (num_classes, image_size)).astype(np.float32)
        self.v = rng.normal(0, 1, (num_classes, image_size)).astype(np.float32)
        self.phase = rng.normal(0, 1, (num_classes, 3)).astype(np.float32)
        self.num_classes = num_classes
        self.image_size = image_size

    def batch(self, batch_size: int, rng: np.random.Generator):
        y = rng.integers(0, self.num_classes, batch_size).astype(np.int32)
        base = np.einsum("bh,bw->bhw", self.u[y], self.v[y])
        img = base[..., None] * self.phase[y][:, None, None, :]
        img += rng.normal(0, 2.0, img.shape).astype(np.float32)
        return {"image": img.astype(np.float32), "label": y}


def synthetic_train_iter(
    batch_size: int,
    *,
    image_size=224,
    num_classes=1000,
    seed=0,
    start_step=0,
) -> Iterator[dict]:
    src = SyntheticImageNet(
        image_size=image_size, num_classes=num_classes, seed=seed
    )
    step = start_step
    while True:
        yield src.batch(batch_size, np.random.default_rng((seed, step)))
        step += 1


def synthetic_eval_iter(
    batch_size: int, *, image_size=224, num_classes=1000, seed=1, batches=8
) -> Iterator[dict]:
    src = SyntheticImageNet(
        image_size=image_size, num_classes=num_classes, seed=seed
    )
    for step in range(batches):
        b = src.batch(batch_size, np.random.default_rng((seed, step)))
        b["mask"] = np.ones(batch_size, np.float32)
        yield b


# ---------------------------------------------------------------- tfrecord


def _tf():
    import tensorflow as tf

    tf.config.set_visible_devices([], "GPU")  # host-side reader only
    try:
        tf.config.set_visible_devices([], "TPU")
    except Exception:
        pass
    return tf


def _parse_and_decode(tf, record, *, train: bool, image_size: int, aug_seed=None):
    """Decode one example. ``aug_seed`` (a [2] int tensor) switches the
    train augmentations to their STATELESS variants keyed on it — the
    exact-resume path, where the same stream position must produce the
    same crop/flip on every run."""
    feats = tf.io.parse_single_example(
        record,
        {
            "image/encoded": tf.io.FixedLenFeature([], tf.string),
            "image/class/label": tf.io.FixedLenFeature([], tf.int64),
        },
    )
    img_bytes = feats["image/encoded"]
    if train:
        # Classic ResNet crop: random area 8–100%, aspect 3/4–4/3.
        crop_kw = dict(
            bounding_boxes=tf.zeros([1, 0, 4], tf.float32),
            area_range=(0.08, 1.0),
            aspect_ratio_range=(3 / 4, 4 / 3),
            max_attempts=10,
            use_image_if_no_bounding_boxes=True,
        )
        shape = tf.io.extract_jpeg_shape(img_bytes)
        if aug_seed is not None:
            begin, size, _ = tf.image.stateless_sample_distorted_bounding_box(
                shape, seed=aug_seed, **crop_kw
            )
        else:
            begin, size, _ = tf.image.sample_distorted_bounding_box(
                shape, **crop_kw
            )
        y, x, _ = tf.unstack(begin)
        h, w, _ = tf.unstack(size)
        img = tf.image.decode_and_crop_jpeg(
            img_bytes, tf.stack([y, x, h, w]), channels=3
        )
        img = tf.image.resize(img, [image_size, image_size])
        if aug_seed is not None:
            img = tf.image.stateless_random_flip_left_right(
                img, seed=aug_seed + 1
            )
        else:
            img = tf.image.random_flip_left_right(img)
    else:
        img = tf.io.decode_jpeg(img_bytes, channels=3)
        shape = tf.shape(img)
        crop = tf.cast(
            tf.cast(tf.minimum(shape[0], shape[1]), tf.float32) * 0.875, tf.int32
        )
        img = tf.image.resize_with_crop_or_pad(img, crop, crop)
        img = tf.image.resize(img, [image_size, image_size])
    # Emit uint8: normalization runs in the threaded C++ host library
    # (native/fastdata.cpp) after the tf graph — and uint8 batches are
    # 4x cheaper to move between tf.data and numpy.
    img = tf.cast(tf.clip_by_value(img, 0.0, 255.0), tf.uint8)
    # ImageNet TFRecord labels are 1-based.
    label = tf.cast(feats["image/class/label"], tf.int32) - 1
    return {"image": img, "label": label}


def _count_records(tf, files: list, data_dir: str, tag: str) -> int:
    """Total record count across ``files`` — one IO-only pass (no JPEG
    decode), cached keyed by the shard list + sizes, so it runs once
    per dataset, not once per resume.

    The cache lives in a HOST-LOCAL dir (``$TFE_TPU_CACHE_DIR``,
    default ``~/.cache/tensorflow_examples_tpu``), never next to the
    shards: data dirs are often shared read-mostly buckets, and a cold
    multi-host start would have every host racing writes into them
    (ADVICE r3). Each host counts only its own shard subset, so the
    cold-start counting pass itself is per-host by construction; the
    cache just keeps it off the resume path."""
    import hashlib
    import json

    # data_dir participates in the key: the cache is global per host,
    # and two datasets with the standard shard naming and equal sizes
    # but different contents must not share a count. Only genuinely
    # local paths are normalized — abspath would both mangle remote
    # URLs ('gs://b/x' -> '<cwd>/gs:/b/x') and make the key depend on
    # the launch CWD, missing the cache on every scheduler restart.
    is_url = "://" in data_dir
    sig = hashlib.sha1(
        "|".join(
            [data_dir if is_url else os.path.abspath(data_dir)]
            + [
                f"{os.path.basename(f)}:{tf.io.gfile.stat(f).length}"
                for f in files
            ]
        ).encode()
    ).hexdigest()[:16]
    cache_dir = os.environ.get(
        "TFE_TPU_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "tensorflow_examples_tpu"),
    )
    cache = os.path.join(cache_dir, f"record_count-{tag}-{sig}.json")
    try:
        with tf.io.gfile.GFile(cache, "r") as fh:
            return int(json.load(fh)["count"])
    except Exception:
        pass
    n = int(
        tf.data.TFRecordDataset(files)
        .batch(4096)
        .reduce(
            np.int64(0), lambda acc, b: acc + tf.shape(b, out_type=tf.int64)[0]
        )
        .numpy()
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        with tf.io.gfile.GFile(cache, "w") as fh:
            json.dump({"count": n}, fh)
    except Exception:
        pass
    return n


def _mix(seed: int, epoch: int) -> int:
    """Cheap int mix for per-epoch tf.data seeds (kept in int32 range)."""
    return (seed * 1_000_003 + epoch * 7919 + 1) % (2**31 - 1)


def tfrecord_iter(
    data_dir: str,
    split: str,
    batch_size: int,
    *,
    train: bool,
    image_size: int = 224,
    seed: int = 0,
    num_parallel: int = 16,
    start_step: int = 0,
    exact: bool = False,
) -> Iterator[dict]:
    """Host tf.data pipeline → numpy batches (masked final eval batch).

    ``exact=True`` (train only) makes the stream a pure function of
    ``seed`` and checkpoint-resumable (SURVEY.md §4, §5b): each epoch is
    an independent deterministic dataset — files permuted by
    numpy ``(seed, epoch)``, seeded record shuffle, order-preserving
    interleave, stateless crop/flip keyed on (seed·epoch mix, in-epoch
    record index) — chained by a Python epoch loop. Resume cost is
    BOUNDED BY ONE EPOCH: a one-time cached record count (IO-only pass,
    no decode) converts ``start_step`` into (epoch, in-epoch offset), so
    restoring at step 450k skips at most one epoch's records of IO and
    none of the decode/augment — and yields batches bit-identical to the
    uninterrupted run's steps N, N+1, … Cost of exactness: the
    order-preserving interleave gives up some read parallelism slack —
    measured small next to decode+augment; flip ``exact=False`` for
    maximum-throughput non-resumable input.
    ``exact=False`` ignores ``start_step`` (a fresh nondeterministic
    shuffle makes skipping meaningless).
    """
    import jax

    tf = _tf()
    pattern = os.path.join(data_dir, f"{split}-*")
    files = sorted(tf.io.gfile.glob(pattern))
    if not files:
        raise FileNotFoundError(f"no TFRecord shards matching {pattern}")
    # Per-host input sharding (multi-host DP, SURVEY.md §3(5)).
    nproc, pidx = jax.process_count(), jax.process_index()
    host_files = files[pidx::nproc]

    if exact and train:
        yield from _exact_train_stream(
            tf, host_files, data_dir, split, batch_size,
            image_size=image_size, seed=seed, num_parallel=num_parallel,
            start_step=start_step,
        )
        return

    if _native_decode_enabled():
        yield from _native_stream(
            tf, host_files, batch_size, train=train,
            image_size=image_size, seed=seed, num_parallel=num_parallel,
        )
        return

    ds = tf.data.Dataset.from_tensor_slices(host_files)
    if train:
        ds = ds.shuffle(len(host_files), seed=seed)
    ds = ds.interleave(
        tf.data.TFRecordDataset,
        cycle_length=num_parallel,
        num_parallel_calls=tf.data.AUTOTUNE,
        deterministic=not train,
    )
    if train:
        ds = ds.shuffle(16 * batch_size, seed=seed)
        ds = ds.repeat()
    ds = ds.map(
        lambda r: _parse_and_decode(tf, r, train=train, image_size=image_size),
        num_parallel_calls=tf.data.AUTOTUNE,
    )
    ds = ds.batch(batch_size, drop_remainder=train)
    ds = ds.prefetch(tf.data.AUTOTUNE)

    for batch in ds.as_numpy_iterator():
        out = {"image": _normalize_uint8(batch["image"]), "label": batch["label"]}
        n = len(out["label"])
        if not train and n < batch_size:
            pad = batch_size - n
            out = {
                k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
                for k, v in out.items()
            }
            out["mask"] = np.concatenate(
                [np.ones(n, np.float32), np.zeros(pad, np.float32)]
            )
        elif not train:
            out["mask"] = np.ones(n, np.float32)
        yield out


def _native_decode_enabled() -> bool:
    """The one C++ stage (libfastjpeg: decode + crop + resize + flip +
    normalize, VERDICT r4 weak #2) is used whenever it built; set
    ``TFE_TPU_NATIVE_DECODE=0`` to force the tf.data decode path."""
    if os.environ.get("TFE_TPU_NATIVE_DECODE", "1") == "0":
        return False
    from tensorflow_examples_tpu import native

    return native.available("fastjpeg")


def _image_seeds(seed: int, step: int, n: int) -> np.ndarray:
    """Per-image uint64 splitmix64 seeds for the C++ augment stream —
    a pure function of (dataset seed, batch index, row), so a given
    stream position always draws the same crop/flip. Mixing wraps mod
    2**64 by design; done in Python ints because numpy SCALAR uint64
    multiplies emit overflow RuntimeWarnings on wraparound."""
    m = 2**64
    base = (seed * 0x9E3779B97F4A7C15 + step * 0xC2B2AE3D27D4EB4F) % m
    k3 = 0x165667B19E3779F9
    return np.array([(base + i * k3) % m for i in range(n)], np.uint64)


def _native_stream(
    tf, host_files, batch_size, *, train, image_size, seed, num_parallel
):
    """tf.data as record reader ONLY (parse proto → bytes + label); the
    whole per-image path — JPEG decode (DCT-scaled), ResNet crop,
    bilinear resize, flip, normalize — is one threaded C++ call
    (native/fastjpeg.cpp). Not resume-exact; the ``exact`` stream keeps
    the stateless-tf path."""
    from tensorflow_examples_tpu import native

    def parse_only(record):
        feats = tf.io.parse_single_example(
            record,
            {
                "image/encoded": tf.io.FixedLenFeature([], tf.string),
                "image/class/label": tf.io.FixedLenFeature([], tf.int64),
            },
        )
        return {
            "encoded": feats["image/encoded"],
            "label": tf.cast(feats["image/class/label"], tf.int32) - 1,
        }

    ds = tf.data.Dataset.from_tensor_slices(host_files)
    if train:
        ds = ds.shuffle(len(host_files), seed=seed)
    ds = ds.interleave(
        tf.data.TFRecordDataset,
        cycle_length=num_parallel,
        num_parallel_calls=tf.data.AUTOTUNE,
        deterministic=not train,
    )
    if train:
        ds = ds.shuffle(16 * batch_size, seed=seed)
        ds = ds.repeat()
    ds = ds.map(parse_only, num_parallel_calls=tf.data.AUTOTUNE)
    ds = ds.batch(batch_size, drop_remainder=train)
    ds = ds.prefetch(tf.data.AUTOTUNE)

    step = 0
    for batch in ds.as_numpy_iterator():
        jpegs = list(batch["encoded"])
        n = len(jpegs)
        res = native.decode_augment_batch(
            jpegs,
            train=train,
            out_size=image_size,
            seeds=_image_seeds(seed, step, n) if train else None,
            mean=MEAN_RGB,
            std=STDDEV_RGB,
        )
        assert res is not None, "fastjpeg vanished mid-stream"
        img, _ok = res  # failed decodes are zero-filled (corrupt shards)
        out = {"image": img, "label": batch["label"]}
        if not train and n < batch_size:
            pad = batch_size - n
            out = {
                k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
                for k, v in out.items()
            }
            out["mask"] = np.concatenate(
                [np.ones(n, np.float32), np.zeros(pad, np.float32)]
            )
        elif not train:
            out["mask"] = np.ones(n, np.float32)
        yield out
        step += 1


# ------------------------------------------------- native-augment mirror
#
# Pure-numpy reference for native/fastjpeg.cpp's crop/resize/flip/
# normalize — SAME splitmix64 draws, same arithmetic — so the C++ stage
# is testable against numpy on any host (tests/test_native.py). Decode
# itself is mirrored with PIL (also libjpeg underneath; parity is
# tolerance-checked, not bit-exact, because IDCT rounding may differ
# between libjpeg builds).


class _SplitMix64:
    MASK = 2**64 - 1

    def __init__(self, seed: int):
        self.s = int(seed) & self.MASK

    def next(self) -> int:
        self.s = (self.s + 0x9E3779B97F4A7C15) & self.MASK
        z = self.s
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & self.MASK
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & self.MASK
        return z ^ (z >> 31)

    def u01(self) -> float:
        return (self.next() >> 11) * (1.0 / 9007199254740992.0)


def _mirror_crop(h, w, train, rng):
    """(y0, x0, ch, cw, flip) — draw-for-draw mirror of fastjpeg.cpp."""
    import math

    if not train:
        m = min(h, w)
        crop = max(1, int(0.875 * m))
        return (h - crop) // 2, (w - crop) // 2, crop, crop, False
    log_lo, log_hi = math.log(3 / 4), math.log(4 / 3)
    found = None
    for _ in range(10):
        a_frac = 0.08 + rng.u01() * 0.92
        ratio = math.exp(log_lo + rng.u01() * (log_hi - log_lo))
        area = a_frac * h * w
        cw = int(math.floor(math.sqrt(area * ratio) + 0.5))
        ch = int(math.floor(math.sqrt(area / ratio) + 0.5))
        if 1 <= cw <= w and 1 <= ch <= h:
            y0 = int(math.floor(rng.u01() * (h - ch + 1)))
            x0 = int(math.floor(rng.u01() * (w - cw + 1)))
            found = (y0, x0, ch, cw)
            break
    if found is None:
        m = min(h, w)
        found = ((h - m) // 2, (w - m) // 2, m, m)
    flip = rng.u01() < 0.5
    return (*found, flip)


def _decode_crop_resize(
    jpeg: bytes, *, train: bool, seed: int, out_size: int
) -> np.ndarray:
    """One image through the PIL/numpy mirror of fastjpeg.cpp's decode +
    crop + bilinear resize + flip — WITHOUT normalization, returning the
    [S, S, 3] f32 0..255 image. Normalization is applied batched by the
    caller (data/augment.normalize_images) with the identical f32
    expression, so batching changes nothing byte-wise."""
    import io

    from PIL import Image

    img = np.asarray(Image.open(io.BytesIO(jpeg)).convert("RGB"), np.float64)
    h, w, _ = img.shape
    rng = _SplitMix64(seed)
    y0, x0, ch, cw, flip = _mirror_crop(h, w, train, rng)
    oy = np.arange(out_size)
    sy = y0 + (oy + 0.5) * ch / out_size - 0.5
    y1 = np.clip(np.floor(sy).astype(np.int64), 0, h - 1)
    y2 = np.clip(y1 + 1, 0, h - 1)
    fy = sy - np.floor(sy)
    sx = x0 + (oy + 0.5) * cw / out_size - 0.5
    x1 = np.clip(np.floor(sx).astype(np.int64), 0, w - 1)
    x2 = np.clip(x1 + 1, 0, w - 1)
    fx = sx - np.floor(sx)
    top = img[y1][:, x1] * ((1 - fy)[:, None] * (1 - fx)[None, :])[..., None] \
        + img[y1][:, x2] * ((1 - fy)[:, None] * fx[None, :])[..., None]
    bot = img[y2][:, x1] * (fy[:, None] * (1 - fx)[None, :])[..., None] \
        + img[y2][:, x2] * (fy[:, None] * fx[None, :])[..., None]
    res = top + bot
    if flip:
        res = res[:, ::-1]
    return res.astype(np.float32)


def decode_augment_reference(
    jpeg: bytes, *, train: bool, seed: int, out_size: int
) -> np.ndarray:
    """Numpy mirror of one fastjpeg.cpp image (denom=1 path: exact when
    the crop is < 2x out_size, which any test-sized image satisfies)."""
    raw = _decode_crop_resize(
        jpeg, train=train, seed=seed, out_size=out_size
    )
    return ((raw / 255.0) - MEAN_RGB) / STDDEV_RGB


def _normalize_uint8(images: np.ndarray) -> np.ndarray:
    """uint8 HWC batch → normalized f32 via the threaded C++ host library
    (native/fastdata.cpp); numpy fallback is the batched LUT gather
    (data/augment.normalize_images — byte-identical to the direct
    expression, mean/std broadcast once). Single definition so the
    exact and non-exact streams cannot drift."""
    from tensorflow_examples_tpu import native
    from tensorflow_examples_tpu.data import augment as augment_mod

    img = native.normalize(images, MEAN_RGB, STDDEV_RGB)
    if img is None:
        img = augment_mod.normalize_images(images, MEAN_RGB, STDDEV_RGB)
    return img


def _exact_train_stream(
    tf,
    host_files: list,
    data_dir: str,
    split: str,
    batch_size: int,
    *,
    image_size: int,
    seed: int,
    num_parallel: int,
    start_step: int,
):
    """Epoch-chained deterministic train stream (see tfrecord_iter).

    Each epoch is built fresh from (seed, epoch): numpy file
    permutation → deterministic interleave → seeded record shuffle
    (reshuffle OFF — the epoch seed varies instead) → skip (first
    resumed epoch only) → stateless-augment map → batch. ``start_step``
    maps to (epoch, in-epoch batches) via the cached per-host record
    count, so the skip never exceeds one epoch."""
    n_records = _count_records(
        tf, host_files, data_dir, f"{split}-h{len(host_files)}"
    )
    bpe = n_records // batch_size  # drop_remainder batches per epoch
    if bpe == 0:
        raise ValueError(
            f"{n_records} records in this host's {split} shards is less "
            f"than one batch of {batch_size}"
        )
    epoch, within = divmod(start_step, bpe)
    skip_records = within * batch_size

    while True:
        rng = np.random.default_rng((seed, epoch))
        order = [host_files[i] for i in rng.permutation(len(host_files))]
        eseed = _mix(seed, epoch)
        ds = tf.data.Dataset.from_tensor_slices(order)
        ds = ds.interleave(
            tf.data.TFRecordDataset,
            cycle_length=num_parallel,
            num_parallel_calls=tf.data.AUTOTUNE,
            deterministic=True,
        )
        ds = ds.shuffle(
            16 * batch_size, seed=eseed, reshuffle_each_iteration=False
        )
        # In-epoch index BEFORE the skip: position k of a resumed epoch
        # carries the same index — hence the same stateless crop/flip —
        # as in the uninterrupted run.
        ds = ds.enumerate()
        if skip_records:
            ds = ds.skip(skip_records)
        ds = ds.map(
            lambda i, r: _parse_and_decode(
                tf, r, train=True, image_size=image_size,
                aug_seed=tf.stack([tf.constant(eseed, tf.int64), i]),
            ),
            num_parallel_calls=tf.data.AUTOTUNE,
        )
        ds = ds.batch(batch_size, drop_remainder=True)
        ds = ds.prefetch(tf.data.AUTOTUNE)
        for batch in ds.as_numpy_iterator():
            yield {
                "image": _normalize_uint8(batch["image"]),
                "label": batch["label"],
            }
        epoch += 1
        skip_records = 0


def has_tfrecords(data_dir: str, split: str) -> bool:
    if not data_dir:
        return False
    import glob

    return bool(glob.glob(os.path.join(data_dir, f"{split}-*")))


# ----------------------------------- parallel pipeline (ISSUE 6 tentpole)
#
# The pure-python hot path: sharded parallel readers (data/sources.py
# ShardedReader — no tf import anywhere on this path) feeding a
# background decode/augment worker pool (data/workers.py). Everything is
# a pure function of (seed, start_step): per-epoch shard order is a
# seeded permutation, records flow in deterministic shard order for ANY
# reader count, per-image augment seeds are keyed on the global batch
# index — so the stream is bit-identical to the sequential
# single-reader/zero-worker reference AND exactly resumable (the golden
# contract tools/host_input_bench.py measures and tests pin).


def parse_imagenet_example(record: bytes) -> tuple[bytes, int]:
    """(jpeg bytes, 0-based label) from one standard ImageNet Example."""
    from tensorflow_examples_tpu.data import sources as sources_mod

    feats = sources_mod.parse_example(record)
    try:
        jpeg = feats["image/encoded"][0]
        label = int(feats["image/class/label"][0]) - 1  # 1-based on disk
    except (KeyError, IndexError) as e:
        raise ValueError(
            f"record is not ImageNet-schema (have {sorted(feats)})"
        ) from e
    return jpeg, label


def decode_augment_batch(
    jpegs: list,
    labels: list,
    *,
    train: bool,
    image_size: int,
    seed: int,
    step: int,
    threads: int | None = None,
) -> dict:
    """One pipeline batch: JPEG decode + ResNet crop/resize/flip +
    normalize. Prefers the threaded C++ stage (native/fastjpeg.cpp) when
    built and enabled; otherwise the PIL/numpy mirror with the SAME
    splitmix64 augment draws, normalized in one batched broadcast
    (data/augment.normalize_images). Deterministic given
    (seed, step, row) on either path."""
    from tensorflow_examples_tpu import native
    from tensorflow_examples_tpu.data import augment as augment_mod

    n = len(jpegs)
    seeds = _image_seeds(seed, step, n) if train else None
    if _native_decode_enabled():
        res = native.decode_augment_batch(
            jpegs, train=train, out_size=image_size, seeds=seeds,
            mean=MEAN_RGB, std=STDDEV_RGB, threads=threads,
        )
        if res is not None:
            img, ok = res
            if not ok.all():
                # Loud, like the PIL fallback (which raises on a bad
                # JPEG): a zero-filled image with a real label is
                # silent training-data corruption. The failure lands at
                # a deterministic batch index on every path.
                bad = [int(i) for i in np.flatnonzero(ok == 0)]
                raise ValueError(
                    f"undecodable JPEG record(s) at batch {step} "
                    f"rows {bad} (corrupt shard?)"
                )
            return {"image": img, "label": np.asarray(labels, np.int32)}
    raw = np.stack(
        [
            _decode_crop_resize(
                j,
                train=train,
                seed=int(seeds[i]) if seeds is not None else 0,
                out_size=image_size,
            )
            for i, j in enumerate(jpegs)
        ]
    )
    img = augment_mod.normalize_images(raw, MEAN_RGB, STDDEV_RGB)
    return {"image": img, "label": np.asarray(labels, np.int32)}


def _count_records_py(host_files: list, data_dir: str, tag: str) -> int:
    """Pure-python record count across this host's shards, cached in
    the same host-local dir as the tf path's count (see
    ``_count_records`` for the cache-placement rationale)."""
    import hashlib
    import json

    from tensorflow_examples_tpu.data import sources as sources_mod

    is_url = "://" in data_dir
    sig = hashlib.sha1(
        "|".join(
            [data_dir if is_url else os.path.abspath(data_dir)]
            + [
                f"{os.path.basename(f)}:{os.path.getsize(f)}"
                for f in host_files
            ]
        ).encode()
    ).hexdigest()[:16]
    cache_dir = os.environ.get(
        "TFE_TPU_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "tensorflow_examples_tpu"),
    )
    cache = os.path.join(cache_dir, f"record_count-{tag}-{sig}.json")
    try:
        with open(cache) as fh:
            return int(json.load(fh)["count"])
    except Exception:
        pass
    n = sum(
        sum(1 for _ in sources_mod.iter_tfrecord_records(f))
        for f in host_files
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        with open(cache, "w") as fh:
            json.dump({"count": n}, fh)
    except Exception:
        pass
    return n


def parallel_tfrecord_iter(
    data_dir: str,
    split: str,
    batch_size: int,
    *,
    train: bool,
    image_size: int = 224,
    seed: int = 0,
    num_readers: int = 2,
    num_workers: int = 0,
    start_step: int = 0,
    host_index: int | None = None,
    host_count: int | None = None,
    buffer_records: int = 512,
    decode_threads: int | None = None,
    shuffle_window: int | None = None,
):
    """Sharded-parallel, worker-pipelined, exactly-resumable TFRecord
    input (ISSUE 6 tentpole). Per-host sharding semantics match
    ``tfrecord_iter`` (``files[host::hosts]``); pass ``host_index`` /
    ``host_count`` explicitly to simulate a fleet without jax.

    Train: infinite epoch-chained stream, each epoch a seeded shard
    permutation read in deterministic order, batches dropped at the
    epoch remainder, per-image augment seeds keyed on the global batch
    index. ``start_step`` resumes mid-epoch (skip bounded by one
    epoch's records, none of the decode). Eval: one pass, final batch
    padded with a zero ``mask``.

    ``num_workers > 0`` returns a closeable
    :class:`~tensorflow_examples_tpu.data.workers.PipelinedIterator`
    (``background = True`` — the prefetch layer records pops as
    ``data_wait``); ``num_workers == 0`` decodes inline — the
    sequential reference the parallel stream is bit-identical to.
    """
    import glob as glob_mod

    from tensorflow_examples_tpu.data import sources as sources_mod
    from tensorflow_examples_tpu.data import workers as workers_mod

    pattern = os.path.join(data_dir, f"{split}-*")
    files = sorted(glob_mod.glob(pattern))
    if not files:
        raise FileNotFoundError(f"no TFRecord shards matching {pattern}")
    if host_index is None or host_count is None:
        import jax

        host_index = jax.process_index() if host_index is None else host_index
        host_count = jax.process_count() if host_count is None else host_count
    host_files = files[host_index::host_count]
    if not host_files:
        raise ValueError(
            f"host {host_index}/{host_count} holds zero of the "
            f"{len(files)} {split} shards"
        )
    # Pool workers decode single-threaded (the pool IS the parallelism);
    # the inline path lets the C++ stage use its own threads unless the
    # caller pins it (the bench's sequential reference pins 1).
    if decode_threads is None and num_workers > 0:
        decode_threads = 1

    def decode(item):
        """One worker item: raw records -> parsed -> decoded batch.
        Parsing sits in the worker stage so the consumer thread's
        serial (GIL-held) work per batch is just chunking bytes."""
        step, records = item
        jpegs, labels = [], []
        for rec in records:
            jpeg, label = parse_imagenet_example(rec)
            jpegs.append(jpeg)
            labels.append(label)
        return decode_augment_batch(
            jpegs, labels, train=train, image_size=image_size,
            seed=seed, step=step, threads=decode_threads,
        )

    if train:
        n_records = _count_records_py(
            host_files, data_dir, f"{split}-h{len(host_files)}"
        )
        bpe = n_records // batch_size
        if bpe == 0:
            raise ValueError(
                f"{n_records} records in this host's {split} shards is "
                f"less than one batch of {batch_size}"
            )
        epoch0, within = divmod(start_step, bpe)

        def raw_batches():
            import contextlib

            step = start_step
            epoch = epoch0
            skip = within * batch_size
            while True:
                rng = np.random.default_rng((seed, epoch))
                order = [
                    host_files[i]
                    for i in rng.permutation(len(host_files))
                ]
                records = sources_mod.interleave_shards(
                    order,
                    sources_mod.iter_tfrecord_records,
                    num_readers=num_readers,
                    buffer_records=buffer_records,
                )
                # Record-level shuffle (the tf.data path's 16*batch
                # shuffle buffer): seeded per epoch, applied to the
                # deterministic merged stream — so it mixes WITHIN
                # shards without breaking reader-count independence.
                # The resume skip runs POST-shuffle: batch N of a
                # resumed epoch is the same batch N the uninterrupted
                # run produced. ``shuffle_window`` overrides the
                # default 16*batch window (0 disables; the bench keeps
                # the window under its tiny epoch so it measures the
                # streaming regime real epochs run in).
                window = (
                    16 * batch_size
                    if shuffle_window is None
                    else shuffle_window
                )
                stream = sources_mod.seeded_window_shuffle(
                    records,
                    window,
                    np.random.default_rng((seed, epoch, 1)),
                )
                group: list = []
                with contextlib.closing(records):
                    skipped = 0
                    for rec in stream:
                        if skipped < skip:
                            skipped += 1
                            continue
                        group.append(rec)
                        if len(group) == batch_size:
                            yield (step, group)
                            step += 1
                            group = []
                # Epoch remainder dropped (drop_remainder semantics —
                # bpe full batches per epoch, every epoch).
                epoch += 1
                skip = 0

        source = raw_batches()
    else:

        def raw_batches():
            import contextlib

            records = sources_mod.interleave_shards(
                host_files,
                sources_mod.iter_tfrecord_records,
                num_readers=num_readers,
                buffer_records=buffer_records,
            )
            group: list = []
            step = 0
            with contextlib.closing(records):
                for rec in records:
                    group.append(rec)
                    if len(group) == batch_size:
                        yield (step, group)
                        step += 1
                        group = []
            if group:
                yield (step, group)

        source = raw_batches()

    def finish(batch: dict) -> dict:
        if train:
            return batch
        n = len(batch["label"])
        if n < batch_size:
            pad = batch_size - n
            batch = {
                k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
                for k, v in batch.items()
            }
            batch["mask"] = np.concatenate(
                [np.ones(n, np.float32), np.zeros(pad, np.float32)]
            )
        else:
            batch["mask"] = np.ones(n, np.float32)
        return batch

    if num_workers > 0:
        pool = workers_mod.WorkerPool(
            decode, num_workers, name="imagenet_decode"
        )
        decoded = workers_mod.PipelinedIterator(pool, source)
        if train:
            return decoded
        return _FinishingIterator(decoded, finish)
    return (finish(decode(item)) for item in source)


class _FinishingIterator:
    """Apply a cheap host-side finisher to a background pipeline while
    keeping the ``background``/``close`` contract visible to prefetch."""

    background = True

    def __init__(self, inner, finish):
        self._inner = inner
        self._finish = finish

    def __iter__(self):
        return self

    def __next__(self):
        return self._finish(next(self._inner))

    def close(self) -> None:
        self._inner.close()
