"""ImageNet input pipeline (SURVEY.md §3(4) — the perf-critical one).

Reference shape: ``TFRecordDataset(shards) → shuffle → map(decode_jpeg +
augment, parallel) → batch → prefetch(device)`` on host CPU threads
overlapped with the device step. Here the same stages run through
``tf.data`` **as a host-side reader only** (TF never touches the TPU;
batches cross into JAX as numpy), feeding the shared loop's async
device-prefetch queue (data/prefetch.py) which replaces
``experimental_distribute_dataset`` + device prefetch:

- standard ImageNet TFRecord schema (``image/encoded``,
  ``image/class/label``) with the classic ResNet augmentation:
  sample_distorted_bounding_box crop → resize 224 → random flip for
  train; 87.5% central crop for eval.
- per-host sharding by ``jax.process_index`` (the multi-worker
  ``dataset.shard(num_workers, index)`` equivalent, SURVEY.md §3(5)).
- without ``data_dir``: a seeded synthetic stream with label-correlated
  low-rank image structure — learnable, so integration tests assert
  actual training, with O(classes·size) memory instead of materializing
  full images.
"""

from __future__ import annotations

import os
from typing import Iterator

import numpy as np

MEAN_RGB = np.array([0.485, 0.456, 0.406], np.float32)
STDDEV_RGB = np.array([0.229, 0.224, 0.225], np.float32)


# --------------------------------------------------------------- synthetic


class SyntheticImageNet:
    """Streaming label-correlated synthetic images.

    Image for class c = outer(u_c, v_c) pattern + noise; u, v are seeded
    per class, so storage is O(classes · size), not O(n · size²)."""

    def __init__(self, *, image_size=224, num_classes=1000, seed=0):
        rng = np.random.default_rng(seed)
        self.u = rng.normal(0, 1, (num_classes, image_size)).astype(np.float32)
        self.v = rng.normal(0, 1, (num_classes, image_size)).astype(np.float32)
        self.phase = rng.normal(0, 1, (num_classes, 3)).astype(np.float32)
        self.num_classes = num_classes
        self.image_size = image_size

    def batch(self, batch_size: int, rng: np.random.Generator):
        y = rng.integers(0, self.num_classes, batch_size).astype(np.int32)
        base = np.einsum("bh,bw->bhw", self.u[y], self.v[y])
        img = base[..., None] * self.phase[y][:, None, None, :]
        img += rng.normal(0, 2.0, img.shape).astype(np.float32)
        return {"image": img.astype(np.float32), "label": y}


def synthetic_train_iter(
    batch_size: int,
    *,
    image_size=224,
    num_classes=1000,
    seed=0,
    start_step=0,
) -> Iterator[dict]:
    src = SyntheticImageNet(
        image_size=image_size, num_classes=num_classes, seed=seed
    )
    step = start_step
    while True:
        yield src.batch(batch_size, np.random.default_rng((seed, step)))
        step += 1


def synthetic_eval_iter(
    batch_size: int, *, image_size=224, num_classes=1000, seed=1, batches=8
) -> Iterator[dict]:
    src = SyntheticImageNet(
        image_size=image_size, num_classes=num_classes, seed=seed
    )
    for step in range(batches):
        b = src.batch(batch_size, np.random.default_rng((seed, step)))
        b["mask"] = np.ones(batch_size, np.float32)
        yield b


# ---------------------------------------------------------------- tfrecord


def _tf():
    import tensorflow as tf

    tf.config.set_visible_devices([], "GPU")  # host-side reader only
    try:
        tf.config.set_visible_devices([], "TPU")
    except Exception:
        pass
    return tf


def _parse_and_decode(tf, record, *, train: bool, image_size: int):
    feats = tf.io.parse_single_example(
        record,
        {
            "image/encoded": tf.io.FixedLenFeature([], tf.string),
            "image/class/label": tf.io.FixedLenFeature([], tf.int64),
        },
    )
    img_bytes = feats["image/encoded"]
    if train:
        # Classic ResNet crop: random area 8–100%, aspect 3/4–4/3.
        bbox = tf.zeros([1, 0, 4], tf.float32)
        begin, size, _ = tf.image.sample_distorted_bounding_box(
            tf.io.extract_jpeg_shape(img_bytes),
            bounding_boxes=bbox,
            area_range=(0.08, 1.0),
            aspect_ratio_range=(3 / 4, 4 / 3),
            max_attempts=10,
            use_image_if_no_bounding_boxes=True,
        )
        y, x, _ = tf.unstack(begin)
        h, w, _ = tf.unstack(size)
        img = tf.image.decode_and_crop_jpeg(
            img_bytes, tf.stack([y, x, h, w]), channels=3
        )
        img = tf.image.resize(img, [image_size, image_size])
        img = tf.image.random_flip_left_right(img)
    else:
        img = tf.io.decode_jpeg(img_bytes, channels=3)
        shape = tf.shape(img)
        crop = tf.cast(
            tf.cast(tf.minimum(shape[0], shape[1]), tf.float32) * 0.875, tf.int32
        )
        img = tf.image.resize_with_crop_or_pad(img, crop, crop)
        img = tf.image.resize(img, [image_size, image_size])
    # Emit uint8: normalization runs in the threaded C++ host library
    # (native/fastdata.cpp) after the tf graph — and uint8 batches are
    # 4x cheaper to move between tf.data and numpy.
    img = tf.cast(tf.clip_by_value(img, 0.0, 255.0), tf.uint8)
    # ImageNet TFRecord labels are 1-based.
    label = tf.cast(feats["image/class/label"], tf.int32) - 1
    return {"image": img, "label": label}


def tfrecord_iter(
    data_dir: str,
    split: str,
    batch_size: int,
    *,
    train: bool,
    image_size: int = 224,
    seed: int = 0,
    num_parallel: int = 16,
) -> Iterator[dict]:
    """Host tf.data pipeline → numpy batches (masked final eval batch)."""
    import jax

    tf = _tf()
    pattern = os.path.join(data_dir, f"{split}-*")
    files = sorted(tf.io.gfile.glob(pattern))
    if not files:
        raise FileNotFoundError(f"no TFRecord shards matching {pattern}")
    ds = tf.data.Dataset.from_tensor_slices(files)
    # Per-host input sharding (multi-host DP, SURVEY.md §3(5)).
    ds = ds.shard(jax.process_count(), jax.process_index())
    if train:
        ds = ds.shuffle(len(files), seed=seed)
    ds = ds.interleave(
        tf.data.TFRecordDataset,
        cycle_length=num_parallel,
        num_parallel_calls=tf.data.AUTOTUNE,
        deterministic=not train,
    )
    if train:
        ds = ds.shuffle(16 * batch_size, seed=seed)
        ds = ds.repeat()
    ds = ds.map(
        lambda r: _parse_and_decode(tf, r, train=train, image_size=image_size),
        num_parallel_calls=tf.data.AUTOTUNE,
    )
    ds = ds.batch(batch_size, drop_remainder=train)
    ds = ds.prefetch(tf.data.AUTOTUNE)

    from tensorflow_examples_tpu import native

    for batch in ds.as_numpy_iterator():
        img = native.normalize(batch["image"], MEAN_RGB, STDDEV_RGB)
        if img is None:  # no toolchain → vectorized numpy fallback
            img = (
                batch["image"].astype(np.float32) / 255.0 - MEAN_RGB
            ) / STDDEV_RGB
        out = {"image": img, "label": batch["label"]}
        n = len(out["label"])
        if not train and n < batch_size:
            pad = batch_size - n
            out = {
                k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
                for k, v in out.items()
            }
            out["mask"] = np.concatenate(
                [np.ones(n, np.float32), np.zeros(pad, np.float32)]
            )
        elif not train:
            out["mask"] = np.ones(n, np.float32)
        yield out


def has_tfrecords(data_dir: str, split: str) -> bool:
    if not data_dir:
        return False
    import glob

    return bool(glob.glob(os.path.join(data_dir, f"{split}-*")))
