"""Dataset sources: real-format readers with seeded synthetic fallbacks.

Reference data came from ``tf.keras.datasets`` downloads and TFRecord
shards; in this hermetic environment (zero egress) each loader first looks
for the standard on-disk format under ``data_dir`` and otherwise produces
a seeded synthetic dataset with the true shapes/dtypes/cardinalities, so
every example CLI and test runs anywhere.

Every actual filesystem read goes through ``retry_io`` (utils/faults.py):
bounded retries with exponential backoff on OSError — at pod scale the
input store (NFS / GCS-fuse) is flaky long before the TPUs are — and the
same wrapper is where fault-injection IO errors land in tests. Existence
checks and their deliberate FileNotFoundError messages stay outside the
retry (a missing dataset is a config error, not a transient fault).

Telemetry (ISSUE 2): each loader brackets its work in a
``dataset_load`` span (telemetry/spans.py) — startup disk-read time
shows up on the Chrome-trace timeline next to the train-loop phases —
and ``retry_io``'s retries count into the ``io/retries`` registry
counter, so flaky-store churn reaches the JSONL windows and run report.
"""

from __future__ import annotations

import functools
import gzip
import os
import pickle
import struct

import numpy as np

from tensorflow_examples_tpu.data.memory import InMemoryDataset
from tensorflow_examples_tpu.telemetry.spans import span as _trace_span
from tensorflow_examples_tpu.utils.faults import retry_io


def _traced_load(dataset: str):
    """Bracket a loader in a ``dataset_load`` trace span (named by
    dataset so a slow startup read is attributable on the timeline)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with _trace_span("dataset_load", dataset=dataset):
                return fn(*args, **kwargs)

        return wrapper

    return deco


# ------------------------------------------------------------------ MNIST


def _read_idx(path: str) -> np.ndarray:
    """Read an IDX file (the standard MNIST distribution format)."""

    def read():
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic = struct.unpack(">HBB", f.read(4))
            _, dtype_code, ndim = magic
            dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
            dtype = {
                8: np.uint8, 9: np.int8, 11: np.int16, 12: np.int32,
                13: np.float32,
            }[dtype_code]
            return np.frombuffer(f.read(), dtype=dtype).reshape(dims)

    return retry_io(read, path)


def _find(data_dir: str, names: list[str]) -> str | None:
    for n in names:
        for cand in (n, n + ".gz"):
            p = os.path.join(data_dir, cand)
            if os.path.exists(p):
                return p
    return None


@_traced_load("mnist")
def load_mnist(data_dir: str = "", split: str = "train") -> InMemoryDataset:
    prefix = "train" if split == "train" else "t10k"
    if data_dir:
        imgs = _find(data_dir, [f"{prefix}-images-idx3-ubyte", f"{prefix}-images.idx3-ubyte"])
        lbls = _find(data_dir, [f"{prefix}-labels-idx1-ubyte", f"{prefix}-labels.idx1-ubyte"])
        if not (imgs and lbls):
            raise FileNotFoundError(
                f"--data_dir={data_dir} set but MNIST IDX files not found there "
                "(expected train-images-idx3-ubyte etc.); omit --data_dir for "
                "synthetic data"
            )
        x = _read_idx(imgs).astype(np.float32) / 255.0
        y = _read_idx(lbls).astype(np.int32)
        return InMemoryDataset({"image": x[..., None], "label": y})
    return synthetic_images(
        n=60000 if split == "train" else 10000,
        shape=(28, 28, 1),
        num_classes=10,
        seed=0 if split == "train" else 1,
    )


# ---------------------------------------------------------------- CIFAR-10


CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)


@_traced_load("cifar10")
def load_cifar10(
    data_dir: str = "", split: str = "train", *, normalized: bool = True
) -> InMemoryDataset:
    """Reads the python-pickle CIFAR-10 distribution if present.

    ``normalized=False`` keeps uint8 pixels (4x smaller in memory) so the
    train-time crop/flip/normalize can run fused in the native C++ host
    library (data/augment.py); synthetic fallback data is always float.
    """
    if data_dir:
        batch_dir = data_dir
        nested = os.path.join(data_dir, "cifar-10-batches-py")
        if os.path.isdir(nested):
            batch_dir = nested
        names = (
            [f"data_batch_{i}" for i in range(1, 6)]
            if split == "train"
            else ["test_batch"]
        )
        paths = [os.path.join(batch_dir, n) for n in names]
        if not all(os.path.exists(p) for p in paths):
            raise FileNotFoundError(
                f"--data_dir={data_dir} set but CIFAR-10 python batches not "
                "found there; omit --data_dir for synthetic data"
            )
        def read_batch(p):
            with open(p, "rb") as f:
                return pickle.load(f, encoding="bytes")

        xs, ys = [], []
        for p in paths:
            d = retry_io(lambda p=p: read_batch(p), p)
            xs.append(d[b"data"])
            ys.append(np.asarray(d[b"labels"]))
        x = (
            np.concatenate(xs)
            .reshape(-1, 3, 32, 32)
            .transpose(0, 2, 3, 1)
        )
        y = np.concatenate(ys).astype(np.int32)
        if not normalized:
            return InMemoryDataset({"image": x.astype(np.uint8), "label": y})
        x = (x.astype(np.float32) / 255.0 - CIFAR10_MEAN) / CIFAR10_STD
        return InMemoryDataset({"image": x, "label": y})
    return synthetic_images(
        n=50000 if split == "train" else 10000,
        shape=(32, 32, 3),
        num_classes=10,
        seed=2 if split == "train" else 3,
    )


# ------------------------------------------------------------- LM corpora


@_traced_load("lm_tokens")
def load_lm_tokens(
    data_dir: str = "",
    split: str = "train",
    *,
    seq_len: int = 1024,
    vocab_size: int = 50257,
) -> InMemoryDataset:
    """Token windows [n, seq_len+1] for causal-LM training.

    Accepts the standard flat-token formats under ``data_dir``:
    ``<split>.bin`` (uint16 memmap, the common GPT-2 prep format),
    ``<split>.npy`` (any int dtype), or ``<split>.txt`` (byte-level,
    vocab 256). Windows are non-overlapping; the +1 column provides the
    shifted next-token labels. Without ``data_dir``: seeded synthetic
    bigram streams (learnable, so tests can assert loss decreases).
    """
    if data_dir:
        base = os.path.join(data_dir, split)
        if os.path.exists(base + ".bin"):
            flat = retry_io(
                lambda: np.memmap(base + ".bin", dtype=np.uint16, mode="r"),
                base + ".bin",
            )
        elif os.path.exists(base + ".npy"):
            flat = retry_io(
                lambda: np.load(base + ".npy", mmap_mode="r"), base + ".npy"
            )
        elif os.path.exists(base + ".txt"):

            def read_txt():
                with open(base + ".txt", "rb") as f:
                    return np.frombuffer(f.read(), dtype=np.uint8)

            flat = retry_io(read_txt, base + ".txt")
        else:
            raise FileNotFoundError(
                f"--data_dir={data_dir} set but {split}.bin/.npy/.txt not "
                "found there; omit --data_dir for synthetic data"
            )
        window = seq_len + 1
        n = len(flat) // window
        if n == 0:
            raise ValueError(
                f"corpus has {len(flat)} tokens < one window ({window})"
            )
        toks = np.asarray(flat[: n * window]).astype(np.int32).reshape(n, window)
        if toks.max() >= vocab_size:
            raise ValueError(
                f"corpus token id {toks.max()} >= vocab_size {vocab_size}"
            )
        return InMemoryDataset({"tokens": toks})
    return synthetic_tokens(
        n=512 if split == "train" else 64,
        seq_len=seq_len + 1,
        vocab_size=vocab_size,
        seed=4 if split == "train" else 5,
    )


# --------------------------------------------------------------- synthetic


def synthetic_images(
    n: int, shape: tuple[int, ...], num_classes: int, seed: int = 0
) -> InMemoryDataset:
    """Seeded learnable synthetic data: images correlate with labels so
    training loss actually decreases (lets integration tests assert
    learning, not just execution)."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, num_classes, size=n).astype(np.int32)
    protos = rng.normal(0, 1, size=(num_classes,) + tuple(shape)).astype(np.float32)
    x = protos[y] + rng.normal(0, 2.0, size=(n,) + tuple(shape)).astype(np.float32)
    return InMemoryDataset({"image": x, "label": y})


def synthetic_tokens(
    n: int, seq_len: int, vocab_size: int, seed: int = 0
) -> InMemoryDataset:
    """Seeded synthetic token streams with learnable bigram structure."""
    rng = np.random.default_rng(seed)
    # Markov chain: each token prefers a fixed successor → learnable.
    succ = rng.integers(0, vocab_size, size=vocab_size)
    toks = np.empty((n, seq_len), dtype=np.int32)
    toks[:, 0] = rng.integers(0, vocab_size, size=n)
    noise = rng.random((n, seq_len)) < 0.2
    rand = rng.integers(0, vocab_size, size=(n, seq_len))
    for t in range(1, seq_len):
        toks[:, t] = np.where(noise[:, t], rand[:, t], succ[toks[:, t - 1]])
    return InMemoryDataset({"tokens": toks})


# ------------------------------------------------------------------- GLUE


GLUE_NUM_LABELS = {
    "cola": 2, "sst2": 2, "mrpc": 2, "stsb": 1, "qqp": 2,
    "mnli": 3, "qnli": 2, "rte": 2, "wnli": 2,
}


@_traced_load("glue")
def load_glue(
    data_dir: str = "",
    task: str = "sst2",
    split: str = "train",
    *,
    seq_len: int = 128,
    vocab_size: int = 30522,
) -> InMemoryDataset:
    """Tokenized GLUE features for BERT fine-tuning.

    With ``data_dir``: expects ``<task>_<split>.npz`` holding pre-tokenized
    arrays (``tokens`` [n, S], ``attention_mask`` [n, S],
    ``token_type_ids`` [n, S], ``label`` [n]) — the output of any BERT
    tokenizer run offline (this hermetic image has no network for
    vocab downloads). Without: a seeded synthetic task with the same
    schema whose label is a linear function of marker-token counts, so
    fine-tuning measurably learns.
    """
    if task not in GLUE_NUM_LABELS:
        raise ValueError(f"unknown GLUE task {task!r}; one of {sorted(GLUE_NUM_LABELS)}")
    if data_dir:
        path = os.path.join(data_dir, f"{task}_{split}.npz")
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"--data_dir={data_dir} set but {task}_{split}.npz not found; "
                "omit --data_dir for synthetic data"
            )
        d = retry_io(lambda: np.load(path), path)
        arrays = {
            "tokens": d["tokens"].astype(np.int32),
            "attention_mask": d["attention_mask"].astype(np.int32),
            "token_type_ids": d["token_type_ids"].astype(np.int32),
            "label": d["label"].astype(
                np.float32 if task == "stsb" else np.int32
            ),
        }
        return InMemoryDataset(arrays)
    return synthetic_glue(
        task,
        n=2048 if split == "train" else 256,
        seq_len=seq_len,
        vocab_size=vocab_size,
        seed=6 if split == "train" else 7,
    )


def synthetic_glue(
    task: str, *, n: int, seq_len: int, vocab_size: int, seed: int = 0
) -> InMemoryDataset:
    """Seeded synthetic sentence(-pair) classification/regression data.

    Marker token ids 10..10+C are planted with class-dependent frequency;
    the label is recoverable from their counts (regression for stsb)."""
    rng = np.random.default_rng(seed)
    num_labels = GLUE_NUM_LABELS[task]
    classes = max(num_labels, 2)
    toks = rng.integers(100, vocab_size, size=(n, seq_len)).astype(np.int32)
    lengths = rng.integers(seq_len // 2, seq_len + 1, size=n)
    mask = (np.arange(seq_len)[None, :] < lengths[:, None]).astype(np.int32)
    y = rng.integers(0, classes, size=n)
    for c in range(classes):
        rows = np.where(y == c)[0]
        # Plant ~8 class-c markers at random valid positions per row.
        for r in rows:
            pos = rng.integers(1, lengths[r], size=8)
            toks[r, pos] = 10 + c
    toks[:, 0] = 101  # [CLS]
    # Pair tasks get a type-id boundary mid-sentence ([SEP] at split).
    boundary = np.maximum(lengths // 2, 1)
    type_ids = (np.arange(seq_len)[None, :] >= boundary[:, None]).astype(np.int32)
    type_ids *= mask
    toks = np.where(mask > 0, toks, 0)
    label = (
        (y.astype(np.float32) / (classes - 1) * 5.0)
        if task == "stsb"
        else y.astype(np.int32)
    )
    return InMemoryDataset(
        {
            "tokens": toks,
            "attention_mask": mask,
            "token_type_ids": type_ids,
            "label": label,
        }
    )
