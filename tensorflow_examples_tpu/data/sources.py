"""Dataset sources: real-format readers with seeded synthetic fallbacks.

Reference data came from ``tf.keras.datasets`` downloads and TFRecord
shards; in this hermetic environment (zero egress) each loader first looks
for the standard on-disk format under ``data_dir`` and otherwise produces
a seeded synthetic dataset with the true shapes/dtypes/cardinalities, so
every example CLI and test runs anywhere.

Every actual filesystem read goes through ``retry_io`` (utils/faults.py):
bounded retries with exponential backoff on OSError — at pod scale the
input store (NFS / GCS-fuse) is flaky long before the TPUs are — and the
same wrapper is where fault-injection IO errors land in tests. Existence
checks and their deliberate FileNotFoundError messages stay outside the
retry (a missing dataset is a config error, not a transient fault).

Telemetry (ISSUE 2): each loader brackets its work in a
``dataset_load`` span (telemetry/spans.py) — startup disk-read time
shows up on the Chrome-trace timeline next to the train-loop phases —
and ``retry_io``'s retries count into the ``io/retries`` registry
counter, so flaky-store churn reaches the JSONL windows and run report.
"""

from __future__ import annotations

import functools
import gzip
import os
import pickle
import struct

import numpy as np

from tensorflow_examples_tpu.data.memory import InMemoryDataset
from tensorflow_examples_tpu.telemetry.spans import span as _trace_span
from tensorflow_examples_tpu.utils.faults import retry_io


def _traced_load(dataset: str):
    """Bracket a loader in a ``dataset_load`` trace span (named by
    dataset so a slow startup read is attributable on the timeline)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with _trace_span("dataset_load", dataset=dataset):
                return fn(*args, **kwargs)

        return wrapper

    return deco


# ------------------------------------------------------------------ MNIST


def _read_idx(path: str) -> np.ndarray:
    """Read an IDX file (the standard MNIST distribution format)."""

    def read():
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic = struct.unpack(">HBB", f.read(4))
            _, dtype_code, ndim = magic
            dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
            dtype = {
                8: np.uint8, 9: np.int8, 11: np.int16, 12: np.int32,
                13: np.float32,
            }[dtype_code]
            return np.frombuffer(f.read(), dtype=dtype).reshape(dims)

    return retry_io(read, path)


def _find(data_dir: str, names: list[str]) -> str | None:
    for n in names:
        for cand in (n, n + ".gz"):
            p = os.path.join(data_dir, cand)
            if os.path.exists(p):
                return p
    return None


@_traced_load("mnist")
def load_mnist(data_dir: str = "", split: str = "train") -> InMemoryDataset:
    prefix = "train" if split == "train" else "t10k"
    if data_dir:
        imgs = _find(data_dir, [f"{prefix}-images-idx3-ubyte", f"{prefix}-images.idx3-ubyte"])
        lbls = _find(data_dir, [f"{prefix}-labels-idx1-ubyte", f"{prefix}-labels.idx1-ubyte"])
        if not (imgs and lbls):
            raise FileNotFoundError(
                f"--data_dir={data_dir} set but MNIST IDX files not found there "
                "(expected train-images-idx3-ubyte etc.); omit --data_dir for "
                "synthetic data"
            )
        x = _read_idx(imgs).astype(np.float32) / 255.0
        y = _read_idx(lbls).astype(np.int32)
        return InMemoryDataset({"image": x[..., None], "label": y})
    return synthetic_images(
        n=60000 if split == "train" else 10000,
        shape=(28, 28, 1),
        num_classes=10,
        seed=0 if split == "train" else 1,
    )


# ---------------------------------------------------------------- CIFAR-10


CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)


@_traced_load("cifar10")
def load_cifar10(
    data_dir: str = "", split: str = "train", *, normalized: bool = True
) -> InMemoryDataset:
    """Reads the python-pickle CIFAR-10 distribution if present.

    ``normalized=False`` keeps uint8 pixels (4x smaller in memory) so the
    train-time crop/flip/normalize can run fused in the native C++ host
    library (data/augment.py); synthetic fallback data is always float.
    """
    if data_dir:
        batch_dir = data_dir
        nested = os.path.join(data_dir, "cifar-10-batches-py")
        if os.path.isdir(nested):
            batch_dir = nested
        names = (
            [f"data_batch_{i}" for i in range(1, 6)]
            if split == "train"
            else ["test_batch"]
        )
        paths = [os.path.join(batch_dir, n) for n in names]
        if not all(os.path.exists(p) for p in paths):
            raise FileNotFoundError(
                f"--data_dir={data_dir} set but CIFAR-10 python batches not "
                "found there; omit --data_dir for synthetic data"
            )
        def read_batch(p):
            with open(p, "rb") as f:
                return pickle.load(f, encoding="bytes")

        xs, ys = [], []
        for p in paths:
            d = retry_io(lambda p=p: read_batch(p), p)
            xs.append(d[b"data"])
            ys.append(np.asarray(d[b"labels"]))
        x = (
            np.concatenate(xs)
            .reshape(-1, 3, 32, 32)
            .transpose(0, 2, 3, 1)
        )
        y = np.concatenate(ys).astype(np.int32)
        if not normalized:
            return InMemoryDataset({"image": x.astype(np.uint8), "label": y})
        x = (x.astype(np.float32) / 255.0 - CIFAR10_MEAN) / CIFAR10_STD
        return InMemoryDataset({"image": x, "label": y})
    return synthetic_images(
        n=50000 if split == "train" else 10000,
        shape=(32, 32, 3),
        num_classes=10,
        seed=2 if split == "train" else 3,
    )


# ------------------------------------------------------------- LM corpora


@_traced_load("lm_tokens")
def load_lm_tokens(
    data_dir: str = "",
    split: str = "train",
    *,
    seq_len: int = 1024,
    vocab_size: int = 50257,
) -> InMemoryDataset:
    """Token windows [n, seq_len+1] for causal-LM training.

    Accepts the standard flat-token formats under ``data_dir``:
    ``<split>.bin`` (uint16 memmap, the common GPT-2 prep format),
    ``<split>.npy`` (any int dtype), or ``<split>.txt`` (byte-level,
    vocab 256). Windows are non-overlapping; the +1 column provides the
    shifted next-token labels. Without ``data_dir``: seeded synthetic
    bigram streams (learnable, so tests can assert loss decreases).
    """
    if data_dir:
        base = os.path.join(data_dir, split)
        if os.path.exists(base + ".bin"):
            flat = retry_io(
                lambda: np.memmap(base + ".bin", dtype=np.uint16, mode="r"),
                base + ".bin",
            )
        elif os.path.exists(base + ".npy"):
            flat = retry_io(
                lambda: np.load(base + ".npy", mmap_mode="r"), base + ".npy"
            )
        elif os.path.exists(base + ".txt"):

            def read_txt():
                with open(base + ".txt", "rb") as f:
                    return np.frombuffer(f.read(), dtype=np.uint8)

            flat = retry_io(read_txt, base + ".txt")
        else:
            raise FileNotFoundError(
                f"--data_dir={data_dir} set but {split}.bin/.npy/.txt not "
                "found there; omit --data_dir for synthetic data"
            )
        window = seq_len + 1
        n = len(flat) // window
        if n == 0:
            raise ValueError(
                f"corpus has {len(flat)} tokens < one window ({window})"
            )
        toks = np.asarray(flat[: n * window]).astype(np.int32).reshape(n, window)
        if toks.max() >= vocab_size:
            raise ValueError(
                f"corpus token id {toks.max()} >= vocab_size {vocab_size}"
            )
        return InMemoryDataset({"tokens": toks})
    return synthetic_tokens(
        n=512 if split == "train" else 64,
        seq_len=seq_len + 1,
        vocab_size=vocab_size,
        seed=4 if split == "train" else 5,
    )


# --------------------------------------------------------------- synthetic


def synthetic_images(
    n: int, shape: tuple[int, ...], num_classes: int, seed: int = 0
) -> InMemoryDataset:
    """Seeded learnable synthetic data: images correlate with labels so
    training loss actually decreases (lets integration tests assert
    learning, not just execution)."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, num_classes, size=n).astype(np.int32)
    protos = rng.normal(0, 1, size=(num_classes,) + tuple(shape)).astype(np.float32)
    x = protos[y] + rng.normal(0, 2.0, size=(n,) + tuple(shape)).astype(np.float32)
    return InMemoryDataset({"image": x, "label": y})


def synthetic_tokens(
    n: int, seq_len: int, vocab_size: int, seed: int = 0
) -> InMemoryDataset:
    """Seeded synthetic token streams with learnable bigram structure."""
    rng = np.random.default_rng(seed)
    # Markov chain: each token prefers a fixed successor → learnable.
    succ = rng.integers(0, vocab_size, size=vocab_size)
    toks = np.empty((n, seq_len), dtype=np.int32)
    toks[:, 0] = rng.integers(0, vocab_size, size=n)
    noise = rng.random((n, seq_len)) < 0.2
    rand = rng.integers(0, vocab_size, size=(n, seq_len))
    for t in range(1, seq_len):
        toks[:, t] = np.where(noise[:, t], rand[:, t], succ[toks[:, t - 1]])
    return InMemoryDataset({"tokens": toks})


# ------------------------------------------------------------------- GLUE


GLUE_NUM_LABELS = {
    "cola": 2, "sst2": 2, "mrpc": 2, "stsb": 1, "qqp": 2,
    "mnli": 3, "qnli": 2, "rte": 2, "wnli": 2,
}


@_traced_load("glue")
def load_glue(
    data_dir: str = "",
    task: str = "sst2",
    split: str = "train",
    *,
    seq_len: int = 128,
    vocab_size: int = 30522,
) -> InMemoryDataset:
    """Tokenized GLUE features for BERT fine-tuning.

    With ``data_dir``: expects ``<task>_<split>.npz`` holding pre-tokenized
    arrays (``tokens`` [n, S], ``attention_mask`` [n, S],
    ``token_type_ids`` [n, S], ``label`` [n]) — the output of any BERT
    tokenizer run offline (this hermetic image has no network for
    vocab downloads). Without: a seeded synthetic task with the same
    schema whose label is a linear function of marker-token counts, so
    fine-tuning measurably learns.
    """
    if task not in GLUE_NUM_LABELS:
        raise ValueError(f"unknown GLUE task {task!r}; one of {sorted(GLUE_NUM_LABELS)}")
    if data_dir:
        path = os.path.join(data_dir, f"{task}_{split}.npz")
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"--data_dir={data_dir} set but {task}_{split}.npz not found; "
                "omit --data_dir for synthetic data"
            )
        d = retry_io(lambda: np.load(path), path)
        arrays = {
            "tokens": d["tokens"].astype(np.int32),
            "attention_mask": d["attention_mask"].astype(np.int32),
            "token_type_ids": d["token_type_ids"].astype(np.int32),
            "label": d["label"].astype(
                np.float32 if task == "stsb" else np.int32
            ),
        }
        return InMemoryDataset(arrays)
    return synthetic_glue(
        task,
        n=2048 if split == "train" else 256,
        seq_len=seq_len,
        vocab_size=vocab_size,
        seed=6 if split == "train" else 7,
    )


def synthetic_glue(
    task: str, *, n: int, seq_len: int, vocab_size: int, seed: int = 0
) -> InMemoryDataset:
    """Seeded synthetic sentence(-pair) classification/regression data.

    Marker token ids 10..10+C are planted with class-dependent frequency;
    the label is recoverable from their counts (regression for stsb)."""
    rng = np.random.default_rng(seed)
    num_labels = GLUE_NUM_LABELS[task]
    classes = max(num_labels, 2)
    toks = rng.integers(100, vocab_size, size=(n, seq_len)).astype(np.int32)
    lengths = rng.integers(seq_len // 2, seq_len + 1, size=n)
    mask = (np.arange(seq_len)[None, :] < lengths[:, None]).astype(np.int32)
    y = rng.integers(0, classes, size=n)
    for c in range(classes):
        rows = np.where(y == c)[0]
        # Plant ~8 class-c markers at random valid positions per row.
        for r in rows:
            pos = rng.integers(1, lengths[r], size=8)
            toks[r, pos] = 10 + c
    toks[:, 0] = 101  # [CLS]
    # Pair tasks get a type-id boundary mid-sentence ([SEP] at split).
    boundary = np.maximum(lengths // 2, 1)
    type_ids = (np.arange(seq_len)[None, :] >= boundary[:, None]).astype(np.int32)
    type_ids *= mask
    toks = np.where(mask > 0, toks, 0)
    label = (
        (y.astype(np.float32) / (classes - 1) * 5.0)
        if task == "stsb"
        else y.astype(np.int32)
    )
    return InMemoryDataset(
        {
            "tokens": toks,
            "attention_mask": mask,
            "token_type_ids": type_ids,
            "label": label,
        }
    )


# ------------------------------------------- sharded parallel readers
#
# ISSUE 6 tentpole (a): N reader threads over disjoint shard slices,
# merged into ONE deterministic stream. The merge order is defined by
# the shard list alone — shards in the given (seeded, per-epoch) order,
# records in in-shard order — NOT by thread timing, so the output is
# bit-identical for every num_readers; num_readers=1 IS the sequential
# reference path. Parallelism comes from readers filling per-shard
# bounded buffers ahead of the consumer's cursor.


class _ShardEnd:
    pass


_SHARD_END = _ShardEnd()


class ShardedReader:
    """Deterministic parallel reader over an ordered shard list.

    ``read_fn(shard)`` yields one shard's records in order. Readers
    claim shards in list order (an atomic cursor — reader t is NOT
    pinned to slice t::N, so one huge shard can't serialize the tail)
    and push records into that shard's bounded queue in BLOCKS of
    ``block_records`` (one queue handoff per block: per-record
    cross-thread wakeups would pay a GIL thread-switch per record and
    dominate small-record streams); the consumer walks shards strictly
    in list order, so the merged stream equals the sequential
    concatenation for ANY reader count. Memory is bounded GLOBALLY,
    not just per shard: readers may claim at most ``max_ahead`` shards
    past the consumer's cursor (a split of many small shards would
    otherwise buffer entirely into host RAM). ``close()`` (also run by
    the generator's ``finally``) stops readers promptly — no orphan
    threads when the consumer abandons the stream mid-epoch.
    """

    def __init__(
        self,
        shards: list,
        read_fn,
        *,
        num_readers: int = 1,
        buffer_records: int = 256,
        block_records: int = 32,
        max_ahead: int = 0,
        name: str = "shard_reader",
    ):
        import queue as queue_mod
        import threading

        self.shards = list(shards)
        self.read_fn = read_fn
        self.num_readers = max(int(num_readers), 1)
        self.block_records = max(int(block_records), 1)
        # Lookahead window: enough shards that every reader has one in
        # flight and one queued behind the consumer's cursor.
        self.max_ahead = int(max_ahead) or max(2 * self.num_readers, 2)
        self._queues = [
            queue_mod.Queue(
                maxsize=max(
                    int(buffer_records) // self.block_records, 1
                )
            )
            for _ in self.shards
        ]
        self._stop = threading.Event()
        self._cursor = 0
        self._consumed = 0  # shards fully drained by the consumer
        self._cursor_lock = threading.Lock()
        self._threads = [
            threading.Thread(
                target=self._read_loop, name=f"{name}-{i}", daemon=True
            )
            for i in range(min(self.num_readers, max(len(self.shards), 1)))
        ]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------ reader

    def _claim(self) -> int | None:
        while not self._stop.is_set():
            with self._cursor_lock:
                if self._cursor >= len(self.shards):
                    return None
                if self._cursor < self._consumed + self.max_ahead:
                    i = self._cursor
                    self._cursor += 1
                    return i
            # Far enough ahead of the consumer: wait for it to advance
            # (global memory bound — see class docstring).
            self._stop.wait(0.05)
        return None

    def _put(self, q, item) -> bool:
        import queue as queue_mod

        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue_mod.Full:
                continue
        return False

    def _read_loop(self) -> None:
        while not self._stop.is_set():
            i = self._claim()
            if i is None:
                return
            q = self._queues[i]
            block: list = []
            try:
                for rec in self.read_fn(self.shards[i]):
                    block.append(rec)
                    if len(block) >= self.block_records:
                        if not self._put(q, block):
                            return
                        block = []
            except BaseException as e:  # noqa: BLE001 - re-raised in order
                if block:
                    self._put(q, block)
                self._put(q, e)
                continue
            if block and not self._put(q, block):
                return
            if not self._put(q, _SHARD_END):
                return

    # ---------------------------------------------------------- consumer

    def records(self):
        """All records, in deterministic shard-list order."""
        import queue as queue_mod

        try:
            for i in range(len(self.shards)):
                q = self._queues[i]
                while True:
                    try:
                        item = q.get(timeout=0.1)
                    except queue_mod.Empty:
                        if self._stop.is_set():
                            raise RuntimeError(
                                "ShardedReader closed mid-stream"
                            ) from None
                        continue
                    if item is _SHARD_END:
                        break
                    if isinstance(item, BaseException):
                        raise RuntimeError(
                            f"shard reader failed on {self.shards[i]!r}"
                        ) from item
                    yield from item
                with self._cursor_lock:
                    self._consumed = i + 1
        finally:
            self.close()

    def close(self) -> None:
        """Stop every reader thread promptly (idempotent)."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)

    def __enter__(self) -> "ShardedReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def seeded_window_shuffle(items, window: int, rng: np.random.Generator):
    """tf.data-style bounded shuffle buffer, deterministic given ``rng``.

    Fill a ``window``-slot buffer; for each further item emit a
    seeded-random slot and refill it, then drain the tail by seeded
    sampling. Because the upstream order is already deterministic (the
    sharded reader's contract), the shuffled stream is a pure function
    of (stream, rng) — identical for any reader count, and exactly
    replayable for resume. ``window <= 1`` is a pass-through.
    """
    if window <= 1:
        yield from items
        return
    buf: list = []
    for item in items:
        if len(buf) < window:
            buf.append(item)
            continue
        j = int(rng.integers(window))
        out = buf[j]
        buf[j] = item
        yield out
    while buf:
        j = int(rng.integers(len(buf)))
        buf[j], out = buf[-1], buf[j]
        buf.pop()
        yield out


def interleave_shards(
    shards: list, read_fn, *, num_readers: int = 1, buffer_records: int = 256
):
    """Generator over every record of ``shards`` in deterministic order
    (sequential-concatenation semantics), read by ``num_readers``
    background threads. ``num_readers <= 1`` runs fully inline — zero
    threads, the literal sequential reference."""
    if num_readers <= 1:
        for shard in shards:
            yield from read_fn(shard)
        return
    reader = ShardedReader(
        shards, read_fn, num_readers=num_readers,
        buffer_records=buffer_records,
    )
    yield from reader.records()


# --------------------------------------------- TFRecord without tf
#
# The parallel pipeline reads (and tests/tools write) TFRecord shards
# with a pure-python implementation of the framing — the sharded reader
# path needs no TensorFlow import at all. Framing per record: uint64le
# length, uint32le masked-crc32c(length), payload, uint32le
# masked-crc32c(payload). CRCs are written correctly (tf readers verify
# them) and skipped on read by default (decode dominates; flip
# ``verify_crc=True`` to pay the check).

_CRC32C_TABLE: list[int] | None = None


def _crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli), the TFRecord checksum."""
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        table = []
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _CRC32C_TABLE = table
    crc = 0xFFFFFFFF
    tab = _CRC32C_TABLE
    for b in data:
        crc = tab[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def iter_tfrecord_records(path: str, *, verify_crc: bool = False):
    """Yield raw record payloads from one TFRecord shard (pure python).

    The open itself goes through ``retry_io`` (flaky-store policy, see
    module docstring). A file ending exactly on a record boundary is
    the clean EOF; a record cut off mid-frame raises — like tf's
    ``DataLossError`` — because silent truncation would both lose data
    and desynchronize the cached record count the resume arithmetic
    depends on. Full CRC verification stays opt-in (decode dominates),
    but frame-structure corruption is always loud.
    """

    def _open():
        return open(path, "rb")

    f = retry_io(_open, path)
    try:
        offset = 0
        while True:
            header = f.read(12)
            if not header:
                return  # clean EOF: record boundary
            if len(header) < 12:
                raise ValueError(
                    f"{path}: truncated record header at byte {offset} "
                    "(torn or corrupt shard)"
                )
            (length,) = struct.unpack("<Q", header[:8])
            if verify_crc:
                (lcrc,) = struct.unpack("<I", header[8:12])
                if _masked_crc(header[:8]) != lcrc:
                    raise ValueError(
                        f"{path}: corrupt length crc at byte {offset}"
                    )
            payload = f.read(length)
            footer = f.read(4)
            if len(payload) < length or len(footer) < 4:
                raise ValueError(
                    f"{path}: truncated record at byte {offset} "
                    f"(expected {length} payload bytes; torn or corrupt "
                    "shard)"
                )
            if verify_crc:
                (dcrc,) = struct.unpack("<I", footer)
                if _masked_crc(payload) != dcrc:
                    raise ValueError(
                        f"{path}: corrupt record crc at byte {offset}"
                    )
            offset += 16 + length
            yield payload
    finally:
        f.close()


def write_tfrecord(path: str, records) -> int:
    """Write raw payloads as a TFRecord shard (correct masked CRCs, so
    tf's reader accepts the file); returns the record count."""
    n = 0
    with open(path, "wb") as f:
        for rec in records:
            rec = bytes(rec)
            header = struct.pack("<Q", len(rec))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(rec)
            f.write(struct.pack("<I", _masked_crc(rec)))
            n += 1
    return n


# Minimal tf.train.Example wire parser — just enough proto to pull
# bytes_list / int64_list / float_list features out of the standard
# ImageNet TFRecord schema without importing TensorFlow.


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_fields(buf: bytes):
    """(field_number, wire_type, value) triples of one message. Value is
    bytes for length-delimited fields, int for varint/fixed."""
    pos = 0
    end = len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 0x7
        if wire == 0:  # varint
            value, pos = _read_varint(buf, pos)
        elif wire == 2:  # length-delimited
            length, pos = _read_varint(buf, pos)
            value = buf[pos:pos + length]
            pos += length
        elif wire == 5:  # fixed32
            value = struct.unpack("<I", buf[pos:pos + 4])[0]
            pos += 4
        elif wire == 1:  # fixed64
            value = struct.unpack("<Q", buf[pos:pos + 8])[0]
            pos += 8
        else:
            raise ValueError(f"unsupported proto wire type {wire}")
        yield field, wire, value


def parse_example(record: bytes) -> dict[str, list]:
    """tf.train.Example bytes -> {feature name: list of values}.

    bytes_list values come back as ``bytes``, int64_list as ``int``
    (packed or unpacked encodings both accepted), float_list as
    ``float``. Unknown feature kinds raise — a schema surprise must be
    loud, not silently empty.
    """
    features = b""
    for field, _, value in _iter_fields(record):
        if field == 1:  # Example.features
            features = value
    out: dict[str, list] = {}
    for field, _, entry in _iter_fields(features):
        if field != 1:  # Features.feature map entries
            continue
        key = None
        feature = b""
        for f2, _, v2 in _iter_fields(entry):
            if f2 == 1:
                key = v2.decode("utf-8")
            elif f2 == 2:
                feature = v2
        if key is None:
            continue
        values: list = []
        for f3, wire3, v3 in _iter_fields(feature):
            if f3 == 1:  # bytes_list
                for f4, _, v4 in _iter_fields(v3):
                    if f4 == 1:
                        values.append(v4)
            elif f3 == 3:  # int64_list
                for f4, wire4, v4 in _iter_fields(v3):
                    if f4 != 1:
                        continue
                    if wire4 == 2:  # packed
                        pos = 0
                        while pos < len(v4):
                            n, pos = _read_varint(v4, pos)
                            values.append(_signed64(n))
                    else:
                        values.append(_signed64(v4))
            elif f3 == 2:  # float_list
                for f4, wire4, v4 in _iter_fields(v3):
                    if f4 != 1:
                        continue
                    if wire4 == 2:  # packed
                        values.extend(
                            struct.unpack(f"<{len(v4) // 4}f", v4)
                        )
                    else:
                        values.append(
                            struct.unpack("<f", struct.pack("<I", v4))[0]
                        )
            else:
                raise ValueError(
                    f"feature {key!r}: unsupported Feature kind {f3}"
                )
        out[key] = values
    return out


def _signed64(n: int) -> int:
    return n - (1 << 64) if n >= (1 << 63) else n


def make_example(features: dict) -> bytes:
    """Serialize {name: bytes | int | float | list thereof} as a
    tf.train.Example — the writer mirror of :func:`parse_example`, so
    tools and tests can produce standard shards without tf."""

    def varint(n: int) -> bytes:
        out = bytearray()
        while True:
            b = n & 0x7F
            n >>= 7
            if n:
                out.append(b | 0x80)
            else:
                out.append(b)
                return bytes(out)

    def ld(field: int, payload: bytes) -> bytes:
        return varint((field << 3) | 2) + varint(len(payload)) + payload

    entries = b""
    for key, vals in features.items():
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        if all(isinstance(v, (bytes, bytearray)) for v in vals):
            inner = b"".join(ld(1, bytes(v)) for v in vals)
            feature = ld(1, inner)  # bytes_list
        elif all(isinstance(v, int) for v in vals):
            inner = b"".join(
                varint(1 << 3) + varint(v & ((1 << 64) - 1)) for v in vals
            )
            feature = ld(3, inner)  # int64_list
        elif all(isinstance(v, float) for v in vals):
            inner = b"".join(
                varint((1 << 3) | 5) + struct.pack("<f", v) for v in vals
            )
            feature = ld(2, inner)  # float_list
        else:
            raise TypeError(f"feature {key!r}: unsupported value types")
        entries += ld(1, ld(1, key.encode("utf-8")) + ld(2, feature))
    return ld(1, entries)  # Example.features
