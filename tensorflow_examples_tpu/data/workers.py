"""Background decode/augment worker pool (ISSUE 6 tentpole b).

The host input hot path used to run JPEG decode + augmentation inline on
the fetch thread: every image decoded between two device steps, serial
with the loop. This module moves that stage onto a pool of background
workers with the three properties the rest of the stack depends on:

* **Deterministic order.** Work items carry sequence numbers and results
  are re-assembled in submission order, so the consumer sees exactly the
  stream a sequential pipeline would produce — bit-identical batches
  regardless of worker count or scheduling (the golden-batch contract of
  the sharded reader, data/sources.py, extends through this stage).
* **Bounded queues.** At most ``depth`` items are in flight (submission
  queue + reorder buffer together), so a stalled consumer back-pressures
  the pipeline instead of buffering the dataset into host RAM.
* **Poison-pill shutdown.** ``close()`` drains the submission queue,
  feeds one pill per worker, and joins them — idempotent, safe from any
  thread, and registered with ``atexit`` so a SIGTERM-preempted run
  (train/resilience.py raises out of the loop) never strands worker
  threads past interpreter shutdown.

Thread-backed by design: the decode stages this pool runs (libfastjpeg
via ctypes, PIL, tf eager ops) all release the GIL during the actual
decode, so threads scale with cores without the pickling/IPC cost a
process pool would put on every batch. Workers record their compute in
``data_work`` spans (telemetry/spans.py) from their own threads — the
span histogram ``span/data_work`` is where fleet straggler attribution
reads "host time actually spent producing batches" (telemetry/fleet.py),
distinct from the consumer's queue-starvation ``data_wait``.
"""

from __future__ import annotations

import atexit
import logging
import queue
import sys
import threading
import weakref
from typing import Callable, Iterable, Iterator

from tensorflow_examples_tpu.telemetry import registry as _registry
from tensorflow_examples_tpu.telemetry import spans as _spans

log = logging.getLogger(__name__)

_POISON = object()  # shutdown sentinel; never a user item

# Pools still open at interpreter exit (weak: a collected pool needs no
# cleanup — its finalizer closed it). atexit walks this so SIGTERM-preempt
# and plain sys.exit paths leave zero orphan worker threads.
_LIVE_POOLS: "weakref.WeakSet[WorkerPool]" = weakref.WeakSet()


def shutdown_all() -> None:
    """Close every live pool (atexit hook; callable from signal paths)."""
    for pool in list(_LIVE_POOLS):
        pool.close()


atexit.register(shutdown_all)

# GIL switch-interval management: the default 5ms interval throttles the
# per-item producer/worker/consumer handoffs this pipeline lives on —
# measured ~2x pipeline throughput from 1ms on a 2-core host (workers
# release the GIL for the decode itself, so the finer interval costs the
# compute nothing). Refcounted: lowered when the first pool opens,
# restored to the prior value when the last one closes, so pool-free
# phases of the process run at the interpreter default again.
_SWITCH_LOCK = threading.Lock()
_SWITCH_DEPTH = 0  # guard: _SWITCH_LOCK
_SAVED_SWITCH_INTERVAL: float | None = None  # guard: _SWITCH_LOCK

# Worker gauges are shared across pools (a rollback briefly overlaps the
# old pipeline's pool with its replacement), so they move by DELTAS
# under one lock — an absolute set() from a stale pool's deferred close
# would clobber the live pool's numbers.
_GAUGE_LOCK = threading.Lock()


def _adjust_gauge(reg, name: str, delta: float) -> None:
    with _GAUGE_LOCK:
        gauge = reg.gauge(name)
        gauge.set(max((gauge.value or 0.0) + delta, 0.0))


def _enter_fast_switch() -> None:
    global _SWITCH_DEPTH, _SAVED_SWITCH_INTERVAL
    with _SWITCH_LOCK:
        _SWITCH_DEPTH += 1
        if _SWITCH_DEPTH == 1 and sys.getswitchinterval() > 0.001:
            _SAVED_SWITCH_INTERVAL = sys.getswitchinterval()
            sys.setswitchinterval(0.001)


def _exit_fast_switch() -> None:
    global _SWITCH_DEPTH, _SAVED_SWITCH_INTERVAL
    with _SWITCH_LOCK:
        _SWITCH_DEPTH = max(_SWITCH_DEPTH - 1, 0)
        if _SWITCH_DEPTH == 0 and _SAVED_SWITCH_INTERVAL is not None:
            sys.setswitchinterval(_SAVED_SWITCH_INTERVAL)
            _SAVED_SWITCH_INTERVAL = None


class WorkerError(RuntimeError):
    """A worker's exception, re-raised at the item's stream position so
    a deterministic pipeline bug surfaces at the same batch index on
    every run (and on the sequential reference path)."""

    def __init__(self, seq: int, cause: BaseException):
        super().__init__(f"input worker failed on item {seq}: {cause!r}")
        self.seq = seq


class WorkerPool:
    """A fixed pool of worker threads applying ``fn`` to submitted items,
    returning results strictly in submission order."""

    def __init__(
        self,
        fn: Callable,
        num_workers: int,
        *,
        depth: int = 0,
        name: str = "input_worker",
        registry=None,
    ):
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        self.fn = fn
        self.num_workers = int(num_workers)
        # In-flight bound: default 2x workers so every worker has one
        # item queued behind its current one (keeps the pool busy across
        # a slow consumer poll without unbounded buffering).
        self.depth = int(depth) if depth else 2 * self.num_workers
        self.name = name
        self._registry = registry
        _enter_fast_switch()  # restored when the last pool closes
        self._in: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._cond = threading.Condition()
        self._results: dict[int, tuple[bool, object]] = {}  # guard: self._cond
        self._closed = False  # guard: self._cond
        self._threads = [
            threading.Thread(
                target=self._work, name=f"{name}-{i}", daemon=True
            )
            for i in range(self.num_workers)
        ]
        for t in self._threads:
            t.start()
        _LIVE_POOLS.add(self)
        _adjust_gauge(self._reg(), "data/input_workers", self.num_workers)

    def _reg(self):
        return (
            self._registry
            if self._registry is not None
            else _registry.default_registry()
        )

    # ------------------------------------------------------------ intake

    def submit(self, seq: int, item) -> None:
        """Queue one item; blocks when ``depth`` items are in flight."""
        if self._closed:  # graftlint: ignore — best-effort early check;
            # a submit racing close() is caught by result()'s closed
            # re-check, and the queue drain makes the item inert.
            raise RuntimeError(f"WorkerPool {self.name!r} is closed")
        self._in.put((seq, item))

    def result(self, seq: int):
        """Block until item ``seq``'s result is ready; re-raise its
        worker's exception (as :class:`WorkerError`) at this position."""
        with self._cond:
            while seq not in self._results:
                if self._closed:
                    raise RuntimeError(
                        f"WorkerPool {self.name!r} closed with item "
                        f"{seq} outstanding"
                    )
                self._cond.wait(timeout=0.1)
            ok, value = self._results.pop(seq)
        if not ok:
            raise WorkerError(seq, value) from value
        return value

    def map_ordered(self, items: Iterable) -> Iterator:
        """Stream ``fn`` over ``items`` with ``depth`` items in flight;
        yields results in input order. Equivalent to ``map(fn, items)``
        item-for-item — only the wall clock differs."""
        it = iter(items)
        submitted = 0
        served = 0
        exhausted = False
        try:
            while True:
                while not exhausted and submitted - served < self.depth:
                    try:
                        item = next(it)
                    except StopIteration:
                        exhausted = True
                        break
                    self.submit(submitted, item)
                    submitted += 1
                if served == submitted and exhausted:
                    return
                yield self.result(served)
                served += 1
        finally:
            # Prompt upstream teardown: closing this generator (the
            # consumer end) unwinds the source generator's own finally
            # (e.g. the sharded reader's thread shutdown) immediately,
            # not at some later GC pass.
            close = getattr(it, "close", None)
            if close is not None:
                close()

    # ------------------------------------------------------------ worker

    def _work(self) -> None:
        reg = self._reg()
        items_ctr = reg.counter("data/worker_items")
        while True:
            got = self._in.get()
            if got is _POISON:
                return
            seq, item = got
            _adjust_gauge(reg, "data/workers_busy", +1)
            try:
                # data_work: host compute actually producing batches —
                # the signal fleet straggler attribution reads, vs the
                # consumer's queue-starvation data_wait.
                with _spans.span("data_work"):
                    out = (True, self.fn(item))
                items_ctr.inc()
            except BaseException as e:  # noqa: BLE001 - re-raised at seq
                out = (False, e)
            _adjust_gauge(reg, "data/workers_busy", -1)
            with self._cond:
                self._results[seq] = out
                self._cond.notify_all()

    # ------------------------------------------------------------- close

    def close(self, *, timeout: float = 5.0) -> None:
        """Poison-pill shutdown: discard queued work, stop every worker,
        wake any blocked ``result()`` caller. Idempotent; safe to call
        from finalizers, ``atexit``, and preemption paths."""
        with self._cond:
            if self._closed:
                return
            # Flag + wake under the condition (ISSUE 14 lock-pass
            # finding): the old unlocked write left a result() waiter
            # to discover the close only on its next 0.1s poll tick —
            # and only notified AFTER the joins below, up to
            # num_workers * timeout later.
            self._closed = True
            self._cond.notify_all()
        # Discard pending submissions so pills reach the workers even
        # when the queue is full of un-started work.
        try:
            while True:
                self._in.get_nowait()
        except queue.Empty:
            pass
        for _ in self._threads:
            self._in.put(_POISON)
        for t in self._threads:
            t.join(timeout)
            if t.is_alive():  # pragma: no cover - wedged C call
                log.warning(
                    "worker thread %s did not exit within %.1fs "
                    "(daemon; will not block interpreter exit)",
                    t.name,
                    timeout,
                )
        _adjust_gauge(self._reg(), "data/input_workers", -self.num_workers)
        _exit_fast_switch()

    @property
    def closed(self) -> bool:
        return self._closed  # graftlint: ignore — monotonic bool snapshot

    def __enter__(self) -> "WorkerPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class PipelinedIterator:
    """Iterator facade over ``pool.map_ordered(items)``.

    Carries ``background = True`` — the marker ``data/prefetch.py`` reads
    to record its queue pops as ``data_wait`` (starvation) instead of
    ``data_work`` (the workers already recorded the real work from their
    own threads). Closing (explicitly, via ``with``, or by the GC
    finalizer) tears down the source generator AND the pool, so the
    whole pipeline unwinds from the consumer end with no orphans.
    """

    background = True

    def __init__(self, pool: WorkerPool, items: Iterable):
        self._pool = pool
        self._gen = pool.map_ordered(items)
        self._finalizer = weakref.finalize(self, pool.close)

    def __iter__(self) -> "PipelinedIterator":
        return self

    def __next__(self):
        try:
            return next(self._gen)
        except StopIteration:
            self.close()
            raise

    def close(self) -> None:
        try:
            self._gen.close()  # unwinds the source generator's finally
        finally:
            self._finalizer()  # idempotent pool.close()

    def __enter__(self) -> "PipelinedIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
