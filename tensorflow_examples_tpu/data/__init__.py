"""Input pipelines.

TPU-native replacement for the reference's per-example ``tf.data`` graphs
(SURVEY.md §3(4): TFRecord → shuffle → map(decode+augment) → batch →
prefetch(device)). Design:

- Small datasets (MNIST/CIFAR) live in host RAM as numpy arrays; a
  deterministic shuffling iterator feeds the mesh. No graph runtime needed.
- Large datasets (ImageNet) stream TFRecord shards — via grain or the
  native C++ loader (``native/``) — sharded per host, decoded/augmented on
  host CPU, with device prefetch overlapping the step (the tf.data
  ``prefetch(AUTOTUNE)``-to-device equivalent).
- Every iterator is deterministic given (seed, step) and checkpointable,
  which the reference's tf.data shuffle was not.
- With no dataset on disk (``data_dir=""``) each workload falls back to a
  seeded synthetic dataset with the real shapes/dtypes, so every example
  and test runs hermetically.
- The ImageNet hot path (ISSUE 6, docs/data.md) is a pure-python
  parallel pipeline: sharded parallel readers (``sources.ShardedReader``)
  feeding a background decode/augment worker pool (``workers.WorkerPool``)
  — deterministic and exactly resumable for any reader/worker count, with
  the ``data_wait``/``data_work`` span split and depth-adaptive device
  prefetch (``prefetch.DepthController``) on top.
"""

from tensorflow_examples_tpu.data.memory import (
    InMemoryDataset,
    eval_batches,
    train_iterator,
)
from tensorflow_examples_tpu.data.prefetch import device_prefetch
from tensorflow_examples_tpu.data.workers import PipelinedIterator, WorkerPool
