"""Device prefetch: overlap host input with device compute.

Equivalent of the reference's ``dataset.prefetch`` + device prefetch into
HBM (BASELINE.json:north_star). A small look-ahead queue of batches is
``device_put`` ahead of time with the mesh batch sharding; transfers are
async in JAX, so batch N+1 streams into HBM while step N runs.
"""

from __future__ import annotations

import collections
from typing import Iterator

import jax
import jax.numpy as jnp


def put_batch(batch, sharding):
    """The one host→device placement path (used by loop and prefetch).

    Global-view semantics: every process passes the SAME full global
    batch and ``device_put`` materializes each process's addressable
    shards from it. For per-host data sources use ``put_local_batch``.
    """
    return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), sharding), batch)


def put_local_batch(batch, sharding):
    """Form a GLOBAL array from THIS process's local rows.

    Per-host semantics (multi-host input sharding, SURVEY.md §3(5)):
    each process contributes ``global_batch / process_count`` rows — its
    own shard of the data — and the result is one global jax.Array on
    ``sharding``. On a single process this is identical to ``put_batch``.
    """
    import numpy as np

    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)
        ),
        batch,
    )


def bundle_batches(it: Iterator, k: int) -> Iterator:
    """Stack ``k`` consecutive host batches along a new leading axis.

    Feeds the ``steps_per_launch`` bundled train step: each yielded
    pytree has leaves shaped ``[k, batch, ...]``, scanned on device one
    step per slice. Exhaustion mid-bundle is an error — silently
    dropping a partial bundle would skip steps the unbundled loop
    would have run (the loop validates the step span divides by k, so
    a well-sized stream never hits this).
    """
    import numpy as np

    while True:
        group = []
        for _ in range(k):
            try:
                group.append(next(it))
            except StopIteration:
                if group:
                    raise ValueError(
                        f"input stream ended mid-bundle ({len(group)}/{k} "
                        "batches); size the stream to a multiple of "
                        "steps_per_launch"
                    ) from None
                return
        yield jax.tree.map(lambda *xs: np.stack(xs), *group)


def device_prefetch(
    it: Iterator, sharding, *, depth: int = 2, local_batches: bool = False
) -> Iterator:
    queue = collections.deque()
    put_fn = put_local_batch if local_batches else put_batch

    def put(batch):
        return put_fn(batch, sharding)

    try:
        for _ in range(depth):
            queue.append(put(next(it)))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(put(next(it)))
        except StopIteration:
            pass
        yield out
