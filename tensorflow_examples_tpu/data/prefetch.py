"""Device prefetch: overlap host input with device compute.

Equivalent of the reference's ``dataset.prefetch`` + device prefetch into
HBM (BASELINE.json:north_star). A small look-ahead queue of batches is
``device_put`` ahead of time with the mesh batch sharding; transfers are
async in JAX, so batch N+1 streams into HBM while step N runs.

Resilience (ISSUE 1): each fetch runs through the fault-injection hook
(utils/faults.py — slow-batch and corrupt-batch faults land here), and a
batch whose host→device conversion/transfer fails is SKIPPED and counted
rather than killing the run, up to a bounded ``max_skips`` budget
(``TrainConfig.max_skipped_batches``; 0 keeps the historical fail-fast).

Telemetry (ISSUE 2): fetches and skips publish into the default metrics
registry (``data/batches_fetched``, ``data/batches_skipped``) so the
formerly write-only skip counter shows up in every JSONL window and in
the run report.
"""

from __future__ import annotations

import collections
import logging
from typing import Iterator

import jax
import jax.numpy as jnp

from tensorflow_examples_tpu.telemetry import registry as _telemetry_registry
from tensorflow_examples_tpu.utils import faults as _faults

log = logging.getLogger(__name__)


def put_batch(batch, sharding):
    """The one host→device placement path (used by loop and prefetch).

    Global-view semantics: every process passes the SAME full global
    batch and ``device_put`` materializes each process's addressable
    shards from it. For per-host data sources use ``put_local_batch``.
    """
    return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), sharding), batch)


def put_local_batch(batch, sharding):
    """Form a GLOBAL array from THIS process's local rows.

    Per-host semantics (multi-host input sharding, SURVEY.md §3(5)):
    each process contributes ``global_batch / process_count`` rows — its
    own shard of the data — and the result is one global jax.Array on
    ``sharding``. On a single process this is identical to ``put_batch``.
    """
    import numpy as np

    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)
        ),
        batch,
    )


def bundle_batches(it: Iterator, k: int) -> Iterator:
    """Stack ``k`` consecutive host batches along a new leading axis.

    Feeds the ``steps_per_launch`` bundled train step: each yielded
    pytree has leaves shaped ``[k, batch, ...]``, scanned on device one
    step per slice. Exhaustion mid-bundle is an error — silently
    dropping a partial bundle would skip steps the unbundled loop
    would have run (the loop validates the step span divides by k, so
    a well-sized stream never hits this).
    """
    import numpy as np

    while True:
        group = []
        for _ in range(k):
            try:
                group.append(next(it))
            except StopIteration:
                if group:
                    raise ValueError(
                        f"input stream ended mid-bundle ({len(group)}/{k} "
                        "batches); size the stream to a multiple of "
                        "steps_per_launch"
                    ) from None
                return
        yield jax.tree.map(lambda *xs: np.stack(xs), *group)


_END = object()


def device_prefetch(
    it: Iterator,
    sharding,
    *,
    depth: int = 2,
    local_batches: bool = False,
    max_skips: int = 0,
    fault_hooks: bool = True,
) -> Iterator:
    """``fault_hooks=False`` (the eval path) keeps this pipeline out of
    the injection engine's fetch-index space, so ``slow@N``/``badbatch@N``
    target train fetch N deterministically even when eval interleaves."""
    queue = collections.deque()
    put_fn = put_local_batch if local_batches else put_batch
    skipped = 0
    reg = _telemetry_registry.default_registry()
    fetched_ctr = reg.counter("data/batches_fetched")
    skipped_ctr = reg.counter("data/batches_skipped")

    def fetch():
        """Next device-resident batch, or _END. With ``max_skips > 0`` a
        batch that fails the host→device put is poisoned: skip it (and
        count), bounded by the budget. With the default ``max_skips=0``
        the original exception propagates untouched — a deterministic
        pipeline bug must surface as itself, not as 'corrupt input'."""
        nonlocal skipped
        while True:
            try:
                batch = next(it)
            except StopIteration:
                return _END
            try:
                if fault_hooks:
                    eng = _faults.active()
                    if eng is not None:
                        batch = eng.batch_hook(batch)
                out = put_fn(batch, sharding)
                fetched_ctr.inc()
                return out
            except Exception as e:
                if max_skips <= 0:
                    raise
                skipped += 1
                skipped_ctr.inc()
                if skipped > max_skips:
                    raise RuntimeError(
                        f"poisoned input batch ({skipped} bad, budget "
                        f"max_skipped_batches={max_skips} exhausted): {e}"
                    ) from e
                log.warning(
                    "skipping poisoned input batch %d/%d: %s",
                    skipped,
                    max_skips,
                    e,
                )

    for _ in range(depth):
        batch = fetch()
        if batch is _END:
            break
        queue.append(batch)
    while queue:
        out = queue.popleft()
        batch = fetch()
        if batch is not _END:
            queue.append(batch)
        yield out
