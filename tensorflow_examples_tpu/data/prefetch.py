"""Device prefetch: overlap host input with device compute.

Equivalent of the reference's ``dataset.prefetch`` + device prefetch into
HBM (BASELINE.json:north_star). A small look-ahead queue of batches is
``device_put`` ahead of time with the mesh batch sharding; transfers are
async in JAX, so batch N+1 streams into HBM while step N runs.
"""

from __future__ import annotations

import collections
from typing import Iterator

import jax
import jax.numpy as jnp


def put_batch(batch, sharding):
    """The one host→device placement path (used by loop and prefetch)."""
    return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), sharding), batch)


def device_prefetch(it: Iterator, sharding, *, depth: int = 2) -> Iterator:
    queue = collections.deque()

    def put(batch):
        return put_batch(batch, sharding)

    try:
        for _ in range(depth):
            queue.append(put(next(it)))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(put(next(it)))
        except StopIteration:
            pass
        yield out
