"""Device prefetch: overlap host input with device compute.

Equivalent of the reference's ``dataset.prefetch`` + device prefetch into
HBM (BASELINE.json:north_star). A look-ahead queue of batches is
``device_put`` ahead of time with the mesh batch sharding; transfers are
async in JAX, so batch N+1 streams into HBM while step N runs.

Resilience (ISSUE 1): each fetch runs through the fault-injection hook
(utils/faults.py — slow-batch and corrupt-batch faults land here), and a
batch whose host→device conversion/transfer fails is SKIPPED and counted
rather than killing the run, up to a bounded ``max_skips`` budget
(``TrainConfig.max_skipped_batches``; 0 keeps the historical fail-fast).

Telemetry (ISSUE 2): fetches and skips publish into the default metrics
registry (``data/batches_fetched``, ``data/batches_skipped``) so the
formerly write-only skip counter shows up in every JSONL window and in
the run report.

Input-pipeline observability + adaptive depth (ISSUE 6): the loop-level
``data_fetch`` span is split here into its two honest components —

* ``data_work``: host compute actually producing batches. For a plain
  (synchronous) iterator that is the whole ``next(it)`` + fault hooks +
  host→device put; for a background pipeline (the iterator carries
  ``background = True`` — data/workers.PipelinedIterator) the worker
  threads record their own ``data_work`` spans and only hooks + put
  count here.
* ``data_wait``: queue starvation — time this consumer spent blocked on
  a background pipeline's output queue. A fast host back-pressured by
  the device shows ``data_wait``, NOT ``data_work``, which is what keeps
  fleet straggler attribution (telemetry/fleet.py) from blaming a
  device-bound host's input pipeline.

``depth_max > depth`` arms the depth controller: every ``ADAPT_EVERY``
fetches it compares the observed ``span/data_fetch`` p95 against the
``span/device_step`` p95 and deepens the queue (up to ``depth_max``)
while the fetch dominates — i.e. while the loop observably waits on
input — and decays back toward the configured floor when the queue
stays ahead. The live depth is published as the ``data/prefetch_depth``
gauge.
"""

from __future__ import annotations

import collections
import contextlib
import logging
from typing import Iterator

import jax
import jax.numpy as jnp

from tensorflow_examples_tpu.telemetry import registry as _telemetry_registry
from tensorflow_examples_tpu.telemetry.spans import span as _trace_span
from tensorflow_examples_tpu.utils import faults as _faults

log = logging.getLogger(__name__)

# Re-evaluate the prefetch depth every N fetches: long enough for the
# span histograms to hold fresh percentiles, short enough to converge
# within a warmup's worth of steps.
ADAPT_EVERY = 16

# Hysteresis thresholds on fetch_p95 / step_p95: grow while the fetch
# is at least GROW x the device-step dispatch time (the loop is
# observably input-waiting), shrink only when it falls under SHRINK x
# (the queue is comfortably ahead; release the host memory).
GROW_RATIO = 1.0
SHRINK_RATIO = 0.1


def put_batch(batch, sharding):
    """The one host→device placement path (used by loop and prefetch).

    Global-view semantics: every process passes the SAME full global
    batch and ``device_put`` materializes each process's addressable
    shards from it. For per-host data sources use ``put_local_batch``.
    """
    return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), sharding), batch)


def put_local_batch(batch, sharding):
    """Form a GLOBAL array from THIS process's local rows.

    Per-host semantics (multi-host input sharding, SURVEY.md §3(5)):
    each process contributes ``global_batch / process_count`` rows — its
    own shard of the data — and the result is one global jax.Array on
    ``sharding``. On a single process this is identical to ``put_batch``.
    """
    import numpy as np

    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)
        ),
        batch,
    )


def bundle_batches(it: Iterator, k: int) -> Iterator:
    """Stack ``k`` consecutive host batches along a new leading axis.

    Feeds the ``steps_per_launch`` bundled train step: each yielded
    pytree has leaves shaped ``[k, batch, ...]``, scanned on device one
    step per slice. Exhaustion mid-bundle is an error — silently
    dropping a partial bundle would skip steps the unbundled loop
    would have run (the loop validates the step span divides by k, so
    a well-sized stream never hits this).
    """
    import numpy as np

    while True:
        group = []
        for _ in range(k):
            try:
                group.append(next(it))
            except StopIteration:
                if group:
                    raise ValueError(
                        f"input stream ended mid-bundle ({len(group)}/{k} "
                        "batches); size the stream to a multiple of "
                        "steps_per_launch"
                    ) from None
                return
        yield jax.tree.map(lambda *xs: np.stack(xs), *group)


class DepthController:
    """Depth-adaptive double buffering (ISSUE 6 tentpole c).

    Sizes the prefetch queue from the observed ``data_fetch`` p95 vs the
    ``device_step`` p95, within ``[depth, depth_max]``. Inert (fixed
    ``depth``) unless ``depth_max > depth``.
    """

    def __init__(
        self,
        depth: int = 2,
        depth_max: int = 0,
        *,
        registry=None,
        adapt_every: int = ADAPT_EVERY,
    ):
        self.floor = max(int(depth), 1)
        self.depth = self.floor
        self.depth_max = int(depth_max)
        self.adaptive = self.depth_max > self.floor
        self._adapt_every = max(int(adapt_every), 1)
        self._registry = registry
        self._fetches = 0
        self._gauge().set(float(self.depth))

    def _gauge(self):
        reg = (
            self._registry
            if self._registry is not None
            else _telemetry_registry.default_registry()
        )
        return reg.gauge("data/prefetch_depth")

    def observe(self) -> int:
        """Count one fetch; periodically re-derive the depth. Returns
        the (possibly updated) current depth."""
        self._fetches += 1
        if not self.adaptive or self._fetches % self._adapt_every:
            return self.depth
        reg = (
            self._registry
            if self._registry is not None
            else _telemetry_registry.default_registry()
        )
        (fetch_p95,) = reg.histogram("span/data_fetch").percentiles(95)
        (step_p95,) = reg.histogram("span/device_step").percentiles(95)
        if fetch_p95 is None or step_p95 is None or step_p95 <= 0:
            return self.depth
        ratio = fetch_p95 / step_p95
        before = self.depth
        if ratio >= GROW_RATIO and self.depth < self.depth_max:
            self.depth += 1
        elif ratio < SHRINK_RATIO and self.depth > self.floor:
            self.depth -= 1
        if self.depth != before:
            self._gauge().set(float(self.depth))
            log.info(
                "prefetch depth %d -> %d (data_fetch p95 %.4fs vs "
                "device_step p95 %.4fs)",
                before,
                self.depth,
                fetch_p95,
                step_p95,
            )
        return self.depth


_END = object()


def device_prefetch(
    it: Iterator,
    sharding,
    *,
    depth: int = 2,
    depth_max: int = 0,
    local_batches: bool = False,
    max_skips: int = 0,
    fault_hooks: bool = True,
    registry=None,
) -> Iterator:
    """``fault_hooks=False`` (the eval path) keeps this pipeline out of
    the injection engine's fetch-index space, so ``slow@N``/``badbatch@N``
    target train fetch N deterministically even when eval interleaves.

    ``depth_max > depth`` enables the adaptive controller (see
    :class:`DepthController`); the queue is refilled to the live depth
    before every yield, so a depth change takes effect within one step.
    """
    queue = collections.deque()
    put_fn = put_local_batch if local_batches else put_batch
    skipped = 0
    reg = (
        registry
        if registry is not None
        else _telemetry_registry.default_registry()
    )
    fetched_ctr = reg.counter("data/batches_fetched")
    skipped_ctr = reg.counter("data/batches_skipped")
    # The controller always reads the DEFAULT registry: the span
    # histograms it compares (span/data_fetch, span/device_step) are
    # recorded through the default tracer regardless of ``registry``,
    # so forwarding a custom registry would silently disarm adaptation.
    ctl = DepthController(depth, depth_max)
    # Background pipelines (worker pools) do the host work on their own
    # threads — popping their queue is WAIT, not WORK. Plain iterators
    # do the work right here in next(it).
    background = bool(getattr(it, "background", False))

    def finish(batch):
        """Fault hooks + host→device placement for one raw batch."""
        if fault_hooks:
            eng = _faults.active()
            if eng is not None:
                batch = eng.batch_hook(batch)
        return put_fn(batch, sharding)

    def fetch():
        """Next device-resident batch, or _END. With ``max_skips > 0`` a
        batch that fails the host→device put is poisoned: skip it (and
        count), bounded by the budget. With the default ``max_skips=0``
        the original exception propagates untouched — a deterministic
        pipeline bug must surface as itself, not as 'corrupt input'."""
        nonlocal skipped
        while True:
            from_source = True  # a source-iterator bug is never "corrupt
            #   input": it propagates untouched regardless of the budget
            try:
                if background:
                    with _trace_span("data_wait"):
                        batch = next(it)
                    from_source = False
                    with _trace_span("data_work"):
                        out = finish(batch)
                else:
                    with _trace_span("data_work"):
                        batch = next(it)
                        from_source = False
                        out = finish(batch)
            except StopIteration:
                return _END
            except Exception as e:
                if from_source or max_skips <= 0:
                    raise
                skipped += 1
                skipped_ctr.inc()
                if skipped > max_skips:
                    raise RuntimeError(
                        f"poisoned input batch ({skipped} bad, budget "
                        f"max_skipped_batches={max_skips} exhausted): {e}"
                    ) from e
                log.warning(
                    "skipping poisoned input batch %d/%d: %s",
                    skipped,
                    max_skips,
                    e,
                )
                continue
            fetched_ctr.inc()
            return out

    done = False
    try:
        while not done and len(queue) < ctl.depth:
            batch = fetch()
            if batch is _END:
                done = True
            else:
                queue.append(batch)
        while queue:
            out = queue.popleft()
            ctl.observe()
            while not done and len(queue) < ctl.depth:
                batch = fetch()
                if batch is _END:
                    done = True
                else:
                    queue.append(batch)
            yield out
    finally:
        # Unwind a background pipeline promptly (worker threads, reader
        # threads) when the consumer stops early — preemption, eval
        # truncation, an exception in the loop.
        close = getattr(it, "close", None)
        if close is not None:
            with contextlib.suppress(Exception):
                close()
