"""In-memory datasets: deterministic shuffling train iterator + eval batches.

Covers MNIST/CIFAR-scale data (the reference loaded these fully into memory
via ``tf.keras.datasets`` too). The iterator is stateless-resumable: batch
order is a pure function of (seed, epoch), so resuming from step N
reproduces the exact batch sequence the un-interrupted run would have seen
— stronger than the reference's stateful tf.data shuffle buffer.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Mapping

import numpy as np


@dataclasses.dataclass
class InMemoryDataset:
    """A dict of equally-long numpy arrays (e.g. {'image': …, 'label': …})."""

    arrays: Mapping[str, np.ndarray]

    def __post_init__(self):
        sizes = {k: len(v) for k, v in self.arrays.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(f"ragged dataset: {sizes}")

    @property
    def size(self) -> int:
        return len(next(iter(self.arrays.values())))


def train_iterator(
    ds: InMemoryDataset,
    batch_size: int,
    *,
    seed: int = 0,
    start_step: int = 0,
    augment=None,
) -> Iterator[dict[str, np.ndarray]]:
    """Infinite shuffled batches; order is a pure function of (seed, epoch)."""
    n = ds.size
    if batch_size > n:
        raise ValueError(f"batch {batch_size} > dataset {n}")
    steps_per_epoch = n // batch_size
    step = start_step
    while True:
        epoch = step // steps_per_epoch
        order = np.random.default_rng(seed + epoch).permutation(n)
        while step // steps_per_epoch == epoch:
            i = (step % steps_per_epoch) * batch_size
            idx = order[i : i + batch_size]
            batch = {k: v[idx] for k, v in ds.arrays.items()}
            if augment is not None:
                batch = augment(batch, np.random.default_rng((seed, step)))
            yield batch
            step += 1


def eval_batches(
    ds: InMemoryDataset, batch_size: int, *, drop_remainder: bool = False
) -> Iterator[dict[str, np.ndarray]]:
    """One sequential pass; final partial batch is padded with weight=0.

    Padding (instead of a ragged final batch) keeps eval shapes static so
    the jitted eval step compiles exactly once (SURVEY.md: no dynamic
    shapes under jit).
    """
    n = ds.size
    for i in range(0, n, batch_size):
        batch = {k: v[i : i + batch_size] for k, v in ds.arrays.items()}
        actual = len(next(iter(batch.values())))
        if actual < batch_size:
            if drop_remainder:
                return
            pad = batch_size - actual
            batch = {
                k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
                for k, v in batch.items()
            }
            mask = np.concatenate([np.ones(actual), np.zeros(pad)])
        else:
            mask = np.ones(actual)
        batch["mask"] = mask.astype(np.float32)
        yield batch
