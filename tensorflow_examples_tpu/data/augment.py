"""Host-side image augmentation (numpy, vectorized over the batch).

Replaces the reference's per-example ``tf.data.map(augment)`` stages
(SURVEY.md §3(4)). Runs on host CPU threads overlapped with the device
step via the prefetch queue; everything is driven by the iterator's
per-step ``np.random.Generator``, so augmentation is deterministic given
(seed, step) and exactly reproducible across resume — which a stateful
tf.data shuffle/augment pipeline was not.
"""

from __future__ import annotations

import numpy as np

# (mean bytes, std bytes) -> [256, C] f32 lookup table. Normalizing a
# uint8 batch is a gather through this table — one pass over the batch,
# no per-image Python and no intermediate f32 copy of the /255 step.
_NORM_LUT_CACHE: dict = {}


def normalize_lut(mean: np.ndarray, std: np.ndarray) -> np.ndarray:
    """The 256-entry per-channel normalization table for uint8 images.

    Entry [v, c] is computed with the exact f32 expression the direct
    path uses — ``(v.astype(f32) / 255.0 - mean) / std`` — so the
    gathered output is BYTE-identical to the unbatched formula."""
    key = (mean.astype(np.float32).tobytes(), std.astype(np.float32).tobytes())
    lut = _NORM_LUT_CACHE.get(key)
    if lut is None:
        vals = np.arange(256, dtype=np.float32)[:, None] / 255.0
        lut = ((vals - mean.astype(np.float32)) / std.astype(np.float32))
        lut = np.ascontiguousarray(lut.astype(np.float32))
        _NORM_LUT_CACHE[key] = lut
    return lut


def normalize_images(
    images: np.ndarray, mean: np.ndarray, std: np.ndarray
) -> np.ndarray:
    """Batched ``(x/255 - mean) / std`` with mean/std broadcast ONCE.

    uint8 batches go through the per-channel lookup table (no f32
    intermediate); float batches take the direct broadcast expression.
    Both are byte-identical to the per-image loop they replace
    (ISSUE 6 satellite)."""
    if images.dtype == np.uint8:
        lut = normalize_lut(mean, std)
        c = images.shape[-1]
        return np.ascontiguousarray(
            lut[images, np.arange(c, dtype=np.intp)]
        )
    return (
        (images.astype(np.float32) / 255.0 - mean.astype(np.float32))
        / std.astype(np.float32)
    ).astype(np.float32)


def flip_images(
    images: np.ndarray, flips: np.ndarray, *, copy: bool = True
) -> np.ndarray:
    """Horizontal-flip the selected rows of a batch in ONE vectorized
    assignment (no per-image loop). The single flip implementation —
    ``_crop_flip`` reuses it with ``copy=False`` on its freshly
    gathered batch."""
    out = images.copy() if copy else images
    fl = flips.astype(bool)
    out[fl] = out[fl, :, ::-1]
    return np.ascontiguousarray(out)


def _crop_flip(
    images: np.ndarray, ys: np.ndarray, xs: np.ndarray, flips: np.ndarray, pad: int
) -> np.ndarray:
    """Reflect-pad + per-example crop/h-flip with precomputed offsets.

    One advanced-indexing gather per batch; shared by the float path and
    the uint8 fallback so the two cannot drift.
    """
    b, h, w, _ = images.shape
    padded = np.pad(
        images, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="reflect"
    )
    row_idx = ys[:, None] + np.arange(h)[None, :]
    col_idx = xs[:, None] + np.arange(w)[None, :]
    out = padded[
        np.arange(b)[:, None, None], row_idx[:, :, None], col_idx[:, None, :]
    ]
    return flip_images(out, flips, copy=False)


def random_crop_flip(
    images: np.ndarray, rng: np.random.Generator, *, pad: int = 4
) -> np.ndarray:
    """CIFAR-standard augmentation: reflect-pad, random crop, random h-flip."""
    b = len(images)
    ys = rng.integers(0, 2 * pad + 1, size=b)
    xs = rng.integers(0, 2 * pad + 1, size=b)
    flips = rng.random(b) < 0.5
    return _crop_flip(images, ys, xs, flips, pad)


def cifar_augment(batch: dict, rng: np.random.Generator) -> dict:
    """Crop/flip a CIFAR batch; fused native path for uint8 batches.

    float32 batches (synthetic / pre-normalized) take the numpy path.
    uint8 batches (load_cifar10(normalized=False)) run pad+crop+flip+
    normalize in one threaded C++ call (native/fastdata.cpp), with an
    equivalent numpy fallback — determinism is identical: the rng draw
    order (ys, xs, flips) is the same on every path.
    """
    out = dict(batch)
    img = batch["image"]
    if img.dtype != np.uint8:
        out["image"] = random_crop_flip(img, rng, pad=4)
        return out

    from tensorflow_examples_tpu import native
    from tensorflow_examples_tpu.data.sources import CIFAR10_MEAN, CIFAR10_STD

    b = len(img)
    pad = 4
    ys = rng.integers(0, 2 * pad + 1, size=b)
    xs = rng.integers(0, 2 * pad + 1, size=b)
    flips = (rng.random(b) < 0.5).astype(np.uint8)
    fast = native.crop_flip_normalize(
        img, ys, xs, flips, CIFAR10_MEAN, CIFAR10_STD, pad=pad
    )
    if fast is not None:
        out["image"] = fast
        return out
    crop = _crop_flip(img.astype(np.float32) / 255.0, ys, xs, flips, pad=pad)
    out["image"] = ((crop - CIFAR10_MEAN) / CIFAR10_STD).astype(np.float32)
    return out
