"""Host-side image augmentation (numpy, vectorized over the batch).

Replaces the reference's per-example ``tf.data.map(augment)`` stages
(SURVEY.md §3(4)). Runs on host CPU threads overlapped with the device
step via the prefetch queue; everything is driven by the iterator's
per-step ``np.random.Generator``, so augmentation is deterministic given
(seed, step) and exactly reproducible across resume — which a stateful
tf.data shuffle/augment pipeline was not.
"""

from __future__ import annotations

import numpy as np


def random_crop_flip(
    images: np.ndarray, rng: np.random.Generator, *, pad: int = 4
) -> np.ndarray:
    """CIFAR-standard augmentation: reflect-pad, random crop, random h-flip.

    images: [B, H, W, C] float. Vectorized: one gather per batch, no
    per-image Python loop.
    """
    b, h, w, c = images.shape
    padded = np.pad(
        images, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="reflect"
    )
    ys = rng.integers(0, 2 * pad + 1, size=b)
    xs = rng.integers(0, 2 * pad + 1, size=b)
    # Gather crops via advanced indexing: rows [B, H, 1], cols [B, 1, W].
    row_idx = ys[:, None] + np.arange(h)[None, :]
    col_idx = xs[:, None] + np.arange(w)[None, :]
    out = padded[
        np.arange(b)[:, None, None], row_idx[:, :, None], col_idx[:, None, :]
    ]
    flip = rng.random(b) < 0.5
    out[flip] = out[flip, :, ::-1]
    return np.ascontiguousarray(out)


def cifar_augment(batch: dict, rng: np.random.Generator) -> dict:
    out = dict(batch)
    out["image"] = random_crop_flip(batch["image"], rng, pad=4)
    return out
