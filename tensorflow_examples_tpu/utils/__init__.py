"""Aux utilities: failure detection, crash diagnostics (SURVEY.md §5c)."""

from tensorflow_examples_tpu.utils.diagnostics import (
    Watchdog,
    install_crash_handlers,
)

__all__ = ["Watchdog", "install_crash_handlers"]
