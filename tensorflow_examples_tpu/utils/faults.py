"""Deterministic fault injection + bounded-retry IO (ISSUE 1 resilience).

At pod scale (v5e-8 .. v5p-64) preemptions, flaky input storage, and
numeric blow-ups are routine; every recovery path in the trainer must be
exercisable in CI on CPU. This module is the injection engine the
training loop, prefetch pipeline, and file readers consult at their
instrumentation points, plus the ``retry_io`` wrapper those readers run
their filesystem operations through.

Fault specs are comma-separated ``kind@arg`` tokens, deterministic by
construction (keyed on step / fetch index, never wall clock):

  ``sigterm@N``    deliver SIGTERM to this process right before train
                   step N runs (the loop finishes the in-flight chunk,
                   checkpoints, and exits cleanly with code 0).
  ``nan@N``        poison the float leaves of step N's batch with NaN.
  ``nan@N:M``      ... for M consecutive steps starting at N.
  ``slow@N:S``     sleep S seconds while fetching train-pipeline batch
                   number N (0-based fetch index — eval prefetch opts
                   out of the hooks, so the numbering is stable even
                   when eval interleaves; trips the watchdog). With
                   ``steps_per_launch=k > 1`` the pipeline fetches
                   k-batch BUNDLES, so index N is the Nth bundle
                   (covering steps N*k .. N*k+k-1), not the Nth host
                   batch. Same indexing for ``badbatch@N``.
  ``ioerr@K``      the first K filesystem operations routed through
                   ``retry_io`` raise OSError (exercises retry/backoff).
  ``badbatch@N``   corrupt host batch number N so host->device transfer
                   fails (exercises the poisoned-batch skip counter).

Each step/index-keyed fault fires ONCE: a rollback that replays step N
does not re-poison it, which models transient faults and keeps the
rollback tests convergent.

Activation: ``install(spec)`` in-process (the ``faults`` pytest fixture)
or the ``TPU_FAULT_INJECT`` environment variable (read lazily on first
``active()`` call — how ``tools/fault_inject.py`` arms a child CLI).
When no plan is armed every hook site is a single global-read + None
check.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import time
from typing import Callable

log = logging.getLogger(__name__)

ENV_VAR = "TPU_FAULT_INJECT"


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    sigterm_at: frozenset[int] = frozenset()
    nan_at: frozenset[int] = frozenset()  # expanded: nan@N:M -> {N..N+M-1}
    slow_at: dict[int, float] = dataclasses.field(default_factory=dict)
    io_errors: int = 0
    bad_batch_at: frozenset[int] = frozenset()


def parse_spec(spec: str) -> FaultPlan:
    """Parse ``"sigterm@10,nan@5:2,slow@3:8,ioerr@2,badbatch@1"``."""
    kinds = ("sigterm", "nan", "slow", "ioerr", "badbatch")
    sigterm, nan, slow, bad = set(), set(), {}, set()
    io_errors = 0
    for token in filter(None, (t.strip() for t in spec.split(","))):
        kind, _, arg = token.partition("@")
        if kind not in kinds:
            raise ValueError(
                f"unknown fault kind {kind!r} (one of {'/'.join(kinds)})"
            )
        if not arg:
            raise ValueError(f"fault token {token!r} needs '@<arg>'")
        head, _, tail = arg.partition(":")
        try:  # only the numeric conversions — routing stays outside
            if kind == "sigterm":
                sigterm.add(int(head))
            elif kind == "nan":
                start, count = int(head), int(tail) if tail else 1
                nan.update(range(start, start + count))
            elif kind == "slow":
                slow[int(head)] = float(tail) if tail else 5.0
            elif kind == "ioerr":
                io_errors += int(head)
            else:
                bad.add(int(head))
        except ValueError as e:
            raise ValueError(f"malformed fault token {token!r}: {e}") from None
    return FaultPlan(
        sigterm_at=frozenset(sigterm),
        nan_at=frozenset(nan),
        slow_at=slow,
        io_errors=io_errors,
        bad_batch_at=frozenset(bad),
    )


class _Unconvertible:
    """A leaf ``jnp.asarray`` cannot convert — the poisoned-batch payload."""

    def __repr__(self):  # pragma: no cover - repr only surfaces in logs
        return "<injected-corrupt-leaf>"


class Engine:
    """Runtime state for one armed FaultPlan (counters, fired-once sets)."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._fetch_idx = 0
        self._io_fails_left = plan.io_errors
        self._fired_sigterm: set[int] = set()
        self._fired_nan: set[int] = set()
        self._fired_bad: set[int] = set()
        self._fired_slow: set[int] = set()

    # ----------------------------------------------------- loop-side hooks

    def step_hook(self, first_step: int, k: int = 1) -> None:
        """Called at the top of each train chunk covering steps
        ``[first_step, first_step + k)``."""
        for s in range(first_step, first_step + k):
            if s in self.plan.sigterm_at and s not in self._fired_sigterm:
                self._fired_sigterm.add(s)
                log.warning("FAULT: delivering SIGTERM before step %d", s)
                os.kill(os.getpid(), signal.SIGTERM)

    def nan_hook(self, first_step: int, k: int, batch):
        """Poison the float leaves of any planned step in the chunk."""
        hits = [
            s in self.plan.nan_at and s not in self._fired_nan
            for s in range(first_step, first_step + k)
        ]
        if not any(hits):
            return batch
        for i, hit in enumerate(hits):
            if hit:
                self._fired_nan.add(first_step + i)
        import jax.numpy as jnp
        import numpy as np

        poisoned = [False]

        def poison(x):
            if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
                return x
            poisoned[0] = True
            if k == 1:
                return x * np.float32(np.nan)
            mult = np.ones((k,) + (1,) * (np.ndim(x) - 1), np.float32)
            for i, hit in enumerate(hits):
                if hit:
                    mult[i] = np.nan
            return x * mult

        import jax

        out = jax.tree.map(poison, batch)
        if not poisoned[0]:
            raise RuntimeError(
                "nan fault requested for step "
                f"{[first_step + i for i, h in enumerate(hits) if h]} but the "
                "batch has no float leaves to poison (token-only workloads "
                "cannot carry a NaN input)"
            )
        log.warning(
            "FAULT: poisoned batch floats with NaN for steps %s",
            [first_step + i for i, h in enumerate(hits) if h],
        )
        return out

    # ------------------------------------------------------ data-side hooks

    def batch_hook(self, batch):
        """Called once per host batch fetch (prefetch pipeline), BEFORE the
        host->device transfer. May sleep (slow) or corrupt (badbatch)."""
        idx = self._fetch_idx
        self._fetch_idx += 1
        s = self.plan.slow_at.get(idx)
        if s is not None and idx not in self._fired_slow:
            self._fired_slow.add(idx)
            log.warning("FAULT: stalling batch fetch %d for %.1fs", idx, s)
            time.sleep(s)
        if idx in self.plan.bad_batch_at and idx not in self._fired_bad:
            self._fired_bad.add(idx)
            log.warning("FAULT: corrupting batch fetch %d", idx)
            return {k: _Unconvertible() for k in batch}
        return batch

    def io_check(self, what: str) -> None:
        """Called per filesystem attempt inside ``retry_io``."""
        if self._io_fails_left > 0:
            self._io_fails_left -= 1
            raise OSError(
                f"injected io error for {what} "
                f"({self._io_fails_left} more to come)"
            )


# ------------------------------------------------------- global activation

_engine: Engine | None = None
_env_checked = False


def install(spec_or_plan: str | FaultPlan) -> Engine:
    """Arm a fault plan in-process (tests use the ``faults`` fixture)."""
    global _engine, _env_checked
    plan = (
        parse_spec(spec_or_plan)
        if isinstance(spec_or_plan, str)
        else spec_or_plan
    )
    _engine = Engine(plan)
    _env_checked = True
    return _engine


def clear() -> None:
    global _engine, _env_checked
    _engine = None
    _env_checked = False


def active() -> Engine | None:
    """The armed engine, lazily initialized from $TPU_FAULT_INJECT."""
    global _engine, _env_checked
    if _engine is None and not _env_checked:
        _env_checked = True
        spec = os.environ.get(ENV_VAR, "")
        if spec:
            _engine = Engine(parse_spec(spec))
            log.info("fault injection armed from $%s=%s", ENV_VAR, spec)
    return _engine


# ------------------------------------------------------------ IO retries

# Defaults; overridden from TrainConfig (io_retries / io_backoff_secs) by
# train/cli._setup via configure_io_retry.
_io_retry = {"attempts": 3, "backoff": 0.25}


def configure_io_retry(attempts: int, backoff_secs: float) -> None:
    _io_retry["attempts"] = max(int(attempts), 0)
    _io_retry["backoff"] = max(float(backoff_secs), 0.0)


def retry_io(
    fn: Callable,
    what: str,
    *,
    attempts: int | None = None,
    backoff_secs: float | None = None,
):
    """Run a filesystem operation with bounded retry + exponential backoff.

    Retries only OSError (flaky NFS/GCS-fuse reads, the pod-scale reality);
    data errors (ValueError etc.) propagate immediately. ``attempts`` is
    the number of RETRIES after the first try. An armed fault engine's
    ``io_check`` runs before each attempt so injected IO faults exercise
    exactly this path.
    """
    import gzip

    attempts = _io_retry["attempts"] if attempts is None else attempts
    backoff = _io_retry["backoff"] if backoff_secs is None else backoff_secs
    for attempt in range(attempts + 1):
        try:
            eng = active()
            if eng is not None:
                eng.io_check(what)
            return fn()
        except OSError as e:
            if isinstance(e, gzip.BadGzipFile):
                raise  # corrupt data, not a transient store fault
            if attempt >= attempts:
                raise
            # Surface the formerly write-only retry in the metrics
            # registry (ISSUE 2): flaky-store churn belongs in the run
            # report, not just interleaved WARNING lines.
            from tensorflow_examples_tpu.telemetry.registry import (
                default_registry,
            )

            default_registry().counter("io/retries").inc()
            delay = backoff * (2**attempt)
            log.warning(
                "io error on %s (attempt %d/%d), retrying in %.2fs: %s",
                what,
                attempt + 1,
                attempts + 1,
                delay,
                e,
            )
            time.sleep(delay)
