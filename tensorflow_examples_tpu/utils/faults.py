"""Deterministic fault injection + bounded-retry IO (ISSUE 1 resilience).

At pod scale (v5e-8 .. v5p-64) preemptions, flaky input storage, and
numeric blow-ups are routine; every recovery path in the trainer must be
exercisable in CI on CPU. This module is the injection engine the
training loop, prefetch pipeline, and file readers consult at their
instrumentation points, plus the ``retry_io`` wrapper those readers run
their filesystem operations through.

Fault specs are comma-separated ``kind@arg`` tokens, deterministic by
construction (keyed on step / fetch index, never wall clock):

  ``sigterm@N``    deliver SIGTERM to this process right before train
                   step N runs (the loop finishes the in-flight chunk,
                   checkpoints, and exits cleanly with code 0).
  ``nan@N``        poison the float leaves of step N's batch with NaN.
  ``nan@N:M``      ... for M consecutive steps starting at N.
  ``slow@N:S``     sleep S seconds while fetching train-pipeline batch
                   number N (0-based fetch index — eval prefetch opts
                   out of the hooks, so the numbering is stable even
                   when eval interleaves; trips the watchdog). With
                   ``steps_per_launch=k > 1`` the pipeline fetches
                   k-batch BUNDLES, so index N is the Nth bundle
                   (covering steps N*k .. N*k+k-1), not the Nth host
                   batch. Same indexing for ``badbatch@N``.
  ``ioerr@K``      the first K filesystem operations routed through
                   ``retry_io`` raise OSError (exercises retry/backoff).
  ``badbatch@N``   corrupt host batch number N so host->device transfer
                   fails (exercises the poisoned-batch skip counter).

Each step/index-keyed fault fires ONCE: a rollback that replays step N
does not re-poison it, which models transient faults and keeps the
rollback tests convergent.

Activation: ``install(spec)`` in-process (the ``faults`` pytest fixture)
or the ``TPU_FAULT_INJECT`` environment variable (read lazily on first
``active()`` call — how ``tools/fault_inject.py`` arms a child CLI).
When no plan is armed every hook site is a single global-read + None
check.

**Serving faults (ISSUE 10).** The serving tier has its own plan — the
failure unit is a *replica*, not a train step, so serve specs are
``kind@replica:arg`` tokens, deterministic by construction (keyed on
each replica's own decode-step / request / probe counters, never wall
clock):

  ``crash@R:N``      replica R dies before its Nth decode step (0-based):
                     its frontend's in-flight connections are RESET (the
                     router sees a transport failure, exactly like a
                     killed process) and the batcher loop aborts with
                     :class:`InjectedCrash`. Needs a registered crash
                     callback (``register_serve_crash``) — the chaos
                     harness's in-proc replicas register their ``kill``.
  ``slowrep@R:S``    every decode step on replica R sleeps S seconds
                     (a straggling replica: hedged dispatch territory).
  ``transport@R:K``  the first K POST requests replica R's frontend
                     receives are dropped with no response bytes (the
                     client sees a reset — the router's in-flight
                     failover path).
  ``kvexhaust@R:N``  replica R's Nth decode step raises a forced
                     ``BlockExhausted`` naming every active slot (the
                     paged pool's loud capacity path, without needing a
                     pool actually sized to starve).
  ``badhealth@R:K``  the first K ``GET /health`` responses from replica
                     R are non-JSON garbage bytes (the probe loop must
                     mark the replica unhealthy, not crash).

Hook sites: ``InferenceEngine.decode`` (``decode_step``),
``ServingFrontend`` POST handling (``transport_fault``) and ``/health``
(``health_fault``). Armed via ``serve_install(spec)`` in-process or the
``TPU_SERVE_FAULT_INJECT`` env var (``tools/fault_inject.py --serve``);
like the train side, an unarmed hook is one global read.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import threading
import time
from typing import Callable

log = logging.getLogger(__name__)

ENV_VAR = "TPU_FAULT_INJECT"


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    sigterm_at: frozenset[int] = frozenset()
    nan_at: frozenset[int] = frozenset()  # expanded: nan@N:M -> {N..N+M-1}
    slow_at: dict[int, float] = dataclasses.field(default_factory=dict)
    io_errors: int = 0
    bad_batch_at: frozenset[int] = frozenset()


def parse_spec(spec: str) -> FaultPlan:
    """Parse ``"sigterm@10,nan@5:2,slow@3:8,ioerr@2,badbatch@1"``."""
    kinds = ("sigterm", "nan", "slow", "ioerr", "badbatch")
    sigterm, nan, slow, bad = set(), set(), {}, set()
    io_errors = 0
    for token in filter(None, (t.strip() for t in spec.split(","))):
        kind, _, arg = token.partition("@")
        if kind not in kinds:
            raise ValueError(
                f"unknown fault kind {kind!r} (one of {'/'.join(kinds)})"
            )
        if not arg:
            raise ValueError(f"fault token {token!r} needs '@<arg>'")
        head, _, tail = arg.partition(":")
        try:  # only the numeric conversions — routing stays outside
            if kind == "sigterm":
                sigterm.add(int(head))
            elif kind == "nan":
                start, count = int(head), int(tail) if tail else 1
                nan.update(range(start, start + count))
            elif kind == "slow":
                slow[int(head)] = float(tail) if tail else 5.0
            elif kind == "ioerr":
                io_errors += int(head)
            else:
                bad.add(int(head))
        except ValueError as e:
            raise ValueError(f"malformed fault token {token!r}: {e}") from None
    return FaultPlan(
        sigterm_at=frozenset(sigterm),
        nan_at=frozenset(nan),
        slow_at=slow,
        io_errors=io_errors,
        bad_batch_at=frozenset(bad),
    )


class _Unconvertible:
    """A leaf ``jnp.asarray`` cannot convert — the poisoned-batch payload."""

    def __repr__(self):  # pragma: no cover - repr only surfaces in logs
        return "<injected-corrupt-leaf>"


class Engine:
    """Runtime state for one armed FaultPlan (counters, fired-once sets)."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._fetch_idx = 0
        self._io_fails_left = plan.io_errors
        self._fired_sigterm: set[int] = set()
        self._fired_nan: set[int] = set()
        self._fired_bad: set[int] = set()
        self._fired_slow: set[int] = set()

    # ----------------------------------------------------- loop-side hooks

    def step_hook(self, first_step: int, k: int = 1) -> None:
        """Called at the top of each train chunk covering steps
        ``[first_step, first_step + k)``."""
        for s in range(first_step, first_step + k):
            if s in self.plan.sigterm_at and s not in self._fired_sigterm:
                self._fired_sigterm.add(s)
                log.warning("FAULT: delivering SIGTERM before step %d", s)
                os.kill(os.getpid(), signal.SIGTERM)

    def nan_hook(self, first_step: int, k: int, batch):
        """Poison the float leaves of any planned step in the chunk."""
        hits = [
            s in self.plan.nan_at and s not in self._fired_nan
            for s in range(first_step, first_step + k)
        ]
        if not any(hits):
            return batch
        for i, hit in enumerate(hits):
            if hit:
                self._fired_nan.add(first_step + i)
        import jax.numpy as jnp
        import numpy as np

        poisoned = [False]

        def poison(x):
            if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
                return x
            poisoned[0] = True
            if k == 1:
                return x * np.float32(np.nan)
            mult = np.ones((k,) + (1,) * (np.ndim(x) - 1), np.float32)
            for i, hit in enumerate(hits):
                if hit:
                    mult[i] = np.nan
            return x * mult

        import jax

        out = jax.tree.map(poison, batch)
        if not poisoned[0]:
            raise RuntimeError(
                "nan fault requested for step "
                f"{[first_step + i for i, h in enumerate(hits) if h]} but the "
                "batch has no float leaves to poison (token-only workloads "
                "cannot carry a NaN input)"
            )
        log.warning(
            "FAULT: poisoned batch floats with NaN for steps %s",
            [first_step + i for i, h in enumerate(hits) if h],
        )
        return out

    # ------------------------------------------------------ data-side hooks

    def batch_hook(self, batch):
        """Called once per host batch fetch (prefetch pipeline), BEFORE the
        host->device transfer. May sleep (slow) or corrupt (badbatch)."""
        idx = self._fetch_idx
        self._fetch_idx += 1
        s = self.plan.slow_at.get(idx)
        if s is not None and idx not in self._fired_slow:
            self._fired_slow.add(idx)
            log.warning("FAULT: stalling batch fetch %d for %.1fs", idx, s)
            time.sleep(s)
        if idx in self.plan.bad_batch_at and idx not in self._fired_bad:
            self._fired_bad.add(idx)
            log.warning("FAULT: corrupting batch fetch %d", idx)
            return {k: _Unconvertible() for k in batch}
        return batch

    def io_check(self, what: str) -> None:
        """Called per filesystem attempt inside ``retry_io``."""
        if self._io_fails_left > 0:
            self._io_fails_left -= 1
            raise OSError(
                f"injected io error for {what} "
                f"({self._io_fails_left} more to come)"
            )


# ---------------------------------------------------------- serving side

SERVE_ENV_VAR = "TPU_SERVE_FAULT_INJECT"

SERVE_KINDS = ("crash", "slowrep", "transport", "kvexhaust", "badhealth",
               "killrouter")


class InjectedCrash(RuntimeError):
    """Raised inside a replica's decode step by a ``crash@R:N`` fault:
    the serving loop treats it like any fatal step error (fails the
    in-flight batch), but by then the replica's transport is already
    dead — clients observe a reset, not an HTTP status."""


@dataclasses.dataclass(frozen=True)
class ServeFaultPlan:
    crash_at: dict[int, int] = dataclasses.field(default_factory=dict)
    slow_replica: dict[int, float] = dataclasses.field(default_factory=dict)
    transport_drop: dict[int, int] = dataclasses.field(default_factory=dict)
    kvexhaust_at: dict[int, int] = dataclasses.field(default_factory=dict)
    bad_health: dict[int, int] = dataclasses.field(default_factory=dict)
    # ISSUE 16: kill the ACTIVE router (hard-abort its frontend, PR-9
    # abort() semantics) after this many accepted dispatches. No
    # replica index — the fault targets whichever router is currently
    # dispatching, which is by definition the active one.
    kill_router_at: int | None = None


def parse_serve_spec(spec: str) -> ServeFaultPlan:
    """Parse ``"crash@1:4,slowrep@0:0.2,transport@2:1,badhealth@0:3"``
    (``kind@replica:arg`` tokens, comma separated). The one
    router-side kind is ``killrouter@T`` — no replica index, just the
    accepted-GENERATE-dispatch count T after which the active router's
    frontend is hard-aborted (classify/score traffic never advances
    T)."""
    crash: dict[int, int] = {}
    slow: dict[int, float] = {}
    transport: dict[int, int] = {}
    kvex: dict[int, int] = {}
    badhealth: dict[int, int] = {}
    kill_router_at: int | None = None
    for token in filter(None, (t.strip() for t in spec.split(","))):
        kind, _, arg = token.partition("@")
        if kind not in SERVE_KINDS:
            raise ValueError(
                f"unknown serve fault kind {kind!r} "
                f"(one of {'/'.join(SERVE_KINDS)})"
            )
        if kind == "killrouter":
            try:
                kill_router_at = int(arg)
            except ValueError:
                raise ValueError(
                    f"malformed serve fault token {token!r}: "
                    "killrouter needs '@<dispatch count>'"
                ) from None
            continue
        head, sep, tail = arg.partition(":")
        if not head or not sep or not tail:
            raise ValueError(
                f"serve fault token {token!r} needs '@<replica>:<arg>'"
            )
        try:
            replica = int(head)
            if kind == "crash":
                crash[replica] = int(tail)
            elif kind == "slowrep":
                slow[replica] = float(tail)
            elif kind == "transport":
                transport[replica] = int(tail)
            elif kind == "kvexhaust":
                kvex[replica] = int(tail)
            else:
                badhealth[replica] = int(tail)
        except ValueError as e:
            raise ValueError(
                f"malformed serve fault token {token!r}: {e}"
            ) from None
    return ServeFaultPlan(
        crash_at=crash, slow_replica=slow, transport_drop=transport,
        kvexhaust_at=kvex, bad_health=badhealth,
        kill_router_at=kill_router_at,
    )


class ServeEngine:
    """Runtime state for one armed ServeFaultPlan (per-replica counters,
    fired-once sets). Every hook is lock-guarded: decode hooks run on
    each replica's batcher thread, transport/health hooks on frontend
    handler threads."""

    def __init__(self, plan: ServeFaultPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self._decode_steps: dict[int, int] = {}
        self._transport_left = dict(plan.transport_drop)
        self._health_left = dict(plan.bad_health)
        self._fired_crash: set[int] = set()
        self._fired_kvex: set[int] = set()
        self._router_dispatches = 0
        self._fired_killrouter = False
        self.fired: list[tuple[str, int, int]] = []  # (kind, replica, idx)

    # ------------------------------------------------------ decode hooks

    def decode_step(self, replica: int, slots) -> None:
        """Called at the top of every decode step; may sleep (slowrep),
        raise a forced BlockExhausted (kvexhaust) or kill the replica
        and raise InjectedCrash (crash)."""
        with self._lock:
            step = self._decode_steps.get(replica, 0)
            self._decode_steps[replica] = step + 1
            delay = self.plan.slow_replica.get(replica)
            crash = (
                self.plan.crash_at.get(replica) == step
                and replica not in self._fired_crash
            )
            kvex = (
                self.plan.kvexhaust_at.get(replica) == step
                and replica not in self._fired_kvex
            )
            if crash:
                self._fired_crash.add(replica)
                self.fired.append(("crash", replica, step))
            if kvex:
                self._fired_kvex.add(replica)
                self.fired.append(("kvexhaust", replica, step))
            kill = _serve_crash_cbs.get(replica)
        if delay:
            log.warning(
                "SERVE FAULT: replica %d decode step %d sleeping %.2fs",
                replica, step, delay,
            )
            time.sleep(delay)
        if kvex:
            from tensorflow_examples_tpu.serving.paged_kv import (
                BlockExhausted,
            )

            log.warning(
                "SERVE FAULT: forced BlockExhausted on replica %d "
                "decode step %d (slots %s)", replica, step, list(slots),
            )
            raise BlockExhausted(
                f"injected KV block exhaustion on replica {replica}",
                slots=tuple(slots),
            )
        if crash:
            log.warning(
                "SERVE FAULT: crashing replica %d before decode step %d",
                replica, step,
            )
            if kill is not None:
                kill()
            raise InjectedCrash(f"injected crash of replica {replica}")

    # --------------------------------------------------- frontend hooks

    def transport_fault(self, replica: int) -> bool:
        """True -> the frontend drops this request with no response
        bytes (client-observable transport failure)."""
        with self._lock:
            left = self._transport_left.get(replica, 0)
            if left <= 0:
                return False
            self._transport_left[replica] = left - 1
            self.fired.append(("transport", replica, left))
        log.warning(
            "SERVE FAULT: dropping request on replica %d at the "
            "transport level (%d more to come)", replica, left - 1,
        )
        return True

    def health_fault(self, replica: int) -> bool:
        """True -> /health answers non-JSON garbage this time."""
        with self._lock:
            left = self._health_left.get(replica, 0)
            if left <= 0:
                return False
            self._health_left[replica] = left - 1
            self.fired.append(("badhealth", replica, left))
        return True

    # ----------------------------------------------------- router hooks

    def router_dispatch(self) -> bool:
        """Called by ``Router.handle`` once per accepted generate
        dispatch (after the intent is journaled). Counts dispatches
        across whichever router is currently active; on the
        ``kill_router_at``-th call it fires the registered router-kill
        callback and returns True — the firing dispatch returns an
        error without reaching the fleet, leaving its intent
        incomplete in the journal for the successor to replay."""
        with self._lock:
            at = self.plan.kill_router_at
            if at is None or self._fired_killrouter:
                return False
            self._router_dispatches += 1
            n = self._router_dispatches
            if n < at:
                return False
            self._fired_killrouter = True
            self.fired.append(("killrouter", -1, n))
            kill = _router_kill_cb
        log.warning(
            "SERVE FAULT: killing the active router after %d dispatches",
            n,
        )
        if kill is not None:
            kill()
        return True


# Crash callbacks live at module level, not on the armed engine, so a
# replica can register its kill at build time regardless of whether the
# plan is armed before or after the fleet comes up (replica id -> the
# callable that makes that replica die at the transport level).
_serve_crash_cbs: dict[int, Callable[[], None]] = {}


def register_serve_crash(replica: int, kill: Callable[[], None]) -> None:
    """Register replica ``replica``'s transport-kill callable (the
    chaos harness registers ``InProcReplica.kill`` at every start)."""
    _serve_crash_cbs[replica] = kill


# The router-kill callback for killrouter@T. Like the replica crash
# callbacks it lives at module level: the chaos harness registers the
# ACTIVE router's hard-abort (frontend abort + router close) and
# re-registers on takeover, so the fault always lands on whichever
# router currently holds the lease.
_router_kill_cb: Callable[[], None] | None = None


def register_router_kill(kill: Callable[[], None] | None) -> None:
    """Register (or clear, with None) the active router's hard-abort
    callable for ``killrouter@T``."""
    global _router_kill_cb
    _router_kill_cb = kill


_serve_engine: ServeEngine | None = None
_serve_env_checked = False


def serve_install(spec_or_plan: str | ServeFaultPlan) -> ServeEngine:
    """Arm a serve fault plan in-process (chaos harness / tests)."""
    global _serve_engine, _serve_env_checked
    plan = (
        parse_serve_spec(spec_or_plan)
        if isinstance(spec_or_plan, str)
        else spec_or_plan
    )
    _serve_engine = ServeEngine(plan)
    _serve_env_checked = True
    return _serve_engine


def serve_clear() -> None:
    global _serve_engine, _serve_env_checked
    _serve_engine = None
    _serve_env_checked = False


def serve_active() -> ServeEngine | None:
    """The armed serve engine, lazily read from $TPU_SERVE_FAULT_INJECT."""
    global _serve_engine, _serve_env_checked
    if _serve_engine is None and not _serve_env_checked:
        _serve_env_checked = True
        spec = os.environ.get(SERVE_ENV_VAR, "")
        if spec:
            _serve_engine = ServeEngine(parse_serve_spec(spec))
            log.info(
                "serve fault injection armed from $%s=%s",
                SERVE_ENV_VAR, spec,
            )
    return _serve_engine


# ------------------------------------------------------- global activation

_engine: Engine | None = None
_env_checked = False


def install(spec_or_plan: str | FaultPlan) -> Engine:
    """Arm a fault plan in-process (tests use the ``faults`` fixture)."""
    global _engine, _env_checked
    plan = (
        parse_spec(spec_or_plan)
        if isinstance(spec_or_plan, str)
        else spec_or_plan
    )
    _engine = Engine(plan)
    _env_checked = True
    return _engine


def clear() -> None:
    global _engine, _env_checked
    _engine = None
    _env_checked = False


def active() -> Engine | None:
    """The armed engine, lazily initialized from $TPU_FAULT_INJECT."""
    global _engine, _env_checked
    if _engine is None and not _env_checked:
        _env_checked = True
        spec = os.environ.get(ENV_VAR, "")
        if spec:
            _engine = Engine(parse_spec(spec))
            log.info("fault injection armed from $%s=%s", ENV_VAR, spec)
    return _engine


# ------------------------------------------------------------ IO retries

# Defaults; overridden from TrainConfig (io_retries / io_backoff_secs) by
# train/cli._setup via configure_io_retry.
_io_retry = {"attempts": 3, "backoff": 0.25}


def configure_io_retry(attempts: int, backoff_secs: float) -> None:
    _io_retry["attempts"] = max(int(attempts), 0)
    _io_retry["backoff"] = max(float(backoff_secs), 0.0)


def retry_io(
    fn: Callable,
    what: str,
    *,
    attempts: int | None = None,
    backoff_secs: float | None = None,
):
    """Run a filesystem operation with bounded retry + exponential backoff.

    Retries only OSError (flaky NFS/GCS-fuse reads, the pod-scale reality);
    data errors (ValueError etc.) propagate immediately. ``attempts`` is
    the number of RETRIES after the first try. An armed fault engine's
    ``io_check`` runs before each attempt so injected IO faults exercise
    exactly this path.
    """
    import gzip

    attempts = _io_retry["attempts"] if attempts is None else attempts
    backoff = _io_retry["backoff"] if backoff_secs is None else backoff_secs
    for attempt in range(attempts + 1):
        try:
            eng = active()
            if eng is not None:
                eng.io_check(what)
            return fn()
        except OSError as e:
            if isinstance(e, gzip.BadGzipFile):
                raise  # corrupt data, not a transient store fault
            if attempt >= attempts:
                raise
            # Surface the formerly write-only retry in the metrics
            # registry (ISSUE 2): flaky-store churn belongs in the run
            # report, not just interleaved WARNING lines.
            from tensorflow_examples_tpu.telemetry.registry import (
                default_registry,
            )

            default_registry().counter("io/retries").inc()
            delay = backoff * (2**attempt)
            log.warning(
                "io error on %s (attempt %d/%d), retrying in %.2fs: %s",
                what,
                attempt + 1,
                attempts + 1,
                delay,
                e,
            )
            time.sleep(delay)
