"""Failure detection + crash diagnostics (SURVEY.md §5c).

The reference had nothing beyond checkpoint-restart; TPU-native failure
handling here is three layers:

1. **Crash handlers** (``install_crash_handlers``): faulthandler tracebacks
   for hard faults (SIGSEGV/SIGABRT — e.g. a dying PJRT plugin) written to
   ``workdir/debugging/``, plus ``cloud_tpu_diagnostics`` integration when
   that package is importable (TPU-side stack traces on Cloud TPU VMs).
2. **Hang watchdog** (``Watchdog``): a daemon thread the training loop
   pings every step. If no progress for ``timeout_s`` (device hang, stuck
   collective, wedged host↔TPU tunnel), it dumps every Python thread's
   stack — turning a silent hang into a diagnosable event. The loop also
   marks which *phase* it is in (``enter("input_fetch")`` /
   ``enter("device_step")``), so the dump says whether the host input
   pipeline or the device step stalled. By default detection-only; with
   ``fatal_timeout_s > 0`` the watchdog FAILS FAST once the stall
   exceeds that bound — dump, then ``on_fatal`` (default:
   ``os._exit(HUNG_EXIT_CODE)``) — because at pod scale a silently hung
   host wedges the whole slice (ISSUE 1 / arXiv:1909.09756).
3. **Recovery** is checkpoint-resume, which the shared loop already does
   (orbax latest-checkpoint restore + stateless-resumable input order),
   plus the preemption/bad-step machinery in train/resilience.py.
"""

from __future__ import annotations

import faulthandler
import logging
import os
import sys
import threading
import time
from typing import Callable

log = logging.getLogger(__name__)


_fault_file = None  # singleton: faulthandler holds exactly one target


def install_crash_handlers(workdir: str = "") -> None:
    """Route hard-fault (SIGSEGV/SIGABRT/…) tracebacks somewhere durable.

    With ``workdir``: to ``workdir/debugging/faults_<pid>.log`` (the path
    is logged so operators know where to look — faulthandler writes to a
    single target, so the file supersedes stderr). Without: to stderr.
    Idempotent; repeated calls reuse the open file.
    """
    global _fault_file
    if workdir:
        debug_dir = os.path.join(workdir, "debugging")
        os.makedirs(debug_dir, exist_ok=True)
        path = os.path.join(debug_dir, f"faults_{os.getpid()}.log")
        if _fault_file is None or _fault_file.name != path:
            if _fault_file is not None:
                _fault_file.close()
            _fault_file = open(path, "w")  # noqa: SIM115 - outlives the call
        faulthandler.enable(file=_fault_file)
        log.info("hard-fault tracebacks -> %s", path)
    else:
        faulthandler.enable()
    try:  # TPU-side stack traces on Cloud TPU VMs (optional dependency)
        import cloud_tpu_diagnostics  # noqa: F401

        log.info("cloud_tpu_diagnostics available for TPU-side traces")
    except ImportError:
        pass


# Exit code for a watchdog-terminated (fail-fast) run: distinguishable
# from clean exits (0), python errors (1), and signal deaths (128+N).
HUNG_EXIT_CODE = 87


class Watchdog:
    """Detects training-loop hangs; dumps all thread stacks once per hang.

    >>> wd = Watchdog(timeout_s=600); wd.start()
    >>> for step ...: wd.ping(step)
    >>> wd.stop()

    ``enter(phase)`` marks loop phases ("input_fetch", "device_step", …)
    and counts as a heartbeat — a phase transition IS progress — so the
    hang report can name the stalled phase and how long it sat there.
    With ``fatal_timeout_s > 0``, a stall that long triggers fail-fast:
    diagnostic dump, then ``on_fatal(step, stalled_s)`` (default
    ``os._exit(HUNG_EXIT_CODE)`` — a deliberate hard exit: the main
    thread is by definition wedged, possibly inside a C call that a
    Python-level exception could never interrupt).
    """

    def __init__(
        self,
        timeout_s: float,
        *,
        fatal_timeout_s: float = 0.0,
        on_hang: Callable[[int, float], None] | None = None,
        on_fatal: Callable[[int, float], None] | None = None,
        flush_fn: Callable[[], None] | None = None,
        poll_s: float | None = None,
    ):
        self.timeout_s = timeout_s
        self.fatal_timeout_s = fatal_timeout_s
        self._on_hang = on_hang
        self._on_fatal = on_fatal
        # Best-effort pre-exit flush: runs on the fatal path BEFORE
        # on_fatal/os._exit, from the watchdog thread, so the run's
        # metrics survive the hard exit (ISSUE 2 abnormal-exit
        # satellite). The trainer passes Telemetry.emergency_flush,
        # which also snapshots the last fleet state and closes the
        # /metrics server (ISSUE 4) — a hung run's last per-host skew
        # picture is never lost, and the port stops answering scrapes
        # as if the run were live.
        self._flush_fn = flush_fn
        self._poll_s = poll_s if poll_s is not None else min(timeout_s / 4, 30.0)
        if fatal_timeout_s > 0:
            self._poll_s = min(self._poll_s, max(fatal_timeout_s / 4, 0.05))
        self._last_ping = time.monotonic()
        self._last_step = -1
        self._phase = "startup"
        self._phase_since = time.monotonic()
        self._paused = False
        self._fired_for = -2  # last step a hang was reported for
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "Watchdog":
        self._thread = threading.Thread(
            target=self._run, name="train-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def ping(self, step: int) -> None:
        self._last_ping = time.monotonic()
        self._last_step = step

    def enter(self, phase: str) -> None:
        """Mark a loop phase ("input_fetch", "device_step", "restore", …).

        A phase transition is progress, so this refreshes the heartbeat
        (but not the step counter)."""
        now = time.monotonic()
        self._phase = phase
        self._phase_since = now
        self._last_ping = now

    def status(self) -> dict:
        """Live state for the /health endpoint (telemetry/serve.py):
        current phase + how long it has been the phase, the stall age,
        and the configured timeouts. Readable from any thread — every
        field is a single attribute read of values the loop thread
        writes atomically."""
        now = time.monotonic()
        return {
            "phase": self._phase,
            "phase_age_secs": now - self._phase_since,
            "stalled_secs": now - self._last_ping,
            "last_step": self._last_step,
            "paused": self._paused,
            "timeout_secs": self.timeout_s,
            "fatal_timeout_secs": self.fatal_timeout_s,
        }

    def pause(self) -> None:
        """Suspend hang detection (long known-slow phase: eval, ckpt,
        first-step compile). Timer restarts on the next ping/resume."""
        self._paused = True

    def resume(self) -> None:
        # Refresh the ping BEFORE unpausing: the watcher thread must
        # never see unpaused state with a stale timestamp.
        self._last_ping = time.monotonic()
        self._paused = False

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _dump(self, stalled: float, *, fatal: bool) -> None:
        # Name the innermost open telemetry span(s), not just the coarse
        # phase marker: "phase 'input_fetch', open spans ['data_fetch']"
        # tells you which instrumented region actually wedged.
        try:
            from tensorflow_examples_tpu.telemetry.spans import (
                active_span_names,
            )

            open_spans = active_span_names()
        except Exception:  # pragma: no cover - telemetry unavailable
            open_spans = []
        log.error(
            "WATCHDOG%s: no training progress for %.1fs (last step %d, "
            "phase %r for %.1fs, open spans %s) — dumping all thread "
            "stacks",
            " FATAL" if fatal else "",
            stalled,
            self._last_step,
            self._phase,
            time.monotonic() - self._phase_since,
            open_spans,
        )
        faulthandler.dump_traceback(file=sys.stderr)
        if _fault_file is not None:
            # Also into the durable fault log (stderr may not be
            # captured on managed VMs — the motivating scenario).
            faulthandler.dump_traceback(file=_fault_file)
            _fault_file.flush()

    def _run(self) -> None:
        fatal_fired = False
        while not self._stop.wait(self._poll_s):
            if self._paused:
                continue
            stalled = time.monotonic() - self._last_ping
            fatal_now = (
                self.fatal_timeout_s > 0
                and stalled >= self.fatal_timeout_s
                and not fatal_fired
            )
            if (
                not fatal_now  # one dump when both fire in the same pass
                and stalled >= self.timeout_s
                and self._fired_for != self._last_step
            ):
                self._fired_for = self._last_step
                self._dump(stalled, fatal=False)
                if self._on_hang is not None:
                    self._on_hang(self._last_step, stalled)
            if fatal_now:
                fatal_fired = True
                self._dump(stalled, fatal=True)
                if self._flush_fn is not None:
                    try:
                        self._flush_fn()
                    except Exception:  # pragma: no cover - best effort
                        log.exception("pre-exit telemetry flush failed")
                if self._on_fatal is not None:
                    self._on_fatal(self._last_step, stalled)
                else:
                    log.critical(
                        "WATCHDOG: failing fast with exit code %d rather "
                        "than hanging the slice",
                        HUNG_EXIT_CODE,
                    )
                    if _fault_file is not None:
                        _fault_file.flush()
                    os._exit(HUNG_EXIT_CODE)
