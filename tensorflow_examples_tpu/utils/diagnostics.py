"""Failure detection + crash diagnostics (SURVEY.md §5c).

The reference had nothing beyond checkpoint-restart; TPU-native failure
handling here is three layers:

1. **Crash handlers** (``install_crash_handlers``): faulthandler tracebacks
   for hard faults (SIGSEGV/SIGABRT — e.g. a dying PJRT plugin) written to
   ``workdir/debugging/``, plus ``cloud_tpu_diagnostics`` integration when
   that package is importable (TPU-side stack traces on Cloud TPU VMs).
2. **Hang watchdog** (``Watchdog``): a daemon thread the training loop
   pings every step. If no progress for ``timeout_s`` (device hang, stuck
   collective, wedged host↔TPU tunnel), it dumps every Python thread's
   stack — turning a silent hang into a diagnosable event. Detection
   only: it never kills the run (a pod-slice restart is the operator's /
   scheduler's call).
3. **Recovery** is checkpoint-resume, which the shared loop already does
   (orbax latest-checkpoint restore + stateless-resumable input order).
"""

from __future__ import annotations

import faulthandler
import logging
import os
import sys
import threading
import time
from typing import Callable

log = logging.getLogger(__name__)


_fault_file = None  # singleton: faulthandler holds exactly one target


def install_crash_handlers(workdir: str = "") -> None:
    """Route hard-fault (SIGSEGV/SIGABRT/…) tracebacks somewhere durable.

    With ``workdir``: to ``workdir/debugging/faults_<pid>.log`` (the path
    is logged so operators know where to look — faulthandler writes to a
    single target, so the file supersedes stderr). Without: to stderr.
    Idempotent; repeated calls reuse the open file.
    """
    global _fault_file
    if workdir:
        debug_dir = os.path.join(workdir, "debugging")
        os.makedirs(debug_dir, exist_ok=True)
        path = os.path.join(debug_dir, f"faults_{os.getpid()}.log")
        if _fault_file is None or _fault_file.name != path:
            if _fault_file is not None:
                _fault_file.close()
            _fault_file = open(path, "w")  # noqa: SIM115 - outlives the call
        faulthandler.enable(file=_fault_file)
        log.info("hard-fault tracebacks -> %s", path)
    else:
        faulthandler.enable()
    try:  # TPU-side stack traces on Cloud TPU VMs (optional dependency)
        import cloud_tpu_diagnostics  # noqa: F401

        log.info("cloud_tpu_diagnostics available for TPU-side traces")
    except ImportError:
        pass


class Watchdog:
    """Detects training-loop hangs; dumps all thread stacks once per hang.

    >>> wd = Watchdog(timeout_s=600); wd.start()
    >>> for step ...: wd.ping(step)
    >>> wd.stop()
    """

    def __init__(
        self,
        timeout_s: float,
        *,
        on_hang: Callable[[int, float], None] | None = None,
        poll_s: float | None = None,
    ):
        self.timeout_s = timeout_s
        self._on_hang = on_hang
        self._poll_s = poll_s if poll_s is not None else min(timeout_s / 4, 30.0)
        self._last_ping = time.monotonic()
        self._last_step = -1
        self._paused = False
        self._fired_for = -2  # last step a hang was reported for
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "Watchdog":
        self._thread = threading.Thread(
            target=self._run, name="train-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def ping(self, step: int) -> None:
        self._last_ping = time.monotonic()
        self._last_step = step

    def pause(self) -> None:
        """Suspend hang detection (long known-slow phase: eval, ckpt,
        first-step compile). Timer restarts on the next ping/resume."""
        self._paused = True

    def resume(self) -> None:
        # Refresh the ping BEFORE unpausing: the watcher thread must
        # never see unpaused state with a stale timestamp.
        self._last_ping = time.monotonic()
        self._paused = False

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            if self._paused:
                continue
            stalled = time.monotonic() - self._last_ping
            if stalled >= self.timeout_s and self._fired_for != self._last_step:
                self._fired_for = self._last_step
                log.error(
                    "WATCHDOG: no training progress for %.0fs (last step %d) "
                    "— dumping all thread stacks",
                    stalled,
                    self._last_step,
                )
                faulthandler.dump_traceback(file=sys.stderr)
                if _fault_file is not None:
                    # Also into the durable fault log (stderr may not be
                    # captured on managed VMs — the motivating scenario).
                    faulthandler.dump_traceback(file=_fault_file)
                    _fault_file.flush()
                if self._on_hang is not None:
                    self._on_hang(self._last_step, stalled)
