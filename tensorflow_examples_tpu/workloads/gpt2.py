"""GPT-2 124M causal-LM workload (BASELINE.json:configs[4]).

Reference behavior: ``tf.function(jit_compile=True)`` train step, XLA,
grad accumulation, 16-chip scale, sampling in eval. Here the whole step
is one jitted XLA program by construction; scale comes from the 4-axis
mesh (dp via batch sharding, tp via GPT2_RULES over ``model``, sp via
ring/Ulysses attention over ``context``, fsdp via ZeRO-style param
sharding) instead of per-example strategy code. The LM loss runs the
fused Pallas cross-entropy (ops/cross_entropy.py) so the [tokens, 50257]
log-softmax never materializes in HBM.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from tensorflow_examples_tpu.data.sources import load_lm_tokens
from tensorflow_examples_tpu.models import transformer
from tensorflow_examples_tpu.ops.cross_entropy import cross_entropy_per_example
from tensorflow_examples_tpu.ops.losses import weighted_mean
from tensorflow_examples_tpu.train import Task, TrainConfig
from tensorflow_examples_tpu.train import optimizers


@dataclasses.dataclass
class Gpt2Config(TrainConfig):
    # GPT-2 124M pretraining recipe (AdamW b2=0.95, warmup-cosine 6e-4,
    # wd 0.1, clip 1.0, bf16 compute).
    vocab_size: int = 50257
    seq_len: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    dropout: float = 0.1
    attention: str = "flash"  # flash | xla | ring | ulysses
    fused_ce: bool = True
    pretrained: str = ""  # local HF GPT2LMHeadModel path to start from

    global_batch_size: int = 16
    train_steps: int = 20000
    warmup_steps: int = 2000
    learning_rate: float = 6e-4
    weight_decay: float = 0.1
    grad_clip_norm: float = 1.0
    eval_every: int = 2000
    checkpoint_every: int = 2000
    log_every: int = 50


def model_config(cfg: Gpt2Config) -> transformer.TransformerConfig:
    return transformer.TransformerConfig(
        vocab_size=cfg.vocab_size,
        max_len=cfg.seq_len,
        num_layers=cfg.num_layers,
        num_heads=cfg.num_heads,
        d_model=cfg.d_model,
        dropout=cfg.dropout,
        attention=cfg.attention,
        remat=cfg.remat,
    )


def make_task(cfg: Gpt2Config, mesh=None) -> Task:
    model = transformer.Transformer(model_config(cfg), mesh=mesh)

    def init_fn(rng):
        import math

        import jax

        from tensorflow_examples_tpu.core.mesh import AxisNames

        # Dummy batch must be shardable over the mesh's batch axes (the
        # shard_map'd attention path sees it at init time).
        nb = (
            math.prod(mesh.shape[a] for a in AxisNames.BATCH_AXES)
            if mesh is not None
            else 1
        )
        dummy = jnp.zeros((nb, cfg.seq_len), jnp.int32)
        variables = dict(model.init({"params": rng}, dummy))
        if cfg.pretrained:
            from tensorflow_examples_tpu.models.hf_import import import_gpt2

            _, params = import_gpt2(cfg.pretrained, model_config(cfg))
            variables["params"] = jax.tree.map(jnp.asarray, params)
        return variables

    def token_nll(params, batch, *, rng, train):
        inputs = batch["tokens"][:, :-1]
        labels = batch["tokens"][:, 1:]
        logits = model.apply(
            {"params": params},
            inputs,
            train=train,
            rngs={"dropout": rng} if train else None,
        )
        nll = cross_entropy_per_example(
            logits.reshape(-1, cfg.vocab_size),
            labels.reshape(-1),
            fused=cfg.fused_ce,
        )
        return nll.reshape(labels.shape)

    def loss_fn(params, model_state, batch, *, rng, train):
        nll = token_nll(params, batch, rng=rng, train=train)
        return jnp.mean(nll), {}, model_state

    def eval_fn(params, model_state, batch):
        nll = token_nll(params, batch, rng=None, train=False)
        per_example = jnp.mean(nll, axis=-1)
        mask = batch.get("mask")
        return {
            "nll": weighted_mean(per_example, mask),
            "weight": jnp.sum(mask) if mask is not None else jnp.float32(
                per_example.shape[0]
            ),
        }

    return Task(
        name="gpt2_124m",
        init_fn=init_fn,
        loss_fn=loss_fn,
        make_optimizer=optimizers.adamw_cosine,
        sharding_rules=transformer.GPT2_RULES,
        eval_fn=eval_fn,
    )


def datasets(cfg: Gpt2Config):
    return (
        load_lm_tokens(
            cfg.data_dir, "train", seq_len=cfg.seq_len, vocab_size=cfg.vocab_size
        ),
        eval_dataset(cfg),
    )


def eval_dataset(cfg: Gpt2Config):
    import logging
    import os

    has_val = bool(cfg.data_dir) and any(
        os.path.exists(os.path.join(cfg.data_dir, "val" + ext))
        for ext in (".bin", ".npy", ".txt")
    )
    if cfg.data_dir and not has_val:
        logging.getLogger(__name__).warning(
            "--data_dir=%s has no val.{bin,npy,txt}; eval runs on SYNTHETIC "
            "data — reported nll is not a real validation score",
            cfg.data_dir,
        )
    return load_lm_tokens(
        cfg.data_dir if has_val else "",
        "val",
        seq_len=cfg.seq_len,
        vocab_size=cfg.vocab_size,
    )
