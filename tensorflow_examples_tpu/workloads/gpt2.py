"""GPT-2 124M causal-LM workload (BASELINE.json:configs[4]).

Reference behavior: ``tf.function(jit_compile=True)`` train step, XLA,
grad accumulation, 16-chip scale, sampling in eval. Here the whole step
is one jitted XLA program by construction; scale comes from the 4-axis
mesh (dp via batch sharding, tp via GPT2_RULES over ``model``, sp via
ring/Ulysses attention over ``context``, fsdp via ZeRO-style param
sharding) instead of per-example strategy code. The LM loss runs the
fused Pallas cross-entropy (ops/cross_entropy.py) so the [tokens, 50257]
log-softmax never materializes in HBM.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from tensorflow_examples_tpu.data.sources import load_lm_tokens
from tensorflow_examples_tpu.models import transformer
from tensorflow_examples_tpu.ops.cross_entropy import (
    cross_entropy_per_example,
    mesh_cross_entropy_per_example,
)
from tensorflow_examples_tpu.ops.losses import weighted_mean
from tensorflow_examples_tpu.train import Task, TrainConfig
from tensorflow_examples_tpu.train import optimizers


@dataclasses.dataclass
class Gpt2Config(TrainConfig):
    # GPT-2 124M pretraining recipe (AdamW b2=0.95, warmup-cosine 6e-4,
    # wd 0.1, clip 1.0, bf16 compute).
    vocab_size: int = 50257
    seq_len: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    dropout: float = 0.1
    attention: str = "flash"  # flash | xla | ring | ulysses
    remat_policy: str = "none"  # none | dots | dots_no_batch (with --remat:
    #   what the checkpointed blocks SAVE; numerics identical, only the
    #   memory/recompute trade moves — see models/transformer.py)
    fused_ce: bool = True
    pretrained: str = ""  # local HF GPT2LMHeadModel path to start from
    # Pipeline parallelism (mesh_pipe > 1): microbatching over the
    # `pipe` axis (parallel/pipeline.py). Schedules: "1f1b" (default —
    # interleaved fwd/bwd, P-bounded activation memory, bubble ticks
    # idle) or "gpipe" (transpose-scheduled backward).
    num_microbatches: int = 4
    pipeline_schedule: str = "1f1b"
    # Virtual stages (chunks) per pipe device for INTERLEAVED 1F1B:
    # v > 1 cuts the pipeline ramp ~v-fold in full-stage units at the
    # cost of v x the ticks/hops (parallel/pipeline.py). Blocks are
    # then STORED slot-major (interleave_perm); 1f1b only.
    pipe_interleave: int = 1
    # Mixture-of-Experts: swap every `moe_every`-th block's MLP for a
    # top-1 Switch MoE with this many experts (expert-parallel over the
    # `model` mesh axis). 0 = dense GPT-2.
    moe_experts: int = 0
    moe_every: int = 2
    moe_top_k: int = 1
    moe_aux_weight: float = 0.01
    # "" = backend default (grouped on TPU, scatter elsewhere); pin
    # "grouped"/"scatter" for cross-backend-identical numerics.
    moe_impl: str = ""
    # Vocab-parallel LM head + fused CE over the `model` axis (Megatron
    # parallel cross-entropy): the [tokens, 50257] logits never exist;
    # each shard holds [tokens, V/m]. Requires mesh_model > 1.
    tp_vocab: bool = False

    global_batch_size: int = 16
    train_steps: int = 20000
    warmup_steps: int = 2000
    learning_rate: float = 6e-4
    weight_decay: float = 0.1
    grad_clip_norm: float = 1.0
    eval_every: int = 2000
    checkpoint_every: int = 2000
    log_every: int = 50


def model_config(cfg: Gpt2Config) -> transformer.TransformerConfig:
    # Fail fast on enum typos regardless of flag combination — the
    # model-side check only triggers under `remat and not decode` (and
    # the stacked-block pipeline path never reaches it).
    if cfg.remat_policy not in ("none", "dots", "dots_no_batch"):
        raise ValueError(
            f"remat_policy={cfg.remat_policy!r} not in "
            "('none', 'dots', 'dots_no_batch')"
        )
    return transformer.TransformerConfig(
        vocab_size=cfg.vocab_size,
        max_len=cfg.seq_len,
        num_layers=cfg.num_layers,
        num_heads=cfg.num_heads,
        d_model=cfg.d_model,
        dropout=cfg.dropout,
        attention=cfg.attention,
        remat=cfg.remat,
        remat_policy=cfg.remat_policy,
        moe_experts=cfg.moe_experts,
        moe_every=cfg.moe_every,
        moe_top_k=cfg.moe_top_k,
        moe_impl=cfg.moe_impl,
    )


def make_task(cfg: Gpt2Config, mesh=None) -> Task:
    from tensorflow_examples_tpu.core.mesh import AxisNames

    if mesh is not None and mesh.shape[AxisNames.PIPE] > 1:
        return _make_pipeline_task(cfg, mesh)
    model = transformer.Transformer(model_config(cfg), mesh=mesh)

    def init_fn(rng):
        import math

        import jax

        from tensorflow_examples_tpu.core.mesh import AxisNames

        # Dummy batch must be shardable over the mesh's batch axes (the
        # shard_map'd attention path sees it at init time).
        nb = (
            math.prod(mesh.shape[a] for a in AxisNames.BATCH_AXES)
            if mesh is not None
            else 1
        )
        dummy = jnp.zeros((nb, cfg.seq_len), jnp.int32)
        variables = dict(model.init({"params": rng}, dummy))
        if cfg.pretrained:
            from tensorflow_examples_tpu.models.hf_import import import_gpt2

            _, params = import_gpt2(cfg.pretrained, model_config(cfg))
            variables["params"] = jax.tree.map(jnp.asarray, params)
        return variables

    from tensorflow_examples_tpu.core.mesh import AxisNames as _A

    tp_vocab = (
        cfg.tp_vocab and mesh is not None and mesh.shape[_A.MODEL] > 1
    )

    def token_nll(params, batch, *, rng, train):
        inputs = batch["tokens"][:, :-1]
        labels = batch["tokens"][:, 1:]
        out = model.apply(
            {"params": params},
            inputs,
            train=train,
            return_hidden=tp_vocab,
            rngs={"dropout": rng} if train else None,
            mutable=["intermediates"] if cfg.moe_experts else False,
        )
        hidden_or_logits, aux = (out if cfg.moe_experts else (out, None))
        if tp_vocab:
            from tensorflow_examples_tpu.ops.cross_entropy import (
                tp_cross_entropy_from_hidden,
            )

            nll = tp_cross_entropy_from_hidden(
                hidden_or_logits.reshape(-1, cfg.d_model),
                params["wte"]["embedding"],
                labels.reshape(-1),
                mesh=mesh,
            )
        else:
            # Token-sharded on meshes: the Pallas CE call is opaque to
            # the partitioner (ops/cross_entropy.py docstring).
            nll = mesh_cross_entropy_per_example(
                hidden_or_logits, labels, mesh=mesh, fused=cfg.fused_ce
            )
        moe_aux, moe_drop = jnp.float32(0.0), jnp.float32(0.0)
        if cfg.moe_experts:
            # Sown intermediates: {"h_i": {"moe": {"moe_aux": (v,),
            # "moe_drop": (v,)}}} — sum the aux losses, average the
            # dropped-token fractions over the MoE layers.
            flat = jax.tree_util.tree_flatten_with_path(aux["intermediates"])[0]
            auxes = [v for p, v in flat if "moe_aux" in jax.tree_util.keystr(p)]
            drops = [v for p, v in flat if "moe_drop" in jax.tree_util.keystr(p)]
            moe_aux = sum(auxes)
            moe_drop = sum(drops) / max(len(drops), 1)
        return nll.reshape(labels.shape), moe_aux, moe_drop

    def loss_fn(params, model_state, batch, *, rng, train):
        nll, moe_aux, moe_drop = token_nll(params, batch, rng=rng, train=train)
        loss = jnp.mean(nll) + cfg.moe_aux_weight * moe_aux
        metrics = (
            {"moe_aux": moe_aux, "moe_drop": moe_drop} if cfg.moe_experts else {}
        )
        return loss, metrics, model_state

    def eval_fn(params, model_state, batch):
        nll, _, _ = token_nll(params, batch, rng=None, train=False)
        per_example = jnp.mean(nll, axis=-1)
        mask = batch.get("mask")
        return {
            "nll": weighted_mean(per_example, mask),
            "weight": jnp.sum(mask) if mask is not None else jnp.float32(
                per_example.shape[0]
            ),
        }

    rules = transformer.GPT2_RULES
    if tp_vocab and cfg.vocab_size % mesh.shape[_A.MODEL] == 0:
        from jax.sharding import PartitionSpec as P

        from tensorflow_examples_tpu.core.sharding import ShardingRules

        # Vocab-shard the tied table (first match wins → prepend). Only
        # when the vocab divides evenly — jit param shardings must be
        # exact; the parallel CE itself pads, so uneven vocabs still run
        # tp_vocab with a replicated table.
        rules = ShardingRules([(r"wte/embedding", P(_A.MODEL, None))]) + rules
    return Task(
        name="gpt2_124m",
        init_fn=init_fn,
        loss_fn=loss_fn,
        make_optimizer=optimizers.adamw_cosine,
        sharding_rules=rules,
        eval_fn=eval_fn,
    )


def _make_pipeline_task(cfg: Gpt2Config, mesh) -> Task:
    """Pipeline-parallel GPT-2 (mesh_pipe > 1; 1F1B default, GPipe opt).

    The block stack lives as a [num_layers]-stacked param tree sharded
    over ``pipe`` (rules below); embeddings/head stay replicated. The
    schedule (parallel/pipeline.py) runs inside the same jitted train
    step under a partial-manual shard_map — only ``pipe`` is manual —
    so it COMPOSES with dp/fsdp batch sharding AND tensor parallelism:
    with mesh_model > 1 the rules below put the Megatron layout on each
    stage's stacked weights (heads/ff over ``model``) and the automatic
    partitioner inserts the TP collectives inside every stage tick,
    exactly as in the non-pipelined model. ``attention="flash"``
    composes too (round 4): mesh_attention detects the pipe-manual
    region with an auto ``model`` axis and nests a model-only shard_map
    around the Pallas kernel, so heads stay sharded
    (parallel/attention.py _stage_tp_axis). sp/context stays outside
    PP. Decode/generate use the non-pipelined model.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from tensorflow_examples_tpu.core.mesh import AxisNames
    from tensorflow_examples_tpu.core.sharding import ShardingRules
    from tensorflow_examples_tpu.parallel.pipeline import (
        interleave_perm,
        make_pipeline_1f1b,
        pipeline_apply,
    )

    n_stages = mesh.shape[AxisNames.PIPE]
    v = cfg.pipe_interleave
    if v < 1:
        raise ValueError(f"pipe_interleave must be >= 1, got {v}")
    s_total = n_stages * v
    if cfg.num_layers % s_total:
        raise ValueError(
            f"num_layers {cfg.num_layers} not divisible by "
            f"pipe={n_stages} x interleave={v}"
        )
    if cfg.pipeline_schedule not in ("1f1b", "gpipe"):
        raise ValueError(f"unknown pipeline_schedule={cfg.pipeline_schedule}")
    if v > 1 and cfg.pipeline_schedule != "1f1b":
        raise ValueError("pipe_interleave > 1 requires the 1f1b schedule")
    mcfg = model_config(cfg)
    embed_head = transformer.EmbedHead(mcfg)
    per_stage = cfg.num_layers // s_total

    # With interleaving, blocks are STORED slot-major: slot i = d·v + j
    # holds the layers of virtual stage j·P + d (interleave_perm), so
    # the dim-0 `pipe` sharding rule places each device's v chunks
    # contiguously with zero train-time movement. Layer-row permutation
    # maps storage <-> logical order (eval/GPipe needs logical).
    if v > 1:
        import numpy as np

        _slot_of_stage = interleave_perm(n_stages, v)
        _row_perm = np.concatenate(
            [
                np.arange(s * per_stage, (s + 1) * per_stage)
                for s in _slot_of_stage
            ]
        )
        _row_unperm = np.argsort(_row_perm)

    # The blocks collection's KEY encodes the storage layout when it is
    # slot-major: a checkpoint written under one (pipe, interleave) and
    # restored into a task with another would otherwise silently load
    # permuted layers (shapes all match) — the key mismatch turns that
    # into a loud orbax tree-structure error instead.
    blocks_key = "blocks" if v == 1 else f"blocks_slotmajor_p{n_stages}v{v}"

    def to_slot_order(blocks):
        if v == 1:
            return blocks
        return jax.tree.map(lambda p: p[_row_perm], blocks)

    def to_logical_order(blocks):
        if v == 1:
            return blocks
        return jax.tree.map(lambda p: p[_row_unperm], blocks)

    def split_stages(blocks):
        """Storage [L, ...] (slot-major when v>1) → [P·v, L/(P·v), ...]."""
        return jax.tree.map(
            lambda p: p.reshape((s_total, per_stage) + p.shape[1:]), blocks
        )

    def head_loss_fn(hp, y, lbl):
        """ln_f + tied LM head + fused CE, mean over the microbatch —
        runs at the LAST pipe stage only under the 1F1B schedule."""
        logits = embed_head.apply({"params": hp}, y, method="logits")
        nll = cross_entropy_per_example(
            logits.reshape(-1, cfg.vocab_size),
            lbl.reshape(-1),
            fused=cfg.fused_ce,
        )
        return jnp.mean(nll)

    run_1f1b_drop = make_pipeline_1f1b(
        lambda sp, h, key: transformer.apply_stacked_blocks(
            mcfg, sp, h, train=True, rng=key
        ),
        head_loss_fn,
        mesh=mesh,
        num_microbatches=cfg.num_microbatches,
        num_virtual_stages=v,
    )
    run_1f1b_plain = make_pipeline_1f1b(
        lambda sp, h: transformer.apply_stacked_blocks(mcfg, sp, h),
        head_loss_fn,
        mesh=mesh,
        num_microbatches=cfg.num_microbatches,
        num_virtual_stages=v,
    )

    def init_fn(rng):
        if cfg.pretrained:
            from tensorflow_examples_tpu.models.hf_import import import_gpt2

            _, full = import_gpt2(cfg.pretrained, mcfg)
            full = jax.tree.map(jnp.asarray, full)
            stacked = transformer.stack_params_for_pipeline(
                full, cfg.num_layers
            )
            blocks = to_slot_order(stacked.pop("blocks"))
            return {"params": {**stacked, blocks_key: blocks}}
        r1, r2 = jax.random.split(rng)
        dummy = jnp.zeros((1, cfg.seq_len), jnp.int32)
        embed = embed_head.init({"params": r1}, dummy)["params"]
        blocks = to_slot_order(transformer.init_stacked_blocks(mcfg, r2))
        return {"params": {"embed": embed, blocks_key: blocks}}

    def logits_fn(params, tokens, *, rng=None, train=False):
        dropout = train and cfg.dropout > 0 and rng is not None
        r_embed, r_blocks = (
            jax.random.split(rng) if dropout else (None, None)
        )
        x = embed_head.apply(
            {"params": params["embed"]},
            tokens,
            dropout,  # embedding dropout, same as the non-PP model
            method="encode",
            rngs={"dropout": r_embed} if dropout else None,
        )
        # Eval/GPipe runs the classic [P, L/P] logical stacking; with
        # interleaved storage this un-permutes layer rows (a gather
        # across pipe — eval-only cost, the train path never moves).
        stage_params = jax.tree.map(
            lambda p: p.reshape(
                (n_stages, cfg.num_layers // n_stages) + p.shape[1:]
            ),
            to_logical_order(params[blocks_key]),
        )
        stage_fn = (
            (
                lambda sp, h, key: transformer.apply_stacked_blocks(
                    mcfg, sp, h, train=True, rng=key
                )
            )
            if dropout
            else (lambda sp, h: transformer.apply_stacked_blocks(mcfg, sp, h))
        )
        x = pipeline_apply(
            stage_fn,
            stage_params,
            x,
            mesh=mesh,
            num_microbatches=cfg.num_microbatches,
            rng=r_blocks,
        )
        return embed_head.apply(
            {"params": params["embed"]}, x, method="logits"
        )

    def token_nll(params, batch, *, rng=None, train=False):
        inputs = batch["tokens"][:, :-1]
        labels = batch["tokens"][:, 1:]
        logits = logits_fn(params, inputs, rng=rng, train=train)
        # Token-sharded (mesh wrapper): the Pallas CE called directly on
        # data-sharded logits hits the partitioner's gather fallback —
        # same fix as the non-PP task (ops/cross_entropy.py docstring).
        # head_loss_fn (inside the pipe-manual 1F1B region) is measured
        # clean and stays direct.
        return mesh_cross_entropy_per_example(
            logits, labels, mesh=mesh, fused=cfg.fused_ce
        )

    def loss_fn(params, model_state, batch, *, rng, train):
        if train and cfg.pipeline_schedule == "1f1b":
            # 1F1B: loss computed inside the pipeline schedule (the
            # microbatch backward starts as soon as its forward exits);
            # embed encode/decode stay outside and differentiate through
            # the custom_vjp.
            inputs = batch["tokens"][:, :-1]
            labels = batch["tokens"][:, 1:]
            dropout = cfg.dropout > 0 and rng is not None
            r_embed, r_blocks = (
                jax.random.split(rng) if dropout else (None, None)
            )
            x = embed_head.apply(
                {"params": params["embed"]},
                inputs,
                dropout,
                method="encode",
                rngs={"dropout": r_embed} if dropout else None,
            )
            run = run_1f1b_drop if dropout else run_1f1b_plain
            loss = run(
                split_stages(params[blocks_key]),
                params["embed"],
                x,
                labels,
                r_blocks,
            )
            return loss, {}, model_state
        nll = token_nll(params, batch, rng=rng, train=train)
        return jnp.mean(nll), {}, model_state

    def eval_fn(params, model_state, batch):
        per_example = jnp.mean(token_nll(params, batch), axis=-1)
        mask = batch.get("mask")
        return {
            "nll": weighted_mean(per_example, mask),
            "weight": jnp.sum(mask)
            if mask is not None
            else jnp.float32(per_example.shape[0]),
        }

    # Stage dim over `pipe` on every blocks leaf; with mesh_model > 1
    # the transformed base rules additionally lay the Megatron TP layout
    # on the stacked weights. Derived from GPT2_RULES — prepend the
    # stage dim, drop the fsdp entry (param-sharding over fsdp is the
    # non-PP path's ZeRO-3 trade; untested under PP) — so the two
    # layouts cannot drift (a size-1 model axis is filtered out at
    # sharding time, keeping these safe on pure-PP meshes).
    _Pp, _Ff = AxisNames.PIPE, AxisNames.FSDP

    def _stage_spec(spec: P) -> P:
        return P(_Pp, *(None if a == _Ff else a for a in spec))

    rules = ShardingRules(
        [
            ("^" + blocks_key + "/" + pat.pattern, _stage_spec(spec))
            for pat, spec in transformer.GPT2_RULES.rules
        ]
        + [("^" + blocks_key + "/", P(_Pp))]
    )
    return Task(
        name="gpt2_124m_pp",
        init_fn=init_fn,
        loss_fn=loss_fn,
        make_optimizer=optimizers.adamw_cosine,
        sharding_rules=rules,
        eval_fn=eval_fn,
    )


def datasets(cfg: Gpt2Config):
    return (
        load_lm_tokens(
            cfg.data_dir, "train", seq_len=cfg.seq_len, vocab_size=cfg.vocab_size
        ),
        eval_dataset(cfg),
    )


def eval_dataset(cfg: Gpt2Config):
    import logging
    import os

    has_val = bool(cfg.data_dir) and any(
        os.path.exists(os.path.join(cfg.data_dir, "val" + ext))
        for ext in (".bin", ".npy", ".txt")
    )
    if cfg.data_dir and not has_val:
        logging.getLogger(__name__).warning(
            "--data_dir=%s has no val.{bin,npy,txt}; eval runs on SYNTHETIC "
            "data — reported nll is not a real validation score",
            cfg.data_dir,
        )
    return load_lm_tokens(
        cfg.data_dir if has_val else "",
        "val",
        seq_len=cfg.seq_len,
        vocab_size=cfg.vocab_size,
    )
