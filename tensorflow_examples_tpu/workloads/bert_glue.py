"""BERT-base GLUE fine-tune workload (BASELINE.json:configs[3]).

Reference behavior: fine-tune a pretrained BERT-base encoder on GLUE
tasks under ``MultiWorkerMirroredStrategy`` (multi-host DP) with AdamW +
warmup-linear-decay and per-task metrics (MCC/F1/accuracy). Here the
multi-host machinery is the mesh (a multi-host run is the same code with
more devices on the ``data`` axis — core/distributed.py bootstraps
processes), pretrained weights import from HF (models/hf_import.py), and
the non-composable GLUE metrics aggregate exactly through the shared
eval loop via confusion/moment rates (ops/glue_metrics.py).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from tensorflow_examples_tpu.data.sources import GLUE_NUM_LABELS, load_glue
from tensorflow_examples_tpu.models import bert
from tensorflow_examples_tpu.ops import glue_metrics
from tensorflow_examples_tpu.ops.losses import softmax_cross_entropy, weighted_mean
from tensorflow_examples_tpu.train import Task, TrainConfig
from tensorflow_examples_tpu.train import optimizers


@dataclasses.dataclass
class BertGlueConfig(TrainConfig):
    # Standard BERT fine-tune recipe: 3 epochs, batch 32, lr 2e-5,
    # 10% warmup, AdamW(b2=0.999, eps=1e-6), linear decay.
    task: str = "sst2"
    seq_len: int = 128
    vocab_size: int = 30522
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    d_ff: int = 3072
    dropout: float = 0.1
    attention: str = "xla"  # xla | flash (Pallas + key-bias padding mask)
    pretrained: str = ""  # local HF BERT path; "" = random init

    global_batch_size: int = 32
    train_steps: int = 6000
    warmup_steps: int = 600
    learning_rate: float = 2e-5
    weight_decay: float = 0.01
    grad_clip_norm: float = 1.0
    eval_every: int = 1000
    checkpoint_every: int = 1000
    log_every: int = 50


def model_config(cfg: BertGlueConfig) -> bert.BertConfig:
    return bert.BertConfig(
        vocab_size=cfg.vocab_size,
        num_layers=cfg.num_layers,
        num_heads=cfg.num_heads,
        d_model=cfg.d_model,
        d_ff=cfg.d_ff,
        dropout=cfg.dropout,
        attention=cfg.attention,
    )


def make_task(cfg: BertGlueConfig, mesh=None) -> Task:
    num_labels = GLUE_NUM_LABELS[cfg.task]
    regression = num_labels == 1
    model = bert.BertClassifier(
        model_config(cfg), num_labels=num_labels, mesh=mesh
    )

    def init_fn(rng):
        import jax

        dummy = jnp.zeros((1, cfg.seq_len), jnp.int32)
        variables = dict(model.init({"params": rng}, dummy))
        if cfg.pretrained:
            from tensorflow_examples_tpu.models.hf_import import import_bert

            _, params = import_bert(cfg.pretrained)
            # Keep the fresh head if the checkpoint lacks a matching one.
            imported = jax.tree.map(jnp.asarray, params)
            if (
                "classifier" not in imported
                or imported["classifier"]["kernel"].shape
                != variables["params"]["classifier"]["kernel"].shape
            ):
                imported["classifier"] = variables["params"]["classifier"]
            variables["params"] = imported
        return variables

    def forward(params, batch, *, rng, train):
        return model.apply(
            {"params": params},
            batch["tokens"],
            batch["attention_mask"],
            batch["token_type_ids"],
            train=train,
            rngs={"dropout": rng} if train else None,
        )

    def loss_fn(params, model_state, batch, *, rng, train):
        logits = forward(params, batch, rng=rng, train=train)
        if regression:
            pred = logits[:, 0]
            loss = jnp.mean((pred - batch["label"]) ** 2)
            metrics = {}
        else:
            loss = softmax_cross_entropy(logits, batch["label"])
            acc = jnp.mean(
                (jnp.argmax(logits, -1) == batch["label"]).astype(jnp.float32)
            )
            metrics = {"accuracy": acc}
        return loss, metrics, model_state

    def eval_fn(params, model_state, batch):
        logits = forward(params, batch, rng=None, train=False)
        w = batch.get("mask")
        if regression:
            pred = logits[:, 0]
            m = glue_metrics.moment_means(pred, batch["label"], w)
            m["loss"] = weighted_mean((pred - batch["label"]) ** 2, w)
        else:
            pred = jnp.argmax(logits, -1)
            m = {
                "accuracy": weighted_mean(
                    (pred == batch["label"]).astype(jnp.float32), w
                ),
                "loss": softmax_cross_entropy(logits, batch["label"], weights=w),
            }
            if num_labels == 2:
                m.update(glue_metrics.confusion_rates(pred, batch["label"], w))
        m["weight"] = (
            jnp.sum(w) if w is not None else jnp.float32(batch["tokens"].shape[0])
        )
        return m

    def eval_finalize(means: dict) -> dict:
        out = dict(means)
        if regression:
            out["pearson"] = glue_metrics.pearson_from_moments(means)
            for k in ("x", "y", "xx", "yy", "xy"):
                out.pop(k, None)
        elif num_labels == 2:
            if cfg.task == "cola":
                out["mcc"] = glue_metrics.mcc_from_rates(means)
            if cfg.task in ("mrpc", "qqp"):
                out["f1"] = glue_metrics.f1_from_rates(means)
            for k in ("tp", "fp", "fn", "tn"):
                out.pop(k, None)
        return out

    return Task(
        name=f"bert_glue_{cfg.task}",
        init_fn=init_fn,
        loss_fn=loss_fn,
        make_optimizer=optimizers.adamw_linear,
        sharding_rules=bert.BERT_RULES,
        eval_fn=eval_fn,
        eval_finalize=eval_finalize,
    )


def datasets(cfg: BertGlueConfig):
    return (
        load_glue(
            cfg.data_dir, cfg.task, "train",
            seq_len=cfg.seq_len, vocab_size=cfg.vocab_size,
        ),
        eval_dataset(cfg),
    )


def eval_dataset(cfg: BertGlueConfig):
    import logging

    kw = dict(seq_len=cfg.seq_len, vocab_size=cfg.vocab_size)
    has_val = _has_split(cfg, "validation")
    if cfg.data_dir and not has_val:
        logging.getLogger(__name__).warning(
            "--data_dir=%s has no %s_validation.npz; eval runs on SYNTHETIC "
            "data — reported metrics are not real GLUE scores",
            cfg.data_dir,
            cfg.task,
        )
    return load_glue(cfg.data_dir if has_val else "", cfg.task, "validation", **kw)


def _has_split(cfg: BertGlueConfig, split: str) -> bool:
    import os

    return bool(cfg.data_dir) and os.path.exists(
        os.path.join(cfg.data_dir, f"{cfg.task}_{split}.npz")
    )
