"""MNIST dense-MLP workload (BASELINE.json:configs[0]).

Reference behavior: ``tf.keras`` Sequential MLP, sparse categorical
cross-entropy, single-host training with a simple eval pass. Here the
same capability on the shared TPU loop: jitted step, bf16 compute, batch
sharded over the mesh's data axes.
"""

from __future__ import annotations

import dataclasses

import jax

from tensorflow_examples_tpu.core.sharding import REPLICATED
from tensorflow_examples_tpu.data.sources import load_mnist
from tensorflow_examples_tpu.models.mlp import MLP
from tensorflow_examples_tpu.ops.losses import accuracy_metrics, softmax_cross_entropy
from tensorflow_examples_tpu.train import Task, TrainConfig
from tensorflow_examples_tpu.train import optimizers


@dataclasses.dataclass
class MnistConfig(TrainConfig):
    global_batch_size: int = 256
    train_steps: int = 2000
    learning_rate: float = 1e-3
    hidden: int = 128
    num_layers: int = 2
    dropout: float = 0.1


def make_task(cfg: MnistConfig, mesh=None) -> Task:
    model = MLP(
        features=(cfg.hidden,) * cfg.num_layers,
        num_classes=10,
        dropout_rate=cfg.dropout,
    )

    def init_fn(rng):
        import jax.numpy as jnp

        dummy = jnp.zeros((1, 28, 28, 1), jnp.float32)
        return model.init({"params": rng}, dummy)

    def loss_fn(params, model_state, batch, *, rng, train):
        logits = model.apply(
            {"params": params},
            batch["image"],
            train=train,
            rngs={"dropout": rng} if train else None,
        )
        loss = softmax_cross_entropy(logits, batch["label"])
        return loss, accuracy_metrics(logits, batch["label"]), model_state

    def eval_fn(params, model_state, batch):
        logits = model.apply({"params": params}, batch["image"], train=False)
        m = accuracy_metrics(logits, batch["label"], weights=batch["mask"])
        m["loss"] = softmax_cross_entropy(
            logits, batch["label"], weights=batch["mask"]
        )
        return m

    return Task(
        name="mnist_mlp",
        init_fn=init_fn,
        loss_fn=loss_fn,
        make_optimizer=optimizers.adam,
        sharding_rules=REPLICATED,
        eval_fn=eval_fn,
    )


def datasets(cfg: MnistConfig):
    return load_mnist(cfg.data_dir, "train"), load_mnist(cfg.data_dir, "test")


def eval_dataset(cfg: MnistConfig):
    return load_mnist(cfg.data_dir, "test")
