"""Workload definitions: one module per reference example.

Each module exposes ``make_task(config) -> Task`` plus dataset helpers;
the ``examples/<name>/train.py`` CLIs are thin shells over these
(preserving the reference's per-example entrypoint contract,
BASELINE.json:north_star).
"""
