"""CIFAR-10 ResNet-20 workload (BASELINE.json:configs[1]).

Reference behavior: ResNet-20 (3 stages × n blocks) under a single-device
``tf.distribute`` strategy, crop/flip augmentation, cosine (or step) LR
with SGD+momentum. Here: the same capability on the shared TPU loop —
jitted fused step, bf16 compute with f32 BN/head, sync-BN for free via
global-batch jit semantics, deterministic host-side augmentation.
"""

from __future__ import annotations

import dataclasses

from tensorflow_examples_tpu.core.sharding import REPLICATED
from tensorflow_examples_tpu.data.augment import cifar_augment
from tensorflow_examples_tpu.data.sources import load_cifar10
from tensorflow_examples_tpu.models.resnet import resnet20
from tensorflow_examples_tpu.ops.losses import accuracy_metrics, softmax_cross_entropy
from tensorflow_examples_tpu.train import Task, TrainConfig
from tensorflow_examples_tpu.train import optimizers


@dataclasses.dataclass
class Cifar10Config(TrainConfig):
    # Classic ResNet-20 recipe: batch 128, ~64k steps, SGD+momentum with
    # cosine decay from 0.1, weight decay 1e-4.
    global_batch_size: int = 128
    train_steps: int = 64000
    warmup_steps: int = 400
    learning_rate: float = 0.1
    weight_decay: float = 1e-4
    eval_every: int = 4000
    checkpoint_every: int = 4000
    augment: bool = True


def make_task(cfg: Cifar10Config, mesh=None) -> Task:
    model = resnet20(num_classes=10)

    def init_fn(rng):
        import jax.numpy as jnp

        dummy = jnp.zeros((1, 32, 32, 3), jnp.float32)
        return model.init({"params": rng}, dummy)

    def loss_fn(params, model_state, batch, *, rng, train):
        logits, new_vars = model.apply(
            {"params": params, **model_state},
            batch["image"],
            train=train,
            mutable=["batch_stats"] if train else [],
        )
        loss = softmax_cross_entropy(logits, batch["label"])
        new_model_state = dict(new_vars) if train else model_state
        return loss, accuracy_metrics(logits, batch["label"]), new_model_state

    def eval_fn(params, model_state, batch):
        logits = model.apply(
            {"params": params, **model_state}, batch["image"], train=False
        )
        m = accuracy_metrics(logits, batch["label"], weights=batch["mask"])
        m["loss"] = softmax_cross_entropy(
            logits, batch["label"], weights=batch["mask"]
        )
        return m

    return Task(
        name="cifar10_resnet20",
        init_fn=init_fn,
        loss_fn=loss_fn,
        make_optimizer=optimizers.sgd_momentum_cosine,
        sharding_rules=REPLICATED,
        eval_fn=eval_fn,
    )


def datasets(cfg: Cifar10Config):
    # Real data keeps uint8 pixels on the train split so augmentation
    # (pad/crop/flip/normalize) runs fused in the C++ host library.
    train = load_cifar10(cfg.data_dir, "train", normalized=not cfg.augment)
    return train, load_cifar10(cfg.data_dir, "test")


def eval_dataset(cfg: Cifar10Config):
    return load_cifar10(cfg.data_dir, "test")


def train_augment(cfg: Cifar10Config):
    return cifar_augment if cfg.augment else None
