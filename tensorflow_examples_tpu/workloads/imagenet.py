"""ResNet-50 ImageNet workload (BASELINE.json:configs[2]).

The reference's throughput workload: tf.data input pipeline with device
prefetch, data-parallel across 8 chips, ResNet-50, label smoothing,
SGD+momentum (LARS for large batch). Here: the same capability on the
shared loop — streaming tf.data/TFRecord host pipeline (or synthetic
fallback) feeding the async device-prefetch queue, one jitted step with
sync-BN semantics for free (global-batch jit), examples/sec as the
north-star metric (BASELINE.json:metric).
"""

from __future__ import annotations

import dataclasses

from tensorflow_examples_tpu.core.sharding import REPLICATED
from tensorflow_examples_tpu.data import imagenet as imagenet_data
from tensorflow_examples_tpu.models import resnet
from tensorflow_examples_tpu.ops.losses import accuracy_metrics, softmax_cross_entropy
from tensorflow_examples_tpu.train import Task, TrainConfig
from tensorflow_examples_tpu.train import optimizers


@dataclasses.dataclass
class ImagenetConfig(TrainConfig):
    # 90-epoch recipe at batch 1024: lr 0.4 (= 0.1 · bs/256) cosine with
    # 5-epoch warmup, wd 1e-4, label smoothing 0.1.
    image_size: int = 224
    num_classes: int = 1000
    model: str = "resnet50"  # resnet18|34|50|101|152
    label_smoothing: float = 0.1
    optimizer: str = "sgd"  # sgd | lars (large-batch)
    global_batch_size: int = 1024
    train_steps: int = 112590  # 90 epochs · 1.28M / 1024
    warmup_steps: int = 6255
    learning_rate: float = 0.4
    weight_decay: float = 1e-4
    eval_every: int = 5000
    checkpoint_every: int = 5000
    eval_batches: int = 8  # synthetic-eval length (real eval: full split)
    # Deterministic, checkpoint-resumable TFRecord input (exact-resume:
    # a restored run replays the uninterrupted run's batch sequence
    # bit-exactly — SURVEY.md §4/§5b). Costs the order-preserving
    # interleave; set False for maximum-throughput non-resumable input.
    deterministic_input: bool = True


def make_task(cfg: ImagenetConfig, mesh=None) -> Task:
    builder = getattr(resnet, cfg.model, None)
    if builder is None:
        raise ValueError(
            f"unknown --model={cfg.model}; one of resnet18/34/50/101/152"
        )
    model = builder(num_classes=cfg.num_classes)

    def init_fn(rng):
        import jax.numpy as jnp

        dummy = jnp.zeros((1, cfg.image_size, cfg.image_size, 3), jnp.float32)
        return model.init({"params": rng}, dummy)

    def loss_fn(params, model_state, batch, *, rng, train):
        logits, new_vars = model.apply(
            {"params": params, **model_state},
            batch["image"],
            train=train,
            mutable=["batch_stats"] if train else [],
        )
        loss = softmax_cross_entropy(
            logits, batch["label"], label_smoothing=cfg.label_smoothing
        )
        new_model_state = dict(new_vars) if train else model_state
        return loss, accuracy_metrics(logits, batch["label"]), new_model_state

    def eval_fn(params, model_state, batch):
        logits = model.apply(
            {"params": params, **model_state}, batch["image"], train=False
        )
        m = accuracy_metrics(logits, batch["label"], weights=batch["mask"], top5=True)
        m["loss"] = softmax_cross_entropy(
            logits, batch["label"], weights=batch["mask"]
        )
        return m

    return Task(
        name=f"imagenet_{cfg.model}",
        init_fn=init_fn,
        loss_fn=loss_fn,
        make_optimizer=(
            optimizers.lars if cfg.optimizer == "lars" else optimizers.sgd_momentum_cosine
        ),
        sharding_rules=REPLICATED,
        eval_fn=eval_fn,
    )


# Streaming pipeline protocol (train/cli.py): tf.data TFRecords when
# --data_dir holds `train-*` shards, synthetic stream otherwise.


def train_iter_is_per_host(cfg: ImagenetConfig) -> bool:
    """Pipeline protocol (train/cli.py): the TFRecord path shards files
    by host, so each host yields only its global_batch/P rows — fed via
    put_local_batch. The synthetic stream is seeded identically on all
    hosts (global view)."""
    return imagenet_data.has_tfrecords(cfg.data_dir, "train")


def make_train_iter(cfg: ImagenetConfig, start_step: int):
    import jax

    if imagenet_data.has_tfrecords(cfg.data_dir, "train"):
        nproc = jax.process_count()
        if cfg.global_batch_size % nproc:
            raise ValueError(
                f"global_batch_size {cfg.global_batch_size} not divisible "
                f"by process_count {nproc}"
            )
        # Per-host rows only: each host decodes exactly the examples its
        # own devices consume (global-view feeding would decode the full
        # global batch on EVERY host and discard (P-1)/P of the work —
        # on the benchmark-critical input pipeline).
        if getattr(cfg, "input_workers", 0) > 0:
            # ISSUE 6 hot path: sharded parallel readers + background
            # decode/augment workers (deterministic AND exactly
            # resumable by construction — every stream position is a
            # pure function of (seed, start_step)).
            return imagenet_data.parallel_tfrecord_iter(
                cfg.data_dir,
                "train",
                cfg.global_batch_size // nproc,
                train=True,
                image_size=cfg.image_size,
                seed=cfg.seed,
                num_readers=max(getattr(cfg, "input_readers", 2), 1),
                num_workers=cfg.input_workers,
                start_step=start_step,
            )
        return imagenet_data.tfrecord_iter(
            cfg.data_dir,
            "train",
            cfg.global_batch_size // nproc,
            train=True,
            image_size=cfg.image_size,
            seed=cfg.seed,
            start_step=start_step,
            exact=cfg.deterministic_input,
        )
    return imagenet_data.synthetic_train_iter(
        cfg.global_batch_size,
        image_size=cfg.image_size,
        num_classes=cfg.num_classes,
        seed=cfg.seed,
        start_step=start_step,
    )


def make_eval_iter(cfg: ImagenetConfig):
    import jax

    # Per-host eval semantics (Trainer.evaluate(per_host=True) in
    # multi-process runs): each host reads its own shard and yields
    # global_batch / process_count rows per batch; the jitted step's
    # global reduction merges hosts exactly.
    nproc = jax.process_count()
    batch = max((cfg.eval_batch_size or cfg.global_batch_size) // nproc, 1)
    if imagenet_data.has_tfrecords(cfg.data_dir, "validation"):
        return imagenet_data.tfrecord_iter(
            cfg.data_dir,
            "validation",
            batch,
            train=False,
            image_size=cfg.image_size,
        )
    return imagenet_data.synthetic_eval_iter(
        batch,
        image_size=cfg.image_size,
        num_classes=cfg.num_classes,
        batches=cfg.eval_batches,
        seed=1 + jax.process_index(),
    )
