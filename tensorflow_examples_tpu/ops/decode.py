"""Flash-decode: Pallas KV-cache attention for autoregressive sampling.

TPU-native replacement for the naive decode path (SURVEY.md §2c kernels
layer, §5g long-context): the previous ``_decode_attend`` materialized a
``[q_len, max_len]`` score matrix against the FULL static cache every
step — quadratic HBM reads once training-scale contexts (4k–16k) meet a
static cache sized for them. This kernel reads only the cache blocks
that are actually populated:

- Grid is (batch·head, q-block, kv-block) like the training flash kernel
  (``ops/attention.py``), with the same online-softmax scratch carry.
  The KV extent of the grid is picked from a power-of-two bucket ladder
  by the populated length (``lax.switch`` over per-bucket compilations),
  so a single-token step through a huge cache SEQUENCES O(context)
  programs, not O(max_len).
- The *valid cache length* rides in as a scalar-prefetch operand
  (``pltpu.PrefetchScalarGridSpec``), so the KV BlockSpec index_map can
  see it: blocks past the last populated one (bucket overshoot) are
  clamped to the last valid index. Re-requesting the same block is a
  no-op for the Pallas pipeline — **no HBM traffic is issued for
  unpopulated cache blocks**, and ``pl.when`` guards skip their MXU
  work. A decode step at context length n reads O(n) cache bytes.
- Causality inside the populated region falls out of global positions:
  query row r sits at position length - q_len + r and sees cache slots
  ≤ its position; the final (partial) block is masked with iota.
- bf16 cache tiles upcast to f32 on the MXU (``preferred_element_type``)
  — same numerics policy as the training kernel.

No backward: decode is inference-only. Parity vs the XLA reference is
asserted in tests/test_kernels.py (interpret mode) and
tests_tpu/test_tpu_kernels.py (compiled, on the live chip).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tensorflow_examples_tpu.ops.attention import NEG_INF, _fit_block


def decode_attention_reference(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    length: jax.Array | int,
    *,
    sm_scale: float | None = None,
) -> jax.Array:
    """Plain-XLA masked cache attention; numerics reference for the kernel.

    q: [B, H, q_len, D] — the newly appended queries, occupying global
    positions ``length - q_len … length - 1``.
    k_cache / v_cache: [B, H, max_len, D]; slots ≥ ``length`` are garbage.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    q_len, max_len = q.shape[2], k_cache.shape[2]
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k_cache, preferred_element_type=jnp.float32
    ) * sm_scale
    pos = (length - q_len) + lax.broadcasted_iota(
        jnp.int32, (q_len, max_len), 0
    )
    col = lax.broadcasted_iota(jnp.int32, (q_len, max_len), 1)
    s = jnp.where(col <= pos, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v_cache.dtype)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", p, v_cache, preferred_element_type=jnp.float32
    ).astype(q.dtype)


def _decode_kernel(
    len_ref, q_ref, k_ref, v_ref, o_ref, m_s, l_s, acc_s, *, sm_scale, q_len
):
    block_q, block_kv = q_ref.shape[1], k_ref.shape[1]
    i, j = pl.program_id(1), pl.program_id(2)
    length = len_ref[0]
    # Global position of this q block's first row (cache slot it occupies).
    q_pos = (length - q_len) + i * block_q
    kv_offset = j * block_kv

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    # KV blocks entirely after this q block's last row position contribute
    # nothing (that also covers every unpopulated block: slot p < length
    # for all rows). The BlockSpec index_map has already clamped their
    # fetches, so skipped iterations issue neither DMA nor MXU work.
    def _attend():
        q = q_ref[0].astype(jnp.float32) * sm_scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_kv]
        row = q_pos + lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
        col = kv_offset + lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1
        )
        s = jnp.where(col <= row, s, NEG_INF)
        m = m_s[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_s[...] = m_new
        l_s[...] = l_s[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_s[...] = acc_s[...] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )

    pl.when(kv_offset <= q_pos + block_q - 1)(_attend)

    @pl.when(j == pl.num_programs(2) - 1)
    def _finalize():
        l = jnp.maximum(l_s[...], 1e-30)
        o_ref[0] = (acc_s[...] / l).astype(o_ref.dtype)


@functools.lru_cache(maxsize=None)
def _make_decode(q_len, block_q, block_kv, interpret, kv_blocks):
    """One kernel variant iterating exactly ``kv_blocks`` KV programs.

    The public entry compiles a power-of-two LADDER of these (see
    ``flash_decode_attention``) and lax.switches on the populated block
    count, so per-step grid-sequencer work is bounded by ~2× the
    populated context rather than by ``max_len`` (VERDICT r3 item 4:
    the clamp already suppressed DMA + MXU for unpopulated blocks, but
    a 32k-slot cache still sequenced cdiv(32k, block) programs per
    single-token step). The kernel body is bucket-agnostic — finalize
    keys off ``pl.num_programs`` and the index clamp covers buckets
    that overshoot the populated length."""

    def call(q, k, v, length, sm_scale):
        bh, _, head_dim = q.shape
        # Partial trailing blocks are safe HERE (unlike the training
        # kernel): padded KV columns carry global indices ≥ max_len and
        # every real row's position is < max_len, so the causal mask
        # kills them; padded query rows are clipped on write-back.
        grid = (bh, pl.cdiv(q_len, block_q), kv_blocks)

        def kv_index(b, i, j, len_ref):
            # Clamp unpopulated blocks to the last populated one: the
            # pipeline sees an unchanged index and skips the copy.
            # (Index_maps receive scalar-prefetch refs AFTER the grid
            # indices — the kernel body receives them first.)
            last = (len_ref[0] - 1) // block_kv
            return (b, jnp.minimum(j, last), 0)

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, block_q, head_dim), lambda b, i, j, s: (b, i, 0)
                ),
                pl.BlockSpec((1, block_kv, head_dim), kv_index),
                pl.BlockSpec((1, block_kv, head_dim), kv_index),
            ],
            out_specs=pl.BlockSpec(
                (1, block_q, head_dim), lambda b, i, j, s: (b, i, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, head_dim), jnp.float32),
            ],
        )
        return pl.pallas_call(
            functools.partial(
                _decode_kernel, sm_scale=sm_scale, q_len=q_len
            ),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            interpret=interpret,
        )(jnp.reshape(length, (1,)).astype(jnp.int32), q, k, v)

    return call


def flash_decode_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    length: jax.Array | int,
    *,
    sm_scale: float | None = None,
    block_q: int | None = None,
    block_kv: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Attend ``q`` [B, H, q_len, D] over a static KV cache, reading only
    populated blocks.

    ``length`` (traced scalar ok) is the total populated cache length
    INCLUDING the q_len tokens just written; queries occupy global
    positions ``length - q_len … length - 1`` and each sees cache slots
    ≤ its own position. Works for both prefill (q_len = prompt length)
    and stepping (q_len = 1) — each distinct q_len compiles once, same
    contract as the caller's cache update.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, q_len, head_dim = q.shape
    max_len = k_cache.shape[2]
    if sm_scale is None:
        sm_scale = head_dim**-0.5
    # Prefer an exact divisor (zero padded work); arbitrary lengths fall
    # back to a 256 block with a partial tail — legal here, see kernel.
    try:
        block_q = block_q or _fit_block(256, q_len)
    except ValueError:
        block_q = 256
    try:
        block_kv = block_kv or _fit_block(256, max_len)
    except ValueError:
        block_kv = 256
    fold = lambda x: x.reshape(b * h, x.shape[2], head_dim)
    qf, kf, vf = fold(q), fold(k_cache), fold(v_cache)
    sm_scale = float(sm_scale)

    # Power-of-two bucket ladder over KV block counts: 1, 2, 4, …,
    # cdiv(max_len, block_kv). Each bucket is its own compiled kernel;
    # the populated block count picks the smallest sufficient bucket,
    # so a short-context step through a huge cache sequences O(context)
    # programs, not O(max_len) (VERDICT r3 item 4).
    total = pl.cdiv(max_len, block_kv)
    counts = []
    c = 1
    while c < total:
        counts.append(c)
        c *= 2
    counts.append(total)

    if isinstance(length, int):  # static length: exact bucket, no switch
        needed = -(-length // block_kv)
        # Clamp to the full-cache bucket for length > max_len, matching
        # the traced path (searchsorted clamps the same overrun); a
        # bare next() would raise an opaque StopIteration here.
        nkv = next((c for c in counts if c >= needed), total)
        call = _make_decode(q_len, block_q, block_kv, bool(interpret), nkv)
        out = call(qf, kf, vf, length, sm_scale)
        return out.reshape(b, h, q_len, head_dim)

    if len(counts) == 1:
        call = _make_decode(
            q_len, block_q, block_kv, bool(interpret), counts[0]
        )
        out = call(qf, kf, vf, length, sm_scale)
        return out.reshape(b, h, q_len, head_dim)

    needed = lax.div(
        jnp.asarray(length, jnp.int32) + (block_kv - 1), block_kv
    )
    idx = jnp.searchsorted(
        jnp.asarray(counts, jnp.int32), needed, side="left"
    )
    branches = [
        (lambda f: lambda a, kk, vv, ln: f(a, kk, vv, ln, sm_scale))(
            _make_decode(q_len, block_q, block_kv, bool(interpret), nkv)
        )
        for nkv in counts
    ]
    out = lax.switch(idx, branches, qf, kf, vf, length)
    return out.reshape(b, h, q_len, head_dim)
