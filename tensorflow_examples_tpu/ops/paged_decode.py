"""Fused Pallas paged-decode attention: block-table gather + varlen
masked attention in ONE kernel (ISSUE 11 tentpole).

The paged serving decode step (``serving/engine._paged_decode_forward``)
previously ran two XLA programs per layer: a gather that materializes
each slot's contiguous cache view out of the block pool
(``kv_cache.gather_block_kv`` — O(bucket) HBM *writes* per step for
bytes that are read exactly once), then the masked attention over the
gathered copy. This kernel folds both: the KV BlockSpec index map reads
each slot's *block table* directly (scalar prefetch), so the Pallas
pipeline DMAs physical cache blocks straight from the pool into VMEM —
no materialized per-slot copy, half the HBM traffic, one kernel launch.

Contract (the per-slot generalization of
``ops/decode.flash_decode_attention``, which covers the scalar-length
prefill case):

* ``q`` [S, H, D] — one new query per slot, its own K/V already
  written through the block table.
* ``k_blocks`` / ``v_blocks`` [NB, H, BS, D] — ONE layer's physical
  block pools (``serving/paged_kv.PagedKVPool`` layout).
* ``lengths`` [S] int32 — populated lengths INCLUDING the new token;
  slot s attends columns ``< lengths[s]``, nothing else.
* ``block_tables`` [S, nb] int32 — logical->physical block map for the
  active KV bucket (``nb = bucket // BS``); entries past a slot's
  allocation point at the null block, whose rows the length mask never
  admits.
* ``k_scale`` / ``v_scale`` [NB, H, BS] f32 (optional) — the int8
  pools' blockwise per-row scales (``core/precision``): passing them
  selects the **dequant-in-kernel** path, so a quantized cache is read
  at 1 byte/element from HBM and widened to f32 only in VMEM — the
  whole point of int8 KV on a bandwidth-bound step.

Grid is (slot, head, kv-block) with the familiar online-softmax scratch
carry (``ops/attention.py``). Unpopulated trailing blocks are clamped
to the last populated index in the index map — a repeated index is a
no-op for the Pallas pipeline, so **no HBM traffic is issued for blocks
past a slot's length** — and ``pl.when`` skips their compute.

The XLA gather path (``kv_cache.varlen_decode_attention`` with
``block_tables=``) stays in-tree as the reference oracle:
tests/test_kernels.py pins this kernel against it element-wise in
interpret mode (tier-1, CPU) across slot-length/block-table edge cases,
and the engine keeps it selectable (``ServeConfig.attention="xla"``).
No backward: decode is inference-only.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tensorflow_examples_tpu.ops.attention import NEG_INF


def _paged_decode_kernel(
    len_ref, tbl_ref, q_ref, k_ref, v_ref, *rest, sm_scale, block_size,
    quantized,
):
    if quantized:
        ksc_ref, vsc_ref, o_ref, m_s, l_s, acc_s = rest
    else:
        o_ref, m_s, l_s, acc_s = rest
    s, j = pl.program_id(0), pl.program_id(2)
    length = len_ref[s]
    col0 = j * block_size

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    # Blocks at or past the slot's length contribute nothing; their
    # fetch was already clamped to the last populated block in the
    # index map (no DMA), and this guard skips their MXU work.
    def _attend():
        q = q_ref[0].astype(jnp.float32) * sm_scale        # [1, D]
        k = k_ref[0, 0].astype(jnp.float32)                # [BS, D]
        v = v_ref[0, 0].astype(jnp.float32)
        if quantized:
            k = k * ksc_ref[0, 0].astype(jnp.float32)[:, None]
            v = v * vsc_ref[0, 0].astype(jnp.float32)[:, None]
        scores = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [1, BS]
        col = col0 + lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1
        )
        scores = jnp.where(col < length, scores, NEG_INF)
        m = m_s[...]
        m_new = jnp.maximum(m, jnp.max(scores, axis=1, keepdims=True))
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m - m_new)
        m_s[...] = m_new
        l_s[...] = l_s[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_s[...] = acc_s[...] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )

    pl.when(col0 < length)(_attend)

    @pl.when(j == pl.num_programs(2) - 1)
    def _finalize():
        # An empty slot (length 0, every block skipped) divides by the
        # epsilon and writes ~0 — discarded garbage, never NaN.
        l = jnp.maximum(l_s[...], 1e-30)
        o_ref[0] = (acc_s[...] / l).astype(o_ref.dtype)


@functools.lru_cache(maxsize=None)
def _make_paged_decode(num_slots, num_heads, nb, block_size, head_dim,
                       quantized, interpret):
    """One compiled variant per (slots, heads, table width, block
    geometry, quantization, interpret) — the engine's KV bucket ladder
    keys the table width, mirroring the dense decode rungs."""

    def kv_index(s, h, j, len_ref, tbl_ref):
        # Clamp unpopulated blocks to the last populated one: the
        # pipeline sees an unchanged physical index and skips the copy.
        last = jnp.maximum((len_ref[s] - 1) // block_size, 0)
        return (tbl_ref[s, jnp.minimum(j, last)], h, 0, 0)

    def sc_index(s, h, j, len_ref, tbl_ref):
        last = jnp.maximum((len_ref[s] - 1) // block_size, 0)
        return (tbl_ref[s, jnp.minimum(j, last)], h, 0)

    in_specs = [
        pl.BlockSpec((1, 1, head_dim), lambda s, h, j, ln, tb: (s, h, 0)),
        pl.BlockSpec((1, 1, block_size, head_dim), kv_index),
        pl.BlockSpec((1, 1, block_size, head_dim), kv_index),
    ]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, block_size), sc_index),
            pl.BlockSpec((1, 1, block_size), sc_index),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(num_slots, num_heads, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, head_dim), lambda s, h, j, ln, tb: (s, h, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, head_dim), jnp.float32),
        ],
    )

    def call(q, k_blocks, v_blocks, lengths, tables, scales, sm_scale):
        kernel = functools.partial(
            _paged_decode_kernel,
            sm_scale=sm_scale,
            block_size=block_size,
            quantized=quantized,
        )
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            interpret=interpret,
        )(lengths, tables, q, k_blocks, v_blocks, *scales)

    return call


def paged_decode_attention(
    q: jax.Array,
    k_blocks: jax.Array,
    v_blocks: jax.Array,
    lengths: jax.Array,
    block_tables: jax.Array,
    *,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    sm_scale: float | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Single-token per-slot attention straight through the block
    table; see the module docstring for the full contract. Returns
    [S, H, D] in ``q.dtype``."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    num_slots, num_heads, head_dim = q.shape
    _, _, block_size, _ = k_blocks.shape
    nb = block_tables.shape[1]
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale, or neither")
    quantized = k_scale is not None
    if sm_scale is None:
        sm_scale = head_dim ** -0.5
    call = _make_paged_decode(
        num_slots, num_heads, nb, block_size, head_dim, quantized,
        bool(interpret),
    )
    scales = (k_scale, v_scale) if quantized else ()
    return call(
        q, k_blocks, v_blocks,
        jnp.asarray(lengths, jnp.int32),
        jnp.asarray(block_tables, jnp.int32),
        scales, float(sm_scale),
    )


def paged_decode_reference(
    q: jax.Array,
    k_blocks: jax.Array,
    v_blocks: jax.Array,
    lengths: jax.Array,
    block_tables: jax.Array,
    *,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    sm_scale: float | None = None,
) -> jax.Array:
    """The XLA gather-path oracle the kernel is pinned against: exactly
    what the engine runs under ``attention="xla"`` — dequantize (int8)
    or gather (fp) by table, then ``varlen_decode_attention``."""
    from tensorflow_examples_tpu.serving.kv_cache import (
        varlen_decode_attention,
    )

    if k_scale is not None:
        from tensorflow_examples_tpu.core.precision import (
            dequantize_int8_rows,
        )

        s, nb = block_tables.shape
        _, h, bs, d = k_blocks.shape

        def gather(blocks, scales):
            g = dequantize_int8_rows(
                blocks[block_tables], scales[block_tables], q.dtype
            )
            return g.transpose(0, 2, 1, 3, 4).reshape(s, h, nb * bs, d)

        return varlen_decode_attention(
            q, gather(k_blocks, k_scale), gather(v_blocks, v_scale),
            lengths, sm_scale=sm_scale,
        )
    return varlen_decode_attention(
        q, k_blocks, v_blocks, lengths, sm_scale=sm_scale,
        block_tables=block_tables,
    )
