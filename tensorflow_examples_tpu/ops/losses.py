"""Losses and classification metrics (pure-XLA reference implementations).

Mirrors the reference's ``SparseCategoricalCrossentropy`` /
``keras.metrics`` usage (SURVEY.md §2a). The fused Pallas cross-entropy in
``ops.cross_entropy`` shares these signatures; tests compare the two.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def select_label(values: jax.Array, labels: jax.Array) -> jax.Array:
    """``values[..., C]`` at ``labels[...]`` WITHOUT a gather.

    ``jnp.take_along_axis`` lowers to an HLO gather, and XLA's SPMD
    partitioner handles that gather via its while-loop fallback that
    ALL-GATHERS the operand across the sharded token axis — measured as
    five ``[tokens, vocab]`` data-axis all-gathers in the dp2×model4
    train-step census (tools/ep_census.py, round 4). The one-hot mask +
    reduce below fuses into a single partition-friendly reduction on
    every backend, sharded or not; the extra O(n·C) elementwise work is
    noise next to the log_softmax that precedes it."""
    iota = lax.broadcasted_iota(jnp.int32, values.shape, values.ndim - 1)
    return jnp.sum(
        jnp.where(iota == labels[..., None], values, 0), axis=-1
    )


def weighted_mean(values: jax.Array, weights: jax.Array | None) -> jax.Array:
    """Weighted mean with a padded-batch-safe denominator (min 1.0)."""
    values = values.astype(jnp.float32)
    if weights is None:
        return jnp.mean(values)
    weights = weights.astype(jnp.float32)
    return jnp.sum(values * weights) / jnp.maximum(jnp.sum(weights), 1.0)


def softmax_cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    *,
    label_smoothing: float = 0.0,
    weights: jax.Array | None = None,
) -> jax.Array:
    """Mean cross-entropy over (optionally weighted) examples.

    logits: [..., C] float; labels: [...] int. Computed in f32 regardless
    of input dtype (bf16 logits are fine; the logsumexp runs in f32).
    """
    logits = logits.astype(jnp.float32)
    num_classes = logits.shape[-1]
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    nll = -select_label(log_probs, labels)
    if label_smoothing > 0.0:
        smooth = -jnp.mean(log_probs, axis=-1)
        nll = (1.0 - label_smoothing) * nll + label_smoothing * smooth
    return weighted_mean(nll, weights)


def accuracy_metrics(
    logits: jax.Array,
    labels: jax.Array,
    weights: jax.Array | None = None,
    *,
    top5: bool = False,
) -> dict[str, jax.Array]:
    pred = jnp.argmax(logits, axis=-1)
    correct = (pred == labels).astype(jnp.float32)
    out = {"accuracy": weighted_mean(correct, weights)}
    if top5:
        # In-top-5 without a sort: count logits strictly above the label's.
        label_logit = select_label(logits, labels)[..., None]
        rank = jnp.sum((logits > label_logit).astype(jnp.int32), axis=-1)
        out["top5_accuracy"] = weighted_mean((rank < 5).astype(jnp.float32), weights)
    if weights is not None:
        out["weight"] = jnp.sum(weights.astype(jnp.float32))
    return out
