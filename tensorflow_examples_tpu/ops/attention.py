"""Flash (blockwise) attention as a Pallas TPU kernel.

TPU-native replacement for the reference's CUDA ``tf.custom_op`` kernels
(BASELINE.json:north_star — "rewrite any tf.custom_op / CUDA kernels ...
as Pallas or XLA custom-calls"; SURVEY.md §2c, §5g). The kernel is the
single-device base for ring attention (``parallel/ring.py``): it computes
attention over KV *blocks* with an online softmax and can return the
per-row logsumexp, so ring hops merge kernel outputs exactly.

Design (TPU-first, not a CUDA translation):
- The grid is (batch·head, q-block, kv-block) with the KV dimension
  innermost: only ONE [block_kv, head_dim] K/V tile is VMEM-resident at
  a time, so sequence length is bounded by HBM, not VMEM — 16k–32k+
  tokens run with the same kernel. The online-softmax running
  (max, sum, acc) live in VMEM scratch carried across the inner KV grid
  steps; outputs are written on the last step.
- All matmuls run on the MXU in f32 accumulation
  (``preferred_element_type``), inputs may be bf16.
- Causal masking skips whole KV blocks above the diagonal (``pl.when``
  guards: no MXU work issued) and masks inside the diagonal block with
  ``broadcasted_iota``.
- Backward is the standard two-kernel split (dkv by KV block, dq by Q
  block) using the saved logsumexp, so the [seq, seq] score matrix is
  never materialized. When the forward exposed the logsumexp, its
  cotangent is exact: d(lse_i)/d(s_ij) = p_ij folds into
  ``ds = p · (dp − delta + dlse)``.

On non-TPU backends the same kernels run in Pallas interpret mode (used
by the CPU test suite) and an XLA reference implementation is provided
for numerics comparison.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    key_bias: jax.Array | None = None,
) -> jax.Array:
    """Plain-XLA attention; the numerics reference for the Pallas kernel.

    q, k, v: [batch, heads, seq, head_dim]. Softmax in f32.
    ``key_bias``: optional [batch, seq_kv] additive score bias (f32),
    broadcast over heads and query rows — the padding-mask shape.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * sm_scale
    if key_bias is not None:
        s = s + key_bias[:, None, None, :].astype(jnp.float32)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        row = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(row + (sk - sq) >= col, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    ).astype(q.dtype)


# --------------------------------------------------------------- forward


def _fwd_kernel(
    q_ref, k_ref, v_ref, *rest, sm_scale, causal, has_bias=False
):
    if has_bias:
        kb_ref, o_ref, lse_ref, m_s, l_s, acc_s = rest
    else:
        kb_ref = None
        o_ref, lse_ref, m_s, l_s, acc_s = rest
    block_q, head_dim = q_ref.shape[1], q_ref.shape[2]
    block_kv = k_ref.shape[1]
    qi, kj = pl.program_id(1), pl.program_id(2)
    num_kv = pl.num_programs(2)
    # Bottom-right-aligned causal diagonal: query i attends keys
    # <= i + (seq_kv - seq_q), matching attention_reference.
    offset = num_kv * block_kv - pl.num_programs(1) * block_q
    q_offset = qi * block_q
    kv_offset = kj * block_kv

    @pl.when(kj == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    # Causal: KV blocks entirely above the diagonal contribute nothing —
    # issue no MXU work for them.
    def _attend():
        q = q_ref[0].astype(jnp.float32) * sm_scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_kv]
        if has_bias:
            s = s + kb_ref[0]  # [1, block_kv] broadcasts over rows
        if causal:
            row = q_offset + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0
            )
            col = kv_offset + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1
            )
            s = jnp.where(row + offset >= col, s, NEG_INF)
        m = m_s[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_s[...] = m_new
        l_s[...] = l_s[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_s[...] = acc_s[...] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(q_offset + block_q - 1 + offset >= kv_offset)(_attend)
    else:
        _attend()

    @pl.when(kj == num_kv - 1)
    def _finalize():
        l = jnp.maximum(l_s[...], 1e-30)
        o_ref[0] = (acc_s[...] / l).astype(o_ref.dtype)
        lse_ref[0] = (m_s[...] + jnp.log(l)).astype(jnp.float32)


def _flash_fwd(
    q, k, v, sm_scale, causal, block_q, block_kv, interpret, kb=None, heads=1
):
    bh, seq_q, head_dim = q.shape
    seq_kv = k.shape[1]
    grid = (bh, seq_q // block_q, seq_kv // block_kv)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, has_bias=kb is not None
    )
    in_specs = [
        pl.BlockSpec((1, block_q, head_dim), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_kv, head_dim), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_kv, head_dim), lambda b, i, j: (b, j, 0)),
    ]
    args = (q, k, v)
    if kb is not None:
        # Carried as [batch, 1, seq_kv]: Mosaic constrains the LAST TWO
        # dims of a block to (8k, 128k) or the full array dim, so a
        # rank-2 [batch, seq_kv] bias with a (1, block_kv) block is
        # unlowerable whenever batch > 1 (compiled-TPU-only failure;
        # interpret mode never enforces it). Rank-3 puts batch outside
        # the constrained dims. Grid dim 0 is batch·heads, so the batch
        # row is program_id(0) // heads (static closure).
        in_specs.append(
            pl.BlockSpec((1, 1, block_kv), lambda b, i, j: (b // heads, 0, j))
        )
        args = args + (kb[:, None, :],)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_q, head_dim), q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return o, lse


# -------------------------------------------------------------- backward


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dlse_ref, *rest,
    sm_scale, causal, has_bias=False,
):
    if has_bias:
        kb_ref, dk_ref, dv_ref, dk_s, dv_s = rest
    else:
        kb_ref = None
        dk_ref, dv_ref, dk_s, dv_s = rest
    block_kv, head_dim = k_ref.shape[1], k_ref.shape[2]
    block_q = q_ref.shape[1]
    ki, qj = pl.program_id(1), pl.program_id(2)
    num_q = pl.num_programs(2)
    offset = pl.num_programs(1) * block_kv - num_q * block_q
    kv_offset = ki * block_kv
    q_offset = qj * block_q

    @pl.when(qj == 0)
    def _init():
        dk_s[...] = jnp.zeros_like(dk_s)
        dv_s[...] = jnp.zeros_like(dv_s)

    # Q blocks strictly above this KV block's diagonal see none of it.
    def _accumulate():
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]  # [block_q, 1]
        delta = delta_ref[0]
        dlse = dlse_ref[0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [block_q, block_kv]
        if has_bias:
            s = s + kb_ref[0]
        if causal:
            row = q_offset + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0
            )
            col = kv_offset + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1
            )
            s = jnp.where(row + offset >= col, s, NEG_INF)
        p = jnp.exp(s - lse)  # [block_q, block_kv]
        # dv += p^T do
        dv_s[...] += lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        # dp = do v^T ; ds = p * (dp - delta + dlse)
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta + dlse)
        # dk += ds^T q * scale
        dk_s[...] += sm_scale * lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(q_offset + block_q - 1 + offset >= kv_offset)(_accumulate)
    else:
        _accumulate()

    @pl.when(qj == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dlse_ref, *rest,
    sm_scale, causal, has_bias=False,
):
    if has_bias:
        kb_ref, dq_ref, dq_s = rest
    else:
        kb_ref = None
        dq_ref, dq_s = rest
    block_q, head_dim = q_ref.shape[1], q_ref.shape[2]
    block_kv = k_ref.shape[1]
    qi, kj = pl.program_id(1), pl.program_id(2)
    num_kv = pl.num_programs(2)
    offset = num_kv * block_kv - pl.num_programs(1) * block_q
    q_offset = qi * block_q
    kv_offset = kj * block_kv

    @pl.when(kj == 0)
    def _init():
        dq_s[...] = jnp.zeros_like(dq_s)

    def _accumulate():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]
        dlse = dlse_ref[0]
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if has_bias:
            s = s + kb_ref[0]
        if causal:
            row = q_offset + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0
            )
            col = kv_offset + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1
            )
            s = jnp.where(row + offset >= col, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta + dlse)
        dq_s[...] += sm_scale * jnp.dot(
            ds, k, preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(q_offset + block_q - 1 + offset >= kv_offset)(_accumulate)
    else:
        _accumulate()

    @pl.when(kj == num_kv - 1)
    def _finalize():
        dq_ref[0] = dq_s[...].astype(dq_ref.dtype)


def _flash_bwd(
    sm_scale, causal, block_q, block_kv, interpret, residuals, do, dlse,
    kb=None, heads=1,
):
    q, k, v, o, lse = residuals
    bh, seq_q, head_dim = q.shape
    seq_kv = k.shape[1]
    has_bias = kb is not None
    # delta_i = rowsum(do_i * o_i) — cheap, let XLA fuse it.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )
    if dlse is None:
        dlse = jnp.zeros_like(lse)
    dlse = dlse.astype(jnp.float32).reshape(lse.shape)

    q_blk = pl.BlockSpec((1, block_q, head_dim), lambda b, i, j: (b, j, 0))
    kv_blk = pl.BlockSpec((1, block_kv, head_dim), lambda b, i, j: (b, i, 0))
    vec_blk = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0))
    # Bias rides as [batch, 1, seq_kv] — see _flash_fwd's spec note on
    # Mosaic's last-two-dims block constraint. In the dkv grid the KV
    # block index is grid dim 1 (i).
    kb3 = kb[:, None, :] if has_bias else None
    kb_blk = pl.BlockSpec((1, 1, block_kv), lambda b, i, j: (b // heads, 0, i))
    in_specs = [q_blk, kv_blk, kv_blk, q_blk, vec_blk, vec_blk, vec_blk]
    args = (q, k, v, do, lse, delta, dlse)
    if has_bias:
        in_specs.append(kb_blk)
        args = args + (kb3,)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
            has_bias=has_bias,
        ),
        grid=(bh, seq_kv // block_kv, seq_q // block_q),
        in_specs=in_specs,
        out_specs=[kv_blk, kv_blk],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, head_dim), jnp.float32),
            pltpu.VMEM((block_kv, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(*args)

    q_blk = pl.BlockSpec((1, block_q, head_dim), lambda b, i, j: (b, i, 0))
    kv_blk = pl.BlockSpec((1, block_kv, head_dim), lambda b, i, j: (b, j, 0))
    vec_blk = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    kb_blk = pl.BlockSpec((1, 1, block_kv), lambda b, i, j: (b // heads, 0, j))
    in_specs = [q_blk, kv_blk, kv_blk, q_blk, vec_blk, vec_blk, vec_blk]
    args = (q, k, v, do, lse, delta, dlse)
    if has_bias:
        in_specs.append(kb_blk)
        args = args + (kb3,)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
            has_bias=has_bias,
        ),
        grid=(bh, seq_q // block_q, seq_kv // block_kv),
        in_specs=in_specs,
        out_specs=q_blk,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        interpret=interpret,
    )(*args)
    return dq, dk, dv


# ------------------------------------------------------------ public api


@functools.lru_cache(maxsize=None)
def _make_flash(causal, block_q, block_kv, interpret):
    # sm_scale stays out of the cache key (a swept/per-layer scale must
    # not leak a closure per value) — it rides through as a nondiff arg.
    @functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
    def flash(q, k, v, sm_scale):
        o, _ = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_kv, interpret)
        return o

    def fwd(q, k, v, sm_scale):
        o, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_kv, interpret)
        return o, (q, k, v, o, lse)

    def bwd(sm_scale, residuals, g):
        return _flash_bwd(
            sm_scale, causal, block_q, block_kv, interpret, residuals, g, None
        )

    flash.defvjp(fwd, bwd)
    return flash


@functools.lru_cache(maxsize=None)
def _make_flash_lse(causal, block_q, block_kv, interpret):
    """Variant returning (o, lse) with the exact lse cotangent in bwd —
    the building block ring attention merges across hops."""

    @functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
    def flash(q, k, v, sm_scale):
        o, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_kv, interpret)
        return o, lse

    def fwd(q, k, v, sm_scale):
        o, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_kv, interpret)
        return (o, lse), (q, k, v, o, lse)

    def bwd(sm_scale, residuals, g):
        do, dlse = g
        return _flash_bwd(
            sm_scale, causal, block_q, block_kv, interpret, residuals, do, dlse
        )

    flash.defvjp(fwd, bwd)
    return flash


@functools.lru_cache(maxsize=None)
def _make_flash_bias(causal, block_q, block_kv, interpret, heads):
    """Variant with a [batch, seq_kv] additive key bias (padding masks).

    The bias is treated as NON-differentiable data — it comes from an
    attention mask, and a ±NEG_INF bias has no meaningful gradient — so
    its cotangent is zeros; the bwd kernels still ADD it when
    recomputing the scores (p must match the forward's softmax).
    """

    @functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
    def flash(q, k, v, kb, sm_scale):
        o, _ = _flash_fwd(
            q, k, v, sm_scale, causal, block_q, block_kv, interpret,
            kb=kb, heads=heads,
        )
        return o

    def fwd(q, k, v, kb, sm_scale):
        o, lse = _flash_fwd(
            q, k, v, sm_scale, causal, block_q, block_kv, interpret,
            kb=kb, heads=heads,
        )
        return o, (q, k, v, o, lse, kb)

    def bwd(sm_scale, residuals, g):
        *res, kb = residuals
        dq, dk, dv = _flash_bwd(
            sm_scale, causal, block_q, block_kv, interpret, tuple(res), g,
            None, kb=kb, heads=heads,
        )
        return dq, dk, dv, jnp.zeros_like(kb)

    flash.defvjp(fwd, bwd)
    return flash


_DEFAULT_BLOCK = 256  # fastest measured end-to-end at GPT-2 shapes (v5e)


def _fit_block(target: int, seq: int) -> int:
    """Auto block size: the largest divisor of ``seq`` ≤ ``target`` that
    is a multiple of 128 (TPU lane width), else of 8 (sublane), else —
    no exact tiling exists — a clear error. When ``seq <= target`` the
    full sequence rides as one block (Pallas pads it internally); longer
    sequences with no multiple-of-8 divisor ≤ target (e.g. 4·odd
    lengths) are rejected rather than tiled with a partial tail, because
    these kernels' in-block masks index from block offsets and would
    read garbage KV columns past ``seq``. (The decode kernel in
    ops/decode.py masks by *global position* instead, so it accepts
    arbitrary lengths.)"""
    b = min(target, seq)
    if seq % b == 0:
        return b
    for cand in range(b - b % 128, 0, -128):
        if seq % cand == 0:
            return cand
    for cand in range(b - b % 8, 0, -8):
        if seq % cand == 0:
            return cand
    raise ValueError(
        f"sequence length {seq} has no multiple-of-8 block divisor "
        f"<= {target}; pad the sequence to a multiple of 8"
    )


@functools.lru_cache(maxsize=1)
def _tuned_block_table() -> dict:
    """Measured per-sequence block defaults from the on-chip sweep
    (tools/flash_tune.py → docs/tpu_sweeps/flash_block_table.json,
    committed with its evidence record). Maps str(seq) →
    {"block_q": B, "block_kv": B} from the fwd+bwd-optimal cell —
    training is the default consumer. Missing file (fresh checkout, no
    sweep banked yet) → empty table → the 256 fallback."""
    import json
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))),
        "docs", "tpu_sweeps", "flash_block_table.json",
    )
    try:
        with open(path) as f:
            return json.load(f).get("by_seq", {})
    except Exception:
        return {}


def _resolve_block(block: int | None, seq: int, which: str = "block_q") -> int:
    """Explicit block sizes are honored exactly (divisibility enforced,
    never silently overridden); None selects the swept per-seq default
    (falling back to the 256 target fit)."""
    if block is None:
        tuned = _tuned_block_table().get(str(seq))
        if tuned and tuned.get(which):
            return _fit_block(int(tuned[which]), seq)
        return _fit_block(_DEFAULT_BLOCK, seq)
    b = min(block, seq)
    if seq % b:
        raise ValueError(
            f"sequence length {seq} is not divisible by block size {b}; "
            "pass block sizes that divide it, or None for auto"
        )
    return b


def _prepare(q, k, v, causal, sm_scale, block_q, block_kv, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, seq_q, head_dim = q.shape
    seq_kv = k.shape[2]
    block_q = _resolve_block(block_q, seq_q, "block_q")
    block_kv = _resolve_block(block_kv, seq_kv, "block_kv")
    if causal and seq_q > seq_kv:
        # Rows with zero visible keys are degenerate (the reference
        # softmaxes an all-masked row into uniform weights; the kernel
        # would return 0) — reject rather than silently diverge.
        raise ValueError(
            f"causal attention requires seq_q ({seq_q}) <= seq_kv ({seq_kv})"
        )
    if sm_scale is None:
        sm_scale = head_dim**-0.5
    return float(sm_scale), block_q, block_kv, interpret


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int | None = None,
    block_kv: int | None = None,
    interpret: bool | None = None,
    key_bias: jax.Array | None = None,
) -> jax.Array:
    """Blockwise attention, differentiable; q/k/v: [batch, heads, seq, dim].

    Runs the Pallas TPU kernel on TPU; on other backends runs the same
    kernel in interpret mode (tests) unless ``interpret=False``.
    block_q/block_kv None = auto: 256-targeted (measured ~1.3% faster
    end-to-end than 128 on GPT-2 124M, b8 s1024, single v5e chip,
    within-run comparison), fitted down to a hardware-legal divisor of
    the sequence; explicit sizes are enforced exactly.

    ``key_bias``: optional [batch, seq_kv] additive score bias (f32),
    broadcast over heads and query rows — the padding-mask shape BERT
    needs. Non-differentiable (zero cotangent; it is mask data).
    """
    sm_scale, block_q, block_kv, interpret = _prepare(
        q, k, v, causal, sm_scale, block_q, block_kv, interpret
    )
    b, h, seq_q, head_dim = q.shape
    fold = lambda x: x.reshape(b * h, x.shape[2], head_dim)
    if key_bias is not None:
        if key_bias.shape != (b, k.shape[2]):
            raise ValueError(
                f"key_bias shape {key_bias.shape} != (batch, seq_kv) "
                f"({b}, {k.shape[2]})"
            )
        flash = _make_flash_bias(bool(causal), block_q, block_kv, interpret, h)
        out = flash(
            fold(q), fold(k), fold(v),
            key_bias.astype(jnp.float32), sm_scale,
        )
        return out.reshape(b, h, seq_q, head_dim)
    flash = _make_flash(bool(causal), block_q, block_kv, interpret)
    out = flash(fold(q), fold(k), fold(v), sm_scale)
    return out.reshape(b, h, seq_q, head_dim)


def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int | None = None,
    block_kv: int | None = None,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Like ``flash_attention`` but also returns the row logsumexp
    [batch, heads, seq] (f32), differentiable in both outputs. Partial
    attention results merge exactly via their lse — the primitive ring
    attention builds on."""
    sm_scale, block_q, block_kv, interpret = _prepare(
        q, k, v, causal, sm_scale, block_q, block_kv, interpret
    )
    b, h, seq_q, head_dim = q.shape
    flash = _make_flash_lse(bool(causal), block_q, block_kv, interpret)
    fold = lambda x: x.reshape(b * h, x.shape[2], head_dim)
    o, lse = flash(fold(q), fold(k), fold(v), sm_scale)
    return o.reshape(b, h, seq_q, head_dim), lse.reshape(b, h, seq_q)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    use_flash: bool = True,
) -> jax.Array:
    """Dispatcher: Pallas flash kernel when enabled, XLA reference otherwise."""
    if use_flash:
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    return attention_reference(q, k, v, causal=causal, sm_scale=sm_scale)
