"""Flash (blockwise) attention as a Pallas TPU kernel.

TPU-native replacement for the reference's CUDA ``tf.custom_op`` kernels
(BASELINE.json:north_star — "rewrite any tf.custom_op / CUDA kernels ...
as Pallas or XLA custom-calls"; SURVEY.md §2c, §5g). The kernel is the
single-device base for ring attention (``parallel/ring.py``): it computes
attention over KV *blocks* with an online softmax, so the same math
extends to KV blocks arriving over ICI.

Design (TPU-first, not a CUDA translation):
- Q is blocked over the grid; K/V live in VMEM per (batch*head) and are
  consumed block-by-block inside a ``fori_loop`` — the online-softmax
  running (max, sum, acc) ride in loop carries, which Mosaic keeps in
  vector registers/VMEM.
- All matmuls run on the MXU in f32 accumulation
  (``preferred_element_type``), inputs may be bf16.
- Causal masking skips whole KV blocks above the diagonal by shortening
  the loop bound (no wasted MXU work), and masks inside the diagonal
  block with ``broadcasted_iota``.
- Backward is the standard two-kernel split (dkv by KV block, dq by Q
  block) using the saved logsumexp, so the [seq, seq] score matrix is
  never materialized in HBM.

On non-TPU backends the same kernels run in Pallas interpret mode (used
by the CPU test suite) and an XLA reference implementation is provided
for numerics comparison.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
) -> jax.Array:
    """Plain-XLA attention; the numerics reference for the Pallas kernel.

    q, k, v: [batch, heads, seq, head_dim]. Softmax in f32.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * sm_scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        row = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(row + (sk - sq) >= col, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    ).astype(q.dtype)


# --------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal, block_kv):
    block_q, head_dim = q_ref.shape[1], q_ref.shape[2]
    seq_kv = k_ref.shape[1]
    num_kv = seq_kv // block_kv
    qi = pl.program_id(1)
    q_offset = qi * block_q
    # Bottom-right-aligned causal diagonal: query i attends keys
    # <= i + (seq_kv - seq_q), matching attention_reference.
    offset = seq_kv - pl.num_programs(1) * block_q

    q = q_ref[0].astype(jnp.float32) * sm_scale

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_kv]
        if causal:
            row = q_offset + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0
            )
            col = j * block_kv + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1
            )
            s = jnp.where(row + offset >= col, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)
    # Causal: KV blocks entirely above the diagonal contribute nothing —
    # shorten the loop instead of masking them (saves MXU work).
    hi = (
        jnp.clip(
            lax.div(q_offset + block_q + offset + block_kv - 1, block_kv),
            0,
            num_kv,
        )
        if causal
        else num_kv
    )
    m, l, acc = lax.fori_loop(0, hi, body, (m0, l0, acc0))

    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l)).astype(jnp.float32)


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_kv, interpret):
    bh, seq_q, head_dim = q.shape
    seq_kv = k.shape[1]
    grid = (bh, seq_q // block_q)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_kv=block_kv
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_kv, head_dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_kv, head_dim), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_q, head_dim), q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# -------------------------------------------------------------- backward


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, sm_scale, causal, block_q,
):
    block_kv, head_dim = k_ref.shape[1], k_ref.shape[2]
    seq_q = q_ref.shape[1]
    seq_kv = pl.num_programs(1) * block_kv
    offset = seq_kv - seq_q
    ki = pl.program_id(1)
    kv_offset = ki * block_kv

    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)

    def body(j, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(j * block_q, block_q), :]  # [block_q, 1]
        delta = delta_ref[0, pl.ds(j * block_q, block_q), :]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [block_q, block_kv]
        if causal:
            row = j * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0
            )
            col = kv_offset + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1
            )
            s = jnp.where(row + offset >= col, s, NEG_INF)
        p = jnp.exp(s - lse)  # [block_q, block_kv]
        # dv += p^T do
        dv_new = dv + lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        # dp = do v^T ; ds = p * (dp - delta)
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        # dk += ds^T q * scale
        dk_new = dk + sm_scale * lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk_new, dv_new

    zeros = jnp.zeros((block_kv, head_dim), jnp.float32)
    # Causal: Q blocks strictly above this KV block's diagonal see none of
    # it — start the loop at the first contributing Q block.
    lo = (
        jnp.clip(lax.div(kv_offset - offset, block_q), 0, seq_q // block_q)
        if causal
        else 0
    )
    dk, dv = lax.fori_loop(lo, seq_q // block_q, body, (zeros, zeros))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
    *, sm_scale, causal, block_kv,
):
    block_q, head_dim = q_ref.shape[1], q_ref.shape[2]
    seq_kv = k_ref.shape[1]
    offset = seq_kv - pl.num_programs(1) * block_q
    qi = pl.program_id(1)
    q_offset = qi * block_q

    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]
    delta = delta_ref[0]

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_kv, block_kv), :].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal:
            row = q_offset + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0
            )
            col = j * block_kv + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1
            )
            s = jnp.where(row + offset >= col, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        return dq + sm_scale * jnp.dot(
            ds, k, preferred_element_type=jnp.float32
        )

    hi = (
        jnp.clip(
            lax.div(q_offset + block_q + offset + block_kv - 1, block_kv),
            0,
            seq_kv // block_kv,
        )
        if causal
        else seq_kv // block_kv
    )
    dq = lax.fori_loop(
        0, hi, body, jnp.zeros((block_q, head_dim), jnp.float32)
    )
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd(sm_scale, causal, block_q, block_kv, interpret, residuals, g):
    q, k, v, o, lse = residuals
    bh, seq_q, head_dim = q.shape
    seq_kv = k.shape[1]
    do = g
    # delta_i = rowsum(do_i * o_i) — cheap, let XLA fuse it.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )

    full_q = pl.BlockSpec((1, seq_q, head_dim), lambda b, i: (b, 0, 0))
    full_kv = pl.BlockSpec((1, seq_kv, head_dim), lambda b, i: (b, 0, 0))
    full_vec = pl.BlockSpec((1, seq_q, 1), lambda b, i: (b, 0, 0))

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q
        ),
        grid=(bh, seq_kv // block_kv),
        in_specs=[full_q,
                  pl.BlockSpec((1, block_kv, head_dim), lambda b, i: (b, i, 0)),
                  pl.BlockSpec((1, block_kv, head_dim), lambda b, i: (b, i, 0)),
                  full_q, full_vec, full_vec],
        out_specs=[
            pl.BlockSpec((1, block_kv, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_kv, head_dim), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, sm_scale=sm_scale, causal=causal, block_kv=block_kv
        ),
        grid=(bh, seq_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0)),
            full_kv, full_kv,
            pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------ public api


@functools.lru_cache(maxsize=None)
def _make_flash(causal, block_q, block_kv, interpret):
    # sm_scale stays out of the cache key (a swept/per-layer scale must
    # not leak a closure per value) — it rides through as a nondiff arg.
    @functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
    def flash(q, k, v, sm_scale):
        o, _ = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_kv, interpret)
        return o

    def fwd(q, k, v, sm_scale):
        o, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_kv, interpret)
        return o, (q, k, v, o, lse)

    def bwd(sm_scale, residuals, g):
        return _flash_bwd(
            sm_scale, causal, block_q, block_kv, interpret, residuals, g
        )

    flash.defvjp(fwd, bwd)
    return flash


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = 128,
    block_kv: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Blockwise attention, differentiable; q/k/v: [batch, heads, seq, dim].

    Runs the Pallas TPU kernel on TPU; on other backends runs the same
    kernel in interpret mode (tests) unless ``interpret=False``.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, seq_q, head_dim = q.shape
    seq_kv = k.shape[2]
    block_q = min(block_q, seq_q)
    block_kv = min(block_kv, seq_kv)
    if seq_q % block_q or seq_kv % block_kv:
        raise ValueError(
            f"seq lengths ({seq_q}, {seq_kv}) must be divisible by block "
            f"sizes ({block_q}, {block_kv})"
        )
    if causal and seq_q > seq_kv:
        # Rows with zero visible keys are degenerate (the reference
        # softmaxes an all-masked row into uniform weights; the kernel
        # would return 0) — reject rather than silently diverge.
        raise ValueError(
            f"causal attention requires seq_q ({seq_q}) <= seq_kv ({seq_kv})"
        )
    if sm_scale is None:
        sm_scale = head_dim**-0.5
    flash = _make_flash(bool(causal), block_q, block_kv, interpret)
    fold = lambda x: x.reshape(b * h, x.shape[2], head_dim)
    out = flash(fold(q), fold(k), fold(v), float(sm_scale))
    return out.reshape(b, h, seq_q, head_dim)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    use_flash: bool = True,
) -> jax.Array:
    """Dispatcher: Pallas flash kernel when enabled, XLA reference otherwise."""
    if use_flash:
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    return attention_reference(q, k, v, causal=causal, sm_scale=sm_scale)
