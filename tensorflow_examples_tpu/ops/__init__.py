"""Ops layer: losses/metrics + Pallas TPU kernels.

The reference relied on CUDA ``tf.custom_op`` kernels for its fused ops
(BASELINE.json:north_star). The TPU-native equivalents here are Pallas
(Mosaic) kernels — fused cross-entropy and blockwise flash attention —
each paired with a pure-XLA reference implementation of identical
signature used for numerics tests (SURVEY.md §4) and as the CPU fallback.
"""

from tensorflow_examples_tpu.ops.attention import (
    attention_reference,
    dot_product_attention,
    flash_attention,
)
from tensorflow_examples_tpu.ops.cross_entropy import (
    cross_entropy_loss,
    cross_entropy_per_example,
    cross_entropy_reference,
)
from tensorflow_examples_tpu.ops.losses import (
    accuracy_metrics,
    softmax_cross_entropy,
)
