"""GLUE metrics (MCC, F1, Pearson/Spearman-free recipe) as mean-composable
pieces.

The shared eval loop (train/loop.py) aggregates *weighted means* across
batches. F1/MCC/Pearson are not batch-mean composable, but they ARE
functions of globally-aggregated means: confusion-cell indicator rates
(tp/fp/fn/tn) and raw moments (x, y, x², y², xy). Each task's ``eval_fn``
emits those per-batch rates; ``Task.eval_finalize`` turns the aggregated
means into the final score. This keeps eval single-pass, jitted, and
static-shape (SURVEY.md §3(3)) with no host-side prediction buffering.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tensorflow_examples_tpu.ops.losses import weighted_mean


def confusion_rates(
    preds: jax.Array, labels: jax.Array, weights: jax.Array | None
) -> dict[str, jax.Array]:
    """Per-batch weighted means of binary confusion indicators."""
    preds = preds.astype(jnp.int32)
    labels = labels.astype(jnp.int32)
    out = {}
    for name, cond in {
        "tp": (preds == 1) & (labels == 1),
        "fp": (preds == 1) & (labels == 0),
        "fn": (preds == 0) & (labels == 1),
        "tn": (preds == 0) & (labels == 0),
    }.items():
        out[name] = weighted_mean(cond.astype(jnp.float32), weights)
    return out


def f1_from_rates(m: dict) -> float:
    tp, fp, fn = m["tp"], m["fp"], m["fn"]
    denom = 2 * tp + fp + fn
    return 2 * tp / denom if denom > 0 else 0.0


def mcc_from_rates(m: dict) -> float:
    tp, fp, fn, tn = m["tp"], m["fp"], m["fn"], m["tn"]
    denom = ((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)) ** 0.5
    return (tp * tn - fp * fn) / denom if denom > 0 else 0.0


def moment_means(
    x: jax.Array, y: jax.Array, weights: jax.Array | None
) -> dict[str, jax.Array]:
    """Raw-moment means for Pearson correlation."""
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    return {
        "x": weighted_mean(x, weights),
        "y": weighted_mean(y, weights),
        "xx": weighted_mean(x * x, weights),
        "yy": weighted_mean(y * y, weights),
        "xy": weighted_mean(x * y, weights),
    }


def pearson_from_moments(m: dict) -> float:
    cov = m["xy"] - m["x"] * m["y"]
    vx = max(m["xx"] - m["x"] ** 2, 0.0)
    vy = max(m["yy"] - m["y"] ** 2, 0.0)
    denom = (vx * vy) ** 0.5
    return cov / denom if denom > 0 else 0.0
