"""Fused softmax cross-entropy as a Pallas TPU kernel.

The reference computed sparse categorical cross-entropy via stock ops,
which materializes a full [tokens, vocab] log-softmax in HBM — at GPT-2
scale (vocab 50257) that is the single largest activation in the model.
This kernel is HBM-bandwidth shaped instead: the vocab axis is consumed
in VMEM-sized chunks with an online logsumexp; only per-row (nll, lse)
ever leave the chip's VMEM in forward, and backward recomputes the
softmax chunk-by-chunk from the saved lse (SURVEY.md §2c obligation —
"fused cross-entropy" in the kernels layer).

Grid layout: (row blocks, vocab chunks). The TPU grid is sequential with
the last dimension fastest, so VMEM scratch carries the running
(max, sumexp, label-logit) across vocab chunks of one row block — the
same accumulation pattern as a blocked matmul's K loop.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from tensorflow_examples_tpu.core import collectives as coll
from tensorflow_examples_tpu.core.collectives import shard_map as _shard_map
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def cross_entropy_reference(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-example NLL in f32 via plain XLA. logits [N, V], labels [N].

    Label selection uses the gather-free mask+reduce (ops.losses
    .select_label) so this path partitions cleanly under SPMD too."""
    from tensorflow_examples_tpu.ops.losses import select_label

    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    return lse - select_label(logits, labels)


# --------------------------------------------------------------- kernels


def _ce_fwd_kernel(
    logits_ref, labels_ref, nll_ref, lse_ref, m_acc, l_acc, t_acc, *, vocab
):
    j = pl.program_id(1)
    block_n, block_v = logits_ref.shape

    @pl.when(j == 0)
    def _():
        m_acc[...] = jnp.full_like(m_acc, NEG_INF)
        l_acc[...] = jnp.zeros_like(l_acc)
        t_acc[...] = jnp.zeros_like(t_acc)

    col = j * block_v + lax.broadcasted_iota(jnp.int32, (block_n, block_v), 1)
    s = jnp.where(col < vocab, logits_ref[...].astype(jnp.float32), NEG_INF)
    labels = labels_ref[...]  # [block_n, 1]

    m_prev, l_prev = m_acc[...], l_acc[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(jnp.exp(s - m_new), axis=1, keepdims=True)
    m_acc[...] = m_new
    l_acc[...] = l_new
    # The label's logit lands in exactly one vocab chunk; accumulate it.
    t_acc[...] += jnp.sum(
        jnp.where(col == labels, s, 0.0), axis=1, keepdims=True
    )

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        lse = m_acc[...] + jnp.log(jnp.maximum(l_acc[...], 1e-30))
        lse_ref[...] = lse
        nll_ref[...] = lse - t_acc[...]


def _ce_bwd_kernel(logits_ref, labels_ref, lse_ref, g_ref, dlogits_ref, *, vocab):
    j = pl.program_id(1)
    block_n, block_v = logits_ref.shape
    col = j * block_v + lax.broadcasted_iota(jnp.int32, (block_n, block_v), 1)
    logits = logits_ref[...].astype(jnp.float32)
    p = jnp.exp(logits - lse_ref[...])  # softmax chunk from saved lse
    onehot = (col == labels_ref[...]).astype(jnp.float32)
    d = g_ref[...] * (p - onehot)
    dlogits_ref[...] = jnp.where(col < vocab, d, 0.0).astype(dlogits_ref.dtype)


def _fwd_call(logits, labels2d, block_n, block_v, interpret):
    n, vocab = logits.shape
    grid = (pl.cdiv(n, block_n), pl.cdiv(vocab, block_v))
    row_spec = pl.BlockSpec((block_n, 1), lambda i, j: (i, 0))
    nll, lse = pl.pallas_call(
        functools.partial(_ce_fwd_kernel, vocab=vocab),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, block_v), lambda i, j: (i, j)),
            row_spec,
        ],
        out_specs=[row_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(logits, labels2d)
    return nll, lse


@functools.lru_cache(maxsize=None)
def _make_fused(block_n, block_v, interpret):
    @jax.custom_vjp
    def fused(logits, labels2d):
        nll, _ = _fwd_call(logits, labels2d, block_n, block_v, interpret)
        return nll

    def fwd(logits, labels2d):
        nll, lse = _fwd_call(logits, labels2d, block_n, block_v, interpret)
        return nll, (logits, labels2d, lse)

    def bwd(residuals, g):
        logits, labels2d, lse = residuals
        n, vocab = logits.shape
        row_spec = pl.BlockSpec((block_n, 1), lambda i, j: (i, 0))
        dlogits = pl.pallas_call(
            functools.partial(_ce_bwd_kernel, vocab=vocab),
            grid=(pl.cdiv(n, block_n), pl.cdiv(vocab, block_v)),
            in_specs=[
                pl.BlockSpec((block_n, block_v), lambda i, j: (i, j)),
                row_spec, row_spec, row_spec,
            ],
            out_specs=pl.BlockSpec((block_n, block_v), lambda i, j: (i, j)),
            out_shape=jax.ShapeDtypeStruct(logits.shape, logits.dtype),
            interpret=interpret,
        )(logits, labels2d, lse, g.astype(jnp.float32))
        return dlogits, None

    fused.defvjp(fwd, bwd)
    return fused


# ------------------------------------------------------------ public api


def cross_entropy_per_example(
    logits: jax.Array,
    labels: jax.Array,
    *,
    block_n: int = 256,
    block_v: int = 4096,
    fused: bool | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Per-example NLL [N] (f32) from logits [N, V] and int labels [N].

    Default blocks 256×4096: ~4% faster fwd and grad than 128×2048 at
    the GPT-2 shape (8192 tokens × 50257 vocab, bf16, single v5e,
    within-run sweep); 512×4096 exceeds the compiler's VMEM budget.
    Blocks clamp to the actual (n, vocab) for small shapes."""
    if fused is None:
        fused = True
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not fused:
        return cross_entropy_reference(logits, labels)
    n, vocab = logits.shape
    block_n = min(block_n, n)
    block_v = min(block_v, vocab)
    fn = _make_fused(block_n, block_v, interpret)
    return fn(logits, labels.astype(jnp.int32)[:, None])[:, 0]


def mesh_cross_entropy_per_example(
    logits: jax.Array,  # [B, S, V]
    labels: jax.Array,  # [B, S] int
    *,
    mesh,
    fused: bool | None = None,
) -> jax.Array:
    """Token-sharded per-example NLL [B, S] for meshed training steps.

    The fused Pallas kernel is OPAQUE to the SPMD partitioner: called on
    data-sharded logits it triggers the partitioner's while-loop gather
    fallback, which all-gathers the full ``[tokens, vocab]`` logits
    across the data axes every step (measured: five data-axis
    ``[1024, 512]`` all-gathers in the dp2×model4 census,
    ``tools/ep_census.py``, round 4). CE is per-token independent, so a
    ``shard_map`` over the token axes makes the kernel local per shard
    with zero collectives. The ``model`` axis joins the seq-dim
    sharding when it divides: CE is replicated work under TP otherwise,
    and feeding logits in model-replicated would cost a [tokens, vocab]
    dlogits psum over ``model`` in the backward (measured before this
    split landed); with the split, sharding propagation pushes the seq
    partition up into the LM-head matmul itself. Axes that don't divide
    the corresponding dim are dropped (tokens replicate there — same policy as
    ``parallel/moe.py``); on a 1-device mesh this degenerates to the
    plain call.
    """
    from jax.sharding import PartitionSpec as P

    from tensorflow_examples_tpu.core.mesh import token_partition_axes

    def _plain(lg, lb):
        v = lg.shape[-1]
        return cross_entropy_per_example(
            lg.reshape(-1, v), lb.reshape(-1), fused=fused
        ).reshape(lb.shape)

    if mesh is None:
        return _plain(logits, labels)
    batch_axes, seq_axes = token_partition_axes(
        mesh, labels.shape[0], labels.shape[1], include_model=True
    )
    if not batch_axes and not seq_axes:
        return _plain(logits, labels)
    lg_spec = P(
        batch_axes if batch_axes else None,
        seq_axes if seq_axes else None,
        None,
    )
    lb_spec = P(
        batch_axes if batch_axes else None, seq_axes if seq_axes else None
    )
    return _shard_map(
        _plain,
        mesh=mesh,
        in_specs=(lg_spec, lb_spec),
        out_specs=lb_spec,
        check_vma=False,
    )(logits, labels)


def cross_entropy_loss(
    logits: jax.Array,
    labels: jax.Array,
    weights: jax.Array | None = None,
    *,
    fused: bool | None = None,
) -> jax.Array:
    """Weighted-mean token cross-entropy for LM heads.

    logits [..., V], labels [...]; weights [...] masks padding. Leading
    dims are flattened so the kernel sees one [tokens, vocab] problem.
    """
    from tensorflow_examples_tpu.ops.losses import weighted_mean

    vocab = logits.shape[-1]
    flat_logits = logits.reshape(-1, vocab)
    flat_labels = labels.reshape(-1)
    nll = cross_entropy_per_example(flat_logits, flat_labels, fused=fused)
    return weighted_mean(
        nll, None if weights is None else weights.reshape(-1)
    )


# ------------------------------------------------- vocab-parallel (TP) CE


def tp_cross_entropy_from_hidden(
    hidden: jax.Array,   # [N, d] final hidden states (post ln_f)
    wte: jax.Array,      # [V, d] tied embedding / LM head table
    labels: jax.Array,   # [N] int
    *,
    mesh,
    axis_name: str = "model",
    block_v: int = 2048,
) -> jax.Array:
    """Per-example NLL with the vocab axis sharded over ``axis_name``.

    The Megatron-style parallel LM head: each device holds a [V/m, d]
    slice of the embedding table, computes its local logits on the MXU,
    and only the online-softmax partials (max, sumexp, label-logit) cross
    ICI via pmax/psum — the full [N, V] logits never exist anywhere, and
    each device's HBM sees at most [N, V/m]. Degenerates to the fused
    Pallas kernel when the axis is trivial.

    Inside, the local [N, V/m] problem is consumed in ``block_v`` chunks
    by a lax.scan (the XLA analogue of the Pallas kernel's vocab loop) so
    peak memory is [N, block_v] regardless of shard width.
    """
    from jax.sharding import PartitionSpec as P

    from tensorflow_examples_tpu.core.mesh import AxisNames

    if mesh is None or mesh.shape[axis_name] == 1:
        logits = jnp.einsum(
            "nd,vd->nv", hidden, wte, preferred_element_type=jnp.float32
        )
        return cross_entropy_per_example(logits, labels)

    n_shards = mesh.shape[axis_name]
    vocab = wte.shape[0]
    batch = tuple(a for a in AxisNames.BATCH_AXES if mesh.shape[a] > 1)
    bspec = P(batch if batch else None)

    # Pad the vocab axis only to the shard count: when vocab % n_shards
    # == 0 this is a no-op and the shard_map split lines up EXACTLY with
    # the P(model, None) table sharding (no resharding collective). The
    # inner chunking pads per-shard, locally.
    v_local = pl.cdiv(vocab, n_shards)
    wte_pad = jnp.pad(wte, ((0, v_local * n_shards - vocab), (0, 0)))
    block = min(block_v, v_local)
    num_blocks = pl.cdiv(v_local, block)

    def local(hidden, wte_local, labels):
        shard = lax.axis_index(axis_name)
        base = shard * v_local
        n = hidden.shape[0]
        # Local pad so every dynamic_slice chunk is full-size; padded
        # rows have global col >= vocab only when base + local idx maps
        # past this shard's true rows — mask on the LOCAL index as well
        # as the global vocab bound.
        local_pad = num_blocks * block - v_local
        wte_loc = jnp.pad(wte_local, ((0, local_pad), (0, 0)))

        def chunk(carry, i):
            m, l, t = carry
            w = lax.dynamic_slice(
                wte_loc, (i * block, 0), (block, wte_loc.shape[1])
            )
            s = jnp.einsum(
                "nd,vd->nv", hidden, w, preferred_element_type=jnp.float32
            )
            local_idx = i * block + lax.broadcasted_iota(
                jnp.int32, (n, block), 1
            )
            col = base + local_idx
            s = jnp.where((local_idx < v_local) & (col < vocab), s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=1))
            l_new = l * jnp.exp(m - m_new) + jnp.sum(
                jnp.exp(s - m_new[:, None]), axis=1
            )
            t_new = t + jnp.sum(
                jnp.where(col == labels[:, None], s, 0.0), axis=1
            )
            return (m_new, l_new, t_new), None

        # Initial carries derived from hidden so they inherit its
        # varying-axes type under shard_map (cf. parallel/ring.py).
        zero = 0.0 * hidden[:, 0].astype(jnp.float32)
        (m, l, t), _ = lax.scan(
            chunk,
            (zero + NEG_INF, zero, zero),
            jnp.arange(num_blocks),
        )
        # Merge shards: global max, rescaled sumexp, label logit (the
        # label lands in exactly one shard; others contribute 0). The max
        # is a pure stabilizer — stop_gradient keeps the exact softmax
        # gradient and sidesteps pmax's missing differentiation rule.
        gm = coll.pmax(lax.stop_gradient(m), axis_name)
        gl = coll.psum(l * jnp.exp(m - gm), axis_name)
        gt = coll.psum(t, axis_name)
        return gm + jnp.log(jnp.maximum(gl, 1e-30)) - gt

    return _shard_map(
        local,
        mesh=mesh,
        in_specs=(bspec, P(axis_name, None), bspec),
        out_specs=bspec,
        check_vma=False,
    )(hidden, wte_pad, labels.astype(jnp.int32))
