"""Resolution: config + mesh + param template -> concrete placements.

This is the machinery both consumers share:

* :func:`resolve_params` — the param-path → PartitionSpec table with
  per-device byte accounting and a stable **digest** (sha256 over the
  sorted ``path → spec`` lines). The digest is deliberately
  mesh-SHAPE-independent: the same rules on a 2×2 and a 4×2 mesh hash
  identically, so a checkpoint reshards freely across layouts while a
  rules-table drift is caught by a digest mismatch
  (``config.ShardingMismatchError``).
* :func:`state_shardings` — the full TrainState placement: params by
  rules, optimizer moments inheriting their param's sharding (matched
  by path suffix + shape), non-trainables by rules, and the **ZeRO-1**
  escalation (arXiv:2004.13336): with ``zero1=True``, a moment whose
  param is replicated is sharded over the batch axes instead — XLA then
  emits reduce-scatter(grads) → sharded moment update → all-gather of
  the applied update, and per-device optimizer bytes scale down with
  the replica count (``TrainState.byte_breakdown(per_device=True)``
  measures it; the tier-1 acceptance asserts ≤ 1/4 of replicated on an
  8-way batch mesh).

Formerly ``train/loop.Trainer._state_shardings``; hoisted here so the
trainer, ``tools/shard_viz.py``, and the serving engine resolve
placement through one code path.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorflow_examples_tpu.core.mesh import AxisNames
from tensorflow_examples_tpu.core.sharding import (
    ShardingRules,
    _clip_spec,
    _filter_spec,
    _path_str,
    _rule_path,
    shardings_for_params,
)

Pytree = Any


def _spec_device_count(spec: P, mesh: Mesh) -> int:
    """Number of distinct shards a spec splits an array into."""
    n = 1
    for entry in spec:
        axes = (entry,) if isinstance(entry, str) else (entry or ())
        for a in axes:
            n *= int(mesh.shape[a])
    return n


@dataclasses.dataclass(frozen=True)
class ParamRow:
    """One resolved param: where it lives and what that costs."""

    path: str
    spec: P            # rule-resolved spec (mesh-shape independent)
    placed: P          # spec after size-1 axis filtering (what jit sees)
    shape: tuple
    dtype: str
    global_bytes: int
    per_device_bytes: int

    @property
    def replicated(self) -> bool:
        return all(a is None for a in self.placed)


@dataclasses.dataclass(frozen=True)
class ResolvedSharding:
    """The full param placement for one (config rules, mesh, template)."""

    mesh: Mesh
    rows: tuple

    def digest(self) -> str:
        """Stable hash of the LOGICAL placement (path → unfiltered
        spec, sorted). Mesh-shape independent by construction: restore
        onto any layout compares equal; a rules change does not."""
        h = hashlib.sha256()
        for row in sorted(self.rows, key=lambda r: r.path):
            h.update(f"{row.path}\t{tuple(row.spec)}\n".encode())
        return h.hexdigest()[:16]

    def spec_by_path(self) -> dict[str, tuple]:
        return {row.path: tuple(row.spec) for row in self.rows}

    def byte_totals(self) -> dict[str, int]:
        """Global vs per-device byte accounting, split replicated vs
        sharded — the shard_viz summary and the "is this rule doing
        anything" signal."""
        totals = {
            "global_bytes": 0,
            "per_device_bytes": 0,
            "replicated_per_device_bytes": 0,
            "sharded_per_device_bytes": 0,
        }
        for row in self.rows:
            totals["global_bytes"] += row.global_bytes
            totals["per_device_bytes"] += row.per_device_bytes
            key = (
                "replicated_per_device_bytes"
                if row.replicated
                else "sharded_per_device_bytes"
            )
            totals[key] += row.per_device_bytes
        return totals

    def table_str(self) -> str:
        """The human table shard_viz prints: one row per param, widest
        dims first, with the per-device cost next to the global one."""
        rows = sorted(self.rows, key=lambda r: -r.global_bytes)
        width = max((len(r.path) for r in rows), default=4)
        lines = [
            f"{'param':<{width}}  {'shape':<18} {'spec':<28} "
            f"{'global':>10} {'per-dev':>10}"
        ]
        for r in rows:
            spec = "replicated" if r.replicated else str(tuple(r.placed))
            lines.append(
                f"{r.path:<{width}}  {str(r.shape):<18} {spec:<28} "
                f"{_fmt_bytes(r.global_bytes):>10} "
                f"{_fmt_bytes(r.per_device_bytes):>10}"
            )
        t = self.byte_totals()
        lines.append(
            f"total: {_fmt_bytes(t['global_bytes'])} global, "
            f"{_fmt_bytes(t['per_device_bytes'])}/device "
            f"({_fmt_bytes(t['sharded_per_device_bytes'])} sharded + "
            f"{_fmt_bytes(t['replicated_per_device_bytes'])} replicated)"
        )
        return "\n".join(lines)


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n:.1f}GiB"  # pragma: no cover - loop always returns


def resolve_params(
    params_template: Pytree, mesh: Mesh, rules: ShardingRules
) -> ResolvedSharding:
    """Resolve every param leaf against the rules table. The template
    may be concrete arrays or ``jax.eval_shape`` abstract leaves — only
    shape/dtype are read."""
    import jax

    rows: list[ParamRow] = []

    def one(path, leaf):
        p = _path_str(path)
        # Rule matching + rank clipping mirror shardings_for_params: a
        # quantized child matches rules under its WEIGHT's path (so
        # anchored patterns keep working), the scale resolves (and is
        # accounted) under the weight's leading-dims spec. The row
        # keeps the FULL path — q and scale stay distinct in the
        # digest and the table.
        spec = _clip_spec(rules.spec_for(_rule_path(path)), path, leaf)
        placed = _filter_spec(spec, mesh)
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = getattr(leaf, "dtype", None)
        itemsize = int(getattr(dtype, "itemsize", 0) or 0)
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        gbytes = size * itemsize
        rows.append(
            ParamRow(
                path=p,
                spec=spec,
                placed=placed,
                shape=shape,
                dtype=str(dtype),
                global_bytes=gbytes,
                per_device_bytes=gbytes
                // max(_spec_device_count(placed, mesh), 1),
            )
        )
        return leaf

    jax.tree_util.tree_map_with_path(one, params_template)
    return ResolvedSharding(mesh=mesh, rows=tuple(rows))


# ----------------------------------------------------------------- ZeRO-1


def verify_digest_agreement(
    digest: str,
    *,
    allgather=None,
    process_index: int | None = None,
    process_count: int | None = None,
) -> None:
    """Fail fast when the fleet disagrees on param placement (ISSUE 8
    satellite, ROADMAP 1d).

    ``workdir/sharding.json`` is written by process 0 only and the
    restore-time rules check is per-process: a host launched with a
    stale config file or drifted flags would sail past its own local
    validation and corrupt the run at the first collective (or, worse,
    silently train under a different layout). Every process allgathers
    its placement digest at fit start — a tiny fixed-shape collective,
    same discipline as ``telemetry/fleet.py`` — and a mismatch raises
    :class:`~...config.ShardingMismatchError` NAMING the disagreeing
    host(s) and both digests, before any restore or step runs.

    ``allgather``/``process_index``/``process_count`` are injectable
    for tests (mirroring ``FleetMonitor``); single-process runs return
    immediately without importing multihost machinery.
    """
    if process_count is None:
        import jax

        process_count = jax.process_count()
    if process_count <= 1:
        return
    if process_index is None:
        import jax

        process_index = jax.process_index()
    if allgather is None:
        from jax.experimental import multihost_utils

        allgather = multihost_utils.process_allgather
    # Fixed-shape wire format: the 16-hex-char digest as 8 bytes.
    local = np.frombuffer(bytes.fromhex(digest), np.uint8).astype(
        np.int32
    )
    matrix = np.asarray(allgather(local), np.int32).reshape(
        process_count, local.size
    )
    mismatched = [
        (host, bytes(matrix[host].astype(np.uint8)).hex())
        for host in range(process_count)
        if not np.array_equal(matrix[host], local)
    ]
    if not mismatched:
        return
    from tensorflow_examples_tpu.sharding.config import (
        ShardingMismatchError,
    )

    shown = ", ".join(f"host {h}: {d}" for h, d in mismatched[:8])
    more = (
        f" (and {len(mismatched) - 8} more)" if len(mismatched) > 8 else ""
    )
    raise ShardingMismatchError(
        f"param-sharding digest disagrees across the fleet: host "
        f"{process_index} resolved {digest} but {shown}{more}. Every "
        "process must run the same rules/config — check for a stale "
        "sharding.json or drifted flags on the named host(s) before "
        "any checkpoint is touched."
    )


def zero1_spec(shape: tuple, mesh: Mesh, batch_axes: tuple) -> NamedSharding | None:
    """ZeRO-1 moment spec: shard the largest evenly-divisible dim over
    the batch axes (dim 0 is often tiny — e.g. conv kernel height).
    None when no dim divides — that moment stays replicated."""
    n_batch = int(np.prod([mesh.shape[a] for a in batch_axes] or [1]))
    best = max(
        (d for d in range(len(shape)) if shape[d] % n_batch == 0),
        key=lambda d: shape[d],
        default=None,
    )
    if best is None or shape[best] < n_batch:
        return None
    spec = [None] * len(shape)
    spec[best] = batch_axes
    return NamedSharding(mesh, P(*spec))


def state_shardings(
    abstract_state,
    mesh: Mesh,
    rules: ShardingRules,
    *,
    zero1: bool = False,
    batch_axes: tuple = AxisNames.BATCH_AXES,
):
    """Placement pytree for a full TrainState (see module docstring)."""
    import jax

    param_sh = shardings_for_params(abstract_state.params, mesh, rules)
    replicated = NamedSharding(mesh, P())

    # Optimizer moments (adam mu/nu, momentum traces, …) embed the param
    # tree, so an opt-state leaf's key path ends with its param's path;
    # match the longest such suffix (with equal shape) and inherit that
    # param's sharding. Everything else (counts, scalars) replicates.
    param_map: dict[str, tuple] = {}

    def record(path, leaf, sh):
        param_map[_path_str(path)] = (leaf.shape, sh)
        return sh

    jax.tree_util.tree_map_with_path(record, abstract_state.params, param_sh)

    active_batch = tuple(a for a in batch_axes if mesh.shape[a] > 1)
    n_batch = int(np.prod([mesh.shape[a] for a in active_batch] or [1]))
    zero1 = zero1 and n_batch > 1

    def opt_sharding(path, leaf):
        parts = _path_str(path).split("/")
        for i in range(len(parts)):
            entry = param_map.get("/".join(parts[i:]))
            if entry is not None and getattr(leaf, "shape", None) == entry[0]:
                shape, sh = entry
                # Replicated == every spec entry None (P() and its
                # filtered P(None, ...) forms compare unequal).
                if zero1 and all(a is None for a in sh.spec) and shape:
                    z1 = zero1_spec(shape, mesh, active_batch)
                    if z1 is not None:
                        return z1
                return sh
        return replicated

    opt_sh = jax.tree_util.tree_map_with_path(
        opt_sharding, abstract_state.opt_state
    )
    # Non-trainable collections (BN stats, …) follow the same path rules
    # (unmatched → replicated, the common case for norm statistics).
    model_state_sh = shardings_for_params(
        abstract_state.model_state, mesh, rules
    )
    return abstract_state.replace(
        step=replicated,
        params=param_sh,
        opt_state=opt_sh,
        model_state=model_state_sh,
    )
