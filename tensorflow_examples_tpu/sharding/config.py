"""ShardingConfig: the one serializable placement spec (ISSUE 7).

A config is four decisions, all JSON-serializable:

* ``mesh`` — logical device-mesh shape over the canonical axes
  (``core/mesh.AxisNames``): ``{"data": 2, "model": 4}``. ``data: -1``
  means "all remaining devices"; axes left out default to 1. Unlike
  ``MeshConfig.resolve`` (which demands the shape exactly cover every
  device), ``build_mesh`` uses the FIRST ``prod(shape)`` devices when
  the host has more — that is what lets tier-1 exercise 1×1, 2×2, and
  4×2 layouts on one 8-fake-CPU-device process, and a single-chip debug
  run consume a pod-shaped config unchanged.
* ``rules`` — the (param-path regex → PartitionSpec) table as
  ``[pattern, spec]`` pairs, where a spec entry is ``null`` / an axis
  name / a list of axis names (``spec_to_json``/``spec_from_json``
  round-trip ``jax.sharding.PartitionSpec`` losslessly). Empty rules
  mean "inherit the task's table" for the trainer and "replicate" for
  standalone consumers.
* ``batch_axes`` — which mesh axes shard the batch dim of activations
  (the ``jax.jit`` in-sharding of every train/eval batch).
* ``zero1`` — ZeRO-1 weight-update sharding (arXiv:2004.13336): shard
  optimizer moments over the batch axes even where the param itself is
  replicated; XLA then emits reduce-scatter(grad) → sharded moment
  update → all-gather(update) and per-device optimizer bytes drop by
  the replica count (``sharding/resolve.py``).

The degenerate config (1×1 mesh, zero1 off) reproduces unsharded
behavior exactly — every pre-existing golden (preemption resume,
serving token identity) runs through this object unchanged.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Mapping, Sequence

from jax.sharding import PartitionSpec as P

from tensorflow_examples_tpu.core.mesh import AxisNames, MeshConfig, create_mesh
from tensorflow_examples_tpu.core.sharding import ShardingRules

# The on-disk format version of sharding.json (NOT the telemetry schema).
SHARDING_JSON_VERSION = 1


class ShardingMismatchError(ValueError):
    """A checkpoint's saved sharding config is incompatible with the
    live one — different rules resolve params to different
    PartitionSpecs. Mesh SHAPE differences are legal (resharding on
    restore is the feature); rule-table drift is not, and this error
    names the drifted param paths instead of letting a run silently
    train/serve with a placement the checkpoint was never built for."""


def spec_to_json(spec: P) -> list:
    """PartitionSpec -> JSON list: entry = None | axis | [axes...]."""
    out: list = []
    for entry in spec:
        if entry is None or isinstance(entry, str):
            out.append(entry)
        else:
            out.append([str(a) for a in entry])
    return out


def spec_from_json(entries: Sequence) -> P:
    """Inverse of :func:`spec_to_json` (lists become axis tuples)."""
    out = []
    for entry in entries:
        if entry is None or isinstance(entry, str):
            out.append(entry)
        else:
            out.append(tuple(str(a) for a in entry))
    return P(*out)


def rules_to_json(rules: ShardingRules) -> list[list]:
    """A core ShardingRules table -> [[pattern, spec-json], ...]."""
    return [
        [pat.pattern, spec_to_json(spec)] for pat, spec in rules.rules
    ]


def rules_from_json(entries: Sequence[Sequence]) -> ShardingRules:
    return ShardingRules(
        [(str(pat), spec_from_json(spec)) for pat, spec in entries]
    )


@dataclasses.dataclass(frozen=True)
class ShardingConfig:
    """Serializable placement spec shared by training and serving."""

    # axis -> size over AxisNames.ALL; absent axes are 1, data may be -1.
    mesh: Mapping[str, int] = dataclasses.field(
        default_factory=lambda: {"data": -1}
    )
    # [(pattern, spec-json entries)] — () inherits the task's table.
    rules: tuple = ()
    batch_axes: tuple = AxisNames.BATCH_AXES
    zero1: bool = False

    def __post_init__(self):
        unknown = set(self.mesh) - set(AxisNames.ALL)
        if unknown:
            raise ValueError(
                f"unknown mesh axes {sorted(unknown)}; canonical axes are "
                f"{list(AxisNames.ALL)}"
            )
        bad_batch = set(self.batch_axes) - set(AxisNames.ALL)
        if bad_batch:
            raise ValueError(
                f"unknown batch axes {sorted(bad_batch)}; canonical axes "
                f"are {list(AxisNames.ALL)}"
            )
        for axis, size in self.mesh.items():
            if axis == AxisNames.DATA and size == -1:
                continue
            if not isinstance(size, int) or isinstance(size, bool) or size < 1:
                raise ValueError(
                    f"mesh[{axis!r}] = {size!r} must be a positive int "
                    "(or -1 for 'data')"
                )
        # Normalize containers so configs built from live
        # PartitionSpecs, from JSON (lists), and from round-trips all
        # compare EQUAL: rule entries become tuples down to the
        # multi-axis level. (`mesh` stays a plain dict — convenient,
        # but it makes the dataclass unhashable despite frozen=True;
        # nothing keys on configs today.)
        object.__setattr__(self, "mesh", dict(self.mesh))

        def norm_entry(e):
            return tuple(str(a) for a in e) if isinstance(
                e, (list, tuple)
            ) else e

        object.__setattr__(
            self,
            "rules",
            tuple(
                (str(p), tuple(norm_entry(e) for e in s))
                for p, s in self.rules
            ),
        )
        object.__setattr__(self, "batch_axes", tuple(self.batch_axes))

    # ------------------------------------------------------ construction

    @classmethod
    def from_train_config(cls, cfg, *, rules=None) -> "ShardingConfig":
        """Derive from the legacy TrainConfig knobs (mesh_data/.../zero1)
        + a task's live rules table, so the ShardingConfig is the single
        source of truth even for runs configured the old way."""
        mc = cfg.mesh_config()
        mesh = {
            AxisNames.DATA: mc.data,
            AxisNames.FSDP: mc.fsdp,
            AxisNames.MODEL: mc.model,
            AxisNames.CONTEXT: mc.context,
            AxisNames.PIPE: mc.pipe,
        }
        return cls(
            mesh=mesh,
            rules=tuple(
                (pat, tuple(spec))
                for pat, spec in (
                    rules_to_json(rules) if rules is not None else ()
                )
            ),
            zero1=bool(getattr(cfg, "zero1", False)),
        )

    @classmethod
    def from_mesh(cls, mesh, *, rules=None, zero1: bool = False) -> "ShardingConfig":
        """Snapshot a live ``jax.sharding.Mesh``'s shape into a config."""
        shape = {a: int(mesh.shape[a]) for a in mesh.axis_names}
        return cls(
            mesh=shape,
            rules=tuple(
                (pat, tuple(spec))
                for pat, spec in (
                    rules_to_json(rules) if rules is not None else ()
                )
            ),
            zero1=zero1,
        )

    # ------------------------------------------------------------- views

    def axis_size(self, axis: str) -> int:
        return int(self.mesh.get(axis, 1))

    def mesh_config(self) -> MeshConfig:
        return MeshConfig(
            data=self.axis_size(AxisNames.DATA),
            fsdp=self.axis_size(AxisNames.FSDP),
            model=self.axis_size(AxisNames.MODEL),
            context=self.axis_size(AxisNames.CONTEXT),
            pipe=self.axis_size(AxisNames.PIPE),
        )

    def sharding_rules(self, default: ShardingRules | None = None) -> ShardingRules:
        """The rules table; empty config rules fall back to ``default``
        (the task's live table — ``from_train_config`` embeds it, so the
        fallback only fires for hand-written configs without rules)."""
        if self.rules:
            return rules_from_json(self.rules)
        return default if default is not None else ShardingRules()

    def build_mesh(self, *, devices=None):
        """Construct the mesh, using the FIRST prod(shape) devices when
        the process has more (a 2×2 config runs on an 8-device host; the
        canonical CPU-mesh debug recipe in docs/sharding.md)."""
        import jax

        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        mc = self.mesh_config()
        fixed = mc.fsdp * mc.model * mc.context * mc.pipe
        data = mc.data
        if data == -1:
            if len(devices) % fixed:
                raise ValueError(
                    f"{len(devices)} devices not divisible by "
                    f"fsdp*model*context*pipe={fixed}"
                )
            data = len(devices) // fixed
        total = data * fixed
        if total > len(devices):
            raise ValueError(
                f"sharding config mesh {dict(self.mesh)} needs {total} "
                f"devices; only {len(devices)} available"
            )
        return create_mesh(
            MeshConfig(data=data, fsdp=mc.fsdp, model=mc.model,
                       context=mc.context, pipe=mc.pipe),
            devices=devices[:total],
        )

    def batch_sharding(self, mesh):
        """NamedSharding for a [global_batch, ...] activation (the core
        helper over THIS config's batch axes)."""
        from tensorflow_examples_tpu.core.sharding import batch_sharding

        return batch_sharding(mesh, self.batch_axes)

    def bundle_sharding(self, mesh):
        """[k, global_batch, ...] step bundle: scan dim unsharded, batch
        dim behind it sharded exactly as :meth:`batch_sharding`."""
        from tensorflow_examples_tpu.core.sharding import bundle_sharding

        return bundle_sharding(mesh, self.batch_axes)

    def mesh_shape_dict(self, mesh=None) -> dict[str, int]:
        """Axis -> size, resolved (no -1) — the telemetry payload. Pass
        the live mesh when one exists; otherwise data=-1 resolves
        against the process's device count."""
        if mesh is not None:
            return {a: int(mesh.shape[a]) for a in mesh.axis_names}
        import jax

        mc = self.mesh_config()
        return dict(
            zip(AxisNames.ALL, mc.resolve(jax.device_count()))
            if mc.data == -1
            else {
                AxisNames.DATA: mc.data,
                AxisNames.FSDP: mc.fsdp,
                AxisNames.MODEL: mc.model,
                AxisNames.CONTEXT: mc.context,
                AxisNames.PIPE: mc.pipe,
            }
        )

    # ----------------------------------------------------- serialization

    def to_json_dict(self) -> dict:
        return {
            "mesh": {a: int(s) for a, s in self.mesh.items()},
            "rules": [[p, list(s)] for p, s in self.rules],
            "batch_axes": list(self.batch_axes),
            "zero1": bool(self.zero1),
        }

    @classmethod
    def from_json_dict(cls, obj: Mapping[str, Any]) -> "ShardingConfig":
        if not isinstance(obj, Mapping):
            raise ValueError(
                f"sharding config must be a JSON object, got "
                f"{type(obj).__name__}"
            )
        unknown = set(obj) - {"mesh", "rules", "batch_axes", "zero1"}
        if unknown:
            raise ValueError(
                f"unknown sharding config keys {sorted(unknown)}"
            )
        return cls(
            mesh=dict(obj.get("mesh", {"data": -1})),
            rules=tuple(
                (str(p), tuple(s)) for p, s in obj.get("rules", ())
            ),
            batch_axes=tuple(
                obj.get("batch_axes", AxisNames.BATCH_AXES)
            ),
            zero1=bool(obj.get("zero1", False)),
        )

    def save(self, path: str, *, extra: Mapping | None = None) -> None:
        """Atomic write of ``{"version", "config", **extra}`` — the
        ``workdir/sharding.json`` the trainer persists next to its
        checkpoints and the serving CLI auto-loads."""
        doc = {
            "version": SHARDING_JSON_VERSION,
            "config": self.to_json_dict(),
        }
        if extra:
            doc.update(extra)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "ShardingConfig":
        config, _ = cls.load_with_extra(path)
        return config

    @classmethod
    def load_with_extra(cls, path: str) -> tuple["ShardingConfig", dict]:
        """Load a sharding.json; returns (config, sidecar-fields) where
        the sidecar carries whatever ``save(extra=...)`` recorded (the
        param digest, the mesh shape at save time)."""
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError(f"{path}: not a JSON object")
        if "config" in doc:
            version = doc.get("version")
            if version != SHARDING_JSON_VERSION:
                raise ValueError(
                    f"{path}: sharding.json version {version!r} "
                    f"(this build reads {SHARDING_JSON_VERSION})"
                )
            extra = {
                k: v for k, v in doc.items()
                if k not in ("version", "config")
            }
            return cls.from_json_dict(doc["config"]), extra
        # A bare config object (hand-written, no wrapper) also loads.
        return cls.from_json_dict(doc), {}
