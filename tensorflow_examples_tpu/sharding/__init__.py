"""Unified sharding subsystem (ISSUE 7 tentpole).

ONE serializable :class:`ShardingConfig` is the source of truth for
placement everywhere: ``train/loop.Trainer`` builds its mesh, its
param/optimizer/activation shardings, and its ZeRO-1 weight-update
sharding from it; ``serving/engine.InferenceEngine`` places the restored
param tree and the KV-cache pool from the very same object; checkpoints
carry it (``workdir/sharding.json``) so a restore onto a different mesh
is validated — same rules restore bitwise-identically onto any layout,
drifted rules fail with a named error instead of silently misplacing.

Layering: ``core/mesh.py`` owns the axis conventions and mesh
construction, ``core/sharding.py`` owns the (regex → PartitionSpec)
rules table. This package is the layer ABOVE both: a serializable
config that binds a mesh shape + a rules table + batch/ZeRO-1 policy
into one object both the trainer and the serving engine consume, plus
the resolution machinery (param table, placement digest, per-device
byte accounting) that makes a layout inspectable before a run
(``tools/shard_viz.py``) and comparable across runs (the digest on the
telemetry ``kind="final"`` line and in ``sharding.json``).

See docs/sharding.md for axis conventions, the config format, ZeRO-1
memory math, and the CPU-mesh debugging recipe
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``).
"""

from tensorflow_examples_tpu.sharding.config import (
    ShardingConfig,
    ShardingMismatchError,
    spec_from_json,
    spec_to_json,
)
from tensorflow_examples_tpu.sharding.resolve import (
    ResolvedSharding,
    resolve_params,
    state_shardings,
    verify_digest_agreement,
    zero1_spec,
)

__all__ = [
    "ResolvedSharding",
    "ShardingConfig",
    "ShardingMismatchError",
    "resolve_params",
    "spec_from_json",
    "spec_to_json",
    "state_shardings",
    "verify_digest_agreement",
    "zero1_spec",
]
