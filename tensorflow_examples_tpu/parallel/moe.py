"""Mixture-of-Experts FFN with expert parallelism (EP).

Framework-native extension (SURVEY.md §2d notes the reference has no MoE
workload; EP is provided as a first-class capability of the parallelism
layer). Switch/GShard-style top-k routing, TPU-first:

- Static shapes everywhere: tokens are routed with a fixed per-expert
  ``capacity``; overflow tokens fall through the residual connection
  (standard Switch behavior) — no dynamic shapes under jit. The dropped
  fraction is returned so training can LOG it (a silently-high drop rate
  is the classic MoE failure mode).
- Dispatch/combine are index ops — a scatter-add into the ``[E, C, d]``
  expert buffers and a gather back — O(n·d) memory and data movement.
  (The round-1 formulation built a dense one-hot ``[n, E, C]`` dispatch
  tensor and einsummed against it: O(n·E·C) memory — fine for toy
  shapes, dead at real n·E. VERDICT r1 item 8.)
- Experts are the *same* FFN pytree with a leading [experts] axis,
  sharded over the ``model`` mesh axis (GPT2_RULES). Activations inside
  the blocks are replicated over ``model`` (TP shards heads/ff, not
  tokens), so under XLA SPMD the scatter lands tokens directly on the
  expert's shard and the combine gathers back — collectives over ICI
  are inserted by the partitioner, the reference stack's hand-written
  NCCL all-to-all has no user-space equivalent here (SURVEY.md §2c).
- Router computes in f32 with jitter noise at train time and the Switch
  auxiliary load-balancing loss (mean fraction · mean prob per expert,
  over rank-0 assignments).

``moe_ffn`` is pure (params in, tokens out) so it slots into flax
modules (models/transformer.py MoeMlp) and composes with remat/scan.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def moe_ffn(
    gate_w: jax.Array,  # [d, E] router weights
    w_in: jax.Array,    # [E, d, ff]
    b_in: jax.Array,    # [E, ff]
    w_out: jax.Array,   # [E, ff, d]
    b_out: jax.Array,   # [E, d]
    x: jax.Array,       # [B, S, d]
    *,
    capacity_factor: float = 1.25,
    top_k: int = 1,
    rng: jax.Array | None = None,
    jitter: float = 1e-2,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k MoE FFN.

    Returns ``(out [B,S,d], aux_loss scalar, drop_fraction scalar)``;
    ``drop_fraction`` is the fraction of (token, rank) assignments that
    overflowed expert capacity and fell through the residual.
    """
    b, s, d = x.shape
    e = gate_w.shape[-1]
    n = b * s
    top_k = min(top_k, e)
    tokens = x.reshape(n, d)

    logits = (tokens.astype(jnp.float32)) @ gate_w.astype(jnp.float32)
    if rng is not None and jitter > 0:
        logits += jax.random.uniform(
            rng, logits.shape, jnp.float32, -jitter, jitter
        )
    probs = jax.nn.softmax(logits, axis=-1)  # [n, E]

    # Sequential top-k: argmax, mask, repeat (k is tiny and static).
    masked = probs
    experts, gates = [], []
    for _ in range(top_k):
        ej = jnp.argmax(masked, axis=-1)  # [n]
        pj = jnp.take_along_axis(masked, ej[:, None], axis=-1)[:, 0]
        experts.append(ej)
        gates.append(pj)
        masked = masked * (1.0 - jax.nn.one_hot(ej, e, dtype=jnp.float32))
    # top-1: keep the raw router probability as the gate (Switch) — it
    # is how the router gets task-loss gradient. Renormalizing would
    # make the gate identically 1.0 and silently detach the router.
    # top-k>1: renormalize over the chosen experts (GShard) — relative
    # weights still carry gradient there.
    if top_k > 1:
        denom = jnp.maximum(sum(gates), 1e-9)
        gates = [g / denom for g in gates]

    # Switch aux loss over rank-0 assignments:
    # E · Σ_e (fraction of tokens → e) · (mean prob of e).
    onehot0 = jax.nn.one_hot(experts[0], e, dtype=jnp.float32)
    aux = e * jnp.sum(jnp.mean(onehot0, axis=0) * jnp.mean(probs, axis=0))

    # Static-capacity slotting: rank-0 assignments queue first, then
    # rank-1, … — each (token, rank) gets a 1-based position in its
    # expert's queue; positions past capacity are dropped.
    capacity = max(1, int(capacity_factor * top_k * n / e))
    counts = jnp.zeros((e,), jnp.int32)  # queue length so far, per expert
    flat_slots, keeps = [], []
    for ej in experts:
        oh = jax.nn.one_hot(ej, e, dtype=jnp.int32)  # [n, E]
        pos = (jnp.cumsum(oh, axis=0) + counts[None, :]) * oh  # [n, E]
        posj = jnp.sum(pos, axis=-1)  # [n], 1-based
        keeps.append(posj <= capacity)
        flat_slots.append(ej * capacity + jnp.clip(posj - 1, 0, capacity - 1))
        counts = counts + jnp.sum(oh, axis=0)
    kept = sum(jnp.sum(k_) for k_ in keeps)
    drop_frac = 1.0 - kept.astype(jnp.float32) / (n * top_k)

    # Dispatch: scatter-add token rows into the expert buffers. Slots are
    # unique per kept (token, rank) pair, so adds never collide.
    xin = jnp.zeros((e * capacity, d), x.dtype)
    for flat, keep in zip(flat_slots, keeps):
        xin = xin.at[flat].add(
            tokens * keep[:, None].astype(x.dtype),
            mode="drop",
        )
    xin = xin.reshape(e, capacity, d)

    # Expert FFN: one batched matmul pair over the expert axis (MXU).
    h = jnp.einsum("ecd,edf->ecf", xin, w_in) + b_in[:, None, :]
    h = jax.nn.gelu(h, approximate=True)
    yout = jnp.einsum("ecf,efd->ecd", h, w_out) + b_out[:, None, :]

    # Combine: gather each (token, rank)'s output row, gate, and sum.
    yflat = yout.reshape(e * capacity, d).astype(jnp.float32)
    out = jnp.zeros((n, d), jnp.float32)
    for flat, keep, gate in zip(flat_slots, keeps, gates):
        out = out + yflat[flat] * (gate * keep)[:, None]
    return out.reshape(b, s, d).astype(x.dtype), aux, drop_frac
