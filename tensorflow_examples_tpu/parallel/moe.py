"""Mixture-of-Experts FFN with expert parallelism (EP).

Framework-native extension (SURVEY.md §2d notes the reference has no MoE
workload; EP is provided as a first-class capability of the parallelism
layer). Switch-Transformer-style top-1 routing, TPU-first:

- Static shapes everywhere: tokens are routed with a fixed per-expert
  ``capacity``; overflow tokens fall through the residual connection
  (standard Switch behavior) — no dynamic shapes under jit.
- Experts are the *same* FFN pytree with a leading [experts] axis. On a
  mesh, experts shard over the ``model`` axis (EP reuses the tensor-
  parallel axis, the common choice when EP and TP are not combined) and
  dispatch/combine are einsums against one-hot dispatch masks — XLA
  lowers them to all_to_all-equivalent collectives over ICI.
- Router computes in f32 with jitter noise at train time and an
  auxiliary load-balancing loss (mean fraction · mean prob per expert).

``moe_ffn`` is pure (params in, tokens out) so it slots into flax
modules (models/transformer.py MoeMlp) and composes with remat/scan.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def moe_ffn(
    gate_w: jax.Array,  # [d, E] router weights
    w_in: jax.Array,    # [E, d, ff]
    b_in: jax.Array,    # [E, ff]
    w_out: jax.Array,   # [E, ff, d]
    b_out: jax.Array,   # [E, d]
    x: jax.Array,       # [B, S, d]
    *,
    capacity_factor: float = 1.25,
    rng: jax.Array | None = None,
    jitter: float = 1e-2,
) -> tuple[jax.Array, jax.Array]:
    """Top-1 (Switch) MoE FFN. Returns (out [B,S,d], aux_loss scalar)."""
    b, s, d = x.shape
    e = gate_w.shape[-1]
    n = b * s
    tokens = x.reshape(n, d)

    logits = (tokens.astype(jnp.float32)) @ gate_w.astype(jnp.float32)
    if rng is not None and jitter > 0:
        logits += jax.random.uniform(
            rng, logits.shape, jnp.float32, -jitter, jitter
        )
    probs = jax.nn.softmax(logits, axis=-1)  # [n, E]
    expert = jnp.argmax(probs, axis=-1)      # [n]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]

    # Switch aux loss: E · Σ_e (fraction of tokens → e) · (mean prob of e).
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)  # [n, E]
    aux = e * jnp.sum(jnp.mean(onehot, axis=0) * jnp.mean(probs, axis=0))

    # Static-capacity dispatch: position of each token within its expert's
    # queue; tokens past capacity are dropped (residual carries them).
    capacity = max(1, int(capacity_factor * n / e))
    position = jnp.cumsum(onehot, axis=0) * onehot  # 1-based slot, [n, E]
    keep = (position > 0) & (position <= capacity)
    slot = jnp.clip(position.sum(axis=-1).astype(jnp.int32) - 1, 0, capacity - 1)
    kept = keep.any(axis=-1)

    # dispatch [n, E, C]: one-hot (expert, slot) for kept tokens.
    dispatch = (
        onehot[:, :, None]
        * jax.nn.one_hot(slot, capacity, dtype=jnp.float32)[:, None, :]
        * kept[:, None, None]
    )
    # Expert inputs [E, C, d] — einsum against the mask; XLA turns this
    # into a gather/all_to_all under sharding.
    xin = jnp.einsum("nec,nd->ecd", dispatch, tokens.astype(jnp.float32))
    xin = xin.astype(x.dtype)

    h = jnp.einsum("ecd,edf->ecf", xin, w_in) + b_in[:, None, :]
    h = jax.nn.gelu(h, approximate=True)
    yout = jnp.einsum("ecf,efd->ecd", h, w_out) + b_out[:, None, :]

    # Combine back with the gate value folded in.
    combined = jnp.einsum(
        "nec,ecd->nd", dispatch * gate[:, None, None], yout.astype(jnp.float32)
    )
    return combined.reshape(b, s, d).astype(x.dtype), aux
