"""Mixture-of-Experts FFN with expert parallelism (EP).

Framework-native extension (SURVEY.md §2d notes the reference has no MoE
workload; EP is provided as a first-class capability of the parallelism
layer). Switch/GShard-style top-k routing, TPU-first:

Three dispatch formulations share one router:

- ``moe_ffn(impl="grouped")`` — sort-based DROPLESS dispatch (round 5,
  the TPU single-program default): argsort (token, rank) pairs by
  expert → grouped matmuls over contiguous segments (MegaBlocks
  ``megablox.gmm`` Pallas kernel at tile-divisible shapes, masked
  ``lax.ragged_dot`` otherwise) → inverse-permutation gather → gated
  sum. Scatter-free in fwd AND bwd (custom-vjp permutation/partial-
  permutation gathers): the round-4 harvest measured the scatter
  formulation leaving the chip >99% idle (rel_mfu 0.00154 vs dense
  0.0624).
- ``moe_ffn(impl="scatter")`` — static-capacity Switch semantics (the
  CPU default and the parity reference): fixed per-expert ``capacity``,
  overflow falls through the residual — no dynamic shapes under jit;
  the dropped fraction is returned so training can LOG it (a
  silently-high drop rate is the classic MoE failure mode).
- ``moe_ffn_ep`` — explicit expert parallelism under ``shard_map``:
  capacity buffers (the fixed-size all-to-all transport format) built
  by the SORTED-GATHER slotting (scatter-free), one ``lax.all_to_all``
  hop each way over the ``model`` axis.

Experts are the *same* FFN pytree with a leading [experts] axis,
sharded over the ``model`` mesh axis (GPT2_RULES). The router computes
in f32 with jitter noise at train time and the Switch auxiliary
load-balancing loss (mean fraction · mean prob per expert, over rank-0
assignments). (The round-1 formulation built a dense one-hot
``[n, E, C]`` dispatch tensor and einsummed against it: O(n·E·C)
memory — fine for toy shapes, dead at real n·E. VERDICT r1 item 8.)

``moe_ffn`` is pure (params in, tokens out) so it slots into flax
modules (models/transformer.py MoeMlp) and composes with remat/scan.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from tensorflow_examples_tpu.core.collectives import shard_map as _shard_map


def _router(
    tokens: jax.Array,  # [n, d] f32-castable
    gate_w: jax.Array,  # [d, E]
    *,
    top_k: int,
    rng: jax.Array | None,
    jitter: float,
):
    """Top-k router, shared by every dispatch formulation. Returns
    (gates, experts, mean_onehot0 [E], mean_probs [E])."""
    e = gate_w.shape[-1]
    logits = tokens.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    if rng is not None and jitter > 0:
        logits += jax.random.uniform(
            rng, logits.shape, jnp.float32, -jitter, jitter
        )
    probs = jax.nn.softmax(logits, axis=-1)  # [n, E]

    # Sequential top-k: argmax, mask, repeat (k is tiny and static).
    masked = probs
    experts, gates = [], []
    for _ in range(top_k):
        ej = jnp.argmax(masked, axis=-1)  # [n]
        pj = jnp.take_along_axis(masked, ej[:, None], axis=-1)[:, 0]
        experts.append(ej)
        gates.append(pj)
        masked = masked * (1.0 - jax.nn.one_hot(ej, e, dtype=jnp.float32))
    # top-1: keep the raw router probability as the gate (Switch) — it
    # is how the router gets task-loss gradient. Renormalizing would
    # make the gate identically 1.0 and silently detach the router.
    # top-k>1: renormalize over the chosen experts (GShard) — relative
    # weights still carry gradient there.
    if top_k > 1:
        denom = jnp.maximum(sum(gates), 1e-9)
        gates = [g / denom for g in gates]

    mean_onehot0 = jnp.mean(
        jax.nn.one_hot(experts[0], e, dtype=jnp.float32), axis=0
    )
    mean_probs = jnp.mean(probs, axis=0)
    return gates, experts, mean_onehot0, mean_probs


def _route(
    tokens: jax.Array,  # [n, d] f32-castable
    gate_w: jax.Array,  # [d, E]
    *,
    top_k: int,
    capacity: int,
    rng: jax.Array | None,
    jitter: float,
):
    """Router + static-capacity slotting (the EP transport format).
    Returns (gates, flat_slots, keeps, mean_onehot0 [E], mean_probs [E],
    kept_count scalar)."""
    e = gate_w.shape[-1]
    gates, experts, mean_onehot0, mean_probs = _router(
        tokens, gate_w, top_k=top_k, rng=rng, jitter=jitter
    )

    # Static-capacity slotting: rank-0 assignments queue first, then
    # rank-1, … — each (token, rank) gets a 1-based position in its
    # expert's queue; positions past capacity are dropped.
    counts = jnp.zeros((e,), jnp.int32)
    flat_slots, keeps = [], []
    for ej in experts:
        oh = jax.nn.one_hot(ej, e, dtype=jnp.int32)  # [n, E]
        pos = (jnp.cumsum(oh, axis=0) + counts[None, :]) * oh  # [n, E]
        posj = jnp.sum(pos, axis=-1)  # [n], 1-based
        keeps.append(posj <= capacity)
        flat_slots.append(ej * capacity + jnp.clip(posj - 1, 0, capacity - 1))
        counts = counts + jnp.sum(oh, axis=0)
    kept = sum(jnp.sum(k_.astype(jnp.int32)) for k_ in keeps)
    return gates, flat_slots, keeps, mean_onehot0, mean_probs, kept


def _dispatch(tokens, flat_slots, keeps, e, capacity):
    """Scatter-add kept token rows into the [E·C, d] expert buffers.
    Slots are unique per kept (token, rank) pair, so adds never collide."""
    xin = jnp.zeros((e * capacity, tokens.shape[-1]), tokens.dtype)
    for flat, keep in zip(flat_slots, keeps):
        xin = xin.at[flat].add(
            tokens * keep[:, None].astype(tokens.dtype), mode="drop"
        )
    return xin


def _expert_ffn(xin, w_in, b_in, w_out, b_out):
    """Batched expert FFN over [E, C, d] buffers (one MXU matmul pair)."""
    h = jnp.einsum("ecd,edf->ecf", xin, w_in) + b_in[:, None, :]
    h = jax.nn.gelu(h, approximate=True)
    return jnp.einsum("ecf,efd->ecd", h, w_out) + b_out[:, None, :]


def _combine(yout, flat_slots, keeps, gates, n):
    """Gather each (token, rank)'s output row, gate, and sum — f32."""
    d = yout.shape[-1]
    yflat = yout.reshape(-1, d).astype(jnp.float32)
    out = jnp.zeros((n, d), jnp.float32)
    for flat, keep, gate in zip(flat_slots, keeps, gates):
        out = out + yflat[flat] * (gate * keep)[:, None]
    return out


# megablox gmm tile cap (tm, tk, tn). The kernel's grid is
# ~(n/tn)·(m/tm + g)·(k/tk) steps of one tm x tk x tn MXU pass each; at
# the bench shape ([16384, 768] x [8, 768, 3072]) the upstream default
# (128, 128, 128) is ~19k grid steps whose per-step overhead dwarfs the
# 4.2-MFLOP tile matmul. tools/moe_diag.py sweeps tilings on-chip
# (docs/tpu_sweeps/round5_moe_diag.json when banked); this cap is the
# grid-arithmetic choice pending that sweep, and the compiled-parity
# selftest re-proves numerics under it either way.
GMM_TILE_CAP: int = 512


def _gmm_tiling(m: int, k: int, n: int) -> "tuple[int, int, int]":
    """Largest tiles <= GMM_TILE_CAP the shape admits: tm must DIVIDE m
    (make_group_metadata raises otherwise). tk prefers the largest
    lane-aligned (multiple-of-128) tile in [cap/2, cap] that DIVIDES
    k — at the bench shape k=768 a capped 512 tile leaves a masked 256
    remainder tile on every contraction pass, where 384 tiles it
    exactly — and falls back to ``min(cap, k)`` (masked remainder)
    when no such divisor exists. The cap/2 floor keeps shapes like
    k=640/896 (no large divisor) on one near-cap masked pass instead
    of many tiny exact ones — grid-step overhead is the whole reason
    these tiles are big. n is masked internally so its tile is only
    capped to the dim."""
    tm = GMM_TILE_CAP
    while m % tm:
        tm //= 2
    tk = next(
        (t for t in range(GMM_TILE_CAP, GMM_TILE_CAP // 2 - 1, -128)
         if k % t == 0),
        min(GMM_TILE_CAP, k),
    )
    return tm, tk, min(GMM_TILE_CAP, n)


def _grouped_matmul(lhs, rhs, sizes):
    """[m, k] x [g, k, n] with per-group row segments -> [m, n].

    TPU: the MegaBlocks-style Pallas grouped-matmul kernel
    (jax.experimental megablox ``gmm``, custom-vjp complete — dlhs via
    gmm, drhs via tgmm), which does ~1x the ideal FLOPs with MXU-tiled
    segments. Everywhere else (and for tile-incompatible shapes):
    ``lax.ragged_dot``, whose generic lowering masks a [g, m, k]
    broadcast into one batched dot — g x the ideal FLOPs, fine for
    tests/CPU but exactly what the gmm path exists to avoid on the
    chip."""
    m, k, n = lhs.shape[0], lhs.shape[1], rhs.shape[-1]
    # m (rows) is the one dimension megablox gmm REQUIRES to be
    # tile-divisible (make_group_metadata raises otherwise, e.g. any
    # decode-time token count); k/n remainders it masks internally, but
    # tiny k/n would under-fill the MXU anyway — ragged_dot both cases.
    if (
        jax.default_backend() == "tpu"
        and m % 128 == 0
        and k % 128 == 0
        and n % 128 == 0
    ):
        from jax.experimental.pallas.ops.tpu.megablox import ops as megablox

        # positional: custom_vjp nondiff_argnums forbids keywords here
        return megablox.gmm(lhs, rhs, sizes, lhs.dtype, _gmm_tiling(m, k, n))
    return lax.ragged_dot(lhs, rhs, sizes)


@jax.custom_vjp
def _permute_rows(x, perm, inv_perm):
    """``x[perm]`` with a GATHER backward.

    XLA transposes a gather into a scatter-add; for a PERMUTATION the
    cotangent is just the inverse gather, and row-granularity scatters
    are exactly what the grouped path exists to avoid on TPU (the
    round-4 scatter formulation measured the chip >99% idle). The
    caller supplies the inverse (argsort already produced it)."""
    del inv_perm
    return x[perm]


def _permute_rows_fwd(x, perm, inv_perm):
    return x[perm], (perm, inv_perm)


def _permute_rows_bwd(res, g):
    perm, inv_perm = res
    return g[inv_perm], None, None


_permute_rows.defvjp(_permute_rows_fwd, _permute_rows_bwd)


@jax.custom_vjp
def _masked_row_gather(src, idx, valid, inv_idx, inv_valid):
    """``src[idx] * valid`` where (idx, valid) describes an INJECTIVE
    row map (no two outputs read the same valid source row) and
    (inv_idx, inv_valid) is its precomputed inverse. The cotangent is
    then the inverse masked gather — never a scatter (the capacity
    slotting below precomputes both directions from one argsort)."""
    del inv_idx, inv_valid
    return src[idx] * valid[:, None].astype(src.dtype)


def _masked_row_gather_fwd(src, idx, valid, inv_idx, inv_valid):
    out = src[idx] * valid[:, None].astype(src.dtype)
    return out, (inv_idx, inv_valid)


def _masked_row_gather_bwd(res, g):
    inv_idx, inv_valid = res
    return (
        g[inv_idx] * inv_valid[:, None].astype(g.dtype),
        None, None, None, None,
    )


_masked_row_gather.defvjp(_masked_row_gather_fwd, _masked_row_gather_bwd)


def _pair_sort(experts, e):
    """The shared sort prelude of both sorted formulations: flatten
    (token, rank) pairs TOKEN-MAJOR (pair p = (token p//k, rank p%k)),
    stable-argsort by expert. Returns (eid, order, inv, sizes)."""
    eid = jnp.stack(experts, axis=1).reshape(-1)          # [n·k]
    order = jnp.argsort(eid)                              # stable
    inv = jnp.argsort(order)
    sizes = jnp.bincount(eid, length=e)
    return eid, order, inv, sizes


def _capacity_slots_sorted(tokens, experts, top_k, e, capacity):
    """Build the [E·C, d] dispatch buffer (the EP all-to-all transport
    format) by SORTED GATHERS instead of scatter-adds.

    One argsort of the (token, rank) pairs by expert yields both
    directions of the pair↔slot bijection (each capacity slot is
    filled by at most one kept pair), so dispatch fwd/bwd and combine
    fwd/bwd are all masked gathers via _masked_row_gather — the
    shard_map EP path has no row-granularity scatter left.

    Queue order is sorted-pair order (token-major), not the scatter
    reference's rank-major cumsum — a different overflow victim set,
    same per-(source, expert) quota semantics; identical whenever
    nothing drops (the parity-tested regime).

    Returns (xin [E·C, d], pair_slot [n·k], pair_keep [n·k],
    slot_pair [E·C], slot_valid [E·C], kept scalar).
    """
    n = tokens.shape[0]
    nk = n * top_k
    eid, order, inv, sizes = _pair_sort(experts, e)
    offsets = jnp.cumsum(sizes) - sizes
    pos = inv - jnp.take(offsets, eid)                    # queue position
    pair_keep = pos < capacity
    pair_slot = eid * capacity + jnp.clip(pos, 0, capacity - 1)
    # slot (e, c) <- sorted row offsets[e] + c when c < sizes[e]; that
    # sorted row is pair order[offsets[e] + c], so the slot reads the
    # PAIR directly (one composed gather — no intermediate sorted
    # [n·k, d] copy) and (pair_slot, pair_keep) is its exact inverse.
    slot_j = offsets[:, None] + jnp.arange(capacity)[None, :]   # [E, C]
    slot_valid = (
        jnp.arange(capacity)[None, :] < sizes[:, None]
    ).reshape(-1)
    slot_j = jnp.clip(slot_j, 0, nk - 1).reshape(-1)
    slot_pair = jnp.take(order, slot_j)
    xin = _masked_row_gather(
        jnp.repeat(tokens, top_k, axis=0),
        slot_pair,
        slot_valid,
        pair_slot,
        pair_keep,
    )
    kept = jnp.sum(pair_keep.astype(jnp.int32))
    return xin, pair_slot, pair_keep, slot_pair, slot_valid, kept


def _moe_ffn_grouped(
    gate_w, w_in, b_in, w_out, b_out, x, *, top_k, rng, jitter
):
    """Sort-based DROPLESS dispatch: the single-chip hot path.

    The capacity formulation's scatter-add dispatch and gathered
    combine dominate single-program MoE step time on TPU (round-4
    measured rel_mfu 0.00154 vs dense 0.0624 — the chip idles while
    row-granularity scatters serialize; VERDICT r4 weak #3). This path
    has NO scatter at all:

      argsort (token, rank) pairs by expert → contiguous per-expert
      segments → two ``lax.ragged_dot`` grouped matmuls (XLA's native
      MoE primitive: one MXU pass over [n·k, d] with per-group weight
      selection) → inverse-permutation gather → gated sum over ranks.

    Every shape is static ([n·k, …] regardless of routing), so it jits
    cleanly; group sizes are data. Dropless semantics: no token is ever
    dropped (strictly better than capacity both in quality and in
    wasted slots — there is no padded [E, C] buffer), so the returned
    drop_fraction is identically 0. With ample capacity the capacity
    path computes the same function, which is what the EP parity tests
    check.
    """
    b, s, d = x.shape
    e = gate_w.shape[-1]
    n = b * s
    tokens = x.reshape(n, d)
    gates, experts, moh0, mpr = _router(
        tokens, gate_w, top_k=top_k, rng=rng, jitter=jitter
    )
    aux = e * jnp.sum(moh0 * mpr)

    # Both permutation hops ride _permute_rows so fwd AND bwd are
    # gathers (argsort hands us the inverse for free); the token
    # replication is a jnp.repeat, whose transpose is a contiguous
    # [n, k] reduce — the whole fwd+bwd dispatch path is scatter-free.
    eid, order, inv, sizes = _pair_sort(experts, e)
    sizes = sizes.astype(jnp.int32)
    gat = jnp.stack(gates, axis=1).reshape(-1)            # [n·k] f32
    srt_tok = _permute_rows(
        jnp.repeat(tokens, top_k, axis=0), order, inv
    )                                                     # [n·k, d]
    srt_eid = jnp.take(eid, order, axis=0)

    h = _grouped_matmul(srt_tok, w_in, sizes) + jnp.take(
        b_in, srt_eid, axis=0
    )
    h = jax.nn.gelu(h, approximate=True)
    y = _grouped_matmul(h, w_out, sizes) + jnp.take(b_out, srt_eid, axis=0)

    yw = y.astype(jnp.float32) * _permute_rows(gat, order, inv)[:, None]
    restored = _permute_rows(yw, inv, order)              # pair order
    out = jnp.sum(restored.reshape(n, top_k, d), axis=1)
    return (
        out.reshape(b, s, d).astype(x.dtype),
        aux,
        jnp.float32(0.0),
    )


def moe_ffn(
    gate_w: jax.Array,  # [d, E] router weights
    w_in: jax.Array,    # [E, d, ff]
    b_in: jax.Array,    # [E, ff]
    w_out: jax.Array,   # [E, ff, d]
    b_out: jax.Array,   # [E, d]
    x: jax.Array,       # [B, S, d]
    *,
    capacity_factor: float = 1.25,
    top_k: int = 1,
    rng: jax.Array | None = None,
    jitter: float = 1e-2,
    impl: str | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k MoE FFN (single-program formulations).

    Returns ``(out [B,S,d], aux_loss scalar, drop_fraction scalar)``;
    ``drop_fraction`` is the fraction of (token, rank) assignments that
    overflowed expert capacity and fell through the residual.

    ``impl``: ``"grouped"`` — sort-based dropless dispatch through
    grouped matmuls (drop_fraction ≡ 0; megablox gmm on TPU);
    ``"scatter"`` — the static-capacity scatter/gather formulation
    (Switch drop semantics, the EP transport's reference). Default
    (None) resolves by backend: "grouped" on TPU — where the grouped
    matmul is a real Pallas kernel and row scatters serialize — and
    "scatter" elsewhere, where the grouped path's ragged_dot fallback
    lowers to an E-times-FLOPs masked dot (measured ~6x slower than
    scatter on this CPU) and would skew CPU floors for no benefit.
    """
    if impl is None:
        impl = "grouped" if jax.default_backend() == "tpu" else "scatter"
    if impl not in ("grouped", "scatter"):
        raise ValueError(
            f"moe_ffn impl={impl!r} unknown (expected 'grouped' or "
            "'scatter')"
        )
    b, s, d = x.shape
    e = gate_w.shape[-1]
    n = b * s
    top_k = min(top_k, e)
    if impl == "grouped":
        return _moe_ffn_grouped(
            gate_w, w_in, b_in, w_out, b_out, x,
            top_k=top_k, rng=rng, jitter=jitter,
        )
    tokens = x.reshape(n, d)
    capacity = max(1, int(capacity_factor * top_k * n / e))

    gates, flat_slots, keeps, moh0, mpr, kept = _route(
        tokens, gate_w, top_k=top_k, capacity=capacity, rng=rng, jitter=jitter
    )
    # Switch aux loss over rank-0 assignments:
    # E · Σ_e (fraction of tokens → e) · (mean prob of e).
    aux = e * jnp.sum(moh0 * mpr)
    drop_frac = 1.0 - kept.astype(jnp.float32) / (n * top_k)

    xin = _dispatch(tokens, flat_slots, keeps, e, capacity)
    yout = _expert_ffn(xin.reshape(e, capacity, d), w_in, b_in, w_out, b_out)
    out = _combine(yout, flat_slots, keeps, gates, n)
    return out.reshape(b, s, d).astype(x.dtype), aux, drop_frac


def moe_ffn_ep(
    gate_w: jax.Array,  # [d, E] (replicated)
    w_in: jax.Array,    # [E, d, ff] (sharded over `model`)
    b_in: jax.Array,    # [E, ff]
    w_out: jax.Array,   # [E, ff, d]
    b_out: jax.Array,   # [E, d]
    x: jax.Array,       # [B, S, d] (sharded over batch/context axes)
    *,
    mesh,
    capacity_factor: float = 1.25,
    top_k: int = 1,
    rng: jax.Array | None = None,
    jitter: float = 1e-2,
    impl: str | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Explicit expert-parallel MoE FFN: all-to-all token exchange.

    ``impl`` applies to the SINGLE-PROGRAM fallback only (trivial/
    non-dividing ``model`` axis — see moe_ffn); the shard_map EP path
    is capacity-based by construction (fixed-size all-to-all buffers).

    Same routing math as :func:`moe_ffn`, but dispatch is a
    ``shard_map`` program with POINT-TO-POINT token exchange
    (DESIGN.md §7 EP note): under pure SPMD the partitioner turns the
    scatter/gather dispatch into all-gathers of the full ``[E, C, d]``
    buffer across the ``model`` axis (measured: 0 all-to-all on a
    dp2×model4 mesh — bench.py --bench=moe), moving E·C rows per device
    where an all-to-all moves only C. Here each device routes ITS
    tokens, ships per-expert-group slices to the owning device with one
    ``lax.all_to_all``, runs the local experts' FFN, and ships results
    back with the inverse all-to-all — the GShard/Switch dispatch
    pattern on ICI.

    Tokens are additionally SPLIT over the ``model`` axis inside the
    shard_map (ADVICE r3: the incoming activations are replicated over
    ``model`` under TP, and routing identical copies on every model-rank
    would multiply expert FLOPs and all-to-all payload by m): each
    model-rank takes a contiguous 1/m block of the local token set,
    routes it with capacity/m, and one tiled all-gather over ``model``
    reassembles the combined outputs at the end — per-device expert
    compute is E·C/m slots, the true EP share. Requires
    ``n_local % m == 0`` (any power-of-two batch·seq); otherwise the
    rank-replicated behavior is kept (correct, m× redundant — decode-
    time single-token steps, where FLOPs are negligible anyway).

    Capacity semantics differ from the single-program path by design:
    capacity is per (source rank, expert) — each (device, model-rank)
    may keep up to ``capacity_factor·k·n_local/(m·E)`` tokens per
    expert, so the drop pattern is per-source quota rather than a
    global queue (the standard multi-device MoE behavior; identical
    when nothing overflows). The aux loss is exact: per-expert
    fractions/probs are pmean'd over the token axes (including the
    ``model`` split) BEFORE the product, which equals the global-batch
    Switch aux when shards hold equal token counts (they do: static
    shapes).

    Requires E % mesh.model == 0; gradients flow through the
    all-to-alls (they transpose to themselves reversed) and the
    all-gather (transposes to a psum-scatter).
    """
    from tensorflow_examples_tpu.core.mesh import (
        AxisNames,
        token_partition_axes,
    )

    e = gate_w.shape[-1]
    m = mesh.shape[AxisNames.MODEL] if mesh is not None else 1
    if m <= 1 or e % m:
        return moe_ffn(
            gate_w, w_in, b_in, w_out, b_out, x,
            capacity_factor=capacity_factor, top_k=top_k,
            rng=rng, jitter=jitter, impl=impl,
        )
    top_k = min(top_k, e)
    # Token sharding via the shared axis-dropping policy
    # (core/mesh.py token_partition_axes): a non-dividing axis is
    # dropped — tokens replicate over it, routing stays correct, only
    # the all-to-all over `model` is essential.
    batch_axes, seq_axes = token_partition_axes(mesh, x.shape[0], x.shape[1])
    token_axes = batch_axes + seq_axes
    x_spec = P(
        batch_axes if batch_axes else None,
        seq_axes if seq_axes else None,
        None,
    )
    ew_spec = P(AxisNames.MODEL)  # leading [E] dim of every expert leaf

    def local(gw, wi, bi, wo, bo, xl, key):
        b_loc, s_loc, d = xl.shape
        all_tokens = xl.reshape(-1, d)
        n_all = all_tokens.shape[0]
        # Static decision: split the (model-replicated) local tokens
        # over the model axis so each rank routes a UNIQUE 1/m block.
        split = n_all % m == 0
        if split:
            n_loc = n_all // m
            rank = lax.axis_index(AxisNames.MODEL)
            tokens = lax.dynamic_slice_in_dim(all_tokens, rank * n_loc, n_loc)
        else:
            n_loc, tokens = n_all, all_tokens
        route_axes = token_axes + ((AxisNames.MODEL,) if split else ())
        capacity = max(1, int(capacity_factor * top_k * n_loc / e))
        if key is not None:
            # Decorrelate router jitter across token shards.
            for a in route_axes:
                key = jax.random.fold_in(key, lax.axis_index(a))
        gates, experts, moh0, mpr = _router(
            tokens, gw, top_k=top_k, rng=key, jitter=jitter
        )
        if route_axes:
            moh0 = lax.pmean(moh0, route_axes)
            mpr = lax.pmean(mpr, route_axes)
        aux = e * jnp.sum(moh0 * mpr)
        # Sorted-gather capacity slotting (round 5): the dispatch
        # buffer and the combine are masked gathers in BOTH fwd and
        # bwd — no row-granularity scatter inside the EP program.
        xin, pair_slot, pair_keep, slot_pair, slot_valid, kept = (
            _capacity_slots_sorted(tokens, experts, top_k, e, capacity)
        )
        drop = 1.0 - kept.astype(jnp.float32) / (n_loc * top_k)
        if route_axes:
            drop = lax.pmean(drop, route_axes)

        # [E·C, d] → [m, E/m, C, d]: group g's slice belongs to device g.
        xin = xin.reshape(m, e // m, capacity, d)
        # One hop: device g receives [m(src), E/m, C, d] for ITS experts.
        recv = lax.all_to_all(
            xin, AxisNames.MODEL, split_axis=0, concat_axis=0
        )
        # Local experts over all sources' slots: [E/m, m·C, d].
        buf = recv.transpose(1, 0, 2, 3).reshape(e // m, m * capacity, d)
        yloc = _expert_ffn(buf, wi, bi, wo, bo)
        # Inverse hop: slot layout returns to expert-major [E, C, d].
        yloc = yloc.reshape(e // m, m, capacity, d).transpose(1, 0, 2, 3)
        yout = lax.all_to_all(
            yloc, AxisNames.MODEL, split_axis=0, concat_axis=0
        )
        # Combine: each (token, rank) pair reads its slot (masked
        # gather; inverse = slot->pair map), gates, sums over ranks.
        yflat = yout.reshape(e * capacity, d).astype(jnp.float32)
        gat = jnp.stack(gates, axis=1).reshape(-1)  # [n_loc·k] f32
        y_pair = _masked_row_gather(
            yflat, pair_slot, pair_keep, slot_pair, slot_valid
        )
        out = jnp.sum(
            (y_pair * gat[:, None]).reshape(n_loc, top_k, d), axis=1
        ).astype(xl.dtype)
        if split:
            # Reassemble the model-split blocks (gather order == the
            # axis_index order used for the dynamic_slice above).
            out = lax.all_gather(out, AxisNames.MODEL, tiled=True)
        return out.reshape(b_loc, s_loc, d), aux, drop

    # Pin the expert params' layout so shard_map's in_specs agree with
    # the rules-placed params (no silent resharding inside the step).
    experts_pinned = jax.lax.with_sharding_constraint(
        (w_in, b_in, w_out, b_out), NamedSharding(mesh, ew_spec)
    )
    args = (gate_w, *experts_pinned, x)
    in_specs = (P(), ew_spec, ew_spec, ew_spec, ew_spec, x_spec)
    fn = functools.partial(local, key=None) if rng is None else local
    if rng is not None:
        args += (rng,)
        in_specs += (P(),)
    return _shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=(x_spec, P(), P()),
        check_vma=False,
    )(*args)
