"""Mesh-aware attention dispatch: the jit ↔ shard_map bridge.

XLA auto-partitions dense math from sharding annotations, but a Pallas
kernel is opaque to the SPMD partitioner — calling it under jit with
sharded operands would force an all-gather. ``mesh_attention`` closes the
gap: it wraps the flash kernel (or the ring/Ulysses collectives when the
``context`` axis is real) in ``shard_map`` with the framework's canonical
specs, so batch rides (data, fsdp), heads ride ``model``, and sequence
rides ``context`` — each device runs the kernel on exactly its shard.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tensorflow_examples_tpu.core.collectives import shard_map as _shard_map
from tensorflow_examples_tpu.core.mesh import AxisNames
from tensorflow_examples_tpu.ops.attention import dot_product_attention
from tensorflow_examples_tpu.parallel.ring import ring_attention, ulysses_attention


def attention_spec(mesh: Mesh) -> P:
    """PartitionSpec for [batch, heads, seq, head_dim] on the mesh."""
    batch = tuple(a for a in AxisNames.BATCH_AXES if mesh.shape[a] > 1)
    model = AxisNames.MODEL if mesh.shape[AxisNames.MODEL] > 1 else None
    ctx = AxisNames.CONTEXT if mesh.shape[AxisNames.CONTEXT] > 1 else None
    return P(batch if batch else None, model, ctx, None)


def decode_spec(mesh: Mesh, batch: int, heads: int) -> P:
    """PartitionSpec for decode-time [batch, heads, seq, head_dim]
    operands: batch over the batch axes, heads over ``model`` — the TP
    layout the projections already produce — with each dimension
    replicated instead when its size doesn't divide the mesh axes.
    No ``context`` entry: the KV cache is positionally complete on every
    device; context parallelism is a training-time concept."""
    batch_axes = tuple(a for a in AxisNames.BATCH_AXES if mesh.shape[a] > 1)
    nb = math.prod(mesh.shape[a] for a in batch_axes) if batch_axes else 1
    if batch % nb:
        batch_axes = ()
    m = mesh.shape[AxisNames.MODEL]
    model = AxisNames.MODEL if m > 1 and heads % m == 0 else None
    return P(batch_axes if batch_axes else None, model, None, None)


def mesh_decode_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    length: jax.Array,
    *,
    mesh: Mesh | None,
    sm_scale: float | None = None,
) -> jax.Array:
    """KV-cache flash-decode on a mesh: the Pallas kernel is opaque to
    the SPMD partitioner (calling it with sharded operands would force
    an all-gather of the cache — the exact O(max_len) read the kernel
    exists to avoid), so it runs under ``shard_map`` with batch/heads
    sharding. Single-device meshes fall through to the plain kernel."""
    from tensorflow_examples_tpu.ops.decode import flash_decode_attention

    if mesh is None or all(mesh.shape[a] == 1 for a in AxisNames.ALL):
        return flash_decode_attention(q, k_cache, v_cache, length, sm_scale=sm_scale)
    spec = decode_spec(mesh, q.shape[0], q.shape[1])
    local = functools.partial(flash_decode_attention, sm_scale=sm_scale)
    return _shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec, P()),
        out_specs=spec,
        check_vma=False,
    )(q, k_cache, v_cache, length)


def _stage_tp_axis(heads: int):
    """Detect the PP×TP stage situation: we are INSIDE a manual
    (shard_map) region — a pipeline stage — whose ``model`` axis is
    still AUTO and nontrivial, and the head count divides it. Returns
    the axis name to nest a model-only shard_map over, else None.

    Without this, a flash call inside a pipe-manual stage is opaque to
    the partitioner, which all-gathers the model-sharded heads around
    the Pallas kernel (the round-3 reason PP×TP stages had to use
    ``attention="xla"``)."""
    get_am = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_am is None:
        # jax builds without the abstract-mesh API can't express the
        # pipe-manual nesting either — there is no stage context.
        return None
    am = get_am()
    manual = getattr(am, "manual_axes", ()) if am is not None else ()
    if not manual or AxisNames.MODEL in manual:
        return None
    m = dict(am.shape).get(AxisNames.MODEL, 1)
    if m > 1 and heads % m == 0:
        return AxisNames.MODEL
    return None


def mesh_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh | None,
    causal: bool = True,
    sm_scale: float | None = None,
    impl: str = "flash",  # flash | xla | ring | ulysses
    key_bias: jax.Array | None = None,
) -> jax.Array:
    """Attention on [B, H, S, D] operands laid out on ``mesh``.

    With no mesh (or a trivial one) this is the plain single-device
    dispatcher; otherwise a shard_map over the canonical spec. ``ring`` /
    ``ulysses`` select the context-parallel algorithm when
    mesh.context > 1 (``flash`` defaults to ring in that case).

    ``key_bias`` ([B, S_kv] additive score bias — padding masks, the
    BERT path) routes through the flash kernel's bias variant under the
    decode-style spec: batch over the batch axes, heads over ``model``
    (each with replication fallback when the dim doesn't divide), seq
    replicated — so TP meshes shard heads WITHOUT gathering around the
    opaque Pallas call (ADVICE r3), and any mesh that doesn't fit
    simply replicates that dim (the ring/ulysses context algorithms
    carry no bias plumbing). Not supported with ``impl="xla"``.
    """
    from tensorflow_examples_tpu.ops.attention import flash_attention

    if key_bias is not None and impl == "xla":
        raise ValueError("key_bias requires the flash path (impl != 'xla')")
    if impl == "xla":
        return dot_product_attention(
            q, k, v, causal=causal, sm_scale=sm_scale, use_flash=False
        )
    if mesh is None or all(mesh.shape[a] == 1 for a in AxisNames.ALL):
        tp = _stage_tp_axis(q.shape[1])
        if tp is not None:
            # PP×TP stage: nest a model-only shard_map (the context
            # mesh already has `pipe` manual) so heads stay sharded
            # around the Pallas call. Proven exact fwd+bwd. No stage
            # caller passes key_bias today (only BERT does, and BERT
            # has no pipeline path) — keep that explicit rather than
            # shipping an unexercised bias-cotangent path.
            if key_bias is not None:
                raise NotImplementedError(
                    "key_bias inside a pipeline stage is unexercised; "
                    "add a test with the bias grad psum before enabling"
                )
            spec = P(None, tp, None, None)
            return _shard_map(
                lambda ql, kl, vl: flash_attention(
                    ql, kl, vl, causal=causal, sm_scale=sm_scale
                ),
                in_specs=(spec, spec, spec),
                out_specs=spec,
                axis_names={tp},
                check_vma=False,
            )(q, k, v)
        if key_bias is not None:
            return flash_attention(
                q, k, v, causal=causal, sm_scale=sm_scale, key_bias=key_bias
            )
        return dot_product_attention(q, k, v, causal=causal, sm_scale=sm_scale)

    has_context = mesh.shape[AxisNames.CONTEXT] > 1
    if key_bias is not None:
        # Divisibility-safe spec (replication fallback per dim), same
        # as the decode path — a non-dividing head count must not turn
        # a previously-working flash config into a trace error.
        spec = decode_spec(mesh, q.shape[0], q.shape[1])
        bias_spec = P(spec[0], None)
        out = _shard_map(
            lambda ql, kl, vl, bl: flash_attention(
                ql, kl, vl, causal=causal, sm_scale=sm_scale, key_bias=bl
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec, bias_spec),
            out_specs=spec,
            check_vma=False,
        )(q, k, v, key_bias)
        return out
    if has_context and impl == "ulysses":
        local = functools.partial(
            ulysses_attention,
            axis_name=AxisNames.CONTEXT, causal=causal, sm_scale=sm_scale,
        )
    elif has_context:
        local = functools.partial(
            ring_attention,
            axis_name=AxisNames.CONTEXT, causal=causal, sm_scale=sm_scale,
        )
    else:
        local = functools.partial(
            dot_product_attention, causal=causal, sm_scale=sm_scale
        )
    # Causal context-parallel padding (VERDICT r3 item 7 — the zigzag
    # odd-shard corner): pad the GLOBAL sequence so every shard is even
    # (zigzag always eligible, perfectly balanced) and every half-chunk
    # kernel-tileable. Tail pads sit at the causal future of every real
    # query — no real row ever attends a pad key, pad rows' outputs are
    # sliced off, and their grads are dropped by the slice transpose.
    # Only valid for causal attention (non-causal would softmax over
    # the pad keys), which is exactly where zigzag applies.
    seq = q.shape[2]
    pad = 0
    if has_context and causal and impl != "ulysses":
        c = mesh.shape[AxisNames.CONTEXT]
        target = -(-seq // (2 * c)) * (2 * c)  # next multiple of 2c
        # Kernel tileability: the zigzag path attends both single
        # half-chunks (length hc) and concatenated pairs (2·hc), so
        # each must either ride one block (≤ 256) or tile by 8
        # (2·hc % 8 == 0 ⟺ hc % 4 == 0).
        hc = target // (2 * c)
        while (hc > 256 and hc % 8) or (2 * hc > 256 and hc % 4):
            target += 2 * c
            hc = target // (2 * c)
        pad = target - seq
    if pad:
        widths = [(0, 0), (0, 0), (0, pad), (0, 0)]
        q, k, v = (jnp.pad(a, widths) for a in (q, k, v))
    spec = attention_spec(mesh)
    # check_vma=False: the Pallas kernel's out_shape carries no
    # varying-axes type, which the vma checker (jax 0.9) rejects.
    out = _shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
    return out[:, :, :seq] if pad else out
