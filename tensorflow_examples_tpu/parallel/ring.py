"""Sequence/context-parallel attention collectives (SURVEY.md §5g).

The reference has no long-context machinery (a TF-examples repo predates
it); these are framework-native extensions required by the task template,
designed TPU-first:

- ``ring_attention``: blockwise attention over a ``context`` mesh axis.
  Each device holds a sequence shard of Q/K/V; K/V shards rotate around
  the ring with ``jax.lax.ppermute`` (nearest-neighbor ICI traffic, no
  all-gather). Every hop attends the arriving KV shard with the Pallas
  flash kernel (``flash_attention_with_lse``) and hop results merge
  exactly through their logsumexp — so per-device memory is
  O(S/c · d) activations + O(block²) VMEM, never O((S/c)²), and the
  inner loop runs at full single-device kernel efficiency. Gradients
  flow through the merge AND the lse (the kernel's custom VJP carries
  the lse cotangent), so the whole ring differentiates exactly.
- ``ulysses_attention``: the all-to-all alternative — reshard from
  sequence-sharded to head-sharded with ``all_to_all``, run the local
  flash kernel on full sequences for H/c heads, reshard back. Two
  all-to-alls per call, but the inner loop is the single-device Pallas
  kernel at full efficiency; preferable when heads ≥ ring size.

Both run inside ``shard_map`` (see parallel/attention.py for the jit-level
wrapper) and differentiate through the collectives (ppermute/all_to_all
transpose to themselves under AD).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from tensorflow_examples_tpu.ops.attention import (
    NEG_INF,
    flash_attention,
    flash_attention_with_lse,
)


def _merge(out, lse, o_blk, lse_blk):
    """Exact merge of two partial attentions via their logsumexp.

    out/o_blk: [B,H,S,D] f32; lse/lse_blk: [B,H,S]. A hop whose
    ``lse_blk`` is NEG_INF contributes weight exp(NEG_INF−lse)=0, which
    is how fully-masked (future) shards drop out.
    """
    lse_new = jnp.logaddexp(lse, lse_blk)
    w_old = jnp.exp(lse - lse_new)[..., None]
    w_blk = jnp.exp(lse_blk - lse_new)[..., None]
    return out * w_old + o_blk.astype(jnp.float32) * w_blk, lse_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "context",
    causal: bool = True,
    sm_scale: float | None = None,
) -> jax.Array:
    """Context-parallel attention; call inside ``shard_map``.

    q, k, v: [batch, heads, seq_shard, head_dim] — the local sequence
    shard on this device. Sharding along ``axis_name`` is assumed to be
    contiguous ascending (shard i holds tokens [i·s, (i+1)·s)), which is
    what ``NamedSharding(P(..., 'context', ...))`` produces.
    """
    axis_size = lax.axis_size(axis_name)
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if axis_size == 1:
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)

    my_idx = lax.axis_index(axis_name)
    s_loc = q.shape[2]
    qf = q.astype(jnp.float32)
    row = lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 0)
    col = lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 1)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def merge(carry, step, k_blk, v_blk):
        m, l, acc = carry
        # After `step` rotations this device holds KV shard (my_idx - step).
        kv_idx = (my_idx - step) % axis_size
        if causal:
            # Global causality between shard indices: earlier KV shard →
            # fully visible; same shard → triangular; later → fully masked.
            mask = (kv_idx < my_idx) | ((kv_idx == my_idx) & (row >= col))
        else:
            mask = jnp.ones((s_loc, s_loc), bool)
        bm, bl, bacc = _block_attend(qf, k_blk, v_blk, mask, sm_scale)
        m_new = jnp.maximum(m, bm)
        a_old = jnp.exp(m - m_new)
        a_blk = jnp.exp(bm - m_new)
        l_new = l * a_old + bl * a_blk
        acc_new = acc * a_old[..., None] + bacc * a_blk[..., None]
        return m_new, l_new, acc_new

    def body(carry, step):
        m, l, acc, k_blk, v_blk = carry
        m, l, acc = merge((m, l, acc), step, k_blk, v_blk)
        # Rotate KV one hop around the ring (nearest-neighbor ICI).
        k_nxt, v_nxt = lax.ppermute((k_blk, v_blk), axis_name, perm)
        return (m, l, acc, k_nxt, v_nxt), None

    # Initial carries derived from q (not fresh zeros) so they inherit
    # q's varying-axes type under shard_map; XLA folds the dead arithmetic.
    acc0 = jnp.zeros_like(qf)
    m0 = acc0[..., 0] - _STABLE_MIN
    l0 = acc0[..., 0]
    # Remat the body: recompute each block's scores in backward instead of
    # saving c × [s_loc, s_loc] score matrices. The final block merges
    # outside the scan so its KV shard is not pointlessly rotated onward
    # (saves 1/c of all ring traffic).
    (m, l, acc, k_last, v_last), _ = lax.scan(
        jax.checkpoint(body), (m0, l0, acc0, k, v), jnp.arange(axis_size - 1)
    )
    m, l, acc = jax.checkpoint(merge)(
        (m, l, acc), axis_size - 1, k_last, v_last
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "context",
    causal: bool = True,
    sm_scale: float | None = None,
) -> jax.Array:
    """All-to-all sequence parallelism; call inside ``shard_map``.

    q, k, v: [batch, heads, seq_shard, head_dim]. Requires
    heads % axis_size == 0. Reshards seq→heads, runs the local Pallas
    flash kernel over the full sequence, reshards back.
    """
    axis_size = lax.axis_size(axis_name)
    if axis_size == 1:
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    h = q.shape[1]
    if h % axis_size:
        raise ValueError(f"heads {h} not divisible by context axis {axis_size}")

    # [B, H, s, D] → [B, H/c, S, D]: gather seq, scatter heads.
    to_seq = functools.partial(
        lax.all_to_all, axis_name=axis_name, split_axis=1, concat_axis=2,
        tiled=True,
    )
    ql, kl, vl = to_seq(q), to_seq(k), to_seq(v)
    out = flash_attention(ql, kl, vl, causal=causal, sm_scale=sm_scale)
    return lax.all_to_all(
        out, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True
    )
