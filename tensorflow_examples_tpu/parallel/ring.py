"""Sequence/context-parallel attention collectives (SURVEY.md §5g).

The reference has no long-context machinery (a TF-examples repo predates
it); these are framework-native extensions required by the task template,
designed TPU-first:

- ``ring_attention``: blockwise attention over a ``context`` mesh axis.
  Each device holds a sequence shard of Q/K/V; K/V shards rotate around
  the ring with ``jax.lax.ppermute`` (nearest-neighbor ICI traffic, no
  all-gather). Every hop attends the arriving KV shard with the Pallas
  flash kernel (``flash_attention_with_lse``) and hop results merge
  exactly through their logsumexp — so per-device memory is
  O(S/c · d) activations + O(block²) VMEM, never O((S/c)²), and the
  inner loop runs at full single-device kernel efficiency. Gradients
  flow through the merge AND the lse (the kernel's custom VJP carries
  the lse cotangent), so the whole ring differentiates exactly.

  **Causal load balance (VERDICT r2 item 2)**: with contiguous shards,
  causality makes device 0 need 1 hop of real work and device c-1 all
  c — and because SPMD devices move in lockstep, masked hops cost full
  wall time even when skipped. The fix is **zigzag sharding** (the
  ring-flash / llama-3 style): the sequence is split into 2c chunks and
  ring position d works on chunks (d, 2c-1-d) — one early, one late —
  so every device does exactly 2 half-chunk attends per hop, the causal
  minimum, ~half the FLOPs AND wall time of the naive ring. The
  permutation happens *inside* the shard_map with half-shard ppermutes
  (`_to_zigzag`/`_from_zigzag`), so callers still see contiguous
  sharding in and out. Causal calls default to it, and the jit-level
  wrapper (parallel/attention.py mesh_attention) pads the global
  sequence so causal shards are ALWAYS even — the balanced path is the
  only causal path in practice. The contiguous variant remains for
  explicit ``zigzag=False`` and non-causal calls; its causal form
  skips fully-masked hops with ``lax.cond`` (no FLOPs burned, though
  lockstep means no wall gain).
- ``ulysses_attention``: the all-to-all alternative — reshard from
  sequence-sharded to head-sharded with ``all_to_all``, run the local
  flash kernel on full sequences for H/c heads, reshard back. Two
  all-to-alls per call, but the inner loop is the single-device Pallas
  kernel at full efficiency; preferable when heads ≥ ring size.

Both run inside ``shard_map`` (see parallel/attention.py for the jit-level
wrapper) and differentiate through the collectives (ppermute/all_to_all
transpose to themselves under AD).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tensorflow_examples_tpu.core import collectives as coll
from tensorflow_examples_tpu.ops.attention import (
    NEG_INF,
    flash_attention,
    flash_attention_with_lse,
)


def _merge(out, lse, o_blk, lse_blk):
    """Exact merge of two partial attentions via their logsumexp.

    out/o_blk: [B,H,S,D] f32; lse/lse_blk: [B,H,S]. A hop whose
    ``lse_blk`` is NEG_INF contributes weight exp(NEG_INF−lse)=0, which
    is how fully-masked (future) shards drop out.
    """
    lse_new = jnp.logaddexp(lse, lse_blk)
    w_old = jnp.exp(lse - lse_new)[..., None]
    w_blk = jnp.exp(lse_blk - lse_new)[..., None]
    return out * w_old + o_blk.astype(jnp.float32) * w_blk, lse_new


def _zigzag_perms(c: int):
    """Static ppermute tables for the contiguous ↔ zigzag exchange.

    Chunk g ∈ [0, 2c) lives contiguously on device g//2 and zigzag on
    device z(g) = g if g < c else 2c-1-g. Each table routes one chunk
    per device, so the whole exchange is two half-shard ppermutes each
    way (even chunks and odd chunks are separately a bijection over
    devices)."""
    z = lambda g: g if g < c else 2 * c - 1 - g
    fwd_even = [(i, z(2 * i)) for i in range(c)]
    fwd_odd = [(i, z(2 * i + 1)) for i in range(c)]
    bwd_even = [(z(2 * i), i) for i in range(c)]
    bwd_odd = [(z(2 * i + 1), i) for i in range(c)]
    return fwd_even, fwd_odd, bwd_even, bwd_odd


def _to_zigzag(x, axis_name: str, c: int, my_idx):
    """[B,H,2·sc,D] contiguous shard → (early, late) zigzag chunks.

    Zigzag device d's early chunk (global chunk d) has d's parity, its
    late chunk (2c-1-d) the opposite — hence the parity select."""
    fwd_even, fwd_odd, _, _ = _zigzag_perms(c)
    sc = x.shape[2] // 2
    recv_even = coll.ppermute(x[:, :, :sc], axis_name, fwd_even)
    recv_odd = coll.ppermute(x[:, :, sc:], axis_name, fwd_odd)
    is_even = (my_idx % 2) == 0
    early = jnp.where(is_even, recv_even, recv_odd)
    late = jnp.where(is_even, recv_odd, recv_even)
    return early, late


def _from_zigzag(early, late, axis_name: str, c: int, my_idx):
    """(early, late) zigzag chunks → [B,H,2·sc,D] contiguous shard."""
    _, _, bwd_even, bwd_odd = _zigzag_perms(c)
    is_even = (my_idx % 2) == 0
    a = coll.ppermute(
        jnp.where(is_even, early, late), axis_name, bwd_even
    )
    b = coll.ppermute(
        jnp.where(is_even, late, early), axis_name, bwd_odd
    )
    return jnp.concatenate([a, b], axis=2)


def _ring_causal_zigzag(q, k, v, axis_name: str, axis_size: int, sm_scale):
    """Causal ring attention on zigzag-exchanged shards: every hop costs
    exactly 2 half-chunk attends on every device — the causal minimum,
    perfectly balanced (see module docstring)."""
    c = axis_size
    my = coll.axis_index(axis_name)
    qe, ql = _to_zigzag(q, axis_name, c, my)
    ke, kl = _to_zigzag(k, axis_name, c, my)
    ve, vl = _to_zigzag(v, axis_name, c, my)
    sc = qe.shape[2]

    # Hop 0 — the diagonal: both local chunks attend themselves causally
    # and the late chunk additionally sees the whole early chunk.
    oe, lse_e = flash_attention_with_lse(qe, ke, ve, causal=True, sm_scale=sm_scale)
    ol, lse_l = flash_attention_with_lse(ql, kl, vl, causal=True, sm_scale=sm_scale)
    oe = oe.astype(jnp.float32)
    o_le, lse_le = flash_attention_with_lse(
        ql, ke, ve, causal=False, sm_scale=sm_scale
    )
    ol, lse_l = _merge(ol.astype(jnp.float32), lse_l, o_le, lse_le)

    perm = coll.ring_perm(c)

    def body(carry, step):
        oe, lse_e, ol, lse_l, ke, kl, ve, vl = carry
        # Rotate the KV chunk pair one hop; after `step` hops this
        # device holds ring position j = (my - step) % c, i.e. global
        # chunks j (early) and 2c-1-j (late).
        ke, kl, ve, vl = coll.ppermute((ke, kl, ve, vl), axis_name, perm)
        j = (my - step) % c

        def earlier(_):
            # j < my: K-chunk j is in both local chunks' past; the late
            # K-chunk 2c-1-j is in neither's. One kernel call over the
            # stacked Q chunks.
            qcat = jnp.concatenate([qe, ql], axis=2)
            o, lse = flash_attention_with_lse(
                qcat, ke, ve, causal=False, sm_scale=sm_scale
            )
            return o[:, :, :sc], lse[:, :, :sc], o[:, :, sc:], lse[:, :, sc:]

        def later(_):
            # j > my: only the local late chunk (2c-1-my) sees anything,
            # and it sees both arriving chunks (j and 2c-1-j < 2c-1-my).
            kcat = jnp.concatenate([ke, kl], axis=2)
            vcat = jnp.concatenate([ve, vl], axis=2)
            o, lse = flash_attention_with_lse(
                ql, kcat, vcat, causal=False, sm_scale=sm_scale
            )
            return (
                jnp.zeros(qe.shape, o.dtype),
                jnp.full(lse.shape, NEG_INF, lse.dtype),
                o,
                lse,
            )

        d_oe, d_lse_e, d_ol, d_lse_l = jax.lax.cond(j < my, earlier, later, None)
        oe, lse_e = _merge(oe, lse_e, d_oe, d_lse_e)
        ol, lse_l = _merge(ol, lse_l, d_ol, d_lse_l)
        return (oe, lse_e, ol, lse_l, ke, kl, ve, vl), None

    (oe, _, ol, _, *_), _ = jax.lax.scan(
        jax.checkpoint(body),
        (oe, lse_e, ol, lse_l, ke, kl, ve, vl),
        jnp.arange(1, c),
    )
    out = _from_zigzag(oe, ol, axis_name, c, my)
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "context",
    causal: bool = True,
    sm_scale: float | None = None,
    zigzag: bool | None = None,
) -> jax.Array:
    """Context-parallel attention; call inside ``shard_map``.

    q, k, v: [batch, heads, seq_shard, head_dim] — the local sequence
    shard on this device. Sharding along ``axis_name`` is assumed to be
    contiguous ascending (shard i holds tokens [i·s, (i+1)·s)), which is
    what ``NamedSharding(P(..., 'context', ...))`` produces.

    ``zigzag`` (causal only): balance the causal load by internally
    re-sharding to the zigzag layout — ~2× fewer FLOPs and wall time
    than the contiguous ring (module docstring). ``None`` = auto: on
    whenever causal and the shard length is even.
    """
    axis_size = coll.axis_size(axis_name)
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if axis_size == 1:
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    if zigzag is None:
        zigzag = causal and q.shape[2] % 2 == 0
    if zigzag and not causal:
        raise ValueError("zigzag ring attention only applies to causal")
    if zigzag and q.shape[2] % 2:
        raise ValueError(f"zigzag needs an even shard length, got {q.shape[2]}")
    if zigzag:
        return _ring_causal_zigzag(q, k, v, axis_name, axis_size, sm_scale)

    my_idx = coll.axis_index(axis_name)
    perm = coll.ring_perm(axis_size)

    # Hop 0 is the local (diagonal) shard: the only hop that needs the
    # intra-shard causal triangle, so it uses the causal kernel variant.
    out, lse = flash_attention_with_lse(q, k, v, causal=causal, sm_scale=sm_scale)
    out = out.astype(jnp.float32)

    def body(carry, step):
        out, lse, k_blk, v_blk = carry
        # Rotate KV one hop around the ring (nearest-neighbor ICI). After
        # `step` rotations this device holds KV shard (my_idx - step).
        k_blk, v_blk = coll.ppermute((k_blk, v_blk), axis_name, perm)

        def attend(_):
            return flash_attention_with_lse(
                q, k_blk, v_blk, causal=False, sm_scale=sm_scale
            )

        if causal:
            # Global causality between shard indices: an earlier KV
            # shard is fully visible, a later one fully masked — skip
            # the attend entirely (lax.cond; lockstep means no wall-time
            # win, but the FLOPs and HBM traffic aren't burned) and
            # contribute NEG_INF lse so the merge weight is exp→0.
            kv_idx = (my_idx - step) % axis_size

            def skip(_):
                return (
                    jnp.zeros(q.shape, q.dtype),
                    jnp.full(q.shape[:3], NEG_INF, jnp.float32),
                )

            o_blk, lse_blk = jax.lax.cond(kv_idx < my_idx, attend, skip, None)
        else:
            o_blk, lse_blk = attend(None)
        out, lse = _merge(out, lse, o_blk, lse_blk)
        return (out, lse, k_blk, v_blk), None

    # Remat the body: recompute each hop's flash attend in backward
    # instead of saving per-hop (o, lse) pairs. axis_size-1 iterations,
    # so the last shard is never pointlessly rotated onward (saves 1/c of
    # all ring traffic).
    (out, lse, _, _), _ = jax.lax.scan(
        jax.checkpoint(body), (out, lse, k, v), jnp.arange(1, axis_size)
    )
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "context",
    causal: bool = True,
    sm_scale: float | None = None,
) -> jax.Array:
    """All-to-all sequence parallelism; call inside ``shard_map``.

    q, k, v: [batch, heads, seq_shard, head_dim]. Requires
    heads % axis_size == 0. Reshards seq→heads, runs the local Pallas
    flash kernel over the full sequence, reshards back.
    """
    axis_size = coll.axis_size(axis_name)
    if axis_size == 1:
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    h = q.shape[1]
    if h % axis_size:
        raise ValueError(f"heads {h} not divisible by context axis {axis_size}")

    # [B, H, s, D] → [B, H/c, S, D]: gather seq, scatter heads.
    to_seq = functools.partial(
        coll.all_to_all, axis=axis_name, split_axis=1, concat_axis=2
    )
    ql, kl, vl = to_seq(q), to_seq(k), to_seq(v)
    out = flash_attention(ql, kl, vl, causal=causal, sm_scale=sm_scale)
    return coll.all_to_all(out, axis_name, split_axis=2, concat_axis=1)
