"""Sequence/context-parallel attention collectives (SURVEY.md §5g).

The reference has no long-context machinery (a TF-examples repo predates
it); these are framework-native extensions required by the task template,
designed TPU-first:

- ``ring_attention``: blockwise attention over a ``context`` mesh axis.
  Each device holds a sequence shard of Q/K/V; K/V shards rotate around
  the ring with ``jax.lax.ppermute`` (nearest-neighbor ICI traffic, no
  all-gather). Every hop attends the arriving KV shard with the Pallas
  flash kernel (``flash_attention_with_lse``) and hop results merge
  exactly through their logsumexp — so per-device memory is
  O(S/c · d) activations + O(block²) VMEM, never O((S/c)²), and the
  inner loop runs at full single-device kernel efficiency. Gradients
  flow through the merge AND the lse (the kernel's custom VJP carries
  the lse cotangent), so the whole ring differentiates exactly.
- ``ulysses_attention``: the all-to-all alternative — reshard from
  sequence-sharded to head-sharded with ``all_to_all``, run the local
  flash kernel on full sequences for H/c heads, reshard back. Two
  all-to-alls per call, but the inner loop is the single-device Pallas
  kernel at full efficiency; preferable when heads ≥ ring size.

Both run inside ``shard_map`` (see parallel/attention.py for the jit-level
wrapper) and differentiate through the collectives (ppermute/all_to_all
transpose to themselves under AD).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tensorflow_examples_tpu.core import collectives as coll
from tensorflow_examples_tpu.ops.attention import (
    NEG_INF,
    flash_attention,
    flash_attention_with_lse,
)


def _merge(out, lse, o_blk, lse_blk):
    """Exact merge of two partial attentions via their logsumexp.

    out/o_blk: [B,H,S,D] f32; lse/lse_blk: [B,H,S]. A hop whose
    ``lse_blk`` is NEG_INF contributes weight exp(NEG_INF−lse)=0, which
    is how fully-masked (future) shards drop out.
    """
    lse_new = jnp.logaddexp(lse, lse_blk)
    w_old = jnp.exp(lse - lse_new)[..., None]
    w_blk = jnp.exp(lse_blk - lse_new)[..., None]
    return out * w_old + o_blk.astype(jnp.float32) * w_blk, lse_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "context",
    causal: bool = True,
    sm_scale: float | None = None,
) -> jax.Array:
    """Context-parallel attention; call inside ``shard_map``.

    q, k, v: [batch, heads, seq_shard, head_dim] — the local sequence
    shard on this device. Sharding along ``axis_name`` is assumed to be
    contiguous ascending (shard i holds tokens [i·s, (i+1)·s)), which is
    what ``NamedSharding(P(..., 'context', ...))`` produces.
    """
    axis_size = coll.axis_size(axis_name)
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if axis_size == 1:
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)

    my_idx = coll.axis_index(axis_name)
    perm = coll.ring_perm(axis_size)

    # Hop 0 is the local (diagonal) shard: the only hop that needs the
    # intra-shard causal triangle, so it uses the causal kernel variant.
    out, lse = flash_attention_with_lse(q, k, v, causal=causal, sm_scale=sm_scale)
    out = out.astype(jnp.float32)

    def body(carry, step):
        out, lse, k_blk, v_blk = carry
        # Rotate KV one hop around the ring (nearest-neighbor ICI). After
        # `step` rotations this device holds KV shard (my_idx - step).
        k_blk, v_blk = coll.ppermute((k_blk, v_blk), axis_name, perm)
        o_blk, lse_blk = flash_attention_with_lse(
            q, k_blk, v_blk, causal=False, sm_scale=sm_scale
        )
        if causal:
            # Global causality between shard indices: an earlier KV shard
            # is fully visible, a later one fully masked — drop it by
            # sending its lse to NEG_INF so the merge weight is exp→0.
            kv_idx = (my_idx - step) % axis_size
            lse_blk = jnp.where(kv_idx < my_idx, lse_blk, NEG_INF)
        out, lse = _merge(out, lse, o_blk, lse_blk)
        return (out, lse, k_blk, v_blk), None

    # Remat the body: recompute each hop's flash attend in backward
    # instead of saving per-hop (o, lse) pairs. axis_size-1 iterations,
    # so the last shard is never pointlessly rotated onward (saves 1/c of
    # all ring traffic).
    (out, lse, _, _), _ = jax.lax.scan(
        jax.checkpoint(body), (out, lse, k, v), jnp.arange(1, axis_size)
    )
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "context",
    causal: bool = True,
    sm_scale: float | None = None,
) -> jax.Array:
    """All-to-all sequence parallelism; call inside ``shard_map``.

    q, k, v: [batch, heads, seq_shard, head_dim]. Requires
    heads % axis_size == 0. Reshards seq→heads, runs the local Pallas
    flash kernel over the full sequence, reshards back.
    """
    axis_size = coll.axis_size(axis_name)
    if axis_size == 1:
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    h = q.shape[1]
    if h % axis_size:
        raise ValueError(f"heads {h} not divisible by context axis {axis_size}")

    # [B, H, s, D] → [B, H/c, S, D]: gather seq, scatter heads.
    to_seq = functools.partial(
        coll.all_to_all, axis=axis_name, split_axis=1, concat_axis=2
    )
    ql, kl, vl = to_seq(q), to_seq(k), to_seq(v)
    out = flash_attention(ql, kl, vl, causal=causal, sm_scale=sm_scale)
    return coll.all_to_all(out, axis_name, split_axis=2, concat_axis=1)
