"""Pipeline parallelism over the ``pipe`` mesh axis: GPipe and 1F1B.

Framework-native extension (SURVEY.md §2d — the reference had no PP; the
distributed design here treats it as a first-class mesh axis like
dp/fsdp/tp/sp). TPU-first shape:

- Stage parameters are the *same pytree* with a leading [stages] axis
  sharded over ``pipe`` — placement is a sharding rule, not a code path,
  exactly like tensor parallelism.
- Schedules run inside a PARTIAL-MANUAL ``shard_map``
  (``axis_names={'pipe'}``): only the pipe axis is manual — each rank
  applies its own stage and activations hop stage→stage with
  ``jax.lax.ppermute`` (nearest-neighbor ICI) — while the batch and
  ``model`` axes stay under the automatic partitioner. That is what
  lets PP COMPOSE with DP/FSDP/TP: inside a stage the math is ordinary
  global-view JAX, so TP falls out of the stacked params' sharding
  rules (workloads/gpt2.py pipe×model rules) exactly as in the
  non-pipelined model, and DP gradient reductions are inserted by XLA
  — no hand-written pmeans.

Two schedules:

- **GPipe** (``pipeline_apply``): forward-only building block whose
  backward is JAX's transpose of the schedule (ppermute transposes to
  the reverse hop). Microbatches stream over M + P - 1 ticks; bubble
  ticks SKIP the stage compute via ``lax.cond`` (VERDICT r2 item 3 —
  previously they burned full FLOPs on clipped garbage). Saved state is
  O(M · microbatch) activations under per-tick remat.
- **1F1B** (``make_pipeline_1f1b``): the real training schedule. The
  per-microbatch loss is computed at the LAST stage inside the
  scheduled program, so microbatch m's backward starts as soon as its
  forward leaves the pipe — forwards and backwards interleave in the
  classic one-forward-one-backward steady state, stages idle only in
  the unavoidable 2(P-1)-tick ramp, and in-flight activations are
  bounded by P - s per stage (the 1F1B memory bound) instead of M.
  Gradients never come from transposing the scan: each backward tick
  recomputes its stage forward from the stashed input (remat) and
  accumulates explicit per-stage param grads, which leave the
  shard_map still sharded over ``pipe``. The schedule itself is
  simulated in numpy at trace time (`_schedule_1f1b`) — per-tick op
  tables with machine-checked queue/stash invariants — and the whole
  thing is wrapped in ``jax.custom_vjp`` so the surrounding
  embed/optimizer code auto-differentiates through it unchanged.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorflow_examples_tpu.core.collectives import shard_map as _shard_map

from tensorflow_examples_tpu.core import collectives as coll
from tensorflow_examples_tpu.core.mesh import AxisNames


def _psum_pipe(tree, axis_name):
    """psum over the pipe axis with sub-f32 leaves routed through f32.

    Works around a jaxlib CPU compiler abort (`Invalid binary
    instruction opcode copy` in AllReducePromotion/CloneAllReduce) when
    a bf16/f16 all-reduce appears inside a PARTIAL-manual shard_map
    region — the full-manual formulation compiles the same reduce fine.
    CPU promotes sub-f32 all-reduces to f32 anyway, so this costs
    nothing there; on TPU it spends 2× bytes on the once-per-step
    loss/grad pipe reduces, noise next to the per-tick activation hops.
    """

    def up(x):
        if x.dtype in (jnp.bfloat16, jnp.float16):
            return x.astype(jnp.float32)
        return x

    out = coll.psum(jax.tree.map(up, tree), axis_name)
    return jax.tree.map(lambda o, t: o.astype(t.dtype), out, tree)


def _pin_pipe_dim(stage_params, mesh):
    """Constrain dim0 of every stage-param leaf to ``pipe`` while
    leaving every other dim UNCONSTRAINED — a plain ``None`` would mean
    *replicated* and silently all-gather away the Megatron TP layout the
    pipe×model rules placed on the stacked weights (PP×TP would still
    be numerically right, but each device would hold full un-sharded
    stage weights)."""
    U = P.UNCONSTRAINED

    def pin(p):
        spec = P(*((AxisNames.PIPE,) + (U,) * (p.ndim - 1)))
        return jax.lax.with_sharding_constraint(p, NamedSharding(mesh, spec))

    return jax.tree.map(pin, stage_params)


def _gpipe_local(stage_fn, params, x_mb, axis_name, rng=None):
    """Per-device GPipe schedule (runs inside shard_map).

    params: this device's stage params (leading [1, ...] stage dim kept).
    x_mb: [M, mb, ...] microbatched input, replicated over the pipe axis.
    rng: optional dropout key — folded per (stage, tick), which is
    per (stage, microbatch) since a stage sees one microbatch per tick.
    Returns [M, mb, ...] outputs, valid on every device (psum-broadcast).
    """
    n_stages = coll.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    m = x_mb.shape[0]
    fwd_perm = coll.ring_perm(n_stages)
    params = jax.tree.map(lambda p: p[0], params)  # drop the stage dim
    if rng is not None:
        rng = jax.random.fold_in(rng, stage)

    def tick(carry, t):
        state, out = carry
        # Stage 0 ingests microbatch t (t < M), others take the incoming
        # activation that arrived last tick.
        mb_idx = jnp.clip(t, 0, m - 1)
        inp = jnp.where(stage == 0, x_mb[mb_idx], state)

        def run(inp):
            if rng is None:
                return stage_fn(params, inp)
            return stage_fn(params, inp, jax.random.fold_in(rng, t))

        # Stage s only holds a real microbatch during ticks
        # [s, s + M - 1]; outside that window (the GPipe bubble) skip the
        # stage compute entirely instead of burning FLOPs on garbage.
        in_window = (t >= stage) & (t <= stage + m - 1)
        y = lax.cond(in_window, run, lambda inp: jnp.zeros_like(inp), inp)
        # Microbatch k exits the last stage at tick k + P - 1.
        done_idx = t - (n_stages - 1)
        is_done = (stage == n_stages - 1) & (done_idx >= 0) & (done_idx < m)
        out = jnp.where(
            is_done, out.at[jnp.clip(done_idx, 0, m - 1)].set(y), out
        )
        # Hop the activation to the next stage (ring hop; the wraparound
        # value into stage 0 is ignored — it re-ingests from x_mb).
        state = coll.ppermute(y, axis_name, fwd_perm)
        return (state, out), None

    state0 = jnp.zeros_like(x_mb[0])
    out0 = jnp.zeros_like(x_mb)
    (_, out), _ = lax.scan(
        jax.checkpoint(tick), (state0, out0), jnp.arange(m + n_stages - 1)
    )
    # Only the last stage holds real outputs; broadcast to all pipe ranks
    # so the (replicated) head/loss runs everywhere.
    return _psum_pipe(
        jnp.where(stage == n_stages - 1, out, jnp.zeros_like(out)), axis_name
    )


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    rng=None,
) -> jax.Array:
    """Apply a [stages]-stacked stage over ``x`` with GPipe scheduling.

    stage_params: pytree with leading [stages] axis on every leaf,
    sharded over ``pipe``. x: [batch, ...] activations. The batch is
    split into ``num_microbatches`` along axis 0. With ``rng``,
    ``stage_fn`` is called as ``stage_fn(params, x, key)`` with a key
    unique per (stage, microbatch) — the dropout path; without, as
    ``stage_fn(params, x)``.
    """
    n_stages = mesh.shape[AxisNames.PIPE]
    if n_stages == 1:
        single = jax.tree.map(lambda p: p[0], stage_params)
        return stage_fn(single, x) if rng is None else stage_fn(single, x, rng)
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f"batch {b} not divisible by num_microbatches {num_microbatches}"
        )
    x_mb = x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])

    param_specs = jax.tree.map(
        lambda p: P(*((AxisNames.PIPE,) + (None,) * (p.ndim - 1))), stage_params
    )
    constrained = _pin_pipe_dim(stage_params, mesh)
    # Partial-manual: only `pipe` is manual (module docstring). Specs
    # may therefore only reference `pipe`; activations are pipe-
    # replicated (P()), their batch sharding rides the auto axes.
    if rng is None:
        out = _shard_map(
            lambda p, xm: _gpipe_local(stage_fn, p, xm, AxisNames.PIPE),
            mesh=mesh,
            in_specs=(param_specs, P()),
            out_specs=P(),
            axis_names={AxisNames.PIPE},
            check_vma=False,
        )(constrained, x_mb)
    else:
        # rng rides in as an explicit replicated argument (a closure
        # capture inside shard_map is not reliably supported).
        out = _shard_map(
            lambda p, xm, r: _gpipe_local(
                stage_fn, p, xm, AxisNames.PIPE, rng=r
            ),
            mesh=mesh,
            in_specs=(param_specs, P(), P()),
            out_specs=P(),
            axis_names={AxisNames.PIPE},
            check_vma=False,
        )(constrained, x_mb, rng)
    return out.reshape((b,) + x.shape[1:])


# ------------------------------------------------------------------ 1F1B


def interleave_perm(p: int, v: int) -> np.ndarray:
    """Slot-major permutation for interleaved stages.

    Virtual stage ``s = chunk·P + device`` (round-robin, Megatron
    layout) is stored in stacked-param slot ``i = device·v + chunk`` so
    a CONTIGUOUS dim-0 ``pipe`` sharding of the ``[P·v, ...]`` stack
    gives each device exactly its v chunks with zero train-time data
    movement. Returns ``perm`` with ``perm[i] = virtual stage in slot
    i``; apply as ``stacked_logical[perm]`` to produce slot order (and
    ``argsort(perm)`` to undo, e.g. for the eval/GPipe path)."""
    return np.asarray(
        [(i % v) * p + i // v for i in range(p * v)], np.int64
    )


def _sim_schedule(m: int, p: int, v: int, bwd_hi: bool, fwd_lo: bool):
    """One greedy simulation of (interleaved) 1F1B; see _schedule_1f1b."""
    s_total = p * v
    next_f = [0] * s_total
    next_b = [0] * s_total
    f_tick: dict = {}
    b_tick: dict = {}
    ops, mbs, chs = [], [], []
    t = 0
    while any(next_b[s] < m for s in range(s_total)):
        if t > 4 * (m * v + s_total) + 8:
            return None  # this policy deadlocked / stalled
        op_row = [0] * p
        mb_row = [0] * p
        ch_row = [0] * p
        for d in range(p):
            stages = [j * p + d for j in range(v)]  # this device's chunks
            b_cands = []
            f_cands = []
            for s in stages:
                b = next_b[s]
                if b < m and (
                    (s == s_total - 1 and f_tick.get((b, s), t) < t)
                    or (s < s_total - 1 and b_tick.get((b, s + 1), t) < t)
                ):
                    b_cands.append(s)
                f = next_f[s]
                # In-flight bound: the classic S - s, additionally
                # capped at 2P for v > 1 — uncapped, greedy warmup
                # pumps up to m microbatches in flight at chunk 0
                # (GPipe-like memory); the cap costs ≤1% ticks in the
                # swept configs and bounds stash depth by min(m, 2P).
                # For v == 1, S - s ≤ P < 2P: identical to round 3.
                if (
                    f < m
                    and (s == 0 or f_tick.get((f, s - 1), t) < t)
                    and next_f[s] - next_b[s] < min(s_total - s, 2 * p)
                ):
                    f_cands.append(s)
            if b_cands:  # backward priority (1F1B)
                s = max(b_cands) if bwd_hi else min(b_cands)
                b = next_b[s]
                op_row[d], mb_row[d], ch_row[d] = 2, b, s // p
                b_tick[(b, s)] = t
                next_b[s] += 1
            elif f_cands:
                s = min(f_cands) if fwd_lo else max(f_cands)
                f = next_f[s]
                op_row[d], mb_row[d], ch_row[d] = 1, f, s // p
                f_tick[(f, s)] = t
                next_f[s] += 1
        ops.append(op_row)
        mbs.append(mb_row)
        chs.append(ch_row)
        t += 1
    return ops, mbs, chs, t, f_tick, b_tick


def _schedule_1f1b(m: int, p: int, v: int = 1):
    """Simulate the 1F1B schedule — interleaved when v > 1 — for M
    microbatches over P devices × V virtual stages (chunks) per device,
    and return static per-tick op tables.

    Virtual stage ``s = chunk·P + device``; each device runs at most
    ONE op per tick among its chunks. Greedy rules per tick, per
    device: run a backward whose cotangent is available (last virtual
    stage: own forward done earlier; else: stage s+1 ran backward
    earlier) — backward priority; else a forward whose activation is
    available (s == 0: always; else s-1 forwarded earlier) subject to
    the in-flight bound ``next_f[s] - next_b[s] < S - s``; else idle.
    Four chunk tie-break policies are simulated and the one with the
    fewest ticks that converges wins (for v == 1 they coincide with the
    round-3 schedule exactly).

    Returns (op[T,P], mb[T,P], ch[T,P], T, depth, q_f, q_b) int32
    arrays, op ∈ {0 idle, 1 fwd, 2 bwd}; ``depth`` is the exact max
    in-flight count over (device, chunk) pairs from the simulation —
    the runtime sizes its activation stash [v, depth, ...] from it —
    and ``q_f``/``q_b`` are the exact max arrived-but-unconsumed counts
    per receive direction, sizing the [v, q, ...] receive queues (v=1
    gives the classic 2 slots; interleaving legitimately needs more
    during warmup because a device is busy with other chunks while
    arrivals pile up). Asserts the slot-reuse invariants the runtime
    relies on at the computed sizes (slot = mb % size).
    """
    s_total = p * v
    best = None
    for bwd_hi in (True, False):
        for fwd_lo in (True, False):
            r = _sim_schedule(m, p, v, bwd_hi, fwd_lo)
            if r is not None and (best is None or r[3] < best[3]):
                best = r
    if best is None:
        raise AssertionError(f"1F1B schedule failed to converge (m={m}, p={p}, v={v})")
    ops, mbs, chs, t, f_tick, b_tick = best

    # Exact stash depth: max simultaneous in-flight per virtual stage.
    depth = 1
    for s in range(s_total):
        live = 0
        events = sorted(
            [(f_tick[(k, s)], 1) for k in range(m)]
            + [(b_tick[(k, s)], -1) for k in range(m)]
        )
        for _, delta in events:
            live += delta
            depth = max(depth, live)
    # Exact receive-queue sizes: max arrived-but-unconsumed per virtual
    # edge. A forward produced at stage s-1 on tick u arrives at stage s
    # on tick u+1 and is consumed at f_tick[(k, s)].
    def _max_live(ticks, lo, hi, shift):
        live_max = 1
        for s in range(lo, hi):
            # Arrival one tick after production at the neighbor; the
            # +0.5 orders consumption after a same-tick arrival (the
            # runtime delivers arrivals at tick start, then consumes).
            events = sorted(
                [(ticks[(k, s + shift)] + 1, 1) for k in range(m)]
                + [(ticks[(k, s)] + 0.5, -1) for k in range(m)]
            )
            live = 0
            for _, delta in events:
                live += delta
                live_max = max(live_max, live)
        return live_max

    q_f = _max_live(f_tick, 1, s_total, -1)
    q_b = _max_live(b_tick, 0, s_total - 1, +1)
    q_f, q_b = max(2, q_f), max(2, q_b)
    # Queue invariant at the computed sizes: arrival of microbatch k+q
    # (same direction, same edge) must not precede consumption of k.
    for s in range(1, s_total):
        for k in range(m - q_f):
            assert f_tick[(k, s)] <= f_tick[(k + q_f, s - 1)], (s, k)
    for s in range(s_total - 1):
        for k in range(m - q_b):
            assert b_tick[(k, s)] <= b_tick[(k + q_b, s + 1)], (s, k)
    # Stash invariant: backward of k precedes forward of k+depth
    # (slot = mb % depth reuse safety).
    for s in range(s_total):
        for k in range(m - depth):
            assert b_tick[(k, s)] < f_tick[(k + depth, s)], (s, k)
    return (
        np.asarray(ops, np.int32),
        np.asarray(mbs, np.int32),
        np.asarray(chs, np.int32),
        t,
        depth,
        q_f,
        q_b,
    )


def _1f1b_local(
    stage_fn,
    head_loss_fn,
    params,
    head_params,
    x_mb,
    labels_mb,
    rng,
    axis_name,
    op_tbl,
    mb_tbl,
    ch_tbl,
    n_virtual,
    depth,
    q_f,
    q_b,
):
    """Per-device (interleaved) 1F1B program (runs inside shard_map).

    params: this device's stage params, leading [v, ...] chunk dim kept
    (slot-major stacking: chunk j on device d is virtual stage
    ``j·P + d`` — ``interleave_perm``). x_mb: [M, mb, ...] microbatched
    stage-0 input (embed output), labels_mb: [M, mb, ...] labels for
    the last virtual stage's loss. All hops are nearest-neighbor ring
    permutes — the wraparound edge P-1 → 0 is exactly the chunk
    boundary (virtual stage j·P+P-1 → (j+1)·P lives on device 0), so
    interleaving adds no new communication pattern, only chunk routing
    on the receive side. Returns (loss_sum_local, dparams [v, ...],
    dhead_local, dx_mb_local) — the caller reduces loss/dhead/dx over
    the pipe axis (each is produced on one device, zeros elsewhere).
    """
    n_dev = coll.axis_size(axis_name)
    dev = lax.axis_index(axis_name)
    v = n_virtual
    s_total_v = op_tbl.shape[1] * v  # == n_dev · v, static
    m = x_mb.shape[0]
    fwd_perm = coll.ring_perm(n_dev)
    bwd_perm = [(d_, s_) for (s_, d_) in fwd_perm]
    # Static chunk slice for v == 1 (see chunk_params below).
    params_static = jax.tree.map(lambda p_: p_[0], params) if v == 1 else None

    def fwd_loss(p_, hp, x, lbl, mb, s_virt, is_last):
        """Uniform chunk program: block stack + (last virtual stage
        only) loss. rng folds per (virtual stage, microbatch)."""
        if rng is None:
            y = stage_fn(p_, x)
        else:
            key = jax.random.fold_in(jax.random.fold_in(rng, s_virt), mb)
            y = stage_fn(p_, x, key)
        loss = lax.cond(
            is_last,
            lambda: head_loss_fn(hp, y, lbl),
            lambda: jnp.float32(0.0),
        )
        return y, loss

    zeros_x = jnp.zeros_like(x_mb[0])
    d_params0 = jax.tree.map(jnp.zeros_like, params)
    d_head0 = jax.tree.map(jnp.zeros_like, head_params)

    def tick(carry, t):
        in_q, d_q, stash, d_par, d_head, dx_out, loss_acc, y_pay, d_pay = carry
        # Deliver last tick's hops (receive side): a forward activation
        # arrives iff my predecessor ran F last tick (and wasn't the
        # final virtual stage); a cotangent arrives iff my successor ran
        # B last tick (and wasn't virtual stage 0). The receive CHUNK is
        # decoded from the sender's table entry: same chunk within the
        # ring, +1 across the P-1 → 0 wraparound.
        prev_op = op_tbl[t - 1]  # t=0 reads row -1, gated off below
        prev_mb = mb_tbl[t - 1]
        prev_ch = ch_tbl[t - 1]
        y_arr = coll.ppermute(y_pay, axis_name, fwd_perm)
        d_arr = coll.ppermute(d_pay, axis_name, bwd_perm)
        pred, succ = (dev - 1) % n_dev, (dev + 1) % n_dev
        s_snd_f = prev_ch[pred] * n_dev + pred
        f_arrived = (t > 0) & (prev_op[pred] == 1) & (s_snd_f < s_total_v - 1)
        s_snd_b = prev_ch[succ] * n_dev + succ
        b_arrived = (t > 0) & (prev_op[succ] == 2) & (s_snd_b > 0)
        in_q = jnp.where(
            f_arrived,
            in_q.at[(s_snd_f + 1) // n_dev, prev_mb[pred] % q_f].set(y_arr),
            in_q,
        )
        d_q = jnp.where(
            b_arrived,
            d_q.at[(s_snd_b - 1) // n_dev, prev_mb[succ] % q_b].set(d_arr),
            d_q,
        )

        op = op_tbl[t, dev]
        mb = mb_tbl[t, dev]
        ch = ch_tbl[t, dev]
        s_virt = ch * n_dev + dev
        is_first = s_virt == 0
        is_last = s_virt == s_total_v - 1
        lbl = labels_mb[mb]

        def chunk_params():
            # v == 1: ch is constantly 0 but traced (from ch_tbl), so a
            # dynamic slice here could not be hoisted out of the scan —
            # use the static slice taken outside instead (round-3
            # behavior). v > 1: gather the chunk inside do_fwd/do_bwd
            # only, so idle ticks pay nothing.
            if v == 1:
                return params_static
            return jax.tree.map(
                lambda p_: lax.dynamic_index_in_dim(
                    p_, ch, 0, keepdims=False
                ),
                params,
            )

        def do_idle(_):
            return (stash, d_par, d_head, dx_out, loss_acc, zeros_x, zeros_x)

        def do_fwd(_):
            p_ch = chunk_params()
            x_in = jnp.where(is_first, x_mb[mb], in_q[ch, mb % q_f])
            y, loss = fwd_loss(p_ch, head_params, x_in, lbl, mb, s_virt, is_last)
            return (
                stash.at[ch, mb % depth].set(x_in),
                d_par,
                d_head,
                dx_out,
                loss_acc + loss,
                y,
                zeros_x,
            )

        def do_bwd(_):
            p_ch = chunk_params()
            x_in = stash[ch, mb % depth]
            _, vjp = jax.vjp(
                lambda p_, hp, x: fwd_loss(p_, hp, x, lbl, mb, s_virt, is_last),
                p_ch,
                head_params,
                x_in,
            )
            dy = jnp.where(is_last, jnp.zeros_like(zeros_x), d_q[ch, mb % q_b])
            g_loss = jnp.where(is_last, jnp.float32(1.0), jnp.float32(0.0))
            dp, dhp, dx = vjp((dy, g_loss))
            new_dx_out = jnp.where(
                is_first, dx_out.at[mb].set(dx), dx_out
            )
            d_par2 = (
                jax.tree.map(lambda acc, g: acc + g[None], d_par, dp)
                if v == 1  # static accumulate, no scatter
                else jax.tree.map(lambda acc, g: acc.at[ch].add(g), d_par, dp)
            )
            return (
                stash,
                d_par2,
                jax.tree.map(jnp.add, d_head, dhp),
                new_dx_out,
                loss_acc,
                zeros_x,
                dx,
            )

        stash, d_par, d_head, dx_out, loss_acc, y_pay, d_pay = lax.switch(
            op, [do_idle, do_fwd, do_bwd], None
        )
        return (
            in_q,
            d_q,
            stash,
            d_par,
            d_head,
            dx_out,
            loss_acc,
            y_pay,
            d_pay,
        ), None

    carry0 = (
        jnp.zeros((v, q_f) + zeros_x.shape, zeros_x.dtype),  # fwd queue
        jnp.zeros((v, q_b) + zeros_x.shape, zeros_x.dtype),  # bwd queue
        jnp.zeros((v, depth) + zeros_x.shape, zeros_x.dtype),  # act stash
        d_params0,
        d_head0,
        jnp.zeros_like(x_mb),  # dx per microbatch (virtual stage 0 only)
        jnp.float32(0.0),
        zeros_x,  # forward hop payload
        zeros_x,  # backward hop payload
    )
    n_ticks = op_tbl.shape[0]
    (in_q, d_q, stash, d_par, d_head, dx_out, loss_acc, y_pay, d_pay), _ = (
        lax.scan(tick, carry0, jnp.arange(n_ticks))
    )
    return loss_acc, d_par, d_head, dx_out


def make_pipeline_1f1b(
    stage_fn: Callable,
    head_loss_fn: Callable,
    *,
    mesh: Mesh,
    num_microbatches: int,
    num_virtual_stages: int = 1,
):
    """Build the 1F1B pipelined loss:
    ``run(stage_params, head_params, x, labels, rng) -> scalar loss``.

    - ``stage_fn(stage_params, x[, rng_key]) -> y`` — one virtual
      stage's block stack (same contract as ``pipeline_apply``).
    - ``head_loss_fn(head_params, y, labels) -> scalar`` — the
      mean-per-microbatch loss, executed at the LAST virtual stage only
      (so the head matmul is never replicated across stages).

    With ``num_virtual_stages = v > 1`` the schedule is INTERLEAVED
    1F1B (Megatron-style): ``stage_params`` must carry a leading
    ``[P·v]`` dim in SLOT-MAJOR order (``interleave_perm``), each tick
    runs one 1/v-sized chunk, and the pipeline ramp shrinks ~v-fold in
    full-stage units (measured by ``_schedule_1f1b``: p=4, m=8 bubble
    6.0 → 5.0 → 2.5 stage-units for v = 1, 2, 4) at the price of v×
    the ticks, hops, and receive-queue slots — worth it when a stage's
    compute dwarfs the hop latency.

    The returned function is a ``jax.custom_vjp``: its *forward* runs
    the scheduled program, producing the loss AND the explicit
    gradients (stage grads stay ``pipe``-sharded; head/dx reduce over
    the pipe axis once); its backward just scales those cached
    gradients by the incoming cotangent. The surrounding program —
    embedding before, optimizer after — differentiates through it with
    plain ``jax.grad``. Memory: the activation stash is
    [v, depth ≤ min(M, 2P)] per device, sized exactly from the trace-
    time schedule simulation, never M-deep.
    """
    n_stages = mesh.shape[AxisNames.PIPE]
    pipe_axis = AxisNames.PIPE
    v = num_virtual_stages

    def _mb_split(a, m):
        return a.reshape((m, a.shape[0] // m) + a.shape[1:])

    def _impl(stage_params, head_params, x, labels, rng):
        m = num_microbatches
        if x.shape[0] % m:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by num_microbatches {m}"
            )
        op_np, mb_np, ch_np, _, depth, q_f, q_b = _schedule_1f1b(
            m, n_stages, v
        )
        op_tbl, mb_tbl, ch_tbl = (
            jnp.asarray(op_np), jnp.asarray(mb_np), jnp.asarray(ch_np)
        )
        x_mb, labels_mb = _mb_split(x, m), _mb_split(labels, m)

        param_specs = jax.tree.map(
            lambda p: P(*((pipe_axis,) + (None,) * (p.ndim - 1))),
            stage_params,
        )
        head_specs = jax.tree.map(lambda _: P(), head_params)
        constrained = _pin_pipe_dim(stage_params, mesh)

        def local(sp, hp, xm, lm, r=None):
            loss, d_sp, d_hp, dx = _1f1b_local(
                stage_fn, head_loss_fn, sp, hp, xm, lm, r,
                pipe_axis, op_tbl, mb_tbl, ch_tbl, v, depth, q_f, q_b,
            )
            dev = lax.axis_index(pipe_axis)
            is_last = dev == n_stages - 1  # hosts the last virtual stage
            # Only `pipe` is manual here (axis_names below): inside this
            # region the arrays are GLOBAL over the batch/model axes and
            # XLA inserts the DP/TP collectives from their shardings —
            # the hand-written pmeans of the all-manual formulation are
            # gone. Loss and head grads exist on the last device, dx on
            # device 0; one psum each replicates them over the pipe
            # (zeros elsewhere).
            loss = _psum_pipe(jnp.where(is_last, loss, 0.0), pipe_axis)
            d_hp = _psum_pipe(
                jax.tree.map(
                    lambda g: jnp.where(is_last, g, jnp.zeros_like(g)),
                    d_hp,
                ),
                pipe_axis,
            )
            dx = _psum_pipe(dx, pipe_axis)  # zeros off device 0
            return loss / m, d_sp, d_hp, dx

        if rng is None:
            # A None rng can't cross the shard_map boundary as an arg.
            return _shard_map(
                lambda sp, hp, xm, lm: local(sp, hp, xm, lm),
                mesh=mesh,
                in_specs=(param_specs, head_specs, P(), P()),
                out_specs=(P(), param_specs, head_specs, P()),
                axis_names={pipe_axis},
                check_vma=False,
            )(constrained, head_params, x_mb, labels_mb)
        return _shard_map(
            local,
            mesh=mesh,
            in_specs=(param_specs, head_specs, P(), P(), P()),
            out_specs=(P(), param_specs, head_specs, P()),
            axis_names={pipe_axis},
            check_vma=False,
        )(constrained, head_params, x_mb, labels_mb, rng)

    @jax.custom_vjp
    def run(stage_params, head_params, x, labels, rng):
        loss, _, _, _ = _impl(stage_params, head_params, x, labels, rng)
        return loss

    def run_fwd(stage_params, head_params, x, labels, rng):
        loss, d_sp, d_hp, dx_mb = _impl(stage_params, head_params, x, labels, rng)
        dx = dx_mb.reshape((x.shape[0],) + x.shape[1:]) / num_microbatches
        d_sp = jax.tree.map(lambda g: g / num_microbatches, d_sp)
        d_hp = jax.tree.map(lambda g: g / num_microbatches, d_hp)
        return loss, (d_sp, d_hp, dx, labels, rng)

    def run_bwd(res, g):
        d_sp, d_hp, dx, labels, rng = res
        scale = lambda t: jax.tree.map(lambda a: a * g, t)
        zero_lbl = np.zeros(labels.shape, jax.dtypes.float0)
        zero_rng = (
            None if rng is None else np.zeros(rng.shape, jax.dtypes.float0)
        )
        return scale(d_sp), scale(d_hp), dx * g, zero_lbl, zero_rng

    run.defvjp(run_fwd, run_bwd)
    return run
