"""Pipeline parallelism: GPipe microbatching over the ``pipe`` mesh axis.

Framework-native extension (SURVEY.md §2d — the reference had no PP; the
distributed design here treats it as a first-class mesh axis like
dp/fsdp/tp/sp). TPU-first shape:

- Stage parameters are the *same pytree* with a leading [stages] axis
  sharded over ``pipe`` — placement is a sharding rule, not a code path,
  exactly like tensor parallelism.
- The schedule runs inside ``shard_map``: each device applies its own
  stage; activations hop stage→stage with ``jax.lax.ppermute``
  (nearest-neighbor ICI), microbatches streaming in GPipe order over
  M + P - 1 ticks. No host round-trips, one compiled program.
- Differentiable by construction: the backward pass is JAX's transpose
  of the forward schedule (ppermute transposes to the reverse hop), i.e.
  the classic reverse pipeline, with per-tick remat to keep the saved
  state at O(M · microbatch) activations.

``pipeline_apply`` is the jit-level entry; ``_gpipe_local`` is the
per-device program.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorflow_examples_tpu.core import collectives as coll
from tensorflow_examples_tpu.core.mesh import AxisNames


def _gpipe_local(stage_fn, params, x_mb, axis_name, rng=None):
    """Per-device GPipe schedule (runs inside shard_map).

    params: this device's stage params (leading [1, ...] stage dim kept).
    x_mb: [M, mb, ...] microbatched input, replicated over the pipe axis.
    rng: optional dropout key — folded per (stage, tick), which is
    per (stage, microbatch) since a stage sees one microbatch per tick.
    Returns [M, mb, ...] outputs, valid on every device (psum-broadcast).
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    m = x_mb.shape[0]
    fwd_perm = coll.ring_perm(n_stages)
    params = jax.tree.map(lambda p: p[0], params)  # drop the stage dim
    if rng is not None:
        rng = jax.random.fold_in(rng, stage)

    def tick(carry, t):
        state, out = carry
        # Stage 0 ingests microbatch t (t < M), others take the incoming
        # activation that arrived last tick.
        mb_idx = jnp.clip(t, 0, m - 1)
        inp = jnp.where(stage == 0, x_mb[mb_idx], state)
        if rng is None:
            y = stage_fn(params, inp)
        else:
            y = stage_fn(params, inp, jax.random.fold_in(rng, t))
        # Microbatch k exits the last stage at tick k + P - 1.
        done_idx = t - (n_stages - 1)
        is_done = (stage == n_stages - 1) & (done_idx >= 0) & (done_idx < m)
        out = jnp.where(
            is_done, out.at[jnp.clip(done_idx, 0, m - 1)].set(y), out
        )
        # Hop the activation to the next stage (ring hop; the wraparound
        # value into stage 0 is ignored — it re-ingests from x_mb).
        state = coll.ppermute(y, axis_name, fwd_perm)
        return (state, out), None

    state0 = jnp.zeros_like(x_mb[0])
    out0 = jnp.zeros_like(x_mb)
    (_, out), _ = lax.scan(
        jax.checkpoint(tick), (state0, out0), jnp.arange(m + n_stages - 1)
    )
    # Only the last stage holds real outputs; broadcast to all pipe ranks
    # so the (replicated) head/loss runs everywhere.
    return coll.psum(
        jnp.where(stage == n_stages - 1, out, jnp.zeros_like(out)), axis_name
    )


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    batch_spec: P = P((AxisNames.DATA, AxisNames.FSDP)),
    rng=None,
) -> jax.Array:
    """Apply a [stages]-stacked stage over ``x`` with GPipe scheduling.

    stage_params: pytree with leading [stages] axis on every leaf,
    sharded over ``pipe``. x: [batch, ...] activations. The batch is
    split into ``num_microbatches`` along axis 0. With ``rng``,
    ``stage_fn`` is called as ``stage_fn(params, x, key)`` with a key
    unique per (stage, microbatch) — the dropout path; without, as
    ``stage_fn(params, x)``.
    """
    n_stages = mesh.shape[AxisNames.PIPE]
    if n_stages == 1:
        single = jax.tree.map(lambda p: p[0], stage_params)
        return stage_fn(single, x) if rng is None else stage_fn(single, x, rng)
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f"batch {b} not divisible by num_microbatches {num_microbatches}"
        )
    x_mb = x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])

    param_specs = jax.tree.map(
        lambda p: P(*((AxisNames.PIPE,) + (None,) * (p.ndim - 1))), stage_params
    )
    # Microbatched activations: batch dim is now axis 1.
    act_spec = P(None, *batch_spec)
    constrained = jax.lax.with_sharding_constraint(
        stage_params, jax.tree.map(lambda s: NamedSharding(mesh, s), param_specs)
    )
    if rng is None:
        out = jax.shard_map(
            lambda p, xm: _gpipe_local(stage_fn, p, xm, AxisNames.PIPE),
            mesh=mesh,
            in_specs=(param_specs, act_spec),
            out_specs=act_spec,
            check_vma=False,
        )(constrained, x_mb)
    else:
        # rng rides in as an explicit replicated argument (a closure
        # capture inside shard_map is not reliably supported).
        out = jax.shard_map(
            lambda p, xm, r: _gpipe_local(
                stage_fn, p, xm, AxisNames.PIPE, rng=r
            ),
            mesh=mesh,
            in_specs=(param_specs, act_spec, P()),
            out_specs=act_spec,
            check_vma=False,
        )(constrained, x_mb, rng)
    return out.reshape((b,) + x.shape[1:])
