"""Parallelism layer: collective attention + mesh-aware dispatch.

The reference's distribution story was per-example ``tf.distribute``
strategies over NCCL (SURVEY.md §2d). Here parallelism is mesh-native:
sharding rules (core.sharding) cover DP/FSDP/TP for the dense math, and
this package supplies the pieces XLA cannot derive automatically —
sequence/context parallelism for attention (ring via ``ppermute``,
Ulysses via ``all_to_all``) and the ``shard_map`` wrapper that runs the
Pallas flash kernel on mesh-sharded operands.
"""

from tensorflow_examples_tpu.parallel.ring import (
    ring_attention,
    ulysses_attention,
)
from tensorflow_examples_tpu.parallel.attention import mesh_attention

__all__ = ["ring_attention", "ulysses_attention", "mesh_attention"]
