"""Model zoo: flax (linen) definitions of the five reference workload models.

MLP (MNIST), ResNet-20/50 (CIFAR/ImageNet), BERT-base (GLUE), GPT-2 124M
(LM) — BASELINE.json:configs. Pure-functional modules so every model
composes with jit/shard_map/remat; params are plain pytrees sharded by
the core rules tables each model exports.
"""

from tensorflow_examples_tpu.models.mlp import MLP
from tensorflow_examples_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)
