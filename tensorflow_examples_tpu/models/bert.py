"""BERT-base encoder + GLUE heads (BASELINE.json:configs[3]).

Capability parity with the reference's BERT-base GLUE fine-tune example
(12L/768H/12 heads, vocab 30522, learned positions, post-LN, gelu,
pooler + per-task head), built TPU-first on the shared framework:

- Bidirectional attention with the padding mask folded in as an additive
  bias. At GLUE sequence lengths (≤128) attention is a small fraction of
  the FLOPs, so the XLA softmax path is the right default; for long
  sequences ``attention="flash"`` runs the Pallas kernel with the
  padding mask as its non-causal key bias (ops/attention.py
  ``key_bias``) — same numerics, O(block²) VMEM instead of the [S, S]
  score matrix.
- Same head-major DenseGeneral layout as the GPT-2 model, so the
  GPT2-style TP sharding rules apply (BERT_RULES below).
- Weight layout maps 1:1 from HF ``BertModel`` (models/hf_import.py →
  ``import_bert``), replacing the reference's TF pretrained-checkpoint
  restore (SURVEY.md §5d).

Classification (single-label) and regression (STS-B) share the module;
``num_labels=1`` means regression.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tensorflow_examples_tpu.core.mesh import AxisNames
from tensorflow_examples_tpu.core.sharding import ShardingRules
from tensorflow_examples_tpu.ops.attention import flash_attention

NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_len: int = 512
    type_vocab_size: int = 2
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    d_ff: int = 3072
    dropout: float = 0.1
    layer_norm_eps: float = 1e-12
    attention: str = "xla"  # xla | flash (Pallas kernel + key_bias mask)

    def __post_init__(self):
        if self.attention not in ("xla", "flash"):
            raise ValueError(
                f"attention={self.attention!r}; expected 'xla' or 'flash'"
            )


def bert_base(**overrides) -> BertConfig:
    return BertConfig(**overrides)


_M, _F = AxisNames.MODEL, AxisNames.FSDP
BERT_RULES = ShardingRules(
    [
        (r"attn_qkv/kernel", P(_F, None, _M, None)),
        (r"attn_qkv/bias", P(None, _M, None)),
        (r"attn_proj/kernel", P(_M, None, _F)),
        (r"ffn_in/kernel", P(_F, _M)),
        (r"ffn_in/bias", P(_M)),
        (r"ffn_out/kernel", P(_M, _F)),
    ]
)


class BertLayer(nn.Module):
    cfg: BertConfig
    train: bool
    mesh: Mesh | None = None

    @nn.compact
    def __call__(self, x, bias):
        cfg = self.cfg
        h, hd = cfg.num_heads, cfg.d_model // cfg.num_heads
        drop = lambda t: nn.Dropout(cfg.dropout, deterministic=not self.train)(t)

        qkv = nn.DenseGeneral(features=(3, h, hd), dtype=x.dtype, name="attn_qkv")(x)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        if cfg.attention == "flash":
            # bias arrives as the raw [B, S] key mask bias on this path.
            # Routed through the mesh-aware wrapper: on a TP mesh the
            # heads stay sharded over `model` around the (otherwise
            # partitioner-opaque) Pallas call (ADVICE r3).
            from tensorflow_examples_tpu.parallel.attention import (
                mesh_attention,
            )

            swap = lambda t: t.transpose(0, 2, 1, 3)
            ctx = swap(
                mesh_attention(
                    swap(q), swap(k), swap(v), mesh=self.mesh,
                    causal=False, key_bias=bias,
                )
            )
        else:
            s = jnp.einsum(
                "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
            ) * (hd ** -0.5)
            p = jax.nn.softmax(s + bias, axis=-1).astype(x.dtype)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        attn_out = nn.DenseGeneral(
            features=cfg.d_model, axis=(-2, -1), dtype=x.dtype, name="attn_proj"
        )(ctx)
        # Post-LN (original BERT): LN(residual + sublayer).
        x = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=x.dtype, name="attn_ln"
        )(x + drop(attn_out))

        y = nn.Dense(cfg.d_ff, dtype=x.dtype, name="ffn_in")(x)
        y = nn.gelu(y, approximate=False)  # BERT uses exact erf gelu
        y = nn.Dense(cfg.d_model, dtype=x.dtype, name="ffn_out")(y)
        return nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=x.dtype, name="ffn_ln"
        )(x + drop(y))


class BertEncoder(nn.Module):
    """Returns (sequence_output [B,S,d], pooled [B,d])."""

    cfg: BertConfig
    mesh: Mesh | None = None

    @nn.compact
    def __call__(self, tokens, attention_mask=None, token_type_ids=None, *,
                 train: bool = False):
        cfg = self.cfg
        if attention_mask is None:
            attention_mask = jnp.ones_like(tokens)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(tokens)

        emb = nn.Embed(
            cfg.vocab_size, cfg.d_model,
            embedding_init=nn.initializers.normal(0.02), name="word_embeddings",
        )(tokens)
        emb += nn.Embed(
            cfg.max_len, cfg.d_model,
            embedding_init=nn.initializers.normal(0.02),
            name="position_embeddings",
        )(jnp.arange(tokens.shape[1], dtype=jnp.int32))[None]
        emb += nn.Embed(
            cfg.type_vocab_size, cfg.d_model,
            embedding_init=nn.initializers.normal(0.02),
            name="token_type_embeddings",
        )(token_type_ids)
        x = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=emb.dtype, name="embeddings_ln"
        )(emb)
        x = nn.Dropout(cfg.dropout, deterministic=not train)(x)

        # Padding mask → additive attention bias: [B, 1, 1, S] for the
        # XLA softmax path, raw [B, S] for the flash kernel's key_bias.
        bias = jnp.where(attention_mask > 0, 0.0, NEG_INF).astype(jnp.float32)
        if cfg.attention != "flash":
            bias = bias[:, None, None, :]

        for i in range(cfg.num_layers):
            x = BertLayer(cfg, train, self.mesh, name=f"layer_{i}")(x, bias)

        pooled = nn.tanh(
            nn.Dense(cfg.d_model, dtype=x.dtype, name="pooler")(x[:, 0])
        )
        return x, pooled


class BertClassifier(nn.Module):
    """BERT encoder + dropout + task head (classification or regression)."""

    cfg: BertConfig
    num_labels: int = 2
    mesh: Mesh | None = None

    @nn.compact
    def __call__(self, tokens, attention_mask=None, token_type_ids=None, *,
                 train: bool = False):
        _, pooled = BertEncoder(self.cfg, self.mesh, name="bert")(
            tokens, attention_mask, token_type_ids, train=train
        )
        pooled = nn.Dropout(self.cfg.dropout, deterministic=not train)(pooled)
        # Head in f32 for stable logits/regression under bf16 compute.
        return nn.Dense(
            self.num_labels, dtype=jnp.float32, name="classifier"
        )(pooled.astype(jnp.float32))
