"""Pretrained-weight importers (HF → framework params).

The reference's BERT/GPT-2 examples restored TF pretrained checkpoints
(SURVEY.md §5d); the TPU-native replacement imports from HuggingFace
``transformers`` (installed in-image) instead. Importers consume a live
torch model or a local ``from_pretrained`` path — pure numpy reshapes,
no torch code in the hot path — and produce the exact param pytree the
flax models expect, ready for ``core.sharding.shard_params``.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from tensorflow_examples_tpu.models.transformer import TransformerConfig


def _np(t) -> np.ndarray:
    return t.detach().cpu().numpy() if hasattr(t, "detach") else np.asarray(t)


def import_gpt2(
    hf_model_or_path: Any, cfg: TransformerConfig | None = None
) -> tuple[TransformerConfig, Mapping]:
    """Convert an HF ``GPT2LMHeadModel`` (or local path) to our params.

    HF GPT-2 uses ``Conv1D`` layers whose weights are stored [in, out] —
    the same layout as flax Dense kernels, so only head/stack reshapes
    are needed (no transposes).
    """
    if isinstance(hf_model_or_path, str):
        from transformers import GPT2LMHeadModel

        hf_model_or_path = GPT2LMHeadModel.from_pretrained(hf_model_or_path)
    sd = {k: _np(v) for k, v in hf_model_or_path.state_dict().items()}
    hfc = hf_model_or_path.config
    if cfg is None:
        cfg = TransformerConfig(
            vocab_size=hfc.vocab_size,
            max_len=hfc.n_positions,
            num_layers=hfc.n_layer,
            num_heads=hfc.n_head,
            d_model=hfc.n_embd,
        )
    d, h, hd = cfg.d_model, cfg.num_heads, cfg.head_dim

    def ln(prefix):
        return {"scale": sd[f"{prefix}.weight"], "bias": sd[f"{prefix}.bias"]}

    params: dict = {
        "wte": {"embedding": sd["transformer.wte.weight"]},
        "wpe": {"embedding": sd["transformer.wpe.weight"]},
        "ln_f": ln("transformer.ln_f"),
    }
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}"
        params[f"h_{i}"] = {
            "ln_1": ln(f"{p}.ln_1"),
            "ln_2": ln(f"{p}.ln_2"),
            "attn": {
                "qkv": {
                    "kernel": sd[f"{p}.attn.c_attn.weight"].reshape(d, 3, h, hd),
                    "bias": sd[f"{p}.attn.c_attn.bias"].reshape(3, h, hd),
                },
                "proj": {
                    "kernel": sd[f"{p}.attn.c_proj.weight"].reshape(h, hd, d),
                    "bias": sd[f"{p}.attn.c_proj.bias"],
                },
            },
            "mlp_fc": {
                "kernel": sd[f"{p}.mlp.c_fc.weight"],
                "bias": sd[f"{p}.mlp.c_fc.bias"],
            },
            "mlp_proj": {
                "kernel": sd[f"{p}.mlp.c_proj.weight"],
                "bias": sd[f"{p}.mlp.c_proj.bias"],
            },
        }
    return cfg, params
