"""Pretrained-weight importers (HF → framework params).

The reference's BERT/GPT-2 examples restored TF pretrained checkpoints
(SURVEY.md §5d); the TPU-native replacement imports from HuggingFace
``transformers`` (installed in-image) instead. Importers consume a live
torch model or a local ``from_pretrained`` path — pure numpy reshapes,
no torch code in the hot path — and produce the exact param pytree the
flax models expect, ready for ``core.sharding.shard_params``.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from tensorflow_examples_tpu.models.bert import BertConfig
from tensorflow_examples_tpu.models.transformer import TransformerConfig


def _np(t) -> np.ndarray:
    return t.detach().cpu().numpy() if hasattr(t, "detach") else np.asarray(t)


def import_gpt2(
    hf_model_or_path: Any, cfg: TransformerConfig | None = None
) -> tuple[TransformerConfig, Mapping]:
    """Convert an HF ``GPT2LMHeadModel`` (or local path) to our params.

    HF GPT-2 uses ``Conv1D`` layers whose weights are stored [in, out] —
    the same layout as flax Dense kernels, so only head/stack reshapes
    are needed (no transposes).
    """
    if isinstance(hf_model_or_path, str):
        from transformers import GPT2LMHeadModel

        hf_model_or_path = GPT2LMHeadModel.from_pretrained(hf_model_or_path)
    sd = {k: _np(v) for k, v in hf_model_or_path.state_dict().items()}
    hfc = hf_model_or_path.config
    if cfg is None:
        cfg = TransformerConfig(
            vocab_size=hfc.vocab_size,
            max_len=hfc.n_positions,
            num_layers=hfc.n_layer,
            num_heads=hfc.n_head,
            d_model=hfc.n_embd,
        )
    d, h, hd = cfg.d_model, cfg.num_heads, cfg.head_dim

    def ln(prefix):
        return {"scale": sd[f"{prefix}.weight"], "bias": sd[f"{prefix}.bias"]}

    params: dict = {
        "wte": {"embedding": sd["transformer.wte.weight"]},
        "wpe": {"embedding": sd["transformer.wpe.weight"]},
        "ln_f": ln("transformer.ln_f"),
    }
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}"
        params[f"h_{i}"] = {
            "ln_1": ln(f"{p}.ln_1"),
            "ln_2": ln(f"{p}.ln_2"),
            "attn": {
                "qkv": {
                    "kernel": sd[f"{p}.attn.c_attn.weight"].reshape(d, 3, h, hd),
                    "bias": sd[f"{p}.attn.c_attn.bias"].reshape(3, h, hd),
                },
                "proj": {
                    "kernel": sd[f"{p}.attn.c_proj.weight"].reshape(h, hd, d),
                    "bias": sd[f"{p}.attn.c_proj.bias"],
                },
            },
            "mlp_fc": {
                "kernel": sd[f"{p}.mlp.c_fc.weight"],
                "bias": sd[f"{p}.mlp.c_fc.bias"],
            },
            "mlp_proj": {
                "kernel": sd[f"{p}.mlp.c_proj.weight"],
                "bias": sd[f"{p}.mlp.c_proj.bias"],
            },
        }
    return cfg, params


def import_bert(hf_model_or_path: Any) -> tuple[BertConfig, Mapping]:
    """Convert an HF ``BertModel``/``BertForSequenceClassification`` (or
    local path) to our ``BertClassifier`` params.

    torch ``Linear`` stores weights [out, in] → transposed here; QKV are
    three separate Linears in HF, stacked into our combined [d, 3, H, hd]
    DenseGeneral kernel.
    """
    if isinstance(hf_model_or_path, str):
        from transformers import BertForSequenceClassification

        hf_model_or_path = BertForSequenceClassification.from_pretrained(
            hf_model_or_path
        )
    sd = {k: _np(v) for k, v in hf_model_or_path.state_dict().items()}
    hfc = hf_model_or_path.config
    cfg = BertConfig(
        vocab_size=hfc.vocab_size,
        max_len=hfc.max_position_embeddings,
        type_vocab_size=hfc.type_vocab_size,
        num_layers=hfc.num_hidden_layers,
        num_heads=hfc.num_attention_heads,
        d_model=hfc.hidden_size,
        d_ff=hfc.intermediate_size,
        layer_norm_eps=hfc.layer_norm_eps,
    )
    d, h = cfg.d_model, cfg.num_heads
    hd = d // h
    pre = "bert." if any(k.startswith("bert.") for k in sd) else ""

    def lin(prefix):  # torch Linear → flax Dense
        return {"kernel": sd[f"{prefix}.weight"].T, "bias": sd[f"{prefix}.bias"]}

    def ln(prefix):
        return {"scale": sd[f"{prefix}.weight"], "bias": sd[f"{prefix}.bias"]}

    bert: dict = {
        "word_embeddings": {
            "embedding": sd[f"{pre}embeddings.word_embeddings.weight"]
        },
        "position_embeddings": {
            "embedding": sd[f"{pre}embeddings.position_embeddings.weight"]
        },
        "token_type_embeddings": {
            "embedding": sd[f"{pre}embeddings.token_type_embeddings.weight"]
        },
        "embeddings_ln": ln(f"{pre}embeddings.LayerNorm"),
        "pooler": lin(f"{pre}pooler.dense"),
    }
    for i in range(cfg.num_layers):
        p = f"{pre}encoder.layer.{i}"
        qkv_w = np.stack(
            [
                sd[f"{p}.attention.self.{n}.weight"].T.reshape(d, h, hd)
                for n in ("query", "key", "value")
            ],
            axis=1,
        )
        qkv_b = np.stack(
            [
                sd[f"{p}.attention.self.{n}.bias"].reshape(h, hd)
                for n in ("query", "key", "value")
            ],
            axis=0,
        )
        bert[f"layer_{i}"] = {
            "attn_qkv": {"kernel": qkv_w, "bias": qkv_b},
            "attn_proj": {
                "kernel": sd[f"{p}.attention.output.dense.weight"].T.reshape(
                    h, hd, d
                ),
                "bias": sd[f"{p}.attention.output.dense.bias"],
            },
            "attn_ln": ln(f"{p}.attention.output.LayerNorm"),
            "ffn_in": lin(f"{p}.intermediate.dense"),
            "ffn_out": lin(f"{p}.output.dense"),
            "ffn_ln": ln(f"{p}.output.LayerNorm"),
        }
    params: dict = {"bert": bert}
    if "classifier.weight" in sd:
        params["classifier"] = lin("classifier")
    # No fabricated head otherwise: the caller keeps its fresh (seeded)
    # task-head init when the checkpoint lacks a matching classifier.
    return cfg, params
