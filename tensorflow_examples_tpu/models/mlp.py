"""Dense MLP classifier — the MNIST "hello world" workload.

Capability parity with the reference's ``tf.keras.Sequential([Flatten,
Dense(relu)…, Dense(10)])`` MNIST example (BASELINE.json:configs[0]).
Single dense stack; no sharding rules needed (params replicate — the
reference's MirroredStrategy behavior falls out as the default).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    features: Sequence[int] = (128, 128)
    num_classes: int = 10
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        x = x.reshape((x.shape[0], -1))
        for i, f in enumerate(self.features):
            x = nn.Dense(f, name=f"dense_{i}")(x)
            x = nn.relu(x)
            if self.dropout_rate > 0:
                x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes, name="head")(x)
